"""Multi-host MPMD fleet search: the cross-host actor/learner round
transport (``search/pipeline.py::FleetTransport``/``run_fleet_actor``,
``launch/workqueue.py`` round-unit verbs, ``search_cli --search-role``)
plus the role-aware fleet launcher.

Fast tests are host-only (stub evaluators, no XLA compiles beyond tiny
PRNG ops); the slow tests are the subprocess acceptance drills —
cross-process steal-fence racing and THE 3-process fleet producing
byte-identical artifacts through a SIGKILLed actor host.
docs/RESILIENCE.md "Fleet search".
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from fast_autoaugment_tpu.core.resilience import clear_preemption
from fast_autoaugment_tpu.launch import fleet as fleet_mod
from fast_autoaugment_tpu.launch.workqueue import WorkQueue
from fast_autoaugment_tpu.search.driver import make_search_space
from fast_autoaugment_tpu.search.pipeline import (
    FleetTransport,
    RemoteEvalError,
    _failure_text,
    replay_trial_log,
    resolve_search_role,
    run_fleet_actor,
    run_fold_pipeline,
)
from fast_autoaugment_tpu.search.tpe import TPE


@pytest.fixture(autouse=True)
def _clean_env(monkeypatch):
    monkeypatch.delenv("FAA_FAULT", raising=False)
    monkeypatch.delenv("FAA_SEARCH_ROLE", raising=False)
    monkeypatch.delenv("FAA_FLEET_TRANSPORT", raising=False)
    from fast_autoaugment_tpu.utils import faultinject

    faultinject.reset()
    clear_preemption()
    yield
    # explicit scrub: tests set FAA_FAULT via os.environ directly, and
    # monkeypatch cannot restore a var that was ABSENT at setup — a
    # leaked spec would quarantine trials in unrelated later tests
    os.environ.pop("FAA_FAULT", None)
    faultinject.reset()
    clear_preemption()


# ------------------------------------------------- workqueue round verbs


def test_publish_unit_payload_roundtrip_and_open_menu(tmp_path):
    q = WorkQueue(str(tmp_path), "host0")
    assert q.open_units() == []
    q.publish_unit("p2r-f0-t000000", {"ids": [0, 1], "fold": 0})
    q.publish_unit("p2r-f0-t000002", {"ids": [2, 3], "fold": 0})
    q.publish_unit("other-unit", {"x": 1})
    assert q.unit_payload("p2r-f0-t000000")["ids"] == [0, 1]
    assert q.unit_payload("p2r-f0-t000000")["unit"] == "p2r-f0-t000000"
    assert q.unit_payload("missing") is None
    assert q.open_units("p2r-") == ["p2r-f0-t000000", "p2r-f0-t000002"]
    assert "other-unit" in q.open_units()
    # a posted result (release info) hides the unit from the claim menu
    assert q.claim("p2r-f0-t000000")
    q.release("p2r-f0-t000000", info={"rewards": [0.5, 0.25]})
    assert q.open_units("p2r-") == ["p2r-f0-t000002"]
    rec = q.done_record("p2r-f0-t000000")
    assert rec["info"]["rewards"] == [0.5, 0.25]
    assert rec["owner"] == "host0" and rec["attempt"] == 1
    # republishing a done unit never resurrects it
    q.publish_unit("p2r-f0-t000000", {"ids": [0, 1], "fold": 0})
    assert q.open_units("p2r-") == ["p2r-f0-t000002"]


def test_tpe_pending_rounds_grouping():
    space = make_search_space(1, 1)
    tpe = TPE(space, seed=3, n_startup=4)
    tpe.ask_tagged(3)
    tpe.ask_tagged(3)
    tpe.ask_tagged(2)  # short final round of an 8-trial budget
    assert tpe.pending_rounds(3) == [[0, 1, 2], [3, 4, 5], [6, 7]]
    for t in (0, 1, 2):
        tpe.tell(t, 0.5)
    assert tpe.pending_rounds(3) == [[3, 4, 5], [6, 7]]
    # round_payload round-trips the pending proposals JSON-exactly
    payload = tpe.round_payload([3, 4])
    assert payload == [json.loads(json.dumps(p)) for p in payload]
    assert payload[0] == tpe.pending_proposal(3)


# --------------------------------------------------- transport primitives


class _Light:
    """A light round (what the learner submits): ids + proposals."""

    def __init__(self, idx, ids, proposals):
        self.idx, self.ids, self.proposals = idx, list(ids), proposals

    @property
    def t_base(self):
        return self.ids[0]

    @property
    def k_eff(self):
        return len(self.ids)


def test_round_unit_names_are_t_base_keyed_and_sortable():
    assert FleetTransport.round_unit(0, 2) == "p2r-f0-t000002"
    units = [FleetTransport.round_unit(0, t) for t in (10, 2, 0, 100)]
    assert sorted(units) == [FleetTransport.round_unit(0, t)
                             for t in (0, 2, 10, 100)]


def test_transport_publish_claim_post_poll_roundtrip(tmp_path):
    learner = FleetTransport(str(tmp_path), "learner0", role="learner")
    actor = FleetTransport(str(tmp_path), "actor0", role="actor")
    rnd = _Light(0, [0, 1], [{"policy_0_0": 1}, {"policy_0_0": 2}])
    unit = learner.publish_round(0, rnd, key_seed=77, trial_batch=2,
                                 num_policy=1, num_op=1)
    assert learner.poll_round(0, 0) is None  # in flight
    assert actor.open_rounds() == [unit]
    payload = actor.wq.unit_payload(unit)
    assert payload["ids"] == [0, 1] and payload["key_seed"] == 77
    assert actor.wq.claim(unit)
    actor.post_result(unit, payload, {"rewards": [0.5, 0.75]})
    kind, rewards = learner.poll_round(0, 0)
    assert kind == "ok" and rewards == [0.5, 0.75]
    assert actor.open_rounds() == []
    # error returns surface as RemoteEvalError with the actor's text
    rnd2 = _Light(1, [2, 3], [{"policy_0_0": 1}, {"policy_0_0": 2}])
    unit2 = learner.publish_round(0, rnd2, key_seed=77, trial_batch=2,
                                  num_policy=1, num_op=1)
    assert actor.wq.claim(unit2)
    actor.post_result(unit2, actor.wq.unit_payload(unit2),
                      {"error": "RuntimeError: boom at trial 2"})
    kind, exc = learner.poll_round(0, 2)
    assert kind == "err" and isinstance(exc, RemoteEvalError)
    # the quarantine text is the actor's formatted text VERBATIM — how
    # fleet quarantine records stay byte-identical to in-process ones
    assert _failure_text(exc) == "RuntimeError: boom at trial 2"
    assert _failure_text(ValueError("x")) == "ValueError: x"


def test_checkpoint_publish_wait_and_digest_gate(tmp_path):
    tr = FleetTransport(str(tmp_path / "tr"), "learner0")
    ckpt = tmp_path / "fold0.msgpack"
    ckpt.write_bytes(b"payload")
    (tmp_path / "fold0.msgpack.meta.json").write_text(
        json.dumps({"epoch": 3, "digest": "abc123"}))
    rec = tr.publish_checkpoint(0, str(ckpt))
    assert rec["digest"] == "abc123" and rec["epoch"] == 3
    assert tr.checkpoint_record(0)["digest"] == "abc123"
    # matching local digest: returns immediately
    got = tr.wait_checkpoint(0, str(ckpt), timeout=5.0, poll_sec=0.01)
    assert got["digest"] == "abc123"
    # digest mismatch (half-synced share): times out loudly
    (tmp_path / "fold0.msgpack.meta.json").write_text(
        json.dumps({"epoch": 3, "digest": "stale"}))
    with pytest.raises(TimeoutError, match="checkpoint"):
        tr.wait_checkpoint(0, str(ckpt), timeout=0.2, poll_sec=0.02)
    # unpublished fold: times out too
    with pytest.raises(TimeoutError):
        tr.wait_checkpoint(7, str(ckpt), timeout=0.2, poll_sec=0.02)


def test_search_done_marker_drains_idle_actor(tmp_path):
    tr = FleetTransport(str(tmp_path), "learner0")
    assert not tr.search_done()
    tr.mark_search_done({"num_sub_policies": 4})
    assert tr.search_done()
    actor_tr = FleetTransport(str(tmp_path), "actor0", role="actor")
    stats = run_fleet_actor(object(), actor_tr, lambda f: "/nope",
                            trial_batch=2, num_policy=1, num_op=1,
                            poll_sec=0.05)
    assert stats["rounds_ok"] == 0 and stats["folds"] == []
    beats = actor_tr.wq.known_hosts()
    assert beats["actor0"]["role"] == "actor"


# ------------------------------------------- fleet learner/actor (stubs)


class _StubFleetEval:
    """Host-only _FoldEval stand-in shared by the thread and fleet
    arms: deterministic per-lane rewards from the policy tensor."""

    def load_fold(self, path):
        return None, None

    @staticmethod
    def _reward(policy_lane):
        return round(float(np.asarray(policy_lane).sum()) % 1.0, 6)

    def evaluate(self, fold, params, batch_stats, policy_t, key):
        return {"top1_valid": self._reward(policy_t)}

    def evaluate_batch(self, fold, params, batch_stats, policies_t, keys):
        return [{"top1_valid": self._reward(policies_t[i])}
                for i in range(int(policies_t.shape[0]))]


def _drive(tmp_path, *, fleet: bool, num_search=8, k=2, actors=2,
           queue_depth=1, seed=11, fold_trials=None):
    """One fold's budget through the thread backend (fleet=False) or
    the cross-host transport serviced by an in-test actor thread
    (fleet=True) — everything else identical."""
    import jax

    tpe = TPE(make_search_space(1, 1), seed=seed, n_startup=4)
    log = list(fold_trials) if fold_trials is not None else []
    replay_trial_log(tpe, log, k, num_search,
                     max_inflight=actors + queue_depth)
    quars = []

    kw = dict(num_search=num_search, trial_batch=k, actors=actors,
              queue_depth=queue_depth, num_policy=1, num_op=1,
              persist=lambda: None,
              record_quarantine=lambda lo, hi, exc, worst: quars.append(
                  (lo, hi, _failure_text(exc), worst)))
    if not fleet:
        stats = run_fold_pipeline(
            _StubFleetEval(), 0, None, None, tpe, jax.random.PRNGKey(7),
            log, **kw)
        return log, stats, quars, None

    root = str(tmp_path / "tr")
    learner_tr = FleetTransport(root, "learner0", role="learner")
    learner_tr.publish_checkpoint(0, str(tmp_path / "missing.msgpack"))
    actor_tr = FleetTransport(root, "actor0", role="actor")
    actor_out: list = []

    def _actor():
        try:
            actor_out.append(run_fleet_actor(
                _StubFleetEval(), actor_tr,
                lambda f: str(tmp_path / "missing.msgpack"),
                trial_batch=k, num_policy=1, num_op=1, poll_sec=0.05))
        except BaseException as e:  # surfaced by the assertions below
            actor_out.append(e)

    th = threading.Thread(target=_actor, daemon=True)
    th.start()
    try:
        backend = learner_tr.learner_backend(
            0, key_seed=7, trial_batch=k, num_policy=1, num_op=1)
        stats = run_fold_pipeline(
            _StubFleetEval(), 0, None, None, tpe, jax.random.PRNGKey(7),
            log, backend=backend, **kw)
    finally:
        learner_tr.mark_search_done()
        th.join(timeout=30)
    assert not th.is_alive(), "actor never drained on search_done"
    return log, stats, quars, actor_out[0] if actor_out else None


def test_fleet_backend_reproduces_thread_backend_bit_for_bit(tmp_path):
    """THE determinism core: the same learner loop over the cross-host
    transport produces the identical trial log (and posterior stream)
    as the in-process thread backend — rewards are pure functions of
    (proposals, id-derived keys) wherever they run."""
    ref, ref_stats, _q, _ = _drive(tmp_path / "a", fleet=False)
    got, stats, quars, actor_stats = _drive(tmp_path / "b", fleet=True)
    assert got == ref
    assert not quars
    assert isinstance(actor_stats, dict), actor_stats
    assert actor_stats["rounds_ok"] == stats["rounds"] == 4
    assert actor_stats["folds"] == [0]
    assert stats["trials"] == ref_stats["trials"] == 8


def test_fleet_resume_adopts_posted_results(tmp_path):
    """A learner that died after actors posted results: the rerun
    replays the log, republishes the pending rounds onto the SAME
    t_base-keyed units, finds the posted done markers immediately, and
    completes identically."""
    full, _s, _q, _ = _drive(tmp_path / "full", fleet=True)
    # crash simulation in the same transport dir: keep only round 0's
    # trials persisted, leave every done marker on disk
    resumed, _s2, _q2, _ = _drive(
        tmp_path / "full", fleet=True, fold_trials=full[:2])
    assert resumed == full


def test_fleet_quarantine_matches_in_process_format(tmp_path):
    """FAA_FAULT trial_error fires on the ACTOR host; the posted error
    quarantines the round on the learner with entry text byte-identical
    to the in-process scheduler's."""
    os.environ["FAA_FAULT"] = "trial_error@trial=2"
    from fast_autoaugment_tpu.utils import faultinject

    faultinject.reset()
    ref, _s, ref_q, _ = _drive(tmp_path / "a", fleet=False)
    os.environ["FAA_FAULT"] = "trial_error@trial=2"
    faultinject.reset()
    got, _s2, got_q, actor_stats = _drive(tmp_path / "b", fleet=True)
    assert got == ref
    assert [q[:3] for q in got_q] == [q[:3] for q in ref_q]
    assert "injected trial_error at trial 2" in got_q[0][2]
    assert actor_stats["rounds_err"] == 1
    bad = got[2:4]
    assert all(m["quarantined"] for _p, _r, m in bad)
    assert all("RuntimeError: injected trial_error" in m["error"]
               for _p, _r, m in bad)


def test_actor_geometry_mismatch_raises_loudly(tmp_path):
    learner = FleetTransport(str(tmp_path), "learner0")
    learner.publish_round(
        0, _Light(0, [0, 1], [{"policy_0_0": 1}, {"policy_0_0": 2}]),
        key_seed=7, trial_batch=2, num_policy=1, num_op=1)
    actor_tr = FleetTransport(str(tmp_path), "actor0")
    with pytest.raises(ValueError, match="geometry mismatch"):
        run_fleet_actor(_StubFleetEval(), actor_tr, lambda f: "/nope",
                        trial_batch=4, num_policy=1, num_op=1,
                        poll_sec=0.05)


def test_sigkill_trial_fault_verb_parses_and_gates():
    from fast_autoaugment_tpu.utils.faultinject import parse_fault_spec

    faults = parse_fault_spec("sigkill_trial@trial=2,attempt=1")
    assert faults[0]["kind"] == "sigkill_trial"
    assert faults[0]["trial"] == 2 and faults[0]["attempt"] == 1
    with pytest.raises(ValueError):
        parse_fault_spec("sigkill_trial@step=2")  # wrong coordinate


# ---------------------------------------------------- roles / CLI / env


def test_resolve_search_role(monkeypatch):
    assert resolve_search_role(None) == "learner"
    assert resolve_search_role("auto") == "learner"
    assert resolve_search_role("actor") == "actor"
    monkeypatch.setenv("FAA_SEARCH_ROLE", "actor")
    assert resolve_search_role("auto") == "actor"
    assert resolve_search_role("learner") == "learner"  # flag wins
    monkeypatch.setenv("FAA_SEARCH_ROLE", "banana")
    with pytest.raises(ValueError, match="role"):
        resolve_search_role("auto")
    with pytest.raises(ValueError):
        resolve_search_role("trainer")


def test_cli_fleet_flags_parse_and_guards(tmp_path, monkeypatch):
    from fast_autoaugment_tpu.launch.search_cli import (
        _resolve_fleet_transport,
        build_parser,
    )

    p = build_parser()
    args = p.parse_args(["-c", "x.yaml"])
    assert args.fleet_transport is None and args.search_role == "auto"
    transport, role = _resolve_fleet_transport(args)
    assert transport is None and role == "learner"
    # actor without a transport dir is a launch error
    args = p.parse_args(["-c", "x.yaml", "--search-role", "actor"])
    with pytest.raises(SystemExit, match="actor"):
        _resolve_fleet_transport(args)
    # env handoff arms the transport without flags
    monkeypatch.setenv("FAA_FLEET_TRANSPORT", str(tmp_path / "tr"))
    monkeypatch.setenv("FAA_SEARCH_ROLE", "actor")
    args = p.parse_args(["-c", "x.yaml"])
    transport, role = _resolve_fleet_transport(args)
    assert role == "actor" and transport is not None
    assert transport.root == str(tmp_path / "tr")
    # transport + workqueue is a contradiction, not a preference
    args = p.parse_args(["-c", "x.yaml", "--fleet-transport",
                         str(tmp_path / "tr"), "--workqueue",
                         str(tmp_path / "wq")])
    monkeypatch.delenv("FAA_SEARCH_ROLE")
    with pytest.raises(SystemExit, match="mutually exclusive"):
        _resolve_fleet_transport(args)


def test_fleet_roles_resolve():
    assert fleet_mod.resolve_roles(None, 3) == [None, None, None]
    assert fleet_mod.resolve_roles("actor", 3) == ["actor"] * 3
    assert fleet_mod.resolve_roles("learner,actor,actor", 3) == [
        "learner", "actor", "actor"]
    with pytest.raises(ValueError, match="roles"):
        fleet_mod.resolve_roles("learner,actor", 3)


def test_fleet_exports_per_host_role(tmp_path, monkeypatch):
    """--roles exports FAA_SEARCH_ROLE per host, re-exported on every
    retry (a relaunched actor must stay an actor)."""
    log = tmp_path / "roles.log"
    monkeypatch.setattr(
        fleet_mod, "_remote_argv",
        lambda host, wire: ["bash", "-c", wire])
    code = fleet_mod.launch_fleet(
        ["a", "b"],
        ["sh", "-c", f'echo "$FAA_HOST_ID=$FAA_SEARCH_ROLE" >> {log}; '
                     f'[ "$FAA_HOST_ID" = 1 ] && exit 1; exit 0'],
        "x:1", host_retries=1, retry_backoff=0.01, rank_args=False,
        roles=["learner", "actor"])
    assert code == 1
    lines = sorted(log.read_text().split())
    # host 0 launched once as learner; host 1 twice (retry) as actor
    assert lines == ["0=learner", "1=actor", "1=actor"]


def test_env_passthrough_pin_includes_fleet_search_vars(tmp_path,
                                                       monkeypatch):
    """The satellite pin: FAA_PIPELINE_TRACE and the fleet-search
    transport env ride the default passthrough to every host launch
    AND retry, exactly like FAA_COMPILE_CACHE/FAA_TELEMETRY."""
    for var in ("FAA_PIPELINE_TRACE", "FAA_SEARCH_ROLE",
                "FAA_FLEET_TRANSPORT", "FAA_COMPILE_CACHE",
                "FAA_TELEMETRY"):
        assert var in fleet_mod.DEFAULT_ENV_PASSTHROUGH
    log = tmp_path / "env.log"
    monkeypatch.setenv("FAA_PIPELINE_TRACE", "1")
    monkeypatch.setenv("FAA_FLEET_TRANSPORT", "/shared/tr")
    monkeypatch.setattr(
        fleet_mod, "_remote_argv",
        lambda host, wire: ["bash", "-c", wire])
    code = fleet_mod.launch_fleet(
        ["a"],
        ["sh", "-c",
         f'echo "$FAA_PIPELINE_TRACE $FAA_FLEET_TRANSPORT" >> {log}; '
         "exit 1"],
        "x:1", host_retries=1, retry_backoff=0.01, rank_args=False)
    assert code == 1
    assert log.read_text().splitlines() == ["1 /shared/tr"] * 2


def test_telemetry_round_event_type_is_in_taxonomy():
    from fast_autoaugment_tpu.core import telemetry

    assert "round" in telemetry.EVENT_TYPES


# ------------------------------------------------- faa_status topology


def test_faa_status_renders_fleet_search_topology(tmp_path):
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    import faa_status

    root = tmp_path
    (root / "hosts").mkdir()
    now = time.time()
    for owner, role in (("host0", "learner"), ("host1", "actor")):
        (root / "hosts" / f"{owner}.json").write_text(json.dumps(
            {"owner": owner, "heartbeat": now, "role": role}))
    (root / "leases").mkdir()
    (root / "leases" / "p2r-f0-t000002.json").write_text(json.dumps(
        {"unit": "p2r-f0-t000002", "owner": "host1", "attempt": 1,
         "heartbeat": now}))
    (root / "work").mkdir()
    (root / "done").mkdir()
    for unit in ("p2r-f0-t000000", "p2r-f0-t000002"):
        (root / "work" / f"{unit}.json").write_text(json.dumps(
            {"unit": unit, "fold": 0}))
    (root / "done" / "p2r-f0-t000000.json").write_text(json.dumps(
        {"unit": "p2r-f0-t000000", "owner": "host1", "attempt": 1,
         "info": {"rewards": [0.5]}}))
    # journal: learner publishes + a phase1 lane; actor claims/returns
    # + a phase2 lane overlapping the learner's phase1 window
    events = [
        {"type": "round", "label": "p2r-f0-t000000", "action": "publish",
         "host": "host0", "t_wall": now, "t_mono": 100.0, "seq": 0},
        {"type": "round", "label": "p2r-f0-t000000", "action": "claim",
         "host": "host1", "t_wall": now + 0.1, "t_mono": 50.0, "seq": 0},
        {"type": "round", "label": "p2r-f0-t000000", "action": "return",
         "host": "host1", "t_wall": now + 1.0, "t_mono": 51.0, "seq": 1},
        {"type": "round", "label": "p2r-f0-t000000", "action": "apply",
         "host": "host0", "t_wall": now + 1.1, "t_mono": 101.1, "seq": 1},
        {"type": "phase", "label": "phase1-fold1", "lane": "phase1",
         "host": "host0", "t_wall": now + 2.0, "t_mono": 102.0,
         "t_mono_start": 100.0, "t_mono_end": 102.0, "seq": 2},
        {"type": "phase", "label": "phase2-fold0", "lane": "phase2",
         "host": "host1", "t_wall": now + 1.0, "t_mono": 51.0,
         "t_mono_start": 50.0, "t_mono_end": 51.0, "seq": 2},
    ]
    with open(root / "journal-host0-a1-p1.000.jsonl", "w") as fh:
        for e in events:
            fh.write(json.dumps(e) + "\n")

    status = faa_status.fleet_status(str(root), ttl=60.0)
    sf = status["search_fleet"]
    assert sf["hosts"]["host0"]["role"] == "learner"
    assert sf["hosts"]["host1"]["role"] == "actor"
    assert sf["hosts"]["host0"]["published"] == 1
    assert sf["hosts"]["host1"]["claimed"] == 1
    assert sf["hosts"]["host1"]["claimed_units"] == ["p2r-f0-t000002"]
    assert sf["open_rounds"] == ["p2r-f0-t000002"]
    assert sf["inflight_rounds"] == 1
    # phase1@host0 spans wall [now, now+2]; phase2@host1 spans
    # [now, now+1] — 1s of cross-host lane concurrency
    assert sf["concurrent_lane_secs"] == pytest.approx(1.0, abs=0.05)
    assert sf["concurrent_lane_pairs"][0]["phase1_host"] == "host0"
    table = faa_status.render_table(status)
    assert "fleet search:" in table
    assert "role=learner" in table and "role=actor" in table
    assert "in-flight window: 1 open round(s)" in table
    assert "concurrent lanes" in table


# ------------------------------------------------------ slow: processes


@pytest.mark.slow
def test_steal_fence_cross_process_racing_claimants(tmp_path):
    """The satellite: the PR-6 steal fence under TRUE cross-process
    racing (the existing races are thread-barrier drills in one
    process).  Four processes gate on a shared go-file and race to
    reclaim one stale lease; exactly one must win, with the reclaim
    provenance (attempt=2, reclaimed_from) intact."""
    root = tmp_path / "wq"
    seeder = WorkQueue(str(root), "dead-host", lease_ttl=1.0)
    assert seeder.claim("unit-x")
    # age the lease well past the TTL
    lease = json.load(open(root / "leases" / "unit-x.json"))
    lease["heartbeat"] -= 300.0
    (root / "leases" / "unit-x.json").write_text(json.dumps(lease))

    go = tmp_path / "go"
    script = (
        "import json, sys, time, os\n"
        "from fast_autoaugment_tpu.launch.workqueue import WorkQueue\n"
        "root, owner, go = sys.argv[1:4]\n"
        "q = WorkQueue(root, owner, lease_ttl=1.0)\n"
        "assert not q.claim('unit-x')  # observer-local: watch first\n"
        "t_obs = time.monotonic()\n"
        "deadline = time.monotonic() + 60\n"
        "while not os.path.exists(go):\n"
        "    if time.monotonic() > deadline: sys.exit(3)\n"
        "    time.sleep(0.005)\n"
        "# everyone's observation must be a full TTL old at race time\n"
        "time.sleep(max(0.0, 1.05 - (time.monotonic() - t_obs)))\n"
        "print('WON' if q.claim('unit-x') else 'LOST')\n")
    procs = [subprocess.Popen(
        [sys.executable, "-c", script, str(root), f"racer{i}", str(go)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
        for i in range(4)]
    time.sleep(2.0)  # let the interpreters reach the gate + observe
    go.write_text("go")
    outs = [p.communicate(timeout=300) for p in procs]
    assert all(p.returncode == 0 for p in procs), outs
    verdicts = [o[0].strip().splitlines()[-1] for o in outs]
    assert sorted(verdicts) == ["LOST", "LOST", "LOST", "WON"]
    lease = json.load(open(root / "leases" / "unit-x.json"))
    assert lease["attempt"] == 2
    assert lease["epoch"] == 2  # the fencing token rode the steal
    assert lease["reclaimed_from"] == "dead-host"
    assert lease["owner"].startswith("racer")
    # the fence file never survives the steal
    assert not os.path.exists(str(root / "leases" / "unit-x.json.steal"))


_CONF_YAML = (
    "model:\n  type: wresnet10_1\ndataset: synthetic\naug: default\n"
    "cutout: 8\nbatch: 8\nepoch: 1\nlr: 0.05\n"
    "lr_schedule:\n  type: cosine\n"
    "optimizer:\n  type: sgd\n  decay: 0.0001\n  momentum: 0.9\n"
    "  nesterov: true\n")


@pytest.mark.slow
def test_fleet_search_e2e_bit_identical_through_actor_sigkill(tmp_path):
    """THE acceptance drill: a 3-process fleet (1 learner+trainer, 2
    actor hosts) over a shared transport + compile cache produces
    search_trials.json and final_policy.json BYTE-IDENTICAL to the
    single-host --async-pipeline run — including after one actor host
    is SIGKILLed mid-round (FAA_FAULT sigkill_trial) and its round is
    reclaimed by the survivor."""
    tmp = str(tmp_path)
    conf = tmp_path / "conf.yaml"
    conf.write_text(_CONF_YAML)
    cache = f"{tmp}/cc"
    base = [sys.executable, "-m",
            "fast_autoaugment_tpu.launch.search_cli",
            "-c", str(conf), "--dataroot", tmp,
            "--num-fold", "2", "--num-search", "4", "--num-policy", "1",
            "--num-op", "1", "--num-top", "2", "--trial-batch", "2",
            "--until", "2", "--fold-quality-floor", "off",
            "--seed", "0", "--compile-cache", cache,
            "--async-pipeline", "on", "--pipeline-actors", "2",
            "--pipeline-queue-depth", "2"]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("FAA_FAULT", None)

    # ---- single-host reference (also warms the shared compile cache)
    ref = subprocess.run(base + ["--save-dir", f"{tmp}/ref"], env=env,
                         capture_output=True, text=True, timeout=900)
    assert ref.returncode == 0, ref.stderr[-3000:]

    # ---- the 3-process fleet; actor host1 dies mid-round, every time
    tr, save = f"{tmp}/transport", f"{tmp}/fleet"
    fleet_base = base + ["--save-dir", save, "--fleet-transport", tr,
                         "--lease-ttl", "6"]
    learner = subprocess.Popen(
        fleet_base + ["--search-role", "learner", "--host-id", "0"],
        env=dict(env, FAA_HOST_ID="0"), stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    doomed = subprocess.Popen(
        fleet_base + ["--search-role", "actor", "--host-id", "1"],
        env=dict(env, FAA_HOST_ID="1",
                 FAA_FAULT="sigkill_trial@trial=2"),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    survivor = subprocess.Popen(
        fleet_base + ["--search-role", "actor", "--host-id", "2"],
        env=dict(env, FAA_HOST_ID="2"), stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    out_l = learner.communicate(timeout=900)[0]
    out_d = doomed.communicate(timeout=120)[0]
    out_s = survivor.communicate(timeout=300)[0]
    assert learner.returncode == 0, out_l[-3000:]
    assert survivor.returncode == 0, out_s[-3000:]
    assert doomed.returncode == -9, (doomed.returncode, out_d[-1500:])

    # byte-identity through the kill + reclaim
    assert (open(f"{tmp}/ref/search_trials.json", "rb").read()
            == open(f"{save}/search_trials.json", "rb").read())
    assert (open(f"{tmp}/ref/final_policy.json", "rb").read()
            == open(f"{save}/final_policy.json", "rb").read())
    result = json.load(open(f"{save}/search_result.json"))
    assert result["degraded"] is True
    assert result["reclaimed_units"], "the dead actor's round reclaimed"
    assert all(u.startswith("p2r-") for u in result["reclaimed_units"])
    assert "host1" in result["lost_hosts"]
    assert result["fleet_transport"]["window"] == 4
    # the single-host reference artifact carries NO fleet stamps
    ref_result = json.load(open(f"{tmp}/ref/search_result.json"))
    assert "fleet_transport" not in ref_result
