import os
import tempfile

import pytest

from fast_autoaugment_tpu.core.config import Config, load_config, parse_overrides


def test_attribute_and_item_access():
    c = Config({"model": {"type": "wresnet40_2", "depth": 40}, "lr": 0.1})
    assert c.model.type == "wresnet40_2"
    assert c["lr"] == 0.1
    assert c.get("model.depth") == 40
    assert c.get("optimizer.clip", 5.0) == 5.0


def test_immutable_and_hashable():
    c = Config({"a": {"b": 1}})
    with pytest.raises(TypeError):
        c.x = 1
    assert hash(c) == hash(Config({"a": {"b": 1}}))
    d = {c: "ok"}
    assert d[Config({"a": {"b": 1}})] == "ok"


def test_replace_returns_new():
    c = Config({"model": {"type": "wrn"}, "lr": 0.1})
    c2 = c.replace(**{"model.type": "resnet50", "epoch": 90})
    assert c2.model.type == "resnet50" and c2.epoch == 90
    assert c.model.type == "wrn" and "epoch" not in c


def test_load_yaml_with_overrides():
    with tempfile.NamedTemporaryFile("w", suffix=".yaml", delete=False) as fh:
        fh.write("model:\n  type: wresnet40_2\nbatch: 128\nlr: 0.1\n")
        path = fh.name
    try:
        cfg = load_config(path, overrides=["lr=0.4", "model.type=resnet50"])
        assert cfg.batch == 128
        assert cfg.lr == 0.4  # coerced to float
        assert cfg.model.type == "resnet50"
    finally:
        os.unlink(path)


def test_parse_overrides_yaml_coercion():
    out = parse_overrides(["a=5", "b=true", "c=hello", "d=[1,2]"])
    assert out == {"a": 5, "b": True, "c": "hello", "d": [1, 2]}


def test_lenient_checkpoint_merge_semantics(tmp_path):
    import numpy as np

    from fast_autoaugment_tpu.core.checkpoint import load_checkpoint, save_checkpoint

    path = str(tmp_path / "ck.msgpack")
    # file has params + an ema the target doesn't want
    save_checkpoint(path, {"params": {"w": np.ones(3)}, "ema": {"w": np.ones(3) * 7}},
                    {"epoch": 1})

    # 1) template WITHOUT ema: grafting must drop the file's ema
    target = {"params": {"w": np.zeros(3)}, "ema": None, "opt": {"m": np.zeros(2)}}
    out = load_checkpoint(path, target, lenient=True)
    np.testing.assert_array_equal(out["params"]["w"], 1.0)
    assert out["ema"] is None
    np.testing.assert_array_equal(out["opt"]["m"], 0.0)  # kept from template

    # 2) template WITH ema and file WITH ema: file wins
    target2 = {"params": {"w": np.zeros(3)}, "ema": {"w": np.zeros(3)}, "opt": None}
    out2 = load_checkpoint(path, target2, lenient=True)
    np.testing.assert_array_equal(out2["ema"]["w"], 7.0)


def test_accumulator():
    from fast_autoaugment_tpu.core.metrics import Accumulator

    acc = Accumulator()
    acc.add_dict({"loss": 2.0 * 4, "top1": 3.0, "num": 4})
    acc.add_dict({"loss": 1.0 * 4, "top1": 4.0, "num": 4})
    norm = acc.normalize()
    assert norm["num"] == 8
    assert norm["loss"] == pytest.approx(1.5)
    assert norm["top1"] == pytest.approx(7 / 8)
