"""Overload-safe policy serving (docs/RESILIENCE.md "Serving under
overload"): admission control, deadline shedding, adaptive-LIFO
watermarks, the circuit breaker, hot policy reload, graceful drain and
the fleet-supervised replica-restart path.

The fast tests drive :class:`PolicyServer` with a host-only dummy
applier (no XLA compiles — tier-1 stays inside its 870s wall); the
chaos/e2e drills that need real AOT executables or subprocess replicas
are ``slow``-marked.
"""

from __future__ import annotations

import io
import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from fast_autoaugment_tpu.core.resilience import (
    PREEMPTED_EXIT_CODE,
    CircuitBreaker,
    CircuitOpenError,
)
from fast_autoaugment_tpu.serve.policy_server import (
    DeadlineExpiredError,
    PolicyServer,
    ServeError,
    ServerOverloadedError,
    ServerStoppedError,
    _RequestQueue,
)
from fast_autoaugment_tpu.utils import faultinject

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
sys.path.insert(0, os.path.join(_REPO, "tools"))

IMG = 8


class DummyApplier:
    """Host-only applier standing in for the AOT executables: shifts
    pixel values by `delta` so tests can tell WHICH applier served a
    request (the hot-reload atomicity check)."""

    def __init__(self, delta=1.0, dispatch="exact", max_batch=4,
                 wall_s=0.0):
        self.delta = float(delta)
        self.dispatch = dispatch
        self.max_batch = max_batch
        self.image = IMG
        self.channels = 3
        self.num_sub = 1
        self.shapes = (max_batch,)
        self.wall_s = float(wall_s)
        self.calls = 0

    def apply(self, images, keys):
        self.calls += 1
        if self.wall_s:
            time.sleep(self.wall_s)
        return np.asarray(images, np.float32) + self.delta


def _images(n, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 256, (n, IMG, IMG, 3)).astype(np.float32)


def _keys(n, base=0):
    # fixed host-side keys: the dummy applier ignores them
    return np.full((n, 2), base, np.uint32)


@pytest.fixture(autouse=True)
def _clean_fault_env():
    saved = os.environ.pop("FAA_FAULT", None)
    saved_at = os.environ.pop("FAA_ATTEMPT", None)
    faultinject.reset()
    yield
    if saved is None:
        os.environ.pop("FAA_FAULT", None)
    else:
        os.environ["FAA_FAULT"] = saved
    if saved_at is None:
        os.environ.pop("FAA_ATTEMPT", None)
    else:
        os.environ["FAA_ATTEMPT"] = saved_at
    faultinject.reset()


# ------------------------------------------------- admission control


def test_submit_never_blocks_on_full_queue():
    """The blocking-admission bug fix: a full queue rejects IMMEDIATELY
    with the typed overload error (the old path parked the caller for
    up to 30s)."""
    srv = PolicyServer(DummyApplier(), queue_depth=2)
    srv.submit(_images(1), _keys(1))
    srv.submit(_images(1), _keys(1))
    t0 = time.perf_counter()
    with pytest.raises(ServerOverloadedError) as ei:
        srv.submit(_images(1), _keys(1))
    assert time.perf_counter() - t0 < 1.0  # fail-fast, not a 30s park
    assert ei.value.retry_after_s > 0
    assert srv.stats()["admission"]["shed_overload"] == 1
    assert srv.stats()["admission"]["admitted"] == 2


def test_submit_after_stop_is_typed_not_racing():
    srv = PolicyServer(DummyApplier()).start()
    srv.stop()
    with pytest.raises(ServerStoppedError):
        srv.submit(_images(1), _keys(1))
    assert srv.stats()["admission"]["shed_stopped"] >= 1


def test_validation_errors_still_valueerror():
    """Bad requests stay ValueError (HTTP 400), not overload errors."""
    srv = PolicyServer(DummyApplier(max_batch=4))
    with pytest.raises(ValueError):
        srv.submit(_images(5), _keys(5))  # oversize
    with pytest.raises(ValueError):
        srv.submit(np.zeros((0, IMG, IMG, 3), np.float32))  # empty


# --------------------------------------------- deadline-aware shedding


def test_expired_requests_shed_before_dispatch():
    """Dead work never reaches the device: requests whose deadline
    passed while queued are retired with the typed error and ZERO
    applier calls."""
    ap = DummyApplier()
    srv = PolicyServer(ap)
    p1 = srv.submit(_images(1), _keys(1), deadline_ms=1)
    p2 = srv.submit(_images(1), _keys(1), deadline_ms=1)
    time.sleep(0.05)  # both deadlines pass while the worker is down
    srv.start()
    for p in (p1, p2):
        with pytest.raises(DeadlineExpiredError):
            srv.result(p)
    assert ap.calls == 0
    st = srv.stats()["admission"]
    assert st["expired"] == 2 and st["deadline_misses"] == 0
    srv.stop()


def test_result_wait_is_deadline_bounded():
    """A client never hangs past its deadline (plus the shed grace):
    even with the worker down, result() times out promptly."""
    srv = PolicyServer(DummyApplier())
    srv.deadline_grace_s = 0.2
    p = srv.submit(_images(1), _keys(1), deadline_ms=50)
    t0 = time.perf_counter()
    with pytest.raises(TimeoutError):
        srv.result(p, timeout=60.0)
    assert time.perf_counter() - t0 < 5.0


def test_default_deadline_applies():
    srv = PolicyServer(DummyApplier(), default_deadline_ms=25.0)
    p = srv.submit(_images(1), _keys(1))
    assert p.deadline is not None
    srv2 = PolicyServer(DummyApplier())
    assert srv2.submit(_images(1), _keys(1)).deadline is None


def test_deadline_miss_counted_on_late_completion():
    """A dispatch that finishes past the deadline still delivers, but
    the miss is counted (the bench's deadline-miss-rate source)."""
    srv = PolicyServer(DummyApplier(wall_s=0.08), max_wait_ms=1)
    p = srv.submit(_images(1), _keys(1), deadline_ms=20)
    srv.start()
    out = srv.result(p, timeout=10.0)  # grace covers the late scatter
    assert out.shape == (1, IMG, IMG, 3)
    assert srv.stats()["admission"]["deadline_misses"] == 1
    srv.stop()


# ------------------------------------------------ adaptive-LIFO drain


def test_lifo_depth_watermark_serves_newest_first():
    srv = PolicyServer(DummyApplier(), max_batch=1, max_wait_ms=1,
                       lifo_depth=2)
    pend = [srv.submit(_images(1), _keys(1)) for _ in range(3)]
    srv.start()
    for p in pend:
        srv.result(p, timeout=10.0)
    # newest (index 2) served first, oldest (index 0) last
    assert pend[2].t_done < pend[1].t_done < pend[0].t_done
    assert srv.stats()["admission"]["lifo_takes"] >= 1
    srv.stop()


def test_fifo_is_default_drain_order():
    srv = PolicyServer(DummyApplier(), max_batch=1, max_wait_ms=1)
    pend = [srv.submit(_images(1), _keys(1)) for _ in range(3)]
    srv.start()
    for p in pend:
        srv.result(p, timeout=10.0)
    assert pend[0].t_done < pend[1].t_done < pend[2].t_done
    assert srv.stats()["admission"]["lifo_takes"] == 0
    srv.stop()


def test_request_queue_age_watermark():
    q = _RequestQueue(10, lifo_age_ms=20.0)
    from fast_autoaugment_tpu.serve.policy_server import _Pending

    a = _Pending(_images(1), None)
    q.offer(a)
    b = _Pending(_images(1), None)
    q.offer(b)
    assert q.take(0.01) is a  # young queue: FIFO
    q.offer(a)
    time.sleep(0.03)  # oldest age crosses the watermark
    c = _Pending(_images(1), None)
    q.offer(c)
    assert q.take(0.01) is c  # newest-first now
    assert q.lifo_takes == 1


# ---------------------------------------------------- circuit breaker


def test_circuit_breaker_unit():
    b = CircuitBreaker(threshold=0)
    assert not b.enabled and b.allow() and not b.is_open()
    b.record_failure()  # disabled: never opens
    assert b.snapshot()["state"] == "disabled"

    b = CircuitBreaker(threshold=2, cooldown_s=0.1)
    assert b.allow()
    b.record_failure()
    assert not b.is_open()  # one failure below threshold
    b.record_failure()
    assert b.is_open() and b.fires == 1 and not b.allow()
    time.sleep(0.12)
    assert not b.is_open()  # cooldown elapsed: probe-eligible
    assert b.allow()        # the single half-open probe
    assert not b.allow()    # second concurrent probe refused
    b.record_failure()      # probe failed: re-open
    assert b.fires == 2 and b.is_open()
    time.sleep(0.12)
    assert b.allow()
    b.record_success()      # probe succeeded: closed
    assert b.snapshot()["state"] == "closed" and b.allow()
    # success resets the consecutive-failure count
    b.record_failure()
    b.record_success()
    b.record_failure()
    assert not b.is_open()


def test_breaker_opens_on_injected_errors_and_recovers():
    """serve_error x threshold opens the breaker: admission fails fast
    with the typed error, a post-cooldown probe closes it again."""
    os.environ["FAA_FAULT"] = "serve_error@dispatch=1;serve_error@dispatch=2"
    faultinject.reset()
    srv = PolicyServer(DummyApplier(), max_wait_ms=1,
                       breaker_threshold=2, breaker_cooldown_s=0.3).start()
    try:
        for _ in range(2):
            with pytest.raises(ServeError):
                srv.augment(_images(1), _keys(1), timeout=10.0)
        snap = srv.stats()["breaker"]
        assert snap["state"] == "open" and snap["fires"] == 1
        with pytest.raises(CircuitOpenError) as ei:
            srv.submit(_images(1), _keys(1))
        assert ei.value.retry_after_s > 0
        assert srv.stats()["admission"]["shed_breaker"] >= 1
        time.sleep(0.35)
        out = srv.augment(_images(1), _keys(1), timeout=10.0)  # probe
        assert out.shape == (1, IMG, IMG, 3)
        assert srv.stats()["breaker"]["state"] == "closed"
    finally:
        srv.stop()


def test_breaker_fails_queued_batch_fast_when_open():
    """Requests already queued when the breaker opens get the typed
    error without a device call."""
    os.environ["FAA_FAULT"] = "serve_error@dispatch=1"
    faultinject.reset()
    ap = DummyApplier()
    srv = PolicyServer(ap, max_batch=1, max_wait_ms=1,
                       breaker_threshold=1, breaker_cooldown_s=30.0)
    p1 = srv.submit(_images(1), _keys(1))
    p2 = srv.submit(_images(1), _keys(1))
    srv.start()
    with pytest.raises(ServeError):
        srv.result(p1, timeout=10.0)
    with pytest.raises(CircuitOpenError):
        srv.result(p2, timeout=10.0)
    assert ap.calls == 0  # injected error + fast-fail: no device work
    srv.stop()


def test_dispatch_timeout_counts_as_breaker_failure():
    """A straggler past dispatch_timeout_s delivers results but feeds
    the breaker — repeated near-hangs open the circuit."""
    srv = PolicyServer(DummyApplier(wall_s=0.05), max_wait_ms=1,
                       breaker_threshold=1, breaker_cooldown_s=30.0,
                       dispatch_timeout_s=0.01).start()
    out = srv.augment(_images(1), _keys(1), timeout=10.0)
    assert out.shape == (1, IMG, IMG, 3)  # results still delivered
    assert srv.stats()["breaker"]["state"] == "open"
    srv.stop()


def test_serve_slow_verb_delays_dispatch():
    os.environ["FAA_FAULT"] = "serve_slow@dispatch=1,factor=0.2"
    faultinject.reset()
    srv = PolicyServer(DummyApplier(), max_wait_ms=1).start()
    t0 = time.perf_counter()
    srv.augment(_images(1), _keys(1), timeout=10.0)
    # no EMA yet -> factor seconds of injected delay
    assert time.perf_counter() - t0 >= 0.2
    srv.stop()


# ------------------------------------------------- FAA_FAULT grammar


def test_parse_serve_verbs():
    faults = faultinject.parse_fault_spec(
        "serve_error@dispatch=3;serve_slow@dispatch=5,factor=2.5")
    assert [f["kind"] for f in faults] == ["serve_error", "serve_slow"]
    assert faults[0]["dispatch"] == 3 and faults[1]["factor"] == 2.5
    with pytest.raises(ValueError):
        faultinject.parse_fault_spec("serve_error@step=3")  # wrong key
    with pytest.raises(ValueError):
        faultinject.parse_fault_spec("serve_slow@dispatch=1")  # no factor


def test_serve_verbs_attempt_gated():
    os.environ["FAA_FAULT"] = "serve_error@dispatch=1,attempt=2"
    os.environ["FAA_ATTEMPT"] = "1"
    faultinject.reset()
    plan = faultinject.active_plan()
    assert plan.serve_fault(1) is None  # gated to attempt 2
    os.environ["FAA_ATTEMPT"] = "2"
    assert plan.serve_fault(1) == ("error", 0.0)
    assert plan.serve_fault(1) is None  # fire-once


def test_serve_fault_consume_order():
    os.environ["FAA_FAULT"] = (
        "serve_error@dispatch=1;serve_slow@dispatch=2,factor=3.0")
    faultinject.reset()
    plan = faultinject.active_plan()
    assert plan.serve_fault(1) == ("error", 0.0)
    assert plan.serve_fault(2) == ("slow", 3.0)
    assert plan.serve_fault(3) is None


# ------------------------------------------------------ hot reload


def test_swap_applier_between_dispatches():
    a, b = DummyApplier(1.0), DummyApplier(5.0)
    srv = PolicyServer(a, max_wait_ms=1).start()
    imgs = _images(1)
    assert srv.augment(imgs, _keys(1), timeout=10.0)[0, 0, 0, 0] \
        == imgs[0, 0, 0, 0] + 1.0
    info = srv.swap_applier(b)
    assert info["reloads"] == 1
    assert srv.augment(imgs, _keys(1), timeout=10.0)[0, 0, 0, 0] \
        == imgs[0, 0, 0, 0] + 5.0
    assert srv.stats()["reloads"] == 1
    srv.stop()


def test_swap_applier_validates_contract():
    srv = PolicyServer(DummyApplier(max_batch=4))
    with pytest.raises(ValueError):  # smaller AOT coverage
        srv.swap_applier(DummyApplier(max_batch=2))
    with pytest.raises(ValueError):  # dispatch-mode change
        srv.swap_applier(DummyApplier(dispatch="grouped"))
    bad = DummyApplier()
    bad.image = 16
    with pytest.raises(ValueError):  # geometry change
        srv.swap_applier(bad)


def test_reload_atomic_under_concurrent_traffic_dummy():
    """Hammer requests while swapping appliers: every response must be
    ENTIRELY one applier's output (delta 1 or delta 5) — no half-policy
    batch, zero dropped requests."""
    a, b = DummyApplier(1.0, max_batch=8), DummyApplier(5.0, max_batch=8)
    srv = PolicyServer(a, max_wait_ms=2).start()
    imgs = _images(4, seed=3)
    results = []
    errors = []

    def client():
        for _ in range(40):
            try:
                results.append(srv.augment(imgs, _keys(4), timeout=10.0))
            except ServeError as e:  # pragma: no cover — would fail below
                errors.append(e)

    threads = [threading.Thread(target=client) for _ in range(3)]
    for t in threads:
        t.start()
    for i in range(6):
        time.sleep(0.01)
        srv.swap_applier(b if i % 2 == 0 else a)
    for t in threads:
        t.join(timeout=30.0)
    srv.stop()
    assert not errors and len(results) == 120  # zero dropped requests
    for out in results:
        deltas = np.unique(out - imgs)
        assert deltas.size == 1 and deltas[0] in (1.0, 5.0), \
            "half-policy response: mixed deltas within one request"
    assert srv.reloads == 6


# --------------------------------------------------- graceful drain


def test_drain_finishes_inflight_then_rejects():
    ap = DummyApplier()
    srv = PolicyServer(ap, max_batch=1, max_wait_ms=1)
    pend = [srv.submit(_images(1), _keys(1)) for _ in range(3)]
    srv.start()
    assert srv.drain(timeout=10.0)
    for p in pend:
        assert p.result is not None  # in-flight completed, not errored
    assert ap.calls == 3
    with pytest.raises(ServerStoppedError):
        srv.submit(_images(1), _keys(1))
    assert srv.stats()["draining"] is True


def test_stop_errors_leftovers_with_typed_error():
    srv = PolicyServer(DummyApplier())
    p = srv.submit(_images(1), _keys(1))
    srv.start()  # worker may or may not pick it up before stop
    srv.stop()
    # either served before the stop won the race, or typed-stopped
    if p.error is not None:
        assert isinstance(p.error, ServerStoppedError)


# ------------------------------------------------------- serve_cli


def test_serve_cli_parser_overload_defaults():
    from fast_autoaugment_tpu.serve.serve_cli import build_parser

    args = build_parser().parse_args(["--policy", "x.json"])
    # bit-for-bit defaults: every overload knob off
    assert args.queue_depth == 4096 and args.default_deadline_ms is None
    assert args.lifo_depth == 0 and args.lifo_age_ms == 0.0
    assert args.breaker_threshold == 0 and not args.breaker_exit
    assert args.dispatch_timeout == 0.0 and args.watchdog == "off"
    assert args.max_inflight == 0 and args.serve_seconds == 0.0
    assert args.heartbeat_dir is None and args.port_file is None


def _http(port, method, path, body=None, headers=None, timeout=30):
    import http.client

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    conn.request(method, path, body=body, headers=headers or {})
    resp = conn.getresponse()
    data = resp.read()
    conn.close()
    return resp, data


def _start_http(server, state=None, **kw):
    from http.server import ThreadingHTTPServer

    from fast_autoaugment_tpu.serve.serve_cli import make_handler

    httpd = ThreadingHTTPServer(
        ("127.0.0.1", 0),
        make_handler(server, server.applier, state=state, **kw))
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd, httpd.server_address[1]


def test_http_structured_errors_and_readyz():
    """Handler hardening on a host-only dummy server: 400/413/429
    structured JSON, /healthz vs /readyz split."""
    from fast_autoaugment_tpu.serve.serve_cli import ServeState

    srv = PolicyServer(DummyApplier(dispatch="grouped"), queue_depth=1)
    state = ServeState(srv, "unused.json")
    httpd, port = _start_http(srv, state, max_body_bytes=4096)
    try:
        # liveness vs readiness: worker not started -> alive, not ready
        resp, data = _http(port, "GET", "/healthz")
        assert resp.status == 200 and json.loads(data)["ok"] is True
        resp, data = _http(port, "GET", "/readyz")
        body = json.loads(data)
        assert resp.status == 503 and body["ready"] is False
        assert "worker" in body["reason"]

        # malformed body -> 400 structured
        resp, data = _http(port, "POST", "/augment", body=b"not-an-npz")
        assert resp.status == 400
        assert json.loads(data)["type"] == "bad_request"

        # oversized body -> 413 without reading it all
        resp, data = _http(port, "POST", "/augment", body=b"x" * 8192)
        assert resp.status == 413
        assert json.loads(data)["type"] == "body_too_large"

        # malformed deadline header -> 400
        buf = io.BytesIO()
        np.savez(buf, images=_images(1).astype(np.uint8))
        resp, data = _http(port, "POST", "/augment", body=buf.getvalue(),
                           headers={"X-FAA-Deadline-Ms": "soon"})
        assert resp.status == 400

        # queue full (depth 1, worker down) -> 429 + Retry-After
        srv.submit(_images(1))
        resp, data = _http(port, "POST", "/augment", body=buf.getvalue())
        assert resp.status == 429
        assert json.loads(data)["type"] == "overloaded"
        assert int(resp.getheader("Retry-After")) >= 1

        # unknown path POST -> structured 404
        resp, data = _http(port, "POST", "/nope", body=b"{}")
        assert resp.status == 404
    finally:
        httpd.shutdown()
        httpd.server_close()
        srv.stop()


def test_http_deadline_header_propagates_and_sheds():
    """An expired X-FAA-Deadline-Ms request is shed with a structured
    503 — the handler thread is released at the deadline, not 60s
    later."""
    srv = PolicyServer(DummyApplier(dispatch="grouped"))
    srv.deadline_grace_s = 0.2
    httpd, port = _start_http(srv)  # worker never started: must expire
    try:
        buf = io.BytesIO()
        np.savez(buf, images=_images(1).astype(np.uint8))
        t0 = time.perf_counter()
        resp, data = _http(port, "POST", "/augment", body=buf.getvalue(),
                           headers={"X-FAA-Deadline-Ms": "100"})
        wall = time.perf_counter() - t0
        assert resp.status == 503
        assert json.loads(data)["type"] in ("deadline_expired", "timeout")
        assert wall < 5.0
    finally:
        httpd.shutdown()
        httpd.server_close()
        srv.stop()


def test_http_stats_carries_robustness_counters():
    srv = PolicyServer(DummyApplier(dispatch="grouped"), queue_depth=1)
    httpd, port = _start_http(srv)
    try:
        srv.submit(_images(1))
        with pytest.raises(ServerOverloadedError):
            srv.submit(_images(1))
        resp, data = _http(port, "GET", "/stats")
        stats = json.loads(data)
        assert resp.status == 200
        assert stats["admission"]["shed_overload"] == 1
        assert stats["breaker"]["state"] == "disabled"
        assert stats["reloads"] == 0 and stats["draining"] is False
    finally:
        httpd.shutdown()
        httpd.server_close()
        srv.stop()


def test_http_metrics_scrape_prometheus_exposition():
    """GET /metrics returns every serve_robustness counter in
    Prometheus text format, and the scraped values match /stats — one
    registry behind both views (core/telemetry.py)."""
    srv = PolicyServer(DummyApplier(dispatch="grouped"), queue_depth=1)
    httpd, port = _start_http(srv)
    try:
        srv.submit(_images(1))
        with pytest.raises(ServerOverloadedError):
            srv.submit(_images(1))
        resp, data = _http(port, "GET", "/metrics")
        text = data.decode()
        assert resp.status == 200
        assert resp.getheader("Content-Type").startswith("text/plain")
        assert "# TYPE faa_serve_robustness_total counter" in text
        for name in ("admitted", "shed_overload", "shed_breaker",
                     "shed_stopped", "expired", "deadline_misses",
                     "lifo_takes", "reloads"):
            assert f'counter="{name}"' in text, name
        # scraped values == /stats values for THIS server's label
        sid = srv._server_id
        scraped = {}
        for line in text.splitlines():
            if line.startswith("faa_serve_robustness_total") \
                    and f'server="{sid}"' in line:
                key = line.split('counter="', 1)[1].split('"', 1)[0]
                scraped[key] = float(line.rsplit(" ", 1)[1])
        adm = srv.stats()["admission"]
        assert scraped["admitted"] == adm["admitted"] == 1
        assert scraped["shed_overload"] == adm["shed_overload"] == 1
        assert scraped["expired"] == adm["expired"] == 0
    finally:
        httpd.shutdown()
        httpd.server_close()
        srv.stop()


def test_http_reload_not_configured_and_max_inflight():
    srv = PolicyServer(DummyApplier(dispatch="grouped"))
    httpd, port = _start_http(srv, max_inflight=1)
    try:
        resp, data = _http(port, "POST", "/reload", body=b"")
        assert resp.status == 503
        assert json.loads(data)["type"] == "not_configured"
    finally:
        httpd.shutdown()
        httpd.server_close()
        srv.stop()


# ------------------------------------------- fleet replica supervision


def test_fleet_no_rank_args_replica_restart(tmp_path, monkeypatch):
    """The serving-replica supervision contract: --no-rank-args launches
    the command VERBATIM (no --coordinator suffix), exit 77 is
    retry-eligible, and the relaunch (attempt 2) succeeds -> fleet exit
    0 with two attempts."""
    from fast_autoaugment_tpu.launch import fleet as fleet_mod

    def _argv(host, wire):
        return ["bash", "-c", wire]

    monkeypatch.setattr(fleet_mod, "_remote_argv", _argv)
    # $1 set => rank args were appended => exit 9 (contract violation);
    # attempt 1 exits 77 (breaker-exit), attempt 2 serves fine (exit 0)
    script = ("if [ -n \"$1\" ]; then exit 9; fi; "
              "if [ \"$FAA_ATTEMPT\" = \"1\" ]; then exit 77; fi; "
              "exit 0")
    code = fleet_mod.launch_fleet(
        ["replica"], ["bash", "-c", script], None,
        host_retries=1, retry_backoff=0.05, rank_args=False)
    assert code == 0


def test_fleet_rank_args_still_default(monkeypatch):
    """Without --no-rank-args the historical rank suffix is appended."""
    from fast_autoaugment_tpu.launch import fleet as fleet_mod

    def _argv(host, wire):
        return ["bash", "-c", wire]

    monkeypatch.setattr(fleet_mod, "_remote_argv", _argv)
    script = "if [ -n \"$1\" ]; then exit 0; fi; exit 9"
    code = fleet_mod.launch_fleet(["h"], ["bash", "-c", script], None)
    assert code == 0


def test_fleet_cli_no_rank_args_flag_parses(monkeypatch, capsys):
    from fast_autoaugment_tpu.launch import fleet as fleet_mod

    called = {}

    def fake_launch(hosts, command, coordinator, **kw):
        called.update(kw, hosts=hosts, command=command)
        return 0

    monkeypatch.setattr(fleet_mod, "launch_fleet", fake_launch)
    with pytest.raises(SystemExit) as ei:
        fleet_mod.main(["--hosts", "2", "--no-rank-args", "--", "echo", "x"])
    assert ei.value.code == 0
    assert called["rank_args"] is False and called["command"] == ["echo", "x"]


# ------------------------------------------------- slow chaos drills


SINGLE_SUB = np.array([[[4, 0.8, 0.7], [10, 0.5, 0.3]]], np.float32)
ALT_SUB = np.array([[[0, 0.9, 0.5], [1, 0.6, 0.4]]], np.float32)


@pytest.mark.slow
def test_http_chaos_breaker_readyz_flip():
    """The chaos drill on real AOT executables: injected serve_error
    opens the breaker, /readyz flips to 503 while /healthz stays 200,
    requests fail fast with typed JSON, and the post-cooldown probe
    returns the replica to ready."""
    from fast_autoaugment_tpu.serve.policy_server import AotPolicyApplier
    from fast_autoaugment_tpu.serve.serve_cli import ServeState

    os.environ["FAA_FAULT"] = "serve_error@dispatch=1;serve_error@dispatch=2"
    faultinject.reset()
    applier = AotPolicyApplier(SINGLE_SUB, image=IMG, shapes=(4,))
    srv = PolicyServer(applier, max_wait_ms=2, breaker_threshold=2,
                       breaker_cooldown_s=0.5).start()
    state = ServeState(srv, "unused.json")
    httpd, port = _start_http(srv, state)
    try:
        buf = io.BytesIO()
        np.savez(buf, images=_images(1, seed=4).astype(np.uint8))
        body = buf.getvalue()
        # two injected dispatch errors -> breaker opens
        for _ in range(2):
            resp, data = _http(port, "POST", "/augment", body=body)
            assert resp.status == 500
            assert json.loads(data)["type"] == "dispatch_error"
        resp, data = _http(port, "GET", "/readyz")
        assert resp.status == 503
        assert json.loads(data)["reason"] == "circuit breaker open"
        resp, _ = _http(port, "GET", "/healthz")
        assert resp.status == 200  # alive through the whole episode
        # fast-fail while open: typed JSON + Retry-After, no hang
        resp, data = _http(port, "POST", "/augment", body=body)
        assert resp.status == 503
        assert json.loads(data)["type"] == "breaker_open"
        assert resp.getheader("Retry-After") is not None
        time.sleep(0.6)
        # post-cooldown probe succeeds -> ready again
        resp, _ = _http(port, "POST", "/augment", body=body)
        assert resp.status == 200
        resp, data = _http(port, "GET", "/readyz")
        assert resp.status == 200 and json.loads(data)["ready"] is True
        resp, data = _http(port, "GET", "/stats")
        stats = json.loads(data)
        assert stats["breaker"]["fires"] == 1
        assert stats["admission"]["shed_breaker"] >= 1
    finally:
        httpd.shutdown()
        httpd.server_close()
        srv.stop()


@pytest.mark.slow
def test_reload_under_traffic_bitwise_per_applier():
    """Hot reload on real AOT executables under concurrent traffic:
    zero dropped requests and every response BITWISE one applier's
    output — never a mixture (the per-applier verification the
    acceptance demands)."""
    from fast_autoaugment_tpu.serve.policy_server import AotPolicyApplier

    ap_a = AotPolicyApplier(SINGLE_SUB, image=IMG, shapes=(4,))
    ap_b = AotPolicyApplier(ALT_SUB, image=IMG, shapes=(4,))
    srv = PolicyServer(ap_a, max_wait_ms=2).start()
    imgs = _images(2, seed=9)
    keys = np.stack([_jax_key(7), _jax_key(8)])
    ref_a = ap_a.apply(imgs, keys)
    ref_b = ap_b.apply(imgs, keys)
    assert not np.array_equal(ref_a, ref_b)  # the policies do differ
    results, errors = [], []

    def client():
        for _ in range(25):
            try:
                results.append(srv.augment(imgs, keys, timeout=30.0))
            except ServeError as e:  # pragma: no cover
                errors.append(e)

    threads = [threading.Thread(target=client) for _ in range(2)]
    for t in threads:
        t.start()
    for i in range(4):
        time.sleep(0.02)
        srv.swap_applier(ap_b if i % 2 == 0 else ap_a)
    for t in threads:
        t.join(timeout=60.0)
    srv.stop()
    assert not errors and len(results) == 50
    n_a = n_b = 0
    for out in results:
        if np.array_equal(out, ref_a):
            n_a += 1
        elif np.array_equal(out, ref_b):
            n_b += 1
        else:
            raise AssertionError("response matches NEITHER applier "
                                 "bitwise — half-policy batch")
    assert n_a + n_b == 50


def _jax_key(i):
    import jax

    return np.asarray(jax.random.PRNGKey(i), np.uint32)


def _write_tiny_policy(path):
    subs = [[["Rotate", 0.5, 0.4], ["Invert", 0.2, 0.0]]]
    path.write_text(json.dumps(subs))
    return str(path)


def _wait_port_file(path, proc, timeout=120.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if os.path.exists(path) and open(path).read().strip():
            return int(open(path).read().strip())
        if proc.poll() is not None:
            raise AssertionError(
                f"serve replica died before binding: rc={proc.returncode}")
        time.sleep(0.2)
    raise AssertionError("serve replica never wrote its port file")


@pytest.mark.slow
def test_serve_replica_breaker_exit_restart_ready(tmp_path):
    """The replica-restart drill as the fleet supervisor runs it:
    attempt 1 hits an attempt-gated serve_error, the breaker opens,
    --breaker-exit maps it to exit 77 (restart me); attempt 2 (the
    relaunch) serves cleanly, /readyz returns 200, and SIGTERM drains
    to exit 0."""
    policy = _write_tiny_policy(tmp_path / "p.json")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               FAA_FAULT="serve_error@dispatch=1,attempt=1")
    base_cmd = [
        sys.executable, "-m", "fast_autoaugment_tpu.serve.serve_cli",
        "--policy", policy, "--image", str(IMG), "--shapes", "1,4",
        "--max-wait-ms", "2", "--queue-depth", "16",
        "--breaker-threshold", "1", "--breaker-cooldown", "60",
        "--breaker-exit", "--port", "0",
        "--heartbeat-dir", str(tmp_path / "q"),
    ]
    buf = io.BytesIO()
    np.savez(buf, images=_images(1, seed=5).astype(np.uint8))
    body = buf.getvalue()

    # ---- attempt 1: injected dispatch error -> breaker -> exit 77
    port_file = tmp_path / "port1"
    env["FAA_ATTEMPT"] = "1"
    p1 = subprocess.Popen(base_cmd + ["--port-file", str(port_file)],
                          env=env, cwd=_REPO)
    try:
        port = _wait_port_file(str(port_file), p1)
        resp, data = _http(port, "POST", "/augment", body=body, timeout=60)
        assert resp.status == 500  # the injected failure
        rc = p1.wait(timeout=60)
        assert rc == PREEMPTED_EXIT_CODE  # 77: restart me
    finally:
        if p1.poll() is None:
            p1.kill()
            p1.wait(timeout=10)

    # ---- attempt 2 (the supervisor's relaunch): clean and ready
    port_file2 = tmp_path / "port2"
    env["FAA_ATTEMPT"] = "2"
    p2 = subprocess.Popen(base_cmd + ["--port-file", str(port_file2)],
                          env=env, cwd=_REPO)
    try:
        port = _wait_port_file(str(port_file2), p2)
        resp, data = _http(port, "GET", "/readyz", timeout=60)
        assert resp.status == 200 and json.loads(data)["ready"] is True
        resp, _ = _http(port, "POST", "/augment", body=body, timeout=60)
        assert resp.status == 200
        # host beats flow in the fleet schema the supervisor consumes
        # (first beat lands one interval after startup — poll briefly)
        beat_path = tmp_path / "q" / "hosts" / "host0.json"
        t0 = time.monotonic()
        while not beat_path.exists() and time.monotonic() - t0 < 15:
            time.sleep(0.2)
        beat = json.load(open(beat_path))
        assert beat["heartbeat"] > 0
        # SIGTERM: graceful drain, exit 0 (the serving exit contract)
        p2.send_signal(signal.SIGTERM)
        assert p2.wait(timeout=60) == 0
    finally:
        if p2.poll() is None:
            p2.kill()
            p2.wait(timeout=10)


@pytest.mark.slow
def test_http_reload_endpoint_roundtrip(tmp_path):
    """POST /reload swaps to a new final_policy.json under live HTTP:
    the response reports the swap and subsequent requests serve the new
    policy bitwise."""
    from fast_autoaugment_tpu.serve.policy_server import AotPolicyApplier
    from fast_autoaugment_tpu.serve.serve_cli import (
        ServeState,
        build_policy_tensor,
    )

    p_a = tmp_path / "a.json"
    p_a.write_text(json.dumps([[["Rotate", 0.5, 0.4], ["Invert", 0.2, 0.0]]]))
    p_b = tmp_path / "b.json"
    p_b.write_text(json.dumps([[["ShearX", 0.9, 0.1], ["Solarize", 0.3, 0.7]]]))

    def build_applier(policy_tensor):
        return AotPolicyApplier(policy_tensor, image=IMG, shapes=(4,),
                                dispatch="exact")

    ap = build_applier(build_policy_tensor(str(p_a)))
    srv = PolicyServer(ap, max_wait_ms=2).start()
    state = ServeState(srv, str(p_a), build_applier)
    httpd, port = _start_http(srv, state)
    try:
        imgs = _images(2, seed=11)
        seeds = np.arange(2)
        buf = io.BytesIO()
        np.savez(buf, images=imgs.astype(np.uint8), seeds=seeds)
        body = buf.getvalue()

        resp, data = _http(port, "POST", "/augment", body=body, timeout=60)
        assert resp.status == 200

        resp, data = _http(port, "POST", "/reload",
                           body=json.dumps({"policy": str(p_b)}).encode(),
                           timeout=120)
        assert resp.status == 200
        info = json.loads(data)
        assert info["reloaded"] is True and info["policy"] == str(p_b)

        resp, data = _http(port, "POST", "/augment", body=body, timeout=60)
        assert resp.status == 200
        got = np.load(io.BytesIO(data))["images"]
        from fast_autoaugment_tpu.serve.serve_cli import _seed_keys

        ap_b = build_applier(build_policy_tensor(str(p_b)))
        ref = np.clip(ap_b.apply(imgs, _seed_keys(seeds)),
                      0, 255).astype(np.uint8)
        assert np.array_equal(got, ref)
        assert srv.reloads == 1
    finally:
        httpd.shutdown()
        httpd.server_close()
        srv.stop()


# ---------------------------------------------------------- bench hook


@pytest.mark.slow
def test_bench_overload_smoke(capsys):
    """tools/bench_serve.py --overload end-to-end at a tiny shape: the
    JSON line carries the sweep schema (goodput/shed/miss per arm,
    shedding on AND off) and the robustness counter stamps."""
    import bench_serve

    rc = bench_serve.main([
        "--overload", "--image", str(IMG), "--num-sub", "1",
        "--shapes", "1,4", "--overload-imgs-per-request", "4",
        "--multipliers", "1,4", "--overload-seconds", "0.4",
        "--deadline-ms", "50", "--max-wait-ms", "1",
        "--overload-queue-depth", "8"])
    assert rc == 0
    line = [ln for ln in capsys.readouterr().out.splitlines()
            if ln.startswith("{")][-1]
    out = json.loads(line)
    assert out["metric"] == "serve_overload_goodput"
    assert out["capacity_qps"] > 0 and out["bitwise_match"] is True
    assert len(out["arms"]) == 4  # 2 multipliers x shedding on/off
    sheds = {(a["shedding"], a["multiplier"]) for a in out["arms"]}
    assert sheds == {("on", 1.0), ("on", 4.0), ("off", 1.0), ("off", 4.0)}
    for arm in out["arms"]:
        assert "goodput_rps" in arm and "shed_rate" in arm
        assert "deadline_miss_rate" in arm
        assert "p99" in arm["admitted_latency_ms"]
        assert "breaker_fires" in arm["serve_robustness"]


def test_bench_robustness_stamp_shape():
    import bench_serve

    srv = PolicyServer(DummyApplier(), queue_depth=1)
    srv.submit(_images(1), _keys(1))
    with pytest.raises(ServerOverloadedError):
        srv.submit(_images(1), _keys(1))
    stamp = bench_serve._robustness_stamp(srv.stats())
    assert stamp["admitted"] == 1 and stamp["shed_overload"] == 1
    assert stamp["breaker_state"] == "disabled" and stamp["reloads"] == 0
