"""Donation correctness for the zero-copy serving data plane
(fast_autoaugment_tpu/serve/policy_server.py ``donate=True`` +
``PolicyServer(double_buffer=True)``).

The invariants pinned here, bitwise across every AOT shape:

- donated dispatch serves the SAME bytes as the undonated PR-7 path —
  donation may only change buffer ownership, never results;
- a donated input staging buffer is never read after dispatch: the
  materialized result owns its memory (mutating the staging arrays
  afterwards cannot corrupt an already-returned batch);
- the two standing staging buffers never alias, and batch k+1's
  staging never overwrites batch k's still-in-flight input (the
  double-buffer invariant the pipelined server relies on);
- pad rows are zeroed on every reuse — a poisoned (previously used)
  staging buffer must not leak old pixels into the padded lanes;
- the CPU fallback is silent: donation is ignored-with-a-filtered-
  warning on backends without buffer donation, not a per-dispatch
  warning spray.

Tiny 8px images and shapes (2, 4) keep the extra AOT compiles in the
tier-1 seconds budget.
"""

import warnings

import jax
import numpy as np
import pytest

from fast_autoaugment_tpu.serve.policy_server import (
    AotPolicyApplier,
    PolicyServer,
)

IMG = 8
SINGLE_SUB = np.array([[[4, 0.8, 0.7], [10, 0.5, 0.3]]], np.float32)
MULTI_SUB = np.array([
    [[4, 0.8, 0.7], [10, 0.5, 0.3]],
    [[0, 0.5, 0.5], [1, 0.5, 0.5]],
], np.float32)


def _images(n, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 256, (n, IMG, IMG, 3)).astype(np.float32)


def _keys(n, base=0):
    return np.stack([np.asarray(jax.random.PRNGKey(base + i), np.uint32)
                     for i in range(n)])


@pytest.fixture(scope="module")
def plain():
    """The undonated PR-7 reference applier (exact, single-sub)."""
    return AotPolicyApplier(SINGLE_SUB, image=IMG, shapes=(2, 4),
                            dispatch="exact")


@pytest.fixture(scope="module")
def donated():
    """Same policy/shapes, donated + double-buffered staging."""
    return AotPolicyApplier(SINGLE_SUB, image=IMG, shapes=(2, 4),
                            dispatch="exact", donate=True)


def test_donated_matches_undonated_bitwise_every_shape(plain, donated):
    # every batch size across both AOT shapes, including the padded
    # ones (n=1 pads to 2, n=3 pads to 4) and the exact fits
    for n in (1, 2, 3, 4):
        imgs, keys = _images(n, seed=n), _keys(n, base=10 * n)
        want = plain.apply(imgs.copy(), keys)
        got = donated.apply(imgs.copy(), keys)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_grouped_donated_matches_undonated():
    imgs = _images(3, seed=7)
    key = np.asarray(jax.random.PRNGKey(3), np.uint32)
    plain_g = AotPolicyApplier(MULTI_SUB, image=IMG, shapes=(4,),
                               dispatch="grouped", groups=2)
    don_g = AotPolicyApplier(MULTI_SUB, image=IMG, shapes=(4,),
                             dispatch="grouped", groups=2, donate=True)
    np.testing.assert_array_equal(
        np.asarray(don_g.apply(imgs.copy(), key)),
        np.asarray(plain_g.apply(imgs.copy(), key)))


def test_multichunk_donated_matches_undonated(plain, donated):
    # n > max AOT shape: the chunked path forces each donated chunk
    # synchronous (two slots only guarantee one overlap step)
    imgs, keys = _images(7, seed=21), _keys(7, base=70)
    np.testing.assert_array_equal(
        np.asarray(donated.apply(imgs.copy(), keys)),
        np.asarray(plain.apply(imgs.copy(), keys)))


def test_pad_rows_never_leak_from_reused_staging(plain, donated):
    # poison BOTH standing slots with old pixels, then serve padded
    # batches twice (hitting both slots): results must match the
    # fresh-allocation path bitwise — the pad lanes were re-zeroed
    for s, bufs in donated._staging.items():
        for buf in bufs:
            buf.fill(123.0)
    for rep in range(2):
        imgs, keys = _images(3, seed=30 + rep), _keys(3, base=300 + rep)
        np.testing.assert_array_equal(
            np.asarray(donated.apply(imgs.copy(), keys)),
            np.asarray(plain.apply(imgs.copy(), keys)))


def test_result_does_not_alias_staging(donated):
    imgs, keys = _images(2, seed=5), _keys(2, base=50)
    out = np.asarray(donated.apply(imgs, keys))
    ref = out.copy()
    # scribble over every staging buffer AFTER the apply returned: a
    # result that aliased host staging would corrupt here
    for bufs in donated._staging.values():
        for buf in bufs:
            buf.fill(-1.0)
    for kbufs in donated._staging_keys.values():
        for kbuf in kbufs:
            kbuf.fill(0)
    np.testing.assert_array_equal(out, ref)
    for bufs in donated._staging.values():
        for buf in bufs:
            assert not np.shares_memory(out, buf)


def test_double_buffers_are_distinct_arrays(donated):
    for s, bufs in donated._staging.items():
        assert len(bufs) == 2
        assert bufs[0] is not bufs[1]
        assert not np.shares_memory(bufs[0], bufs[1])


def test_inflight_batch_survives_next_stage(plain, donated):
    # the pipelined server's exact overlap shape: dispatch batch A,
    # stage + dispatch batch B while A is still in flight, THEN
    # materialize A — B's staging must not have overwritten A's input
    a_imgs, a_keys = _images(2, seed=41), _keys(2, base=410)
    b_imgs, b_keys = _images(2, seed=42), _keys(2, base=420)
    want_a = np.asarray(plain.apply(a_imgs.copy(), a_keys))
    want_b = np.asarray(plain.apply(b_imgs.copy(), b_keys))
    h_a = donated.apply_async(a_imgs.copy(), a_keys)
    h_b = donated.apply_async(b_imgs.copy(), b_keys)
    np.testing.assert_array_equal(np.asarray(h_a.materialize()), want_a)
    np.testing.assert_array_equal(np.asarray(h_b.materialize()), want_b)


def test_cpu_donation_warning_is_filtered():
    # on backends without donation support, lowering warns-and-ignores
    # per executable; the compile seam (core/compilecache.aot_compile)
    # filters that spray — compiling a donating applier and serving
    # with it must not surface a single donation warning.  The seam's
    # filter is installed INSIDE aot_compile's catch_warnings block, so
    # it wins over this test's "always" filter.
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        app = AotPolicyApplier(SINGLE_SUB, image=IMG, shapes=(2,),
                               dispatch="exact", donate=True)
        app.apply(_images(2, seed=9), _keys(2, base=90))
    spray = [w for w in caught
             if "donat" in str(w.message).lower()]
    assert spray == []


def test_double_buffered_server_matches_sequential(plain, donated):
    # end to end through the coalescer: a pipelined double-buffered
    # server over the donated applier serves the same bytes as the
    # strictly sequential default server over the undonated applier
    seq = PolicyServer(plain, max_wait_ms=1.0).start()
    dbuf = PolicyServer(donated, max_wait_ms=1.0,
                        double_buffer=True).start()
    try:
        batches = [( _images(n, seed=60 + n), _keys(n, base=600 + n))
                   for n in (1, 2, 3, 2)]
        want = [np.asarray(seq.result(seq.submit(i.copy(), k)))
                for i, k in batches]
        pend = [dbuf.submit(i.copy(), k) for i, k in batches]
        got = [np.asarray(dbuf.result(p, timeout=60.0)) for p in pend]
        for w, g in zip(want, got):
            np.testing.assert_array_equal(g, w)
        stats = dbuf.stats()
        assert stats["data_plane"] == {"donate": True,
                                       "double_buffer": True}
    finally:
        seq.stop()
        dbuf.stop()
