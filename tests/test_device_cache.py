"""Device-resident dataset cache + multi-step fused train dispatch:
index-matrix parity with the host iterators, DeviceCache placement,
dispatch chunk clamping, multistep scan parity (sequential + stacked),
trainer-level checkpoint equivalence and resume across dispatch
boundaries, lazy force-off, driver stamping/accounting, CLI flags."""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fast_autoaugment_tpu.core.config import Config
from fast_autoaugment_tpu.data.datasets import ArrayDataset


def _conf(**over):
    base = {
        "model": {"type": "wresnet10_1"},
        "dataset": "synthetic",
        "aug": "default",
        "cutout": 8,
        "batch": 8,
        "epoch": 1,
        "lr": 0.05,
        "lr_schedule": {"type": "cosine", "warmup": {"multiplier": 2, "epoch": 1}},
        "optimizer": {"type": "sgd", "decay": 2e-4, "clip": 5.0,
                      "momentum": 0.9, "nesterov": True},
    }
    base.update(over)
    return Config(base)


def _dataset(n=64, img=4, seed=0):
    rng = np.random.default_rng(seed)
    return ArrayDataset(
        rng.integers(0, 256, (n, img, img, 3), dtype=np.uint8),
        rng.integers(0, 10, (n,), np.int32), 10)


# ------------------------------------------------- index-matrix parity

def test_train_index_matrix_matches_train_batches():
    """The matrix IS what train_batches walks — row s must equal the
    s-th yielded batch's indices (same permutation, drop-last, shard)."""
    from fast_autoaugment_tpu.data.pipeline import (
        train_batches,
        train_index_matrix,
    )

    ds = _dataset(70)
    idx = np.arange(13, 61)
    mat = train_index_matrix(idx, 8, epoch=5, seed=3)
    assert mat.shape == (6, 8)  # 48 // 8, drop-last
    got = list(train_batches(ds, idx, 8, epoch=5, seed=3))
    assert len(got) == len(mat)
    for row, (x, y) in zip(mat, got):
        np.testing.assert_array_equal(x, ds.images[row])
        np.testing.assert_array_equal(y, ds.labels[row])
    # per-process sharding: each process's matrix is its contiguous shard
    m0 = train_index_matrix(idx, 8, epoch=5, seed=3,
                            process_index=0, process_count=2)
    m1 = train_index_matrix(idx, 8, epoch=5, seed=3,
                            process_index=1, process_count=2)
    np.testing.assert_array_equal(np.concatenate([m0, m1], axis=1), mat)


def test_stacked_index_matrix_matches_stacked_batches():
    from fast_autoaugment_tpu.data.pipeline import (
        stacked_index_matrix,
        stacked_train_batches,
    )

    ds = _dataset(64)
    folds = [np.arange(32), np.arange(16)]  # 4 vs 2 steps at batch 8
    chunks, active = stacked_index_matrix(folds, 8, epoch=2, seeds=[0, 7])
    assert chunks.shape == (4, 2, 8) and active.shape == (4, 2)
    np.testing.assert_array_equal(active[:, 1], [1, 1, 0, 0])
    for s, (x, y, a) in enumerate(
            stacked_train_batches(ds, folds, 8, epoch=2, seeds=[0, 7])):
        np.testing.assert_array_equal(a, active[s])
        np.testing.assert_array_equal(x, ds.images[chunks[s]])
        np.testing.assert_array_equal(y, ds.labels[chunks[s]])


def test_split_dispatch_chunks_clamps_remainder():
    from fast_autoaugment_tpu.data.pipeline import split_dispatch_chunks

    assert split_dispatch_chunks(10, 1) == [1] * 10
    assert split_dispatch_chunks(10, 4) == [4, 4, 2]
    assert split_dispatch_chunks(4, 4) == [4]
    assert split_dispatch_chunks(3, 8) == [3]  # N clamped to the epoch
    assert split_dispatch_chunks(0, 4) == []
    with pytest.raises(ValueError, match="steps_per_dispatch"):
        split_dispatch_chunks(10, 0)


# --------------------------------------------- cache placement/resolve

def test_device_cache_contents_and_padding(devices8):
    from fast_autoaugment_tpu.data.pipeline import DeviceCache
    from fast_autoaugment_tpu.parallel.mesh import make_mesh

    ds = _dataset(n=13)  # not a multiple of 8 devices -> padded
    cache = DeviceCache(ds, make_mesh(devices8))
    assert cache.num_examples == 13
    assert cache.images.shape[0] == 16 and cache.labels.shape[0] == 16
    np.testing.assert_array_equal(np.asarray(cache.images)[:13], ds.images)
    np.testing.assert_array_equal(np.asarray(cache.labels)[:13], ds.labels)
    assert not np.any(np.asarray(cache.images)[13:])  # zero pad rows
    assert cache.nbytes == ds.images.nbytes + ds.labels.nbytes
    lazy = ArrayDataset(np.asarray(["a.jpg"] * 4, object),
                        np.zeros(4, np.int32), 10, lazy=True)
    with pytest.raises(ValueError, match="in-memory"):
        DeviceCache(lazy, make_mesh(devices8))


def test_resolve_device_cache_gates():
    from fast_autoaugment_tpu.data.pipeline import resolve_device_cache

    eager = _dataset(4)
    lazy = ArrayDataset(np.asarray(["a.jpg"] * 4, object),
                        np.zeros(4, np.int32), 10, lazy=True)
    assert resolve_device_cache("auto", eager) is True
    assert resolve_device_cache("auto", lazy) is False  # lazy forces off
    assert resolve_device_cache("auto", eager, process_count=2) is False
    assert resolve_device_cache("off", eager) is False
    assert resolve_device_cache("on", eager) is True
    with pytest.raises(ValueError, match="lazy"):
        resolve_device_cache("on", lazy)  # explicit ask fails LOUDLY
    with pytest.raises(ValueError, match="multi-host"):
        resolve_device_cache("on", eager, process_count=2)
    with pytest.raises(ValueError, match="unknown device-cache"):
        resolve_device_cache("maybe", eager)


def test_place_index_matrix_shapes(devices8):
    from fast_autoaugment_tpu.parallel.mesh import (
        make_fold_mesh,
        make_mesh,
        place_index_matrix,
        place_stacked_index_matrix,
    )

    idx = np.arange(16).reshape(2, 8)
    dev = place_index_matrix(make_mesh(devices8), idx)
    assert dev.shape == (2, 8) and dev.dtype == jnp.int32
    np.testing.assert_array_equal(np.asarray(dev), idx)
    mesh = make_fold_mesh(2, devices8)
    st = np.arange(32).reshape(2, 2, 8)
    act = np.ones((2, 2), np.float32)
    i_dev, a_dev = place_stacked_index_matrix(mesh, st, act)
    assert i_dev.shape == (2, 2, 8) and a_dev.shape == (2, 2)
    np.testing.assert_array_equal(np.asarray(i_dev), st)


def test_steps_per_dispatch_requires_cache(tmp_path):
    from fast_autoaugment_tpu.train.trainer import train_and_eval

    with pytest.raises(ValueError, match="needs the device"):
        train_and_eval(_conf(), str(tmp_path), test_ratio=0.4,
                       device_cache="off", steps_per_dispatch=4)


# ------------------------------------------------- multistep step parity

@pytest.mark.slow
def test_multistep_n1_bitwise_matches_host_step(devices8):
    """The N=1 multistep program (gather + body, no scan) from the
    device cache is BIT-FOR-BIT the host-fed jitted step — the property
    that makes the default flags a pure transport change.  Slow-marked
    per the tier-1 wall-budget discipline (compile-heavy; the slow
    trainer-level default-equivalence test pins the same property
    end-to-end)."""
    from fast_autoaugment_tpu.data.pipeline import (
        DeviceCache,
        train_index_matrix,
    )
    from fast_autoaugment_tpu.models import get_model
    from fast_autoaugment_tpu.ops.optim import build_optimizer
    from fast_autoaugment_tpu.parallel.mesh import (
        make_mesh,
        place_index_matrix,
        replicated,
        shard_batch,
    )
    from fast_autoaugment_tpu.train.steps import (
        create_train_state,
        make_multistep_train_step,
        make_train_step,
        make_train_step_body,
    )

    mesh = make_mesh(devices8)
    model = get_model({"type": "wresnet10_1"}, 10)
    opt_conf = dict(_conf()["optimizer"])
    kw = dict(num_classes=10, cutout_length=4, use_policy=False)
    sample = jnp.zeros((2, 8, 8, 3), jnp.float32)
    ds = _dataset(n=64, img=8)
    pol = jnp.zeros((1, 1, 3), jnp.float32)
    key = jax.random.PRNGKey(3)
    mat = train_index_matrix(np.arange(64), 16, epoch=1, seed=0)  # 4 steps

    def fresh():
        opt = build_optimizer(opt_conf, lambda s: 0.05)
        return create_train_state(model, opt, jax.random.PRNGKey(0), sample,
                                  use_ema=False)

    opt = build_optimizer(opt_conf, lambda s: 0.05)
    host_step = make_train_step(model, opt, **kw)
    s_host = fresh()
    for row in mat:
        b = shard_batch(mesh, {"x": ds.images[row], "y": ds.labels[row]})
        s_host, m_host = host_step(s_host, b["x"], b["y"], pol, key)

    cache = DeviceCache(ds, mesh)
    multi = make_multistep_train_step(
        make_train_step_body(model, opt, **kw), steps_per_dispatch=1)
    rep = replicated(mesh)
    s_dev = jax.device_put(fresh(), rep)
    pol_c, key_c = jax.device_put(pol, rep), jax.device_put(key, rep)
    for row in mat:
        s_dev, m_dev = multi(s_dev, cache.images, cache.labels,
                             place_index_matrix(mesh, row[None]), pol_c, key_c)
    for a, b in zip(jax.tree.leaves(s_host), jax.tree.leaves(s_dev)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for k in m_host:
        np.testing.assert_array_equal(np.asarray(m_host[k]),
                                      np.asarray(m_dev[k]))


@pytest.mark.slow
def test_multistep_scan_parity_sequential_and_stacked(devices8):
    """N>1 parity for both bodies, rolled AND unrolled: the fused
    program matches the per-step path to the documented ~1 f32 ULP/step
    bound — the fold-stacking deviation class (fusing several steps into
    one program lets XLA reorder sharded-kernel reductions across them,
    with or without a while loop; only N=1 is bitwise, which is why it
    is the default).  Stacked lanes that go inactive mid-dispatch do
    not take the masked step."""
    from fast_autoaugment_tpu.data.pipeline import DeviceCache
    from fast_autoaugment_tpu.models import get_model
    from fast_autoaugment_tpu.ops.optim import build_optimizer
    from fast_autoaugment_tpu.parallel.mesh import make_mesh, replicated
    from fast_autoaugment_tpu.train.steps import (
        create_train_state,
        default_dispatch_unroll,
        make_multistep_train_step,
        make_stacked_step_body,
        make_stacked_train_step,
        make_train_step_body,
        slice_state,
        stack_states,
    )

    assert default_dispatch_unroll(4) == 4  # cpu backend: full unroll
    mesh = make_mesh(devices8)
    rep = replicated(mesh)
    model = get_model({"type": "wresnet10_1"}, 10)
    opt_conf = dict(_conf()["optimizer"])
    kw = dict(num_classes=10, cutout_length=4, use_policy=False)
    sample = jnp.zeros((2, 8, 8, 3), jnp.float32)
    ds = _dataset(n=64, img=8)
    pol = jax.device_put(jnp.zeros((1, 1, 3), jnp.float32), rep)
    key = jax.device_put(jax.random.PRNGKey(3), rep)
    rng = np.random.default_rng(1)

    def fresh(seed=0):
        opt = build_optimizer(opt_conf, lambda s: 0.05)
        return create_train_state(model, opt, jax.random.PRNGKey(seed),
                                  sample, use_ema=False)

    opt = build_optimizer(opt_conf, lambda s: 0.05)
    cache = DeviceCache(ds, mesh)
    body = make_train_step_body(model, opt, **kw)
    mat = rng.permutation(64)[:4 * 16].reshape(4, 16)

    multi1 = make_multistep_train_step(body, steps_per_dispatch=1)
    s1 = jax.device_put(fresh(), rep)
    for row in mat:
        s1, _ = multi1(s1, cache.images, cache.labels,
                       jnp.asarray(row[None], jnp.int32), pol, key)
    for n_label, unroll in (("unrolled", None), ("rolled", 1)):
        multi4 = make_multistep_train_step(body, steps_per_dispatch=4,
                                           unroll=unroll)
        s4 = jax.device_put(fresh(), rep)
        s4, _ = multi4(s4, cache.images, cache.labels,
                       jnp.asarray(mat, jnp.int32), pol, key)
        for a, b in zip(jax.tree.leaves(s1.params), jax.tree.leaves(s4.params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6,
                                       err_msg=n_label)
        assert int(s4.step) == 4

    # stacked: scan outside the fold vmap, inactive lane frozen bitwise
    K = 2
    st_body = make_stacked_step_body(model, opt, **kw)
    st_step = make_stacked_train_step(model, opt, **kw)
    keys = jax.device_put(
        jnp.stack([jax.random.PRNGKey(100 + k) for k in range(K)]), rep)
    idx_st = rng.permutation(64)[:2 * K * 8].reshape(2, K, 8)
    act = np.asarray([[1.0, 1.0], [1.0, 0.0]], np.float32)  # lane 1 dies
    s_ref = stack_states([fresh(k) for k in range(K)])
    for t in range(2):
        s_ref, _ = st_step(s_ref, jnp.asarray(ds.images[idx_st[t]]),
                           jnp.asarray(ds.labels[idx_st[t]]),
                           jnp.zeros((1, 1, 3), jnp.float32), keys,
                           jnp.asarray(act[t]))
    multi_st = make_multistep_train_step(st_body, steps_per_dispatch=2,
                                         stacked=True)
    s_st = jax.device_put(stack_states([fresh(k) for k in range(K)]), rep)
    s_st, metrics = multi_st(s_st, cache.images, cache.labels,
                             jnp.asarray(idx_st, jnp.int32), pol, keys,
                             jnp.asarray(act))
    for k in range(K):
        for a, b in zip(jax.tree.leaves(slice_state(s_ref, k).params),
                        jax.tree.leaves(slice_state(s_st, k).params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)
    assert int(slice_state(s_st, 1).step) == 1  # masked step not taken
    assert int(slice_state(s_st, 0).step) == 2
    assert metrics["num"].shape == (K,)


# --------------------------------------------- trainer-level equivalence

@pytest.mark.slow
def test_trainer_device_cache_default_bitwise_equivalence(tmp_path, devices8):
    """The acceptance pin: default flags (device_cache=auto,
    steps_per_dispatch=1) on an eager dataset produce a BIT-FOR-BIT
    identical checkpoint to the host-fed path (and the replayed eval
    split reports identical metrics)."""
    from fast_autoaugment_tpu.core.checkpoint import load_checkpoint
    from fast_autoaugment_tpu.models import get_model
    from fast_autoaugment_tpu.ops.optim import build_optimizer
    from fast_autoaugment_tpu.parallel.mesh import make_mesh
    from fast_autoaugment_tpu.train.steps import create_train_state
    from fast_autoaugment_tpu.train.trainer import train_and_eval

    conf = _conf()
    tmp = str(tmp_path)
    mesh = make_mesh(devices8)
    r_off = train_and_eval(conf, tmp, test_ratio=0.4, cv_fold=0,
                           save_path=f"{tmp}/off.msgpack", metric="last",
                           seed=0, evaluation_interval=1, mesh=mesh,
                           device_cache="off")
    r_on = train_and_eval(conf, tmp, test_ratio=0.4, cv_fold=0,
                          save_path=f"{tmp}/on.msgpack", metric="last",
                          seed=0, evaluation_interval=1, mesh=mesh,
                          device_cache="auto")
    model = get_model({"type": "wresnet10_1"}, 10)
    opt = build_optimizer(dict(conf["optimizer"]), lambda s: 0.0)
    tmpl = create_train_state(model, opt, jax.random.PRNGKey(0),
                              jnp.zeros((2, 32, 32, 3)), use_ema=False)
    a = load_checkpoint(f"{tmp}/off.msgpack", tmpl)
    b = load_checkpoint(f"{tmp}/on.msgpack", tmpl)
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    for k in ("top1_valid", "loss_valid", "top1_test", "top1_train"):
        assert r_off[k] == pytest.approx(r_on[k], abs=1e-6), k


@pytest.mark.slow
def test_trainer_resume_across_dispatch_boundary(tmp_path, devices8):
    """Epoch boundaries stay dispatch boundaries when N does not divide
    steps_per_epoch (clamped remainder chunk): a run interrupted at the
    epoch-1 checkpoint and resumed with the SAME N reproduces the
    uninterrupted 2-epoch run exactly."""
    import shutil

    from fast_autoaugment_tpu.core.checkpoint import load_checkpoint
    from fast_autoaugment_tpu.models import get_model
    from fast_autoaugment_tpu.ops.optim import build_optimizer
    from fast_autoaugment_tpu.parallel.mesh import make_mesh
    from fast_autoaugment_tpu.train.steps import create_train_state
    from fast_autoaugment_tpu.train.trainer import train_and_eval

    conf = _conf(epoch=2)
    tmp = str(tmp_path)
    mesh = make_mesh(devices8)
    # synthetic: 512 examples, test_ratio 0.4 -> 307 train; global batch
    # 64 -> 4 steps/epoch; N=3 -> chunks [3, 1] every epoch
    kw = dict(test_ratio=0.4, cv_fold=0, metric="last", seed=0,
              evaluation_interval=1, mesh=mesh, device_cache="auto",
              steps_per_dispatch=3)
    train_and_eval(conf, tmp, save_path=f"{tmp}/full.msgpack", **kw)
    train_and_eval(_conf(epoch=1), tmp, save_path=f"{tmp}/part.msgpack", **kw)
    shutil.copy(f"{tmp}/part.msgpack", f"{tmp}/resumed.msgpack")
    shutil.copy(f"{tmp}/part.msgpack.meta.json",
                f"{tmp}/resumed.msgpack.meta.json")
    train_and_eval(conf, tmp, save_path=f"{tmp}/resumed.msgpack", **kw)

    model = get_model({"type": "wresnet10_1"}, 10)
    opt = build_optimizer(dict(conf["optimizer"]), lambda s: 0.0)
    tmpl = create_train_state(model, opt, jax.random.PRNGKey(0),
                              jnp.zeros((2, 32, 32, 3)), use_ema=False)
    a = load_checkpoint(f"{tmp}/full.msgpack", tmpl)
    b = load_checkpoint(f"{tmp}/resumed.msgpack", tmpl)
    assert int(a.step) == int(b.step) == 8
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


@pytest.mark.slow
def test_stacked_trainer_cache_matches_host(tmp_path, devices8):
    """train_folds_stacked with the cache + N=2 lands per-fold
    checkpoints matching the host-fed stacked path within the
    documented multi-step bound (ULP-level per-dispatch differences
    amplified over the epoch — the PR-2 trainer-equivalence class and
    tolerances)."""
    from fast_autoaugment_tpu.core.checkpoint import load_checkpoint, read_metadata
    from fast_autoaugment_tpu.models import get_model
    from fast_autoaugment_tpu.ops.optim import build_optimizer
    from fast_autoaugment_tpu.parallel.mesh import make_fold_mesh
    from fast_autoaugment_tpu.train.steps import create_train_state
    from fast_autoaugment_tpu.train.trainer import train_folds_stacked

    conf = _conf()
    tmp = str(tmp_path)
    host_paths = [os.path.join(tmp, f"h{f}.msgpack") for f in (0, 1)]
    cache_paths = [os.path.join(tmp, f"c{f}.msgpack") for f in (0, 1)]
    train_folds_stacked(
        conf, tmp, cv_ratio=0.4, folds=[0, 1], save_paths=host_paths, seed=0,
        evaluation_interval=1, mesh=make_fold_mesh(2, devices8, fold_shards=1),
        device_cache="off")
    train_folds_stacked(
        conf, tmp, cv_ratio=0.4, folds=[0, 1], save_paths=cache_paths, seed=0,
        evaluation_interval=1, mesh=make_fold_mesh(2, devices8, fold_shards=1),
        device_cache="auto", steps_per_dispatch=2)
    model = get_model({"type": "wresnet10_1"}, 10)
    opt = build_optimizer(dict(conf["optimizer"]), lambda s: 0.0)
    tmpl = create_train_state(model, opt, jax.random.PRNGKey(0),
                              jnp.zeros((2, 32, 32, 3)), use_ema=False)
    for f in (0, 1):
        a = load_checkpoint(host_paths[f], tmpl)
        b = load_checkpoint(cache_paths[f], tmpl)
        assert int(a.step) == int(b.step)
        for x, y in zip(jax.tree.leaves(a.params), jax.tree.leaves(b.params)):
            np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                       rtol=1e-3, atol=1e-3)
        for x, y in zip(jax.tree.leaves(a.batch_stats),
                        jax.tree.leaves(b.batch_stats)):
            np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                       rtol=5e-2, atol=1e-2)
        assert read_metadata(cache_paths[f])["epoch"] == 1


@pytest.mark.slow
def test_driver_stamps_and_accounting_under_multistep(tmp_path):
    """search_policies with the cache + N=2: flags stamped into the
    result, phase-1 per-fold device-seconds attribution identity holds
    (the PR-2 identity extended to multi-step dispatch), and the final
    policy set matches the host-fed run (same proposals, rewards within
    the documented bound)."""
    from fast_autoaugment_tpu.search.driver import search_policies

    conf = _conf()

    def kwargs(sub):
        d = str(tmp_path / sub)
        os.makedirs(d, exist_ok=True)
        return dict(
            dataroot=d, save_dir=os.path.join(d, "search"), cv_num=2,
            cv_ratio=0.4, num_policy=1, num_op=1, num_search=2, num_top=1,
        )

    r_host = search_policies(conf, **kwargs("host"), device_cache="off")
    r_cache = search_policies(conf, **kwargs("cache"), device_cache="auto",
                              steps_per_dispatch=2, fold_stack="auto")
    assert r_host["device_cache"] == "off"
    assert r_host["steps_per_dispatch"] == 1
    assert r_cache["device_cache"] == "auto"
    assert r_cache["steps_per_dispatch"] == 2
    assert r_cache["final_policy_set"]
    for r in (r_host, r_cache):
        attr = r["device_secs_phase1_per_fold"]
        assert sorted(attr) == ["0", "1"]
        s = sum(attr.values())
        assert 0 < s <= r["device_secs_phase1"] + 1e-6
    # stacked group under multistep still splits its one wall evenly
    assert r_cache["fold_stack"] == 2
    assert r_cache["device_secs_phase1_per_fold"]["0"] == pytest.approx(
        r_cache["device_secs_phase1_per_fold"]["1"])
    t_host = json.load(open(os.path.join(
        str(tmp_path / "host"), "search", "search_trials.json")))
    t_cache = json.load(open(os.path.join(
        str(tmp_path / "cache"), "search", "search_trials.json")))
    for fold in ("0", "1"):
        for (pa, ra), (pb, rb) in zip(t_host[fold], t_cache[fold]):
            assert pa == pb  # same fold-seeded proposal stream
            assert rb == pytest.approx(ra, abs=0.1)


# ----------------------------------------------------------- CLI flags

def test_cli_device_cache_flags():
    from fast_autoaugment_tpu.launch.search_cli import build_parser as search_p
    from fast_autoaugment_tpu.launch.train_cli import build_parser as train_p

    for parser in (search_p(), train_p()):
        args = parser.parse_args(["-c", "x.yaml"])
        assert args.device_cache == "auto"
        assert args.steps_per_dispatch == 1
        args = parser.parse_args(["-c", "x.yaml", "--device-cache", "off",
                                  "--steps-per-dispatch", "32"])
        assert args.device_cache == "off"
        assert args.steps_per_dispatch == 32
        with pytest.raises(SystemExit):
            parser.parse_args(["-c", "x.yaml", "--device-cache", "maybe"])


def test_bench_dispatch_helpers_exist():
    """`make bench-dispatch` wiring: the bench callable and its probe
    are importable and the Makefile target exists (the full bench run
    is exercised out-of-band — it is a measurement, not a test)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    assert callable(bench.bench_step_dispatch)
    assert callable(bench._dispatch_probe_model)
    mk = open(os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "Makefile")).read()
    assert "bench-dispatch" in mk and "--dispatch-only" in mk
