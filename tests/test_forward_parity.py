"""Forward-pass golden parity: reference torch models vs our flax models
on IDENTICAL weights (imported via utils/interop) and identical inputs.

This is the strongest numerical-parity evidence short of full training
runs: eval-mode logits must agree to float32 tolerance for every model
family.  It also exercises the published-checkpoint import path
(``--only-eval`` with reference .pth weights).
"""

import importlib.util
import os
import sys
import types

import numpy as np
import pytest

import jax
import jax.numpy as jnp

torch = pytest.importorskip("torch")

from fast_autoaugment_tpu.utils.interop import import_state_dict


def _load_ref(name, path):
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def ref():
    if not os.path.isdir("/root/reference/FastAutoAugment/networks"):
        pytest.skip("reference tree /root/reference not present on this host")
    for n in ("FastAutoAugment", "FastAutoAugment.networks",
              "FastAutoAugment.networks.shakeshake"):
        sys.modules.setdefault(n, types.ModuleType(n))
    six = types.ModuleType("torch._six")
    import collections.abc

    six.container_abcs = collections.abc
    sys.modules.setdefault("torch._six", six)
    base = "/root/reference/FastAutoAugment/networks/"
    mods = {}
    mods["shakeshake"] = _load_ref(
        "FastAutoAugment.networks.shakeshake.shakeshake", base + "shakeshake/shakeshake.py"
    )
    mods["shakedrop"] = _load_ref("FastAutoAugment.networks.shakedrop", base + "shakedrop.py")
    mods["wrn"] = _load_ref("ref_wrn", base + "wideresnet.py")
    mods["resnet"] = _load_ref("ref_resnet", base + "resnet.py")
    mods["shake_resnet"] = _load_ref("ref_shake_resnet", base + "shakeshake/shake_resnet.py")
    mods["pyramid"] = _load_ref("ref_pyramid", base + "pyramidnet.py")
    pkg = "FastAutoAugment.networks.efficientnet_pytorch"
    sys.modules.setdefault(pkg, types.ModuleType(pkg))
    sys.modules[pkg].__path__ = [base + "efficientnet_pytorch"]
    _load_ref(pkg + ".condconv", base + "efficientnet_pytorch/condconv.py")
    _load_ref(pkg + ".utils", base + "efficientnet_pytorch/utils.py")
    mods["efficientnet"] = _load_ref(pkg + ".model", base + "efficientnet_pytorch/model.py")
    return mods


def _compare(torch_model, flax_model, variables, x_np, rtol, atol):
    torch_model.eval()
    with torch.no_grad():
        want = torch_model(torch.tensor(np.transpose(x_np, (0, 3, 1, 2)))).numpy()
    got = np.asarray(flax_model.apply(variables, jnp.asarray(x_np), train=False))
    np.testing.assert_allclose(got, want, rtol=rtol, atol=atol)


def _input(shape, seed=0):
    return np.random.default_rng(seed).normal(size=shape).astype(np.float32)


def test_wideresnet_forward_parity(ref):
    from fast_autoaugment_tpu.models.wideresnet import WideResNet

    tm = ref["wrn"].WideResNet(10, 2, 0.0, 10)
    variables = import_state_dict(tm.state_dict(), "wideresnet")
    _compare(tm, WideResNet(depth=10, widen_factor=2, num_classes=10),
             variables, _input((2, 32, 32, 3)), 1e-4, 1e-4)


def test_resnet_cifar_forward_parity(ref):
    from fast_autoaugment_tpu.models.resnet import ResNet

    tm = ref["resnet"].ResNet("cifar10", 20, 10, False)
    variables = import_state_dict(tm.state_dict(), "resnet")
    _compare(tm, ResNet(dataset="cifar10", depth=20, num_classes=10),
             variables, _input((2, 32, 32, 3)), 1e-4, 1e-4)


def test_resnet_imagenet_bottleneck_forward_parity(ref):
    from fast_autoaugment_tpu.models.resnet import ResNet

    tm = ref["resnet"].ResNet("imagenet", 50, 100, True)
    variables = import_state_dict(tm.state_dict(), "resnet")
    _compare(tm, ResNet(dataset="imagenet", depth=50, num_classes=100, bottleneck=True),
             variables, _input((1, 64, 64, 3)), 1e-3, 1e-3)


def test_shake_resnet_forward_parity(ref):
    from fast_autoaugment_tpu.models.shake_resnet import ShakeResNet

    # patch the reference's CUDA-only eval path: at eval alpha=0.5 and
    # ShakeShake.apply never allocates cuda tensors, so CPU works
    tm = ref["shake_resnet"].ShakeResNet(26, 32, 10)
    variables = import_state_dict(tm.state_dict(), "shakeshake")
    _compare(tm, ShakeResNet(depth=26, w_base=32, num_classes=10),
             variables, _input((2, 32, 32, 3)), 1e-3, 1e-3)


def test_pyramidnet_forward_parity(ref, monkeypatch):
    from fast_autoaugment_tpu.models.pyramidnet import PyramidNet

    # the reference's zero-channel-pad allocates torch.cuda tensors
    # directly (pyramidnet.py:111); shim to CPU for the parity check
    monkeypatch.setattr(torch.cuda, "FloatTensor", torch.FloatTensor, raising=False)
    tm = ref["pyramid"].PyramidNet("cifar10", 29, 48, 10, True)
    variables = import_state_dict(tm.state_dict(), "pyramid")
    _compare(tm, PyramidNet(dataset="cifar10", depth=29, alpha=48,
                            num_classes=10, bottleneck=True),
             variables, _input((2, 32, 32, 3)), 1e-3, 1e-3)


def test_efficientnet_b0_forward_parity(ref):
    from fast_autoaugment_tpu.models.efficientnet import EfficientNet

    tm = ref["efficientnet"].EfficientNet.from_name(
        "efficientnet-b0", condconv_num_expert=1
    )
    variables = import_state_dict(tm.state_dict(), "efficientnet")
    fm = EfficientNet.from_name("efficientnet-b0", num_classes=1000)
    _compare(tm, fm, variables, _input((1, 224, 224, 3)), 2e-3, 2e-3)


def test_shake_resnext_forward_parity(ref):
    base = "/root/reference/FastAutoAugment/networks/"
    resnext = _load_ref("ref_shake_resnext", base + "shakeshake/shake_resnext.py")

    from fast_autoaugment_tpu.models.shake_resnet import ShakeResNeXt

    tm = resnext.ShakeResNeXt(26, 64, 4, 10)
    variables = import_state_dict(tm.state_dict(), "shakeshake_next")
    _compare(tm, ShakeResNeXt(depth=26, w_base=64, cardinality=4, num_classes=10),
             variables, _input((2, 32, 32, 3)), 1e-3, 1e-3)


def test_efficientnet_b0_condconv_forward_parity(ref):
    from fast_autoaugment_tpu.models.efficientnet import EfficientNet

    # pin the torch global RNG: this test compares RANDOMLY-INITIALIZED
    # weights, and every parity test before it advances the same global
    # stream, so the init draw — and with it the ~1e10 logit scale the
    # tolerance divides by — used to depend on which tests ran first
    # (VERDICT r5 weak 4: order-flaky margin).  With the seed fixed the
    # comparison is one deterministic (weights, input) pair.
    torch.manual_seed(0)
    tm = ref["efficientnet"].EfficientNet.from_name(
        "efficientnet-b0", condconv_num_expert=4
    )
    fm = EfficientNet.from_name("efficientnet-b0", num_classes=1000,
                                condconv_num_expert=4)
    variables = import_state_dict(tm.state_dict(), "efficientnet", model=fm)
    # the reference initializes CondConv experts with fan_out computed on
    # the FLAT [E, prod] buffer (condconv.py:129-137) -> std ~0.7, so an
    # untrained condconv model's logits explode to ~1e10; per-element
    # rtol is meaningless near zero — use range-relative tolerance.
    # Bound justification: float32 has ~1e-7 relative precision and the
    # B0 forward chains ~100 convs/matmuls whose order differs between
    # frameworks, so worst-case accumulated drift is ~1e-5 of the output
    # RANGE; 1e-4 x max|logit| gives a 10x margin above that while still
    # catching any structural mismatch (wrong expert routing changes
    # logits at the 1e-1-of-range level).
    tm.eval()
    with torch.no_grad():
        x_np = _input((1, 224, 224, 3))
        want = tm(torch.tensor(np.transpose(x_np, (0, 3, 1, 2)))).numpy()
    got = np.asarray(fm.apply(variables, jnp.asarray(x_np), train=False))
    scale = np.abs(want).max()
    assert np.abs(got - want).max() <= 1e-4 * scale
