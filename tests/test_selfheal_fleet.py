"""Self-healing fleet: elastic supervision units (fast, bash-backed)
plus the slow end-to-end acceptance drills — a 3-host fleet surviving
an injected dispatch hang on one host and a SIGKILL on another with no
operator action, completing the search bit-for-bit (modulo the
degraded-accounting stamps).  docs/RESILIENCE.md "Self-healing fleet".
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from fast_autoaugment_tpu.launch import fleet as fleet_mod


def _fake_remote(script_by_host):
    """Substitute a local bash script for ssh (per host), ignoring the
    wire command (pure supervision-protocol tests)."""
    def _argv(host, wire):
        return ["bash", "-c", script_by_host[host]]
    return _argv


def _wire_remote(preamble_by_host=None):
    """Run the REAL wire command locally (it is plain shell: ``cd …​ &&
    ENV… exec cmd``), optionally prefixed per host — how the e2e gives
    each host its own FAA_FAULT while keeping the supervisor's
    FAA_ATTEMPT/env plumbing live."""
    pre = preamble_by_host or {}

    def _argv(host, wire):
        return ["bash", "-c", f"{pre.get(host, '')}{wire}"]
    return _argv


# ----------------------------------------------- elastic supervision

def test_elastic_fleet_completes_with_survivor(tmp_path, monkeypatch):
    scripts = {"a": "exit 5", "b": "sleep 0.3; exit 0"}
    monkeypatch.setattr(fleet_mod, "_remote_argv", _fake_remote(scripts))
    code = fleet_mod.launch_fleet(["a", "b"], ["true"], "x:1",
                                  host_retries=0, elastic=True)
    assert code == 0  # b finished; a's loss degrades, not kills


def test_non_elastic_still_tears_down(tmp_path, monkeypatch):
    scripts = {"a": "exit 5", "b": "sleep 30; exit 0"}
    monkeypatch.setattr(fleet_mod, "_remote_argv", _fake_remote(scripts))
    t0 = time.time()
    code = fleet_mod.launch_fleet(["a", "b"], ["true"], "x:1",
                                  host_retries=0, elastic=False)
    assert code == 5
    assert time.time() - t0 < 20  # teardown killed b's sleep


def test_elastic_all_lost_propagates_first_failure(tmp_path, monkeypatch):
    scripts = {"a": "exit 5", "b": "sleep 0.3; exit 6"}
    monkeypatch.setattr(fleet_mod, "_remote_argv", _fake_remote(scripts))
    code = fleet_mod.launch_fleet(["a", "b"], ["true"], "x:1",
                                  host_retries=0, elastic=True)
    assert code == 5  # nobody succeeded: first genuine failure wins


def test_attempt_counter_exported_to_each_launch(tmp_path, monkeypatch):
    """FAA_ATTEMPT gates fault specs to one attempt in the process
    chain — the supervisor must export 1, 2, 3 across relaunches."""
    log = tmp_path / "attempts.log"
    monkeypatch.setattr(fleet_mod, "_remote_argv", _wire_remote())
    code = fleet_mod.launch_fleet(
        ["a"], ["sh", "-c", f'echo "$FAA_ATTEMPT" >> {log}; exit 1'],
        "x:1", host_retries=2, retry_backoff=0.01)
    assert code == 1
    assert log.read_text().split() == ["1", "2", "3"]


def test_heartbeat_stale_process_is_killed(tmp_path, monkeypatch):
    """An ALIVE process whose host beat went stale is wedged beyond the
    in-process watchdog — the supervisor SIGKILLs it."""
    wq = tmp_path / "wq"
    (wq / "hosts").mkdir(parents=True)
    (wq / "hosts" / "host0.json").write_text(json.dumps(
        {"owner": "host0", "heartbeat": time.time() - 100}))
    scripts = {"a": "sleep 30; exit 0"}
    monkeypatch.setattr(fleet_mod, "_remote_argv", _fake_remote(scripts))
    t0 = time.time()
    code = fleet_mod.launch_fleet(
        ["a"], ["true"], "x:1", host_retries=0,
        workqueue_dir=str(wq), heartbeat_timeout=0.5)
    assert code == -signal.SIGKILL
    assert time.time() - t0 < 15  # killed on staleness, not the sleep


def test_done_host_beat_is_not_wedged(tmp_path, monkeypatch):
    """A terminal ``done`` beat means finished, not wedged — the
    supervisor must let the process exit on its own."""
    wq = tmp_path / "wq"
    (wq / "hosts").mkdir(parents=True)
    (wq / "hosts" / "host0.json").write_text(json.dumps(
        {"owner": "host0", "heartbeat": time.time() - 100, "done": True}))
    scripts = {"a": "sleep 1; exit 0"}
    monkeypatch.setattr(fleet_mod, "_remote_argv", _fake_remote(scripts))
    code = fleet_mod.launch_fleet(
        ["a"], ["true"], "x:1", host_retries=0,
        workqueue_dir=str(wq), heartbeat_timeout=0.5)
    assert code == 0


def test_fleet_cli_new_flags_parse():
    with pytest.raises(SystemExit):  # no command given
        fleet_mod.main(["--hosts", "2", "--elastic", "--workqueue", "/x",
                        "--heartbeat-timeout", "5"])


# ----------------------------------------------- slow e2e drills

_CONF_YAML = (
    "model:\n  type: wresnet10_1\ndataset: synthetic\naug: default\n"
    "cutout: 0\nbatch: 8\nepoch: 2\nlr: 0.05\n"
    "lr_schedule:\n  type: cosine\n"
    "optimizer:\n  type: sgd\n  decay: 0.0001\n  momentum: 0.9\n"
    "  nesterov: true\n")


@pytest.mark.slow
def test_watchdog_hang_restarts_and_resumes_bit_identical(tmp_path):
    """The watchdog arm of the acceptance criterion, single host: an
    injected dispatch hang fires the watchdog, the CLI exits 77, and
    the (attempt-gated) rerun resumes to a checkpoint bit-identical to
    the no-fault run."""
    from fast_autoaugment_tpu.core.checkpoint import read_metadata

    tmp = str(tmp_path)
    conf = tmp_path / "conf.yaml"
    conf.write_text(_CONF_YAML)

    def run(save, attempt, fault=None, watchdog="5"):
        env = dict(os.environ)
        env.pop("FAA_FAULT", None)
        if fault:
            env["FAA_FAULT"] = fault
        env["FAA_ATTEMPT"] = str(attempt)
        return subprocess.run(
            [sys.executable, "-m", "fast_autoaugment_tpu.launch.train_cli",
             "-c", str(conf), "--dataroot", tmp, "--save", save,
             "--cv-ratio", "0.4", "--evaluation-interval", "1",
             "--watchdog", watchdog, "--ckpt-every-dispatch", "1"],
            env=env, capture_output=True, text=True, timeout=900)

    # reference runs with the watchdog OFF: the final digest equality
    # below then also pins monitored == unmonitored numerics
    full = f"{tmp}/full.msgpack"
    r = run(full, attempt=1, watchdog="off")
    assert r.returncode == 0, r.stderr[-2000:]

    part = f"{tmp}/part.msgpack"
    fault = "hang@step=6,attempt=1"
    r = run(part, attempt=1, fault=fault)
    assert r.returncode == 77, (r.returncode, r.stderr[-2000:])
    assert "watchdog FIRED" in r.stderr or "HUNG" in r.stderr

    r = run(part, attempt=2, fault=fault)  # same spec, gated off
    assert r.returncode == 0, r.stderr[-2000:]
    assert read_metadata(part)["digest"] == read_metadata(full)["digest"]


@pytest.mark.slow
def test_workqueue_search_matches_plain_search_bit_for_bit(tmp_path):
    """Single-host sanity for the lease layer: a --workqueue search
    completes, stamps a clean (non-degraded) accounting, and selects
    the IDENTICAL policies as the historical in-process path."""
    from fast_autoaugment_tpu.core.config import Config
    from fast_autoaugment_tpu.launch.workqueue import WorkQueue
    from fast_autoaugment_tpu.search.driver import search_policies

    conf = Config({
        "model": {"type": "wresnet10_1"},
        "dataset": "synthetic",
        "aug": "default", "cutout": 8, "batch": 8, "epoch": 1,
        "lr": 0.05,
        "lr_schedule": {"type": "cosine"},
        "optimizer": {"type": "sgd", "decay": 1e-4, "clip": 5.0,
                      "momentum": 0.9, "nesterov": True},
    })
    kw = dict(cv_num=2, cv_ratio=0.4, num_policy=2, num_op=2,
              num_search=4, num_top=2, smoke_test=True)
    plain = search_policies(
        conf, dataroot=str(tmp_path), save_dir=str(tmp_path / "plain"), **kw)
    wq = WorkQueue(str(tmp_path / "wq"), "host0", lease_ttl=60.0)
    queued = search_policies(
        conf, dataroot=str(tmp_path), save_dir=str(tmp_path / "queued"),
        work_queue=wq, **kw)
    assert queued["final_policy_set"] == plain["final_policy_set"]
    assert queued["degraded"] is False
    assert queued["lost_hosts"] == [] and queued["reclaimed_units"] == []
    # every unit went through the lease protocol exactly once
    assert wq.is_done("p1-fold0") and wq.is_done("p2-fold1")
    assert queued["resilience"]["fleet"]["num_reclaimed_units"] == 0
    # per-fold trial logs replace the shared file in workqueue mode
    assert os.path.exists(str(tmp_path / "queued" /
                              "search_trials.fold0.json"))


@pytest.mark.slow
def test_selfheal_fleet_e2e_hang_and_sigkill(tmp_path, monkeypatch):
    """THE acceptance drill: 3 hosts share a workqueue; host b is
    SIGKILLed mid-fold on every attempt (permanently lost), host a's
    dispatch hangs on attempt 1 (watchdog -> 77 -> resume), host c is
    clean.  No operator action: the fleet exits 0, the dead host's
    units are finished by survivors, and the selected policies match a
    no-fault single-host run bit-for-bit."""
    tmp = str(tmp_path)
    conf = tmp_path / "conf.yaml"
    conf.write_text(_CONF_YAML)
    shared = tmp_path / "search"
    wq_dir = tmp_path / "wq"

    # ---- no-fault reference: one clean host, no queue
    ref = subprocess.run(
        [sys.executable, "-m", "fast_autoaugment_tpu.launch.search_cli",
         "-c", str(conf), "--dataroot", tmp,
         "--save-dir", str(tmp_path / "ref"),
         "--num-fold", "3", "--num-policy", "2", "--num-op", "2",
         "--num-search", "4", "--num-top", "2", "--until", "2",
         "--fold-quality-floor", "off"],
        env=dict(os.environ), capture_output=True, text=True, timeout=1200)
    assert ref.returncode == 0, ref.stderr[-3000:]
    ref_policies = json.load(open(tmp_path / "ref" / "final_policy.json"))

    # ---- the 3-host fleet, faults injected per host via env preamble
    preamble = {
        "a": "export FAA_FAULT='hang@step=2,attempt=1'; ",
        "b": "export FAA_FAULT='sigkill@step=3'; ",  # fires EVERY attempt
        "c": "",
    }
    monkeypatch.setattr(fleet_mod, "_remote_argv", _wire_remote(preamble))
    command = [
        sys.executable, "-m", "fast_autoaugment_tpu.launch.search_cli",
        "-c", str(conf), "--dataroot", tmp, "--save-dir", str(shared),
        "--num-fold", "3", "--num-policy", "2", "--num-op", "2",
        "--num-search", "4", "--num-top", "2", "--until", "2",
        "--fold-quality-floor", "off",
        "--workqueue", str(wq_dir), "--lease-ttl", "45",
        "--watchdog", "30", "--ckpt-every-dispatch", "1",
    ]
    code = fleet_mod.launch_fleet(
        ["a", "b", "c"], command, "x:1",
        host_retries=2, retry_backoff=0.2, elastic=True,
        workqueue_dir=str(wq_dir))
    assert code == 0  # both faults recovered without operator action

    result = json.load(open(shared / "search_result.json"))
    # degraded-completion accounting is stamped.  (Membership, not
    # equality: a live survivor mid-compile can transiently look stale
    # to whichever host stamped last — the DEAD host must be listed,
    # over-reporting a live one is harmless noise.)
    assert result["degraded"] is True
    assert "host1" in result["lost_hosts"]  # b, by launch order
    assert result["reclaimed_units"], "dead host's units were reclaimed"
    assert "watchdog" in result["resilience"]
    # ... and the search itself is UNDAMAGED: selected policies match
    # the no-fault run bit-for-bit
    fleet_policies = json.load(open(shared / "final_policy.json"))
    assert fleet_policies == ref_policies
    # every work unit reached done (nothing silently dropped)
    done = sorted(os.listdir(wq_dir / "done"))
    for fold in range(3):
        assert f"p1-fold{fold}.json" in done
        assert f"p2-fold{fold}.json" in done
