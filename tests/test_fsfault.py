"""Hostile shared-substrate survival (ISSUE 15): the FAA_FSFAULT seam
(``core/fsfault.py``), skew at the telemetry ``wall()`` seam, the
hardened journal tailing, and the workqueue/transport behavior under
injected lag — all fast, host-only, no jax.

The slow tests are THE acceptance drill (a 3-process fleet search
under ``lag+skew+eio`` with a SIGKILLed skewed actor, byte-identical
artifacts, epoch-stamped reclaim provenance) and the ``make chaos``
composed-fault smoke.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import pytest

from fast_autoaugment_tpu.core import fsfault, telemetry
from fast_autoaugment_tpu.launch.workqueue import WorkQueue

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_fsfault_env(monkeypatch):
    monkeypatch.delenv("FAA_FSFAULT", raising=False)
    monkeypatch.delenv("FAA_FAULT", raising=False)
    fsfault.reset()
    yield
    os.environ.pop("FAA_FSFAULT", None)
    fsfault.reset()


def _arm(spec: str):
    os.environ["FAA_FSFAULT"] = spec
    fsfault.reset()
    return fsfault.active_plan()


# ------------------------------------------------------------- grammar


def test_grammar_parses_all_kinds():
    faults = fsfault.parse_fsfault_spec(
        "lag@dir=work,secs=2;stale@dir=done,window=1.5;"
        "eio@p=0.05,seed=7;skew@host=1,offset=-45;torn@path=*.json")
    kinds = [f["kind"] for f in faults]
    assert kinds == ["lag", "stale", "eio", "skew", "torn"]
    assert faults[0]["secs"] == 2.0
    assert faults[2]["seed"] == 7
    assert faults[3]["offset"] == -45.0


@pytest.mark.parametrize("bad", [
    "nonsense@x=1",            # unknown kind
    "lag@secs=2",              # missing dir
    "lag@dir=work",            # missing secs
    "eio@p=1.5",               # p outside [0, 1]
    "lag@dir=work,bogus=1",    # unknown key
    "skew@host=,offset=1",     # empty value
    "lag=work",                # no @
])
def test_grammar_rejects_loudly(bad):
    with pytest.raises(ValueError):
        fsfault.parse_fsfault_spec(bad)


def test_unset_env_means_no_plan_and_passthrough(tmp_path):
    assert fsfault.active_plan() is None
    p = tmp_path / "x.json"
    p.write_text(json.dumps({"a": 1}))
    assert fsfault.read_json(str(p)) == {"a": 1}
    assert fsfault.load_json(str(p)) == {"a": 1}
    assert fsfault.listdir(str(tmp_path)) == ["x.json"]
    assert fsfault.getsize(str(p)) == len(json.dumps({"a": 1}))
    assert fsfault.exists(str(p))
    assert fsfault.read_json(str(tmp_path / "missing.json")) is None


# ---------------------------------------------------------------- skew


def test_skew_offsets_wall_for_matching_host_only(monkeypatch):
    monkeypatch.setenv("FAA_HOST_ID", "1")
    _arm("skew@host=1,offset=45")
    assert abs(telemetry.wall() - time.time() - 45.0) < 1.0
    # a different host sees an honest clock
    monkeypatch.setenv("FAA_HOST_ID", "2")
    fsfault.reset()
    assert abs(telemetry.wall() - time.time()) < 1.0
    # host form 'host1' matches too
    monkeypatch.setenv("FAA_HOST_ID", "1")
    _arm("skew@host=host1,offset=-30")
    assert abs(telemetry.wall() - time.time() + 30.0) < 1.0


def test_mono_is_never_skewed(monkeypatch):
    monkeypatch.setenv("FAA_HOST_ID", "1")
    _arm("skew@host=1,offset=3600")
    m0 = telemetry.mono()
    assert abs(telemetry.mono() - m0) < 1.0  # no hour-sized jump


# ----------------------------------------------------------------- lag


def test_lag_hides_fresh_foreign_files_but_not_own_writes(tmp_path):
    work = tmp_path / "work"
    work.mkdir()
    foreign = str(work / "foreign.json")
    with open(foreign, "w") as fh:  # written OUTSIDE the seam
        json.dump({"who": "other-host"}, fh)
    _arm("lag@dir=work,secs=30")
    # the foreign write is too fresh: invisible to reads, lists, stats
    assert fsfault.read_json(foreign) is None
    assert fsfault.listdir(str(work)) == []
    assert not fsfault.exists(foreign)
    with pytest.raises(OSError):
        fsfault.getsize(foreign)
    # but THIS process's seam writes are always visible to itself
    own = str(work / "own.json")
    fsfault.write_json_atomic(own, {"who": "me"})
    assert fsfault.read_json(own) == {"who": "me"}
    assert fsfault.listdir(str(work)) == ["own.json"]
    # an OLD foreign file (mtime outside the window) is visible
    old = str(work / "old.json")
    with open(old, "w") as fh:
        json.dump({"who": "old"}, fh)
    past = time.time() - 120
    os.utime(old, (past, past))
    assert fsfault.read_json(old) == {"who": "old"}
    # paths outside the matched dir never lag
    outside = str(tmp_path / "outside.json")
    with open(outside, "w") as fh:
        json.dump({"who": "outside"}, fh)
    assert fsfault.read_json(outside) == {"who": "outside"}


def test_lag_expires_after_the_window(tmp_path):
    work = tmp_path / "work"
    work.mkdir()
    p = str(work / "f.json")
    with open(p, "w") as fh:
        json.dump({"v": 1}, fh)
    _arm("lag@dir=work,secs=0.2")
    assert fsfault.read_json(p) is None
    time.sleep(0.3)
    assert fsfault.read_json(p) == {"v": 1}


# --------------------------------------------------------------- stale


def test_stale_rereads_serve_the_previous_version(tmp_path):
    d = tmp_path / "done"
    d.mkdir()
    p = str(d / "m.json")
    with open(p, "w") as fh:
        json.dump({"v": 1}, fh)
    past = time.time() - 60
    os.utime(p, (past, past))
    _arm("stale@dir=done,window=30")
    assert fsfault.read_json(p) == {"v": 1}  # first read caches v1
    with open(p, "w") as fh:                 # foreign update to v2
        json.dump({"v": 2}, fh)
    # within the window: the observer's attribute cache answers v1
    assert fsfault.read_json(p) == {"v": 1}
    plan = fsfault.active_plan()
    assert plan.injected.get("stale", 0) >= 1
    # after the window the fresh bytes win
    os.utime(p, (past, past))
    assert fsfault.read_json(p) == {"v": 2}


# ----------------------------------------------------------------- eio


def test_eio_is_seeded_and_seam_retries_absorb_most(tmp_path):
    p = str(tmp_path / "x.json")
    with open(p, "w") as fh:
        json.dump({"a": 1}, fh)
    _arm("eio@p=1.0,seed=3")
    # p=1.0: every attempt fails, retries exhaust, the error surfaces
    with pytest.raises(OSError):
        fsfault.load_json(p)
    assert fsfault.read_json(p) is None  # absorbing variant
    plan = fsfault.active_plan()
    assert plan.injected["eio"] >= 2
    # p=0.3: the in-seam retry (3 attempts) absorbs nearly everything
    _arm("eio@p=0.3,seed=3")
    vals = [fsfault.read_json(p) for _ in range(30)]
    assert vals.count({"a": 1}) >= 28
    # determinism: the same seed gives the same injection stream
    _arm("eio@p=0.3,seed=3")
    again = [fsfault.read_json(p) for _ in range(30)]
    assert vals == again


# ---------------------------------------------------------------- torn


def test_torn_truncates_first_read_only(tmp_path):
    p = str(tmp_path / "t.json")
    payload = {"k": "v" * 200}
    with open(p, "w") as fh:
        json.dump(payload, fh)
    past = time.time() - 60
    os.utime(p, (past, past))
    _arm("torn@path=t.json")
    assert fsfault.read_json(p) is None       # torn tail: unparseable
    assert fsfault.read_json(p) == payload    # the write "completed"
    assert fsfault.active_plan().injected["torn"] == 1


# ------------------------------------------- workqueue under the seam


def test_workqueue_claim_poll_rides_out_lag(tmp_path):
    """An actor polling open_units/claim under publish lag simply sees
    the unit a little later — no torn reads, no spurious claims."""
    root = str(tmp_path / "wq")
    learner = WorkQueue(root, "learner", lease_ttl=5.0)
    _arm("lag@dir=work,secs=0.3")
    learner.publish_unit("p2r-f0-t000000", {"ids": [0, 1]})
    # the learner sees its own publish instantly (own-write exemption)
    assert learner.open_units("p2r-") == ["p2r-f0-t000000"]
    actor = WorkQueue(root, "actor", lease_ttl=5.0)
    # both queues share this test process; drop the own-write record
    # to see the publish exactly as a REMOTE actor host would
    fsfault.active_plan().own_writes.clear()
    assert actor.open_units("p2r-") == []  # not yet visible there
    time.sleep(0.4)
    assert actor.open_units("p2r-") == ["p2r-f0-t000000"]
    assert actor.unit_payload("p2r-f0-t000000")["ids"] == [0, 1]
    assert actor.claim("p2r-f0-t000000")
    actor.release("p2r-f0-t000000", info={"rewards": [0.5, 0.6]})
    time.sleep(0.1)
    assert learner.done_info("p2r-f0-t000000") == {
        "rewards": [0.5, 0.6]}


def test_workqueue_lease_protocol_survives_eio(tmp_path):
    _arm("eio@p=0.1,seed=11")
    a = WorkQueue(str(tmp_path / "wq"), "a", lease_ttl=5.0)
    for i in range(10):
        unit = f"u{i}"
        assert a.claim(unit)
        a.renew(unit)
        a.release(unit, info={"i": i})
        assert a.is_done(unit)


# ------------------------------------ journal tailing under the seam


def _write_journal(path, records):
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "a") as fh:
        for rec in records:
            fh.write(json.dumps(rec) + "\n")


def _recs(host, seqs, mean=100.0):
    return [{"type": "dispatch", "label": "serve_dispatch",
             "input_mean": mean, "reward_proxy": 0.1,
             "host": host, "pid": 1, "seq": s} for s in seqs]


def test_reader_watermark_dedups_stale_rereads(tmp_path):
    from fast_autoaugment_tpu.control.drift import TrafficSampleReader

    tel = str(tmp_path / "tel")
    jpath = os.path.join(tel, "journal-0.jsonl")
    _write_journal(jpath, _recs("h0", range(5)))
    reader = TrafficSampleReader(tel)
    assert len(reader.poll()) == 5
    # a stale re-read / shrink-then-grow share hands the reader the
    # same bytes again: offsets reset, the seq watermark deduplicates
    reader._offsets.clear()
    assert reader.poll() == []
    _write_journal(jpath, _recs("h0", range(5, 8)))
    assert [r["seq"] for r in reader.poll()] == [5, 6, 7]


def test_reader_rides_out_eio_and_torn(tmp_path):
    from fast_autoaugment_tpu.control.drift import TrafficSampleReader

    tel = str(tmp_path / "tel")
    jpath = os.path.join(tel, "journal-0.jsonl")
    _write_journal(jpath, _recs("h0", range(10)))
    past = time.time() - 60
    os.utime(jpath, (past, past))
    _arm("eio@p=0.2,seed=5;torn@path=journal-*.jsonl")
    reader = TrafficSampleReader(tel)
    got: list = []
    for _ in range(20):  # a torn/eio poll just retries next time
        got.extend(reader.poll())
    assert [r["seq"] for r in got] == list(range(10))


def test_reader_skip_to_end_for_resume(tmp_path):
    from fast_autoaugment_tpu.control.drift import TrafficSampleReader

    tel = str(tmp_path / "tel")
    jpath = os.path.join(tel, "journal-0.jsonl")
    _write_journal(jpath, _recs("h0", range(50), mean=500.0))
    reader = TrafficSampleReader(tel)
    assert reader.skip_to_end() == 1
    assert reader.poll() == []  # the pre-crash history is never replayed
    _write_journal(jpath, _recs("h0", range(50, 53)))
    assert [r["seq"] for r in reader.poll()] == [50, 51, 52]


# ------------------------------------------------- status integration


def test_faa_status_lease_epochs_skew_suspects_and_counters(tmp_path):
    sys.path.insert(0, os.path.join(_REPO, "tools"))
    try:
        from faa_status import search_fleet_status
    finally:
        sys.path.pop(0)

    root = str(tmp_path)
    os.makedirs(os.path.join(root, "leases"))
    with open(os.path.join(root, "leases", "p2r-f0-t000000.json"),
              "w") as fh:
        json.dump({"unit": "p2r-f0-t000000", "owner": "host2",
                   "attempt": 2, "epoch": 2, "reclaimed_from": "host1",
                   "heartbeat": time.time() + 600}, fh)
    journal = [{"type": "fsfault", "label": "lag"},
               {"type": "fsfault", "label": "lag"},
               {"type": "fsfault", "label": "eio"},
               {"type": "round", "action": "claim", "host": "host2"}]
    beats = {"host1": {"owner": "host1",
                       "heartbeat": time.time() + 45, "role": "actor"}}
    st = search_fleet_status(root, journal, beats)
    assert st["lease_epochs"]["p2r-f0-t000000"]["epoch"] == 2
    assert st["lease_epochs"]["p2r-f0-t000000"]["reclaimed_from"] == \
        "host1"
    assert st["fsfault_injections"] == {"lag": 2, "eio": 1}
    kinds = {(s["kind"], s["name"]) for s in st["skew_suspects"]}
    assert ("lease", "p2r-f0-t000000") in kinds
    assert ("host", "host1") in kinds


def test_fsfault_event_type_is_in_taxonomy():
    assert "fsfault" in telemetry.EVENT_TYPES


def test_fsfault_injection_counter_lands_in_registry(tmp_path):
    p = str(tmp_path / "x.json")
    with open(p, "w") as fh:
        json.dump({}, fh)
    _arm("eio@p=1.0,seed=0")
    before = telemetry.registry().counter(
        "faa_fsfault_injections_total", "d", kind="eio").value
    assert fsfault.read_json(p) is None
    after = telemetry.registry().counter(
        "faa_fsfault_injections_total", "d", kind="eio").value
    assert after > before


# ================================================== slow: THE drills


_CONF_YAML = (
    "model:\n  type: wresnet10_1\ndataset: synthetic\naug: default\n"
    "cutout: 8\nbatch: 8\nepoch: 1\nlr: 0.05\n"
    "lr_schedule:\n  type: cosine\n"
    "optimizer:\n  type: sgd\n  decay: 0.0001\n  momentum: 0.9\n"
    "  nesterov: true\n")


def _fleet_cmd(conf, tmp, cache):
    return [sys.executable, "-m",
            "fast_autoaugment_tpu.launch.search_cli",
            "-c", str(conf), "--dataroot", tmp,
            "--num-fold", "2", "--num-search", "4", "--num-policy", "1",
            "--num-op", "1", "--num-top", "2", "--trial-batch", "2",
            "--until", "2", "--fold-quality-floor", "off",
            "--seed", "0", "--compile-cache", cache,
            "--async-pipeline", "on", "--pipeline-actors", "2",
            "--pipeline-queue-depth", "2"]


@pytest.mark.slow
def test_fleet_search_byte_identical_under_lag_skew_eio(tmp_path):
    """THE ISSUE-15 acceptance drill: a 3-process fleet search under
    ``FAA_FSFAULT=lag@dir=work,secs=2;skew@host=1,offset=45;
    eio@p=0.05,seed=7`` — publish->claim visibility lag, a +45s wall
    clock on actor host1, and seeded transient read errors everywhere —
    completes with ``final_policy.json`` BYTE-IDENTICAL to the
    fault-free single-host run.  Host1 (the SKEWED host) is also
    SIGKILLed mid-round: its future-stamped lease must still be
    reclaimed (observer-local staleness) and the reclaim provenance
    carries the bumped epoch."""
    tmp = str(tmp_path)
    conf = tmp_path / "conf.yaml"
    conf.write_text(_CONF_YAML)
    cache = f"{tmp}/cc"
    base = _fleet_cmd(conf, tmp, cache)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("FAA_FAULT", None)
    env.pop("FAA_FSFAULT", None)

    # ---- fault-free single-host reference (warms the shared cache)
    ref = subprocess.run(base + ["--save-dir", f"{tmp}/ref"], env=env,
                         capture_output=True, text=True, timeout=900)
    assert ref.returncode == 0, ref.stderr[-3000:]

    # ---- the 3-process fleet on a hostile substrate ---------------
    fsf = "lag@dir=work,secs=2;skew@host=1,offset=45;eio@p=0.05,seed=7"
    tr, save = f"{tmp}/transport", f"{tmp}/fleet"
    fleet_base = base + ["--save-dir", save, "--fleet-transport", tr,
                         "--lease-ttl", "6"]
    learner = subprocess.Popen(
        fleet_base + ["--search-role", "learner", "--host-id", "0"],
        env=dict(env, FAA_HOST_ID="0", FAA_FSFAULT=fsf),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    # trial=1: the doomed actor dies on the FIRST round it evaluates
    # (any round covers a trial index >= 1), and it launches ahead of
    # the survivor so it reliably wins a claim race before dying
    doomed = subprocess.Popen(
        fleet_base + ["--search-role", "actor", "--host-id", "1"],
        env=dict(env, FAA_HOST_ID="1", FAA_FSFAULT=fsf,
                 FAA_FAULT="sigkill_trial@trial=1"),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    time.sleep(5.0)
    survivor = subprocess.Popen(
        fleet_base + ["--search-role", "actor", "--host-id", "2"],
        env=dict(env, FAA_HOST_ID="2", FAA_FSFAULT=fsf),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    out_l = learner.communicate(timeout=900)[0]
    out_d = doomed.communicate(timeout=300)[0]
    out_s = survivor.communicate(timeout=300)[0]
    assert learner.returncode == 0, out_l[-3000:]
    assert survivor.returncode == 0, out_s[-3000:]
    assert doomed.returncode == -9, (doomed.returncode, out_d[-1500:])

    # byte-identity through lag + skew + eio + kill + reclaim
    assert (open(f"{tmp}/ref/search_trials.json", "rb").read()
            == open(f"{save}/search_trials.json", "rb").read())
    assert (open(f"{tmp}/ref/final_policy.json", "rb").read()
            == open(f"{save}/final_policy.json", "rb").read())
    result = json.load(open(f"{save}/search_result.json"))
    assert result["degraded"] is True
    assert result["reclaimed_units"], "the dead actor's round reclaimed"
    assert all(u.startswith("p2r-") for u in result["reclaimed_units"])
    # THE epoch-provenance acceptance bit: every reclaim in the full
    # accounting carries the bumped fencing token, robbed from host1
    for rec in result["resilience"]["fleet"]["reclaimed_units"]:
        assert rec["epoch"] >= 2, rec
        assert rec["reclaimed_from"] == "host1", rec


@pytest.mark.slow
def test_chaos_composed_fault_smoke(tmp_path):
    """``make chaos``: FAA_FAULT (sigkill) layered with FAA_FSFAULT
    (lag + eio) over a bounded fleet drill — the composed-fault smoke.
    Asserts completion and artifact integrity (the byte-identity
    deep-dive is the acceptance drill above) and stamps the run's
    telemetry evidence."""
    import bench

    tmp = str(tmp_path)
    conf = tmp_path / "conf.yaml"
    conf.write_text(_CONF_YAML)
    cache = f"{tmp}/cc"
    tel = f"{tmp}/tel"
    base = _fleet_cmd(conf, tmp, cache)
    env = dict(os.environ, JAX_PLATFORMS="cpu", FAA_TELEMETRY=tel)
    env.pop("FAA_FAULT", None)
    env.pop("FAA_FSFAULT", None)
    fsf = "lag@dir=work,secs=1;eio@p=0.05,seed=13"
    tr, save = f"{tmp}/transport", f"{tmp}/chaos"
    fleet_base = base + ["--save-dir", save, "--fleet-transport", tr,
                         "--lease-ttl", "5"]
    t0 = time.monotonic()
    learner = subprocess.Popen(
        fleet_base + ["--search-role", "learner", "--host-id", "0"],
        env=dict(env, FAA_HOST_ID="0", FAA_FSFAULT=fsf),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    doomed = subprocess.Popen(
        fleet_base + ["--search-role", "actor", "--host-id", "1"],
        env=dict(env, FAA_HOST_ID="1", FAA_FSFAULT=fsf,
                 FAA_FAULT="sigkill_trial@trial=1"),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    time.sleep(5.0)  # the doomed actor claims first, then dies
    survivor = subprocess.Popen(
        fleet_base + ["--search-role", "actor", "--host-id", "2"],
        env=dict(env, FAA_HOST_ID="2", FAA_FSFAULT=fsf),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    out_l = learner.communicate(timeout=900)[0]
    doomed.communicate(timeout=300)
    out_s = survivor.communicate(timeout=300)[0]
    assert learner.returncode == 0, out_l[-3000:]
    assert survivor.returncode == 0, out_s[-3000:]
    assert doomed.returncode == -9

    result = json.load(open(f"{save}/search_result.json"))
    policy = json.load(open(f"{save}/final_policy.json"))
    assert policy, "chaos run produced an empty policy"
    assert result["degraded"] is True
    reclaims = result["resilience"]["fleet"]["reclaimed_units"]
    line = {
        "chaos": {"fsfault": fsf, "fault": "sigkill_trial@trial=1",
                  "wall_sec": round(time.monotonic() - t0, 1),
                  "reclaimed_units": reclaims,
                  "lost_hosts": result["lost_hosts"]},
        **bench.telemetry_stamp(),
    }
    print("CHAOS " + json.dumps(line))
    assert reclaims
    for rec in reclaims:
        assert rec["epoch"] >= 2
