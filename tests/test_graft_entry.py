"""The driver's `dryrun_multichip` must pass without real chips.

Round-1 failure mode: the dryrun inherited the ambient single-chip TPU
environment and hung in backend init (MULTICHIP_r01.json rc=124).  The
entry point now unconditionally re-execs into a forced-CPU subprocess;
this test runs it exactly the way the driver does — ambient environment,
no special setup — and must finish well inside the driver's timeout.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_dryrun_multichip_passes_under_ambient_env():
    # Deliberately do NOT scrub the environment: the point is that the
    # entry point itself must survive whatever the driver inherits.
    sys.path.insert(0, REPO)
    import __graft_entry__ as graft

    out = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__ as g; g.dryrun_multichip(4)"],
        # worker budget + generous outer-process startup allowance (the
        # outer interpreter pays its own jax import before the worker's
        # clock starts on a loaded 1-core host)
        cwd=REPO, capture_output=True, text=True,
        timeout=graft.DRYRUN_WORKER_TIMEOUT + 300,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    # round 3: the dryrun is an equivalence check, not just a smoke run
    assert "equivalent" in out.stdout, out.stdout
