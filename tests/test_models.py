"""Model zoo tests: parameter-count parity with the reference (counts
extracted from the reference PyTorch modules on CPU), forward shapes,
and the stochastic shake custom-VJPs."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fast_autoaugment_tpu.models import get_model, num_class

# Ground truth from /root/reference networks instantiated with torch-cpu.
# ShakeResNet counts are the reference totals MINUS its dead parameters:
# `self.equal_io and None or Shortcut(...)` (shake_resnet.py:17) always
# evaluates to a Shortcut, registering shortcut modules that the forward
# never uses on equal-io blocks (65856 params for 2x32d, 584640 for
# 2x96d, 794976 for 2x112d).  We don't replicate dead parameters.
REF_PARAM_COUNTS = {
    "wresnet40_2": ("cifar10", 2246474),
    "wresnet28_10": ("cifar10", 36489290),
    "shakeshake26_2x32d": ("cifar10", 2923146),
    "shakeshake26_2x96d_next": ("cifar10", 22717706),
}
REF_PARAM_COUNTS_SLOW = {
    "shakeshake26_2x96d": ("cifar10", 26192906),
    "shakeshake26_2x112d": ("cifar10", 35640426),
    "resnet50": ("imagenet", 25557032),
}


def _param_count(tree):
    return sum(int(np.prod(p.shape)) for p in jax.tree.leaves(tree))


def _init(model_type, dataset, image=32, model_extra=None, shapes_only=False):
    conf = {"type": model_type, "dataset": dataset}
    conf.update(model_extra or {})
    model = get_model(conf, num_class(dataset))
    x = jnp.zeros((2, image, image, 3), jnp.float32)
    rngs = {"params": jax.random.PRNGKey(0), "shake": jax.random.PRNGKey(1)}
    if shapes_only:
        variables = jax.eval_shape(lambda: model.init(rngs, x, train=False))
    else:
        variables = model.init(rngs, x, train=False)
    return model, variables, x


@pytest.mark.parametrize("model_type", sorted(REF_PARAM_COUNTS))
def test_param_counts_match_reference(model_type):
    dataset, want = REF_PARAM_COUNTS[model_type]
    _, variables, _ = _init(model_type, dataset, shapes_only=True)
    assert _param_count(variables["params"]) == want


@pytest.mark.parametrize("model_type", sorted(REF_PARAM_COUNTS_SLOW))
def test_param_counts_match_reference_slow(model_type):
    dataset, want = REF_PARAM_COUNTS_SLOW[model_type]
    image = 224 if dataset == "imagenet" else 32
    _, variables, _ = _init(model_type, dataset, image, shapes_only=True)
    assert _param_count(variables["params"]) == want


def test_pyramidnet_param_count_matches_reference():
    _, variables, _ = _init(
        "pyramid", "cifar10",
        model_extra={"depth": 272, "alpha": 200, "bottleneck": True},
        shapes_only=True,
    )
    assert _param_count(variables["params"]) == 26210842


@pytest.mark.parametrize(
    "model_type,extra",
    [
        ("wresnet40_2", None),
        ("shakeshake26_2x32d", None),
        ("pyramid", {"depth": 29, "alpha": 48, "bottleneck": True}),
    ],
)
def test_forward_shapes_and_train_mode(model_type, extra):
    model, variables, x = _init(model_type, "cifar10", model_extra=extra)
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (2, 10)
    out, mutated = model.apply(
        variables,
        x,
        train=True,
        mutable=["batch_stats"],
        rngs={"shake": jax.random.PRNGKey(2), "dropout": jax.random.PRNGKey(3)},
    )
    assert out.shape == (2, 10)
    # batch stats actually updated
    old = jax.tree.leaves(variables["batch_stats"])
    new = jax.tree.leaves(mutated["batch_stats"])
    assert any(not np.allclose(a, b) for a, b in zip(old, new))


def test_resnet_cifar_variant():
    model, variables, x = _init("wresnet40_2", "cifar100")
    assert model.apply(variables, x, train=False).shape == (2, 100)


# ---------------------------------------------------------------------------
# shake custom VJPs: independent forward/backward randomness
# ---------------------------------------------------------------------------


def test_shake_shake_forward_and_backward_noise():
    from fast_autoaugment_tpu.ops.shake import shake_shake

    x1 = jnp.ones((4, 2, 2, 3))
    x2 = jnp.zeros((4, 2, 2, 3))
    alpha = jnp.array([0.0, 0.25, 0.5, 1.0]).reshape(4, 1, 1, 1)
    beta = jnp.array([1.0, 0.75, 0.5, 0.0]).reshape(4, 1, 1, 1)

    out, vjp = jax.vjp(lambda a, b: shake_shake(a, b, alpha, beta), x1, x2)
    np.testing.assert_allclose(np.asarray(out[:, 0, 0, 0]), [0.0, 0.25, 0.5, 1.0])
    g1, g2 = vjp(jnp.ones_like(out))
    # backward must use beta, NOT alpha
    np.testing.assert_allclose(np.asarray(g1[:, 0, 0, 0]), [1.0, 0.75, 0.5, 0.0])
    np.testing.assert_allclose(np.asarray(g2[:, 0, 0, 0]), [0.0, 0.25, 0.5, 1.0])


def test_shake_drop_gate_semantics():
    from fast_autoaugment_tpu.ops.shake import shake_drop

    x = jnp.full((2, 1, 1, 1), 3.0)
    alpha = jnp.full((2, 1, 1, 1), -0.5)
    beta = jnp.full((2, 1, 1, 1), 0.25)

    # gate = 1 (keep): identity fwd, identity bwd
    out, vjp = jax.vjp(lambda v: shake_drop(v, jnp.float32(1.0), alpha, beta), x)
    np.testing.assert_allclose(np.asarray(out), 3.0)
    np.testing.assert_allclose(np.asarray(vjp(jnp.ones_like(out))[0]), 1.0)

    # gate = 0 (drop): alpha fwd, beta bwd
    out, vjp = jax.vjp(lambda v: shake_drop(v, jnp.float32(0.0), alpha, beta), x)
    np.testing.assert_allclose(np.asarray(out), -1.5)
    np.testing.assert_allclose(np.asarray(vjp(jnp.ones_like(out))[0]), 0.25)


def test_shake_ops_work_under_jit_and_grad():
    from fast_autoaugment_tpu.ops.shake import (
        sample_shake_shake_noise,
        shake_shake,
    )

    @jax.jit
    def loss_fn(x1, x2, key):
        alpha, beta = sample_shake_shake_noise(key, x1.shape[0])
        return shake_shake(x1, x2, alpha, beta).sum()

    g = jax.grad(loss_fn)(jnp.ones((3, 2, 2, 1)), jnp.ones((3, 2, 2, 1)), jax.random.PRNGKey(0))
    assert g.shape == (3, 2, 2, 1)
    assert np.isfinite(np.asarray(g)).all()
