"""EfficientNet/CondConv tests: param parity with the reference torch
implementation, block codec, scaling rules, CondConv equivalence with
the per-sample legacy path, drop-connect semantics."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fast_autoaugment_tpu.models.efficientnet import (
    BlockArgs,
    CondConv,
    EfficientNet,
    decode_block_string,
    drop_connect,
    efficientnet_params,
    round_filters,
    round_repeats,
)


def _param_count(tree):
    return sum(int(np.prod(p.shape)) for p in jax.tree.leaves(tree))


# Ground truth from the reference torch modules on CPU.
@pytest.mark.parametrize(
    "name,experts,want",
    [
        ("efficientnet-b0", 0, 5288548),
        ("efficientnet-b1", 0, 7794184),
        ("efficientnet-b0", 4, 13314116),
    ],
)
def test_param_counts_match_reference(name, experts, want):
    model = EfficientNet.from_name(name, num_classes=1000, condconv_num_expert=experts)
    res = efficientnet_params(name)[2]
    variables = jax.eval_shape(
        lambda: model.init(
            {"params": jax.random.PRNGKey(0)},
            jnp.zeros((1, res, res, 3), jnp.float32),
            train=False,
        )
    )
    assert _param_count(variables["params"]) == want


def test_block_string_codec():
    args = decode_block_string("r2_k5_s22_e6_i24_o40_se0.25")
    assert args == BlockArgs(
        kernel_size=5, num_repeat=2, input_filters=24, output_filters=40,
        expand_ratio=6, se_ratio=0.25, stride=2, id_skip=True,
    )
    assert decode_block_string("r1_k3_s11_e1_i32_o16_noskip").id_skip is False


def test_round_filters_and_repeats():
    # reference utils.py:55-73 examples
    assert round_filters(32, 1.0) == 32
    assert round_filters(32, 1.1) == 32   # b2: 35.2 rounds down to 32 (within 10%)
    assert round_filters(32, 1.4) == 48   # b4
    assert round_filters(1280, 1.2) == 1536
    assert round_repeats(2, 1.1) == 3
    assert round_repeats(3, 1.0) == 3


def test_forward_shape_b0_small_input():
    model = EfficientNet.from_name("efficientnet-b0", num_classes=17)
    x = jnp.zeros((2, 64, 64, 3), jnp.float32)
    variables = model.init({"params": jax.random.PRNGKey(0)}, x, train=False)
    out = model.apply(variables, x, train=False)
    assert out.shape == (2, 17)


def test_condconv_matches_per_sample_loop():
    """The vmapped expert-mix conv must equal the explicit per-sample
    convolution (the reference's forward vs forward_legacy check,
    condconv.py:169-199)."""
    cc = CondConv(features=8, kernel_size=3, num_experts=4, stride=1)
    x = jax.random.normal(jax.random.PRNGKey(0), (5, 9, 9, 6))
    routing = jax.nn.sigmoid(jax.random.normal(jax.random.PRNGKey(1), (5, 4)))
    variables = cc.init(jax.random.PRNGKey(2), x, routing)
    out = cc.apply(variables, x, routing)
    assert out.shape == (5, 9, 9, 8)

    experts = variables["params"]["experts"]  # [E, kh, kw, cin, cout]
    for b in range(5):
        kernel = jnp.einsum("e,ehwio->hwio", routing[b], experts)
        want = jax.lax.conv_general_dilated(
            x[b:b + 1], kernel, (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        np.testing.assert_allclose(np.asarray(out[b]), np.asarray(want[0]),
                                   rtol=2e-5, atol=2e-5)


def test_condconv_depthwise_shape():
    cc = CondConv(features=6, kernel_size=3, num_experts=3, stride=2, depthwise=True)
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 8, 6))
    routing = jnp.full((2, 3), 1.0 / 3.0)
    variables = cc.init(jax.random.PRNGKey(1), x, routing)
    out = cc.apply(variables, x, routing)
    assert out.shape == (2, 4, 4, 6)


def test_drop_connect_semantics():
    x = jnp.ones((8, 2, 2, 1))
    # eval: deterministic (1-p) scaling, NO rescale at train (utils.py:92-99)
    out_eval = drop_connect(x, None, 0.25, train=False)
    np.testing.assert_allclose(np.asarray(out_eval), 0.75)
    out_train = drop_connect(x, jax.random.PRNGKey(0), 0.5, train=True)
    vals = np.unique(np.asarray(out_train))
    assert set(vals.tolist()) <= {0.0, 1.0}  # kept samples NOT rescaled


def test_registry_builds_efficientnet():
    from fast_autoaugment_tpu.models import get_model, input_image_size

    m = get_model({"type": "efficientnet-b0"}, 1000)
    assert isinstance(m, EfficientNet)
    mc = get_model({"type": "efficientnet-b0-condconv", "condconv_num_expert": 4}, 1000)
    assert mc.blocks_args[-1].condconv_num_expert == 4
    assert input_image_size("imagenet", "efficientnet-b1") == 240
    assert input_image_size("imagenet", "efficientnet-b4") == 380
