"""Native C++ loader tests: builds the shared library with the in-repo
Makefile, then checks decode parity vs PIL and gather correctness."""

import os

import numpy as np
import pytest

from fast_autoaugment_tpu.data import native_loader


@pytest.fixture(scope="module")
def built():
    if not native_loader.available():
        assert native_loader.build(), "g++/libjpeg build failed"
    return True


def _write_jpegs(tmpdir, n=6):
    import PIL.Image

    rng = np.random.default_rng(0)
    paths = []
    for i in range(n):
        w, h = int(rng.integers(40, 120)), int(rng.integers(40, 120))
        arr = rng.integers(0, 256, (h, w, 3), dtype=np.uint8)
        p = os.path.join(tmpdir, f"im{i}.jpg")
        PIL.Image.fromarray(arr).save(p, quality=95)
        paths.append(p)
    return paths


def test_decode_resize_close_to_pil(built, tmp_path):
    import PIL.Image

    paths = _write_jpegs(str(tmp_path))
    target = 32
    batch, failures = native_loader.decode_resize_batch(paths, target)
    assert failures == 0
    assert batch.shape == (len(paths), target, target, 3)

    for i, p in enumerate(paths):
        want = np.asarray(
            PIL.Image.open(p).convert("RGB").resize((target, target), PIL.Image.BILINEAR),
            np.int32,
        )
        got = batch[i].astype(np.int32)
        # same decoder (libjpeg); resample both bilinear with the same
        # half-pixel grid -> differences are rounding-level
        diff = np.abs(got - want)
        assert np.mean(diff) < 3.0, f"image {i}: mean diff {np.mean(diff)}"
        assert np.percentile(diff, 99) <= 12


def test_decode_with_crop_boxes(built, tmp_path):
    import PIL.Image

    paths = _write_jpegs(str(tmp_path), n=3)
    boxes = np.array([[0, 0, 20, 20], [5, 5, 25, 30], [0, 0, 40, 40]], np.float32)
    batch, failures = native_loader.decode_resize_batch(paths, 16, boxes)
    assert failures == 0 and batch.shape == (3, 16, 16, 3)
    want = np.asarray(
        PIL.Image.open(paths[0]).convert("RGB").crop((0, 0, 20, 20)).resize(
            (16, 16), PIL.Image.BILINEAR
        ),
        np.int32,
    )
    assert np.mean(np.abs(batch[0].astype(np.int32) - want)) < 4.0


def test_decode_failure_is_counted_not_fatal(built, tmp_path):
    paths = _write_jpegs(str(tmp_path), n=2) + [str(tmp_path / "missing.jpg")]
    batch, failures = native_loader.decode_resize_batch(paths, 8)
    assert failures == 1
    assert (batch[2] == 0).all()
    assert (batch[0] != 0).any()


def test_gather_u8(built):
    src = np.random.default_rng(0).integers(0, 256, (100, 7, 5, 3), dtype=np.uint8)
    idx = np.random.default_rng(1).integers(0, 100, (64,))
    out = native_loader.gather_u8(src, idx)
    np.testing.assert_array_equal(out, src[idx])


def test_end_to_end_drift_native_vs_pil(built, tmp_path):
    """Bound the FULL-pipeline drift of feeding native-decoded pixels
    instead of PIL's: real JPEGs -> decode+crop+resize (native bilinear
    vs PIL bilinear) -> identical on-device augmentation (same key,
    same policy) -> logits of a fixed deterministically-initialized
    WRN-10-1.  The stated bound (VERDICT r3, weak 6): mean relative
    logit drift < 5% and top-1 predictions identical — i.e. the native
    feed is interchangeable with the golden-parity PIL path at
    model-output level, not just at decode level."""
    import PIL.Image

    import jax
    import jax.numpy as jnp

    from fast_autoaugment_tpu.models import get_model
    from fast_autoaugment_tpu.ops.preprocess import cifar_train_batch
    from fast_autoaugment_tpu.policies.archive import policy_to_tensor

    paths = _write_jpegs(str(tmp_path), n=8)
    target = 32
    native_px, failures = native_loader.decode_resize_batch(paths, target)
    assert failures == 0
    pil_px = np.stack([
        np.asarray(
            PIL.Image.open(p).convert("RGB").resize((target, target),
                                                    PIL.Image.BILINEAR),
            np.uint8)
        for p in paths
    ])

    # identical device-side augmentation: one mild geometric+photometric
    # sub-policy, fixed key -> both pixel sets see the same transform
    policy = jnp.asarray(policy_to_tensor(
        [[("Rotate", 1.0, 0.6), ("Brightness", 1.0, 0.6)]]))
    model = get_model({"type": "wresnet10_1"}, 10)
    variables = model.init({"params": jax.random.PRNGKey(3)},
                           jnp.zeros((1, target, target, 3)), train=False)

    @jax.jit
    def pixels_to_logits(px_u8):
        augmented = cifar_train_batch(
            jnp.asarray(px_u8, jnp.float32), jax.random.PRNGKey(11),
            policy=policy, cutout_length=0)
        return model.apply(variables, augmented, train=False)

    logits_native = np.asarray(pixels_to_logits(native_px))
    logits_pil = np.asarray(pixels_to_logits(pil_px))

    rel = (np.linalg.norm(logits_native - logits_pil, axis=-1)
           / np.maximum(np.linalg.norm(logits_pil, axis=-1), 1e-9))
    assert float(rel.mean()) < 0.05, f"mean relative logit drift {rel.mean():.4f}"
    np.testing.assert_array_equal(
        logits_native.argmax(-1), logits_pil.argmax(-1),
        err_msg="native-fed top-1 predictions diverge from the PIL path",
    )
