"""Persistent compile cache + compile seam (core/compilecache.py).

Fast tests cover spec resolution, seam classification/delegation, the
watchdog warm-allowance coupling and the bench probe memo — all host
side or one tiny compile.  The slow tests are the acceptance drills:
a warm SECOND PROCESS reports cache hits and a fast first step, a
config change goes cold again, cached-vs-fresh executables train
bit-identically, and the exit-77 resume e2e reports a cache hit.
"""

import json
import os
import re
import subprocess
import sys

import numpy as np
import pytest

from fast_autoaugment_tpu.core import compilecache as cc
from fast_autoaugment_tpu.core.watchdog import DispatchWatchdog

sys.path.insert(0, os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


@pytest.fixture()
def clean_cache_state(monkeypatch, tmp_path):
    """Zero the seam stats and guarantee the process-global cache
    config is restored after the test — enabling the cache is
    process-wide state the rest of the suite must not inherit."""
    monkeypatch.delenv(cc.ENV_VAR, raising=False)
    cc._reset_stats_for_tests()
    cc._disable_for_tests()
    yield tmp_path
    cc._reset_stats_for_tests()
    cc._disable_for_tests()


# ---------------------------------------------------- spec resolution


def test_resolve_off_and_dir(monkeypatch):
    monkeypatch.delenv(cc.ENV_VAR, raising=False)
    assert cc.resolve_compile_cache(None) is None
    assert cc.resolve_compile_cache("off") is None
    assert cc.resolve_compile_cache("OFF") is None
    assert cc.resolve_compile_cache("/x/y") == "/x/y"


def test_resolve_env_fallback_and_precedence(monkeypatch):
    monkeypatch.setenv(cc.ENV_VAR, "/from/env")
    # "off"/unset spec falls back to the env handoff (fleet contract)
    assert cc.resolve_compile_cache(None) == "/from/env"
    assert cc.resolve_compile_cache("off") == "/from/env"
    # an explicit dir wins over the env
    assert cc.resolve_compile_cache("/explicit") == "/explicit"
    monkeypatch.setenv(cc.ENV_VAR, "off")
    assert cc.resolve_compile_cache(None) is None


def test_enable_exports_env_for_children(clean_cache_state):
    d = str(clean_cache_state / "cache")
    got = cc.configure_compile_cache(d)
    assert got == os.path.abspath(d)
    assert os.path.isdir(d)
    # children (fleet hosts, exit-77 relaunches) inherit via the env
    assert os.environ[cc.ENV_VAR] == os.path.abspath(d)
    assert cc.cache_dir() == os.path.abspath(d)


# ------------------------------------------------------- seam wrapper


def test_seam_uncached_classification_and_stats(clean_cache_state):
    import jax.numpy as jnp

    fn = cc.seam_jit(lambda x: x * 2 + 1, label="t_uncached")
    out = fn(jnp.ones((4,)))
    assert np.allclose(np.asarray(out), 3.0)
    stats = cc.compile_cache_stats()
    assert stats["enabled"] is False and stats["dir"] is None
    assert stats["labels"]["t_uncached"]["uncached"] == 1
    assert stats["labels"]["t_uncached"]["sec"] > 0
    assert stats["first_step_secs"] >= stats["labels"]["t_uncached"]["sec"]
    # second call is not re-recorded
    fn(jnp.ones((4,)))
    assert cc.compile_cache_stats()["labels"]["t_uncached"]["uncached"] == 1


def test_seam_delegates_lower_and_attributes(clean_cache_state):
    import jax.numpy as jnp

    fn = cc.seam_jit(lambda x: x + 1, label="t_deleg")
    # bench.py AOT-lowers through .lower on the seam wrapper
    compiled = fn.lower(jnp.ones((2,))).compile()
    assert np.allclose(np.asarray(compiled(jnp.ones((2,)))), 2.0)
    # census probes _cache_size through the wrapper (attribute
    # delegation); attaching attributes works too (tta trace counter)
    fn._faa_trace_count = lambda: 7
    assert fn._faa_trace_count() == 7


def test_seam_hit_miss_in_process(clean_cache_state):
    """Enable the cache, compile a fn (miss), re-jit an IDENTICAL but
    distinct fn (hit: a distinct function identity bypasses jax's
    in-memory tracing caches, so the compile reaches the persistent
    layer and deserializes — the same path a fresh process takes)."""
    import jax.numpy as jnp

    cc.configure_compile_cache(str(clean_cache_state / "cache"))

    def make_body():
        def body(x):
            return (x * 3).sum() + 1
        return body

    a = cc.seam_jit(make_body(), label="t_cold")
    a(jnp.ones((8, 8)))
    stats = cc.compile_cache_stats()
    assert stats["misses"] > 0
    assert stats["labels"]["t_cold"]["miss"] == 1

    b = cc.seam_jit(make_body(), label="t_warm")
    b(jnp.ones((8, 8)))
    stats = cc.compile_cache_stats()
    assert stats["hits"] > 0
    assert stats["labels"]["t_warm"]["hit"] == 1
    # at least one persistent entry landed on disk
    assert any(f.endswith("-cache")
               for f in os.listdir(cc.cache_dir()))


# --------------------------------------- watchdog warm-allowance coupling


def test_watchdog_first_call_blind_window_when_cold():
    wd = DispatchWatchdog("auto", compile_allowance=600.0)
    assert wd.deadline("train_dispatch") == 600.0


def test_watchdog_shrinks_first_call_when_process_warm(monkeypatch):
    wd = DispatchWatchdog("auto", compile_allowance=600.0,
                          warm_allowance=45.0)
    monkeypatch.setattr(cc, "process_is_warm", lambda: True)
    # the seam has proven the cache warm: no blind 600s window
    assert wd.deadline("train_dispatch") == 45.0
    # steady state is untouched
    wd.observe("train_dispatch", 2.0)
    assert wd.deadline("train_dispatch") == pytest.approx(40.0)


def test_watchdog_mark_compile_warm_fixed_mode():
    wd = DispatchWatchdog(5.0, compile_allowance=600.0)
    assert wd.deadline("serve_exact_b8") == 600.0
    wd.mark_compile_warm("serve_exact_b8")
    # AOT-loaded executable: first dispatch gets the NORMAL deadline
    assert wd.deadline("serve_exact_b8") == 5.0
    assert "serve_exact_b8" in wd.stats()["warm_labels"]


def test_watchdog_warm_floor_respects_min_deadline():
    wd = DispatchWatchdog("auto", warm_allowance=1.0, min_deadline=10.0)
    wd.mark_compile_warm("d")
    assert wd.deadline("d") == 10.0


# --------------------------------------------------- bench probe memo


def test_probe_memo_roundtrip_and_ttl(tmp_path, monkeypatch):
    import bench

    memo = tmp_path / "probe.json"
    monkeypatch.setenv("FAA_PROBE_MEMO_PATH", str(memo))
    assert bench._read_probe_memo(600) is None  # no memo yet
    bench._write_probe_memo("dead")
    assert bench._read_probe_memo(600) == "dead"
    assert bench._read_probe_memo(0) is None  # ttl 0 disables
    # a stale memo is ignored
    rec = json.loads(memo.read_text())
    rec["ts"] -= 10_000
    memo.write_text(json.dumps(rec))
    assert bench._read_probe_memo(600) is None
    # a torn memo is ignored, not fatal
    memo.write_text("{not json")
    assert bench._read_probe_memo(600) is None


def test_probe_memo_short_circuits_retry_window(tmp_path, monkeypatch):
    """A fresh 'dead' verdict skips the whole probe-retry window (the
    11-minute tax BENCH_r05 paid per bench round) and goes straight to
    the CPU fallback re-exec; 'alive' skips the probe and returns."""
    import bench

    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "10.0.0.1")
    monkeypatch.setenv("FAA_PROBE_MEMO_PATH", str(tmp_path / "probe.json"))
    monkeypatch.delenv("FAA_SKIP_TPU_PROBE", raising=False)
    probes = []
    monkeypatch.setattr(bench, "_probe_backend_once",
                        lambda t: probes.append(t) or -1)
    execs = []
    monkeypatch.setattr(bench.os, "execvpe",
                        lambda *a: execs.append(a))

    bench._write_probe_memo("alive")
    bench._ensure_live_backend()
    assert not probes and not execs  # memoized alive: no probe at all

    bench._write_probe_memo("dead")
    bench._ensure_live_backend(reexec_argv=["python", "x"])
    assert not probes  # memoized dead: no retry window either
    assert len(execs) == 1  # straight to the CPU fallback
    assert execs[0][2]["JAX_PLATFORMS"] == "cpu"


def test_probe_skip_env(monkeypatch):
    import bench

    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "10.0.0.1")
    monkeypatch.setenv("FAA_SKIP_TPU_PROBE", "1")
    monkeypatch.setattr(bench, "_probe_backend_once",
                        lambda t: (_ for _ in ()).throw(AssertionError))
    bench._ensure_live_backend()  # returns without probing or exec


def test_probe_writes_memo_after_real_probe(tmp_path, monkeypatch):
    import bench

    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "10.0.0.1")
    memo = tmp_path / "probe.json"
    monkeypatch.setenv("FAA_PROBE_MEMO_PATH", str(memo))
    monkeypatch.delenv("FAA_SKIP_TPU_PROBE", raising=False)
    monkeypatch.setenv("FAA_BENCH_RETRY_WINDOW", "0")
    monkeypatch.setattr(bench, "_probe_backend_once", lambda t: 0)
    bench._ensure_live_backend()
    assert json.loads(memo.read_text())["verdict"] == "alive"


# ---------------------------------------------------- bench stamp block


def test_bench_compile_cache_stamp_schema(clean_cache_state):
    import bench

    stamp = bench.compile_cache_stamp()
    for key in ("dir", "enabled", "hits", "misses", "first_step_secs",
                "labels"):
        assert key in stamp, key


# -------------------------------------------------- subprocess drills

_CHILD = r"""
import json, os, sys, time
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax, jax.numpy as jnp, numpy as np
from fast_autoaugment_tpu.core.compilecache import (
    compile_cache_stats, configure_compile_cache)
configure_compile_cache(None)  # FAA_COMPILE_CACHE from the parent
from fast_autoaugment_tpu.models import get_model
from fast_autoaugment_tpu.ops.optim import build_optimizer
from fast_autoaugment_tpu.train.steps import create_train_state, make_train_step
width = int(os.environ.get("T_WIDTH", "1"))
model = get_model({"type": "wresnet10_%d" % width}, 10)
opt = build_optimizer({"type": "sgd", "decay": 2e-4, "clip": 5.0,
                       "momentum": 0.9, "nesterov": True}, lambda s: 0.05)
rng = jax.random.PRNGKey(0)
sample = jnp.zeros((2, 8, 8, 3), jnp.float32)
state = create_train_state(model, opt, rng, sample, use_ema=False)
step = make_train_step(model, opt, num_classes=10, cutout_length=0,
                       use_policy=False)
host = np.random.default_rng(0)
x = jnp.asarray(host.integers(0, 256, (4, 8, 8, 3), dtype=np.uint8))
y = jnp.asarray(host.integers(0, 10, (4,), np.int32))
pol = jnp.zeros((1, 1, 3), jnp.float32)
t0 = time.perf_counter()
state, m = step(state, x, y, pol, rng)
jax.block_until_ready(state.params)
print(json.dumps({"first_step_sec": time.perf_counter() - t0,
                  "stats": compile_cache_stats()}))
"""


def _run_child(cache_dir, width=1):
    env = dict(os.environ)
    env["FAA_COMPILE_CACHE"] = str(cache_dir)
    env["JAX_PLATFORMS"] = "cpu"
    env["T_WIDTH"] = str(width)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    r = subprocess.run([sys.executable, "-c", _CHILD], env=env,
                       capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stderr[-2000:]
    return json.loads(r.stdout.strip().splitlines()[-1])


@pytest.mark.slow
def test_cache_key_stability_warm_second_process_cold_after_change(tmp_path):
    """The tentpole acceptance shape: same config -> the second process
    is WARM (hits, no misses, faster first step); a config change ->
    cold again (misses)."""
    cache = tmp_path / "cache"
    cold = _run_child(cache)
    assert cold["stats"]["misses"] > 0
    assert cold["stats"]["labels"]["train_step"]["miss"] == 1

    warm = _run_child(cache)
    assert warm["stats"]["hits"] > 0
    assert warm["stats"]["misses"] == 0
    assert warm["stats"]["labels"]["train_step"]["hit"] == 1
    # the whole point: the warm first step costs a fraction of cold
    assert warm["first_step_sec"] < cold["first_step_sec"]

    changed = _run_child(cache, width=2)  # different model width
    assert changed["stats"]["misses"] > 0  # cold for the new program


@pytest.mark.slow
def test_cached_vs_fresh_executables_bitwise(tmp_path):
    """Seeded equivalence across the cache boundary: a COLD process and
    a WARM process (deserialized executables) produce bit-identical
    training results — caching changes where executables come from,
    never what they compute."""
    conf = (
        "model:\n  type: wresnet10_1\ndataset: synthetic\naug: default\n"
        "cutout: 0\nbatch: 8\nepoch: 1\nlr: 0.05\n"
        "lr_schedule:\n  type: cosine\n"
        "optimizer:\n  type: sgd\n  decay: 0.0001\n  momentum: 0.9\n"
        "  nesterov: true\n")
    conf_yaml = tmp_path / "conf.yaml"
    conf_yaml.write_text(conf)

    def train(save, cache_dir):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.pop("FAA_COMPILE_CACHE", None)
        r = subprocess.run(
            [sys.executable, "-m", "fast_autoaugment_tpu.launch.train_cli",
             "-c", str(conf_yaml), "--dataroot", str(tmp_path),
             "--save", save, "--cv-ratio", "0.4",
             "--evaluation-interval", "1",
             "--compile-cache", str(cache_dir)],
            env=env, capture_output=True, text=True, timeout=900)
        assert r.returncode == 0, r.stderr[-2000:]
        return r

    import hashlib

    def digest(path):
        with open(path, "rb") as fh:
            return hashlib.sha256(fh.read()).hexdigest()

    ck_cache = tmp_path / "ck_cache"
    train(str(tmp_path / "a.msgpack"), ck_cache)   # cold
    r2 = train(str(tmp_path / "b.msgpack"), ck_cache)  # warm
    assert re.search(r"compile cache: dir=\S+ hits=[1-9]", r2.stderr), \
        r2.stderr[-2000:]
    assert digest(tmp_path / "a.msgpack") == digest(tmp_path / "b.msgpack")


@pytest.mark.slow
def test_exit77_resume_reports_cache_hit(tmp_path):
    """The resilience coupling end-to-end: a SIGTERMed CLI trainer
    exits 77 (checkpointed), and the RESUMED process — sharing the
    compile-cache dir — reports cache hits: the resume reached its
    first step without re-paying the compile tax."""
    conf_yaml = tmp_path / "conf.yaml"
    conf_yaml.write_text(
        "model:\n  type: wresnet10_1\ndataset: synthetic\naug: default\n"
        "cutout: 0\nbatch: 8\nepoch: 2\nlr: 0.05\n"
        "lr_schedule:\n  type: cosine\n"
        "optimizer:\n  type: sgd\n  decay: 0.0001\n  momentum: 0.9\n"
        "  nesterov: true\n")
    cache = tmp_path / "cache"

    def run(fault=None):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.pop("FAA_FAULT", None)
        env.pop("FAA_COMPILE_CACHE", None)
        if fault:
            env["FAA_FAULT"] = fault
        return subprocess.run(
            [sys.executable, "-m", "fast_autoaugment_tpu.launch.train_cli",
             "-c", str(conf_yaml), "--dataroot", str(tmp_path),
             "--save", str(tmp_path / "ck.msgpack"), "--cv-ratio", "0.4",
             "--evaluation-interval", "1",
             "--compile-cache", str(cache)],
            env=env, capture_output=True, text=True, timeout=900)

    r = run(fault="sigterm@step=2")
    assert r.returncode == 77, (r.returncode, r.stderr[-2000:])

    r2 = run()
    assert r2.returncode == 0, r2.stderr[-2000:]
    assert "resumed" in r2.stderr
    m = re.search(r"compile cache: dir=\S+ hits=(\d+) misses=(\d+)",
                  r2.stderr)
    assert m, r2.stderr[-2000:]
    assert int(m.group(1)) > 0, "resumed process reported no cache hits"
