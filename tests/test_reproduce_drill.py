"""The real-data fire-drill (tools/reproduce.py, `make reproduce`):
offline it must skip gracefully with exit 0; with a reachable (file://)
source it must fetch, verify and extract through the integrity-gated
path.  The actual CIFAR training leg is exercised by tests/test_train.py
on synthetic data — here we only prove the drill's wiring."""

import hashlib
import os
import sys
import tarfile

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))

import reproduce  # noqa: E402


def test_offline_fetch_skips_gracefully(tmp_path, monkeypatch, capsys):
    """Unreachable URLs (zero-egress environment) must not raise: the
    drill reports the skip and exits 0."""
    monkeypatch.setitem(
        reproduce.DATA_TABLE, "cifar10",
        [{"url": "file:///nonexistent/cifar.tar.gz", "md5": "0" * 32,
          "extract": True}],
    )
    rc = reproduce.main(["--dataroot", str(tmp_path), "--dry-run"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "skipping" in out and "datasets ready: none" in out


def test_local_fetch_verify_extract(tmp_path, monkeypatch, capsys):
    """file:// source with the right md5 flows through fetch + extract
    (the same path a real download takes)."""
    src_dir = tmp_path / "mirror"
    src_dir.mkdir()
    inner = src_dir / "payload.bin"
    inner.write_bytes(b"cifar-stand-in")
    tar_path = src_dir / "cifar-10-python.tar.gz"
    with tarfile.open(tar_path, "w:gz") as tar:
        tar.add(inner, arcname="cifar-10-batches-py/data_batch_1")
    md5 = hashlib.md5(tar_path.read_bytes()).hexdigest()

    monkeypatch.setitem(
        reproduce.DATA_TABLE, "cifar10",
        [{"url": f"file://{tar_path}", "md5": md5, "extract": True}],
    )
    dataroot = tmp_path / "data"
    rc = reproduce.main(["--dataroot", str(dataroot), "--dry-run"])
    assert rc == 0
    assert "cifar10" in capsys.readouterr().out
    assert (dataroot / "cifar-10-batches-py" / "data_batch_1").exists()


def test_data_table_shape():
    """Every entry carries a well-formed md5 and an http(s) URL (the
    torchvision-pinned checksums the reference relies on)."""
    for name, items in reproduce.DATA_TABLE.items():
        for item in items:
            assert item["url"].startswith(("http://", "https://")), name
            assert len(item["md5"]) == 32 and "extract" in item, name
