"""End-to-end training smoke tests on the 8-device virtual CPU mesh,
plus single-vs-multi-device equivalence of the jitted train step."""

import os
import tempfile

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fast_autoaugment_tpu.core.config import Config


def _smoke_conf(**over):
    base = {
        "model": {"type": "wresnet10_1"},
        "dataset": "synthetic",
        "aug": "fa_reduced_cifar10",
        "cutout": 16,
        "batch": 8,
        "epoch": 2,
        "lr": 0.05,
        "lr_schedule": {"type": "cosine", "warmup": {"multiplier": 2, "epoch": 1}},
        "optimizer": {"type": "sgd", "decay": 0.0002, "clip": 5.0,
                      "momentum": 0.9, "nesterov": True},
    }
    base.update(over)
    return Config(base)


def test_train_and_eval_smoke_with_checkpoint_resume():
    from fast_autoaugment_tpu.train.trainer import train_and_eval

    with tempfile.TemporaryDirectory() as tmp:
        save = os.path.join(tmp, "ckpt", "model.msgpack")
        reports = []
        result = train_and_eval(
            _smoke_conf(),
            dataroot=tmp,
            test_ratio=0.2,
            cv_fold=0,
            save_path=save,
            evaluation_interval=1,
            reporter=lambda **kw: reports.append(kw),
            metric="last",
        )
        assert result["epoch"] == 2
        assert np.isfinite(result["loss_train"]) and result["loss_train"] > 0
        assert 0.0 <= result["top1_valid"] <= 1.0
        assert 0.0 <= result["top1_test"] <= 1.0
        assert len(reports) == 2
        assert os.path.exists(save)

        # metadata readable without loading tensors
        from fast_autoaugment_tpu.core.checkpoint import read_metadata

        meta = read_metadata(save)
        assert meta["epoch"] == 2

        # resume: epoch_start > epochs -> auto only_eval (reference train.py:205)
        result2 = train_and_eval(
            _smoke_conf(),
            dataroot=tmp,
            test_ratio=0.2,
            cv_fold=0,
            save_path=save,
            evaluation_interval=1,
            metric="last",
        )
        assert result2["epoch"] == 2
        assert result2["top1_test"] == pytest.approx(result["top1_test"], abs=1e-6)


def test_empty_valid_split_skipped_and_metric_valid_errors():
    """With test_ratio=0 (every phase-3 search retrain) the empty valid
    split must be skipped entirely — no zero-metric rows — and
    metric='valid' must be a hard error instead of silently tracking a
    best of 0.0 (reference only evaluates real splits, train.py:272-280)."""
    from fast_autoaugment_tpu.train.trainer import train_and_eval

    with tempfile.TemporaryDirectory() as tmp:
        conf = _smoke_conf(aug="default", epoch=1)
        with pytest.raises(ValueError, match="metric='valid'"):
            train_and_eval(conf, dataroot=tmp, test_ratio=0.0, metric="valid")

        result = train_and_eval(
            conf, dataroot=tmp, test_ratio=0.0, evaluation_interval=1,
            metric="last",
        )
        assert not any(k.endswith("_valid") for k in result), \
            f"empty valid split leaked zero metrics: {sorted(result)}"
        assert "top1_test" in result  # real split still evaluated


def test_train_with_mixup_ema_default_aug():
    from fast_autoaugment_tpu.train.trainer import train_and_eval

    with tempfile.TemporaryDirectory() as tmp:
        conf = _smoke_conf(
            aug="default",
            mixup=0.2,
            lb_smooth=0.1,
        ).replace(**{"optimizer.ema": 0.99, "epoch": 1})
        result = train_and_eval(
            conf, dataroot=tmp, test_ratio=0.2, evaluation_interval=1, metric="last"
        )
        assert np.isfinite(result["loss_train"])
        assert "top1_test_ema" in result


@pytest.mark.slow
def test_bf16_precision_smoke():
    """bf16 activations: params/logits stay f32, training runs, and the
    f32-vs-bf16 forward agree to bf16 tolerance."""
    from fast_autoaugment_tpu.models import get_model

    m32 = get_model({"type": "wresnet10_1", "precision": "f32"}, 10)
    m16 = get_model({"type": "wresnet10_1", "precision": "bf16"}, 10)
    x = jnp.asarray(
        np.random.default_rng(0).integers(0, 256, (4, 32, 32, 3)), jnp.float32
    ) / 255.0
    v = m32.init({"params": jax.random.PRNGKey(0)}, x, train=False)
    assert all(p.dtype == jnp.float32 for p in jax.tree.leaves(v["params"]))
    o32 = m32.apply(v, x, train=False)
    o16 = m16.apply(v, x, train=False)
    assert o16.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(o32), np.asarray(o16), atol=5e-2)

    # every family accepts bf16 now; unknown strings raise
    for conf in (
        {"type": "pyramid", "precision": "bf16", "depth": 11, "alpha": 4,
         "bottleneck": False},
        {"type": "shakeshake26_2x32d", "precision": "bf16"},
        {"type": "efficientnet-b0", "precision": "bf16"},
    ):
        m = get_model(conf, 10)
        vv = m.init({"params": jax.random.PRNGKey(0),
                     "shake": jax.random.PRNGKey(1)},
                    jnp.zeros((1, 32, 32, 3)), train=False)
        out = m.apply(vv, jnp.zeros((1, 32, 32, 3)), train=False)
        assert out.dtype == jnp.float32
        assert all(p.dtype == jnp.float32 for p in jax.tree.leaves(vv["params"]))
    with pytest.raises(ValueError, match="unknown precision"):
        get_model({"type": "wresnet10_1", "precision": "fp16"}, 10)


def test_ema_interval_restores_weights():
    """ema_interval > 0 must copy the EMA shadow into the live weights
    every interval epochs (reference train.py:262-270)."""
    from fast_autoaugment_tpu.train.trainer import train_and_eval

    with tempfile.TemporaryDirectory() as tmp:
        conf = _smoke_conf(aug="default", epoch=1).replace(
            **{"optimizer.ema": 0.5, "optimizer.ema_interval": 1}
        )
        result = train_and_eval(
            conf, dataroot=tmp, test_ratio=0.2, evaluation_interval=1, metric="last"
        )
        # with EMA on, reported test metrics ARE the EMA metrics
        assert result["top1_test"] == pytest.approx(result["top1_test_ema"])
        assert "top1_test_raw" in result


def test_target_lb_restricts_to_single_class():
    from fast_autoaugment_tpu.train.trainer import train_and_eval

    with tempfile.TemporaryDirectory() as tmp:
        conf = _smoke_conf(aug="default", epoch=1, batch=2)
        result = train_and_eval(
            conf, dataroot=tmp, test_ratio=0.4, evaluation_interval=1,
            metric="last", target_lb=3,
        )
        # synthetic has ~51 examples/class; valid fold ~20 of class 3 only
        assert 0 < result["num_valid"] < 40


def test_lenient_import_seeds_ema_and_schedule_position(tmp_path):
    """Regression: resuming from a torch-imported checkpoint (no
    opt_state/ema in the file) must (a) seed the EMA shadow from the
    IMPORTED weights, not random init, and (b) place the step counter at
    the resume epoch so the LR schedule continues from its tail."""
    import jax.numpy as jnp

    from fast_autoaugment_tpu.core.checkpoint import save_checkpoint
    from fast_autoaugment_tpu.models import get_model
    from fast_autoaugment_tpu.ops.optim import build_optimizer
    from fast_autoaugment_tpu.train.steps import create_train_state
    from fast_autoaugment_tpu.train.trainer import train_and_eval

    # build "imported" weights: a real state with a recognizable value
    model = get_model({"type": "wresnet10_1"}, 10)
    opt = build_optimizer({"type": "sgd", "decay": 0, "momentum": 0.9,
                           "nesterov": True}, lambda s: 0.1)
    donor = create_train_state(model, opt, jax.random.PRNGKey(42),
                               jnp.zeros((2, 32, 32, 3)), use_ema=False)
    marked = jax.tree.map(lambda p: jnp.full_like(p, 0.0123), donor.params)
    path = str(tmp_path / "imported.msgpack")
    save_checkpoint(
        path,
        {"step": 0, "params": marked, "batch_stats": donor.batch_stats},
        {"epoch": 1, "imported_from": "x.pth", "has_ema": False},
    )

    conf = _smoke_conf(aug="default", epoch=2).replace(**{"optimizer.ema": 0.9999})
    result = train_and_eval(
        conf, dataroot=str(tmp_path), test_ratio=0.2, save_path=path,
        evaluation_interval=1, metric="last",
    )
    # epoch 1 came from metadata; only epoch 2 trains
    assert result["epoch"] == 2
    # EMA with mu≈1 and warmup mu_t=min(mu,(1+s)/(10+s)): after resuming at
    # a large step the shadow barely moves off its seed — if it had been
    # seeded from random init, top1_test_ema would differ wildly from the
    # few-step-trained raw model.  Instead both must be finite and the run
    # must not crash; the sharp check is the seed value itself:
    assert np.isfinite(result["loss_train"])


def test_train_step_single_vs_eight_devices(devices8):
    """The same global batch must produce (numerically) the same update
    whether it lives on 1 device or is sharded over 8 — XLA's implicit
    gradient reduction is the DDP allreduce."""
    from fast_autoaugment_tpu.models import get_model
    from fast_autoaugment_tpu.ops.optim import build_optimizer
    from fast_autoaugment_tpu.parallel.mesh import make_mesh, shard_batch
    from fast_autoaugment_tpu.train.steps import create_train_state, make_train_step

    model = get_model({"type": "wresnet10_1"}, 10)
    rng = jax.random.PRNGKey(0)
    sample = jnp.zeros((2, 32, 32, 3), jnp.float32)

    def build():
        optimizer = build_optimizer(
            {"type": "sgd", "decay": 1e-4, "clip": 5.0, "momentum": 0.9,
             "nesterov": True},
            lambda s: 0.1,
        )
        state = create_train_state(model, optimizer, rng, sample, use_ema=False)
        step = make_train_step(model, optimizer, num_classes=10, use_policy=False)
        return state, step

    images = np.random.default_rng(0).integers(0, 256, (16, 32, 32, 3), dtype=np.uint8)
    labels = np.random.default_rng(1).integers(0, 10, (16,), dtype=np.int32)
    key = jax.random.PRNGKey(7)
    pol = jnp.zeros((1, 1, 3), jnp.float32)

    state1, step1 = build()
    mesh1 = make_mesh(devices8[:1])
    b1 = shard_batch(mesh1, {"x": images, "y": labels})
    out1, m1 = step1(state1, b1["x"], b1["y"], pol, key)

    state8, step8 = build()
    mesh8 = make_mesh(devices8)
    b8 = shard_batch(mesh8, {"x": images, "y": labels})
    out8, m8 = step8(state8, b8["x"], b8["y"], pol, key)

    assert float(m1["top1"]) == float(m8["top1"])
    np.testing.assert_allclose(float(m1["loss"]), float(m8["loss"]), rtol=1e-5)
    l1 = jax.tree.leaves(out1.params)
    l8 = jax.tree.leaves(out8.params)
    # f32 cross-device reduction reordering through batch-norm gives
    # O(1e-5) absolute drift after one lr=0.1 step; anything larger
    # would indicate a real semantic difference.
    for a, b in zip(l1, l8):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-4)
