"""Async actor/learner search pipeline (ISSUE 9, search/pipeline.py):
the TPE proposal ledger's out-of-order tell semantics, the pipeline's
determinism under completion reordering, serial bit-for-bit
equivalence at the one-round in-flight window, resume-to-identical
continuation, phase-1/phase-2 overlap, and the preemption drill."""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from fast_autoaugment_tpu.core.resilience import (
    PreemptedError,
    clear_preemption,
    request_preemption,
)
from fast_autoaugment_tpu.search.pipeline import (
    DispatchTrace,
    replay_trial_log,
    resolve_async_pipeline,
    run_fold_pipeline,
    run_overlapped_phases,
)
from fast_autoaugment_tpu.search.tpe import TPE, choice, uniform

SPACE = [uniform("x", 0, 1), uniform("y", 0, 1), choice("c", 4)]


def _objective(s):
    return -((s["x"] - 0.7) ** 2) + (0.5 if s["c"] == 2 else 0.0)


@pytest.fixture(autouse=True)
def _clean_preemption():
    clear_preemption()
    yield
    clear_preemption()


# ------------------------------------------------------ proposal ledger

def test_resolve_async_pipeline():
    assert resolve_async_pipeline("off") is False
    assert resolve_async_pipeline("on") is True
    assert resolve_async_pipeline(None) is False
    assert resolve_async_pipeline(True) is True
    with pytest.raises(ValueError, match="async_pipeline"):
        resolve_async_pipeline("maybe")


@pytest.mark.parametrize("k", [1, 3])
def test_ask_tagged_lockstep_is_serial_ask_bit_for_bit(k):
    """With no pending trials at ask time (tell each round before the
    next ask), ask_tagged consumes exactly the RNG stream of ask() —
    the property behind the pipeline's serial-equivalence mode.  Spans
    the startup -> posterior transition."""
    a, b = TPE(SPACE, seed=3), TPE(SPACE, seed=3)
    for _ in range(30):
        ps = a.ask(k)
        a.tell_batch(ps, [_objective(p) for p in ps])
        tagged = b.ask_tagged(k)
        assert [p for _t, p in tagged] == ps
        for tid, p in tagged:
            b.tell(tid, _objective(p))
    assert b.num_told == len(a.observations)
    assert b.best_told[1] == a.best[1]


def test_shuffled_tells_reproduce_in_order_posterior():
    """The satellite contract: tells applied in ANY order produce the
    same posterior (the ledger materializes observations in canonical
    trial-id order), so the next proposals are identical."""
    import random

    def run(order_seed):
        t = TPE(SPACE, seed=7, n_startup=4)
        flat = [x for _ in range(3) for x in t.ask_tagged(4)]
        idx = list(range(len(flat)))
        random.Random(order_seed).shuffle(idx)
        for i in idx:
            tid, p = flat[i]
            t.tell(tid, _objective(p))
        return t, [p for _t, p in t.ask_tagged(4)]

    t_in, next_in = run(1)
    t_sh, next_sh = run(42)
    assert next_in == next_sh
    # in-order is id order only by luck of the shuffle; at least one of
    # the two runs must have observed reorders with 12 pending trials
    assert t_in.tell_reorders + t_sh.tell_reorders > 0


def test_ledger_tell_errors_and_reorder_count():
    t = TPE(SPACE, seed=0)
    (t0_id, _), (t1_id, _) = t.ask_tagged(2)
    with pytest.raises(KeyError, match="never asked"):
        t.tell(99, 0.5)
    t.tell(t1_id, 0.5)  # out of order: t0 still pending
    assert t.tell_reorders == 1
    with pytest.raises(KeyError, match="already told"):
        t.tell(t1_id, 0.6)
    t.tell(t0_id, 0.4)
    assert t.tell_reorders == 1
    assert t.worst_told() == 0.4
    assert t.pending_ids == []


def test_replay_continuation_matches_uninterrupted():
    """Ledger replay re-runs the exact canonical ask/tell interleaving
    (ask round r after telling round r-M), so a resumed run's remaining
    proposals — including the rounds in flight at the crash — are the
    uninterrupted run's, bit for bit."""
    def reward(p):
        return p["x"] * 0.3 + (0.2 if p["c"] == 1 else 0.0)

    num_search, K, M = 17, 3, 2

    def drive(t, log, inflight):
        while len(log) < num_search:
            while len(inflight) < M and t._next_trial_id < num_search:
                inflight.append(t.ask_tagged(
                    min(K, num_search - t._next_trial_id)))
            rnd = inflight.pop(0)
            for tid, p in rnd:
                r = reward(p)
                t.tell(tid, r)
                log.append((p, r))
        return log

    full = drive(TPE(SPACE, seed=5, n_startup=4), [], [])
    for cut in (3, 9, 15):  # whole-round prefixes
        t = TPE(SPACE, seed=5, n_startup=4)
        log = list(full[:cut])
        replay_trial_log(t, log, K, num_search, max_inflight=M)
        rounds: dict[int, list] = {}
        for tid in t.pending_ids:
            rounds.setdefault(tid // K, []).append(tid)
        inflight = [[(tid, t.pending_proposal(tid)) for tid in rounds[r]]
                    for r in sorted(rounds)]
        assert drive(t, log, inflight) == full, cut


# --------------------------------------------- pipeline (stub evaluator)

class _StubEval:
    """Host-only _FoldEval stand-in: deterministic per-lane rewards
    from the policy tensor, optional per-round delays (to force
    out-of-order completion) and injected failures."""

    def __init__(self, delay_fn=None, fail_bases=()):
        self.delay_fn = delay_fn
        self.fail_bases = set(fail_bases)
        self.calls = 0

    def _maybe_fail_delay(self, t_base):
        self.calls += 1
        if self.delay_fn is not None:
            time.sleep(self.delay_fn(t_base))
        if t_base in self.fail_bases:
            raise RuntimeError(f"stub failure at {t_base}")

    @staticmethod
    def _reward(policy_lane):
        return round(float(np.asarray(policy_lane).sum()) % 1.0, 6)

    def evaluate(self, fold, params, batch_stats, policy_t, key):
        raise AssertionError("stub is batched-only in these tests")

    def evaluate_batch(self, fold, params, batch_stats, policies_t, keys):
        t_base = getattr(self, "_t_base", None)
        self._maybe_fail_delay(t_base)
        return [{"top1_valid": self._reward(policies_t[i])}
                for i in range(int(policies_t.shape[0]))]


def _policy_space():
    from fast_autoaugment_tpu.search.driver import make_search_space

    return make_search_space(1, 1)  # the decoder's real key layout


def _drive_pipeline(num_search=12, k=3, actors=1, queue_depth=0,
                    seed=11, delay_fn=None, fail_bases=(),
                    fold_trials=None):
    """run_fold_pipeline against the stub with driver-equivalent
    callbacks; returns (fold_trials, stats, persist_calls,
    quarantines)."""
    tpe = TPE(_policy_space(), seed=seed, n_startup=4)
    fold_trials = fold_trials if fold_trials is not None else []
    replay_trial_log(tpe, fold_trials, k, num_search,
                     max_inflight=actors + queue_depth)
    persists = []
    quarantines = []
    ev = _StubEval(delay_fn=delay_fn, fail_bases=fail_bases)

    # the stub needs the round base to decide failures/delays; wrap
    # evaluate_batch to receive it via the keys' first trial id is not
    # visible, so thread it through a tiny shim
    orig = ev.evaluate_batch

    def eb(fold, params, batch_stats, policies_t, keys):
        ev._t_base = eb_bases.pop(0) if eb_bases else None
        return orig(fold, params, batch_stats, policies_t, keys)

    eb_bases: list[int] = []

    class _Shim:
        def evaluate_batch(self, *a):
            return eb(*a)

        def evaluate(self, *a):
            return ev.evaluate(*a)

    # precompute the base sequence: rounds are dispatched in ask order
    pending = tpe.pending_ids
    bases = sorted({t - t % k for t in pending})
    nxt = tpe._next_trial_id
    while nxt < num_search:
        bases.append(nxt)
        nxt += min(k, num_search - nxt)
    eb_bases.extend(bases)

    import jax

    stats = run_fold_pipeline(
        _Shim(), 0, None, None, tpe, jax.random.PRNGKey(0), fold_trials,
        num_search=num_search, trial_batch=k, actors=actors,
        queue_depth=queue_depth, num_policy=1, num_op=1,
        persist=lambda: persists.append(len(fold_trials)),
        record_quarantine=lambda lo, hi, exc, worst: quarantines.append(
            (lo, hi, str(exc), worst)),
    )
    return fold_trials, stats, persists, quarantines


def _serial_reference(num_search=12, k=3, seed=11):
    """The serial batched scheduler's trial log for the stub reward."""
    from fast_autoaugment_tpu.policies.archive import (
        policy_decoder,
        policy_to_tensor,
    )

    tpe = TPE(_policy_space(), seed=seed, n_startup=4)
    log = []
    while len(tpe.observations) < num_search:
        t_base = len(tpe.observations)
        k_eff = min(k, num_search - t_base)
        proposals = tpe.ask(k_eff)
        rewards = [
            _StubEval._reward(np.asarray(
                policy_to_tensor(policy_decoder(p, 1, 1)), np.float32))
            for p in proposals
        ]
        tpe.tell_batch(proposals, rewards)
        log.extend(zip(proposals, rewards))
    return [(p, r) for p, r in log]


def test_pipeline_lockstep_reproduces_serial_log():
    """actors=1, queue_depth=0 (one-round in-flight window): the
    pipeline's trial log equals the serial ask/tell_batch scheduler's
    bit for bit — the acceptance equivalence mode."""
    got, stats, persists, _q = _drive_pipeline(actors=1, queue_depth=0)
    want = _serial_reference()
    assert [(p, float(r)) for p, r in got] == want
    assert stats["rounds"] == 4 and stats["tell_reorders"] == 0
    assert persists == [3, 6, 9, 12]  # one persist per processed round


def test_pipeline_deterministic_under_out_of_order_completion():
    """3 actors, delays that invert completion order: the log, stats
    and final posterior must be identical to the no-delay run (tells
    buffer and apply in id order; asks follow the fixed horizon)."""
    base, s0, _p, _q = _drive_pipeline(actors=3, queue_depth=2)
    slow_first = _drive_pipeline(
        actors=3, queue_depth=2,
        delay_fn=lambda t_base: 0.15 if t_base == 0 else 0.0)
    jittered = _drive_pipeline(
        actors=3, queue_depth=2,
        delay_fn=lambda t_base: [0.12, 0.0, 0.06][(t_base or 0) // 3 % 3])
    assert slow_first[0] == base
    assert jittered[0] == base
    # delaying round 0 while rounds 1-2 finish forces observed reorders
    assert slow_first[1]["tell_reorders"] > 0


def test_pipeline_resume_mid_log_completes_identically():
    """Crash simulation: truncate the log to a whole-round prefix and
    rerun — the continuation (including the rounds that were in flight
    at the cut) matches the uninterrupted log exactly."""
    full, _s, _p, _q = _drive_pipeline(actors=2, queue_depth=1)
    for cut in (3, 6, 9):
        resumed, _s2, _p2, _q2 = _drive_pipeline(
            actors=2, queue_depth=1, fold_trials=list(full[:cut]))
        assert resumed == full, cut


def test_pipeline_quarantine_entry_format_and_never_ranks():
    """A failed round quarantines with the serial scheduler's entry
    shape — (proposal, worst-so-far, {'quarantined': True, ...}) — and
    the driver's ranking filter drops exactly those entries."""
    got, stats, _p, quars = _drive_pipeline(
        actors=1, queue_depth=0, fail_bases={3})
    assert len(got) == 12
    bad = got[3:6]
    worst = min(float(r) for _p2, r in got[:3])
    for p, r, meta in bad:
        assert meta["quarantined"] and "stub failure" in meta["error"]
        assert float(r) == worst
    assert quars == [(3, 6, "stub failure at 3", worst)]
    # the driver's ranking filter (search_policies top-N loop)
    scored = [t for t in got
              if len(t) < 3 or not (t[2] or {}).get("quarantined")]
    assert len(scored) == 9
    assert all(len(t) == 2 for t in scored)


def test_pipeline_faa_fault_trial_error_quarantines_round():
    """The deterministic injection seam (FAA_FAULT trial_error@trial=N)
    fires inside the ACTOR, exactly like the serial scheduler's
    per-trial check — the round quarantines, the search continues, and
    the log stays deterministic."""
    from fast_autoaugment_tpu.utils import faultinject

    os.environ["FAA_FAULT"] = "trial_error@trial=4"
    faultinject.reset()
    try:
        got, _s, _p, quars = _drive_pipeline(actors=2, queue_depth=1)
    finally:
        os.environ.pop("FAA_FAULT", None)
        faultinject.reset()
    assert len(got) == 12
    # trial 4 lives in round 1 (trials 3-5): the whole round quarantines
    assert quars and quars[0][:2] == (3, 6)
    assert "injected trial_error at trial 4" in quars[0][2]
    for p, r, meta in got[3:6]:
        assert meta["quarantined"]
    assert all(len(t) == 2 for t in got[:3] + got[6:])


def test_pipeline_preemption_stops_at_round_boundary():
    """SIGTERM flag mid-run: the learner raises PreemptedError at the
    next boundary with every processed round already persisted."""
    seen = []

    def delay(t_base):
        seen.append(t_base)
        if t_base == 6:  # third round: request shutdown mid-flight
            request_preemption()
        return 0.0

    with pytest.raises(PreemptedError, match="mid-pipeline"):
        _drive_pipeline(actors=1, queue_depth=0, delay_fn=delay)
    clear_preemption()


def test_pipeline_fatal_errors_propagate_not_quarantine():
    """DispatchHungError from an actor is the wedged-backend signal:
    it must re-raise (exit-77 restart path), never quarantine."""
    from fast_autoaugment_tpu.core.resilience import DispatchHungError

    def delay(t_base):
        if t_base == 3:
            raise DispatchHungError("tta_batched", 1.0, 2.0)
        return 0.0

    with pytest.raises(DispatchHungError):
        _drive_pipeline(actors=1, queue_depth=0, delay_fn=delay)


# ------------------------------------------------------- dispatch trace

def test_dispatch_trace_summary_merges_and_buckets():
    tr = DispatchTrace()
    tr.record(0.0, 1.0)  # ignored: no open segment
    tr.begin_segment("p2-fold0")
    tr.record(0.0, 1.0)
    tr.record(1.005, 2.0)    # 5 ms gap
    tr.record(2.5, 3.0)      # 500 ms gap
    tr.record(2.6, 2.9)      # overlapping window: merged, no gap
    tr.end_segment()
    tr.record(5.0, 6.0)      # ignored: segment closed
    s = tr.summary()
    assert s["num_dispatches"] == 4 and s["num_segments"] == 1
    assert s["num_gaps"] == 2
    assert s["busy_secs"] == pytest.approx(2.495)
    assert s["device_busy_frac"] == pytest.approx(2.495 / 3.0)
    assert s["gap_hist"]["<10ms"] == 1 and s["gap_hist"]["<1000ms"] == 1
    assert DispatchTrace().summary() is None


# -------------------------------------------------------- phase overlap

def test_run_overlapped_phases_timeline_and_errors():
    """Fold k's phase 2 runs while fold k+1's phase 1 still trains;
    trainer exceptions re-raise in the caller with their type."""
    def p1(f):
        time.sleep(0.15)

    def p2(f):
        time.sleep(0.15)

    tl = run_overlapped_phases([0, 1, 2], p1, p2, poll_sec=0.02)
    assert tl["overlap_secs"] > 0.0
    assert tl["phase2"]["0"]["start"] < tl["phase1"]["2"]["end"]
    assert set(tl["phase1"]) == set(tl["phase2"]) == {"0", "1", "2"}

    def p1_boom(f):
        if f == 1:
            raise PreemptedError("trainer preempted")
        time.sleep(0.01)

    with pytest.raises(PreemptedError, match="trainer preempted"):
        run_overlapped_phases([0, 1, 2], p1_boom, p2, poll_sec=0.02)


def test_run_overlapped_phases_phase2_error_stops_trainer():
    trained = []
    stop_seen = threading.Event()

    def p1(f):
        trained.append(f)
        time.sleep(0.05)

    def p2(f):
        raise ValueError("phase2 boom")

    with pytest.raises(ValueError, match="phase2 boom"):
        run_overlapped_phases([0, 1, 2, 3], p1, p2, poll_sec=0.02)
    stop_seen.set()
    # the trainer stops between folds: it cannot have trained them all
    # strictly after the failure (bounded, not instant — allow slack)
    assert len(trained) <= 3


# ------------------------------------------------------------ CLI flags

def test_cli_pipeline_flags():
    from fast_autoaugment_tpu.launch.search_cli import build_parser

    p = build_parser()
    args = p.parse_args(["-c", "x.yaml"])
    assert args.async_pipeline == "off"
    assert args.pipeline_actors == 1
    assert args.pipeline_queue_depth == 1
    args = p.parse_args(["-c", "x.yaml", "--async-pipeline", "on",
                         "--pipeline-actors", "2",
                         "--pipeline-queue-depth", "3"])
    assert (args.async_pipeline, args.pipeline_actors,
            args.pipeline_queue_depth) == ("on", 2, 3)
    with pytest.raises(SystemExit):
        p.parse_args(["-c", "x.yaml", "--async-pipeline", "maybe"])


# ----------------------------------------------- e2e (real stack, slow)

def _tiny_conf():
    from fast_autoaugment_tpu.core.config import Config

    return Config({
        "model": {"type": "wresnet10_1"},
        "dataset": "synthetic",
        "aug": "default",
        "cutout": 8,
        "batch": 8,
        "epoch": 1,
        "lr": 0.05,
        "lr_schedule": {"type": "cosine"},
        "optimizer": {"type": "sgd", "decay": 1e-4, "clip": 5.0,
                      "momentum": 0.9, "nesterov": True},
    })


_CKPT_SUFFIXES = ("", ".meta.json")


def _copy_fold0(src_dir, dst_dir, conf, cv_ratio=0.4):
    from fast_autoaugment_tpu.search.driver import _fold_ckpt_path

    os.makedirs(dst_dir, exist_ok=True)
    name = os.path.basename(_fold_ckpt_path(src_dir, conf, 0, cv_ratio))
    for suffix in _CKPT_SUFFIXES:
        p = os.path.join(src_dir, name + suffix)
        if os.path.exists(p):
            shutil.copy2(p, os.path.join(dst_dir, name + suffix))


@pytest.mark.slow
def test_async_lockstep_matches_serial_e2e(tmp_path):
    """Real stack: --async-pipeline on with 1 actor + queue depth 0
    reproduces the serial scheduler's trial log and final policy set
    bit for bit; the default off run stays stamp-free (bit-for-bit
    historical artifact)."""
    from fast_autoaugment_tpu.search.driver import search_policies

    conf = _tiny_conf()
    common = dict(dataroot=str(tmp_path), cv_num=1, cv_ratio=0.4,
                  num_policy=1, num_op=1, num_search=5, num_top=2,
                  trial_batch=2)
    r1 = search_policies(conf, save_dir=str(tmp_path / "serial"), **common)
    assert "pipeline" not in r1  # off = the historical artifact
    t_serial = json.load(open(tmp_path / "serial" / "search_trials.json"))

    _copy_fold0(str(tmp_path / "serial"), str(tmp_path / "lock"), conf)
    r2 = search_policies(conf, save_dir=str(tmp_path / "lock"),
                         async_pipeline="on", pipeline_actors=1,
                         pipeline_queue_depth=0, **common)
    t_lock = json.load(open(tmp_path / "lock" / "search_trials.json"))
    assert t_lock == t_serial
    assert r2["final_policy_set"] == r1["final_policy_set"]
    assert r2["pipeline"]["mode"] == "on"
    assert r2["pipeline"]["max_inflight"] == 1
    assert r2["pipeline"]["dispatch_gaps"]["num_dispatches"] > 0
    assert r2["pipeline"]["device_busy_frac"] > 0
    # census invariants hold through the actor threads
    assert r2["tta_batched_executables"] in (None, 1)


@pytest.mark.slow
def test_async_resume_completes_to_identical_artifacts(tmp_path):
    """The acceptance resume contract: truncate an async run's trial
    log to a mid-search whole-round prefix, rerun — trial log AND
    final_policy.json complete bit-identical to the uninterrupted
    run's (ledger replay reconstructs the exact in-flight horizon)."""
    from fast_autoaugment_tpu.search.driver import search_policies

    conf = _tiny_conf()
    common = dict(dataroot=str(tmp_path), cv_num=1, cv_ratio=0.4,
                  num_policy=1, num_op=1, num_search=6, num_top=2,
                  trial_batch=2, async_pipeline="on", pipeline_actors=1,
                  pipeline_queue_depth=1)
    a = str(tmp_path / "uninterrupted")
    search_policies(conf, save_dir=a, **common)
    log_a = json.load(open(os.path.join(a, "search_trials.json")))
    final_a = open(os.path.join(a, "final_policy.json"), "rb").read()

    b = str(tmp_path / "resumed")
    _copy_fold0(a, b, conf)
    # crash simulation: only the first round (2 trials) was persisted
    from fast_autoaugment_tpu.search.driver import write_json_atomic

    write_json_atomic(os.path.join(b, "search_trials.json"),
                      {"0": log_a["0"][:2]})
    search_policies(conf, save_dir=b, **common)
    assert json.load(open(os.path.join(b, "search_trials.json"))) == log_a
    assert open(os.path.join(b, "final_policy.json"), "rb").read() == final_a


@pytest.mark.slow
def test_preemption_mid_overlap_drill(tmp_path):
    """THE acceptance drill, end to end through the CLI: fold 0's
    phase-2 pipeline runs while fold 1's phase-1 training is in flight
    (the overlap timeline proves it), FAA_FAULT sigterm fires during
    that overlap -> exit 77 -> the rerun resumes and completes with
    final_policy.json bit-identical to an uninterrupted reference."""
    tmp = str(tmp_path)
    conf_yaml = tmp_path / "conf.yaml"
    conf_yaml.write_text(
        "model:\n  type: wresnet10_1\ndataset: synthetic\naug: default\n"
        "cutout: 8\nbatch: 8\nepoch: 1\nlr: 0.05\n"
        "lr_schedule:\n  type: cosine\n"
        "optimizer:\n  type: sgd\n  decay: 0.0001\n  momentum: 0.9\n"
        "  nesterov: true\n")

    def run(save, fault=None):
        env = dict(os.environ)
        env.pop("FAA_FAULT", None)
        if fault:
            env["FAA_FAULT"] = fault
        return subprocess.run(
            [sys.executable, "-m",
             "fast_autoaugment_tpu.launch.search_cli",
             "-c", str(conf_yaml), "--dataroot", tmp, "--save-dir", save,
             "--num-fold", "2", "--num-search", "4", "--num-policy", "1",
             "--num-op", "1", "--num-top", "2", "--trial-batch", "2",
             "--until", "2", "--fold-quality-floor", "off",
             "--async-pipeline", "on", "--pipeline-actors", "1",
             "--pipeline-queue-depth", "1", "--seed", "0"],
            env=env, capture_output=True, text=True, timeout=900)

    # reference: uninterrupted overlapped run
    ref = f"{tmp}/ref"
    r = run(ref)
    assert r.returncode == 0, r.stderr[-2000:]
    result = json.load(open(f"{ref}/search_result.json"))
    overlap = result["pipeline"]["overlap"]
    # fold 0's trials started while fold 1 still trained
    assert overlap["phase2"]["0"]["start"] < overlap["phase1"]["1"]["end"]
    assert overlap["overlap_secs"] > 0

    # drill: fold 0's checkpoint is pre-seeded so training starts at
    # fold 1 — the sigterm then fires MID-OVERLAP (fold-0 trials in
    # flight against fold-1 training)
    drill = f"{tmp}/drill"
    conf = _tiny_conf()
    _copy_fold0(ref, drill, conf)
    r = run(drill, fault="sigterm@step=2")
    assert r.returncode == 77, (r.returncode, r.stderr[-2000:])

    r = run(drill)
    assert r.returncode == 0, r.stderr[-2000:]
    assert (open(f"{drill}/final_policy.json", "rb").read()
            == open(f"{ref}/final_policy.json", "rb").read())
    assert (json.load(open(f"{drill}/search_trials.json"))
            == json.load(open(f"{ref}/search_trials.json")))
