"""Fault-injection matrix for the resilience subsystem
(docs/RESILIENCE.md): every recovery path — checksummed restore
chains, preemption-safe shutdown + exit-77 resume, divergence
recovery, phase-2 trial quarantine, fleet host retries — is driven
DETERMINISTICALLY through ``FAA_FAULT`` (``utils/faultinject.py``)
rather than trusted on faith.  Defaults-equivalence (all resilience
knobs off => bit-for-bit the historical run) rides on the existing
checkpoint-equivalence harness plus the chain-depth pin here."""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import jax

from fast_autoaugment_tpu.core import resilience
from fast_autoaugment_tpu.core.checkpoint import (
    CheckpointCorruptError,
    chain_paths,
    checkpoint_exists,
    load_checkpoint,
    load_checkpoint_chain,
    read_metadata,
    save_checkpoint,
)
from fast_autoaugment_tpu.core.config import Config
from fast_autoaugment_tpu.utils import faultinject


@pytest.fixture(autouse=True)
def _clean_fault_state():
    """Every test starts (and ends) with no fault plan and a clear
    preemption flag — faultinject state is process-wide."""
    os.environ.pop("FAA_FAULT", None)
    faultinject.reset()
    resilience.clear_preemption()
    yield
    os.environ.pop("FAA_FAULT", None)
    faultinject.reset()
    resilience.clear_preemption()


def _conf(**over):
    base = {
        "model": {"type": "wresnet10_1"},
        "dataset": "synthetic",
        "aug": "default",
        "cutout": 0,
        "batch": 8,
        "epoch": 2,
        "lr": 0.05,
        "lr_schedule": {"type": "cosine"},
        "optimizer": {"type": "sgd", "decay": 1e-4, "momentum": 0.9,
                      "nesterov": True},
    }
    base.update(over)
    return Config(base)


# ------------------------------------------------- FAA_FAULT grammar

def test_parse_fault_spec_grammar():
    faults = faultinject.parse_fault_spec(
        "nan_loss@step=7;sigterm@step=12;torn_ckpt@save=3;"
        "io_error@p=0.1,seed=4; trial_error@trial=2")
    kinds = [f["kind"] for f in faults]
    assert kinds == ["nan_loss", "sigterm", "torn_ckpt", "io_error",
                     "trial_error"]
    assert faults[0]["step"] == 7
    assert faults[2]["save"] == 3
    assert faults[3]["p"] == pytest.approx(0.1)
    assert faults[3]["seed"] == 4
    assert faults[4]["trial"] == 2
    assert faultinject.parse_fault_spec("") == []


@pytest.mark.parametrize("bad", [
    "explode@step=1",           # unknown kind
    "nan_loss",                 # missing @args
    "nan_loss@step",            # malformed kv
    "nan_loss@save=1",          # wrong key for kind
    "io_error@p=1.5",           # p outside [0, 1]
    "sigterm@",                 # missing required key
])
def test_parse_fault_spec_rejects(bad):
    with pytest.raises(ValueError):
        faultinject.parse_fault_spec(bad)


def test_fault_plan_fires_once_and_caches_by_env_value():
    os.environ["FAA_FAULT"] = "nan_loss@step=5"
    plan = faultinject.active_plan()
    assert plan is not None
    assert not plan.nan_loss_in(0, 5)       # [0, 5) misses step 5
    assert plan.nan_loss_in(5, 10)          # fires
    assert not plan.nan_loss_in(5, 10)      # consumed
    assert faultinject.active_plan() is plan  # same env -> same state
    os.environ["FAA_FAULT"] = ""
    assert faultinject.active_plan() is None


def test_preemption_flag_roundtrip():
    assert not resilience.preemption_requested()
    resilience.request_preemption()
    assert resilience.preemption_requested()
    resilience.clear_preemption()
    assert not resilience.preemption_requested()
    assert resilience.PREEMPTED_EXIT_CODE == 77
    assert resilience.PreemptedError.exit_code == 77


def test_signal_handler_sets_flag_only():
    assert resilience.install_signal_handlers()
    os.kill(os.getpid(), signal.SIGUSR1)
    # the handler only sets the flag; nothing raised, nothing exited
    assert resilience.preemption_requested()


# ------------------------------------------- restore chain integrity

def _toy_state(v: float):
    return {"w": np.full((4, 4), v, np.float32), "b": np.float32(v)}


def test_checkpoint_digest_and_corruption_detected(tmp_path):
    path = str(tmp_path / "ck.msgpack")
    save_checkpoint(path, _toy_state(1.0), {"epoch": 1})
    meta = read_metadata(path)
    assert meta["epoch"] == 1 and len(meta["digest"]) == 64
    assert meta["nbytes"] == os.path.getsize(path)
    out = load_checkpoint(path, _toy_state(0.0))
    assert float(out["b"]) == 1.0

    # silent bit-rot: same size, flipped byte -> typed corruption error
    blob = bytearray(open(path, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    with open(path, "wb") as fh:  # robust: allow — test corrupts on purpose
        fh.write(bytes(blob))
    with pytest.raises(CheckpointCorruptError):
        load_checkpoint(path, _toy_state(0.0))
    # torn write: truncated payload -> size mismatch, same typed error
    with open(path, "wb") as fh:  # robust: allow — test tears on purpose
        fh.write(bytes(blob[: len(blob) // 2]))
    with pytest.raises(CheckpointCorruptError):
        load_checkpoint(path, _toy_state(0.0))


def test_restore_chain_rotation_and_walk(tmp_path):
    path = str(tmp_path / "ck.msgpack")
    for i, v in enumerate([1.0, 2.0, 3.0]):
        save_checkpoint(path, _toy_state(v), {"epoch": i + 1}, keep=2)
    links = chain_paths(path, keep=2)
    assert links == [path, path + ".prev"]
    assert read_metadata(path)["epoch"] == 3
    assert read_metadata(path + ".prev")["epoch"] == 2
    assert not os.path.exists(path + ".prev2")  # bounded depth

    # corrupt the newest link: the chain walk recovers the predecessor,
    # reporting which link it used
    with open(path, "wb") as fh:  # robust: allow — test corrupts on purpose
        fh.write(b"garbage")
    got = load_checkpoint_chain(path, _toy_state(0.0), keep=2)
    assert got is not None
    state, meta, used = got
    assert used == path + ".prev"
    assert meta["epoch"] == 2 and float(state["b"]) == 2.0

    # accept predicate: reject everything -> None
    assert load_checkpoint_chain(path, _toy_state(0.0), keep=2,
                                 accept=lambda m: False) is None


def test_ckpt_keep_one_is_prechain_overwrite(tmp_path):
    path = str(tmp_path / "ck.msgpack")
    save_checkpoint(path, _toy_state(1.0), {"epoch": 1}, keep=1)
    save_checkpoint(path, _toy_state(2.0), {"epoch": 2}, keep=1)
    assert not os.path.exists(path + ".prev")
    assert read_metadata(path)["epoch"] == 2


def test_checkpoint_exists_rejects_zero_byte_and_orphan(tmp_path):
    path = str(tmp_path / "ck.msgpack")
    # zero-byte payload left by a crashed pre-atomic-write process
    open(path, "wb").close()  # robust: allow — simulating the crash artifact
    with open(path + ".meta.json", "w") as fh:  # robust: allow — ditto
        json.dump({"epoch": 1}, fh)
    assert not checkpoint_exists(path)
    # nonzero payload but no/torn sidecar
    with open(path, "wb") as fh:  # robust: allow — ditto
        fh.write(b"x" * 64)
    os.remove(path + ".meta.json")
    assert not checkpoint_exists(path)
    with open(path + ".meta.json", "w") as fh:  # robust: allow — ditto
        fh.write("{torn")
    assert not checkpoint_exists(path)
    # intact pair
    save_checkpoint(path, _toy_state(1.0), {"epoch": 1})
    assert checkpoint_exists(path)


def test_read_metadata_absorbs_oserror(tmp_path):
    # sidecar path resolves to a directory -> OSError, not a crash
    path = str(tmp_path / "ck.msgpack")
    os.makedirs(path + ".meta.json")
    assert read_metadata(path) is None


# ------------------------------------ injected checkpoint-write faults

def test_torn_ckpt_injection_walks_chain(tmp_path):
    path = str(tmp_path / "ck.msgpack")
    # saves are counted while the plan is active (1-based)
    os.environ["FAA_FAULT"] = "torn_ckpt@save=2"
    faultinject.reset()
    save_checkpoint(path, _toy_state(1.0), {"epoch": 1})
    save_checkpoint(path, _toy_state(2.0), {"epoch": 2})  # torn mid-write
    # the live link is torn (full-payload digest over half the bytes)
    with pytest.raises(CheckpointCorruptError):
        load_checkpoint(path, _toy_state(0.0))
    state, meta, used = load_checkpoint_chain(path, _toy_state(0.0))
    assert used == path + ".prev" and meta["epoch"] == 1
    assert float(state["b"]) == 1.0  # one torn file cost one epoch


def test_corrupt_ckpt_injection_detected(tmp_path):
    path = str(tmp_path / "ck.msgpack")
    os.environ["FAA_FAULT"] = "corrupt_ckpt@save=2"
    faultinject.reset()
    save_checkpoint(path, _toy_state(1.0), {"epoch": 1})
    save_checkpoint(path, _toy_state(2.0), {"epoch": 2})  # bit-rot
    with pytest.raises(CheckpointCorruptError):
        load_checkpoint(path, _toy_state(0.0))
    _state, meta, used = load_checkpoint_chain(path, _toy_state(0.0))
    assert used == path + ".prev" and meta["epoch"] == 1


def test_io_error_injection_chain_exhaustion(tmp_path):
    path = str(tmp_path / "ck.msgpack")
    save_checkpoint(path, _toy_state(1.0), {"epoch": 1})
    os.environ["FAA_FAULT"] = "io_error@p=1.0,seed=0"
    faultinject.reset()
    with pytest.raises(OSError):
        load_checkpoint(path, _toy_state(0.0))
    # every link unreadable -> the walk comes up empty, loudly, instead
    # of crashing the caller
    assert load_checkpoint_chain(path, _toy_state(0.0)) is None
    os.environ.pop("FAA_FAULT")
    faultinject.reset()
    assert load_checkpoint_chain(path, _toy_state(0.0)) is not None


# --------------------------------------------- fleet host supervision

def _fake_remote(script_by_host, tmp_path):
    """Substitute a local bash script for ssh (per host)."""
    def _argv(host, wire):
        return ["bash", "-c", script_by_host[host]]
    return _argv


def test_fleet_retries_preempted_host_then_succeeds(tmp_path, monkeypatch):
    from fast_autoaugment_tpu.launch import fleet as fleet_mod

    counter = tmp_path / "attempts"
    script = (f"n=$(cat {counter} 2>/dev/null || echo 0); n=$((n+1)); "
              f"echo $n > {counter}; [ $n -ge 3 ] && exit 0 || exit 77")
    monkeypatch.setattr(fleet_mod, "_remote_argv",
                        _fake_remote({"a": script}, tmp_path))
    code = fleet_mod.launch_fleet(["a"], ["true"], "x:1", host_retries=2,
                                  retry_backoff=0.01)
    assert code == 0  # two preempted exits (77), third attempt clean
    assert counter.read_text().strip() == "3"


def test_fleet_out_of_retries_propagates_first_genuine_failure(
        tmp_path, monkeypatch):
    from fast_autoaugment_tpu.launch import fleet as fleet_mod

    # host a (waited FIRST) hangs and dies from the teardown kill; host
    # b fails genuinely with 5.  The old `worst = worst or code` wait
    # loop reported a's kill signal; the supervisor must report b's 5.
    scripts = {"a": "sleep 30; exit 0", "b": "sleep 0.1; exit 5"}
    monkeypatch.setattr(fleet_mod, "_remote_argv",
                        _fake_remote(scripts, tmp_path))
    t0 = time.time()
    code = fleet_mod.launch_fleet(["a", "b"], ["true"], "x:1",
                                  host_retries=0, retry_backoff=0.01)
    assert code == 5
    assert time.time() - t0 < 20  # teardown, not the 30 s sleep


def test_fleet_zero_retries_tears_down_on_77(tmp_path, monkeypatch):
    from fast_autoaugment_tpu.launch import fleet as fleet_mod

    monkeypatch.setattr(fleet_mod, "_remote_argv",
                        _fake_remote({"a": "exit 77"}, tmp_path))
    code = fleet_mod.launch_fleet(["a"], ["true"], "x:1", host_retries=0)
    # with no retry budget the preempted code propagates — the OUTER
    # supervisor (or operator) still sees "resume me"
    assert code == 77


def test_fleet_backoff_is_exponential(tmp_path, monkeypatch):
    from fast_autoaugment_tpu.launch import fleet as fleet_mod

    stamps = tmp_path / "stamps"
    script = f"date +%s.%N >> {stamps}; exit 1"
    monkeypatch.setattr(fleet_mod, "_remote_argv",
                        _fake_remote({"a": script}, tmp_path))
    code = fleet_mod.launch_fleet(["a"], ["true"], "x:1", host_retries=2,
                                  retry_backoff=0.2)
    assert code == 1
    times = [float(x) for x in stamps.read_text().split()]
    assert len(times) == 3  # 1 launch + 2 retries
    gap1, gap2 = times[1] - times[0], times[2] - times[1]
    assert gap1 >= 0.2 and gap2 >= 0.4  # 0.2 * 2^attempt


def test_fleet_cli_flags_parse():
    from fast_autoaugment_tpu.launch.fleet import main

    with pytest.raises(SystemExit):  # no command after flags
        main(["--hosts", "2", "--host-retries", "3", "--retry-backoff",
              "0.5"])


# ------------------------------------------ trainer fault matrix (slow)

_TRAIN_KW = dict(test_ratio=0.4, cv_fold=0, metric="last", seed=0,
                 evaluation_interval=1)


def _final_digest(path: str) -> str:
    meta = read_metadata(path)
    assert meta and "digest" in meta
    return meta["digest"]


@pytest.mark.slow
def test_sigterm_preemption_checkpoints_and_resumes_bit_identical(tmp_path):
    """The flagship matrix case: SIGTERM mid-epoch-2 (injected at the
    step seam) -> checkpoint at the dispatch boundary with
    ``preempted: true`` + the exact position -> PreemptedError (exit-77
    contract) -> the rerun fast-forwards and lands a checkpoint
    BIT-IDENTICAL to an uninterrupted run."""
    from fast_autoaugment_tpu.core.resilience import PreemptedError
    from fast_autoaugment_tpu.train.trainer import train_and_eval

    tmp = str(tmp_path)
    conf = _conf()  # 512 synthetic examples, 0.4 ratio -> 4 steps/epoch
    full = f"{tmp}/full.msgpack"
    train_and_eval(conf, tmp, save_path=full, **_TRAIN_KW)

    part = f"{tmp}/part.msgpack"
    os.environ["FAA_FAULT"] = "sigterm@step=6"  # epoch 2, position 2/4
    faultinject.reset()
    with pytest.raises(PreemptedError):
        train_and_eval(conf, tmp, save_path=part, **_TRAIN_KW)
    meta = read_metadata(part)
    assert meta["preempted"] is True
    assert meta["in_epoch"] == {
        "epoch": 2, "pos": 2, "sums": meta["in_epoch"]["sums"],
        "retries": 0}
    assert meta["epoch"] == 1  # last COMPLETED epoch

    os.environ.pop("FAA_FAULT")
    faultinject.reset()
    resilience.clear_preemption()
    r = train_and_eval(conf, tmp, save_path=part, **_TRAIN_KW)
    assert r["epoch"] == 2
    assert _final_digest(part) == _final_digest(full)
    # the resumed epoch's reported metrics continue the same f32 chain
    m_full, m_part = read_metadata(full)["metrics"], read_metadata(part)["metrics"]
    for k in ("loss_train", "top1_train", "top1_test"):
        assert m_full[k] == m_part[k], k


@pytest.mark.slow
def test_sigterm_on_host_path_preempts_at_epoch_boundary(tmp_path):
    from fast_autoaugment_tpu.core.resilience import PreemptedError
    from fast_autoaugment_tpu.train.trainer import train_and_eval

    tmp = str(tmp_path)
    conf = _conf()
    part = f"{tmp}/host.msgpack"
    os.environ["FAA_FAULT"] = "sigterm@step=2"  # mid-epoch-1
    faultinject.reset()
    with pytest.raises(PreemptedError):
        train_and_eval(conf, tmp, save_path=part, device_cache="off",
                       **_TRAIN_KW)
    meta = read_metadata(part)
    # host path: honored at the epoch boundary, no mid-epoch record
    assert meta["preempted"] is True and meta["epoch"] == 1
    assert "in_epoch" not in meta

    os.environ.pop("FAA_FAULT")
    faultinject.reset()
    resilience.clear_preemption()
    r = train_and_eval(conf, tmp, save_path=part, device_cache="off",
                       **_TRAIN_KW)
    assert r["epoch"] == 2
    full = f"{tmp}/host_full.msgpack"
    train_and_eval(conf, tmp, save_path=full, device_cache="off",
                   **_TRAIN_KW)
    assert _final_digest(part) == _final_digest(full)


@pytest.mark.slow
def test_nan_divergence_rollback_retry_then_succeed(tmp_path):
    """NaN at an epoch-2 step: with --divergence-retries 1 the trainer
    rolls back to the epoch-1 checkpoint, replays with retry-folded
    randomness (the consumed injection does not re-fire) and completes;
    with the default 0 it raises exactly as before."""
    from fast_autoaugment_tpu.train.trainer import train_and_eval

    tmp = str(tmp_path)
    conf = _conf()
    os.environ["FAA_FAULT"] = "nan_loss@step=5"
    faultinject.reset()
    with pytest.raises(RuntimeError, match="diverged"):
        train_and_eval(conf, tmp, save_path=f"{tmp}/raise.msgpack",
                       **_TRAIN_KW)

    os.environ["FAA_FAULT"] = "nan_loss@step=5"
    faultinject.reset()
    r = train_and_eval(conf, tmp, save_path=f"{tmp}/retry.msgpack",
                       divergence_retries=1, **_TRAIN_KW)
    assert r["epoch"] == 2
    assert np.isfinite(r["loss_train"])


@pytest.mark.slow
def test_nan_without_checkpoint_still_raises(tmp_path):
    from fast_autoaugment_tpu.train.trainer import train_and_eval

    os.environ["FAA_FAULT"] = "nan_loss@step=1"  # epoch 1: nothing saved yet
    faultinject.reset()
    with pytest.raises(RuntimeError, match="diverged"):
        train_and_eval(_conf(), str(tmp_path),
                       save_path=f"{tmp_path}/x.msgpack",
                       divergence_retries=3, **_TRAIN_KW)


@pytest.mark.slow
def test_torn_checkpoint_resume_recovers_from_chain(tmp_path):
    """A torn WRITE of the epoch-2 checkpoint (crash mid-save) costs
    exactly one epoch on resume: the chain walks back to epoch 1 and
    the rerun reproduces the uninterrupted final checkpoint."""
    from fast_autoaugment_tpu.train.trainer import train_and_eval

    tmp = str(tmp_path)
    conf = _conf()
    full = f"{tmp}/full.msgpack"
    train_and_eval(conf, tmp, save_path=full, **_TRAIN_KW)

    part = f"{tmp}/torn.msgpack"
    os.environ["FAA_FAULT"] = "torn_ckpt@save=2"  # the epoch-2 save tears
    faultinject.reset()
    train_and_eval(conf, tmp, save_path=part, **_TRAIN_KW)
    os.environ.pop("FAA_FAULT")
    faultinject.reset()
    # the live link is corrupt; resume walks to epoch 1 and replays
    r = train_and_eval(conf, tmp, save_path=part, **_TRAIN_KW)
    assert r["epoch"] == 2
    assert _final_digest(part) == _final_digest(full)


@pytest.mark.slow
def test_ckpt_keep_default_chain_matches_keep1_bitwise(tmp_path):
    """Defaults-equivalence: the rollback chain only ADDS .prev files —
    the live checkpoint trajectory is bit-for-bit the keep=1
    (pre-chain) behavior."""
    from fast_autoaugment_tpu.train.trainer import train_and_eval

    tmp = str(tmp_path)
    conf = _conf()
    a, b = f"{tmp}/keep2.msgpack", f"{tmp}/keep1.msgpack"
    train_and_eval(conf, tmp, save_path=a, ckpt_keep=2, **_TRAIN_KW)
    train_and_eval(conf, tmp, save_path=b, ckpt_keep=1, **_TRAIN_KW)
    assert _final_digest(a) == _final_digest(b)
    assert os.path.exists(a + ".prev") and not os.path.exists(b + ".prev")


@pytest.mark.slow
def test_stacked_preemption_and_resume_bit_identical(tmp_path, devices8):
    """Fold-stacked phase 1 under SIGTERM at a dispatch boundary: every
    active fold checkpoints its slice with the shared mid-epoch
    position; the rerun fast-forwards and matches the uninterrupted
    stacked run bit-for-bit per fold."""
    from fast_autoaugment_tpu.core.resilience import PreemptedError
    from fast_autoaugment_tpu.parallel.mesh import make_fold_mesh
    from fast_autoaugment_tpu.train.trainer import train_folds_stacked

    tmp = str(tmp_path)
    conf = _conf()
    mesh = make_fold_mesh(2, devices=jax.devices()[:8])
    kw = dict(cv_ratio=0.4, folds=[0, 1], seed=0, evaluation_interval=1,
              mesh=mesh)
    full_paths = [f"{tmp}/full_f{k}.msgpack" for k in (0, 1)]
    train_folds_stacked(conf, tmp, save_paths=full_paths, **kw)

    part_paths = [f"{tmp}/part_f{k}.msgpack" for k in (0, 1)]
    os.environ["FAA_FAULT"] = "sigterm@step=6"
    faultinject.reset()
    with pytest.raises(PreemptedError):
        train_folds_stacked(conf, tmp, save_paths=part_paths, **kw)
    for p in part_paths:
        meta = read_metadata(p)
        assert meta["preempted"] is True and "in_epoch" in meta

    os.environ.pop("FAA_FAULT")
    faultinject.reset()
    resilience.clear_preemption()
    res = train_folds_stacked(conf, tmp, save_paths=part_paths, **kw)
    assert res[0]["epoch"] == res[1]["epoch"] == 2
    for fp, pp in zip(full_paths, part_paths):
        assert _final_digest(fp) == _final_digest(pp)


# ------------------------------------------ phase-2 trial quarantine

@pytest.mark.slow
def test_search_quarantines_failed_trial(tmp_path):
    """An injected TTA failure at trial 1 must not kill the search: the
    trial is told to TPE as the worst observed reward, the trial log
    carries the failure record, search_result stamps
    quarantined_trials, and the quarantined trial never ranks."""
    from fast_autoaugment_tpu.search.driver import search_policies

    save = str(tmp_path / "search")
    kwargs = dict(
        dataroot=str(tmp_path), save_dir=save, cv_num=1, cv_ratio=0.4,
        num_policy=1, num_op=1, num_search=4, num_top=2)
    os.environ["FAA_FAULT"] = "trial_error@trial=1"
    faultinject.reset()
    result = search_policies(_conf(epoch=1), **kwargs)
    trials = json.load(open(os.path.join(save, "search_trials.json")))
    assert len(trials["0"]) == 4  # the failed trial still spent budget
    q_entries = [t for t in trials["0"] if len(t) >= 3]
    assert len(q_entries) == 1
    assert q_entries[0][2]["quarantined"] is True
    assert "injected trial_error" in q_entries[0][2]["error"]
    # pessimistic reward: the worst observation at quarantine time —
    # trial 0 was the only one told, so its reward is the liar value
    assert q_entries[0][1] == pytest.approx(trials["0"][0][1])
    assert result["quarantined_trials"] == [
        {"fold": 0, "trial": 1,
         "error": q_entries[0][2]["error"]}]
    assert result["num_quarantined_trials"] == 1
    assert result["final_policy_set"]  # the search completed and ranked

    # resume: the quarantined entry is NOT re-evaluated and the stamp
    # survives from the persisted log
    os.environ.pop("FAA_FAULT")
    faultinject.reset()
    result2 = search_policies(_conf(epoch=1), **kwargs)
    assert result2["num_quarantined_trials"] == 1
    trials2 = json.load(open(os.path.join(save, "search_trials.json")))
    assert trials2 == trials


# --------------------------------- resume under fire (SIGKILL, subprocess)

@pytest.mark.slow
def test_sigkill_resume_from_last_dispatch_boundary(tmp_path):
    """The unannounced-death case: a subprocess trainer is SIGKILLed
    mid-epoch (faultinject sigkill@step) while --ckpt-every-dispatch 1
    snapshots every boundary; the rerun resumes from the LAST dispatch
    boundary and the completed checkpoint is bit-identical to an
    uninterrupted run."""
    tmp = str(tmp_path)
    conf_yaml = tmp_path / "conf.yaml"
    conf_yaml.write_text(
        "model:\n  type: wresnet10_1\ndataset: synthetic\naug: default\n"
        "cutout: 0\nbatch: 8\nepoch: 2\nlr: 0.05\n"
        "lr_schedule:\n  type: cosine\n"
        "optimizer:\n  type: sgd\n  decay: 0.0001\n  momentum: 0.9\n"
        "  nesterov: true\n")

    def run(save, fault=None, extra=()):
        env = dict(os.environ)
        env.pop("FAA_FAULT", None)
        if fault:
            env["FAA_FAULT"] = fault
        return subprocess.run(
            [sys.executable, "-m", "fast_autoaugment_tpu.launch.train_cli",
             "-c", str(conf_yaml), "--dataroot", tmp, "--save", save,
             "--cv-ratio", "0.4", "--evaluation-interval", "1",
             *extra],
            env=env, capture_output=True, text=True, timeout=900)

    full = f"{tmp}/full.msgpack"
    r = run(full)
    assert r.returncode == 0, r.stderr[-2000:]

    part = f"{tmp}/part.msgpack"
    r = run(part, fault="sigkill@step=6",
            extra=("--ckpt-every-dispatch", "1"))
    assert r.returncode == -signal.SIGKILL  # died without ceremony
    meta = read_metadata(part)
    assert meta is not None and "in_epoch" in meta
    assert meta["in_epoch"]["epoch"] == 2  # a mid-epoch-2 boundary

    r = run(part, extra=("--ckpt-every-dispatch", "1"))
    assert r.returncode == 0, r.stderr[-2000:]
    assert _final_digest(part) == _final_digest(full)


@pytest.mark.slow
def test_train_cli_maps_preemption_to_exit_77(tmp_path):
    """The exit-code contract end-to-end: a SIGTERMed CLI trainer exits
    exactly 77 after checkpointing (the code fleet.py retries)."""
    tmp = str(tmp_path)
    conf_yaml = tmp_path / "conf.yaml"
    conf_yaml.write_text(
        "model:\n  type: wresnet10_1\ndataset: synthetic\naug: default\n"
        "cutout: 0\nbatch: 8\nepoch: 2\nlr: 0.05\n"
        "lr_schedule:\n  type: cosine\n"
        "optimizer:\n  type: sgd\n  decay: 0.0001\n  momentum: 0.9\n"
        "  nesterov: true\n")
    env = dict(os.environ)
    env["FAA_FAULT"] = "sigterm@step=2"
    r = subprocess.run(
        [sys.executable, "-m", "fast_autoaugment_tpu.launch.train_cli",
         "-c", str(conf_yaml), "--dataroot", tmp, "--save",
         f"{tmp}/ck.msgpack", "--cv-ratio", "0.4",
         "--evaluation-interval", "1"],
        env=env, capture_output=True, text=True, timeout=900)
    assert r.returncode == 77, (r.returncode, r.stderr[-2000:])
    assert read_metadata(f"{tmp}/ck.msgpack")["preempted"] is True
