"""AOT policy-application serving (fast_autoaugment_tpu/serve/).

Covers the tentpole's serving pillar: AOT shape-padding correctness
(padded lanes never leak), bitwise equivalence of served outputs with
direct ``apply_policy`` application, the grouped batch kernel contract,
coalescer ordering/timeout behavior, and the CLI/bench plumbing.  Tiny
8px images keep the augment-kernel compiles in the seconds; the
HTTP round-trip and the bench smoke are ``slow``-marked per the 870s
tier-1 wall budget.
"""

import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fast_autoaugment_tpu.ops.augment import (
    apply_policy,
    apply_policy_batch_grouped,
)
from fast_autoaugment_tpu.serve.policy_server import (
    AotPolicyApplier,
    PolicyServer,
    ServeError,
    pick_shape,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
sys.path.insert(0, os.path.join(_REPO, "tools"))

IMG = 8
SINGLE_SUB = np.array([[[4, 0.8, 0.7], [10, 0.5, 0.3]]], np.float32)
MULTI_SUB = np.array([
    [[4, 0.8, 0.7], [10, 0.5, 0.3]],
    [[0, 0.5, 0.5], [1, 0.5, 0.5]],
    [[8, 0.9, 0.2], [12, 0.4, 0.6]],
], np.float32)


def _images(n, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 256, (n, IMG, IMG, 3)).astype(np.float32)


def _keys(n, base=0):
    return np.stack([np.asarray(jax.random.PRNGKey(base + i), np.uint32)
                     for i in range(n)])


@pytest.fixture(scope="module")
def applier_single():
    """One module-scoped exact single-sub applier (shapes 2 and 4) —
    shared across tests to pay the AOT compile once."""
    return AotPolicyApplier(SINGLE_SUB, image=IMG, shapes=(2, 4),
                            dispatch="auto")


# ------------------------------------------------------- shape picking


def test_pick_shape():
    assert pick_shape((1, 8, 32), 1) == 1
    assert pick_shape((1, 8, 32), 2) == 8
    assert pick_shape((1, 8, 32), 32) == 32
    with pytest.raises(ValueError):
        pick_shape((1, 8), 9)


def test_applier_validates_inputs(applier_single):
    assert applier_single.dispatch == "exact"  # auto: single sub
    with pytest.raises(ValueError):
        applier_single.apply(np.zeros((2, 4, 4, 3), np.float32), _keys(2))
    with pytest.raises(ValueError):
        AotPolicyApplier(np.zeros((3, 2)), image=IMG)
    with pytest.raises(ValueError):
        AotPolicyApplier(SINGLE_SUB, image=IMG, dispatch="nope")


# ------------------------------------------------ bitwise + pad safety


def test_exact_single_sub_bitwise_vs_apply_policy(applier_single):
    """The acceptance contract: served row i == direct
    apply_policy(image_i, policy, key_i), bitwise."""
    imgs, keys = _images(3), _keys(3)
    out = applier_single.apply(imgs, keys)
    ref = np.stack([
        np.asarray(apply_policy(jnp.asarray(imgs[i]),
                                jnp.asarray(SINGLE_SUB),
                                jnp.asarray(keys[i])))
        for i in range(3)])
    assert np.array_equal(out, ref)


def test_padding_never_leaks(applier_single):
    """The same images through two different padded shapes give
    identical results — lane i depends only on (image i, key i)."""
    imgs, keys = _images(2, seed=3), _keys(2, base=9)
    via_2 = applier_single.apply(imgs, keys)              # exact fit
    # force the 4-shape by batching with 1 extra then slicing
    imgs3 = np.concatenate([imgs, _images(1, seed=4)])
    via_4 = applier_single.apply(imgs3, _keys(3, base=9))[:2]
    assert np.array_equal(via_2, via_4)


def test_chunking_over_largest_shape(applier_single):
    """Batches above the largest AOT shape chunk transparently and
    stay bitwise with the per-image reference."""
    imgs, keys = _images(7, seed=5), _keys(7, base=20)
    out = applier_single.apply(imgs, keys)  # 4 + 3 across two dispatches
    ref = np.stack([
        np.asarray(apply_policy(jnp.asarray(imgs[i]),
                                jnp.asarray(SINGLE_SUB),
                                jnp.asarray(keys[i])))
        for i in range(7)])
    assert np.array_equal(out, ref)


@pytest.mark.slow
def test_exact_multi_sub_bitwise():
    """Multi-sub exact dispatch (the select-all lowering — compile-heavy,
    hence slow-marked) is still bitwise per-image apply_policy."""
    ap = AotPolicyApplier(MULTI_SUB, image=IMG, shapes=(4,),
                          dispatch="exact")
    imgs, keys = _images(3), _keys(3)
    out = ap.apply(imgs, keys)
    ref = np.stack([
        np.asarray(apply_policy(jnp.asarray(imgs[i]),
                                jnp.asarray(MULTI_SUB),
                                jnp.asarray(keys[i])))
        for i in range(3)])
    assert np.array_equal(out, ref)


@pytest.mark.slow
def test_grouped_matches_batch_kernel():
    """Grouped dispatch serves exactly what the PR-3 batch kernel
    produces on the padded batch (auto picks grouped for multi-sub)."""
    ap = AotPolicyApplier(MULTI_SUB, image=IMG, shapes=(4,),
                          dispatch="auto", groups=2)
    assert ap.dispatch == "grouped"
    imgs = _images(3)
    key = np.asarray(jax.random.PRNGKey(7), np.uint32)
    out = ap.apply(imgs, key)
    padded = np.concatenate([imgs, np.zeros((1, IMG, IMG, 3), np.float32)])
    ref = np.asarray(apply_policy_batch_grouped(
        jnp.asarray(padded), jnp.asarray(MULTI_SUB), jnp.asarray(key),
        groups=2))[:3]
    assert np.array_equal(out, ref)


@pytest.mark.slow
def test_export_serialize_roundtrip(applier_single):
    """jax.export round-trip: the serialized program reproduces the
    live executable bitwise at the exported padded shape."""
    from fast_autoaugment_tpu.serve.policy_server import deserialize_apply

    blob = applier_single.export_serialized()  # largest shape (4)
    fn = deserialize_apply(blob)
    imgs, keys = _images(4, seed=6), _keys(4, base=40)
    out = np.asarray(fn(imgs, keys))
    assert np.array_equal(out, applier_single.apply(imgs, keys))


# --------------------------------------------------------- coalescing


def test_server_coalesces_and_scatters_fifo(applier_single):
    srv = PolicyServer(applier_single, max_wait_ms=50).start()
    try:
        imgs, keys = _images(4, seed=7), _keys(4, base=50)
        p1 = srv.submit(imgs[:2], keys[:2])
        p2 = srv.submit(imgs[2:3], keys[2:3])
        p3 = srv.submit(imgs[3:4], keys[3:4])
        got = np.concatenate([srv.result(p1), srv.result(p2),
                              srv.result(p3)])
        assert np.array_equal(got, applier_single.apply(imgs, keys))
        st = srv.stats()
        assert st["requests"] == 3 and st["images_served"] == 4
        # 4 images <= max_batch 4: the window coalesced them into FEWER
        # dispatches than requests (usually exactly one)
        assert st["dispatches"] < 3
    finally:
        srv.stop()


def test_server_timeout_flushes_partial_batch(applier_single):
    """A lone request completes after max_wait_ms — the coalescer never
    waits for a batch that is not coming."""
    import time

    srv = PolicyServer(applier_single, max_wait_ms=30).start()
    try:
        t0 = time.perf_counter()
        out = srv.augment(_images(1, seed=8), _keys(1, base=60))
        wall = time.perf_counter() - t0
        assert out.shape == (1, IMG, IMG, 3)
        assert wall < 5.0  # one window + one dispatch, not forever
    finally:
        srv.stop()


def test_server_never_splits_a_request(applier_single):
    """A request that would overflow the batch is carried WHOLE to the
    next dispatch, preserving FIFO and per-request key contiguity."""
    srv = PolicyServer(applier_single, max_batch=4, max_wait_ms=40).start()
    try:
        imgs, keys = _images(6, seed=9), _keys(6, base=70)
        p1 = srv.submit(imgs[:3], keys[:3])   # 3
        p2 = srv.submit(imgs[3:6], keys[3:6])  # 3 -> carried (3+3 > 4)
        r1, r2 = srv.result(p1), srv.result(p2)
        assert np.array_equal(np.concatenate([r1, r2]),
                              applier_single.apply(imgs, keys))
        assert srv.stats()["dispatches"] >= 2
    finally:
        srv.stop()


def test_server_rejects_oversized_and_empty(applier_single):
    srv = PolicyServer(applier_single, max_batch=4)
    with pytest.raises(ValueError):
        srv.submit(_images(5), _keys(5))
    with pytest.raises(ValueError):
        srv.submit(np.zeros((0, IMG, IMG, 3), np.float32))


def test_server_error_propagates_to_caller(applier_single):
    """A failed dispatch surfaces as ServeError on every coalesced
    request instead of wedging the worker."""
    srv = PolicyServer(applier_single, max_wait_ms=10).start()
    try:
        # wrong spatial dims pass submit() but fail in the applier
        bad = srv.submit(np.zeros((1, 4, 4, 3), np.float32))
        with pytest.raises(ServeError):
            srv.result(bad, timeout=30.0)
        # the worker survives: the next request still completes
        assert srv.augment(_images(1, seed=11)).shape == (1, IMG, IMG, 3)
    finally:
        srv.stop()


def test_server_stop_drains_queue(applier_single):
    srv = PolicyServer(applier_single, max_wait_ms=10).start()
    srv.stop()
    p = srv._q  # after stop, a late submit is answered with an error
    assert p.empty()


# ----------------------------------------------------------- serve_cli


def test_build_policy_tensor_from_json_and_archive(tmp_path):
    from fast_autoaugment_tpu.serve.serve_cli import build_policy_tensor

    subs = [[["Rotate", 0.5, 0.4], ["Invert", 0.2, 0.0]],
            [["ShearX", 0.9, 0.1], ["Solarize", 0.3, 0.7]]]
    path = tmp_path / "final_policy.json"
    path.write_text(json.dumps(subs))
    t = build_policy_tensor(str(path))
    assert t.shape == (2, 2, 3) and t.dtype == np.float32
    assert t[0, 0, 0] == 4.0  # Rotate's op index

    t2 = build_policy_tensor("fa_reduced_cifar10")
    assert t2.ndim == 3 and t2.shape[0] > 100  # the shipped archive

    (tmp_path / "empty.json").write_text("[]")
    with pytest.raises(ValueError):
        build_policy_tensor(str(tmp_path / "empty.json"))


def test_serve_cli_parser_defaults():
    from fast_autoaugment_tpu.serve.serve_cli import build_parser

    args = build_parser().parse_args(["--policy", "x.json"])
    assert args.dispatch == "auto" and args.compile_cache == "off"
    assert args.shapes == "1,8,32,128" and args.max_wait_ms == 5.0


def test_seed_keys_are_prngkeys():
    from fast_autoaugment_tpu.serve.serve_cli import _seed_keys

    keys = _seed_keys([0, 1, 2])
    assert keys.shape == (3, 2) and keys.dtype == np.uint32
    assert np.array_equal(keys[1], np.asarray(jax.random.PRNGKey(1),
                                              np.uint32))


@pytest.mark.slow
def test_http_roundtrip(tmp_path):
    """End-to-end over HTTP: POST an npz with seeds, the response is
    bitwise the direct apply_policy application (uint8-clipped)."""
    import http.client
    import io
    import threading
    from http.server import ThreadingHTTPServer

    from fast_autoaugment_tpu.serve.serve_cli import _seed_keys, make_handler

    applier = AotPolicyApplier(SINGLE_SUB, image=IMG, shapes=(4,))
    srv = PolicyServer(applier, max_wait_ms=5).start()
    httpd = ThreadingHTTPServer(("127.0.0.1", 0),
                                make_handler(srv, applier))
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        port = httpd.server_address[1]
        imgs = _images(3, seed=12).astype(np.uint8)
        buf = io.BytesIO()
        np.savez(buf, images=imgs, seeds=np.arange(3))
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
        conn.request("POST", "/augment", body=buf.getvalue())
        resp = conn.getresponse()
        assert resp.status == 200, resp.read()
        got = np.load(io.BytesIO(resp.read()))["images"]
        keys = _seed_keys(np.arange(3))
        ref = np.clip(applier.apply(imgs.astype(np.float32), keys),
                      0, 255).astype(np.uint8)
        assert np.array_equal(got, ref)

        conn.request("GET", "/stats")
        stats = json.loads(conn.getresponse().read())
        assert stats["images_served"] == 3
        assert "compile_cache" in stats and "aot_compile" in stats

        conn.request("GET", "/healthz")
        assert json.loads(conn.getresponse().read())["ok"] is True
    finally:
        httpd.shutdown()
        httpd.server_close()
        srv.stop()


# ---------------------------------------------------------- bench hook


@pytest.mark.slow
def test_bench_serve_smoke(capsys):
    """tools/bench_serve.py end-to-end at a tiny shape: one JSON line
    with the latency/throughput fields, stamps, and a passing bitwise
    re-verification."""
    import bench_serve

    rc = bench_serve.main([
        "--image", str(IMG), "--num-sub", "1", "--shapes", "1,4",
        "--qps", "50", "--seconds", "0.5", "--max-wait-ms", "2"])
    assert rc == 0
    line = [ln for ln in capsys.readouterr().out.splitlines()
            if ln.startswith("{")][-1]
    out = json.loads(line)
    assert out["metric"] == "serve_policy_latency_ms"
    assert out["bitwise_match"] is True
    assert out["latency_ms"]["p50"] > 0 and out["latency_ms"]["p99"] > 0
    assert out["images_per_sec"] > 0
    assert out["qps_offered"] == 50
    for key in ("compile_cache", "contention", "watchdog", "aot_compile"):
        assert key in out, key


def test_bench_serve_synthetic_policy_shape():
    import bench_serve

    pol = bench_serve.synthetic_policy(5, 2)
    assert pol.shape == (5, 2, 3)
    assert (pol[:, :, 0] < 15).all()  # searchable ops only
