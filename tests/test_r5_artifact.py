"""Round-5 flagship-evidence regression over the COMMITTED r5 artifact
(VERDICT r4, next-steps 3+4): the pose300 three-way comparison —
searched vs random-control vs default — at n>=16 paired seeds, with
backend provenance recorded in the artifact itself.

Produced by `tools/run_search_e2e_r5.sh` (resumes the r4 run dir, adds
seeds 17..30 and the 30-seed random arm) and committed; these tests pin
its meaning.  The reference reports bare means only
(`search.py:301-311`) and has no random-control arm at all.
"""

import json
import os

import pytest

ARTIFACT = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                        "search_e2e_r5", "search_result.json")

# ONE loud aggregated skip instead of five quiet per-test ones (ADVICE
# r5): the r5 artifact is still untracked because the staged three-arm
# run has not completed — until it is committed, every pin in this file
# is vacuous and docs/PARITY.md's round-5 "three-way comparison" bullet
# is PENDING EVIDENCE, not a closed claim.  Committing the artifact
# (tools/run_search_e2e_r5.sh, then `git add search_e2e_r5/`) turns
# these back on; they then gate regressions against the committed run.
if not os.path.exists(ARTIFACT):
    pytest.skip(
        "round-5 flagship artifact search_e2e_r5/search_result.json is NOT "
        "COMMITTED (staged run incomplete) — all five r5 evidence pins are "
        "inactive and the docs/PARITY.md round-5 three-way-comparison bullet "
        "is pending; produce and commit it with tools/run_search_e2e_r5.sh",
        allow_module_level=True)


@pytest.fixture(scope="module")
def artifact():
    with open(ARTIFACT) as fh:
        art = json.load(fh)
    # the producer persists after EVERY phase-3 run and declares partial
    # artifacts valid; these pins only apply once the run has reached
    # all three arms at the committed n>=16 seeds — skip (not fail) on
    # an in-flight or interrupted state
    p3 = art.get("phase3", {})
    arms = [m for m in ("default", "augment", "random") if m in p3]
    if len(arms) < 3 or min(
            len(p3[m]["per_seed"]) for m in arms) < 16:
        pytest.skip("r5 artifact still partial (in-flight run)")
    return art


def test_backend_provenance_recorded(artifact):
    """Device-hours without provenance read CPU wall-time as TPU-hours
    (VERDICT r4 weak 5): the artifact must say what measured it."""
    assert artifact["backend"] in ("cpu", "tpu", "axon")
    assert artifact["device_count"] >= 1
    assert artifact["device_hours_total"] == artifact["tpu_hours_total"]
    assert artifact["device_secs_phase2"] == artifact["tpu_secs_phase2"]


def test_three_arms_paired_by_seed(artifact):
    """default, augment AND random must carry per-seed values over the
    same seeds; every pairwise contrast carries a paired t-test."""
    p3 = artifact["phase3"]
    n = min(len(p3[m]["per_seed"]) for m in ("default", "augment", "random"))
    assert n >= 16, f"only {n} balanced seeds"
    for a, b in (("augment", "default"), ("augment", "random"),
                 ("random", "default")):
        paired = p3[f"paired_{a}_minus_{b}"]
        assert paired["n"] >= 16
        assert 0.0 <= paired["p_value"] <= 1.0


def test_random_arm_same_pipeline(artifact):
    """The control arm must have gone through the same selection
    pipeline: equal-size pre-audit draw, same audit floor applied.
    (The r5 run uses the validated default guards, so the audit keys
    must be present; audit-off runs are out of scope for this pin.)"""
    if artifact["guards"]["audit_floor"] is None:
        pytest.skip("audit disabled in this artifact")
    assert artifact["num_sub_policies_selected"] > 0
    assert artifact["num_sub_policies_random_drawn"] == \
        artifact["num_sub_policies_selected"]
    assert artifact["num_sub_policies_random"] == (
        artifact["num_sub_policies_random_drawn"]
        - artifact.get("num_sub_policies_random_dropped", 0))


def test_searched_not_worse_than_random(artifact):
    """The density-matching claim at the committed seeds: the searched
    set's mean must not fall below the random control's (allow 1pt of
    sampling noise — the direction, not just non-inferiority, is
    reported via the paired test above)."""
    p3 = artifact["phase3"]
    n = min(len(p3["augment"]["per_seed"]), len(p3["random"]["per_seed"]))
    aug = p3["augment"]["per_seed"][:n]
    rnd = p3["random"]["per_seed"][:n]
    assert sum(aug) / n >= sum(rnd) / n - 0.01, (
        f"searched {sum(aug) / n:.4f} vs random {sum(rnd) / n:.4f}")


def test_executable_census_recorded(artifact):
    """The artifact records the absolute executable census.  On this
    RESUMED run the trials replay from the log, so the only in-process
    evaluations are the gate baselines ([1, num_op, 3]) — at most one
    executable.  The census's failure mode (a leak raises at run time,
    driver.py) and the fresh-run count of 2 are pinned by
    test_defaults_artifact.py; this pin is consistency only."""
    assert artifact["tta_executables"] == artifact["tta_executables_expected"]
    assert artifact["tta_executables_expected"] <= 2
