"""Test harness configuration.

Forces JAX onto the CPU backend with 8 virtual devices BEFORE jax
initializes, so every test exercises real multi-device semantics
(pjit/shard_map over a Mesh) without TPU hardware.  The reference had
no equivalent (its cluster paths were only testable by running the
cluster, SURVEY.md section 4); this is the TPU-native answer.
"""

import os

# Must run before `import jax` anywhere in the test process.  The outer
# environment pins JAX_PLATFORMS=axon (the single-chip TPU tunnel); tests
# must NOT use it — force the virtual CPU mesh unconditionally.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices8():
    import jax

    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs[:8]


@pytest.fixture()
def rng():
    import jax

    return jax.random.PRNGKey(0)
