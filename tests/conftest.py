"""Test harness configuration.

Forces JAX onto the CPU backend with 8 virtual devices BEFORE jax
initializes, so every test exercises real multi-device semantics
(pjit/shard_map over a Mesh) without TPU hardware.  The reference had
no equivalent (its cluster paths were only testable by running the
cluster, SURVEY.md section 4); this is the TPU-native answer.
"""

import os
import sys

# The ambient sitecustomize loads the axon TPU-tunnel PJRT plugin into
# EVERY interpreter at startup (gated on PALLAS_AXON_POOL_IPS) — before
# this conftest can run.  Even with the factory deregistered below, the
# loaded client library keeps background threads that SIGABRT the whole
# process when the tunnel is dead (observed round 3: 'Fatal Python
# error: Aborted' mid-eval under pytest while the identical clean-env
# run passes).  The only full cure is to never load the plugin: re-exec
# pytest once into a cleaned environment.  This must happen from
# pytest_configure (below), NOT at conftest import: initial conftests
# load inside pytest's fd-level global capture, so an exec here would
# hand the child pytest capture tempfiles as stdout/stderr and the
# whole run's output would vanish into an unlinked file.


def pytest_configure(config):
    if not os.environ.get("PALLAS_AXON_POOL_IPS") or os.environ.get("_FAA_PYTEST_REEXEC"):
        return
    capman = config.pluginmanager.getplugin("capturemanager")
    if capman is not None:  # restore the real stdout/stderr fds pre-exec
        capman.stop_global_capturing()
    env = {k: v for k, v in os.environ.items() if k != "PALLAS_AXON_POOL_IPS"}
    env["_FAA_PYTEST_REEXEC"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
    args = list(config.invocation_params.args)
    os.execvpe(sys.executable, [sys.executable, "-m", "pytest"] + args, env)


# Must run before any backend initializes.  The outer environment pins
# JAX_PLATFORMS=axon (the single-chip TPU tunnel); tests must NOT use
# it — force the virtual CPU mesh unconditionally.  The env vars also
# flow to every subprocess tests spawn; dropping PALLAS_AXON_POOL_IPS
# stops the ambient sitecustomize from registering the TPU plugin in
# those children at all.
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
# Synchronous host feed in tests: the prefetch worker's device_put races
# the consumer's dispatch inside the CPU PJRT client and intermittently
# aborts the process (see data/pipeline.py:prefetch).  Tests that
# exercise the async worker itself override this locally.
os.environ.setdefault("FAA_PREFETCH_SYNC", "1")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

# In THIS process the sitecustomize hook already ran (it fires at
# interpreter start): the TPU plugin is registered and jax has cached
# JAX_PLATFORMS=axon from import time.  Two observed consequences when
# the tunnel is dead (it drops mid-round; see docs/BENCHMARKS.md round-1
# note): backend discovery initializes every registered plugin and hangs
# on the dead one, and the cached platform selection ignores the env
# assignment above.  Undo both in-process: deregister the axon factory
# and override the platform config explicitly.
import jax  # noqa: E402

try:
    from jax._src import xla_bridge as _xb  # noqa: E402 — private, best effort

    _xb._backend_factories.pop("axon", None)
except Exception:  # pragma: no cover — jax internals moved; suite still
    pass  # works whenever the tunnel is alive
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices8():
    import jax

    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs[:8]


@pytest.fixture()
def rng():
    import jax

    return jax.random.PRNGKey(0)
