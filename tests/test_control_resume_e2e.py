"""THE ISSUE-15 controller-crash drill (slow): a live 3-replica routed
fleet, drift injected, the controller SIGKILLed MID-CANARY — then
``control_cli --resume`` reconstructs the dangling episode from the
journal WAL and drives it to a clean journaled promote with no
dangling router split and ZERO dropped requests.

The un-resumed world is pinned as the regression shape: after the
SIGKILL the router's canary split is still armed with nobody scoring
it — the traffic-split-forever failure ``--resume`` exists to end.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
sys.path.insert(0, os.path.join(_REPO, "tools"))

pytestmark = pytest.mark.slow


def _http(host, port, method, path, body=None, headers=None,
          timeout=60.0):
    import http.client

    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request(method, path, body=body, headers=headers or {})
        resp = conn.getresponse()
        return resp.status, dict(resp.getheaders()), resp.read()
    finally:
        conn.close()


def _read_journal(tel_dir):
    import glob

    records = []
    for path in sorted(glob.glob(
            os.path.join(tel_dir, "**", "journal-*.jsonl"),
            recursive=True)):
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict) and "type" in rec:
                    records.append(rec)
    return records


def test_controller_sigkilled_mid_canary_resumes_to_promote(tmp_path):
    from fast_autoaugment_tpu.control.research import policy_file_digest

    tmp = str(tmp_path)
    tel_dir = os.path.join(tmp, "telemetry")
    port_dir = os.path.join(tmp, "replicas")
    cc_dir = os.path.join(tmp, "compile-cache")
    baseline_policy = os.path.join(tmp, "baseline.json")
    candidate_policy = os.path.join(tmp, "candidate.json")
    with open(baseline_policy, "w") as fh:
        json.dump([[["Rotate", 0.5, 0.4], ["Invert", 0.2, 0.0]]], fh)
    with open(candidate_policy, "w") as fh:
        json.dump([[["ShearX", 0.9, 0.1], ["Solarize", 0.3, 0.7]]], fh)
    baseline_digest = policy_file_digest(baseline_policy)
    candidate_digest = policy_file_digest(candidate_policy)

    def _ctl_cmd(extra):
        return [sys.executable, "-m",
                "fast_autoaugment_tpu.launch.control_cli",
                "--telemetry", tel_dir, "--port-dir", port_dir,
                "--router-url", f"http://127.0.0.1:{router_port}",
                "--baseline-policy", baseline_policy,
                "--candidate-policy", candidate_policy,
                "--baseline-samples", "10",
                "--canary-replicas", "1", "--split-every", "2",
                "--quality-margin", "10",
                "--min-arm-dispatches", "1",
                "--reload-timeout", "600"] + extra

    procs = []
    failures = []
    ok_rows = []
    stop = threading.Event()
    try:
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   FAA_COMPILE_CACHE=cc_dir,
                   FAA_FAULT="drift@dispatch=12,shift=60")
        env.pop("FAA_TELEMETRY", None)
        for i in range(3):
            procs.append(subprocess.Popen([
                sys.executable, "-m",
                "fast_autoaugment_tpu.serve.serve_cli",
                "--policy", baseline_policy, "--image", "8",
                "--shapes", "1,8", "--max-wait-ms", "2",
                "--dispatch", "exact",
                "--traffic-stats", "--telemetry", tel_dir,
                "--compile-cache", cc_dir,
                "--port", "0", "--port-dir", port_dir,
                "--host-tag", f"replica{i}",
            ], env=dict(env, FAA_HOST_ID=str(i)), cwd=_REPO))
        from bench_router import wait_port_record, wait_ready

        ports = []
        for i in range(3):
            port = wait_port_record(port_dir, f"replica{i}", procs[i],
                                    600.0)
            wait_ready("127.0.0.1", port, procs[i], 600.0)
            ports.append(port)

        router_pf = os.path.join(tmp, "router.port")
        router_env = dict(env)
        router_env.pop("FAA_FAULT", None)
        router = subprocess.Popen([
            sys.executable, "-m",
            "fast_autoaugment_tpu.serve.router_cli",
            "--port-dir", port_dir, "--port", "0",
            "--port-file", router_pf, "--poll-interval", "0.2",
            "--telemetry", tel_dir,
        ], env=router_env, cwd=_REPO)
        procs.append(router)
        t0 = time.monotonic()
        while not os.path.exists(router_pf) \
                and time.monotonic() - t0 < 120:
            time.sleep(0.1)
        with open(router_pf) as fh:
            router_port = int(fh.read().strip())
        wait_ready("127.0.0.1", router_port, router, 120.0)

        # ---- continuous traffic, across the controller's death ------
        rng = np.random.default_rng(0)
        pool = rng.integers(0, 256, (64, 8, 8, 3),
                            dtype=np.uint8).astype(np.float32)

        def _traffic():
            import io

            i = 0
            while not stop.is_set():
                batch = pool[(4 * i) % 48:(4 * i) % 48 + 4]
                buf = io.BytesIO()
                np.savez(buf, images=batch)
                try:
                    status, _h, _b = _http(
                        "127.0.0.1", router_port, "POST", "/augment",
                        body=buf.getvalue(), timeout=120.0)
                except OSError as e:
                    failures.append(f"transport: {e}")
                    continue
                if status == 200:
                    ok_rows.append(time.time())
                else:
                    failures.append(f"status {status}")
                i += 1

        client = threading.Thread(target=_traffic, daemon=True)
        client.start()

        # ---- controller #1: a WIDE gate window so the kill lands ----
        ctl_env = dict(env)
        ctl_env.pop("FAA_FAULT", None)
        ctl = subprocess.Popen(
            _ctl_cmd(["--gate-polls", "40", "--gate-timeout-polls",
                      "200", "--poll-interval", "0.5"]),
            env=ctl_env, cwd=_REPO)
        procs.append(ctl)

        # wait for the canary split to be ARMED on the live router
        deadline = time.monotonic() + 600
        armed = None
        while time.monotonic() < deadline and armed is None:
            assert ctl.poll() is None, "controller died before canary"
            _s, _h, body = _http("127.0.0.1", router_port, "GET",
                                 "/stats")
            armed = (json.loads(body) or {}).get("canary")
            time.sleep(0.2)
        assert armed is not None, "canary split never armed"
        assert armed["digest"] == candidate_digest

        # ---- SIGKILL mid-canary ------------------------------------
        ctl.kill()
        ctl.wait(timeout=30)
        time.sleep(1.0)

        # THE pre-fix regression shape, pinned: the dead controller
        # left the router splitting traffic with NOBODY scoring the
        # canary arm — and nothing in the system will ever clear it
        _s, _h, body = _http("127.0.0.1", router_port, "GET", "/stats")
        dangling = (json.loads(body) or {}).get("canary")
        assert dangling is not None, \
            "expected a DANGLING canary split after the controller kill"
        assert dangling["digest"] == candidate_digest

        # ---- controller #2: --resume -------------------------------
        stats_file = os.path.join(tmp, "resume_stats.json")
        ctl2 = subprocess.Popen(
            _ctl_cmd(["--gate-polls", "2", "--poll-interval", "0.3",
                      "--resume", "--stats-file", stats_file]),
            env=ctl_env, cwd=_REPO)
        procs.append(ctl2)

        deadline = time.monotonic() + 600
        promote = None
        while time.monotonic() < deadline and promote is None:
            assert ctl2.poll() is None, "resumed controller died"
            evs = _read_journal(tel_dir)
            promote = next((r for r in evs if r["type"] == "promote"),
                           None)
            time.sleep(0.5)
        assert promote is not None, "the resumed loop never promoted"
        time.sleep(2.0)
        stop.set()
        client.join(timeout=120)
        ctl2.send_signal(15)
        ctl2.wait(timeout=60)

        # no dangling split: the resumed episode TERMINATED
        _s, _h, body = _http("127.0.0.1", router_port, "GET", "/stats")
        assert (json.loads(body) or {}).get("canary") is None

        # fleet-wide on the promoted candidate
        for i, port in enumerate(ports):
            _s, _h, body = _http("127.0.0.1", port, "GET", "/stats")
            st = json.loads(body)
            assert st["policy_digest"] == candidate_digest, f"replica{i}"
    finally:
        stop.set()
        for proc in reversed(procs):
            if proc.poll() is None:
                try:
                    proc.send_signal(15)
                except ProcessLookupError:
                    pass
        deadline = time.monotonic() + 60
        for proc in procs:
            left = max(1.0, deadline - time.monotonic())
            try:
                proc.wait(timeout=left)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=10)

    # ---- ZERO dropped requests through kill + resume + promote ------
    assert not failures, failures[:10]
    assert len(ok_rows) > 20

    # ---- the WAL story: canary ... resume(canary) ... promote -------
    evs = _read_journal(tel_dir)
    resumes = [r for r in evs if r["type"] == "mark"
               and r.get("event") == "resume"]
    assert resumes and resumes[0]["stage"] == "canary"
    assert resumes[0]["digest"] == candidate_digest
    promote = next(r for r in evs if r["type"] == "promote")
    assert promote["digest"] == candidate_digest
    assert promote["digest"] != baseline_digest
    # one drift episode end to end: detected pre-crash, promoted
    # post-resume by a DIFFERENT process
    drift = next(r for r in evs if r["type"] == "drift")
    assert promote["drift_id"] == drift["id"]
    assert promote["pid"] != drift["pid"]
    stats = json.load(open(stats_file))
    assert stats["promotes"] == 1 and stats["rollbacks"] == 0
    assert stats["state"] == "watching"
