import numpy as np
import pytest

from fast_autoaugment_tpu.ops.augment import OP_NAMES, SEARCH_OP_NAMES
from fast_autoaugment_tpu.policies import archive as P


def test_archive_counts_match_reference():
    # reference archive.py:281-293
    assert len(P.fa_reduced_cifar10()) == 493
    assert len(P.fa_resnet50_rimagenet()) == 498
    assert len(P.fa_reduced_svhn()) == 497
    assert len(P.autoaug_paper_cifar10()) == 25
    assert len(P.autoaug_policy()) == 95
    assert len(P.arsaug_policy()) == 35


def test_all_ops_known_and_levels_in_range():
    for name in ("fa_reduced_cifar10", "fa_resnet50_rimagenet", "fa_reduced_svhn"):
        for sub in P.load_policy(name):
            assert len(sub) == 2
            for op, prob, level in sub:
                assert op in OP_NAMES
                assert 0.0 <= prob <= 1.0
                assert 0.0 <= level <= 1.0


def test_tensor_roundtrip():
    pol = P.fa_reduced_cifar10()[:10]
    t = P.policy_to_tensor(pol)
    assert t.shape == (10, 2, 3) and t.dtype == np.float32
    back = P.tensor_to_policy(t)
    for sub, subb in zip(pol, back):
        for (n1, p1, l1), (n2, p2, l2) in zip(sub, subb):
            assert n1 == n2
            assert p1 == pytest.approx(p2, abs=1e-6)
            assert l1 == pytest.approx(l2, abs=1e-6)


def test_policy_decoder_matches_reference_semantics():
    augment = {}
    for i in range(2):
        for j in range(2):
            augment[f"policy_{i}_{j}"] = (i * 2 + j) % len(SEARCH_OP_NAMES)
            augment[f"prob_{i}_{j}"] = 0.25 * (i + 1)
            augment[f"level_{i}_{j}"] = 0.1 * (j + 1)
    pol = P.policy_decoder(augment, 2, 2)
    assert pol == [
        [("ShearX", 0.25, 0.1), ("ShearY", 0.25, 0.2)],
        [("TranslateX", 0.5, 0.1), ("TranslateY", 0.5, 0.2)],
    ]


def test_remove_duplicates_keys_on_names_only():
    pol = [
        [("ShearX", 0.1, 0.1), ("Rotate", 0.2, 0.2)],
        [("ShearX", 0.9, 0.9), ("Rotate", 0.8, 0.8)],  # same names -> dropped
        [("Rotate", 0.1, 0.1), ("ShearX", 0.2, 0.2)],  # different order -> kept
    ]
    out = P.remove_duplicates(pol)
    assert len(out) == 2
    assert out[0][0][1] == 0.1  # first occurrence wins


def test_unknown_archive_raises():
    with pytest.raises(KeyError):
        P.load_policy("nope")
