"""TensorBoard event-file writer (utils/tb_events.py): CRC-verified
round-trip through the in-tree reader, plus known crc32c vectors so the
framing matches TensorFlow's TFRecord format exactly (no tensorboard
install exists here to cross-check against — the CRC vectors and the
proto layout ARE the compatibility contract)."""

import os

from fast_autoaugment_tpu.utils.logging import ScalarWriter, TeeWriter, make_writers
from fast_autoaugment_tpu.utils.tb_events import TBEventWriter, crc32c, read_events


def test_crc32c_known_vectors():
    # RFC 3720 / kernel test vectors for CRC-32C (Castagnoli)
    assert crc32c(b"") == 0x00000000
    assert crc32c(b"123456789") == 0xE3069283
    assert crc32c(b"a") == 0xC1D04330
    assert crc32c(bytes(32)) == 0x8A9136AA


def test_event_file_round_trip(tmp_path):
    w = TBEventWriter(str(tmp_path), "train")
    w.add_scalar("loss", 1.5, step=1)
    w.add_scalar("top1", 0.25, step=2)
    w.close()

    events = read_events(w.path)  # CRC-verified parse
    assert events[0]["file_version"] == "brain.Event:2"
    scalars = [(e["tag"], round(e["value"], 6), e.get("step"))
               for e in events[1:]]
    assert scalars == [("loss", 1.5, 1), ("top1", 0.25, 2)]
    assert all(e["wall_time"] > 0 for e in events)


def test_make_writers_tb_opt_in(tmp_path):
    train, valid, test = make_writers(str(tmp_path), "run", True, tb=True)
    assert isinstance(train, TeeWriter)
    train.add_scalar("loss", 2.0, step=1)
    train.flush()
    # JSONL sidecar still written
    assert os.path.exists(os.path.join(tmp_path, "run_train.jsonl"))
    # and a tfevents file per split under tb/
    tb_dir = os.path.join(tmp_path, "tb", "run_train")
    files = os.listdir(tb_dir)
    assert len(files) == 1 and files[0].startswith("events.out.tfevents.")
    events = read_events(os.path.join(tb_dir, files[0]))
    assert events[1]["tag"] == "loss" and events[1]["value"] == 2.0
    for w in (train, valid, test):
        w.close()

    # default stays JSONL-only (no tb/ churn in search sidecar flows)
    w2 = make_writers(str(tmp_path / "plain"), "run", True)[0]
    assert isinstance(w2, ScalarWriter)
    w2.close()


def test_two_writers_same_second_get_distinct_files(tmp_path):
    """Same logdir/name within one second must not interleave two
    streams in one file (ADVICE r4): exclusive create + numbered retry."""
    w1 = TBEventWriter(str(tmp_path), "train")
    w2 = TBEventWriter(str(tmp_path), "train")
    try:
        assert w1.path != w2.path
        w1.add_scalar("a", 1.0, 0)
        w2.add_scalar("a", 2.0, 0)
    finally:
        w1.close()
        w2.close()
    # each file parses standalone with exactly one file_version record
    for p in (w1.path, w2.path):
        events = read_events(p)
        assert sum("file_version" in e for e in events) == 1


def test_reader_crc_mismatch_raises_value_error(tmp_path):
    """CRC failures must raise ValueError, not assert (python -O strips
    asserts, silently voiding verify_crc=True) — ADVICE r4."""
    import pytest

    w = TBEventWriter(str(tmp_path), "train")
    w.add_scalar("loss", 1.5, step=1)
    w.close()
    data = bytearray(open(w.path, "rb").read())
    data[12] ^= 0xFF  # first payload byte of the file_version record
    with open(w.path, "wb") as fh:
        fh.write(bytes(data))
    with pytest.raises(ValueError, match="crc mismatch"):
        read_events(w.path)
    # opting out of verification still parses the frames
    assert read_events(w.path, verify_crc=False)
