"""The telemetry-driven autoscaler (serve/autoscaler.py): the pure
watermark/hysteresis/cooldown state machine on synthetic metrics, the
Prometheus scrape path, the journaled control loop, and the local
replica-fleet actuator — fast and host-only."""

from __future__ import annotations

import glob
import itertools
import json
import os
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from fast_autoaugment_tpu.serve.autoscaler import (
    Autoscaler,
    AutoscalerPolicy,
    LocalReplicaFleet,
    ReplicaScraper,
    parse_prometheus_text,
)

_NAME_SEQ = itertools.count()

OVER = {"queue_depth": 50.0, "shed_rate": 10.0, "breaker_open": False}
IDLE = {"queue_depth": 0.0, "shed_rate": 0.0, "breaker_open": False}
MID = {"queue_depth": 4.0, "shed_rate": 0.2, "breaker_open": False}


def _policy(**kw) -> AutoscalerPolicy:
    kw.setdefault("high_queue", 8.0)
    kw.setdefault("low_queue", 1.0)
    kw.setdefault("high_shed_rate", 1.0)
    kw.setdefault("low_shed_rate", 0.0)
    return AutoscalerPolicy(**kw)


# ---------------------------------------------- the pure state machine


def test_policy_watermark_classification():
    p = _policy(up_polls=1, down_polls=1, cooldown_s=0.0)
    assert p.decide(OVER, 1, 0.0)[0] == "up"
    p2 = _policy(up_polls=1, down_polls=1, cooldown_s=0.0)
    assert p2.decide(IDLE, 2, 0.0)[0] == "down"
    # the dead band between watermarks: nothing happens, ever
    p3 = _policy(up_polls=1, down_polls=1, cooldown_s=0.0)
    for i in range(10):
        assert p3.decide(MID, 2, float(i)) == (None, "nominal")


def test_policy_breaker_open_is_overload():
    p = _policy(up_polls=1, cooldown_s=0.0)
    sig = {"queue_depth": 0.0, "shed_rate": 0.0, "breaker_open": True}
    action, reason = p.decide(sig, 1, 0.0)
    assert action == "up" and "breaker_open=True" in reason


def test_policy_hysteresis_needs_consecutive_breaches():
    p = _policy(up_polls=3, cooldown_s=0.0)
    assert p.decide(OVER, 1, 0.0)[0] is None
    assert p.decide(OVER, 1, 1.0)[0] is None
    # a nominal poll RESETS the streak — one blip never scales
    assert p.decide(MID, 1, 2.0)[0] is None
    assert p.decide(OVER, 1, 3.0)[0] is None
    assert p.decide(OVER, 1, 4.0)[0] is None
    assert p.decide(OVER, 1, 5.0)[0] == "up"


def test_policy_cooldown_blocks_consecutive_actions():
    p = _policy(up_polls=1, cooldown_s=10.0, max_replicas=8)
    assert p.decide(OVER, 1, 100.0)[0] == "up"
    # still overloaded, but cooling down: hold
    assert p.decide(OVER, 2, 101.0)[0] is None
    assert p.decide(OVER, 2, 109.9)[0] is None
    assert p.decide(OVER, 2, 110.1)[0] == "up"


def test_policy_cooldown_applies_across_directions():
    p = _policy(up_polls=1, down_polls=1, cooldown_s=10.0)
    assert p.decide(OVER, 1, 0.0)[0] == "up"
    # load vanished instantly: the cooldown still holds the shrink
    assert p.decide(IDLE, 2, 1.0)[0] is None
    assert p.decide(IDLE, 2, 11.0)[0] == "down"


def test_policy_respects_fleet_bounds():
    p = _policy(up_polls=1, down_polls=1, cooldown_s=0.0,
                min_replicas=1, max_replicas=2)
    assert p.decide(OVER, 2, 0.0)[0] is None  # at max: hold
    assert p.decide(IDLE, 1, 1.0)[0] is None  # at min: hold
    assert p.decide(OVER, 1, 2.0)[0] == "up"
    assert p.decide(IDLE, 2, 3.0)[0] == "down"


def test_policy_invalid_configs_raise():
    with pytest.raises(ValueError):
        _policy(high_queue=1.0, low_queue=2.0)
    with pytest.raises(ValueError):
        _policy(high_shed_rate=0.0, low_shed_rate=1.0)
    with pytest.raises(ValueError):
        _policy(min_replicas=5, max_replicas=2)


def test_policy_full_drill_up_then_cooldown_then_down():
    """The acceptance shape on synthetic metrics: overload -> scale_up
    after up_polls, cooldown holds, load drains -> scale_down after
    down_polls once the cooldown passes."""
    p = _policy(up_polls=2, down_polls=3, cooldown_s=5.0,
                min_replicas=1, max_replicas=3)
    t = 0.0
    actions = []
    timeline = [OVER] * 4 + [IDLE] * 12
    n = 1
    for sig in timeline:
        a, _r = p.decide(sig, n, t)
        if a == "up":
            n += 1
        elif a == "down":
            n -= 1
        actions.append(a)
        t += 1.0
    assert actions.count("up") == 1 and actions.count("down") == 1
    assert actions.index("up") == 1          # after 2 overloaded polls
    down_at = actions.index("down")
    assert down_at >= 6                      # cooldown + 3 idle polls
    assert n == 1                            # back at the floor


# ------------------------------------------------------- scrape path


def test_parse_prometheus_roundtrip():
    from fast_autoaugment_tpu.core import telemetry

    reg = telemetry.MetricsRegistry()
    reg.gauge("faa_serve_queue_depth", "q", server="3").set(7.0)
    reg.counter("faa_serve_robustness_total", "r",
                counter="shed_overload", server="3").inc(11)
    reg.gauge("faa_breaker_open", "b", breaker="serve3").set(1.0)
    reg.histogram("faa_dispatch_seconds", "h", label="x").observe(0.1)
    fams = parse_prometheus_text(reg.prometheus_text())
    assert fams["faa_serve_queue_depth"] == [({"server": "3"}, 7.0)]
    labels, v = fams["faa_serve_robustness_total"][0]
    assert labels == {"counter": "shed_overload", "server": "3"}
    assert v == 11.0
    assert fams["faa_breaker_open"][0][1] == 1.0
    assert "faa_dispatch_seconds_bucket" in fams  # histograms expand


class StubMetricsReplica:
    """A /metrics endpoint whose queue/shed/breaker numbers the test
    steers directly."""

    def __init__(self):
        self.queue_depth = 0.0
        self.shed_total = 0.0
        self.breaker_open = 0.0
        self._lock = threading.Lock()
        stub = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def do_GET(self):
                with stub._lock:
                    body = (
                        "# TYPE faa_serve_queue_depth gauge\n"
                        f'faa_serve_queue_depth{{server="0"}} '
                        f"{stub.queue_depth:g}\n"
                        "# TYPE faa_serve_robustness_total counter\n"
                        f'faa_serve_robustness_total{{counter='
                        f'"shed_overload",server="0"}} '
                        f"{stub.shed_total:g}\n"
                        "# TYPE faa_breaker_open gauge\n"
                        f'faa_breaker_open{{breaker="serve0"}} '
                        f"{stub.breaker_open:g}\n").encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.httpd.daemon_threads = True
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()
        self.port = self.httpd.server_address[1]

    def set(self, queue=None, shed=None, breaker=None):
        with self._lock:
            if queue is not None:
                self.queue_depth = float(queue)
            if shed is not None:
                self.shed_total = float(shed)
            if breaker is not None:
                self.breaker_open = float(breaker)

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def _port_dir_with(tmp_path, stubs) -> str:
    d = tmp_path / "replicas"
    d.mkdir(exist_ok=True)
    for i, s in enumerate(stubs):
        (d / f"replica{i}.json").write_text(json.dumps(
            {"tag": f"replica{i}", "host": "127.0.0.1", "port": s.port}))
    return str(d)


def test_scraper_aggregates_and_derives_rates(tmp_path):
    stubs = [StubMetricsReplica(), StubMetricsReplica()]
    try:
        port_dir = _port_dir_with(tmp_path, stubs)
        sc = ReplicaScraper(port_dir)
        stubs[0].set(queue=3, shed=10)
        stubs[1].set(queue=9, shed=0, breaker=1)
        sig = sc.scrape()
        assert sig["reachable"] == 2
        assert sig["queue_depth"] == 9.0     # max across replicas
        assert sig["shed_rate"] == 0.0       # first scrape: no baseline
        assert sig["breaker_open"] is True
        time.sleep(0.05)
        stubs[0].set(shed=20)  # ~10 sheds over the interval
        sig = sc.scrape()
        assert sig["shed_rate"] > 0.0
        assert sig["replicas"]["replica1"]["shed_rate"] == 0.0
    finally:
        for s in stubs:
            s.close()


def test_scraper_unreachable_replica_counts_out(tmp_path):
    stub = StubMetricsReplica()
    port_dir = _port_dir_with(tmp_path, [stub])
    stub.close()
    sig = ReplicaScraper(port_dir).scrape()
    assert sig["reachable"] == 0
    assert sig["replicas"]["replica0"]["reachable"] is False
    assert sig["queue_depth"] == 0.0 and sig["breaker_open"] is False


# --------------------------------------------- the journaled loop


def test_autoscaler_journals_up_then_down(tmp_path):
    """The acceptance drill's control half on a steered signal: an
    overload drives a journaled scale_up (metric evidence inline), the
    cooldown holds, the drained fleet drives a journaled scale_down —
    and the registry counters agree."""
    from fast_autoaugment_tpu.core import telemetry as T

    T.enable_telemetry(str(tmp_path / "tel"), tb_bridge=False)
    try:
        signal_box = {"sig": dict(OVER)}
        fleet = {"n": 1}

        def scrape():
            return dict(signal_box["sig"])

        def up():
            fleet["n"] += 1
            return f"replica{fleet['n'] - 1}"

        def down():
            fleet["n"] -= 1
            return f"replica{fleet['n']}"

        policy = _policy(up_polls=2, down_polls=2, cooldown_s=0.2,
                         min_replicas=1, max_replicas=3)
        scaler = Autoscaler(scrape, up, down, lambda: fleet["n"], policy,
                            name=f"as{next(_NAME_SEQ)}")
        assert scaler.step() is None   # hysteresis: first breach holds
        assert scaler.step() == "up"
        assert fleet["n"] == 2
        assert scaler.step() is None   # cooldown
        signal_box["sig"] = dict(IDLE)
        deadline = time.monotonic() + 10.0
        action = None
        while time.monotonic() < deadline:
            action = scaler.step()
            if action == "down":
                break
            time.sleep(0.05)
        assert action == "down" and fleet["n"] == 1
        st = scaler.stats()
        assert st["scale_ups"] == 1 and st["scale_downs"] == 1
        T.journal_flush()
        recs = []
        for path in glob.glob(str(tmp_path / "tel" / "journal-*.jsonl")):
            with open(path) as fh:
                recs += [json.loads(ln) for ln in fh if ln.strip()]
        ups = [x for x in recs if x["type"] == "scale_up"]
        downs = [x for x in recs if x["type"] == "scale_down"]
        assert len(ups) == 1 and len(downs) == 1
        # the metric evidence rides INLINE in the decision event
        assert ups[0]["queue_depth"] == OVER["queue_depth"]
        assert ups[0]["shed_rate"] == OVER["shed_rate"]
        assert ups[0]["replicas_before"] == 1
        assert ups[0]["replicas_after"] == 2
        assert ups[0]["replica"] == "replica1"
        assert downs[0]["replicas_after"] == 1
    finally:
        T._disable_for_tests()


def test_autoscaler_loop_thread_lifecycle():
    policy = _policy(up_polls=1, cooldown_s=0.0, max_replicas=2)
    fleet = {"n": 1}
    scaler = Autoscaler(lambda: dict(OVER),
                        lambda: fleet.__setitem__("n", fleet["n"] + 1),
                        lambda: fleet.__setitem__("n", fleet["n"] - 1),
                        lambda: fleet["n"], policy,
                        poll_interval_s=0.02,
                        name=f"as{next(_NAME_SEQ)}")
    scaler.start()
    try:
        deadline = time.monotonic() + 5.0
        while fleet["n"] < 2 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert fleet["n"] == 2  # scaled up, then held at max
    finally:
        scaler.stop()


# ------------------------------------------------- the fleet actuator


_FAKE_REPLICA = (
    "import signal, sys, time\n"
    "signal.signal(signal.SIGTERM, lambda *a: sys.exit(0))\n"
    "time.sleep(120)\n")


def test_local_replica_fleet_spawn_and_drain(tmp_path):
    """scale_up launches a tagged replica process with the port-dir
    args appended; scale_down SIGTERMs the NEWEST (LIFO) and reaps
    it."""
    fleet = LocalReplicaFleet(
        [sys.executable, "-c", _FAKE_REPLICA], str(tmp_path / "replicas"))
    try:
        assert fleet.count() == 0
        t0 = fleet.scale_up()
        t1 = fleet.scale_up()
        assert (t0, t1) == ("replica0", "replica1")
        assert fleet.count() == 2
        assert fleet.scale_down(drain_timeout=15.0) == "replica1"
        assert fleet.count() == 1
        assert fleet.scale_down(drain_timeout=15.0) == "replica0"
        assert fleet.count() == 0
        assert fleet.scale_down() is None
    finally:
        fleet.stop_all()


def test_local_replica_fleet_reaps_dead(tmp_path):
    fleet = LocalReplicaFleet(
        [sys.executable, "-c", "pass"], str(tmp_path / "replicas"))
    try:
        fleet.scale_up()
        deadline = time.monotonic() + 10.0
        while fleet.count() > 0 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert fleet.count() == 0  # exited process reaped from census
    finally:
        fleet.stop_all()


def test_local_replica_fleet_exports_identity(tmp_path):
    """Replicas get FAA_HOST_ID + the port-dir/tag args — the fleet
    supervision idiom (attempt-gated faults stay addressable)."""
    script = (
        "import json, os, sys\n"
        "print(json.dumps({'host_id': os.environ.get('FAA_HOST_ID'),"
        " 'attempt': os.environ.get('FAA_ATTEMPT'),"
        " 'argv': sys.argv[1:]}))\n")
    out_path = tmp_path / "out.json"
    wrapper = (f"import subprocess, sys\n"
               f"r = subprocess.run([sys.executable, '-c', "
               f"{script!r}] + sys.argv[1:], capture_output=True, "
               f"text=True)\n"
               f"open({str(out_path)!r}, 'w').write(r.stdout)\n")
    fleet = LocalReplicaFleet([sys.executable, "-c", wrapper],
                              str(tmp_path / "replicas"))
    fleet.scale_up()
    deadline = time.monotonic() + 15.0
    while not out_path.exists() and time.monotonic() < deadline:
        time.sleep(0.05)
    time.sleep(0.2)
    rec = json.loads(out_path.read_text())
    assert rec["host_id"] == "0" and rec["attempt"] == "1"
    assert "--port-dir" in rec["argv"] and "--host-tag" in rec["argv"]
    assert rec["argv"][rec["argv"].index("--host-tag") + 1] == "replica0"
    fleet.stop_all()


# ----------------------------------------------------------- the CLI


def test_autoscaler_cli_parser():
    from fast_autoaugment_tpu.serve.autoscaler import build_parser

    args = build_parser().parse_args(
        ["--port-dir", "/tmp/x", "--max-replicas", "5", "--",
         "python", "-m", "x"])
    assert args.max_replicas == 5 and args.min_replicas == 1
    assert args.high_queue == 8.0 and args.cooldown == 10.0
    assert args.up_polls == 2 and args.down_polls == 5
    assert args.replica_cmd == ["--", "python", "-m", "x"]


def test_autoscaler_cli_bounded_run(tmp_path):
    """The CLI end to end with a fake replica command: floors the
    fleet at min-replicas, runs for --scale-seconds, drains, and
    prints its stats JSON."""
    import subprocess

    out = subprocess.run(
        [sys.executable, "-m", "fast_autoaugment_tpu.serve.autoscaler",
         "--port-dir", str(tmp_path / "replicas"),
         "--min-replicas", "1", "--max-replicas", "2",
         "--poll-interval", "0.1", "--scale-seconds", "1.0", "--",
         sys.executable, "-c", _FAKE_REPLICA],
        capture_output=True, text=True, timeout=120,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert out.returncode == 0, out.stderr[-2000:]
    line = [ln for ln in out.stdout.splitlines()
            if ln.startswith("{")][-1]
    stats = json.loads(line)
    assert stats["replicas"] == 0  # drained on exit
    assert stats["policy"]["min_replicas"] == 1
