"""Crash-resumable control loop (ISSUE 15): the journal-as-WAL
reconstruction (``control/resume.py``), idempotent stage re-entry
(``ControlLoop.resume``), and the per-poll canary split RE-ASSERT with
echo verification — all fast, host-only, on stub transports.

The live SIGKILL-mid-canary drill (real replicas + router +
``control_cli --resume``) is tests/test_control_resume_e2e.py (slow).
"""

from __future__ import annotations

import glob
import json
import os

import pytest

from fast_autoaugment_tpu.core import telemetry as T
from fast_autoaugment_tpu.control import (
    CanaryController,
    ControlLoop,
    DriftMonitor,
    PromotionGate,
    load_provenance,
    policy_file_digest,
    read_control_events,
    reconstruct_inflight_episode,
    write_provenance,
)
from fast_autoaugment_tpu.utils import faultinject


@pytest.fixture(autouse=True)
def _clean_env(monkeypatch):
    monkeypatch.delenv("FAA_TELEMETRY", raising=False)
    monkeypatch.delenv("FAA_FAULT", raising=False)
    faultinject.reset()
    T.registry()._reset_for_tests()
    yield
    T._disable_for_tests()
    faultinject.reset()


@pytest.fixture()
def journal_dir(tmp_path):
    d = str(tmp_path / "tel")
    T.enable_telemetry(d, tb_bridge=False)
    yield d
    T._disable_for_tests()


def _journal_records(directory):
    T.journal_flush()
    records = []
    for path in sorted(glob.glob(
            os.path.join(directory, "journal-*.jsonl"))):
        with open(path) as fh:
            records.extend(json.loads(ln) for ln in fh if ln.strip())
    records.sort(key=lambda r: r["seq"])
    return records


# ------------------------------------------- WAL reconstruction (pure)


def _ev(etype, seq, **fields):
    return {"type": etype, "host": "h0", "pid": 1, "seq": seq, **fields}


def test_clean_wal_reconstructs_nothing():
    events = [
        _ev("drift", 1, id="drift-1", metric="input_mean"),
        _ev("research", 2, candidate="/c.json", digest="abc"),
        _ev("canary", 3, action="rollout", replica="replica1"),
        _ev("promote", 4, digest="abc"),
    ]
    assert reconstruct_inflight_episode(events) is None
    # rollback and the terminal marks close an episode too
    for closer in (_ev("rollback", 4),
                   _ev("mark", 4, event="research_failed"),
                   _ev("mark", 4, event="candidate_is_baseline")):
        assert reconstruct_inflight_episode(events[:1] + [closer]) is None


def test_dangling_research_stage_reconstructs():
    events = [_ev("drift", 1, id="drift-1", metric="input_mean",
                  stat=12.0)]
    ep = reconstruct_inflight_episode(events)
    assert ep is not None
    assert ep["stage"] == "research"
    assert ep["verdict"]["id"] == "drift-1"
    assert ep["verdict"]["stat"] == 12.0
    # journal envelope keys are stripped from the verdict
    assert "seq" not in ep["verdict"] and "host" not in ep["verdict"]


def test_dangling_canary_stage_reconstructs_with_candidate():
    events = [
        _ev("drift", 1, id="drift-1"),
        _ev("research", 2, candidate="/cand/final_policy.json",
            digest="abc123def456"),
        _ev("canary", 3, action="rollout", replica="replica1"),
    ]
    ep = reconstruct_inflight_episode(events)
    assert ep["stage"] == "canary"
    assert ep["candidate"] == "/cand/final_policy.json"
    assert ep["digest"] == "abc123def456"


def test_only_the_last_episode_dangles():
    events = [
        _ev("drift", 1, id="drift-1"),
        _ev("research", 2, candidate="/c1.json", digest="d1"),
        _ev("promote", 3, digest="d1"),
        _ev("drift", 4, id="drift-2"),
        _ev("research", 5, candidate="/c2.json", digest="d2"),
    ]
    ep = reconstruct_inflight_episode(events)
    assert ep["verdict"]["id"] == "drift-2" and ep["digest"] == "d2"


def test_read_control_events_from_journal_with_torn_tail(tmp_path):
    tel = str(tmp_path / "tel")
    os.makedirs(tel)
    path = os.path.join(tel, "journal-0.jsonl")
    with open(path, "w") as fh:
        fh.write(json.dumps(_ev("drift", 1, id="drift-1")) + "\n")
        fh.write(json.dumps(_ev("dispatch", 2)) + "\n")  # not control
        fh.write(json.dumps(_ev("research", 3, candidate="/c",
                                digest="d")) + "\n")
        fh.write('{"type": "promote", "seq": 4, "trunc')  # torn tail
    events = read_control_events(tel)
    assert [e["type"] for e in events] == ["drift", "research"]
    ep = reconstruct_inflight_episode(events)
    assert ep["stage"] == "canary"  # the torn promote never happened


# ------------------------------------------------ loop resume (stubs)


class _StubRouter:
    """The router's /canary admin as a stateful stub: records every
    admin call and echoes the armed split like the real handler."""

    def __init__(self):
        self.split: dict | None = None
        self.calls: list[dict] = []
        self.echo_override: dict | None = None

    def __call__(self, payload: dict):
        self.calls.append(dict(payload))
        if self.echo_override is not None:
            return self.echo_override
        if payload.get("clear"):
            self.split = None
            return {"canary": None}
        self.split = {"digest": payload["digest"],
                      "tags": list(payload["replicas"]),
                      "every": payload.get("every", 2)}
        return {"canary": dict(self.split)}


def _mk_loop(tmp_path, journal_dir):
    policy = [[["Rotate", 0.5, 0.4], ["Invert", 0.2, 0.0]]]
    base = str(tmp_path / "baseline.json")
    cand = str(tmp_path / "candidate.json")
    with open(base, "w") as fh:
        json.dump(policy, fh)
    with open(cand, "w") as fh:
        json.dump([[["ShearX", 0.9, 0.1], ["Solarize", 0.3, 0.7]]], fh)
    write_provenance(cand, {"kind": "test_candidate"})
    reloads = []

    def reload_fn(host, port, policy_path):
        reloads.append((host, policy_path))
        return {"digest": policy_file_digest(policy_path)}

    replicas = [{"tag": f"replica{i}", "host": "h", "port": 9000 + i}
                for i in range(3)]
    ctl = CanaryController(lambda: list(replicas), reload_fn=reload_fn,
                           router_url="http://stub")
    router = _StubRouter()
    ctl._router_canary = router

    class _Scraper:
        def sample(self, reps):
            return {str(r["tag"]): {
                "reachable": True, "reward_proxy": 0.1,
                "new_dispatches": 5, "new_breaker_fires": 0,
                "dispatches": 5, "breaker_fires": 0} for r in reps}

    monitor = DriftMonitor(lambda: [], baseline_n=5)
    loop = ControlLoop(
        monitor, lambda verdict: {"policy": cand,
                                  "provenance": load_provenance(cand)},
        ctl, PromotionGate(gate_polls=2, quality_margin=10.0),
        _Scraper(), baseline_policy=base,
        baseline_digest=policy_file_digest(base), n_canary=1,
        split_every=2)
    return loop, router, reloads, cand, policy_file_digest(cand)


def test_resume_canary_stage_terminates_in_promote(tmp_path,
                                                   journal_dir):
    """The resumed-controller shape: a fresh loop adopts a dangling
    canary-stage episode, idempotently re-runs the rollout (digest
    re-verify + split re-arm) and drives it to a journaled promote."""
    loop, router, reloads, cand, cand_digest = _mk_loop(tmp_path,
                                                        journal_dir)
    episode = {"verdict": {"id": "drift-9", "metric": "input_mean"},
               "stage": "canary", "candidate": cand,
               "digest": cand_digest, "provenance": {}}
    assert loop.resume(episode) == "canary"
    assert loop.step() == "canary"     # adoption
    assert loop.step() == "observing"  # idempotent rollout + split
    assert router.split["digest"] == cand_digest
    assert loop.step() == "observing"  # gate 1/2 (split re-asserted)
    assert loop.step() == "watching"   # gate 2/2 -> promote
    assert router.split is None        # promote cleared the split
    evs = _journal_records(journal_dir)
    marks = [r for r in evs if r["type"] == "mark"
             and r.get("event") == "resume"]
    assert marks and marks[0]["stage"] == "canary"
    promotes = [r for r in evs if r["type"] == "promote"]
    assert promotes and promotes[0]["digest"] == cand_digest
    assert promotes[0]["drift_id"] == "drift-9"
    assert loop.baseline_digest == cand_digest


def test_resume_research_stage_reenters_research(tmp_path, journal_dir):
    loop, router, reloads, cand, cand_digest = _mk_loop(tmp_path,
                                                        journal_dir)
    episode = {"verdict": {"id": "drift-7"}, "stage": "research",
               "candidate": None, "digest": None}
    assert loop.resume(episode) == "research"
    assert loop.step() == "research"  # adoption
    assert loop.step() == "canary"    # re-search re-ran
    assert loop.step() == "observing"


def test_router_restart_mid_canary_is_reasserted_every_poll(
        tmp_path, journal_dir):
    """THE satellite pin: a restarted router (split lost, 100% baseline
    routing) is re-armed by the next gate poll's idempotent POST
    /canary — the gate never scores a phantom canary arm for more than
    one poll."""
    loop, router, reloads, cand, cand_digest = _mk_loop(tmp_path,
                                                        journal_dir)
    episode = {"verdict": {"id": "drift-1"}, "stage": "canary",
               "candidate": cand, "digest": cand_digest}
    loop.resume(episode)
    loop.step()                        # adopt
    assert loop.step() == "observing"  # rollout, split armed
    router.split = None                # <-- the router restarts
    assert loop.step() == "observing"  # next poll...
    assert router.split is not None    # ...re-armed the split
    assert router.split["digest"] == cand_digest
    # every observe poll carried a split (re-)assert admin call
    sets = [c for c in router.calls if c.get("digest") == cand_digest]
    assert len(sets) >= 2


def test_split_echo_mismatch_rolls_back(tmp_path, journal_dir):
    """A router echoing a DIFFERENT armed digest (another controller
    owns the split) must roll back, not fight over traffic."""
    loop, router, reloads, cand, cand_digest = _mk_loop(tmp_path,
                                                        journal_dir)
    episode = {"verdict": {"id": "drift-2"}, "stage": "canary",
               "candidate": cand, "digest": cand_digest}
    loop.resume(episode)
    loop.step()                        # adopt
    assert loop.step() == "observing"  # rollout ok
    router.echo_override = {"canary": {"digest": "someone-else"}}
    assert loop.step() == "watching"   # re-assert mismatch -> rollback
    evs = _journal_records(journal_dir)
    assert any(r["type"] == "rollback" for r in evs)
    assert loop.stats()["rollbacks"] == 1


def test_control_cli_resume_flag_parses():
    from fast_autoaugment_tpu.launch.control_cli import build_parser

    args = build_parser().parse_args(
        ["--telemetry", "/t", "--port-dir", "/p",
         "--baseline-policy", "/b.json", "--candidate-policy",
         "/c.json", "--resume"])
    assert args.resume is True
    args = build_parser().parse_args(
        ["--telemetry", "/t", "--port-dir", "/p",
         "--baseline-policy", "/b.json", "--candidate-policy",
         "/c.json"])
    assert args.resume is False
