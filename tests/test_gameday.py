"""Trace-driven game days (fast_autoaugment_tpu/gameday/).

Fast half, host-only (no plane, no jax): the offered schedule is a
pure function of ``(scenario, seed)`` — byte-identical across builds,
digest-stamped; request bodies are deterministic; ``scaled`` shrinks
load while scaling the dispatch floor inversely so overload scenarios
still overload; and EVERY verdict predicate is exercised against
synthetic evidence dicts, including the expected-fail semantics that
keep the broken-config demonstration honest.

Slow half: one smoke-scaled suite over a real plane — a healthy
scenario must PASS and the deliberately broken no-shedding flash
crowd must FAIL (and the suite must be GREEN precisely because it
failed on cue).
"""

import dataclasses
import glob
import json
import os
import sys

import numpy as np
import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
sys.path.insert(0, os.path.join(_REPO, "tools"))

from fast_autoaugment_tpu.core import telemetry as T
from fast_autoaugment_tpu.gameday import (
    SCENARIOS,
    Traffic,
    build_schedule,
    schedule_digest,
    scaled,
    suite_names,
)
from fast_autoaugment_tpu.gameday.verdict import (
    PREDICATES,
    evaluate,
    render_table,
)
from fast_autoaugment_tpu.gameday.workload import (
    SHED_STATUSES,
    request_body,
)
from fast_autoaugment_tpu.serve import wire


@pytest.fixture(autouse=True)
def _quiet_telemetry():
    yield
    T._disable_for_tests()


# ------------------------------------------------- schedule identity


def test_schedule_is_deterministic_for_every_scenario():
    for name, scn in SCENARIOS.items():
        a = build_schedule(scn.traffic, scn.seed)
        b = build_schedule(scn.traffic, scn.seed)
        assert a == b, name
        assert schedule_digest(a) == schedule_digest(b), name
        assert len(schedule_digest(a)) == 16
        assert len(a) > 0, name


def test_schedule_digest_tracks_seed_and_traffic():
    scn = SCENARIOS["flash-crowd-10x"]
    base = schedule_digest(build_schedule(scn.traffic, scn.seed))
    other_seed = schedule_digest(
        build_schedule(scn.traffic, scn.seed + 1))
    other_shape = schedule_digest(build_schedule(
        dataclasses.replace(scn.traffic, base_rps=scn.traffic.base_rps
                            + 1.0), scn.seed))
    assert base != other_seed
    assert base != other_shape


def test_request_bodies_are_deterministic_and_decodable():
    scn = SCENARIOS["flash-crowd-10x"]
    sched = build_schedule(scn.traffic, scn.seed)
    by_lane = {}
    for o in sched:
        by_lane.setdefault(o.lane, o)
    assert set(by_lane) == {"raw", "npz", "shm"}
    for lane, o in by_lane.items():
        b1, h1, imgs1 = request_body(o, image=8)
        b2, h2, imgs2 = request_body(o, image=8)
        assert b1 == b2
        if lane == "shm":
            # shm bodies are built later from a process-unique region;
            # the deterministic part is the tensor + the PRNG keys
            np.testing.assert_array_equal(imgs1, imgs2)
            keys = h1["_keys"]
            assert keys.shape == (o.batch, 2)
            assert (keys[:, 0] == 0).all()  # PRNGKey(seed) layout
        else:
            assert imgs1 is None and h1 == h2
    imgs, keys = wire.decode_raw(request_body(by_lane["raw"], 8)[0])
    assert imgs.shape == (by_lane["raw"].batch, 8, 8, 3)
    assert keys.shape == (by_lane["raw"].batch, 2)


def test_rate_curves():
    flash = Traffic(kind="flash", duration_s=10.0, base_rps=5.0,
                    peak_rps=50.0, flash_at_frac=0.5, ramp_s=2.0)
    assert flash.rate_at(-1.0) == 0.0 and flash.rate_at(10.0) == 0.0
    assert flash.rate_at(1.0) == 5.0
    assert flash.rate_at(6.0) == pytest.approx(27.5)  # mid-ramp
    assert flash.rate_at(9.0) == 50.0
    assert flash.peak_rate == 50.0
    di = Traffic(kind="diurnal", duration_s=20.0, base_rps=4.0,
                 peak_rps=12.0, period_s=10.0)
    assert di.rate_at(0.0) == pytest.approx(4.0)
    assert di.rate_at(5.0) == pytest.approx(12.0)
    assert Traffic(kind="constant", base_rps=7.0).rate_at(1.0) == 7.0
    with pytest.raises(ValueError, match="unknown traffic kind"):
        Traffic(kind="lunar").rate_at(1.0)


# ------------------------------------------------- registry + scaling


def test_registry_has_teeth_and_resolvable_predicates():
    names = suite_names()
    assert len(names) >= 6 and names == list(SCENARIOS)
    expects = [SCENARIOS[n].expect for n in names]
    assert expects.count("pass") >= 5
    assert expects.count("fail") >= 1  # the standing teeth-proof
    for name in names:
        for pred, params in SCENARIOS[name].predicates:
            assert pred in PREDICATES, f"{name}: {pred}"
            assert isinstance(params, dict)


def test_scaled_shrinks_load_and_scales_dispatch_floor():
    scn = SCENARIOS["flash-crowd-10x"]
    sm = scaled(scn, 0.4)
    assert sm.traffic.duration_s == pytest.approx(
        scn.traffic.duration_s * 0.4)
    assert sm.traffic.peak_rps == pytest.approx(
        scn.traffic.peak_rps * 0.4)
    # capacity shrinks WITH the offered load (floor grows by 1/factor)
    # so the overload the scenario drills still materializes in smoke
    assert sm.plane.dispatch_floor_ms == pytest.approx(
        scn.plane.dispatch_floor_ms / 0.4)
    assert sm.predicates == scn.predicates
    no_floor = scaled(SCENARIOS["replica-loss-mid-canary"], 0.4)
    assert no_floor.plane.dispatch_floor_ms == 0.0


# ------------------------------------------------- verdict predicates


def _report(**kw) -> dict:
    base = {"offered": 100, "completed": 100, "ok": 90, "shed": 10,
            "unexpected_status": 0, "transport_errors": 0,
            "cancelled": 0, "ok_by_tenant": {"0": 90},
            "shed_by_status": {"503": 10}, "p99_ms_ok": 50.0,
            "shm_created": 5, "shm_leftover": [],
            "errors_sample": []}
    base.update(kw)
    return base


def _ev(journal=(), **kw) -> dict:
    ev = {"report": _report(), "journal": list(journal),
          "router_stats": None, "killed": None, "tenants": 1}
    ev.update(kw)
    return ev


def _rec(etype, t, **fields):
    return {"type": etype, "t_wall": t, **fields}


def test_goodput_floor():
    assert PREDICATES["goodput_floor"](_ev(), floor=0.9).ok
    assert not PREDICATES["goodput_floor"](_ev(), floor=0.95).ok
    row = PREDICATES["goodput_floor"](
        {"report": _report(offered=0, ok=0)}, floor=0.1)
    assert not row.ok  # nothing offered can never satisfy a floor


def test_shed_not_hang():
    assert PREDICATES["shed_not_hang"](_ev()).ok
    hung = _ev(report=_report(transport_errors=3))
    assert not PREDICATES["shed_not_hang"](hung).ok
    assert PREDICATES["shed_not_hang"](hung, max_hung=3).ok
    weird = _ev(report=_report(unexpected_status=1))
    assert not PREDICATES["shed_not_hang"](weird).ok
    slow = _ev(report=_report(p99_ms_ok=900.0))
    assert not PREDICATES["shed_not_hang"](slow, p99_ms_ok=500.0).ok


def test_max_transport_errors():
    assert PREDICATES["max_transport_errors"](_ev(), max_errors=0).ok
    bad = _ev(report=_report(transport_errors=2,
                             errors_sample=["timed out"]))
    row = PREDICATES["max_transport_errors"](bad, max_errors=1)
    assert not row.ok and row.observed["errors_sample"] == ["timed out"]


def test_affinity_floor():
    stats = {"affinity": {"hits": 80, "misses": 20, "hit_rate": 0.8}}
    assert PREDICATES["affinity_floor"](
        _ev(router_stats=stats), floor=0.75).ok
    assert not PREDICATES["affinity_floor"](
        _ev(router_stats=stats), floor=0.9).ok
    # no router stats at all is a FAIL, not a vacuous pass
    assert not PREDICATES["affinity_floor"](_ev(), floor=0.1).ok


def test_autoscaler_bounds():
    j = [_rec("scale_up", 1.0, replicas_after=2),
         _rec("scale_up", 2.0, replicas_after=3),
         _rec("scale_down", 9.0, replicas_after=2)]
    row = PREDICATES["autoscaler_bounds"](
        _ev(journal=j), min_replicas=1, max_replicas=3,
        require_scale_up=True)
    assert row.ok and row.observed["scale_ups"] == 2
    out = j + [_rec("scale_up", 3.0, replicas_after=7)]
    assert not PREDICATES["autoscaler_bounds"](
        _ev(journal=out), min_replicas=1, max_replicas=3).ok
    # a flash scenario that never scaled up fails its requirement
    assert not PREDICATES["autoscaler_bounds"](
        _ev(journal=[]), min_replicas=1, max_replicas=3,
        require_scale_up=True).ok


def test_control_decision_requires_causal_order():
    good = [_rec("drift", 1.0), _rec("canary", 2.0, action="rollout"),
            _rec("promote", 8.0)]
    assert PREDICATES["control_decision"](_ev(journal=good)).ok
    rollback = [_rec("drift", 1.0),
                _rec("canary", 2.0, action="rollout"),
                _rec("rollback", 8.0)]
    row = PREDICATES["control_decision"](_ev(journal=rollback))
    assert row.ok and row.observed["decision"] == "rollback"
    shuffled = [_rec("canary", 1.0, action="rollout"),
                _rec("drift", 2.0), _rec("promote", 8.0)]
    assert not PREDICATES["control_decision"](
        _ev(journal=shuffled)).ok
    no_terminal = good[:2]
    assert not PREDICATES["control_decision"](
        _ev(journal=no_terminal)).ok
    assert PREDICATES["control_decision"](
        _ev(journal=no_terminal), require_terminal=False).ok


def test_rotation_ejected_falls_back_to_killed_tag():
    j = [_rec("rotation", 1.0, action="eject", replica="replica2")]
    assert PREDICATES["rotation_ejected"](
        _ev(journal=j, killed="replica2")).ok
    assert not PREDICATES["rotation_ejected"](
        _ev(journal=j, killed="replica0")).ok
    assert PREDICATES["rotation_ejected"](
        _ev(journal=j), tag="replica2").ok
    assert not PREDICATES["rotation_ejected"](_ev()).ok


def test_tenant_churn_and_cohort_service():
    j = [_rec("tenant", t, action="admit") for t in (1.0, 2.0, 3.0)] \
        + [_rec("tenant", 4.0, action="evict")]
    assert PREDICATES["tenant_churn"](
        _ev(journal=j), min_admits=3, min_evicts=1).ok
    assert not PREDICATES["tenant_churn"](
        _ev(journal=j), min_admits=4, min_evicts=1).ok
    served = _ev(report=_report(ok_by_tenant={"0": 5, "1": 2, "2": 1,
                                              "3": 9}), tenants=4)
    assert PREDICATES["all_cohorts_served"](served).ok
    starved = _ev(report=_report(ok_by_tenant={"0": 5, "1": 2}),
                  tenants=4)
    row = PREDICATES["all_cohorts_served"](starved)
    assert not row.ok and row.observed["starved"] == [2, 3]


def test_fsfault_observed_and_no_shm_leak():
    j = [_rec("fsfault", 1.0, kind="lag"), _rec("fsfault", 2.0,
                                                kind="eio")]
    assert PREDICATES["fsfault_observed"](_ev(journal=j)).ok
    # surviving faults that never fired proves nothing
    assert not PREDICATES["fsfault_observed"](_ev()).ok
    assert PREDICATES["no_shm_leak"](_ev()).ok
    leak = _ev(report=_report(shm_leftover=["psm_dead"]))
    assert not PREDICATES["no_shm_leak"](leak).ok


# ------------------------------------------------- evaluate + table


def test_evaluate_expected_fail_inverts_suite_greenness():
    scn = SCENARIOS["flash-crowd-10x-noshed"]
    assert scn.expect == "fail"
    hung = _ev(report=_report(transport_errors=40, ok=30))
    rec = evaluate(scn, hung, schedule_digest="cafe")
    assert rec["pass"] is False
    assert rec["ok_as_expected"] is True  # failed ON CUE => green
    healthy = evaluate(scn, _ev(), schedule_digest="cafe")
    assert healthy["pass"] is True
    assert healthy["ok_as_expected"] is False  # teeth-proof missed


def test_evaluate_contains_unknown_and_crashing_predicates():
    scn = dataclasses.replace(
        SCENARIOS["stale-fs-under-load"],
        predicates=(("no_such_predicate", {}),
                    ("goodput_floor", {"floor": 0.1})))
    rec = evaluate(scn, _ev())
    rows = {r["predicate"]: r for r in rec["predicates"]}
    assert rows["no_such_predicate"]["ok"] is False
    assert rows["goodput_floor"]["ok"] is True
    assert rec["pass"] is False
    # a predicate crash (evidence missing a key) is a failing row,
    # not an exception out of the engine
    crash = evaluate(dataclasses.replace(
        scn, predicates=(("goodput_floor", {"floor": 0.1}),)),
        {"journal": []})
    assert crash["pass"] is False
    assert crash["predicates"][0]["detail"] == "predicate crashed"


def test_render_table_marks_expectations():
    scn = SCENARIOS["flash-crowd-10x-noshed"]
    ok_rec = evaluate(SCENARIOS["stale-fs-under-load"], _ev(journal=[
        _rec("fsfault", 1.0, kind="lag")]))
    fail_rec = evaluate(
        scn, _ev(report=_report(transport_errors=40, ok=30)))
    table = render_table([ok_rec, fail_rec])
    assert "FAIL (expected-fail)" in table
    assert "suite:" in table
    missed = evaluate(scn, _ev())  # broken config passed => RED
    assert "RED" in render_table([ok_rec, missed])
    assert "(!! expected FAIL)" in render_table([missed])


def test_scenario_and_verdict_are_journaled_event_types(tmp_path):
    d = str(tmp_path / "tel")
    T.enable_telemetry(d, tb_bridge=False)
    T.emit("scenario", "gameday", scenario="x", action="start", seed=1)
    T.emit("verdict", "gameday", scenario="x",
           predicate="goodput_floor", ok=True)
    T.journal_flush()
    records = []
    for path in sorted(glob.glob(os.path.join(d, "journal-*.jsonl"))):
        with open(path) as fh:
            records.extend(json.loads(ln) for ln in fh if ln.strip())
    types = {r["type"] for r in records}
    assert {"scenario", "verdict"} <= types


def test_faa_status_gameday_section(tmp_path):
    d = str(tmp_path / "tel")
    T.enable_telemetry(d, tb_bridge=False)
    T.emit("scenario", "flash-crowd-10x", action="start", seed=20,
           schedule_digest="ab12", requests=100, traffic="flash",
           expect="pass")
    T.emit("scenario", "flash-crowd-10x", action="phase",
           phase="traffic")
    T.emit("scenario", "flash-crowd-10x", action="progress",
           offered=40, completed=30, ok=24)
    T.emit("scenario", "flash-crowd-10x", action="kill",
           replica="replica1", victim_pid=4242)
    T.emit("verdict", "stale-fs-under-load",
           predicate="goodput_floor", ok=True,
           observed={"goodput": 0.97}, bound={"floor": 0.8})
    T.emit("scenario", "stale-fs-under-load", action="end",
           passed=True, expect="pass", ok_as_expected=True,
           schedule_digest="cd34", elapsed_s=31.0)
    T.journal_flush()
    from faa_status import fleet_status, gameday_status, render_table

    status = fleet_status(d)
    gd = status["gameday"]
    act = gd["active"]
    assert act["scenario"] == "flash-crowd-10x"
    assert act["phase"] == "traffic"
    assert act["offered"] == 40 and act["ok"] == 24
    assert act["served_frac"] == pytest.approx(0.6)
    assert gd["finished"][0]["scenario"] == "stale-fs-under-load"
    assert gd["finished"][0]["ok_as_expected"] is True
    assert gd["kills"][0]["replica"] == "replica1"
    assert gd["kills"][0]["victim_pid"] == 4242
    assert gd["verdict_total"] == 1
    table = render_table(status)
    assert "game day:" in table
    assert "ACTIVE flash-crowd-10x" in table
    assert "offered=40 served=24" in table
    assert "stale-fs-under-load :: goodput_floor: ok" in table
    # an empty journal has no game-day section at all
    assert gameday_status([]) is None


def test_shed_statuses_are_the_structured_rejections():
    assert SHED_STATUSES == {400, 408, 413, 429, 503}
    assert 500 not in SHED_STATUSES  # a 500 is a plane bug, not a shed
    assert 502 not in SHED_STATUSES  # transport-only failure


# ------------------------------------------------- live plane (slow)


@pytest.mark.slow
def test_smoke_suite_passes_healthy_and_fails_broken(tmp_path):
    """One smoke-scaled mini-suite over a REAL plane: the healthy
    stale-fs scenario must pass, the no-shedding flash crowd must fail
    — and the suite is GREEN precisely because it failed on cue."""
    from fast_autoaugment_tpu.gameday.runner import run_suite

    root = str(tmp_path / "gd")
    result = run_suite(["stale-fs-under-load", "flash-crowd-10x-noshed"],
                       smoke=True, keep=True, root=root)
    by_name = {r["scenario"]: r for r in result["records"]}
    healthy = by_name["stale-fs-under-load"]
    broken = by_name["flash-crowd-10x-noshed"]
    assert healthy.get("error") is None
    assert healthy["pass"] is True, healthy
    assert healthy["ok_as_expected"] is True
    assert healthy["schedule_digest"]
    assert broken["pass"] is False, broken
    assert broken["ok_as_expected"] is True  # failed ON CUE
    assert result["suite_green"] is True
    assert "GREEN" in result["table"]
    # the run journaled its own scenario lifecycle + verdicts
    journal = []
    pattern = os.path.join(root, "stale-fs-under-load", "telemetry",
                           "**", "journal-*.jsonl")
    for path in glob.glob(pattern, recursive=True):
        with open(path) as fh:
            journal.extend(json.loads(ln) for ln in fh if ln.strip())
    types = {r["type"] for r in journal}
    assert {"scenario", "verdict", "fsfault"} <= types
    # nothing the workload created is left in /dev/shm
    assert healthy["report"]["shm_leftover"] == []
    assert broken["report"]["shm_leftover"] == []
