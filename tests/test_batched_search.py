"""Batched ask-tell TPE + vmapped multi-candidate TTA (trial-parallel
phase 2): K=1 bit-for-bit equivalence with the sequential scheduler,
K>1 posterior sanity vs random search, exact numerical parity of the
candidate-axis vmap, the executable census across K, and the batched
driver loop end-to-end."""

import json
import os
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fast_autoaugment_tpu.search.tpe import TPE, choice, uniform

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))


# ---------------------------------------------------------------- TPE

def test_ask_one_is_suggest_bit_for_bit():
    """ask(1)/tell_batch must consume the same RNG stream and produce
    the same proposals as suggest/tell — the property that makes
    --trial-batch 1 reproduce the sequential search bit-for-bit."""
    space = [uniform("x", 0, 1), uniform("y", 0, 1), choice("c", 4)]

    def objective(s):
        return -((s["x"] - 0.7) ** 2) + (0.5 if s["c"] == 2 else 0.0)

    a, b = TPE(space, seed=3), TPE(space, seed=3)
    for _ in range(40):  # spans the startup -> posterior transition
        sa = a.suggest()
        [sb] = b.ask(1)
        assert sa == sb
        a.tell(sa, objective(sa))
        b.tell_batch([sb], [objective(sb)])
    assert a.observations == b.observations


def test_ask_batch_leaves_observations_intact():
    """The constant-liar lies must never leak into the real history —
    even when a proposal raises mid-batch."""
    space = [uniform("x"), choice("c", 3)]
    t = TPE(space, seed=0, n_startup=2)
    for _ in range(4):
        ps = t.ask(3)
        t.tell_batch(ps, [p["x"] for p in ps])
    assert len(t.observations) == 12
    assert all(isinstance(r, float) for _, r in t.observations)
    n_before = len(t.observations)
    t.ask(5)  # lies applied and discarded
    assert len(t.observations) == n_before
    with pytest.raises(ValueError, match="tell_batch"):
        t.tell_batch([{"x": 0.1, "c": 0}], [0.5, 0.6])


def test_batched_tpe_beats_random_on_policy_space():
    """Posterior sanity at K>1: constant-liar batches on the REAL 30-D
    policy space (planted-policy reward, the tools/bench_tpe.py
    methodology) must beat paired random search about as often as the
    sequential TPE does.  Measured at this cell (60 trials, sigma=0.02,
    20 seeds): sequential 16/20, K=4 16/20, K=16 16/20 with equal or
    better mean gain — so the gates are wins >= 15/20 and gain > 0.02,
    plus non-inferiority to the sequential optimizer on the same seeds.
    (The issue's nominal ">= 17/20" traced to an 18/20 claim that the
    committed benchmark table itself revised to 14-16/20,
    docs/tpe_benchmark.md; fully deterministic given the seeds.)"""
    import bench_tpe

    from fast_autoaugment_tpu.search.driver import make_search_space

    trials, noise, runs = 60, 0.02, 20

    def run_batched(seed, k):
        rng = np.random.default_rng((seed, 1))
        target = bench_tpe.plant_target(np.random.default_rng((seed, 2)))
        observed_fn, true_fn = bench_tpe.make_reward(target, noise, rng)
        opt = TPE(make_search_space(bench_tpe.NUM_POLICY, bench_tpe.NUM_OP),
                  seed=seed, n_startup=bench_tpe.driver_n_startup(trials))
        best_obs, best_true, done = -np.inf, 0.0, 0
        while done < trials:
            ps = opt.ask(min(k, trials - done))
            rs = [observed_fn(p) for p in ps]
            opt.tell_batch(ps, rs)
            for p, r in zip(ps, rs):
                if r > best_obs:
                    best_obs, best_true = r, true_fn(p)
            done += len(ps)
        return best_true

    rand = np.array([bench_tpe.run_strategy("random", trials, s, noise)[-1]
                     for s in range(runs)])
    seq = np.array([bench_tpe.run_strategy("tpe", trials, s, noise)[-1]
                    for s in range(runs)])
    seq_wins = int((seq > rand).sum())
    for k in (4, 16):
        batched = np.array([run_batched(s, k) for s in range(runs)])
        wins = int((batched > rand).sum())
        gain = float(batched.mean() - rand.mean())
        assert wins >= 15, (k, wins, gain)
        assert wins >= seq_wins - 2, (k, wins, seq_wins)
        assert gain > 0.02, (k, wins, gain)


# ------------------------------------------------------- vmapped TTA

def _probe_model():
    from flax import linen as nn

    class Probe(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            x = nn.Conv(4, (3, 3))(x)
            x = nn.relu(x).mean(axis=(1, 2))
            return nn.Dense(10)(x)

    return Probe()


def _policy_scaled_augment(images, policy, key):
    # policy-dependent + key-dependent, cheap to compile: brightness
    # scale from the first (prob, level) row plus per-draw noise
    scale = 0.5 + policy[0, 0, 1] * policy[0, 0, 2]
    noise = jax.random.uniform(key, images.shape, jnp.float32, -0.05, 0.05)
    return images.astype(jnp.float32) / 255.0 * scale + noise


def test_tta_batched_matches_single_exact():
    """K candidates through the num_candidates=K step must equal the
    same K (policy, key) pairs through the single-candidate step
    EXACTLY — the candidate axis is a pure vmap, and per-candidate keys
    are identical by construction (eval_tta_batched docstring)."""
    from fast_autoaugment_tpu.search.tta import (
        eval_tta,
        eval_tta_batched,
        make_tta_step,
    )

    model = _probe_model()
    rng = np.random.default_rng(0)
    batch_a = {
        "x": jnp.asarray(rng.integers(0, 256, (6, 8, 8, 3), dtype=np.uint8)),
        "y": jnp.asarray(rng.integers(0, 10, (6,), np.int32)),
        "m": jnp.asarray(np.array([1, 1, 1, 1, 1, 0], np.float32)),
    }
    batch_b = {
        "x": jnp.asarray(rng.integers(0, 256, (6, 8, 8, 3), dtype=np.uint8)),
        "y": jnp.asarray(rng.integers(0, 10, (6,), np.int32)),
        "m": jnp.asarray(np.ones(6, np.float32)),
    }
    variables = model.init(jax.random.PRNGKey(1), batch_a["x"].astype(jnp.float32))
    params, batch_stats = variables["params"], {}

    k = 3
    policies = jnp.asarray(
        rng.uniform(0, 1, (k, 2, 2, 3)).astype(np.float32))
    keys = jnp.stack([jax.random.PRNGKey(50 + i) for i in range(k)])

    single = make_tta_step(model, num_policy=3, cutout_length=0,
                           augment_fn=_policy_scaled_augment)
    batched = make_tta_step(model, num_policy=3, cutout_length=0,
                            augment_fn=_policy_scaled_augment,
                            num_candidates=k)
    got = eval_tta_batched(batched, params, batch_stats,
                           [batch_a, batch_b], policies, keys)
    for i in range(k):
        want = eval_tta(single, params, batch_stats, [batch_a, batch_b],
                        policies[i], keys[i])
        for field in ("minus_loss", "top1_valid", "top1_mean", "cnt"):
            assert got[i][field] == want[field], (i, field, got[i], want)


def test_tta_batched_census_one_executable_across_rounds():
    """One fixed candidate-axis size K -> ONE executable no matter how
    many different policy batches flow through (the zero-recompile
    invariant extended to --trial-batch)."""
    from fast_autoaugment_tpu.search.census import executable_census
    from fast_autoaugment_tpu.search.tta import make_tta_step

    model = _probe_model()
    rng = np.random.default_rng(2)
    images = rng.integers(0, 256, (4, 8, 8, 3), dtype=np.uint8)
    labels = rng.integers(0, 10, (4,), np.int32)
    mask = np.ones(4, np.float32)
    variables = model.init(jax.random.PRNGKey(1),
                           jnp.asarray(images, jnp.float32))
    step = make_tta_step(model, num_policy=2, cutout_length=0,
                         augment_fn=_policy_scaled_augment, num_candidates=4)
    for round_i in range(3):
        policies = jnp.asarray(
            rng.uniform(0, 1, (4, 2, 2, 3)).astype(np.float32))
        keys = jnp.stack([jax.random.PRNGKey(round_i * 10 + i)
                          for i in range(4)])
        step(variables["params"], {}, images, labels, mask, policies, keys)
    assert executable_census(step) == 1
    # the trace-event fallback agrees with the cache probe
    assert step._faa_trace_count() == 1


# ------------------------------------------------------------ census

def test_executable_census_fallbacks(monkeypatch):
    from fast_autoaugment_tpu.search import census

    warnings = []
    monkeypatch.setattr(census.logger, "warning",
                        lambda *a, **k: warnings.append(a))

    class CacheOnly:
        def _cache_size(self):
            return 2

    assert census.executable_census(CacheOnly()) == 2
    assert not warnings

    class TraceOnly:
        def _faa_trace_count(self):
            return 3

    assert census.executable_census(TraceOnly()) == 3
    assert len(warnings) == 1  # loud: private probe gone

    class Neither:
        pass

    assert census.executable_census(Neither()) is None
    assert len(warnings) == 2  # loud: census unavailable, never silent


# ---------------------------------------------------- driver / CLI

def _tiny_conf():
    from fast_autoaugment_tpu.core.config import Config

    return Config({
        "model": {"type": "wresnet10_1"},
        "dataset": "synthetic",
        "aug": "default",
        "cutout": 8,
        "batch": 8,
        "epoch": 1,
        "lr": 0.05,
        "lr_schedule": {"type": "cosine"},
        "optimizer": {"type": "sgd", "decay": 1e-4, "clip": 5.0,
                      "momentum": 0.9, "nesterov": True},
    })


def test_search_trial_batch_e2e(tmp_path):
    """Batched phase 2 end-to-end: num_search=5 at --trial-batch 2 runs
    3 rounds (2+2+1-padded), persists all 5 trials, keeps the batched
    executable census at one compile, and resumes at batch
    granularity."""
    from fast_autoaugment_tpu.search.driver import search_policies

    save = str(tmp_path / "search")
    kwargs = dict(
        dataroot=str(tmp_path), save_dir=save, cv_num=1, cv_ratio=0.4,
        num_policy=1, num_op=1, num_search=5, num_top=2, trial_batch=2,
    )
    result = search_policies(_tiny_conf(), **kwargs)
    trials = json.load(open(os.path.join(save, "search_trials.json")))
    assert len(trials["0"]) == 5  # padded lane's result was discarded
    assert result["trial_batch"] == 2
    assert result["tta_batched_executables"] in (None, 1)
    assert result["tta_batched_executables_expected"] == 1
    assert result["final_policy_set"]
    # resume: nothing left to evaluate, trial log unchanged
    result2 = search_policies(_tiny_conf(), **kwargs)
    trials2 = json.load(open(os.path.join(save, "search_trials.json")))
    assert trials2 == trials
    assert result2["final_policy_set"] == result["final_policy_set"]


@pytest.mark.slow
def test_search_trial_batch_matches_sequential_evaluation(tmp_path):
    """Real-stack parity: the SAME K policies evaluated through the
    driver's batched evaluator equal K sequential evaluations exactly
    (same fold data, same checkpoint, same per-trial keys), and a
    --trial-batch 1 rerun of a default run reproduces its trial log
    bit-for-bit."""
    from fast_autoaugment_tpu.policies.archive import policy_to_tensor
    from fast_autoaugment_tpu.search.driver import (
        _FoldEval,
        _fold_ckpt_path,
        search_policies,
    )
    from fast_autoaugment_tpu.parallel.mesh import make_mesh

    conf = _tiny_conf()
    save = str(tmp_path / "search")
    kwargs = dict(
        dataroot=str(tmp_path), save_dir=save, cv_num=1, cv_ratio=0.4,
        num_policy=2, num_op=2, num_search=3, num_top=2,
    )
    search_policies(conf, **kwargs)  # default scheduler
    trials_path = os.path.join(save, "search_trials.json")
    trials_default = json.load(open(trials_path))
    os.remove(trials_path)
    search_policies(conf, **kwargs, trial_batch=1)  # resumes phase 1
    assert json.load(open(trials_path)) == trials_default

    # batched evaluator vs sequential evaluator on identical inputs
    mesh = make_mesh()
    ev = _FoldEval(conf, str(tmp_path), mesh, num_policy=2, num_op=2,
                   cv_ratio=0.4, seed=0, trial_batch=2)
    path = _fold_ckpt_path(save, conf, 0, 0.4)
    params, batch_stats = ev.load_fold(path)
    subs = [
        [("Brightness", 1.0, 0.9), ("Cutout", 0.3, 0.3)],
        [("Invert", 0.8, 1.0), ("TranslateX", 0.5, 0.5)],
    ]
    policies_t = jnp.asarray(np.stack([
        np.asarray(policy_to_tensor([sub, sub]), np.float32) for sub in subs
    ]))
    keys = jnp.stack([jax.random.PRNGKey(11), jax.random.PRNGKey(22)])
    got = ev.evaluate_batch(0, params, batch_stats, policies_t, keys)
    for i in range(2):
        want = ev.evaluate(0, params, batch_stats, policies_t[i], keys[i])
        for field in ("minus_loss", "top1_valid", "top1_mean", "cnt"):
            assert float(got[i][field]) == pytest.approx(
                float(want[field]), abs=1e-6), (i, field)


@pytest.mark.slow
def test_census_failure_persists_artifact_before_raising(tmp_path, monkeypatch):
    """ADVICE r5 (low): a census RuntimeError fires AFTER all trial
    compute is spent — the partial search_result.json with a failure
    marker must hit disk before the raise so the run stays
    diagnosable/resumable."""
    from fast_autoaugment_tpu.search import driver

    monkeypatch.setattr(driver, "executable_census", lambda step: 99)
    save = str(tmp_path / "search")
    with pytest.raises(RuntimeError, match="recompilation is leaking"):
        driver.search_policies(
            _tiny_conf(), dataroot=str(tmp_path), save_dir=save,
            cv_num=1, cv_ratio=0.4, num_policy=1, num_op=1,
            num_search=2, num_top=1,
        )
    persisted = json.load(open(os.path.join(save, "search_result.json")))
    assert persisted["failure"]["stage"] == "tta_executable_census"
    assert "99" in persisted["failure"]["error"]
    assert persisted["tta_executables"] == 99
    assert "final_policy_set" not in persisted  # sets stay unserialized


def test_cli_trial_batch_flag():
    from fast_autoaugment_tpu.launch.search_cli import build_parser

    p = build_parser()
    assert p.parse_args(["-c", "x.yaml"]).trial_batch == 1  # sequential
    assert p.parse_args(["-c", "x.yaml", "--trial-batch", "16"]).trial_batch == 16


def test_random_arm_skip_reason():
    """ADVICE r5 (medium): a requested --phase3-random arm that comes
    back empty must be surfaced, with the reason recorded."""
    from fast_autoaugment_tpu.launch.search_cli import random_arm_skip_reason

    ok = {"random_policy_set": [[("Invert", 1.0, 1.0)]]}
    assert random_arm_skip_reason(ok) is None
    audited_away = {"random_policy_set": [],
                    "num_sub_policies_random_drawn": 23,
                    "num_sub_policies_random_dropped": 23}
    assert "dropped by the audit" in random_arm_skip_reason(audited_away)
    partial = {"random_policy_set": [],
               "num_sub_policies_random_drawn": 23,
               "num_sub_policies_random_dropped": 0}
    assert "empty after audit" in random_arm_skip_reason(partial)
    never_drawn = {}
    assert "no random policy set" in random_arm_skip_reason(never_drawn)


# ------------------------------------------------------------- bench

def test_host_contention_stamp():
    """Every bench artifact carries loadavg + process-count provenance
    (VERDICT r5 weak 1: a busy-host capture must be visible in the
    artifact itself)."""
    import bench

    stamp = bench.host_contention_stamp()
    assert stamp["cpu_count"] >= 1
    assert stamp["loadavg_1m"] is None or stamp["loadavg_1m"] >= 0.0
    assert stamp["process_count"] is None or stamp["process_count"] >= 1
    assert isinstance(stamp["contended"], bool)


def test_refuse_quiet_exits_on_contention(monkeypatch):
    import bench

    monkeypatch.setenv("FAA_BENCH_REQUIRE_QUIET", "1")
    with pytest.raises(SystemExit) as exc:
        bench.refuse_or_flag_contention(
            {"contended": True, "loadavg_1m": 9.0, "cpu_count": 1,
             "process_count": 42})
    assert exc.value.code == 3
    monkeypatch.delenv("FAA_BENCH_REQUIRE_QUIET")
    flagged = bench.refuse_or_flag_contention(
        {"contended": True, "loadavg_1m": 9.0, "cpu_count": 1,
         "process_count": 42})
    assert "contention" in flagged["note"]
    quiet = bench.refuse_or_flag_contention({"contended": False})
    assert "note" not in quiet
