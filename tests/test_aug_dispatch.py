"""Grouped scalar-dispatch augmentation kernels (``--aug-dispatch``).

Covers the three contracts of the dispatch split:

- ``exact`` (the default) is bit-for-bit the historical path — pinned
  against a committed golden capture (``tests/data/aug_exact_golden.npz``,
  generated from the pre-grouped-kernel tree) so a silent default flip
  or kernel drift fails loudly;
- ``grouped`` is a *documented distributional deviation* with identical
  per-image marginals: stratified (per-chunk) sub-policy selection,
  exactly per-image `prob` gating — checked statistically (chi-square on
  selection counts, gate-rate preservation, within-chunk gate variety);
- where the sub-policy is already fixed per lane (single-sub policies:
  the audit, the quality-gate baseline), grouped needs no distribution
  change at all and must match exact numerically.

Tier-1 keeps only the cheap guards (the golden exact-default pin, the
grouped permutation-plumbing check, flag/bench units); every
compile-heavy wiring/parity test and the statistical tests carry
``@pytest.mark.slow`` so the tier-1 suite stays inside its wall-clock
budget on a 1-core host (``make test`` still runs everything).
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fast_autoaugment_tpu.ops import augment as A
from fast_autoaugment_tpu.ops.preprocess import cifar_train_batch

GOLDEN = os.path.join(os.path.dirname(__file__), "data",
                      "aug_exact_golden.npz")


def _rand_imgs(seed, b=32, h=16, w=16):
    return np.random.default_rng(seed).integers(
        0, 256, (b, h, w, 3), dtype=np.uint8)


# ------------------------------------------------- exact-path pinning


def test_exact_default_bitwise_unchanged_golden():
    """The exact path (and the DEFAULT dispatch) must reproduce the
    pre-grouped-kernel tree's outputs bit-for-bit on seeded inputs —
    the guard against a silent default flip or kernel drift."""
    g = np.load(GOLDEN)
    imgs, policy = jnp.asarray(g["images"]), jnp.asarray(g["policy"])
    key = jax.random.PRNGKey(99)
    out = A.apply_policy_batch(jnp.float32(imgs), policy, key)
    np.testing.assert_array_equal(np.asarray(out), g["out_policy_batch"])
    # the full train stack, through the DEFAULT dispatch argument
    out2 = cifar_train_batch(imgs, jax.random.PRNGKey(7), policy=policy,
                             cutout_length=8)
    np.testing.assert_array_equal(np.asarray(out2), g["out_train_batch"])
    # and explicitly spelled exact == default
    out3 = cifar_train_batch(imgs, jax.random.PRNGKey(7), policy=policy,
                             cutout_length=8, aug_dispatch="exact")
    np.testing.assert_array_equal(np.asarray(out3), g["out_train_batch"])


def test_unknown_dispatch_rejected():
    imgs = jnp.float32(_rand_imgs(0, b=4))
    with pytest.raises(ValueError, match="aug_dispatch"):
        cifar_train_batch(imgs, jax.random.PRNGKey(0),
                          aug_dispatch="typo")
    with pytest.raises(ValueError, match="groups"):
        A.apply_policy_batch_grouped(
            imgs, jnp.zeros((2, 1, 3)), jax.random.PRNGKey(0), groups=0)


# ------------------------------------------------- grouped semantics

# four sub-policies with deterministic, mutually-distinguishable effects
# (prob 1, no mirrored ops, no op-internal randomness): Invert,
# Brightness@0.1, Brightness@1.9, Solarize@128
_MARKER_POLICY = np.asarray([
    [[6, 1.0, 0.0]],
    [[12, 1.0, 0.0]],
    [[12, 1.0, 1.0]],
    [[8, 1.0, 0.5]],
], np.float32)


def _marker_candidates(imgs_f32):
    x = imgs_f32.astype(np.float32)
    inv = 255.0 - x
    b_lo = np.clip(np.trunc(x * 0.1), 0, 255)
    b_hi = np.clip(np.trunc(x * 1.9), 0, 255)
    sol = np.where(x < 128.0, x, 255.0 - x)
    return np.stack([inv, b_lo, b_hi, sol])  # [4, B, H, W, C]


def _identify_selection(out, candidates):
    """Per-image index of the candidate transform that produced it."""
    matches = (np.abs(candidates - np.asarray(out)[None]) < 0.5).all(
        axis=(2, 3, 4))  # [4, B]
    counts = matches.sum(axis=0)
    assert (counts == 1).all(), "ambiguous or unmatched grouped output"
    return matches.argmax(axis=0)  # [B]


def test_grouped_output_is_a_subpolicy_application_of_its_own_image():
    """Every grouped output must be SOME sub-policy applied to the SAME
    input image — validates the permutation/inverse-permutation plumbing
    end to end."""
    imgs = _rand_imgs(1, b=24)
    candidates = _marker_candidates(imgs)
    out = A.apply_policy_batch_grouped(
        jnp.float32(imgs), jnp.asarray(_MARKER_POLICY),
        jax.random.PRNGKey(5), groups=6)
    _identify_selection(out, candidates)  # asserts a unique match per image


@pytest.mark.slow
def test_grouped_determinism_and_key_sensitivity():
    imgs = jnp.float32(_rand_imgs(2, b=16))
    pol = jnp.asarray(_MARKER_POLICY)
    k = jax.random.PRNGKey(3)
    o1 = A.apply_policy_batch_grouped(imgs, pol, k, groups=4)
    o2 = A.apply_policy_batch_grouped(imgs, pol, k, groups=4)
    np.testing.assert_array_equal(np.asarray(o1), np.asarray(o2))
    o3 = A.apply_policy_batch_grouped(imgs, pol, jax.random.PRNGKey(4),
                                      groups=4)
    assert not np.array_equal(np.asarray(o1), np.asarray(o3))


@pytest.mark.slow
def test_grouped_prob_zero_policy_is_identity():
    imgs = jnp.float32(_rand_imgs(3, b=12))
    pol = jnp.float32([[[4, 0.0, 1.0], [0, 0.0, 1.0]],
                       [[6, 0.0, 1.0], [8, 0.0, 1.0]]])
    out = A.apply_policy_batch_grouped(imgs, pol, jax.random.PRNGKey(1),
                                       groups=3)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(imgs))


@pytest.mark.slow
def test_grouped_uneven_batch_and_group_clamp():
    """B not divisible by G (pad path) and G > B (clamp) both produce
    valid per-image sub-policy applications."""
    for b, g in ((10, 4), (3, 8)):
        imgs = _rand_imgs(40 + b, b=b)
        out = A.apply_policy_batch_grouped(
            jnp.float32(imgs), jnp.asarray(_MARKER_POLICY),
            jax.random.PRNGKey(b), groups=g)
        _identify_selection(out, _marker_candidates(imgs))


@pytest.mark.slow
def test_single_sub_grouped_is_bitwise_exact():
    """One sub-policy leaves nothing to stratify: the grouped kernel
    must short-circuit to the scalar path and match the exact kernel
    bit-for-bit — the property the audit / quality-gate lanes rely on."""
    imgs = jnp.float32(_rand_imgs(4, b=16))
    pol = jnp.float32([[[2, 0.7, 0.9], [14, 0.5, 0.6]]])  # TranslateX, Cutout
    key = jax.random.PRNGKey(11)
    exact = A.apply_policy_batch(imgs, pol, key)
    grouped = A.apply_policy_batch_grouped(imgs, pol, key, groups=4)
    np.testing.assert_array_equal(np.asarray(exact), np.asarray(grouped))
    # and through the full train stack
    u8 = _rand_imgs(5, b=16)
    se = cifar_train_batch(jnp.asarray(u8), key, policy=pol, cutout_length=8)
    sg = cifar_train_batch(jnp.asarray(u8), key, policy=pol, cutout_length=8,
                           aug_dispatch="grouped", aug_groups=4)
    np.testing.assert_array_equal(np.asarray(se), np.asarray(sg))


@pytest.mark.slow
def test_grouped_selection_stratified_and_marginally_uniform():
    """Statistical parity: per-image sub-policy marginals stay uniform
    (chi-square over many seeded batches) while within-batch counts are
    stratified — every sub-policy's count is a multiple of the chunk
    size, the grouped kernel's defining signature (i.i.d. exact draws
    would essentially never align to chunk multiples batch after
    batch)."""
    b, g, runs = 32, 8, 60
    chunk = b // g
    imgs = _rand_imgs(6, b=b)
    candidates = _marker_candidates(imgs)
    pol = jnp.asarray(_MARKER_POLICY)
    fn = jax.jit(lambda k: A.apply_policy_batch_grouped(
        jnp.float32(imgs), pol, k, groups=g))
    counts = np.zeros(4)
    for r in range(runs):
        sel = _identify_selection(fn(jax.random.PRNGKey(1000 + r)),
                                  candidates)
        per_batch = np.bincount(sel, minlength=4)
        assert (per_batch % chunk == 0).all(), (r, per_batch)
        counts += per_batch
    expected = counts.sum() / 4.0
    # chunks are the independent draws (g per run), not images
    chi2 = float((((counts / chunk) - (runs * g / 4.0)) ** 2
                  / (runs * g / 4.0)).sum())
    assert chi2 < 16.27, (chi2, counts)  # df=3, p=0.001
    assert counts.sum() == runs * b and expected > 0


@pytest.mark.slow
def test_grouped_gate_probability_stays_per_image():
    """`prob` gating must remain exactly per-image under grouping: the
    pooled fire rate matches the gate probability, and gates vary
    WITHIN chunks (an accidental per-chunk gate would make every chunk
    all-or-nothing)."""
    b, g, p_gate, runs = 32, 2, 0.5, 40
    chunk = b // g
    imgs = _rand_imgs(7, b=b)
    # two IDENTICAL subs: selection is irrelevant, only the gate acts
    pol = jnp.float32([[[6, p_gate, 0.0]], [[6, p_gate, 0.0]]])
    fn = jax.jit(lambda k: A.apply_policy_batch_grouped(
        jnp.float32(imgs), pol, k, groups=g))
    fired_total, interior_chunks, total_chunks = 0, 0, 0
    for r in range(runs):
        out = np.asarray(fn(jax.random.PRNGKey(2000 + r)))
        fired = (np.abs(out - imgs.astype(np.float32)) > 0.5).any(
            axis=(1, 2, 3))
        fired_total += int(fired.sum())
        # chunk membership is hidden by the permutation, but an
        # all-or-nothing per-chunk gate would force the BATCH fire count
        # to chunk multiples; count interior batches as evidence
        total_chunks += 1
        if 0 < int(fired.sum()) % chunk < chunk:
            interior_chunks += 1
    rate = fired_total / (runs * b)
    assert abs(rate - p_gate) < 0.05, rate  # n=1280, 3.6 sigma
    assert interior_chunks / total_chunks > 0.5, interior_chunks


# --------------------------------------------------- train-step wiring


def _probe_bn_model():
    """Tiny conv+BN model: exercises the full train-step machinery
    (mutable batch_stats, EMA-free state) at a fraction of a WRN's
    compile time — these tests guard augmentation WIRING, not model
    math."""
    from flax import linen as nn

    class ProbeBN(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            x = nn.Conv(4, (3, 3))(x)
            x = nn.BatchNorm(use_running_average=not train)(x)
            x = nn.relu(x).mean(axis=(1, 2))
            return nn.Dense(10)(x)

    return ProbeBN()


def _train_pieces(aug_kw, stacked=False):
    from fast_autoaugment_tpu.ops.optim import build_optimizer
    from fast_autoaugment_tpu.train.steps import (
        create_train_state,
        make_stacked_train_step,
        make_train_step,
    )

    model = _probe_bn_model()
    opt = build_optimizer(
        {"type": "sgd", "decay": 2e-4, "clip": 5.0, "momentum": 0.9,
         "nesterov": True}, lambda s: 0.05)
    maker = make_stacked_train_step if stacked else make_train_step
    step = maker(model, opt, num_classes=10, cutout_length=4,
                 use_policy=True, **aug_kw)

    def fresh(seed=0):
        return create_train_state(model, opt, jax.random.PRNGKey(seed),
                                  jnp.zeros((2, 8, 8, 3), jnp.float32),
                                  use_ema=False)

    return step, fresh


# two subs, ONE op row each: enough to hit the genuine stratified path
# while compiling half the switches of a 2-op policy (compile time is
# what keeps these wiring tests inside the tier-1 budget)
_POLICY_2SUB = jnp.float32([[[6, 0.9, 0.0]], [[8, 0.9, 0.4]]])


@pytest.mark.slow
def test_train_step_exact_flag_is_default_bitwise():
    """Slow: near-tautological vs the current literals — the committed
    golden capture is the real default-flip guard (tier-1)."""
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.integers(0, 256, (8, 8, 8, 3), dtype=np.uint8))
    y = jnp.asarray(rng.integers(0, 10, (8,), np.int32))
    key = jax.random.PRNGKey(2)
    step_d, fresh = _train_pieces({})
    step_e, _ = _train_pieces({"aug_dispatch": "exact"})
    sd, md = step_d(fresh(), x, y, _POLICY_2SUB, key)
    se, me = step_e(fresh(), x, y, _POLICY_2SUB, key)
    for a, b in zip(jax.tree.leaves(sd.params), jax.tree.leaves(se.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert float(md["loss"]) == float(me["loss"])


@pytest.mark.slow
def test_train_step_grouped_runs_and_differs():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.integers(0, 256, (8, 8, 8, 3), dtype=np.uint8))
    y = jnp.asarray(rng.integers(0, 10, (8,), np.int32))
    key = jax.random.PRNGKey(2)
    step_e, fresh = _train_pieces({})
    step_g, _ = _train_pieces({"aug_dispatch": "grouped", "aug_groups": 4})
    se, me = step_e(fresh(), x, y, _POLICY_2SUB, key)
    sg, mg = step_g(fresh(), x, y, _POLICY_2SUB, key)
    assert np.isfinite(float(mg["loss"]))
    assert int(sg.step) == 1
    # different augmented batches -> different gradients (overwhelmingly)
    assert float(me["loss"]) != float(mg["loss"])


@pytest.mark.slow
def test_stacked_train_step_grouped_runs_and_masks():
    from fast_autoaugment_tpu.train.steps import stack_states

    rng = np.random.default_rng(2)
    k_folds = 2
    x = jnp.asarray(rng.integers(0, 256, (k_folds, 8, 8, 8, 3),
                                 dtype=np.uint8))
    y = jnp.asarray(rng.integers(0, 10, (k_folds, 8), np.int32))
    keys = jnp.stack([jax.random.PRNGKey(k) for k in range(k_folds)])
    step_g, fresh = _train_pieces(
        {"aug_dispatch": "grouped", "aug_groups": 4}, stacked=True)
    stacked = stack_states([fresh(0), fresh(1)])
    frozen_lane = jax.tree.map(lambda a: np.asarray(a[1]), stacked)
    active = jnp.asarray([1.0, 0.0], jnp.float32)
    new_states, metrics = step_g(stacked, x, y, _POLICY_2SUB, keys, active)
    assert np.isfinite(float(metrics["loss"][0]))
    assert float(metrics["num"][1]) == 0.0  # masked lane reports nothing
    for got, want in zip(jax.tree.leaves(
            jax.tree.map(lambda a: np.asarray(a[1]), new_states)),
            jax.tree.leaves(frozen_lane)):
        np.testing.assert_array_equal(got, want)  # bitwise pass-through


@pytest.mark.slow
def test_stacked_train_step_exact_flag_is_default_bitwise():
    """Slow: same rationale as the sequential flag-equality test; the
    stacked EXACT path's historical behavior is pinned by
    tests/test_stacked_phase1.py's parity suite (tier-1)."""
    from fast_autoaugment_tpu.train.steps import stack_states

    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.integers(0, 256, (2, 8, 8, 8, 3), dtype=np.uint8))
    y = jnp.asarray(rng.integers(0, 10, (2, 8), np.int32))
    keys = jnp.stack([jax.random.PRNGKey(k) for k in range(2)])
    active = jnp.ones((2,), jnp.float32)
    step_d, fresh = _train_pieces({}, stacked=True)
    step_e, _ = _train_pieces({"aug_dispatch": "exact"}, stacked=True)
    sd, md = step_d(stack_states([fresh(0), fresh(1)]), x, y, _POLICY_2SUB,
                    keys, active)
    se, me = step_e(stack_states([fresh(0), fresh(1)]), x, y, _POLICY_2SUB,
                    keys, active)
    for a, b in zip(jax.tree.leaves(sd.params), jax.tree.leaves(se.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(np.asarray(md["loss"]),
                                  np.asarray(me["loss"]))


# --------------------------------------------------------- TTA wiring


def _probe_model():
    from flax import linen as nn

    class Probe(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            x = nn.Conv(4, (3, 3))(x)
            x = nn.relu(x).mean(axis=(1, 2))
            return nn.Dense(10)(x)

    return Probe()


def _probe_batch(seed=0, b=6, hw=8):
    rng = np.random.default_rng(seed)
    return {
        "x": jnp.asarray(rng.integers(0, 256, (b, hw, hw, 3),
                                      dtype=np.uint8)),
        "y": jnp.asarray(rng.integers(0, 10, (b,), np.int32)),
        "m": jnp.asarray(np.ones(b, np.float32)),
    }


@pytest.mark.slow
def test_audit_step_grouped_matches_exact():
    """The audit's S axis fixes the sub-policy per lane, so grouped
    dispatch changes NOTHING distributionally — outputs must match the
    exact path (per-lane sub-policies are single-sub: bitwise-equal
    augmentation, identical flattened forward)."""
    from fast_autoaugment_tpu.search.tta import make_audit_step

    model = _probe_model()
    batch = _probe_batch(0)
    variables = model.init(jax.random.PRNGKey(1),
                           batch["x"].astype(jnp.float32))
    subs = jnp.float32([[[6, 0.9, 0.0]],
                        [[2, 0.8, 1.0]],
                        [[12, 0.7, 0.8]]])  # [S=3, num_op=1, 3]
    key = jax.random.PRNGKey(9)
    exact = make_audit_step(model, num_policy=2, cutout_length=4)
    grouped = make_audit_step(model, num_policy=2, cutout_length=4,
                              aug_dispatch="grouped", aug_groups=3)
    oe = exact(variables["params"], {}, batch["x"], batch["y"], batch["m"],
               subs, key)
    og = grouped(variables["params"], {}, batch["x"], batch["y"], batch["m"],
                 subs, key)
    np.testing.assert_allclose(np.asarray(oe["correct_mean_sum"]),
                               np.asarray(og["correct_mean_sum"]),
                               rtol=0, atol=1e-6)
    assert float(oe["cnt"]) == float(og["cnt"])


@pytest.mark.slow
def test_tta_step_grouped_single_sub_matches_exact():
    """A single-sub candidate (the quality gate's identity baseline
    shape) through the grouped TTA step equals the exact step — the
    fixed-sub-per-lane case needs no distribution change."""
    from fast_autoaugment_tpu.search.tta import eval_tta, make_tta_step

    model = _probe_model()
    batches = [_probe_batch(0), _probe_batch(1)]
    variables = model.init(jax.random.PRNGKey(1),
                           batches[0]["x"].astype(jnp.float32))
    pol = jnp.float32([[[6, 0.8, 0.0]]])  # [1, num_op=1, 3]
    exact = make_tta_step(model, num_policy=2, cutout_length=4)
    grouped = make_tta_step(model, num_policy=2, cutout_length=4,
                            aug_dispatch="grouped", aug_groups=2)
    oe = eval_tta(exact, variables["params"], {}, batches, pol,
                  jax.random.PRNGKey(5))
    og = eval_tta(grouped, variables["params"], {}, batches, pol,
                  jax.random.PRNGKey(5))
    for field in ("minus_loss", "top1_valid", "top1_mean", "cnt"):
        assert float(oe[field]) == pytest.approx(float(og[field]),
                                                 abs=1e-6), field


@pytest.mark.slow
def test_tta_grouped_batched_matches_grouped_single():
    """K candidates through the grouped num_candidates=K step must equal
    the same K (policy, key) pairs through the grouped single-candidate
    step — the candidate axis only batches the forward, never the
    dispatch."""
    from fast_autoaugment_tpu.search.tta import (
        eval_tta,
        eval_tta_batched,
        make_tta_step,
    )

    model = _probe_model()
    batches = [_probe_batch(0), _probe_batch(1)]
    variables = model.init(jax.random.PRNGKey(1),
                           batches[0]["x"].astype(jnp.float32))
    k = 2
    rng = np.random.default_rng(8)
    # multi-sub policies with real op rows: the genuine stratified path
    ops = rng.integers(0, 15, (k, 2, 1, 1)).astype(np.float32)
    pl = rng.uniform(0.2, 1.0, (k, 2, 1, 2)).astype(np.float32)
    policies = jnp.asarray(np.concatenate([ops, pl], axis=-1))
    keys = jnp.stack([jax.random.PRNGKey(60 + i) for i in range(k)])
    single = make_tta_step(model, num_policy=2, cutout_length=4,
                           aug_dispatch="grouped", aug_groups=2)
    batched = make_tta_step(model, num_policy=2, cutout_length=4,
                            aug_dispatch="grouped", aug_groups=2,
                            num_candidates=k)
    got = eval_tta_batched(batched, variables["params"], {}, batches,
                           policies, keys)
    for i in range(k):
        want = eval_tta(single, variables["params"], {}, batches,
                        policies[i], keys[i])
        for field in ("minus_loss", "top1_valid", "top1_mean", "cnt"):
            assert got[i][field] == pytest.approx(want[field],
                                                  abs=1e-6), (i, field)


# ------------------------------------------------------- driver / CLI


@pytest.mark.slow
def test_search_driver_stamps_dispatch_mode(tmp_path):
    """A grouped search runs end-to-end and stamps the dispatch mode
    into its result artifact.  Slow: trains a real phase-1 fold model
    (the non-slow e2e coverage of the driver's exact path lives in
    tests/test_batched_search.py; the stamp/plumbing itself is also
    covered by test_cli_dispatch_flags + the unit parity tests)."""
    from fast_autoaugment_tpu.core.config import Config
    from fast_autoaugment_tpu.search.driver import search_policies

    conf = Config({
        "model": {"type": "wresnet10_1"},
        "dataset": "synthetic",
        "aug": "default",
        "cutout": 8,
        "batch": 8,
        "epoch": 1,
        "lr": 0.05,
        "lr_schedule": {"type": "cosine"},
        "optimizer": {"type": "sgd", "decay": 1e-4, "clip": 5.0,
                      "momentum": 0.9, "nesterov": True},
    })
    result = search_policies(
        conf, dataroot=str(tmp_path), save_dir=str(tmp_path / "search"),
        cv_num=1, cv_ratio=0.4, num_policy=2, num_op=1, num_search=2,
        num_top=1, aug_dispatch="grouped", aug_groups=2,
    )
    assert result["aug_dispatch"] == "grouped"
    assert result["aug_groups"] == 2
    assert result["final_policy_set"]
    # zero-recompile invariant holds for the grouped step too
    assert result["tta_executables"] in (
        None, result["tta_executables_expected"])


def test_cli_dispatch_flags():
    from fast_autoaugment_tpu.launch.search_cli import build_parser
    from fast_autoaugment_tpu.launch.train_cli import (
        build_parser as train_parser,
    )

    p = build_parser()
    args = p.parse_args(["-c", "x.yaml"])
    assert args.aug_dispatch == "exact" and args.aug_groups == 8
    args = p.parse_args(["-c", "x.yaml", "--aug-dispatch", "grouped",
                         "--aug-groups", "16"])
    assert args.aug_dispatch == "grouped" and args.aug_groups == 16
    with pytest.raises(SystemExit):
        p.parse_args(["-c", "x.yaml", "--aug-dispatch", "banana"])
    t = train_parser()
    args = t.parse_args(["-c", "x.yaml"])
    assert args.aug_dispatch == "exact" and args.aug_groups == 8


# ------------------------------------------------------------- bench


def test_bench_vs_baseline_null_on_cpu_fallback():
    """A cpu-fallback bench run must not compare its plumbing number
    against the TPU baseline (BENCH_r05.json's vs_baseline 0.003)."""
    import bench

    assert bench.vs_baseline(46.4, cpu_fallback=True) is None
    assert bench.vs_baseline(65046.3, cpu_fallback=False) == 43.364


def test_bench_aug_full19_policy_covers_every_op():
    import sys as _sys

    _sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    import bench_aug

    pol = bench_aug.full_19op_policy()
    assert pol.shape == (A.NUM_OPS, 2, 3)
    assert set(pol[:, :, 0].astype(int).ravel()) == set(range(A.NUM_OPS))
