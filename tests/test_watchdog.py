"""Dispatch-watchdog units: EMA deadline math, hang detection, the
typed error contract, and the new FAA_FAULT dispatch verbs.

All fast host-only tests — the monitored "dispatches" are plain Python
callables (the watchdog is dispatch-agnostic: it times a callable and
blocks on its result).  The jax-integration seams are covered by the
trainer/driver wiring tests and the slow self-healing e2e.
"""

from __future__ import annotations

import os
import time

import pytest

from fast_autoaugment_tpu.core.resilience import (
    PREEMPTED_EXIT_CODE,
    DispatchHungError,
)
from fast_autoaugment_tpu.core.watchdog import (
    DispatchWatchdog,
    resolve_watchdog,
)
from fast_autoaugment_tpu.utils import faultinject


# ------------------------------------------------- deadline/EMA math

def test_ema_seeded_by_first_observation_then_smoothed():
    wd = DispatchWatchdog("auto", ema_alpha=0.5)
    wd.observe("d", 2.0)
    assert wd.ema("d") == 2.0  # first observation seeds directly
    wd.observe("d", 1.0)
    assert wd.ema("d") == pytest.approx(0.5 * 1.0 + 0.5 * 2.0)
    wd.observe("d", 1.0)
    assert wd.ema("d") == pytest.approx(0.5 * 1.0 + 0.5 * 1.5)


def test_auto_deadline_first_call_gets_compile_allowance():
    wd = DispatchWatchdog("auto", compile_allowance=123.0,
                          hang_factor=10.0, min_deadline=0.5)
    assert wd.deadline("d") == 123.0  # nothing observed yet
    wd.observe("d", 2.0)
    assert wd.deadline("d") == pytest.approx(20.0)  # factor x EMA
    # a tiny EMA cannot produce a hair-trigger deadline
    wd2 = DispatchWatchdog("auto", min_deadline=5.0)
    wd2.observe("d", 0.001)
    assert wd2.deadline("d") == 5.0


def test_fixed_deadline_keeps_compile_allowance_on_first_call():
    wd = DispatchWatchdog(2.0, compile_allowance=300.0)
    assert wd.deadline("d") == 300.0  # compile must not read as a hang
    wd.observe("d", 0.01)
    assert wd.deadline("d") == 2.0


def test_labels_have_independent_emas():
    wd = DispatchWatchdog("auto")
    wd.observe("train", 0.1)
    wd.observe("eval", 3.0)
    assert wd.ema("train") == pytest.approx(0.1)
    assert wd.ema("eval") == pytest.approx(3.0)


# ------------------------------------------------- run(): the monitor

def test_run_returns_result_and_observes():
    wd = DispatchWatchdog("auto")
    out = wd.run("d", lambda a, b: a + b, 2, 3)
    assert out == 5
    assert wd.ema("d") is not None and wd.fires == 0


def test_run_fires_on_hang_and_raises_typed_error():
    wd = DispatchWatchdog(0.2, compile_allowance=0.2)
    t0 = time.monotonic()
    with pytest.raises(DispatchHungError) as ei:
        wd.run("d", lambda: 1, inject_delay=30.0)
    assert time.monotonic() - t0 < 5.0  # the deadline, not the sleep
    assert wd.fires == 1
    assert ei.value.exit_code == PREEMPTED_EXIT_CODE
    assert ei.value.label == "d" and ei.value.deadline_sec == 0.2


def test_run_propagates_worker_exception():
    wd = DispatchWatchdog(5.0, compile_allowance=5.0)

    def boom():
        raise ValueError("from the worker")

    with pytest.raises(ValueError, match="from the worker"):
        wd.run("d", boom)
    assert wd.fires == 0


def test_disabled_mode_calls_through_inline():
    wd = DispatchWatchdog("off")
    assert not wd.enabled
    assert wd.run("d", lambda: 7) == 7
    # an injected (finite) delay still sleeps inline — the unwatched
    # wedge is reproduced for real, just bounded here for the test
    t0 = time.monotonic()
    assert wd.run("d", lambda: 8, inject_delay=0.05) == 8
    assert time.monotonic() - t0 >= 0.05


def test_stats_shape():
    wd = DispatchWatchdog("auto")
    wd.observe("d", 0.5)
    s = wd.stats()
    assert s["mode"] == "auto" and s["fires"] == 0
    assert "d" in s["deadline_sec"] and "d" in s["ema_sec"]


def test_label_state_thread_safe_under_hammer():
    """ISSUE 9 satellite: stats()/mark_compile_warm()/observe()/
    deadline() mutate shared dicts — one monitored dispatch per async
    actor means they now run concurrently.  Hammer all four from
    threads; every call must survive (no RuntimeError from a dict
    changing size mid-iteration, the pre-lock failure mode) and the
    final state must account for every write."""
    import threading

    wd = DispatchWatchdog("auto")
    n_threads, n_iter = 8, 300
    errors: list[BaseException] = []
    start = threading.Barrier(n_threads)

    def hammer(idx: int):
        try:
            start.wait(timeout=10)
            for i in range(n_iter):
                label = f"lab{idx}-{i % 7}"
                wd.observe(label, 0.01 * (i + 1))
                wd.mark_compile_warm(f"warm{idx}-{i % 5}")
                wd.deadline(label)
                s = wd.stats()
                assert s["fires"] == 0
        except BaseException as e:  # surfaced below
            errors.append(e)

    threads = [threading.Thread(target=hammer, args=(i,), daemon=True)
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors, errors
    s = wd.stats()
    # every (thread, label) stream folded in: 7 labels per thread, and
    # each label observed ceil/floor(n_iter/7) times
    assert len(s["ema_sec"]) == n_threads * 7
    assert len(s["warm_labels"]) == n_threads * 5
    assert sum(wd._calls.values()) == n_threads * n_iter


# ------------------------------------------------- resolve_watchdog

def test_resolve_watchdog_specs():
    assert not resolve_watchdog("off").enabled
    assert not resolve_watchdog(None).enabled
    assert resolve_watchdog("auto").mode == "auto"
    assert resolve_watchdog("2.5").mode == 2.5
    assert resolve_watchdog(4).mode == 4.0
    wd = DispatchWatchdog("auto")
    assert resolve_watchdog(wd) is wd  # shared instance passes through
    with pytest.raises(ValueError):
        resolve_watchdog("-1")
    with pytest.raises(ValueError):
        resolve_watchdog("sometimes")


# ------------------------------------------------- FAA_FAULT verbs

@pytest.fixture(autouse=True)
def _clean_fault_env():
    saved = os.environ.pop("FAA_FAULT", None)
    saved_at = os.environ.pop("FAA_ATTEMPT", None)
    faultinject.reset()
    yield
    if saved is None:
        os.environ.pop("FAA_FAULT", None)
    else:
        os.environ["FAA_FAULT"] = saved
    if saved_at is None:
        os.environ.pop("FAA_ATTEMPT", None)
    else:
        os.environ["FAA_ATTEMPT"] = saved_at
    faultinject.reset()


def test_parse_new_verbs():
    faults = faultinject.parse_fault_spec(
        "hang@step=4;slow@step=7,factor=3.5;stale_lease@unit=p1-fold0")
    kinds = [f["kind"] for f in faults]
    assert kinds == ["hang", "slow", "stale_lease"]
    assert faults[1]["factor"] == 3.5
    assert faults[2]["unit"] == "p1-fold0"


@pytest.mark.parametrize("bad", [
    "hang@",                       # missing step
    "slow@step=3",                 # missing factor
    "stale_lease@unit=",           # empty unit
    "hang@step=3,factor=2",        # factor not a hang key
])
def test_parse_new_verbs_reject(bad):
    with pytest.raises(ValueError):
        faultinject.parse_fault_spec(bad)


def test_dispatch_delay_hang_fires_once_at_least():
    os.environ["FAA_FAULT"] = "hang@step=5"
    faultinject.reset()
    plan = faultinject.active_plan()
    assert plan.dispatch_delay(4) is None
    kind, val = plan.dispatch_delay(7)  # >= 5: at_least matching
    assert kind == "hang" and val == float("inf")
    assert plan.dispatch_delay(8) is None  # consumed


def test_dispatch_delay_slow_carries_factor():
    os.environ["FAA_FAULT"] = "slow@step=2,factor=4"
    faultinject.reset()
    plan = faultinject.active_plan()
    assert plan.dispatch_delay(2) == ("slow", 4.0)
    assert plan.dispatch_delay(3) is None


def test_attempt_gating_blocks_other_attempts():
    os.environ["FAA_FAULT"] = "hang@step=1,attempt=1"
    os.environ["FAA_ATTEMPT"] = "2"
    faultinject.reset()
    plan = faultinject.active_plan()
    assert plan.dispatch_delay(10) is None  # gated to attempt 1
    os.environ["FAA_ATTEMPT"] = "1"
    assert plan.dispatch_delay(10) is not None


def test_stale_lease_latches_per_unit():
    os.environ["FAA_FAULT"] = "stale_lease@unit=p1-fold1"
    faultinject.reset()
    plan = faultinject.active_plan()
    assert not plan.lease_stale("p1-fold0")
    assert plan.lease_stale("p1-fold1")
    assert plan.lease_stale("p1-fold1")  # latched, not consume-once


def test_slow_injection_observed_by_watchdog_without_firing():
    """A straggler (slow@) delays the dispatch but stays under a
    generous deadline — distinguishing it from a hang is the point of
    the two verbs."""
    wd = DispatchWatchdog(5.0, compile_allowance=5.0)
    wd.observe("d", 0.01)
    out = wd.run("d", lambda: 3, inject_delay=0.05)
    assert out == 3 and wd.fires == 0


def test_hang_injection_fires_watchdog():
    wd = DispatchWatchdog(0.2, compile_allowance=0.2)
    with pytest.raises(DispatchHungError):
        wd.run("d", lambda: 3, inject_delay=float("inf"))
    assert wd.fires == 1


# --------------------------------------------- trainer seam (host-only)

def test_monitored_dispatch_off_no_fault_is_the_direct_call():
    """The bit-for-bit default: watchdog off + no fault plan must be
    the plain call — no worker thread, no block."""
    from fast_autoaugment_tpu.train.trainer import _monitored_dispatch

    wd = DispatchWatchdog("off")
    sentinel = object()
    out = _monitored_dispatch(wd, "train_dispatch", None, 3,
                              lambda a: (a, "m"), sentinel)
    assert out[0] is sentinel  # identity through, nothing wrapped


def test_monitored_dispatch_injected_hang_fires_and_maps_to_exit77():
    from fast_autoaugment_tpu.train.trainer import _monitored_dispatch

    os.environ["FAA_FAULT"] = "hang@step=5"
    faultinject.reset()
    fi = faultinject.active_plan()
    wd = DispatchWatchdog(0.2, compile_allowance=0.2)
    with pytest.raises(DispatchHungError) as ei:
        _monitored_dispatch(wd, "train_dispatch", fi, 6, lambda: "x")
    assert ei.value.exit_code == PREEMPTED_EXIT_CODE
    # the spec was consumed: the next dispatch proceeds normally
    assert _monitored_dispatch(wd, "train_dispatch", fi, 7,
                               lambda: "y") == "y"


def test_monitored_dispatch_slow_scales_by_ema():
    from fast_autoaugment_tpu.train.trainer import _monitored_dispatch

    os.environ["FAA_FAULT"] = "slow@step=1,factor=2"
    faultinject.reset()
    fi = faultinject.active_plan()
    wd = DispatchWatchdog(5.0, compile_allowance=5.0)
    wd.observe("train_dispatch", 0.05)
    t0 = time.monotonic()
    out = _monitored_dispatch(wd, "train_dispatch", fi, 2, lambda: 9)
    assert out == 9
    assert time.monotonic() - t0 >= 0.1  # ~factor x EMA injected
    assert wd.fires == 0  # a straggler, not a hang
