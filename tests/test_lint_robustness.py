"""The robustness lint (tools/lint_robustness.py): rule coverage on
synthetic sources plus the live-repo gate (`make lint-robust` and the
test-t1 preamble run the same entry point)."""

import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))

from lint_robustness import check_source, lint_tree  # noqa: E402


IN_SCOPE = "fast_autoaugment_tpu/search/x.py"
OUT_SCOPE = "fast_autoaugment_tpu/utils/x.py"


def _rules(findings):
    return [f.rule for f in findings]


def test_bare_except_flagged():
    src = "try:\n    x()\nexcept:\n    pass\n"
    assert _rules(check_source(src, OUT_SCOPE)) == ["R1"]


def test_swallowed_broad_except_flagged():
    src = "try:\n    x()\nexcept Exception:\n    pass\n"
    assert _rules(check_source(src, OUT_SCOPE)) == ["R2"]


def test_broad_except_with_logging_ok():
    src = ("try:\n    x()\nexcept Exception as e:\n"
           "    logger.warning('boom %s', e)\n")
    assert not check_source(src, OUT_SCOPE)


def test_broad_except_with_reraise_ok():
    src = "try:\n    x()\nexcept Exception:\n    raise\n"
    assert not check_source(src, OUT_SCOPE)


def test_broad_except_capturing_exception_ok():
    # the prefetch-worker pattern: propagate through a channel
    src = "try:\n    x()\nexcept BaseException as e:\n    err.append(e)\n"
    assert not check_source(src, OUT_SCOPE)


def test_narrow_except_never_flagged():
    src = "try:\n    x()\nexcept ValueError:\n    pass\n"
    assert not check_source(src, OUT_SCOPE)


def test_artifact_write_flagged_in_scope_only():
    src = ("import json\n"
           "def persist(path, obj):\n"
           "    with open(path, 'w') as fh:\n"
           "        json.dump(obj, fh)\n")
    rules = _rules(check_source(src, IN_SCOPE))
    assert rules.count("R3") == 2  # the open AND the dump
    assert not check_source(src, OUT_SCOPE)  # utils/ is out of scope


def test_append_and_read_modes_ok():
    src = ("def tail(path):\n"
           "    with open(path) as fh:\n"
           "        return fh.read()\n"
           "def log(path, line):\n"
           "    with open(path, 'a') as fh:\n"
           "        fh.write(line)\n")
    assert not check_source(src, IN_SCOPE)


def test_allowlisted_atomic_helpers_ok():
    src = ("import json\n"
           "def write_json_atomic(path, obj):\n"
           "    with open(path + '.tmp', 'w') as fh:\n"
           "        json.dump(obj, fh)\n")
    assert not check_source(src, "fast_autoaugment_tpu/search/driver.py")
    # the same body under another name IS a finding
    src2 = src.replace("write_json_atomic", "sneaky_write")
    assert _rules(check_source(
        src2, "fast_autoaugment_tpu/search/driver.py")).count("R3") == 2


def test_robust_allow_suppression():
    src = ("try:\n    x()\n"
           "except:  # robust: allow — deliberate for this test\n"
           "    pass\n")
    assert not check_source(src, OUT_SCOPE)


BLOCK_SCOPE = "fast_autoaugment_tpu/launch/x.py"
TRAIN_SCOPE = "fast_autoaugment_tpu/train/x.py"  # R3 yes, R4 no


def test_untimed_thread_join_flagged():
    src = ("import threading\n"
           "t = threading.Thread(target=f)\n"
           "t.start()\n"
           "t.join()\n")
    assert _rules(check_source(src, BLOCK_SCOPE)) == ["R4"]


def test_timed_thread_join_ok():
    src = ("import threading\n"
           "t = threading.Thread(target=f)\n"
           "t.join(timeout=2)\n"
           "t.join(5)\n")
    assert not check_source(src, BLOCK_SCOPE)


def test_untimed_queue_get_flagged_including_self_attr():
    src = ("import queue\n"
           "class W:\n"
           "    def __init__(self):\n"
           "        self.q = queue.Queue()\n"
           "    def pull(self):\n"
           "        return self.q.get()\n")
    assert _rules(check_source(src, BLOCK_SCOPE)) == ["R4"]


def test_queue_get_with_timeout_or_nonblocking_ok():
    src = ("import queue\n"
           "q = queue.Queue()\n"
           "q.get(timeout=1)\n"
           "q.get(False)\n")
    assert not check_source(src, BLOCK_SCOPE)


def test_str_join_and_dict_get_never_flagged():
    # receiver tracking is constructor-based: only names bound from
    # Thread/Queue constructors count
    src = ("sep = ','\n"
           "out = sep.join(['a', 'b'])\n"
           "d = {}\n"
           "v = d.get('k')\n"
           "cfg = Config.get()\n")
    assert not check_source(src, BLOCK_SCOPE)


def test_r4_out_of_scope_dir_not_flagged():
    src = ("import threading\n"
           "t = threading.Thread(target=f)\n"
           "t.join()\n")
    assert not check_source(src, TRAIN_SCOPE)
    assert not check_source(src, OUT_SCOPE)


def test_r4_robust_allow_suppression():
    src = ("import threading\n"
           "t = threading.Thread(target=f)\n"
           "t.join()  # robust: allow — joined at interpreter exit\n")
    assert not check_source(src, BLOCK_SCOPE)


SERVE_SCOPE = "fast_autoaugment_tpu/serve/x.py"


def test_r5_direct_jit_flagged_in_seam_dirs():
    src = "import jax\nstep = jax.jit(body)\n"
    for scope in (IN_SCOPE, TRAIN_SCOPE, SERVE_SCOPE):
        assert "R5" in _rules(check_source(src, scope)), scope


def test_r5_partial_and_decorator_forms_flagged():
    # the historical steps.py idiom AND the decorator form both carry
    # a jax.jit attribute reference — all uninstrumented compiles
    src_partial = ("import functools, jax\n"
                   "step = functools.partial(jax.jit, donate_argnums=(0,))(f)\n")
    src_deco = "import jax\n@jax.jit\ndef f(x):\n    return x\n"
    assert "R5" in _rules(check_source(src_partial, TRAIN_SCOPE))
    assert "R5" in _rules(check_source(src_deco, TRAIN_SCOPE))


def test_r5_out_of_scope_dirs_not_flagged():
    src = "import jax\nstep = jax.jit(body)\n"
    for scope in (OUT_SCOPE, "fast_autoaugment_tpu/ops/x.py",
                  "fast_autoaugment_tpu/core/compilecache.py"):
        assert "R5" not in _rules(check_source(src, scope)), scope


def test_r5_seam_jit_is_clean():
    src = ("from fast_autoaugment_tpu.core.compilecache import seam_jit\n"
           "step = seam_jit(body, label='train_step', donate_argnums=(0,))\n")
    assert not check_source(src, TRAIN_SCOPE)


def test_r5_robust_allow_suppression():
    src = "import jax\nstep = jax.jit(body)  # robust: allow — export path\n"
    assert "R5" not in _rules(check_source(src, TRAIN_SCOPE))


def test_r6_unbounded_queue_put_flagged():
    """The blocking-admission bug class: Queue.put without a timeout
    in serve/ parks a handler thread on a full queue."""
    src = "import queue\nq = queue.Queue()\nq.put(item)\n"
    assert _rules(check_source(src, SERVE_SCOPE)) == ["R6"]


def test_r6_bounded_and_nonblocking_put_ok():
    src = ("import queue\nq = queue.Queue()\n"
           "q.put(item, False)\nq.put(item, timeout=1.0)\n"
           "q.put(item, block=False)\n")
    assert not check_source(src, SERVE_SCOPE)


def test_r6_event_wait_via_attribute_suffix():
    """Cross-object receivers match by constructor-bound attribute
    suffix: pending.event.wait() is caught through the self.event =
    Event() construction elsewhere in the file."""
    src = ("import threading\n"
           "class P:\n"
           "    def __init__(self):\n"
           "        self.event = threading.Event()\n"
           "def wait_for(p):\n"
           "    p.event.wait()\n")
    assert _rules(check_source(src, SERVE_SCOPE)) == ["R6"]
    timed = src.replace("p.event.wait()", "p.event.wait(timeout=2.0)")
    assert not check_source(timed, SERVE_SCOPE)


def test_r6_untimed_thread_join_and_queue_get_flagged():
    src = ("import threading, queue\n"
           "t = threading.Thread(target=f)\nq = queue.Queue()\n"
           "t.join()\nq.get()\n")
    assert _rules(check_source(src, SERVE_SCOPE)) == ["R6", "R6"]


def test_r6_bare_sleep_loop_flagged():
    src = "import time\nwhile not done():\n    time.sleep(0.5)\n"
    assert _rules(check_source(src, SERVE_SCOPE)) == ["R6"]
    # a one-shot sleep outside a loop is not a poll loop
    assert not check_source("import time\ntime.sleep(0.5)\n", SERVE_SCOPE)
    # the bounded idiom: Event.wait(timeout) as the loop condition
    ok = ("import threading\nevt = threading.Event()\n"
          "while not evt.wait(0.5):\n    poll()\n")
    assert not check_source(ok, SERVE_SCOPE)


def test_r6_out_of_scope_dirs_not_flagged():
    src = ("import queue, time\nq = queue.Queue()\nq.put(item)\n"
           "while True:\n    time.sleep(0.1)\n")
    for scope in (OUT_SCOPE, TRAIN_SCOPE, BLOCK_SCOPE):
        assert "R6" not in _rules(check_source(src, scope)), scope


def test_r6_str_join_dict_get_never_flagged():
    src = ("x = ','.join(items)\nd = {}\nd.get('k')\n"
           "class C:\n    pass\n")
    assert not check_source(src, SERVE_SCOPE)


def test_r6_robust_allow_suppression():
    src = ("import queue\nq = queue.Queue()\n"
           "q.put(item)  # robust: allow — bounded by construction\n")
    assert not check_source(src, SERVE_SCOPE)


# ------------------------------------------------------------------ R7
# the R6 rule set extended to search/ scope: the async actor/learner
# pipeline threads dispatches under the same no-thread-parks-forever
# contract as serving (ISSUE 9; search/pipeline.py is gated from day one)

SEARCH_SCOPE = "fast_autoaugment_tpu/search/pipeline.py"


def test_r7_unbounded_queue_put_flagged_in_search():
    src = "import queue\nq = queue.Queue()\nq.put(item)\n"
    assert _rules(check_source(src, SEARCH_SCOPE)) == ["R7"]
    assert not check_source(
        src.replace("q.put(item)", "q.put(item, timeout=60.0)"),
        SEARCH_SCOPE)


def test_r7_event_and_condition_wait_flagged():
    src = ("import threading\n"
           "evt = threading.Event()\ncond = threading.Condition()\n"
           "evt.wait()\ncond.wait()\n")
    assert _rules(check_source(src, SEARCH_SCOPE)) == ["R7", "R7"]
    timed = src.replace("evt.wait()", "evt.wait(0.5)").replace(
        "cond.wait()", "cond.wait(timeout=0.5)")
    assert not check_source(timed, SEARCH_SCOPE)


def test_r7_untimed_join_get_flagged_alongside_r4():
    """search/ sits in BOTH the R4 supervision scope and the R7
    pipeline scope: an untimed join/get on a constructor-tracked
    receiver trips both rules (same fix clears both)."""
    src = ("import threading, queue\n"
           "t = threading.Thread(target=f)\nq = queue.Queue()\n"
           "t.join()\nq.get()\n")
    rules = _rules(check_source(src, SEARCH_SCOPE))
    assert rules.count("R7") == 2
    assert rules.count("R4") == 2
    timed = src.replace("t.join()", "t.join(timeout=5)").replace(
        "q.get()", "q.get(timeout=0.2)")
    assert not check_source(timed, SEARCH_SCOPE)


def test_r7_bare_sleep_loop_flagged():
    src = "import time\nwhile not done():\n    time.sleep(0.5)\n"
    assert _rules(check_source(src, SEARCH_SCOPE)) == ["R7"]
    assert not check_source("import time\ntime.sleep(0.5)\n", SEARCH_SCOPE)


def test_r7_out_of_scope_dirs_not_flagged():
    src = ("import queue, time\nq = queue.Queue()\nq.put(item)\n"
           "while True:\n    time.sleep(0.1)\n")
    for scope in (OUT_SCOPE, TRAIN_SCOPE):
        assert "R7" not in _rules(check_source(src, scope)), scope
    # serve/ keeps its own rule id for the same engine
    assert "R7" not in _rules(check_source(src, SERVE_SCOPE))
    assert "R6" in _rules(check_source(src, SERVE_SCOPE))


def test_r7_robust_allow_suppression():
    src = ("import time\nwhile pending:\n"
           "    time.sleep(1.0)  # robust: allow — TTL-bounded poll\n")
    assert "R7" not in _rules(check_source(src, SEARCH_SCOPE))


# ------------------------------------------------------------------ R8


def test_r8_raw_clocks_flagged_in_hot_paths():
    src = "import time\nt0 = time.time()\nt1 = time.perf_counter()\n"
    for scope in (TRAIN_SCOPE, SEARCH_SCOPE, SERVE_SCOPE):
        assert _rules(check_source(src, scope)).count("R8") == 2, scope


def test_r8_import_alias_form_flagged():
    src = "from time import time, perf_counter\n"
    assert _rules(check_source(src, TRAIN_SCOPE)).count("R8") == 2
    # importing only sleep/monotonic is fine
    assert not check_source("from time import sleep, monotonic\n",
                            TRAIN_SCOPE)


def test_r8_monotonic_and_sleep_not_flagged():
    # deadline plumbing and waits are not timing evidence
    src = "import time\nd = time.monotonic()\ntime.sleep(0.1)\n"
    assert "R8" not in _rules(check_source(src, SERVE_SCOPE))


def test_r8_seam_calls_not_flagged():
    src = ("from fast_autoaugment_tpu.core.telemetry import mono, wall\n"
           "t0 = mono()\nw = wall()\n")
    assert not check_source(src, TRAIN_SCOPE)


def test_r8_out_of_scope_dirs_not_flagged():
    # core/ and utils/ ARE the seam; launch/ heartbeats are protocol
    # stamps, not measurements
    src = "import time\nt = time.time()\n"
    for scope in (OUT_SCOPE, "fast_autoaugment_tpu/core/x.py",
                  "fast_autoaugment_tpu/launch/x.py"):
        assert "R8" not in _rules(check_source(src, scope)), scope


def test_r8_robust_allow_suppression():
    src = ("import time\n"
           "t = time.time()  # robust: allow — protocol stamp\n")
    assert "R8" not in _rules(check_source(src, SEARCH_SCOPE))


def test_repo_is_clean():
    """The live gate: the package must hold the discipline the
    resilience subsystem depends on (make lint-robust)."""
    findings = lint_tree()
    assert not findings, "\n".join(map(repr, findings))
