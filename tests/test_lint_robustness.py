"""The robustness lint (tools/lint_robustness.py): rule coverage on
synthetic sources plus the live-repo gate (`make lint-robust` and the
test-t1 preamble run the same entry point)."""

import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))

from lint_robustness import check_source, lint_tree  # noqa: E402


IN_SCOPE = "fast_autoaugment_tpu/search/x.py"
OUT_SCOPE = "fast_autoaugment_tpu/utils/x.py"


def _rules(findings):
    return [f.rule for f in findings]


def test_bare_except_flagged():
    src = "try:\n    x()\nexcept:\n    pass\n"
    assert _rules(check_source(src, OUT_SCOPE)) == ["R1"]


def test_swallowed_broad_except_flagged():
    src = "try:\n    x()\nexcept Exception:\n    pass\n"
    assert _rules(check_source(src, OUT_SCOPE)) == ["R2"]


def test_broad_except_with_logging_ok():
    src = ("try:\n    x()\nexcept Exception as e:\n"
           "    logger.warning('boom %s', e)\n")
    assert not check_source(src, OUT_SCOPE)


def test_broad_except_with_reraise_ok():
    src = "try:\n    x()\nexcept Exception:\n    raise\n"
    assert not check_source(src, OUT_SCOPE)


def test_broad_except_capturing_exception_ok():
    # the prefetch-worker pattern: propagate through a channel
    src = "try:\n    x()\nexcept BaseException as e:\n    err.append(e)\n"
    assert not check_source(src, OUT_SCOPE)


def test_narrow_except_never_flagged():
    src = "try:\n    x()\nexcept ValueError:\n    pass\n"
    assert not check_source(src, OUT_SCOPE)


def test_artifact_write_flagged_in_scope_only():
    src = ("import json\n"
           "def persist(path, obj):\n"
           "    with open(path, 'w') as fh:\n"
           "        json.dump(obj, fh)\n")
    rules = _rules(check_source(src, IN_SCOPE))
    assert rules.count("R3") == 2  # the open AND the dump
    assert not check_source(src, OUT_SCOPE)  # utils/ is out of scope


def test_append_and_read_modes_ok():
    src = ("def tail(path):\n"
           "    with open(path) as fh:\n"
           "        return fh.read()\n"
           "def log(path, line):\n"
           "    with open(path, 'a') as fh:\n"
           "        fh.write(line)\n")
    assert not check_source(src, IN_SCOPE)


def test_allowlisted_atomic_helpers_ok():
    src = ("import json\n"
           "def write_json_atomic(path, obj):\n"
           "    with open(path + '.tmp', 'w') as fh:\n"
           "        json.dump(obj, fh)\n")
    assert not check_source(src, "fast_autoaugment_tpu/search/driver.py")
    # the same body under another name IS a finding
    src2 = src.replace("write_json_atomic", "sneaky_write")
    assert _rules(check_source(
        src2, "fast_autoaugment_tpu/search/driver.py")).count("R3") == 2


def test_robust_allow_suppression():
    src = ("try:\n    x()\n"
           "except:  # robust: allow — deliberate for this test\n"
           "    pass\n")
    assert not check_source(src, OUT_SCOPE)


def test_repo_is_clean():
    """The live gate: the package must hold the discipline the
    resilience subsystem depends on (make lint-robust)."""
    findings = lint_tree()
    assert not findings, "\n".join(map(repr, findings))
