"""Closed-loop control plane, end to end (ISSUE 14, slow):

- the driver-level warm-start contract: ``topup_trials=0`` resume
  reproduces the one-shot ``final_policy.json`` byte-identically, and
  a top-up extends the trial log without touching the base entries;
- THE acceptance drill: a live 3-replica routed fleet under FAA_FAULT
  ``drift@...`` injection runs detect -> warm-started re-search (a
  real ``search_cli --topup-trials`` subprocess) -> canary -> promote
  with ZERO dropped requests during rollover, ``make trace`` rendering
  the whole causal chain from one journal, and ``make status``
  summarizing it.

Everything here is compile-heavy and slow-marked (the 870s tier-1
wall); the host-only logic is covered by tests/test_control.py.
"""

from __future__ import annotations

import glob
import json
import os
import shutil
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
sys.path.insert(0, os.path.join(_REPO, "tools"))

pytestmark = pytest.mark.slow


def _tiny_conf():
    from fast_autoaugment_tpu.core.config import Config

    return Config({
        "model": {"type": "wresnet10_1"},
        "dataset": "synthetic",
        "aug": "default",
        "cutout": 8,
        "batch": 8,
        "epoch": 1,
        "lr": 0.05,
        "lr_schedule": {"type": "cosine"},
        "optimizer": {"type": "sgd", "decay": 1e-4, "clip": 5.0,
                      "momentum": 0.9, "nesterov": True},
    })


CONF_YAML = (
    "model:\n  type: wresnet10_1\ndataset: synthetic\naug: default\n"
    "cutout: 8\nbatch: 8\nepoch: 1\nlr: 0.05\n"
    "lr_schedule:\n  type: cosine\n"
    "optimizer:\n  type: sgd\n  decay: 0.0001\n  momentum: 0.9\n"
    "  nesterov: true\n")


def test_warm_start_topup_driver_contract(tmp_path):
    """``search_policies(topup_trials=...)`` through the ledger warm
    start: zero top-up = byte-identical final_policy.json (the
    no-drift defaults pin), a real top-up extends the log with the
    base prefix untouched and stamps ``warm_start``."""
    from fast_autoaugment_tpu.control.research import warm_started_research
    from fast_autoaugment_tpu.search.driver import search_policies

    conf = _tiny_conf()
    common = dict(cv_num=1, cv_ratio=0.4, num_policy=1, num_op=1,
                  num_search=5, num_top=2, trial_batch=2,
                  async_pipeline="on", pipeline_actors=1,
                  pipeline_queue_depth=1, fold_quality_floor=None,
                  seed=0)
    base = str(tmp_path / "base")
    r0 = search_policies(conf, str(tmp_path), base, **common)
    assert "warm_start" not in r0  # defaults: no new artifact keys
    final_bytes = open(os.path.join(base, "final_policy.json"),
                       "rb").read()
    log0 = json.load(open(os.path.join(base, "search_trials.json")))

    # ---- zero top-up: the one-shot artifact, byte for byte ----------
    zero = warm_started_research(
        conf, str(tmp_path), base, str(tmp_path / "zero"),
        topup_trials=0, **common)
    assert open(zero["policy"], "rb").read() == final_bytes
    assert "warm_start" not in zero["result"]
    assert zero["provenance"]["topup_trials"] == 0
    # the candidate digest names the same bytes the fleet would verify
    from fast_autoaugment_tpu.control.research import policy_file_digest

    assert zero["provenance"]["policy_digest"] == \
        policy_file_digest(os.path.join(base, "final_policy.json"))
    zero_log = json.load(open(tmp_path / "zero" / "search_trials.json"))
    assert zero_log == log0  # zero new trials dispatched

    # ---- real top-up: base prefix byte-identical, budget extended ---
    topped = warm_started_research(
        conf, str(tmp_path), base, str(tmp_path / "top"),
        topup_trials=3,
        drift={"id": "drift-test-1", "metric": "input_mean"},
        **common)
    log1 = json.load(open(tmp_path / "top" / "search_trials.json"))
    assert len(log1["0"]) == 8
    assert json.dumps(log1["0"][:5]) == json.dumps(log0["0"])
    ws = topped["result"]["warm_start"]
    assert ws["base_num_search"] == 5 and ws["topup_trials"] == 3
    assert ws["resumed_trials_per_fold"]["0"] == 5
    assert topped["provenance"]["drift"]["id"] == "drift-test-1"
    assert topped["provenance"]["warm_start"] == ws


# ------------------------------- fleet-routed re-search (ISSUE 15 sat)


def test_research_through_fleet_learner_actor_byte_identical(tmp_path):
    """The PR-14 REMAINING item, measured: the control loop's
    warm-started re-search pointed at a REAL PR-13 learner+actor fleet
    launch (``search_cli --search-role``) produces artifacts
    BYTE-IDENTICAL to the controller-host re-search — so
    ``--research-cmd`` can offload the top-up to a fleet without
    changing a single candidate byte."""
    from fast_autoaugment_tpu.control.research import seed_research_dir

    tmp = str(tmp_path)
    cc = os.path.join(tmp, "cc")
    conf_yaml = os.path.join(tmp, "conf.yaml")
    with open(conf_yaml, "w") as fh:
        fh.write(CONF_YAML)
    flags = [
        "-c", conf_yaml, "--dataroot", tmp,
        "--num-fold", "1", "--num-search", "4", "--num-policy", "1",
        "--num-op", "1", "--num-top", "2", "--trial-batch", "2",
        "--until", "2", "--fold-quality-floor", "off",
        "--audit-floor", "0", "--async-pipeline", "on",
        "--pipeline-actors", "2", "--pipeline-queue-depth", "2",
        "--seed", "0", "--compile-cache", cc]
    cli = [sys.executable, "-m",
           "fast_autoaugment_tpu.launch.search_cli"]
    env = dict(os.environ, JAX_PLATFORMS="cpu", FAA_COMPILE_CACHE=cc)
    env.pop("FAA_FAULT", None)

    # ---- the base search whose log both re-searches warm-start from
    base_dir = os.path.join(tmp, "base")
    r = subprocess.run(cli + flags + ["--save-dir", base_dir], env=env,
                       capture_output=True, text=True, timeout=900)
    assert r.returncode == 0, r.stderr[-3000:]

    # ---- arm A: the controller-host re-search (the PR-14 default)
    out_a = os.path.join(tmp, "research_host")
    seed_research_dir(base_dir, out_a)
    t0 = time.monotonic()
    r = subprocess.run(
        cli + flags + ["--save-dir", out_a, "--topup-trials", "2"],
        env=env, capture_output=True, text=True, timeout=900)
    host_wall = time.monotonic() - t0
    assert r.returncode == 0, r.stderr[-3000:]

    # ---- arm B: the SAME re-search through a learner+actor fleet
    out_b = os.path.join(tmp, "research_fleet")
    seed_research_dir(base_dir, out_b)
    tr = os.path.join(tmp, "transport")
    fleet_flags = flags + ["--save-dir", out_b, "--topup-trials", "2",
                           "--fleet-transport", tr, "--lease-ttl", "30"]
    t0 = time.monotonic()
    learner = subprocess.Popen(
        cli + fleet_flags + ["--search-role", "learner",
                             "--host-id", "0"],
        env=dict(env, FAA_HOST_ID="0"), stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    actor = subprocess.Popen(
        cli + fleet_flags + ["--search-role", "actor",
                             "--host-id", "1"],
        env=dict(env, FAA_HOST_ID="1"), stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    out_l = learner.communicate(timeout=900)[0]
    out_ac = actor.communicate(timeout=300)[0]
    fleet_wall = time.monotonic() - t0
    assert learner.returncode == 0, out_l[-3000:]
    assert actor.returncode == 0, out_ac[-3000:]

    # ---- byte-identity: the fleet path changes NOTHING --------------
    for name in ("final_policy.json", "search_trials.json"):
        assert (open(os.path.join(out_a, name), "rb").read()
                == open(os.path.join(out_b, name), "rb").read()), name
    res_a = json.load(open(os.path.join(out_a, "search_result.json")))
    res_b = json.load(open(os.path.join(out_b, "search_result.json")))
    assert res_a["warm_start"]["topup_trials"] == 2
    assert res_b["warm_start"] == res_a["warm_start"]
    # the base prefix is the base log verbatim, extended by the top-up
    base_log = json.load(open(os.path.join(base_dir,
                                           "search_trials.json")))
    log_b = json.load(open(os.path.join(out_b, "search_trials.json")))
    assert json.dumps(log_b["0"][:4]) == json.dumps(base_log["0"])
    assert len(log_b["0"]) == 6
    # the fleet really evaluated remotely: the actor posted rounds
    assert "fleet_transport" in res_b and "fleet_transport" not in res_a
    import bench

    print("RESEARCH_FLEET " + json.dumps({
        "research_fleet": {
            "host_wall_sec": round(host_wall, 1),
            "fleet_wall_sec": round(fleet_wall, 1),
            "topup_trials": 2,
            "single_core_caveat": True,
        }, **bench.telemetry_stamp()}))


# ----------------------------------------------------------- THE drill


def _http(host, port, method, path, body=None, headers=None,
          timeout=60.0):
    import http.client

    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request(method, path, body=body, headers=headers or {})
        resp = conn.getresponse()
        return resp.status, dict(resp.getheaders()), resp.read()
    finally:
        conn.close()


def _read_journal(tel_dir):
    records = []
    for path in sorted(glob.glob(
            os.path.join(tel_dir, "**", "journal-*.jsonl"),
            recursive=True)):
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict) and "type" in rec:
                    records.append(rec)
    return records


def test_drift_detect_research_canary_promote_drill(tmp_path):
    """The ISSUE-14 acceptance drill: seeded FAA_FAULT drift injection
    against a live 3-replica routed fleet triggers detect ->
    warm-started re-search -> canary -> promote with zero dropped
    requests during rollover, and the journal renders the full causal
    chain via trace_export + faa_status."""
    from fast_autoaugment_tpu.control.research import policy_file_digest
    from fast_autoaugment_tpu.search.driver import search_policies

    tmp = str(tmp_path)
    tel_dir = os.path.join(tmp, "telemetry")
    port_dir = os.path.join(tmp, "replicas")
    cc_dir = os.path.join(tmp, "compile-cache")
    base_dir = os.path.join(tmp, "base_search")
    conf_yaml = os.path.join(tmp, "conf.yaml")
    with open(conf_yaml, "w") as fh:
        fh.write(CONF_YAML)

    # ---- the one-shot search whose policy the fleet serves ----------
    conf = _tiny_conf()
    os.environ["FAA_COMPILE_CACHE"] = cc_dir  # warm every subprocess
    try:
        search_policies(conf, tmp, base_dir, cv_num=1, cv_ratio=0.4,
                        num_policy=1, num_op=1, num_search=4, num_top=1,
                        trial_batch=2, async_pipeline="on",
                        fold_quality_floor=None, seed=0,
                        compile_cache=cc_dir)
    finally:
        os.environ.pop("FAA_COMPILE_CACHE", None)
    baseline_policy = os.path.join(base_dir, "final_policy.json")
    baseline_digest = policy_file_digest(baseline_policy)

    procs = []
    failures = []
    ok_rows = []
    stop = threading.Event()
    try:
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   FAA_COMPILE_CACHE=cc_dir,
                   # the seeded drill fault: every replica's input
                   # stream shifts from its 12th coalesced dispatch on
                   FAA_FAULT="drift@dispatch=12,shift=60")
        env.pop("FAA_TELEMETRY", None)
        for i in range(3):
            env_i = dict(env, FAA_HOST_ID=str(i))
            procs.append(subprocess.Popen([
                sys.executable, "-m",
                "fast_autoaugment_tpu.serve.serve_cli",
                "--policy", baseline_policy, "--image", "8",
                "--shapes", "1,8", "--max-wait-ms", "2",
                # pinned: 'auto' would flip exact->grouped when the
                # candidate's sub-policy count crosses 1, and a reload
                # may not change dispatch mode (serving contract)
                "--dispatch", "exact",
                "--traffic-stats", "--telemetry", tel_dir,
                "--compile-cache", cc_dir,
                "--port", "0", "--port-dir", port_dir,
                "--host-tag", f"replica{i}",
            ], env=env_i, cwd=_REPO))
        from bench_router import wait_port_record, wait_ready

        ports = []
        for i in range(3):
            port = wait_port_record(port_dir, f"replica{i}", procs[i],
                                    600.0)
            wait_ready("127.0.0.1", port, procs[i], 600.0)
            ports.append(port)

        # ---- the router front door ------------------------------
        router_pf = os.path.join(tmp, "router.port")
        router_env = dict(env)
        router_env.pop("FAA_FAULT", None)
        router = subprocess.Popen([
            sys.executable, "-m",
            "fast_autoaugment_tpu.serve.router_cli",
            "--port-dir", port_dir, "--port", "0",
            "--port-file", router_pf, "--poll-interval", "0.2",
            "--telemetry", tel_dir,
        ], env=router_env, cwd=_REPO)
        procs.append(router)
        t0 = time.monotonic()
        while not os.path.exists(router_pf) \
                and time.monotonic() - t0 < 120:
            time.sleep(0.1)
        with open(router_pf) as fh:
            router_port = int(fh.read().strip())
        wait_ready("127.0.0.1", router_port, router, 120.0)

        # ---- the control loop: REAL warm-started re-search ------
        research_cmd = (
            f"{sys.executable} -m fast_autoaugment_tpu.launch.search_cli"
            f" -c {conf_yaml} --dataroot {tmp} --save-dir {{out}}"
            f" --num-fold 1 --num-search 4 --topup-trials 2"
            f" --num-policy 1 --num-op 1 --num-top 2 --trial-batch 2"
            f" --until 2 --fold-quality-floor off --audit-floor 0"
            f" --async-pipeline on --seed 0 --compile-cache {cc_dir}")
        stats_file = os.path.join(tmp, "control_stats.json")
        ctl_env = dict(env)
        ctl_env.pop("FAA_FAULT", None)
        ctl = subprocess.Popen([
            sys.executable, "-m",
            "fast_autoaugment_tpu.launch.control_cli",
            "--telemetry", tel_dir, "--port-dir", port_dir,
            "--router-url", f"http://127.0.0.1:{router_port}",
            "--baseline-policy", baseline_policy,
            "--base-search-dir", base_dir,
            "--research-cmd", research_cmd,
            "--candidate-dir", os.path.join(tmp, "research"),
            "--baseline-samples", "10",
            "--canary-replicas", "1", "--split-every", "2",
            "--gate-polls", "2", "--quality-margin", "10",
            "--min-arm-dispatches", "1",
            "--poll-interval", "0.3",
            "--reload-timeout", "600",
            "--stats-file", stats_file,
        ], env=ctl_env, cwd=_REPO)
        procs.append(ctl)

        # ---- continuous traffic through the router --------------
        rng = np.random.default_rng(0)
        pool = rng.integers(0, 256, (64, 8, 8, 3),
                            dtype=np.uint8).astype(np.float32)

        def _traffic():
            import io

            i = 0
            while not stop.is_set():
                batch = pool[(4 * i) % 48:(4 * i) % 48 + 4]
                buf = io.BytesIO()
                np.savez(buf, images=batch)
                try:
                    status, _h, _b = _http(
                        "127.0.0.1", router_port, "POST", "/augment",
                        body=buf.getvalue(), timeout=120.0)
                except OSError as e:
                    failures.append(f"transport: {e}")
                    continue
                if status == 200:
                    ok_rows.append(time.time())
                else:
                    failures.append(f"status {status}")
                i += 1

        client = threading.Thread(target=_traffic, daemon=True)
        client.start()

        # ---- wait for the promote event -------------------------
        deadline = time.monotonic() + 900
        promote = None
        while time.monotonic() < deadline and promote is None:
            if ctl.poll() is not None:
                raise AssertionError(
                    f"control_cli died early rc={ctl.returncode}")
            evs = _read_journal(tel_dir)
            promote = next((r for r in evs if r["type"] == "promote"),
                           None)
            time.sleep(1.0)
        assert promote is not None, "the loop never promoted"
        # a little post-promote traffic proves the fleet still serves
        time.sleep(3.0)
        stop.set()
        client.join(timeout=120)

        ctl.send_signal(15)
        ctl.wait(timeout=60)

        # every replica (still live) answers with the promoted digest
        # + provenance — the reload-verification surface, fleet-wide
        promoted_digest = promote["digest"]
        for i, port in enumerate(ports):
            _s, _h, body = _http("127.0.0.1", port, "GET", "/stats")
            st = json.loads(body)
            assert st["policy_digest"] == promoted_digest, f"replica{i}"
            assert st["policy_provenance"]["policy_digest"] == \
                promoted_digest
            assert st["traffic"]["samples"] > 0
    finally:
        stop.set()
        for proc in reversed(procs):
            if proc.poll() is None:
                try:
                    proc.send_signal(15)
                except ProcessLookupError:
                    pass
        deadline = time.monotonic() + 60
        for proc in procs:
            left = max(1.0, deadline - time.monotonic())
            try:
                proc.wait(timeout=left)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=10)

    # ---- ZERO dropped requests through the whole drill --------------
    assert not failures, failures[:10]
    assert len(ok_rows) > 20

    # ---- the causal chain, in order, from ONE journal ---------------
    evs = _read_journal(tel_dir)
    by_type = {}
    for r in evs:
        if r["type"] in ("drift", "research", "canary", "promote",
                         "rollback"):
            by_type.setdefault(r["type"], []).append(r)
    assert "rollback" not in by_type
    drift = by_type["drift"][0]
    research = by_type["research"][0]
    rollouts = [r for r in by_type["canary"]
                if r.get("action") == "rollout"]
    promote = by_type["promote"][0]
    assert drift["t_wall"] < research["t_wall"] \
        < rollouts[0]["t_wall"] < promote["t_wall"]
    assert drift["metric"] in ("input_mean", "reward_proxy")
    assert drift["stat"] > drift["threshold"]
    # the re-search really warm-started: its provenance names the base
    cand_dir = os.path.join(tmp, "research", "episode1")
    cand_result = json.load(open(
        os.path.join(cand_dir, "search_result.json")))
    assert cand_result["warm_start"]["topup_trials"] == 2
    assert cand_result["warm_start"]["resumed_trials_per_fold"]["0"] == 4
    prov = json.load(open(
        os.path.join(cand_dir, "final_policy.provenance.json")))
    assert prov["policy_digest"] == promote["digest"]
    assert prov["policy_digest"] != baseline_digest
    # base prefix of the candidate's trial log is the base log verbatim
    base_log = json.load(open(
        os.path.join(base_dir, "search_trials.json")))
    cand_log = json.load(open(
        os.path.join(cand_dir, "search_trials.json")))
    assert json.dumps(cand_log["0"][:4]) == json.dumps(base_log["0"])
    assert len(cand_log["0"]) == 6
    # the canary subset was the candidate digest's rendezvous prefix
    from fast_autoaugment_tpu.control.canary import select_canary_replicas

    expect = select_canary_replicas(
        promote["digest"], ["replica0", "replica1", "replica2"], 1)
    assert sorted({r["replica"] for r in rollouts}) == expect
    assert promote["drift_id"] == drift["id"]
    assert promote["detect_to_promote_sec"] > 0

    # the loop settled: one episode, one promote, monitor re-baselined
    stats = json.load(open(stats_file))
    assert stats["promotes"] == 1 and stats["rollbacks"] == 0
    assert stats["state"] == "watching"
    assert stats["baseline_digest"] == promote["digest"]
    assert not stats["monitor"]["latched"]

    # ---- make trace renders the chain; make status summarizes it ----
    trace_out = os.path.join(tmp, "trace.json")
    r = subprocess.run(
        [sys.executable, "tools/trace_export.py", "--telemetry",
         tel_dir, "--out", trace_out],
        cwd=_REPO, capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stderr[-1000:]
    trace = json.load(open(trace_out))
    names = {e["name"] for e in trace["traceEvents"] if e["ph"] == "i"}
    for marker in ("drift:", "research:", "canary:", "promote:"):
        assert any(n.startswith(marker) for n in names), (marker, names)
    r = subprocess.run(
        [sys.executable, "tools/faa_status.py", "--dir", tel_dir,
         "--json"],
        cwd=_REPO, capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stderr[-1000:]
    status = json.loads(r.stdout)
    assert status["control"]["promotes"] == 1
    assert status["control"]["last_decision"]["action"] == "promote"
    assert status["control"]["drift_verdict_total"] >= 1
