"""Unified telemetry (core/telemetry.py): registry semantics, the
flight-recorder journal, the span seam, Prometheus exposition, the TB
bridge, and the one-source-of-truth equality pins that keep artifact
stamps from drifting away from the counters the hot paths bump.

All host-only / no-XLA-compile (tier-1 discipline): the only jax
touched is import-time.
"""

import glob
import json
import os
import sys
import threading
import time
import urllib.request

import pytest

from fast_autoaugment_tpu.core import telemetry as T

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
sys.path.insert(0, os.path.join(_REPO, "tools"))


@pytest.fixture()
def journal_dir(tmp_path):
    """Arm the process journal in a tmp dir, detach afterwards."""
    d = str(tmp_path / "tel")
    T.enable_telemetry(d, tb_bridge=True)
    yield d
    T._disable_for_tests()


@pytest.fixture(autouse=True)
def _no_env_journal(monkeypatch):
    """An inherited FAA_TELEMETRY must not leak into these tests."""
    monkeypatch.delenv("FAA_TELEMETRY", raising=False)
    yield
    T._disable_for_tests()


def _read_records(directory):
    T.journal_flush()  # events are interval-buffered; force them out
    records = []
    for path in sorted(glob.glob(os.path.join(directory,
                                              "journal-*.jsonl"))):
        with open(path) as fh:
            records.extend(json.loads(ln) for ln in fh if ln.strip())
    records.sort(key=lambda r: r["seq"])
    return records


# ------------------------------------------------------------ registry


def test_counter_gauge_histogram_basics():
    reg = T.MetricsRegistry()
    c = reg.counter("faa_x_total", "x", label="a")
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5
    with pytest.raises(ValueError):
        c.inc(-1)
    g = reg.gauge("faa_g")
    g.set(7)
    g.inc(-2)
    assert g.value == 5.0
    h = reg.histogram("faa_h_seconds", buckets=(0.1, 1.0))
    for v in (0.05, 0.5, 5.0):
        h.observe(v)
    snap = h.snapshot()
    assert snap["count"] == 3
    assert snap["buckets"] == {"0.1": 1, "1": 2, "+Inf": 3}
    assert abs(snap["sum"] - 5.55) < 1e-9


def test_registry_get_or_create_and_label_children():
    reg = T.MetricsRegistry()
    a1 = reg.counter("faa_c_total", label="a")
    a2 = reg.counter("faa_c_total", label="a")
    b = reg.counter("faa_c_total", label="b")
    assert a1 is a2 and a1 is not b
    a1.inc()
    snap = reg.snapshot()
    assert snap["counters"]['faa_c_total{label="a"}'] == 1.0
    assert snap["counters"]['faa_c_total{label="b"}'] == 0.0


def test_registry_kind_and_bucket_conflicts_raise():
    reg = T.MetricsRegistry()
    reg.counter("faa_c_total")
    with pytest.raises(ValueError):
        reg.gauge("faa_c_total")
    reg.histogram("faa_h_seconds", buckets=(1.0, 2.0))
    with pytest.raises(ValueError):
        reg.histogram("faa_h_seconds", buckets=(5.0,))
    with pytest.raises(ValueError):
        reg.counter("not a name!")


def test_prometheus_text_exposition_format():
    reg = T.MetricsRegistry()
    reg.counter("faa_c_total", "the counter", label="x").inc(3)
    reg.gauge("faa_g").set(1.5)
    reg.histogram("faa_h_seconds", buckets=(0.1, 1.0),
                  label="y").observe(0.5)
    text = reg.prometheus_text()
    assert "# HELP faa_c_total the counter" in text
    assert "# TYPE faa_c_total counter" in text
    assert 'faa_c_total{label="x"} 3' in text
    assert "faa_g 1.5" in text
    assert '# TYPE faa_h_seconds histogram' in text
    assert 'faa_h_seconds_bucket{label="y",le="0.1"} 0' in text
    assert 'faa_h_seconds_bucket{label="y",le="1"} 1' in text
    assert 'faa_h_seconds_bucket{label="y",le="+Inf"} 1' in text
    assert 'faa_h_seconds_sum{label="y"} 0.5' in text
    assert 'faa_h_seconds_count{label="y"} 1' in text


def test_registry_reset_for_tests_keeps_registrations():
    reg = T.MetricsRegistry()
    c = reg.counter("faa_c_total")
    c.inc(5)
    reg._reset_for_tests()
    assert c.value == 0.0
    assert reg.counter("faa_c_total") is c


# ------------------------------------------------------------- journal


def test_emit_is_noop_when_off(tmp_path):
    assert not T.journal_active()
    T.emit("mark", "nothing-happens")  # must not raise or write


def test_journal_records_carry_identity_and_both_clocks(tmp_path,
                                                        monkeypatch):
    monkeypatch.setenv("FAA_HOST_ID", "7")
    monkeypatch.setenv("FAA_ATTEMPT", "3")
    d = str(tmp_path / "tel")
    T.enable_telemetry(d, tb_bridge=False)
    try:
        T.emit("mark", "hello", value=1.5)
    finally:
        T._disable_for_tests()
    (rec,) = _read_records(d)
    assert rec["type"] == "mark" and rec["label"] == "hello"
    assert rec["host"] == "host7" and rec["attempt"] == 3
    assert rec["pid"] == os.getpid() and rec["tid"] > 0
    assert rec["thread"] == threading.current_thread().name
    assert isinstance(rec["t_wall"], float)
    assert isinstance(rec["t_mono"], float)
    assert rec["value"] == 1.5
    assert "a3" in os.path.basename(glob.glob(
        os.path.join(d, "journal-*.jsonl"))[0])


def test_journal_taxonomy_is_closed(journal_dir):
    with pytest.raises(ValueError):
        T.emit("made_up_event")
    for etype in sorted(T.EVENT_TYPES):
        T.emit(etype, "ok")  # every documented type is accepted


def test_journal_segment_rotation_bounds_size(tmp_path):
    d = str(tmp_path / "tel")
    rec = T.FlightRecorder(d, max_segment_bytes=400, max_segments=3,
                           tb_bridge=False)
    for i in range(60):
        rec.emit("mark", "m", i=i)
    rec.close()
    segs = sorted(glob.glob(os.path.join(d, "journal-*.jsonl")))
    assert len(segs) == 3  # older segments were deleted
    total = sum(os.path.getsize(s) for s in segs)
    assert total < 3 * (400 + 400)  # bounded: ring, not an archive
    # the SURVIVING records are the newest ones, seq-contiguous
    records = _read_records(d)
    seqs = [r["seq"] for r in records]
    assert seqs == list(range(seqs[0], 60))


def test_env_handoff_and_resolve(monkeypatch, tmp_path):
    assert T.resolve_telemetry("off") is None
    assert T.resolve_telemetry(None) is None
    monkeypatch.setenv("FAA_TELEMETRY", str(tmp_path / "env"))
    assert T.resolve_telemetry(None) == str(tmp_path / "env")
    assert T.resolve_telemetry("off") == str(tmp_path / "env")
    explicit = str(tmp_path / "flag")
    assert T.resolve_telemetry(explicit) == explicit
    got = T.configure_telemetry(explicit)
    try:
        assert got == os.path.abspath(explicit)
        assert os.environ["FAA_TELEMETRY"] == got  # child-process handoff
        assert T.telemetry_dir() == got
    finally:
        T._disable_for_tests()


# ----------------------------------------------------------- span seam


def test_span_feeds_registry_trace_and_journal(journal_dir):
    reg = T.registry()
    c0 = reg.counter("faa_dispatches_total",
                     label="train_dispatch").value
    windows = []
    with T.span("train_dispatch", trace=lambda t0, t1: windows.append(
            (t0, t1)), step=4):
        time.sleep(0.01)
    assert len(windows) == 1 and windows[0][1] > windows[0][0]
    assert reg.counter("faa_dispatches_total",
                       label="train_dispatch").value == c0 + 1
    rec = [r for r in _read_records(journal_dir)
           if r["type"] == "dispatch"][-1]
    assert rec["label"] == "train_dispatch" and rec["step"] == 4
    assert rec["t_mono_end"] >= rec["t_mono_start"]
    assert abs(rec["dur_sec"]
               - (rec["t_mono_end"] - rec["t_mono_start"])) < 1e-6


def test_dispatch_journal_rate_bound_registry_stays_exact(tmp_path):
    """A kHz dispatch loop journals at most the per-label budget of
    slices per second (suppressed slices are counted), while the
    registry histogram observes EVERY dispatch — exact counts, bounded
    journal cost."""
    d = str(tmp_path / "tel")
    T.enable_telemetry(d, dispatch_events_per_sec=50, tb_bridge=False)
    reg = T.registry()
    hist = reg.histogram("faa_dispatch_seconds", label="rate_test")
    sup = reg.counter("faa_dispatch_events_suppressed_total",
                      label="rate_test")
    h0, s0 = hist.snapshot()["count"], sup.value
    try:
        for i in range(500):
            T.record_dispatch("rate_test", 1.0, 1.001, step=i)
        assert hist.snapshot()["count"] == h0 + 500  # registry: exact
        journaled = [r for r in _read_records(d)
                     if r["type"] == "dispatch"
                     and r["label"] == "rate_test"]
        # the tight loop runs well under a second: one 50-slice window
        assert len(journaled) <= 101
        assert sup.value - s0 == 500 - len(journaled) > 0
    finally:
        T._disable_for_tests()


def test_record_dispatch_histogram_observation():
    reg = T.registry()
    h = reg.histogram("faa_dispatch_seconds", label="unit_test_label")
    before = h.snapshot()["count"]
    T.record_dispatch("unit_test_label", 10.0, 10.5)
    snap = h.snapshot()
    assert snap["count"] == before + 1
    assert snap["sum"] >= 0.5


def test_phase_event_counter_and_journal(journal_dir):
    reg = T.registry()
    c = reg.counter("faa_phase_seconds_total", label="phase1-fold9")
    T.phase_event("phase1-fold9", 100.0, 101.5, fold=9, lane="phase1")
    assert abs(c.value - 1.5) < 1e-9
    rec = [r for r in _read_records(journal_dir)
           if r["type"] == "phase"][-1]
    assert rec["lane"] == "phase1" and rec["fold"] == 9


# ----------------------------------------------------------- TB bridge


def test_tb_bridge_crc_verified_roundtrip(journal_dir):
    from fast_autoaugment_tpu.utils.tb_events import read_events

    T.emit("trial", "fold0", fold=0, trial=5, reward=0.875, step=5)
    T.emit("trial", "fold0", fold=0, trial=6, reward=0.9, step=6)
    (tb_file,) = glob.glob(os.path.join(journal_dir, "tb",
                                        "events.out.tfevents.*"))
    events = read_events(tb_file, verify_crc=True)  # raises on bad CRC
    scalars = {(e.get("tag"), e.get("step")): e.get("value")
               for e in events if "tag" in e}
    assert abs(scalars[("trial/fold0/reward", 5)] - 0.875) < 1e-6
    assert abs(scalars[("trial/fold0/reward", 6)] - 0.9) < 1e-6
    # non-numeric and identity fields never become scalars
    assert all(not (tag or "").endswith("/host")
               for tag, _ in scalars)


# ------------------------------------------- one-source-of-truth pins


def test_compile_cache_stats_sourced_from_registry():
    from fast_autoaugment_tpu.core import compilecache as cc

    cc._reset_stats_for_tests()
    try:
        reg_hits = T.registry().counter("faa_compile_cache_hits_total")
        reg_misses = T.registry().counter("faa_compile_cache_misses_total")
        assert cc.compile_cache_stats()["hits"] == int(reg_hits.value) == 0
        cc._listener("/jax/compilation_cache/cache_hits")
        cc._listener("/jax/compilation_cache/cache_hits")
        cc._listener("/jax/compilation_cache/cache_misses")
        stats = cc.compile_cache_stats()
        assert stats["hits"] == int(reg_hits.value) == 2
        assert stats["misses"] == int(reg_misses.value) == 1
    finally:
        cc._reset_stats_for_tests()


def test_watchdog_fire_mirrors_registry_and_journal(journal_dir):
    from fast_autoaugment_tpu.core.watchdog import DispatchWatchdog

    wd = DispatchWatchdog(0.2, compile_allowance=0.2)
    ctr = T.registry().counter("faa_watchdog_fires_total",
                               label="unit_wd_label")
    before = ctr.value
    from fast_autoaugment_tpu.core.resilience import DispatchHungError

    with pytest.raises(DispatchHungError):
        wd.run("unit_wd_label", time.sleep, 5.0)
    assert wd.fires == 1
    assert ctr.value == before + 1
    rec = [r for r in _read_records(journal_dir)
           if r["type"] == "watchdog_fire"][-1]
    assert rec["label"] == "unit_wd_label"
    assert rec["deadline_sec"] == pytest.approx(0.2, abs=0.05)


def test_watchdog_ema_mirrors_registry_gauge():
    from fast_autoaugment_tpu.core.watchdog import DispatchWatchdog

    wd = DispatchWatchdog("auto")
    wd.observe("unit_ema_label", 0.5)
    wd.observe("unit_ema_label", 1.0)
    g = T.registry().gauge("faa_watchdog_ema_seconds",
                           label="unit_ema_label")
    assert g.value == pytest.approx(wd.ema("unit_ema_label"))


def test_breaker_fire_counts_and_journals(journal_dir):
    from fast_autoaugment_tpu.core.resilience import CircuitBreaker

    br = CircuitBreaker(threshold=2, cooldown_s=60.0, name="unit_breaker")
    ctr = T.registry().counter("faa_breaker_fires_total",
                               breaker="unit_breaker")
    br.record_failure()
    assert ctr.value == 0  # below threshold: no fire
    br.record_failure()
    assert br.fires == 1 and ctr.value == 1
    rec = [r for r in _read_records(journal_dir)
           if r["type"] == "breaker_fire"][-1]
    assert rec["label"] == "unit_breaker"
    assert rec["consecutive_failures"] == 2


def test_serve_counters_one_source_of_truth():
    import numpy as np

    from fast_autoaugment_tpu.serve.policy_server import (
        PolicyServer,
        ServerOverloadedError,
    )

    class _Applier:
        dispatch = "grouped"
        max_batch = 4
        image = 8
        channels = 3
        num_sub = 1
        shapes = (4,)

        def apply(self, images, keys):
            return images

    srv = PolicyServer(_Applier(), queue_depth=1)
    img = np.zeros((1, 8, 8, 3), np.float32)
    srv.submit(img)
    with pytest.raises(ServerOverloadedError):
        srv.submit(img)
    reg = T.registry()
    adm = reg.counter("faa_serve_robustness_total", counter="admitted",
                      server=srv._server_id)
    shed = reg.counter("faa_serve_robustness_total",
                       counter="shed_overload", server=srv._server_id)
    # attribute view == /stats view == registry child — one number
    assert srv.admitted == int(adm.value) == 1
    assert srv.shed_overload == int(shed.value) == 1
    assert srv.stats()["admission"]["admitted"] == 1
    assert srv.stats()["admission"]["shed_overload"] == 1
    srv.stop()


def test_lease_events_counters_and_journal(journal_dir, tmp_path):
    from fast_autoaugment_tpu.launch.workqueue import WorkQueue

    reg = T.registry()
    claims = reg.counter("faa_lease_events_total", action="claim")
    reclaims = reg.counter("faa_lease_events_total", action="reclaim")
    releases = reg.counter("faa_lease_events_total", action="release")
    c0, r0, d0 = claims.value, reclaims.value, releases.value

    q1 = WorkQueue(str(tmp_path / "wq"), "hostA", lease_ttl=0.05)
    assert q1.claim("u1")
    q2 = WorkQueue(str(tmp_path / "wq"), "hostB", lease_ttl=0.05)
    assert not q2.claim("u1")  # hostB observes the foreign lease...
    time.sleep(0.12)           # ...which sits unchanged past the TTL
    assert q2.claim("u1")  # reclaim (observer-local staleness)
    q2.release("u1", info={"ok": True})
    assert claims.value == c0 + 1
    assert reclaims.value == r0 + 1
    assert releases.value == d0 + 1
    recs = [r for r in _read_records(journal_dir) if r["type"] == "lease"]
    actions = [r["action"] for r in recs if r["label"] == "u1"]
    assert actions == ["claim", "reclaim", "release"]
    reclaim_rec = recs[[r["action"] for r in recs].index("reclaim")]
    assert reclaim_rec["reclaimed_from"] == "hostA"
    assert reclaim_rec["lease_attempt"] == 2


def test_checkpoint_events_on_save_and_load(journal_dir, tmp_path):
    import numpy as np

    from fast_autoaugment_tpu.core.checkpoint import (
        load_checkpoint,
        save_checkpoint,
    )

    reg = T.registry()
    saved = reg.counter("faa_checkpoints_saved_total")
    loaded = reg.counter("faa_checkpoints_loaded_total")
    s0, l0 = saved.value, loaded.value
    path = str(tmp_path / "ck" / "state.msgpack")
    state = {"w": np.arange(4, dtype=np.float32)}
    save_checkpoint(path, state, metadata={"epoch": 3})
    load_checkpoint(path, {"w": np.zeros(4, np.float32)})
    assert saved.value == s0 + 1 and loaded.value == l0 + 1
    recs = [r for r in _read_records(journal_dir)
            if r["type"] == "checkpoint"]
    assert [r["action"] for r in recs] == ["save", "load"]
    assert recs[0]["epoch"] == 3 and recs[0]["nbytes"] > 0


# ------------------------------------- profiling satellite (stopwatch)


def test_phase_stopwatch_mirrors_registry_gauges():
    from fast_autoaugment_tpu.utils.profiling import PhaseStopwatch

    reg = T.MetricsRegistry()
    sw = PhaseStopwatch(device_count=4, registry=reg)
    with sw.phase("unit_phase"):
        time.sleep(0.01)
    wall_g = reg.gauge("faa_phase_wall_seconds", phase="unit_phase")
    dev_g = reg.gauge("faa_phase_device_seconds", phase="unit_phase")
    assert wall_g.value == pytest.approx(sw.wall_seconds("unit_phase"))
    assert dev_g.value == pytest.approx(sw.device_seconds("unit_phase"))
    assert dev_g.value == pytest.approx(4 * wall_g.value)
    # accumulation: a second window updates BOTH views identically
    with sw.phase("unit_phase"):
        time.sleep(0.01)
    assert wall_g.value == pytest.approx(sw.wall_seconds("unit_phase"))


def test_phase1_attribution_identity_matches_stopwatch():
    """The device_secs_phase1_per_fold identity: the stamp is the
    attribution helper over the stopwatch ledger — per-fold phases
    credit directly, stacked groups split one measured wall evenly, and
    the registry gauges carry the same numbers."""
    from fast_autoaugment_tpu.search.driver import (
        phase1_device_seconds_attribution,
    )
    from fast_autoaugment_tpu.utils.profiling import PhaseStopwatch

    reg = T.MetricsRegistry()
    sw = PhaseStopwatch(device_count=2, registry=reg)
    with sw.phase("phase1_fold0"):
        time.sleep(0.01)
    with sw.phase("phase1_fold0"):  # a gate retrain accumulates
        time.sleep(0.01)
    with sw.phase("phase1_stack0"):  # folds 1+2 trained stacked
        time.sleep(0.02)
    attr = phase1_device_seconds_attribution(sw, [0, 1, 2], [[1, 2]])
    assert attr[0] == pytest.approx(sw.device_seconds("phase1_fold0"))
    assert attr[1] == attr[2] == pytest.approx(
        sw.device_seconds("phase1_stack0") / 2)
    assert attr[0] > 0 and attr[1] > 0
    # registry mirror: the gauge holds exactly the ledger's number
    assert reg.gauge("faa_phase_device_seconds",
                     phase="phase1_stack0").value == pytest.approx(
        sw.device_seconds("phase1_stack0"))


def test_step_timer_mirrors_registry_histogram():
    from fast_autoaugment_tpu.utils.profiling import StepTimer

    reg = T.MetricsRegistry()
    st = StepTimer(warmup=1, name="unit_steps", registry=reg)
    for _ in range(3):
        st.start()
        time.sleep(0.002)
        st.stop()
    h = reg.histogram("faa_step_seconds", timer="unit_steps")
    assert h.snapshot()["count"] == st.steps_timed == 2


# -------------------------------------------------- export surfaces


def test_metrics_http_server_scrape():
    T.registry().counter("faa_scrape_test_total").inc(3)
    httpd, port = T.start_metrics_server(0)
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10) as resp:
            assert resp.status == 200
            assert resp.headers["Content-Type"].startswith("text/plain")
            text = resp.read().decode()
        assert "faa_scrape_test_total 3" in text
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=10) as resp:
            assert resp.status == 200
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_bench_telemetry_stamp_unified_schema():
    import bench

    stamp = bench.telemetry_stamp([0.1], label="unit_stamp")
    assert stamp["schema_version"] == bench.TELEMETRY_STAMP_SCHEMA_VERSION
    assert set(stamp) == {"schema_version", "contention", "watchdog",
                          "compile_cache", "telemetry_counters"}
    assert "loadavg_1m" in stamp["contention"]
    assert stamp["watchdog"]["watchdog_deadline_sec"] is not None
    assert "hits" in stamp["compile_cache"]
    assert isinstance(stamp["telemetry_counters"], dict)
    # a pre-built per-row watchdog stamp rides through untouched
    wd = {"watchdog_fires": 7}
    assert bench.telemetry_stamp(watchdog=wd)["watchdog"] is wd


def test_faa_status_aggregates_journals_and_beats(tmp_path):
    from faa_status import fleet_status, render_table

    d = str(tmp_path)
    now = time.time()
    # host0: journal with dispatch windows + a watchdog fire
    rec = T.FlightRecorder(d, host="host0", attempt=1, tb_bridge=False)
    rec.emit("dispatch", "tta", t_mono_start=1.0, t_mono_end=2.0)
    rec.emit("dispatch", "tta", t_mono_start=2.5, t_mono_end=3.0)
    rec.emit("watchdog_fire", "tta", deadline_sec=1.0, waited_sec=2.0)
    rec.close()
    # heartbeats: host0 alive, host1 stale, host2 done
    os.makedirs(os.path.join(d, "hosts"))
    for owner, beat, done in (("host0", now, False),
                              ("host1", now - 600, False),
                              ("host2", now - 600, True)):
        with open(os.path.join(d, "hosts", f"{owner}.json"), "w") as fh:
            json.dump({"owner": owner, "heartbeat": beat, "done": done},
                      fh)
    # done markers: one reclaimed unit finished by host0
    os.makedirs(os.path.join(d, "done"))
    with open(os.path.join(d, "done", "p1-fold1.json"), "w") as fh:
        json.dump({"unit": "p1-fold1", "owner": "host0", "attempt": 2,
                   "reclaimed_from": "host1"}, fh)

    status = fleet_status(d, ttl=60.0, now=now)
    h0 = status["hosts"]["host0"]
    assert h0["dispatches"] == 2
    assert h0["busy_frac"] == pytest.approx(1.5 / 2.0)
    assert h0["gap_p50_ms"] == pytest.approx(500.0)
    assert h0["watchdog_fires"] == 1
    assert h0["beat"] == "alive"
    assert h0["units_done"] == 1
    assert status["hosts"]["host1"]["beat"].startswith("STALE")
    assert status["hosts"]["host2"]["beat"] == "done"
    assert status["reclaimed_units"] == [{
        "unit": "p1-fold1", "attempt": 2, "finished_by": "host0",
        "reclaimed_from": "host1"}]
    table = render_table(status)
    assert "host0" in table and "STALE" in table and "reclaimed" in table
