"""Real multi-process validation of the multi-host data-parallel path.

Launches TWO actual JAX processes (jax.distributed on localhost, 4
virtual CPU devices each -> an 8-device global mesh) and runs training
steps where each process feeds only its local shard of every global
batch — exercising `shard_batch`'s
``make_array_from_process_local_data`` branch and the per-process
`train_batches` sharding that single-process tests can't reach.
The replicas must report IDENTICAL losses (replicated state staying in
sync is the whole point of the DDP-equivalent design).
"""

import os
import socket
import subprocess
import sys

import pytest

_WORKER = r"""
import os, sys
proc_id = int(sys.argv[1]); port = sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
sys.path.insert(0, {repo!r})
import jax
jax.distributed.initialize(f"localhost:{{port}}", num_processes=2, process_id=proc_id)
import numpy as np, jax.numpy as jnp
from fast_autoaugment_tpu.models import get_model
from fast_autoaugment_tpu.ops.optim import build_optimizer
from fast_autoaugment_tpu.parallel.mesh import make_mesh, shard_batch
from fast_autoaugment_tpu.train.steps import create_train_state, make_train_step
from fast_autoaugment_tpu.data.pipeline import train_batches
from fast_autoaugment_tpu.data.datasets import ArrayDataset

assert jax.process_count() == 2 and len(jax.devices()) == 8
mesh = make_mesh()
model = get_model({{"type": "wresnet10_1"}}, 10)
opt = build_optimizer({{"type": "sgd", "decay": 1e-4, "clip": 5.0,
                        "momentum": 0.9, "nesterov": True}}, lambda s: 0.1)
state = create_train_state(model, opt, jax.random.PRNGKey(0),
                           jnp.zeros((2, 32, 32, 3)), use_ema=False)
step = make_train_step(model, opt, num_classes=10, use_policy=False)
rng = np.random.default_rng(0)
ds = ArrayDataset(rng.integers(0, 256, (64, 32, 32, 3), dtype=np.uint8),
                  rng.integers(0, 10, (64,), dtype=np.int32), 10)
losses = []
for images, labels in train_batches(ds, None, 16, epoch=1,
                                    process_index=proc_id, process_count=2):
    assert images.shape[0] == 8, "local shard must be global/2"
    batch = shard_batch(mesh, {{"x": images, "y": labels}})
    assert batch["x"].shape[0] == 16, "global batch must reassemble"
    state, metrics = step(state, batch["x"], batch["y"],
                          jnp.zeros((1, 1, 3), jnp.float32), jax.random.PRNGKey(1))
    losses.append(round(float(metrics["loss"]) / float(metrics["num"]), 6))
print("LOSSES", proc_id, losses, flush=True)

# eval path: each host feeds only its shard (no P-x duplicated device
# work, ADVICE round 1 medium); counts must reflect the REAL dataset
# size once, globally
from fast_autoaugment_tpu.data.pipeline import eval_batches
from fast_autoaugment_tpu.train.steps import make_eval_step
from fast_autoaugment_tpu.core.metrics import Accumulator

eval_ds = ArrayDataset(rng.integers(0, 256, (30, 32, 32, 3), dtype=np.uint8),
                       rng.integers(0, 10, (30,), dtype=np.int32), 10)
eval_step = make_eval_step(model, num_classes=10)
acc = Accumulator()
for images, labels, mask in eval_batches(eval_ds, None, 16, process_index=proc_id,
                                         process_count=2, pad_multiple=8):
    assert images.shape[0] == 8, "per-process shard of the padded global batch"
    batch = shard_batch(mesh, {{"x": images, "y": labels, "m": mask}})
    acc.add_dict(eval_step(state.params, state.batch_stats,
                           batch["x"], batch["y"], batch["m"]))
norm = acc.normalize()
assert int(acc["num"]) == 30, f"eval must count each sample once, got {{acc['num']}}"
print("EVAL", proc_id, round(norm["loss"], 6), round(norm["top1"], 6), flush=True)
"""


@pytest.mark.slow
def test_two_process_training_stays_in_sync(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    script = tmp_path / "worker.py"
    script.write_text(_WORKER.format(repo=repo))

    env = {k: v for k, v in os.environ.items() if k != "PALLAS_AXON_POOL_IPS"}
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(i), str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env, text=True,
        )
        for i in range(2)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=600)
        outs.append(out)
        assert p.returncode == 0, out[-2000:]

    losses, evals = {}, {}
    for out in outs:
        for line in out.splitlines():
            if line.startswith("LOSSES"):
                _tag, pid, vals = line.split(" ", 2)
                losses[pid] = vals
            elif line.startswith("EVAL"):
                _tag, pid, vals = line.split(" ", 2)
                evals[pid] = vals
    assert set(losses) == {"0", "1"}, outs
    # replicated training state: both processes observe identical losses
    assert losses["0"] == losses["1"]
    assert "2.3" in losses["0"]  # ~ln(10) at init on random labels
    # sharded eval: both processes assemble the same global metrics
    assert set(evals) == {"0", "1"}, outs
    assert evals["0"] == evals["1"]
