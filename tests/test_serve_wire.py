"""The zero-copy serving wire layer (fast_autoaugment_tpu/serve/wire.py)
and its HTTP integration (serve_cli raw/frames/shm lanes, keep-alive).

Fast half: pure codec/pool contracts — raw-format roundtrips are
zero-copy views, the arena recycles buffers, frames pack/unpack, the
connection pool reuses sockets, survives a stale keep-alive, and
refuses to replay once ANY request byte reached the wire (half-written
or fully-sent — both propagate, a replay could double-send).  Slow
half: through a live ``make_handler`` server — the raw format serves
the SAME BYTES as the legacy npz format, the batch endpoint scatters
per-part responses, the shm lane round-trips without image bytes on
the socket, and an oversized Content-Length is refused BEFORE the body
is buffered (with the connection closed so the keep-alive stream can't
desync).
"""

import io
import json
import threading

import numpy as np
import pytest

from fast_autoaugment_tpu.serve import wire

# ----------------------------------------------------------- raw codec


def test_raw_roundtrip_uint8_and_float32():
    for dtype in (np.uint8, np.float32):
        imgs = (np.arange(2 * 4 * 4 * 3) % 256).reshape(2, 4, 4, 3) \
            .astype(dtype)
        body = wire.encode_raw(imgs)
        got, seeds = wire.decode_raw(body)
        assert seeds is None
        assert got.dtype == np.dtype(dtype)
        np.testing.assert_array_equal(got, imgs)


def test_raw_roundtrip_with_seeds():
    imgs = np.zeros((3, 2, 2, 3), np.float32)
    keys = np.arange(6, dtype=np.uint32).reshape(3, 2)
    got, got_keys = wire.decode_raw(wire.encode_raw(imgs, seeds=keys))
    np.testing.assert_array_equal(got_keys, keys)
    np.testing.assert_array_equal(got, imgs)


def test_raw_decode_is_zero_copy_view():
    imgs = np.ones((2, 4, 4, 3), np.float32)
    body = wire.encode_raw(imgs)
    got, _ = wire.decode_raw(body)
    assert np.shares_memory(got, np.frombuffer(body, np.uint8))


def test_raw_decode_rejects_bad_payloads():
    imgs = np.ones((1, 2, 2, 3), np.uint8)
    ok = wire.encode_raw(imgs)
    with pytest.raises(ValueError, match="magic"):
        wire.decode_raw(b"NOPE!\n" + ok[len(wire.RAW_MAGIC):])
    with pytest.raises(ValueError, match="truncated"):
        wire.decode_raw(ok[:-4])
    evil = wire.RAW_MAGIC + json.dumps(
        {"dtype": "object", "shape": [1, 2, 2, 3], "seeds": 0}).encode() \
        + b"\n" + b"\x00" * 64
    with pytest.raises(ValueError, match="dtype"):
        wire.decode_raw(evil)
    evil = wire.RAW_MAGIC + json.dumps(
        {"dtype": "uint8", "shape": [2, 2], "seeds": 0}).encode() \
        + b"\n" + b"\x00" * 64
    with pytest.raises(ValueError, match="shape"):
        wire.decode_raw(evil)


def test_encode_raw_into_matches_encode_raw_with_fused_cast():
    arena = wire.BufferArena()
    out = np.linspace(0, 255, 2 * 3 * 3 * 3, dtype=np.float32) \
        .reshape(2, 3, 3, 3)
    view, lease = wire.encode_raw_into(arena, out, as_dtype=np.uint8)
    want = wire.encode_raw(out.astype(np.uint8))
    assert bytes(view) == want
    view = None  # release the memoryview before the lease goes back
    arena.checkin(lease)


# --------------------------------------------------------------- arena


def test_arena_recycles_buffers():
    arena = wire.BufferArena()
    a = arena.checkout(1000)
    arena.checkin(a)
    b = arena.checkout(900)  # same power-of-two class
    assert b is a
    assert arena.stats()["hits"] == 1


def test_arena_is_bounded_per_class():
    arena = wire.BufferArena(max_per_class=1)
    a, b = arena.checkout(100), arena.checkout(100)
    arena.checkin(a)
    arena.checkin(b)  # over the bound: dropped, not pooled
    assert arena.stats()["pooled"] == 1


# -------------------------------------------------------------- frames


def test_frames_roundtrip():
    parts = [({"ctype": "a"}, b"hello"), ({"status": 200}, b""),
             ({"k": 1}, b"\x00\x01\x02")]
    got = wire.decode_frames(wire.encode_frames(parts))
    assert [(m, bytes(b)) for m, b in got] \
        == [(m, b) for m, b in parts]


def test_frames_reject_garbage():
    with pytest.raises(ValueError, match="magic"):
        wire.decode_frames(b"whatever")
    ok = wire.encode_frames([({}, b"abcdef")])
    with pytest.raises(ValueError, match="truncated"):
        wire.decode_frames(ok[:-3])


# ----------------------------------------------------------- shm codec


def test_shm_descriptor_roundtrip():
    body = wire.encode_shm_request("psm_x", "float32", (2, 4, 4, 3),
                                   seeds=np.arange(4).reshape(2, 2))
    name, dtype, shape, seeds = wire.decode_shm_request(body)
    assert (name, dtype, shape) == ("psm_x", np.float32, (2, 4, 4, 3))
    np.testing.assert_array_equal(
        seeds, np.arange(4, dtype=np.uint32).reshape(2, 2))
    with pytest.raises(ValueError, match="dtype"):
        wire.decode_shm_request(wire.encode_shm_request(
            "x", "complex128", (1, 2, 2, 3)))


# ------------------------------------------------------ connection pool


def _tiny_server():
    """Minimal HTTP/1.1 keep-alive server for pool tests."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class H(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def do_GET(self):
            body = b"ok"
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    return httpd


def test_pool_reuses_connections_and_sets_nodelay():
    import socket

    httpd = _tiny_server()
    try:
        port = httpd.server_address[1]
        pool = wire.ConnectionPool(timeout_s=10.0)
        for _ in range(3):
            status, _h, body = pool.request("127.0.0.1", port, "GET", "/")
            assert (status, body) == (200, b"ok")
        st = pool.stats()
        assert st["opens"] == 1 and st["reuses"] == 2
        conn = pool._idle[("127.0.0.1", port)][0]
        assert conn.sock.getsockopt(socket.IPPROTO_TCP,
                                    socket.TCP_NODELAY) != 0
        pool.close_all()
        assert pool.stats()["idle"] == 0
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_pool_retries_stale_keepalive_once():
    httpd = _tiny_server()
    try:
        port = httpd.server_address[1]
        pool = wire.ConnectionPool(timeout_s=10.0)
        assert pool.request("127.0.0.1", port, "GET", "/")[0] == 200
        # sever the pooled socket behind the pool's back — the next
        # request must transparently retry on a fresh connection
        pool._idle[("127.0.0.1", port)][0].sock.close()
        status, _h, body = pool.request("127.0.0.1", port, "GET", "/")
        assert (status, body) == (200, b"ok")
        assert pool.stats()["opens"] == 2
        pool.close_all()
    finally:
        httpd.shutdown()
        httpd.server_close()


def _counting_server():
    """Keep-alive server that records every request it fully parsed —
    the ground truth for double-send assertions."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    served = []

    class H(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def _serve(self):
            n = int(self.headers.get("Content-Length", 0) or 0)
            self.rfile.read(n)
            served.append((self.command, self.path))
            body = b"ok"
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        do_GET = do_POST = _serve

        def log_message(self, *a):
            pass

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    return httpd, served


class _PartialSendSock:
    """Socket wrapper that lets the first ``limit`` bytes through and
    then dies mid-write — a half-written request on the wire."""

    def __init__(self, real, limit):
        self._real = real
        self._limit = limit

    def send(self, data):
        if self._limit <= 0:
            raise ConnectionResetError("injected mid-write failure")
        n = self._real.send(memoryview(data)[:self._limit])
        self._limit -= n
        return n

    def __getattr__(self, name):
        return getattr(self._real, name)


class _DeadResponseSock:
    """Socket wrapper that sends fine but hands back an empty response
    stream — the peer vanished AFTER the request was fully written."""

    def __init__(self, real):
        self._real = real

    def send(self, data):
        return self._real.send(data)

    def makefile(self, *a, **k):
        return io.BytesIO(b"")

    def __getattr__(self, name):
        return getattr(self._real, name)


def test_pool_no_retry_after_partial_body_write():
    # a reused connection that dies with part of the request already on
    # the wire must NOT be replayed: the server may be processing the
    # half it saw, and a replay risks a double-send
    httpd, served = _counting_server()
    try:
        port = httpd.server_address[1]
        pool = wire.ConnectionPool(timeout_s=10.0)
        assert pool.request("127.0.0.1", port, "GET", "/")[0] == 200
        conn = pool._idle[("127.0.0.1", port)][0]
        conn.sock = _PartialSendSock(conn.sock, limit=8)
        with pytest.raises(ConnectionResetError):
            pool.request("127.0.0.1", port, "POST", "/augment",
                         b"x" * 64)
        # no retry happened: no fresh socket was opened for the failed
        # attempt, and the server never parsed a second request
        assert pool.stats()["opens"] == 1
        assert served == [("GET", "/")]
        # the pool itself is healthy — the next request opens fresh
        assert pool.request("127.0.0.1", port, "GET", "/")[0] == 200
        assert pool.stats()["opens"] == 2
        assert len(served) == 2
        pool.close_all()
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_pool_no_retry_after_request_fully_sent():
    # response-stage failures are NOT the stale-keep-alive case either:
    # the request reached the server, so a replay would double-send it
    import http.client

    httpd, served = _counting_server()
    try:
        port = httpd.server_address[1]
        pool = wire.ConnectionPool(timeout_s=10.0)
        assert pool.request("127.0.0.1", port, "POST", "/augment",
                            b"y" * 32)[0] == 200
        conn = pool._idle[("127.0.0.1", port)][0]
        conn.sock = _DeadResponseSock(conn.sock)
        with pytest.raises(http.client.RemoteDisconnected):
            pool.request("127.0.0.1", port, "POST", "/augment",
                         b"y" * 32)
        assert pool.stats()["opens"] == 1
        # the server DID see the doomed request exactly once — and no
        # replay of it ever arrived
        deadline = threading.Event()
        deadline.wait(0.2)
        assert served == [("POST", "/augment"), ("POST", "/augment")]
        pool.close_all()
    finally:
        httpd.shutdown()
        httpd.server_close()


# ------------------------------------------------- shm lane lifecycle


def test_shm_region_unlinks_on_close():
    import os

    region = wire.ShmRegion((2, 4, 4, 3), np.float32)
    path = f"/dev/shm/{region.name}"
    if not os.path.exists(path):
        pytest.skip("shm segments not backed by /dev/shm here")
    region.close()
    assert not os.path.exists(path)
    region.close()  # idempotent


# --------------------------------------------- HTTP integration (slow)


IMG = 8
SINGLE_SUB = np.array([[[4, 0.8, 0.7], [10, 0.5, 0.3]]], np.float32)


@pytest.fixture(scope="module")
def live_server():
    """One module-scoped serve_cli handler stack over a real
    PolicyServer (shm lane armed, small body cap) — shared so the AOT
    compile is paid once."""
    from http.server import ThreadingHTTPServer

    from fast_autoaugment_tpu.serve.policy_server import (
        AotPolicyApplier,
        PolicyServer,
    )
    from fast_autoaugment_tpu.serve.serve_cli import make_handler

    applier = AotPolicyApplier(SINGLE_SUB, image=IMG, shapes=(4,))
    srv = PolicyServer(applier, max_wait_ms=2).start()
    httpd = ThreadingHTTPServer(
        ("127.0.0.1", 0),
        make_handler(srv, applier, max_body_bytes=256 * 1024,
                     shm_ingest=True))
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield httpd.server_address[1], applier
    httpd.shutdown()
    httpd.server_close()
    srv.stop()


def _seeded_bodies(n=3):
    import jax

    rng = np.random.default_rng(3)
    imgs = rng.integers(0, 256, (n, IMG, IMG, 3), dtype=np.uint8)
    seeds = np.arange(n)
    keys = np.asarray(jax.vmap(jax.random.PRNGKey)(
        np.asarray(seeds, np.int64) & 0x7FFFFFFF), np.uint32)
    buf = io.BytesIO()
    np.savez(buf, images=imgs, seeds=seeds)
    return imgs, keys, buf.getvalue(), wire.encode_raw(imgs, seeds=keys)


@pytest.mark.slow
def test_raw_and_npz_serve_identical_bytes(live_server):
    port, _applier = live_server
    _imgs, _keys, npz_body, raw_body = _seeded_bodies()
    pool = wire.ConnectionPool(timeout_s=60.0)
    try:
        s1, h1, npz_resp = pool.request(
            "127.0.0.1", port, "POST", "/augment", npz_body,
            {"Content-Type": "application/octet-stream"})
        s2, h2, raw_resp = pool.request(
            "127.0.0.1", port, "POST", "/augment", raw_body,
            {"Content-Type": wire.RAW_CONTENT_TYPE})
        assert s1 == 200 and s2 == 200
        assert h2["Content-Type"] == wire.RAW_CONTENT_TYPE
        npz_imgs = np.load(io.BytesIO(npz_resp))["images"]
        raw_imgs, _ = wire.decode_raw(raw_resp)
        assert raw_imgs.dtype == np.uint8
        np.testing.assert_array_equal(np.asarray(raw_imgs), npz_imgs)
        # the whole exchange rode ONE keep-alive connection
        assert pool.stats()["opens"] == 1
    finally:
        pool.close_all()


@pytest.mark.slow
def test_batch_endpoint_scatters_per_part(live_server):
    port, _applier = live_server
    _imgs, _keys, npz_body, raw_body = _seeded_bodies()
    frames = wire.encode_frames([
        ({"ctype": wire.RAW_CONTENT_TYPE}, raw_body),
        ({"ctype": "application/octet-stream"}, npz_body),
    ])
    pool = wire.ConnectionPool(timeout_s=60.0)
    try:
        status, headers, resp = pool.request(
            "127.0.0.1", port, "POST", "/augment_batch", frames,
            {"Content-Type": wire.FRAME_CONTENT_TYPE})
        assert status == 200
        assert headers["Content-Type"] == wire.FRAME_CONTENT_TYPE
        parts = wire.decode_frames(resp)
        assert len(parts) == 2
        assert all(m["status"] == 200 for m, _ in parts)
        raw_imgs, _ = wire.decode_raw(bytes(parts[0][1]))
        npz_imgs = np.load(io.BytesIO(bytes(parts[1][1])))["images"]
        np.testing.assert_array_equal(np.asarray(raw_imgs), npz_imgs)
    finally:
        pool.close_all()


@pytest.mark.slow
def test_shm_lane_roundtrip(live_server):
    port, _applier = live_server
    imgs, keys, npz_body, _raw = _seeded_bodies()
    region = wire.ShmRegion((imgs.shape[0], IMG, IMG, 3), np.float32)
    pool = wire.ConnectionPool(timeout_s=60.0)
    try:
        region.write(imgs.astype(np.float32))
        status, _h, resp = pool.request(
            "127.0.0.1", port, "POST", "/augment",
            region.request_body(seeds=keys),
            {"Content-Type": wire.SHM_CONTENT_TYPE})
        assert status == 200, resp
        echo = json.loads(resp)
        assert echo["shm"] == region.name
        got = region.read_result()
        # same bytes as the npz lane for the same seeded batch
        s2, _h2, npz_resp = pool.request(
            "127.0.0.1", port, "POST", "/augment", npz_body,
            {"Content-Type": "application/octet-stream"})
        assert s2 == 200
        np.testing.assert_array_equal(
            got, np.load(io.BytesIO(npz_resp))["images"])
    finally:
        pool.close_all()
        region.close()


@pytest.mark.slow
def test_oversized_body_refused_before_read(live_server):
    import http.client

    port, _applier = live_server
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    try:
        # declare a body far over the cap but never send it: the 413
        # must arrive up front (pre-buffering) and close the connection
        conn.putrequest("POST", "/augment")
        conn.putheader("Content-Length", str(512 * 1024 * 1024))
        conn.putheader("Content-Type", "application/octet-stream")
        conn.endheaders()
        resp = conn.getresponse()
        assert resp.status == 413
        assert json.loads(resp.read())["type"] == "body_too_large"
        assert resp.getheader("Connection") == "close"
    finally:
        conn.close()


@pytest.mark.slow
def test_shm_error_path_releases_server_mapping(live_server):
    """A rejected shm request must not strand the SERVER's mapping of
    the client's segment — under a flash crowd a pinned mapping per
    shed request is a real /dev/shm memory leak."""
    import os
    import time

    port, _applier = live_server
    n = 5  # one over the applier's max AOT shape -> submit refuses
    region = wire.ShmRegion((n, IMG, IMG, 3), np.float32)
    path = f"/dev/shm/{region.name}"
    if not os.path.exists(path):
        pytest.skip("shm segments not backed by /dev/shm here")

    def mapped() -> int:
        with open("/proc/self/maps") as fh:
            return sum(1 for ln in fh if region.name in ln)

    pool = wire.ConnectionPool(timeout_s=60.0)
    try:
        rng = np.random.default_rng(5)
        region.write(rng.random((n, IMG, IMG, 3), dtype=np.float32))
        base = mapped()  # our own client-side mapping(s)
        keys = np.arange(2 * n, dtype=np.uint32).reshape(n, 2)
        status, _h, resp = pool.request(
            "127.0.0.1", port, "POST", "/augment",
            region.request_body(seeds=keys),
            {"Content-Type": wire.SHM_CONTENT_TYPE})
        assert status == 400, resp
        assert json.loads(resp)["type"] == "bad_request"
        # the handler's finally must drop its view and close its map;
        # poll briefly — the client can read the response a beat
        # before the server thread reaches its finally
        deadline = time.monotonic() + 5.0
        while mapped() > base and time.monotonic() < deadline:
            time.sleep(0.05)
        assert mapped() <= base
    finally:
        pool.close_all()
        region.close()
    assert not os.path.exists(path)
