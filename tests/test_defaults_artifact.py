"""Defaults-safety regression over the COMMITTED round-4 e2e artifact
(VERDICT r3, next-step 3): a user running the documented CLI with pure
defaults on the pose task must get a non-destructive policy set.  The
artifact is produced by `tools/run_search_e2e_r4.sh` (full 3-phase
search, no guard flags) and committed; this test pins its meaning so a
future defaults regression cannot silently ship.
"""

import json
import os

import pytest

ARTIFACT = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                        "search_e2e_r4_defaults", "search_result.json")


@pytest.fixture(scope="module")
def artifact():
    if not os.path.exists(ARTIFACT):
        pytest.skip("round-4 defaults e2e artifact not present (run "
                    "tools/run_search_e2e_r4.sh)")
    with open(ARTIFACT) as fh:
        return json.load(fh)


def test_artifact_used_cli_defaults(artifact):
    """The artifact must certify DEFAULT guard settings — the exact
    values build_parser ships — otherwise it proves nothing about the
    out-of-the-box behavior."""
    from fast_autoaugment_tpu.launch.search_cli import build_parser
    from fast_autoaugment_tpu.search.driver import resolve_quality_floor

    args = build_parser().parse_args(["-c", "x.yaml"])
    guards = artifact["guards"]
    assert guards["audit_floor"] == args.audit_floor == 0.95
    assert guards["fold_quality_floor"] == pytest.approx(
        resolve_quality_floor(args.fold_quality_floor, 10))


def test_defaults_do_not_select_destructive_policies(artifact):
    """The round-2 failure mode (augmented accuracy collapsing to
    chance while default trains fine) must be impossible at defaults:
    augmented mean >= default mean - 1pt (sampling-noise allowance) and
    far above chance."""
    d = artifact["phase3"]["default"]["mean"]
    a = artifact["phase3"]["augment"]["mean"]
    assert a >= d - 0.01, f"augmented {a:.4f} vs default {d:.4f}"
    assert a > 0.5, f"augmented accuracy {a:.4f} is chance-level"


def test_artifact_quantifies_the_comparison(artifact):
    """Per-seed values, std and a paired test with >=8 seeds per mode
    (VERDICT r3, next-step 4)."""
    p3 = artifact["phase3"]
    assert p3["num_runs"] >= 8
    for mode in ("default", "augment"):
        assert len(p3[mode]["per_seed"]) == p3["num_runs"]
        assert p3[mode]["std"] > 0.0
    paired = p3["paired_augment_minus_default"]
    assert paired["n"] == p3["num_runs"]
    assert 0.0 <= paired["p_value"] <= 1.0


def test_zero_recompiles_across_all_trials(artifact):
    """Policy-as-tensor TTA (SURVEY.md hard-part 3): the executable
    count must not GROW between the first trial and the end of phase 2
    — i.e. zero recompiles across all folds x trials.  The absolute
    count is 2, not 1: the fold-quality gate's identity-policy baseline
    is a [1, num_op, 3] tensor while every candidate is [num_policy*
    num_op/num_op...] shaped [5, 2, 3], so the gate compiles its own
    executable once, before any trial."""
    assert artifact["tta_executables"] == artifact["tta_executables_first"] == 2
