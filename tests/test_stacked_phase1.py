"""Fold-stacked phase-1 training: vmapped K-model train step, the
multiplexed per-fold data feed, the fold mesh, seeded stacked-vs-
sequential equivalence, driver wiring (--fold-stack), device-seconds
attribution, and the prefetch failure paths the pipeline relies on."""

import json
import os
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fast_autoaugment_tpu.core.config import Config


def _conf(**over):
    base = {
        "model": {"type": "wresnet10_1"},
        "dataset": "synthetic",
        "aug": "default",
        "cutout": 8,
        "batch": 8,
        "epoch": 1,
        "lr": 0.05,
        "lr_schedule": {"type": "cosine", "warmup": {"multiplier": 2, "epoch": 1}},
        "optimizer": {"type": "sgd", "decay": 2e-4, "clip": 5.0,
                      "momentum": 0.9, "nesterov": True},
    }
    base.update(over)
    return Config(base)


# --------------------------------------------------- stacked data feed

def test_stacked_train_batches_match_sequential_streams():
    """Fold k's stream out of the multiplexed iterator must equal
    train_batches' for (indices[k], seeds[k]) EXACTLY — the property
    that makes stacked training consume bit-identical per-fold data."""
    from fast_autoaugment_tpu.data.datasets import ArrayDataset
    from fast_autoaugment_tpu.data.pipeline import (
        stacked_train_batches,
        train_batches,
    )

    rng = np.random.default_rng(0)
    ds = ArrayDataset(rng.integers(0, 256, (64, 4, 4, 3), dtype=np.uint8),
                      rng.integers(0, 10, (64,), np.int32), 10)
    folds = [np.arange(0, 40), np.arange(20, 60)]
    seeds = [0, 7]
    stacked = list(stacked_train_batches(ds, folds, 8, epoch=3, seeds=seeds))
    assert len(stacked) == 5  # 40 // 8
    for k in range(2):
        seq = list(train_batches(ds, folds[k], 8, epoch=3, seed=seeds[k]))
        assert len(seq) == len(stacked)
        for (sx, sy, sa), (qx, qy) in zip(stacked, seq):
            assert sa[k] == 1.0
            np.testing.assert_array_equal(sx[k], qx)
            np.testing.assert_array_equal(sy[k], qy)


def test_stacked_train_batches_uneven_folds_mask_out():
    """A fold with fewer steps goes active=0 on its exhausted lanes —
    the stacked shape never changes, the mask carries correctness."""
    from fast_autoaugment_tpu.data.datasets import ArrayDataset
    from fast_autoaugment_tpu.data.pipeline import stacked_train_batches

    rng = np.random.default_rng(1)
    ds = ArrayDataset(rng.integers(0, 256, (64, 4, 4, 3), dtype=np.uint8),
                      rng.integers(0, 10, (64,), np.int32), 10)
    folds = [np.arange(32), np.arange(16)]  # 4 vs 2 steps at batch 8
    out = list(stacked_train_batches(ds, folds, 8, epoch=1, seeds=[0, 0]))
    assert len(out) == 4
    actives = np.stack([a for _, _, a in out])
    np.testing.assert_array_equal(actives[:, 0], [1, 1, 1, 1])
    np.testing.assert_array_equal(actives[:, 1], [1, 1, 0, 0])
    assert all(x.shape == (2, 8, 4, 4, 3) for x, _, _ in out)


# ----------------------------------------------------------- fold mesh

def test_make_fold_mesh_sharding_rule(devices8):
    """The fold->mesh mapping rule: gcd(K, n_devices) fold shards, the
    rest on the data axis — devices >= K shard folds instead of
    replicating when the counts divide."""
    from fast_autoaugment_tpu.parallel.mesh import make_fold_mesh

    m = make_fold_mesh(4, devices8)  # 8 devices, K=4 -> (4, 2)
    assert m.shape["fold"] == 4 and m.shape["data"] == 2
    m = make_fold_mesh(5, devices8)  # coprime -> pure vmap stacking
    assert m.shape["fold"] == 1 and m.shape["data"] == 8
    m = make_fold_mesh(2, devices8, fold_shards=1)  # explicit override
    assert m.shape["fold"] == 1 and m.shape["data"] == 8
    m = make_fold_mesh(3, devices8[:1])  # single device
    assert m.shape["fold"] == 1 and m.shape["data"] == 1
    with pytest.raises(ValueError, match="does not divide"):
        make_fold_mesh(4, devices8, fold_shards=3)


def test_stacked_step_matches_sequential_per_step(devices8):
    """One stacked step from identical states equals K sequential steps
    to within the documented ~1 f32 ULP batched-kernel bound, and
    inactive lanes pass state through bit-for-bit unchanged."""
    from fast_autoaugment_tpu.models import get_model
    from fast_autoaugment_tpu.ops.optim import build_optimizer
    from fast_autoaugment_tpu.train.steps import (
        create_train_state,
        make_stacked_train_step,
        make_train_step,
        slice_state,
        stack_states,
    )

    model = get_model({"type": "wresnet10_1"}, 10)
    opt_conf = {"type": "sgd", "decay": 2e-4, "clip": 5.0, "momentum": 0.9,
                "nesterov": True}
    sample = jnp.zeros((2, 32, 32, 3), jnp.float32)
    kw = dict(num_classes=10, cutout_length=8, use_policy=False)
    K = 3

    def states():
        opt = build_optimizer(opt_conf, lambda s: 0.05)
        return [create_train_state(model, opt, jax.random.PRNGKey(k), sample,
                                   use_ema=False) for k in range(K)]

    opt = build_optimizer(opt_conf, lambda s: 0.05)
    seq_step = make_train_step(model, opt, **kw)
    st_step = make_stacked_train_step(model, opt, **kw)

    rng = np.random.default_rng(0)
    images = rng.integers(0, 256, (K, 8, 32, 32, 3), dtype=np.uint8)
    labels = rng.integers(0, 10, (K, 8), np.int32)
    pol = jnp.zeros((1, 1, 3), jnp.float32)
    keys = jnp.stack([jax.random.PRNGKey(100 + k) for k in range(K)])

    seq = states()
    seq_out = [seq_step(seq[k], jnp.asarray(images[k]),
                        jnp.asarray(labels[k]), pol, keys[k])
               for k in range(K)]
    stacked, metrics = st_step(stack_states(states()), jnp.asarray(images),
                               jnp.asarray(labels), pol, keys,
                               jnp.ones((K,), jnp.float32))
    for k in range(K):
        want_state, want_metrics = seq_out[k]
        got = slice_state(stacked, k)
        for a, b in zip(jax.tree.leaves(want_state.params),
                        jax.tree.leaves(got.params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)
        for a, b in zip(jax.tree.leaves(want_state.batch_stats),
                        jax.tree.leaves(got.batch_stats)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)
        assert float(metrics["num"][k]) == float(want_metrics["num"])
        assert float(metrics["top1"][k]) == float(want_metrics["top1"])

    # inactive lanes: state passes through UNTOUCHED (bitwise), metrics
    # zeroed — a masked lane is indistinguishable from not stepping
    base = stack_states(states())
    frozen, m0 = st_step(base, jnp.asarray(images), jnp.asarray(labels), pol,
                         keys, jnp.asarray([1.0, 0.0, 1.0], jnp.float32))
    ref = states()[1]
    for a, b in zip(jax.tree.leaves(ref.params),
                    jax.tree.leaves(slice_state(frozen, 1).params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert int(slice_state(frozen, 1).step) == 0
    assert float(m0["num"][1]) == 0.0
    assert int(slice_state(frozen, 0).step) == 1


# ------------------------------------------- trainer-level equivalence

def test_train_folds_stacked_matches_sequential(tmp_path, devices8):
    """Seeded equivalence at matched data-axis device count: per-fold
    params/batch_stats from the stacked trainer match sequential
    train_and_eval within the documented bound (ULP-level per-step
    kernel reduction-order differences, amplified over the run — the
    same deviation class as the committed 1-vs-8-device tolerance),
    checkpoints land under the same layout, and eval metrics agree."""
    from fast_autoaugment_tpu.core.checkpoint import load_checkpoint, read_metadata
    from fast_autoaugment_tpu.models import get_model
    from fast_autoaugment_tpu.ops.optim import build_optimizer
    from fast_autoaugment_tpu.parallel.mesh import make_fold_mesh, make_mesh
    from fast_autoaugment_tpu.train.steps import create_train_state
    from fast_autoaugment_tpu.train.trainer import train_and_eval, train_folds_stacked

    conf = _conf()
    tmp = str(tmp_path)
    seq_paths = [os.path.join(tmp, f"seq{f}.msgpack") for f in (0, 1)]
    st_paths = [os.path.join(tmp, f"st{f}.msgpack") for f in (0, 1)]
    for f in (0, 1):
        train_and_eval(conf, tmp, test_ratio=0.4, cv_fold=f,
                       save_path=seq_paths[f], metric="last", seed=0,
                       evaluation_interval=1, mesh=make_mesh(devices8))
    res = train_folds_stacked(
        conf, tmp, cv_ratio=0.4, folds=[0, 1], save_paths=st_paths, seed=0,
        evaluation_interval=1, mesh=make_fold_mesh(2, devices8, fold_shards=1),
    )

    model = get_model({"type": "wresnet10_1"}, 10)
    opt = build_optimizer(dict(conf["optimizer"]), lambda s: 0.0)
    tmpl = create_train_state(model, opt, jax.random.PRNGKey(0),
                              jnp.zeros((2, 32, 32, 3)), use_ema=False)
    for f in (0, 1):
        a = load_checkpoint(seq_paths[f], tmpl)
        b = load_checkpoint(st_paths[f], tmpl)
        assert int(a.step) == int(b.step)
        for x, y in zip(jax.tree.leaves(a.params), jax.tree.leaves(b.params)):
            np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                       rtol=1e-3, atol=1e-3)
        for x, y in zip(jax.tree.leaves(a.batch_stats),
                        jax.tree.leaves(b.batch_stats)):
            np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                       rtol=5e-2, atol=1e-2)
        ma, mb = read_metadata(seq_paths[f]), read_metadata(st_paths[f])
        assert ma["epoch"] == mb["epoch"] == 1
        assert res[f]["top1_valid"] == pytest.approx(
            ma["metrics"]["top1_valid"], abs=0.05)
        # the sidecar layout the gate/retrain promotion walks
        assert os.path.exists(st_paths[f] + ".meta.json")
        assert os.path.exists(st_paths[f] + "_train.jsonl")


def test_train_folds_stacked_resume_and_fold_sharded_mesh(tmp_path, devices8):
    """Resume: a second call with complete checkpoints trains nothing
    and preserves state; a fold-SHARDED mesh (K=2 over 8 devices ->
    (2, 4)) trains to completion with folds on disjoint device groups."""
    from fast_autoaugment_tpu.core.checkpoint import read_metadata
    from fast_autoaugment_tpu.parallel.mesh import make_fold_mesh
    from fast_autoaugment_tpu.train.trainer import train_folds_stacked

    conf = _conf()
    tmp = str(tmp_path)
    paths = [os.path.join(tmp, f"f{f}.msgpack") for f in (0, 1)]
    mesh = make_fold_mesh(2, devices8)  # (2, 4): folds sharded
    assert mesh.shape["fold"] == 2
    res = train_folds_stacked(conf, tmp, cv_ratio=0.4, folds=[0, 1],
                              save_paths=paths, seed=0, mesh=mesh,
                              evaluation_interval=1)
    for f in (0, 1):
        assert read_metadata(paths[f])["epoch"] == 1
        assert np.isfinite(res[f]["loss_train"])
    mtimes = [os.path.getmtime(p) for p in paths]
    res2 = train_folds_stacked(conf, tmp, cv_ratio=0.4, folds=[0, 1],
                               save_paths=paths, seed=0, mesh=mesh,
                               evaluation_interval=1)
    assert [os.path.getmtime(p) for p in paths] == mtimes  # nothing retrained
    assert res2[0]["epoch"] == 1


def test_train_folds_stacked_rejects_lazy_and_ragged(monkeypatch):
    from fast_autoaugment_tpu.data.datasets import ArrayDataset
    from fast_autoaugment_tpu.train import trainer

    lazy = ArrayDataset(np.asarray(["a.jpg"] * 64, object),
                        np.zeros(64, np.int32), 10, lazy=True)
    monkeypatch.setattr(trainer, "load_dataset", lambda name, root: (lazy, lazy))
    with pytest.raises(ValueError, match="in-memory"):
        trainer.train_folds_stacked(_conf(), "/tmp", cv_ratio=0.4,
                                    folds=[0, 1], save_paths=["a", "b"],
                                    seed=0)
    monkeypatch.undo()
    with pytest.raises(ValueError, match="folds but"):
        trainer.train_folds_stacked(_conf(), "/tmp", cv_ratio=0.4,
                                    folds=[0, 1], save_paths=["a"], seed=0)


# --------------------------------------------------- driver / CLI / e2e

def _search_kwargs(tmp, **over):
    kw = dict(
        dataroot=tmp, save_dir=os.path.join(tmp, "search"), cv_num=2,
        cv_ratio=0.4, num_policy=1, num_op=1, num_search=2, num_top=1,
    )
    kw.update(over)
    return kw


def test_search_fold_stack_e2e_matches_sequential(tmp_path):
    """--fold-stack auto end-to-end: phase 1 trains both folds in one
    stacked program, phase 2 runs unchanged, the final policy set
    matches a sequential (--fold-stack 0) run of the same seed (fold
    oracles differ only within the documented stacking bound, the TPE
    trial stream is driven by the same keys), and the device-seconds
    accounting identity holds in both modes."""
    from fast_autoaugment_tpu.search.driver import search_policies

    conf = _conf()
    seq_tmp = str(tmp_path / "seq")
    st_tmp = str(tmp_path / "st")
    for d in (seq_tmp, st_tmp):
        os.makedirs(d, exist_ok=True)
    r_seq = search_policies(conf, **_search_kwargs(seq_tmp), fold_stack=0)
    r_st = search_policies(conf, **_search_kwargs(st_tmp), fold_stack="auto")
    assert r_seq["fold_stack"] == 0
    assert r_st["fold_stack"] == 2
    assert r_st["final_policy_set"]
    trials_seq = json.load(open(os.path.join(seq_tmp, "search", "search_trials.json")))
    trials_st = json.load(open(os.path.join(st_tmp, "search", "search_trials.json")))
    assert sorted(trials_st) == sorted(trials_seq) == ["0", "1"]
    # the TPE proposal stream is fold-seeded and identical across
    # modes; rewards (fold-oracle evals on stacked-vs-sequential
    # checkpoints) may differ only within the stacking bound, so the
    # final set is drawn from the same proposal pool in either mode
    for fold in ("0", "1"):
        for (pa, ra), (pb, rb) in zip(trials_seq[fold], trials_st[fold]):
            assert pa == pb
            assert rb == pytest.approx(ra, abs=0.1)
    # device_secs_phase1 accounting under stacking (ISSUE satellite):
    # the per-fold attribution sums to (at most) the once-recorded
    # phase total in BOTH modes and covers the bulk of it (gate off —
    # the non-attributed remainder is setup only), and a stacked group
    # splits its ONE wall measurement evenly
    for r, stacked_mode in ((r_seq, False), (r_st, True)):
        attr = r["device_secs_phase1_per_fold"]
        assert sorted(attr) == ["0", "1"]
        total = r["device_secs_phase1"]
        s = sum(attr.values())
        assert 0 < s <= total + 1e-6
        assert s >= 0.5 * total, (stacked_mode, attr, total)
        if stacked_mode:
            assert attr["0"] == pytest.approx(attr["1"])
    # resume: a stacked rerun retrains nothing and replays the trials
    r_resume = search_policies(conf, **_search_kwargs(st_tmp), fold_stack="auto")
    assert r_resume["final_policy_set"] == r_st["final_policy_set"]
    assert r_resume["fold_stack"] == 0  # nothing pending -> sequential no-op


def test_fold_stack_gate_retrain_and_exclusion(tmp_path, monkeypatch):
    """The fold-oracle quality gate still works over stacked-trained
    checkpoints: an unreachable floor triggers the sequential per-fold
    retrain path and excludes still-weak folds.  The retrain itself is
    stubbed with a checkpoint copy (its full training path is covered
    by the equivalence tests above and the gate tests in
    test_search.py) — what this pins is the gate/retrain MECHANISM over
    a stacked phase 1: assessment, .retryN promotion paths, exclusion."""
    import shutil

    from fast_autoaugment_tpu.search import driver

    conf = _conf()
    tmp = str(tmp_path)
    retrained = []

    def stub_retrain(_conf_, _dataroot, *, save_path, cv_fold, **kw):
        retrained.append(save_path)
        src = save_path.rsplit(".retry", 1)[0]
        for suffix in ("", ".meta.json"):
            shutil.copy(src + suffix, save_path + suffix)
        return {}

    monkeypatch.setattr(driver, "train_and_eval", stub_retrain)
    r = driver.search_policies(
        conf, **_search_kwargs(tmp), until=1, fold_stack="auto",
        fold_quality_floor=0.99, fold_retrain_tries=1,
    )
    assert r["fold_stack"] == 2
    # stacked training bypassed train_and_eval; every spy call is a
    # quality-gate retrain of a single below-floor fold
    assert len(retrained) == 2
    assert all(p.endswith((".retry1",)) for p in retrained)
    assert sorted(r["excluded_folds"]) == [0, 1]  # 0.99 is unreachable
    assert set(r["fold_baselines"]) == {"0", "1"}


def test_cli_fold_stack_flag():
    from fast_autoaugment_tpu.launch.search_cli import build_parser

    p = build_parser()
    assert p.parse_args(["-c", "x.yaml"]).fold_stack == 0
    assert p.parse_args(["-c", "x.yaml", "--fold-stack", "auto"]).fold_stack == "auto"
    assert p.parse_args(["-c", "x.yaml", "--fold-stack", "5"]).fold_stack == 5
    with pytest.raises(SystemExit):
        p.parse_args(["-c", "x.yaml", "--fold-stack", "nope"])
    with pytest.raises(SystemExit):
        p.parse_args(["-c", "x.yaml", "--fold-stack", "-1"])


def test_resolve_fold_stack():
    from fast_autoaugment_tpu.search.driver import resolve_fold_stack

    assert resolve_fold_stack(0, 5) == 0
    assert resolve_fold_stack(None, 5) == 0
    assert resolve_fold_stack("auto", 5) == 5
    assert resolve_fold_stack("auto", 1) == 0  # 1-fold stack buys nothing
    assert resolve_fold_stack(3, 5) == 3
    assert resolve_fold_stack(8, 3) == 3  # capped at pending folds
    assert resolve_fold_stack(1, 5) == 0
    with pytest.raises(ValueError):
        resolve_fold_stack(-2, 5)


# ------------------------------------------------ prefetch failure paths

def test_prefetch_worker_exception_propagates():
    """A worker exception must surface in the consumer — no deadlock,
    no swallowed error — after the items yielded before it."""
    from fast_autoaugment_tpu.data.pipeline import prefetch

    def gen():
        yield 1
        yield 2
        raise RuntimeError("decode boom")

    out = []
    with pytest.raises(RuntimeError, match="decode boom"):
        for item in prefetch(gen(), depth=1):
            out.append(item)
    assert out == [1, 2]


def test_prefetch_transform_exception_propagates():
    from fast_autoaugment_tpu.data.pipeline import prefetch

    def bad_transform(item):
        raise ValueError("transform boom")

    with pytest.raises(ValueError, match="transform boom"):
        list(prefetch(iter([1, 2]), depth=1, transform=bad_transform))


def test_prefetch_early_break_stops_worker_and_closes_generator():
    """Abandoning the consumer (break) must stop the worker within the
    bounded-wait window and close the SOURCE generator (its finally
    runs), releasing whatever the feed held."""
    from fast_autoaugment_tpu.data.pipeline import prefetch

    closed = threading.Event()
    produced = []

    def gen():
        try:
            for i in range(10_000):
                produced.append(i)
                yield i
        finally:
            closed.set()

    n_before = threading.active_count()
    it = prefetch(gen(), depth=2)
    for item in it:
        assert item == 0
        break
    it.close()
    assert closed.wait(2.0), "source generator not closed after break"
    deadline = time.time() + 2.0
    while threading.active_count() > n_before and time.time() < deadline:
        time.sleep(0.01)
    assert threading.active_count() <= n_before, "worker thread leaked"
    # bounded production: the worker stopped near the queue depth, it
    # did not run the 10k-item feed dry into a dead queue
    assert len(produced) <= 10
