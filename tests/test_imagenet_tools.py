"""Devkit parsing + val reorganization + listfile generation on a
fabricated mini ILSVRC2012 tree (reference ``imagenet.py:165-245``
capabilities; VERDICT round 1, missing item 3)."""

import os

import numpy as np
import pytest

from fast_autoaugment_tpu.data.imagenet_tools import (
    parse_devkit,
    parse_meta_mat,
    prepare_val_folder,
    write_listfile,
)

WNIDS = ["n01440764", "n01443537", "n02084071"]


def _write_devkit(root, n_val=6):
    """Fabricate devkit/data/{meta.mat, ground truth} with 3 leaf synsets
    and one internal node (num_children > 0, must be dropped)."""
    import scipy.io

    data_dir = os.path.join(root, "data")
    os.makedirs(data_dir, exist_ok=True)
    synsets = np.array(
        [
            (1, WNIDS[0], "tench, Tinca tinca", "a fish", 0),
            (2, WNIDS[1], "goldfish", "a fish", 0),
            (3, WNIDS[2], "dog", "an animal", 0),
            (4, "n00001740", "entity", "internal node", 3),
        ],
        dtype=[
            ("ILSVRC2012_ID", "i4"), ("WNID", "O"), ("words", "O"),
            ("gloss", "O"), ("num_children", "i4"),
        ],
    )
    scipy.io.savemat(os.path.join(data_dir, "meta.mat"), {"synsets": synsets})
    # val image i (sorted order) belongs to synset id gt[i]
    gt = [(i % 3) + 1 for i in range(n_val)]
    with open(
        os.path.join(data_dir, "ILSVRC2012_validation_ground_truth.txt"), "w"
    ) as fh:
        fh.writelines(f"{g}\n" for g in gt)
    return gt


def _write_flat_val(root, n_val=6):
    os.makedirs(root, exist_ok=True)
    names = [f"ILSVRC2012_val_{i:08d}.JPEG" for i in range(1, n_val + 1)]
    for name in names:
        with open(os.path.join(root, name), "w") as fh:
            fh.write(name)
    return names


def test_parse_meta_drops_internal_nodes(tmp_path):
    _write_devkit(str(tmp_path))
    idx_to_wnid, wnid_to_classes = parse_meta_mat(str(tmp_path))
    assert idx_to_wnid == {1: WNIDS[0], 2: WNIDS[1], 3: WNIDS[2]}
    assert "n00001740" not in wnid_to_classes
    assert wnid_to_classes[WNIDS[0]] == ("tench", "Tinca tinca")


def test_val_reorg_pairs_sorted_files_with_groundtruth(tmp_path):
    devkit = tmp_path / "devkit"
    val = tmp_path / "val"
    gt = _write_devkit(str(devkit))
    names = _write_flat_val(str(val))

    moved = prepare_val_folder(str(val), str(devkit))
    assert moved == len(names)
    for i, name in enumerate(names):
        wnid = WNIDS[gt[i] - 1]
        assert os.path.exists(os.path.join(str(val), wnid, name))
    # idempotent: second run moves nothing
    assert prepare_val_folder(str(val), str(devkit)) == 0


def test_val_reorg_refuses_count_mismatch(tmp_path):
    devkit = tmp_path / "devkit"
    val = tmp_path / "val"
    _write_devkit(str(devkit), n_val=6)
    _write_flat_val(str(val), n_val=5)
    with pytest.raises(ValueError, match="refusing to mispair"):
        prepare_val_folder(str(val), str(devkit))


def test_listfile_roundtrip_through_dataset_reader(tmp_path):
    """Generated CLS-LOC listfile (2-token, extensionless) must load back
    through `_load_imagenet_listing` with identical paths/labels as the
    os.walk path."""
    from fast_autoaugment_tpu.data.datasets import _load_imagenet_listing

    root = tmp_path / "train"
    for wnid in WNIDS:
        os.makedirs(root / wnid)
        for j in range(2):
            with open(root / wnid / f"{wnid}_{j}.JPEG", "w") as fh:
                fh.write("x")

    walk = _load_imagenet_listing(str(tmp_path), "train")

    out = tmp_path / "train_cls.txt"
    n = write_listfile(str(root), str(out))
    assert n == 6
    with open(out) as fh:
        first = fh.readline().split()
    assert len(first) == 2 and "/" in first[0] and "." not in first[0]

    listed = _load_imagenet_listing(str(tmp_path), "train")
    assert list(listed.images) == list(walk.images)
    assert listed.labels.tolist() == walk.labels.tolist()


def test_devkit_cli(tmp_path, capsys):
    import sys
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))
    import prepare_imagenet

    devkit = tmp_path / "devkit"
    _write_devkit(str(devkit))
    val = tmp_path / "imagenet" / "val"
    _write_flat_val(str(val))
    prepare_imagenet.main(["val-reorg", "--root", str(tmp_path / "imagenet"),
                           "--devkit", str(devkit)])
    prepare_imagenet.main(["listfile", "--root", str(tmp_path / "imagenet"),
                           "--split", "val"])
    assert os.path.exists(tmp_path / "imagenet" / "val_cls.txt")
    out = capsys.readouterr().out
    assert "moved 6" in out and "wrote 6 entries" in out


# ---------------------------------------------------------------------------
# download/extract pipeline on fabricated tars + file:// URLs (reference
# imagenet.py:164-231; VERDICT round 2, next-step 8)
# ---------------------------------------------------------------------------


def _make_tar(path, files, gzip=False):
    """files: {member_name: bytes}"""
    import io
    import tarfile

    mode = "w:gz" if gzip else "w"
    with tarfile.open(path, mode) as tar:
        for name, data in files.items():
            info = tarfile.TarInfo(name)
            info.size = len(data)
            tar.addfile(info, io.BytesIO(data))
    return str(path)


def test_fetch_verifies_and_skips_existing(tmp_path):
    from fast_autoaugment_tpu.data.imagenet_tools import fetch, md5sum

    src = tmp_path / "archive.bin"
    src.write_bytes(b"payload")
    md5 = md5sum(str(src))
    dest = tmp_path / "downloads"

    got = fetch(f"file://{src}", str(dest), md5=md5)
    assert os.path.exists(got)
    mtime = os.path.getmtime(got)
    # second fetch: checksum matches -> no re-transfer
    assert fetch(f"file://{src}", str(dest), md5=md5) == got
    assert os.path.getmtime(got) == mtime

    # corrupt target with a checksum -> re-fetched and repaired
    with open(got, "wb") as fh:
        fh.write(b"garbage")
    assert fetch(f"file://{src}", str(dest), md5=md5) == got
    assert md5sum(got) == md5

    # upstream corruption -> loud failure
    with pytest.raises(IOError, match="md5"):
        fetch(f"file://{src}", str(dest), filename="other.bin", md5="0" * 32)


def test_extract_tar_rejects_traversal(tmp_path):
    from fast_autoaugment_tpu.data.imagenet_tools import extract_tar

    bad = _make_tar(tmp_path / "evil.tar", {"../escape.txt": b"x"})
    with pytest.raises(ValueError, match="unsafe"):
        extract_tar(bad, str(tmp_path / "out"))


def test_download_and_extract_train_expands_inner_tars(tmp_path):
    """The train archive is a tar of per-class tars; download_and_extract
    must fetch (file://), verify, extract, and expand each class tar into
    its wnid folder (reference imagenet.py:101-131,224-226)."""
    from fast_autoaugment_tpu.data.imagenet_tools import (
        download_and_extract,
        md5sum,
        write_listfile,
    )

    inner_dir = tmp_path / "inner"
    inner_dir.mkdir()
    wnids = ["n01440764", "n01443537"]
    inner_tars = {}
    for w in wnids:
        p = _make_tar(inner_dir / f"{w}.tar",
                      {f"{w}_{i}.JPEG": b"img" for i in range(3)})
        inner_tars[f"{w}.tar"] = open(p, "rb").read()
    outer = _make_tar(tmp_path / "ILSVRC2012_img_train.tar", inner_tars)

    root = tmp_path / "data"
    dest = download_and_extract("train", str(root),
                                url=f"file://{outer}", md5=md5sum(outer))
    assert sorted(os.listdir(dest)) == wnids  # inner tars gone, dirs in place
    for w in wnids:
        assert len(os.listdir(os.path.join(dest, w))) == 3
    # the expanded tree feeds the listfile generator (full offline chain)
    n = write_listfile(dest, str(tmp_path / "train_cls.txt"))
    assert n == 6


def test_download_and_extract_devkit_gz(tmp_path):
    from fast_autoaugment_tpu.data.imagenet_tools import (
        download_and_extract,
        md5sum,
    )

    gz = _make_tar(
        tmp_path / "ILSVRC2012_devkit_t12.tar.gz",
        {"ILSVRC2012_devkit_t12/data/ILSVRC2012_validation_ground_truth.txt":
         b"1\n2\n"},
        gzip=True,
    )
    root = tmp_path / "data"
    dest = download_and_extract("devkit", str(root),
                                url=f"file://{gz}", md5=md5sum(gz))
    assert os.path.exists(os.path.join(
        dest, "data", "ILSVRC2012_validation_ground_truth.txt"))
