"""Chrome-trace export (tools/trace_export.py): journal -> trace.json
schema round-trip, clock alignment, and the phase-overlap lane
rendering (the PR-9 drill's evidence as a timeline).

Host-only / no-XLA-compile (tier-1 discipline): the overlap drill runs
``run_overlapped_phases`` with stub phase bodies.
"""

import json
import os
import sys
import time

import pytest

from fast_autoaugment_tpu.core import telemetry as T

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_REPO, "tools"))

from trace_export import (  # noqa: E402
    PHASE_LANES,
    journal_to_trace,
    read_journal,
    validate_trace,
)
import trace_export  # noqa: E402


@pytest.fixture()
def journal_dir(tmp_path):
    d = str(tmp_path / "tel")
    T.enable_telemetry(d, tb_bridge=False)
    yield d
    T._disable_for_tests()


def _slices(trace, cat=None):
    return [e for e in trace["traceEvents"] if e["ph"] == "X"
            and (cat is None or e.get("cat") == cat)]


def test_roundtrip_validates_against_chrome_schema(journal_dir):
    with T.span("train_dispatch", step=0):
        time.sleep(0.002)
    with T.span("serve_dispatch", etype="dispatch", batch=8):
        time.sleep(0.002)
    T.emit("shed", "serve0", reason="overload", n=2)
    T.emit("breaker_fire", "serve0", fires=1)
    T.phase_event("phase1-fold0", 1.0, 2.0, fold=0, lane="phase1")

    T.journal_flush()
    records = read_journal(journal_dir)
    assert len(records) == 5
    trace = journal_to_trace(records)
    assert validate_trace(trace) == []  # the schema gate
    # and the file round-trips through JSON intact
    again = json.loads(json.dumps(trace))
    assert validate_trace(again) == []

    slices = _slices(trace, "dispatch")
    assert {s["name"] for s in slices} == {"train_dispatch",
                                           "serve_dispatch"}
    for s in slices:
        assert s["dur"] > 0 and s["ts"] >= 0
    marks = [e for e in trace["traceEvents"] if e["ph"] == "i"]
    assert {m["cat"] for m in marks} == {"shed", "breaker_fire"}
    assert all(m["s"] == "t" for m in marks)
    # args carry the typed payload fields
    (shed,) = [m for m in marks if m["cat"] == "shed"]
    assert shed["args"]["reason"] == "overload" and shed["args"]["n"] == 2


def test_validate_trace_catches_schema_violations():
    assert validate_trace({"traceEvents": "nope"})
    bad = {"traceEvents": [{"ph": "X", "name": "x", "pid": 1, "tid": 1,
                            "ts": 0}]}  # X without dur
    assert any("dur" in p for p in validate_trace(bad))
    bad = {"traceEvents": [{"ph": "i", "name": "x", "pid": 1, "tid": 1,
                            "ts": 0}]}  # instant without scope
    assert any("'s'" in p for p in validate_trace(bad))
    ok = {"traceEvents": [{"ph": "X", "name": "x", "pid": 1, "tid": 1,
                           "ts": 0, "dur": 1}]}
    assert validate_trace(ok) == []


def test_overlap_drill_renders_distinct_phase_lanes(journal_dir):
    """The PR-9 overlap evidence as a timeline: fold k's phase-2 slice
    overlaps fold k+1's phase-1 slice, on two DISTINCT lanes."""
    from fast_autoaugment_tpu.search.pipeline import run_overlapped_phases

    def p1(fold):
        time.sleep(0.05)

    def p2(fold):
        with T.span("tta", step=fold):
            time.sleep(0.02)

    timeline = run_overlapped_phases([0, 1, 2], p1, p2, poll_sec=0.01)
    assert timeline["overlap_secs"] > 0  # the drill really overlapped

    T.journal_flush()
    trace = journal_to_trace(read_journal(journal_dir))
    assert validate_trace(trace) == []
    phases = _slices(trace, "phase")
    by_lane = {}
    for s in phases:
        by_lane.setdefault(s["tid"], []).append(s)
    # two distinct lanes, one per phase, each holding all three folds
    assert set(by_lane) == set(PHASE_LANES.values())
    assert len(by_lane[PHASE_LANES["phase1"]]) == 3
    assert len(by_lane[PHASE_LANES["phase2"]]) == 3
    # lane names are human-readable in the metadata
    names = {e["args"]["name"] for e in trace["traceEvents"]
             if e["ph"] == "M" and e["name"] == "thread_name"}
    assert {"phase-1 (train)", "phase-2 (search)"} <= names
    # the rendered timeline shows the overlap: fold 0's phase-2 slice
    # intersects fold 1's phase-1 slice in trace time
    p2_f0 = next(s for s in by_lane[PHASE_LANES["phase2"]]
                 if s["name"] == "phase2-fold0")
    p1_f1 = next(s for s in by_lane[PHASE_LANES["phase1"]]
                 if s["name"] == "phase1-fold1")
    lo = max(p2_f0["ts"], p1_f1["ts"])
    hi = min(p2_f0["ts"] + p2_f0["dur"], p1_f1["ts"] + p1_f1["dur"])
    assert hi > lo, "phase lanes do not overlap in the rendered trace"
    # the TTA dispatch spans landed on the real (main) thread lane,
    # separate from the synthetic phase lanes
    tta = [s for s in _slices(trace, "dispatch") if s["name"] == "tta"]
    assert len(tta) == 3
    assert all(s["tid"] not in PHASE_LANES.values() for s in tta)


def test_cross_process_wall_alignment():
    """Records from two processes with different monotonic origins land
    on one shared wall timeline via the per-process offset median."""
    base_wall = 1_700_000_000.0
    records = [
        # process A: mono origin ~0 (offset = base_wall)
        {"type": "dispatch", "label": "a", "host": "host0", "pid": 1,
         "tid": 1, "thread": "t", "attempt": 1, "seq": 0,
         "t_wall": base_wall + 10.0, "t_mono": 10.0,
         "t_mono_start": 9.0, "t_mono_end": 10.0},
        # process B: mono origin shifted by 1000 (offset differs)
        {"type": "dispatch", "label": "b", "host": "host1", "pid": 2,
         "tid": 2, "thread": "t", "attempt": 1, "seq": 0,
         "t_wall": base_wall + 10.0, "t_mono": 1010.0,
         "t_mono_start": 1009.0, "t_mono_end": 1010.0},
    ]
    trace = journal_to_trace(records)
    assert validate_trace(trace) == []
    a, b = _slices(trace)
    # both windows cover the same wall second -> identical ts/dur
    assert a["ts"] == pytest.approx(b["ts"], abs=1.0)
    assert a["dur"] == pytest.approx(1e6, rel=1e-6)


def test_cli_writes_trace_file(journal_dir, tmp_path, capsys):
    with T.span("train_dispatch"):
        time.sleep(0.001)
    T.journal_flush()
    out = str(tmp_path / "trace.json")
    rc = trace_export.main(["--telemetry", journal_dir, "--out", out])
    assert rc == 0
    with open(out) as fh:
        trace = json.load(fh)
    assert validate_trace(trace) == []
    assert "trace_export:" in capsys.readouterr().out


def test_cli_empty_dir_is_loud(tmp_path):
    rc = trace_export.main(["--telemetry", str(tmp_path / "empty"),
                            "--out", str(tmp_path / "t.json")])
    assert rc == 2
    assert not os.path.exists(tmp_path / "t.json")
