"""Multi-policy tenancy (serve/policy_server.py TenantPool +
serve_cli tenancy surface): digest identity, LRU admit/evict with
dispatch-boundary retirement, one-tenant-per-batch coalescing,
cold-warm-then-serve, and the HTTP digest header — fast and host-only
(DummyApplier, no XLA)."""

from __future__ import annotations

import io
import json
import threading
import time

import numpy as np
import pytest

from fast_autoaugment_tpu.serve.policy_server import (
    PolicyServer,
    TenantNotResidentError,
    TenantPool,
    policy_digest,
)

IMG = 8


class DummyApplier:
    """Host-only applier with a settable digest: shifts pixels by
    `delta` so tests can tell WHICH tenant served a request."""

    def __init__(self, delta=1.0, digest="default00000", dispatch="exact",
                 max_batch=8, wall_s=0.0):
        self.delta = float(delta)
        self.digest = digest
        self.dispatch = dispatch
        self.max_batch = max_batch
        self.image = IMG
        self.channels = 3
        self.num_sub = 1
        self.shapes = (max_batch,)
        self.wall_s = float(wall_s)
        self.calls = 0

    def apply(self, images, keys):
        self.calls += 1
        if self.wall_s:
            time.sleep(self.wall_s)
        return np.asarray(images, np.float32) + self.delta


def _images(n, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 256, (n, IMG, IMG, 3)).astype(np.float32)


def _keys(n):
    return np.zeros((n, 2), np.uint32)


def _srv(default=None, capacity=2, **kw) -> PolicyServer:
    return PolicyServer(default or DummyApplier(),
                        tenant_capacity=capacity, max_wait_ms=1, **kw)


# ------------------------------------------------------ digest identity


def test_policy_digest_stable_and_distinct():
    a = np.zeros((1, 2, 3), np.float32)
    b = np.ones((1, 2, 3), np.float32)
    assert policy_digest(a) == policy_digest(a)
    assert policy_digest(a) != policy_digest(b)
    assert len(policy_digest(a)) == 12
    # shape participates: a [2,1,3] zero tensor is a DIFFERENT policy
    assert policy_digest(a) != policy_digest(np.zeros((2, 1, 3),
                                                      np.float32))
    # dtype-normalizing: int input digests like its float32 image
    assert policy_digest(np.zeros((1, 2, 3), np.int32)) == policy_digest(a)


# ------------------------------------------------------ the TenantPool


def test_pool_lru_admit_evict_order():
    pool = TenantPool(2, server_id="t0")
    pool.admit("aaa", "ap_a")
    pool.admit("bbb", "ap_b")
    assert pool.resident_digests() == ["aaa", "bbb"]
    # touching aaa bumps it MRU; admitting ccc evicts bbb (the LRU)
    assert pool.lookup_submit("aaa") == "ap_a"
    evicted = pool.admit("ccc", "ap_c")
    assert evicted == ["bbb"]
    assert pool.resident_digests() == ["aaa", "ccc"]
    # bbb is retiring: invisible to new submissions, still
    # dispatchable for queued work
    assert pool.lookup_submit("bbb") is None
    assert pool.lookup_dispatch("bbb") == "ap_b"


def test_pool_retirement_waits_for_queued_work():
    """The dispatch-boundary eviction contract: a retiring tenant with
    queued work survives sweeps until its work drains."""
    pool = TenantPool(1, server_id="t1")
    pool.admit("old", "ap_old")
    pool.track_submit("old")
    pool.admit("new", "ap_new")  # old starts retiring with 1 queued
    assert pool.sweep() == []    # queued work: NOT swept
    assert pool.lookup_dispatch("old") == "ap_old"
    pool.track_done("old")
    assert pool.sweep() == ["old"]
    assert pool.lookup_dispatch("old") is None


def test_pool_readmit_resurrects_retiring():
    pool = TenantPool(1, server_id="t2")
    pool.admit("x", "ap1")
    pool.admit("y", "ap2")      # x retires
    assert pool.lookup_submit("x") is None
    pool.admit("x", "ap1b")     # re-admitted before the sweep
    assert pool.lookup_submit("x") == "ap1b"
    assert pool.lookup_submit("y") is None  # y took x's place retiring
    snap = pool.snapshot()
    assert snap["resident"] == ["x"] and snap["retiring"] == ["y"]
    assert snap["evicts"] == 2


# ----------------------------------------------- server-level tenancy


def test_submit_unknown_digest_typed_error():
    srv = _srv()
    with pytest.raises(TenantNotResidentError) as ei:
        srv.submit(_images(1), _keys(1), digest="nope00000000")
    assert ei.value.digest == "nope00000000"
    assert ei.value.resident == ()


def test_submit_digest_disabled_tenancy_typed_error():
    srv = PolicyServer(DummyApplier(digest="def000000000"))
    with pytest.raises(TenantNotResidentError):
        srv.submit(_images(1), _keys(1), digest="other0000000")
    # the default applier's own digest is always servable
    p = srv.submit(_images(1), _keys(1), digest="def000000000")
    assert p.digest is None  # normalized to the pinned default


def test_warm_tenant_and_serve_by_digest():
    default = DummyApplier(1.0, digest="def000000000")
    srv = _srv(default)
    tenant = DummyApplier(7.0, digest="aaa000000000")
    info = srv.warm_tenant(tenant)
    assert info["digest"] == "aaa000000000" and info["evicted"] == []
    srv.start()
    try:
        imgs = _images(2)
        out_t = srv.result(srv.submit(imgs, _keys(2),
                                      digest="aaa000000000"), timeout=10.0)
        out_d = srv.result(srv.submit(imgs, _keys(2)), timeout=10.0)
        assert np.all(out_t - imgs == 7.0)
        assert np.all(out_d - imgs == 1.0)
        assert tenant.calls == 1 and default.calls == 1
    finally:
        srv.stop()
    st = srv.stats()
    assert st["tenancy"]["resident"] == ["aaa000000000"]
    assert st["default_digest"] == "def000000000"
    assert st["tenancy"]["admits"] == 1


def test_warm_tenant_validates_contract():
    srv = _srv(DummyApplier(max_batch=8, digest="def000000000"))
    with pytest.raises(ValueError):  # no digest
        srv.warm_tenant(DummyApplier(digest=None))
    with pytest.raises(ValueError):  # the pinned default's digest
        srv.warm_tenant(DummyApplier(digest="def000000000"))
    with pytest.raises(ValueError):  # smaller AOT coverage
        srv.warm_tenant(DummyApplier(max_batch=2, digest="aaa"))
    with pytest.raises(ValueError):  # dispatch-mode mismatch
        srv.warm_tenant(DummyApplier(dispatch="grouped", digest="bbb"))
    bad = DummyApplier(digest="ccc")
    bad.image = 16
    with pytest.raises(ValueError):  # geometry mismatch
        srv.warm_tenant(bad)
    with pytest.raises(RuntimeError):  # tenancy off entirely
        PolicyServer(DummyApplier()).warm_tenant(
            DummyApplier(digest="ddd"))


def test_lru_eviction_rejects_new_but_drains_queued():
    """Capacity pressure: the evicted tenant's QUEUED request still
    completes on its applier (zero dropped in-flight), while a NEW
    submission for it gets the typed cold error."""
    default = DummyApplier(0.0, digest="def000000000")
    srv = _srv(default, capacity=1)
    ap_a = DummyApplier(3.0, digest="aaa000000000")
    ap_b = DummyApplier(5.0, digest="bbb000000000")
    srv.warm_tenant(ap_a)
    imgs = _images(1)
    queued = srv.submit(imgs, _keys(1), digest="aaa000000000")
    evicted = srv.warm_tenant(ap_b)["evicted"]  # a starts retiring
    assert evicted == ["aaa000000000"]
    with pytest.raises(TenantNotResidentError):
        srv.submit(imgs, _keys(1), digest="aaa000000000")
    srv.start()
    try:
        out = srv.result(queued, timeout=10.0)
        assert np.all(out - imgs == 3.0)  # served by the RETIRING applier
        out_b = srv.result(srv.submit(imgs, _keys(1),
                                      digest="bbb000000000"), timeout=10.0)
        assert np.all(out_b - imgs == 5.0)
        # the dispatch boundary swept the drained retiree
        deadline = time.monotonic() + 5.0
        while srv._tenants.snapshot()["retiring"] \
                and time.monotonic() < deadline:
            srv.augment(imgs, _keys(1), timeout=10.0)  # drive boundaries
        assert srv._tenants.snapshot()["retiring"] == []
    finally:
        srv.stop()


def test_batches_never_mix_tenants():
    """Interleaved digests queued while the worker is down: every
    dispatch binds ONE applier (outputs homogeneous per request) and
    FIFO order survives the tenant-boundary carry."""
    default = DummyApplier(1.0, digest="def000000000", max_batch=8)
    srv = _srv(default, capacity=2, max_batch=8)
    ap_a = DummyApplier(10.0, digest="aaa000000000", max_batch=8)
    srv.warm_tenant(ap_a)
    imgs = _images(2)
    pend = []
    for i in range(6):
        digest = "aaa000000000" if i % 2 else None
        pend.append(srv.submit(imgs, _keys(2), digest=digest))
    srv.start()
    try:
        for i, p in enumerate(pend):
            out = srv.result(p, timeout=10.0)
            want = 10.0 if i % 2 else 1.0
            deltas = np.unique(out - imgs)
            assert deltas.size == 1 and deltas[0] == want, \
                f"request {i}: mixed-tenant batch"
        # FIFO preserved across the carries
        for a, b in zip(pend, pend[1:]):
            assert a.t_done <= b.t_done
    finally:
        srv.stop()
    # 6 alternating-tenant requests = 6 single-tenant dispatches
    assert default.calls == 3 and ap_a.calls == 3


def test_per_tenant_counters_and_gauge():
    from fast_autoaugment_tpu.core import telemetry

    default = DummyApplier(0.0, digest="def000000000")
    srv = _srv(default)
    ap = DummyApplier(2.0, digest="ten000000000")
    srv.warm_tenant(ap)
    srv.start()
    try:
        for _ in range(3):
            srv.augment(_images(2), _keys(2), digest="ten000000000",
                        timeout=10.0)
    finally:
        srv.stop()
    reg = telemetry.registry()
    reqs = reg.counter("faa_tenant_requests_total", "",
                       digest="ten000000000", server=srv._server_id)
    imgs = reg.counter("faa_tenant_images_total", "",
                       digest="ten000000000", server=srv._server_id)
    assert int(reqs.value) == 3 and int(imgs.value) == 6
    gauge = reg.gauge("faa_tenant_resident", "", server=srv._server_id)
    assert int(gauge.value) == 1


def test_cold_warm_under_concurrent_traffic():
    """The cold-warm-then-swap drill on dummies: traffic to the warm
    default NEVER errors while a tenant warms and admits off to the
    side (the p99-unmoved acceptance, minus the timing claim)."""
    default = DummyApplier(1.0, digest="def000000000")
    srv = _srv(default).start()
    imgs = _images(2)
    errors, results = [], []
    stop = threading.Event()

    def client():
        while not stop.is_set():
            try:
                results.append(srv.augment(imgs, _keys(2), timeout=10.0))
            except Exception as e:  # noqa: BLE001 — the assertion target
                errors.append(e)

    threads = [threading.Thread(target=client, daemon=True)
               for _ in range(2)]
    for t in threads:
        t.start()
    try:
        # "AOT warm" off to the side (simulated cost), then admit
        slow_build = DummyApplier(9.0, digest="cold00000000")
        time.sleep(0.05)
        srv.warm_tenant(slow_build)
        out = srv.augment(imgs, _keys(2), digest="cold00000000",
                          timeout=10.0)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10.0)
        srv.stop()
    assert not errors and len(results) > 0
    assert np.all(out - imgs == 9.0)
    for r in results:
        assert np.all(r - imgs == 1.0)  # warm traffic untouched


def test_tenancy_off_defaults_identical_stats_shape():
    """tenant_capacity=0 keeps the historical stream: no tenancy block,
    digest-less submits untouched."""
    srv = PolicyServer(DummyApplier())
    st = srv.stats()
    assert "tenancy" not in st
    p = srv.submit(_images(1), _keys(1))
    assert p.digest is None


# -------------------------------------------------- serve_cli surface


def _start_http(server, state=None, **kw):
    from http.server import ThreadingHTTPServer

    from fast_autoaugment_tpu.serve.serve_cli import make_handler

    httpd = ThreadingHTTPServer(
        ("127.0.0.1", 0),
        make_handler(server, server.applier, state=state, **kw))
    httpd.daemon_threads = True
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd, httpd.server_address[1]


def _http(port, method, path, body=None, headers=None, timeout=30):
    import http.client

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    conn.request(method, path, body=body, headers=headers or {})
    resp = conn.getresponse()
    data = resp.read()
    conn.close()
    return resp, data


def _npz_body(imgs):
    buf = io.BytesIO()
    np.savez(buf, images=imgs.astype(np.uint8))
    return buf.getvalue()


def test_http_digest_header_selects_tenant():
    default = DummyApplier(1.0, digest="def000000000")
    srv = _srv(default).start()
    srv.warm_tenant(DummyApplier(200.0, digest="aaa000000000"))
    httpd, port = _start_http(srv)
    try:
        imgs = _images(1, seed=2)
        body = _npz_body(imgs)
        resp, data = _http(port, "POST", "/augment", body=body,
                           headers={"X-FAA-Policy-Digest":
                                    "aaa000000000"})
        assert resp.status == 200
        got = np.load(io.BytesIO(data))["images"]
        ref = np.clip(imgs + 200.0, 0, 255).astype(np.uint8)
        assert np.array_equal(got, ref)
    finally:
        httpd.shutdown()
        httpd.server_close()
        srv.stop()


def test_http_cold_digest_structured_503():
    srv = _srv(DummyApplier(digest="def000000000")).start()
    httpd, port = _start_http(srv)  # no state: warming impossible
    try:
        resp, data = _http(port, "POST", "/augment",
                           body=_npz_body(_images(1)),
                           headers={"X-FAA-Policy-Digest":
                                    "cold00000000"})
        assert resp.status == 503
        body = json.loads(data)
        assert body["type"] == "tenant_cold"
        assert body["digest"] == "cold00000000"
        assert body["warming"] is False
    finally:
        httpd.shutdown()
        httpd.server_close()
        srv.stop()


def test_http_warm_endpoint_and_background_warm(tmp_path):
    """POST /tenants/warm admits from a policy file; a cold digest
    with a --policy-dir recipe kicks the background warm and later
    requests hit the resident tenant."""
    from fast_autoaugment_tpu.serve.serve_cli import (
        ServeState,
        build_policy_tensor,
    )

    policy_dir = tmp_path / "policies"
    policy_dir.mkdir()
    spec = policy_dir / "b.json"
    spec.write_text(json.dumps(
        [[["ShearX", 0.9, 0.1], ["Solarize", 0.3, 0.7]]]))
    tensor = build_policy_tensor(str(spec))
    digest_b = policy_digest(tensor)

    def build_applier(policy_tensor):
        return DummyApplier(50.0, digest=policy_digest(policy_tensor))

    srv = _srv(DummyApplier(1.0, digest="def000000000")).start()
    state = ServeState(srv, "unused.json", build_applier,
                       policy_dir=str(policy_dir))
    httpd, port = _start_http(srv, state)
    try:
        # recipe resolution: content digest scan finds b.json
        assert state.tenant_recipe(digest_b) == str(spec)
        assert state.tenant_recipe("ffff00000000") is None

        # cold request: 503 + warming=true (recipe exists)
        resp, data = _http(port, "POST", "/augment",
                           body=_npz_body(_images(1)),
                           headers={"X-FAA-Policy-Digest": digest_b})
        assert resp.status == 503
        assert json.loads(data)["warming"] is True
        assert resp.getheader("Retry-After") is not None
        # the background warm admits; a retry then serves the tenant
        deadline = time.monotonic() + 10.0
        status = None
        while time.monotonic() < deadline:
            resp, data = _http(port, "POST", "/augment",
                               body=_npz_body(_images(1)),
                               headers={"X-FAA-Policy-Digest": digest_b})
            status = resp.status
            if status == 200:
                break
            time.sleep(0.1)
        assert status == 200
        assert digest_b in srv.resident_tenants()

        # the explicit warm endpoint (operator preload): idempotent
        resp, data = _http(port, "POST", "/tenants/warm",
                           body=json.dumps({"policy":
                                            str(spec)}).encode())
        assert resp.status == 200
        info = json.loads(data)
        assert info["warmed"] is True and info["digest"] == digest_b
        # /stats reports the tenancy block
        resp, data = _http(port, "GET", "/stats")
        st = json.loads(data)
        assert st["tenancy"]["resident"] == [digest_b]
        # malformed warm bodies answer structured 400
        resp, data = _http(port, "POST", "/tenants/warm", body=b"{}")
        assert resp.status == 400
        resp, data = _http(port, "POST", "/tenants/warm",
                           body=json.dumps({"policy":
                                            "/nope.json"}).encode())
        assert resp.status == 400
        assert json.loads(data)["type"] == "warm_failed"
    finally:
        httpd.shutdown()
        httpd.server_close()
        srv.stop()


def test_serve_cli_parser_tenancy_defaults():
    from fast_autoaugment_tpu.serve.serve_cli import build_parser

    args = build_parser().parse_args(["--policy", "x.json"])
    assert args.tenant_capacity == 0 and args.policy_dir is None
    assert args.port_dir is None
