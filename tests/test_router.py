"""The serving-plane router (serve/router.py + router_cli.py): digest
rendezvous stability, port-dir discovery, health-aware rotation with
hysteresis, bounded Retry-After failover, and the FAA_FAULT drill
verbs — all fast and host-only (stub HTTP replicas, no jax)."""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from fast_autoaugment_tpu.serve.router import (
    Router,
    discover_replicas,
    parse_static_replicas,
    rendezvous_order,
)
from fast_autoaugment_tpu.utils import faultinject

_NAME_SEQ = itertools.count()


def _router(**kw) -> Router:
    """A Router with a unique registry label per test (the metrics
    registry is process-wide; shared names would accumulate)."""
    kw.setdefault("name", f"rt{next(_NAME_SEQ)}")
    return Router(**kw)


@pytest.fixture(autouse=True)
def _clean_fault_env():
    saved = os.environ.pop("FAA_FAULT", None)
    faultinject.reset()
    yield
    if saved is None:
        os.environ.pop("FAA_FAULT", None)
    else:
        os.environ["FAA_FAULT"] = saved
    faultinject.reset()


class StubReplica:
    """A controllable upstream: /readyz verdict flips on demand,
    /augment answers a configurable status + headers, and every
    routed request is recorded."""

    def __init__(self):
        self.ready = True
        self.augment_status = 200
        self.augment_headers: dict = {}
        self.augment_body = b"ok"
        self.requests: list[dict] = []
        self._lock = threading.Lock()
        stub = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def _answer(self, code, body, headers=None):
                self.send_response(code)
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/readyz":
                    with stub._lock:
                        ok = stub.ready
                    self._answer(200 if ok else 503, b"{}")
                else:
                    self._answer(404, b"{}")

            def do_POST(self):
                length = int(self.headers.get("Content-Length", "0") or 0)
                body = self.rfile.read(length) if length else b""
                with stub._lock:
                    stub.requests.append({
                        "path": self.path,
                        "digest": self.headers.get("X-FAA-Policy-Digest"),
                        "deadline": self.headers.get("X-FAA-Deadline-Ms"),
                        "n": len(body)})
                    code = stub.augment_status
                    headers = dict(stub.augment_headers)
                    out = stub.augment_body
                self._answer(code, out, headers)

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.httpd.daemon_threads = True
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()
        self.port = self.httpd.server_address[1]

    @property
    def n_requests(self) -> int:
        with self._lock:
            return len(self.requests)

    def set_ready(self, ok: bool) -> None:
        with self._lock:
            self.ready = ok

    def set_augment(self, status: int, headers: dict | None = None) -> None:
        with self._lock:
            self.augment_status = status
            self.augment_headers = dict(headers or {})

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


@pytest.fixture()
def stubs():
    reps = [StubReplica() for _ in range(3)]
    yield reps
    for r in reps:
        r.close()


def _static(reps) -> list[dict]:
    return [{"tag": f"r{i}", "host": "127.0.0.1", "port": r.port}
            for i, r in enumerate(reps)]


# ------------------------------------------------- rendezvous hashing


def test_rendezvous_deterministic_and_total():
    ids = [f"r{i}" for i in range(5)]
    order = rendezvous_order("abc123", ids)
    assert sorted(order) == sorted(ids)
    assert order == rendezvous_order("abc123", list(reversed(ids)))


def test_rendezvous_minimal_disruption_on_leave_and_join():
    """Removing one replica moves ONLY the digests it was primary for;
    every other digest keeps its primary (the warm-tenant-stability
    property the affinity model rests on)."""
    ids = [f"r{i}" for i in range(4)]
    digests = [f"d{i:04x}" for i in range(64)]
    primary = {d: rendezvous_order(d, ids)[0] for d in digests}
    gone = "r2"
    rest = [i for i in ids if i != gone]
    for d in digests:
        new_primary = rendezvous_order(d, rest)[0]
        if primary[d] != gone:
            assert new_primary == primary[d], d
        else:
            assert new_primary in rest
    # join back: everything returns to the original assignment
    for d in digests:
        assert rendezvous_order(d, ids)[0] == primary[d]


def test_rendezvous_spreads_digests():
    ids = [f"r{i}" for i in range(3)]
    primaries = {rendezvous_order(f"digest{i}", ids)[0]
                 for i in range(48)}
    assert primaries == set(ids)  # no replica starves


# ------------------------------------------------------ discovery


def test_parse_static_replicas():
    recs = parse_static_replicas("127.0.0.1:8765, 10.0.0.2:9000")
    assert [(r["host"], r["port"]) for r in recs] == \
        [("127.0.0.1", 8765), ("10.0.0.2", 9000)]
    with pytest.raises(ValueError):
        parse_static_replicas("no-port")


def test_discover_replicas_reads_and_skips_torn(tmp_path):
    good = {"tag": "replica0", "host": "127.0.0.1", "port": 1234,
            "pid": 42}
    (tmp_path / "replica0.json").write_text(json.dumps(good))
    (tmp_path / "torn.json").write_text('{"host": "x", ')
    (tmp_path / "notes.txt").write_text("ignored")
    recs = discover_replicas(str(tmp_path))
    assert len(recs) == 1 and recs[0]["tag"] == "replica0"
    assert recs[0]["port"] == 1234
    assert discover_replicas(str(tmp_path / "missing")) == []


def test_port_dir_join_and_leave(tmp_path, stubs):
    """Replicas joining the port-dir enter the table (and rotation
    after proving readyz); a removed record leaves the table."""
    d = tmp_path / "replicas"
    d.mkdir()
    r = _router(port_dir=str(d))
    r.refresh_discovery()
    assert r.stats()["replicas"] == {}
    for i, stub in enumerate(stubs[:2]):
        (d / f"replica{i}.json").write_text(json.dumps(
            {"tag": f"replica{i}", "host": "127.0.0.1",
             "port": stub.port}))
    r.refresh_discovery()
    r.poll_once()
    st = r.stats()
    assert sorted(st["replicas"]) == ["replica0", "replica1"]
    assert st["in_rotation"] == ["replica0", "replica1"]
    (d / "replica1.json").unlink()
    r.refresh_discovery()
    assert sorted(r.stats()["replicas"]) == ["replica0"]


def test_static_replicas_survive_port_dir_reconciliation(tmp_path, stubs):
    """Static (configured) membership is never dropped by port-dir
    reconciliation — only discovered records can leave."""
    d = tmp_path / "replicas"
    d.mkdir()
    (d / "dyn0.json").write_text(json.dumps(
        {"tag": "dyn0", "host": "127.0.0.1", "port": stubs[1].port}))
    r = _router(port_dir=str(d),
                static_replicas=[{"tag": "stat0", "host": "127.0.0.1",
                                  "port": stubs[0].port}])
    r.refresh_discovery()
    assert sorted(r.stats()["replicas"]) == ["dyn0", "stat0"]
    (d / "dyn0.json").unlink()
    r.refresh_discovery()
    assert sorted(r.stats()["replicas"]) == ["stat0"]


def test_relaunched_replica_new_port_reproves(tmp_path, stubs):
    d = tmp_path / "replicas"
    d.mkdir()
    (d / "replica0.json").write_text(json.dumps(
        {"tag": "replica0", "host": "127.0.0.1", "port": stubs[0].port}))
    r = _router(port_dir=str(d))
    r.refresh_discovery()
    r.poll_once()
    assert r.stats()["in_rotation"] == ["replica0"]
    # supervisor relaunch on a fresh port: must re-prove readiness
    (d / "replica0.json").write_text(json.dumps(
        {"tag": "replica0", "host": "127.0.0.1", "port": stubs[1].port}))
    r.refresh_discovery()
    assert r.stats()["in_rotation"] == []
    r.poll_once()
    assert r.stats()["in_rotation"] == ["replica0"]


# ------------------------------------------------- rotation hysteresis


def test_rotation_eject_and_readmit_hysteresis(stubs):
    r = _router(static_replicas=_static(stubs), eject_after=2,
                readmit_after=2)
    r.poll_once()
    assert r.stats()["in_rotation"] == []  # one ok poll < readmit_after
    r.poll_once()
    assert sorted(r.stats()["in_rotation"]) == ["r0", "r1", "r2"]
    stubs[1].set_ready(False)
    r.poll_once()
    # hysteresis: ONE failed poll does not eject
    assert "r1" in r.stats()["in_rotation"]
    r.poll_once()
    assert "r1" not in r.stats()["in_rotation"]
    # recovery: two good polls readmit
    stubs[1].set_ready(True)
    r.poll_once()
    assert "r1" not in r.stats()["in_rotation"]
    r.poll_once()
    assert "r1" in r.stats()["in_rotation"]


def test_unreachable_replica_ejects(stubs):
    recs = _static(stubs)
    stubs[2].close()  # port now refuses connections
    r = _router(static_replicas=recs, eject_after=1, readmit_after=1)
    r.poll_once()
    st = r.stats()
    assert "r2" not in st["in_rotation"]
    assert sorted(st["in_rotation"]) == ["r0", "r1"]
    assert "unreachable" in st["replicas"]["r2"]["last_reason"]


# ------------------------------------------------------- routing


def _ready(r: Router, n: int = 1):
    for _ in range(n):
        r.poll_once()


def test_forward_digest_affinity_lands_on_primary(stubs):
    r = _router(static_replicas=_static(stubs), readmit_after=1)
    _ready(r)
    tags = ["r0", "r1", "r2"]
    for digest in ("aaaa11", "bbbb22", "cccc33", "dddd44"):
        primary = rendezvous_order(digest, tags)[0]
        idx = tags.index(primary)
        before = stubs[idx].n_requests
        status, _h, body, routed = r.forward(
            "POST", "/augment", b"x", {"Content-Length": "1"}, digest)
        assert status == 200 and routed == primary
        assert stubs[idx].n_requests == before + 1
    st = r.stats()
    assert st["affinity"]["hit_rate"] == 1.0
    assert st["outcomes"]["ok"] == 4 and st["failovers"] == 0


def test_forward_headers_pass_through(stubs):
    r = _router(static_replicas=_static(stubs), readmit_after=1)
    _ready(r)
    r.forward("POST", "/augment", b"xy",
              {"Content-Length": "2", "X-FAA-Policy-Digest": "abcd12",
               "X-FAA-Deadline-Ms": "250"}, "abcd12")
    rec = [q for s in stubs for q in s.requests][0]
    assert rec["digest"] == "abcd12" and rec["deadline"] == "250"
    assert rec["n"] == 2


def test_forward_failover_on_503_honors_retry_after(stubs):
    """A 429/503 upstream answer fails the request over AND backs the
    replica off for its Retry-After window — new traffic routes around
    it until the window passes."""
    r = _router(static_replicas=_static(stubs), readmit_after=1,
                failover_attempts=2)
    _ready(r)
    digest = "feed01"
    tags = ["r0", "r1", "r2"]
    order = rendezvous_order(digest, tags)
    primary_stub = stubs[tags.index(order[0])]
    second_tag = order[1]
    primary_stub.set_augment(429, {"Retry-After": "30"})
    status, _h, _b, routed = r.forward(
        "POST", "/augment", b"x", {"Content-Length": "1"}, digest)
    assert status == 200 and routed == second_tag
    st = r.stats()
    assert st["failovers"] == 1
    assert st["replicas"][order[0]]["backing_off"] is True
    # the backoff window steers the NEXT request straight to the
    # second candidate — no repeat attempt against the cooling replica
    before = primary_stub.n_requests
    status, _h, _b, routed = r.forward(
        "POST", "/augment", b"x", {"Content-Length": "1"}, digest)
    assert status == 200 and routed == second_tag
    assert primary_stub.n_requests == before
    assert r.stats()["affinity"]["misses"] >= 2


def test_forward_bounded_failover_passes_through_last_answer(stubs):
    """Every candidate rejecting: the router answers with the LAST
    upstream rejection (Retry-After included) instead of retrying
    forever — the bounded-failover contract."""
    for s in stubs:
        s.set_augment(503, {"Retry-After": "7"})
    r = _router(static_replicas=_static(stubs), readmit_after=1,
                failover_attempts=2)
    _ready(r)
    status, headers, _b, _routed = r.forward(
        "POST", "/augment", b"x", {"Content-Length": "1"}, "cafe55")
    assert status == 503
    assert any(k.lower() == "retry-after" and v == "7"
               for k, v in headers.items())
    assert sum(s.n_requests for s in stubs) == 3  # 1 + failover_attempts
    assert r.stats()["outcomes"]["upstream_reject"] == 1


def test_forward_no_replica_is_structured_503(stubs):
    r = _router(static_replicas=_static(stubs))  # nothing polled yet
    status, headers, body, routed = r.forward(
        "POST", "/augment", b"x", {"Content-Length": "1"}, "ab")
    assert status == 503 and routed is None
    assert json.loads(body)["type"] == "no_replica"
    assert r.stats()["outcomes"]["no_replica"] == 1


def test_forward_transport_failure_fails_over(stubs):
    r = _router(static_replicas=_static(stubs), readmit_after=1,
                failover_attempts=2)
    _ready(r)
    digest = "dead77"
    tags = ["r0", "r1", "r2"]
    order = rendezvous_order(digest, tags)
    stubs[tags.index(order[0])].close()  # primary vanishes post-poll
    status, _h, _b, routed = r.forward(
        "POST", "/augment", b"x", {"Content-Length": "1"}, digest)
    assert status == 200 and routed == order[1]


def test_digestless_requests_round_robin(stubs):
    r = _router(static_replicas=_static(stubs), readmit_after=1)
    _ready(r)
    for _ in range(6):
        status, _h, _b, _routed = r.forward(
            "POST", "/augment", b"x", {"Content-Length": "1"}, None)
        assert status == 200
    counts = [s.n_requests for s in stubs]
    assert counts == [2, 2, 2]


def test_batch_forwarder_falls_back_per_part_exactly_once(stubs):
    """A replica dying between lane assignment and the framed flush:
    every coalesced entry falls back through the singleton forward
    path to a survivor EXACTLY once — no lost parts, no double-sends,
    and each fallback is visible in the fallbacks counter."""
    from fast_autoaugment_tpu.serve.router import BatchForwarder

    r = _router(static_replicas=_static(stubs), readmit_after=1,
                failover_attempts=2)
    _ready(r)
    fwd = BatchForwarder(r, window_ms=400.0)
    digest = "feed99"
    tags = ["r0", "r1", "r2"]
    victim_tag = rendezvous_order(digest, tags)[0]
    victim = stubs[tags.index(victim_tag)]
    results: list = [None] * 3

    def go(i: int) -> None:
        results[i] = fwd.submit(
            f"part{i}".encode(),
            {"Content-Type": "application/octet-stream",
             "Content-Length": "5"}, digest)

    threads = [threading.Thread(target=go, args=(i,)) for i in range(3)]
    for t in threads:
        t.start()
    time.sleep(0.1)   # all three entries are parked in the victim lane
    victim.close()    # replica dies BEFORE the leader ships the frame
    for t in threads:
        t.join(timeout=30)
    assert all(res is not None for res in results)
    assert [res[0] for res in results] == [200, 200, 200]
    assert victim_tag not in {res[3] for res in results}
    # exactly-once: the survivors saw each part once, as singleton
    # /augment POSTs (never a replayed frame), and nothing twice
    survivor_reqs = [q for s in stubs if s is not victim
                     for q in s.requests]
    assert len(survivor_reqs) == 3
    assert all(q["path"] == "/augment" for q in survivor_reqs)
    assert fwd.stats()["fallbacks"] == 3
    assert fwd.stats()["flushes"] == 0  # the framed flush never landed


# ------------------------------------------------- FAA_FAULT verbs


def test_fault_grammar_parses_new_verbs():
    faults = faultinject.parse_fault_spec(
        "replica_down@request=5;readyz_flap@period=3")
    assert [f["kind"] for f in faults] == ["replica_down", "readyz_flap"]
    assert faults[0]["request"] == 5 and faults[1]["period"] == 3
    with pytest.raises(ValueError):
        faultinject.parse_fault_spec("replica_down@step=5")  # wrong key
    with pytest.raises(ValueError):
        faultinject.parse_fault_spec("readyz_flap@request=1")


def test_replica_down_fault_ejects_deterministic_victim(stubs):
    """replica_down@request=N: after N routed requests the first
    sorted replica is declared dead at the health-poll seam — latched,
    like a killed process — and traffic fails over."""
    os.environ["FAA_FAULT"] = "replica_down@request=2"
    faultinject.reset()
    r = _router(static_replicas=_static(stubs), readmit_after=1,
                eject_after=1, failover_attempts=2)
    _ready(r)
    assert len(r.stats()["in_rotation"]) == 3
    for _ in range(2):
        assert r.forward("POST", "/augment", b"x",
                         {"Content-Length": "1"}, "aa11")[0] == 200
    r.poll_once()  # the seam consults the routed-request counter
    st = r.stats()
    assert "r0" not in st["in_rotation"]  # sorted-first victim
    assert st["replicas"]["r0"]["forced_down"] is True
    # the dead replica stays dead (latched), traffic keeps flowing
    r.poll_once()
    assert "r0" not in r.stats()["in_rotation"]
    for digest in ("x1", "x2", "x3", "x4"):
        assert r.forward("POST", "/augment", b"x",
                         {"Content-Length": "1"}, digest)[0] == 200


def test_readyz_flap_fault_cycles_rotation(stubs):
    """readyz_flap@period=P alternates the victim's verdict every P
    polls: with eject_after=readmit_after=1 the rotation census
    follows the flap — the hysteresis-drill fixture."""
    os.environ["FAA_FAULT"] = "readyz_flap@period=2"
    faultinject.reset()
    r = _router(static_replicas=_static(stubs), readmit_after=1,
                eject_after=1)
    seen = []
    for _ in range(8):
        r.poll_once()
        seen.append("r0" in r.stats()["in_rotation"])
    # rounds 1-2 up, 3-4 down, 5-6 up, 7-8 down
    assert seen == [True, True, False, False, True, True, False, False]


def test_readyz_flap_hysteresis_rides_through_short_flap(stubs):
    """With eject_after above the flap period the rotation never
    ejects — the hysteresis absorbs the flapping backend."""
    os.environ["FAA_FAULT"] = "readyz_flap@period=1"
    faultinject.reset()
    r = _router(static_replicas=_static(stubs), readmit_after=1,
                eject_after=2)
    for _ in range(6):
        r.poll_once()
        assert "r0" in r.stats()["in_rotation"] or \
            r.stats()["poll_round"] < 2


# ----------------------------------------------------- cli + handler


def test_router_cli_parser_defaults():
    from fast_autoaugment_tpu.serve.router_cli import build_parser

    args = build_parser().parse_args(["--port-dir", "/tmp/x"])
    assert args.poll_interval == 0.5 and args.eject_after == 2
    assert args.readmit_after == 1 and args.failover_attempts == 2
    assert args.port == 8780 and args.telemetry == "off"


def test_router_http_handler_end_to_end(stubs):
    """The router's own HTTP surface over stub replicas: /augment
    proxies (with the routed-to header), /readyz reflects rotation,
    /stats carries the topology."""
    from http.client import HTTPConnection

    from fast_autoaugment_tpu.serve.router_cli import make_router_handler

    r = _router(static_replicas=_static(stubs), readmit_after=1)
    _ready(r)
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), make_router_handler(r))
    httpd.daemon_threads = True
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    port = httpd.server_address[1]
    try:
        def call(method, path, body=None, headers=None):
            conn = HTTPConnection("127.0.0.1", port, timeout=30)
            conn.request(method, path, body=body, headers=headers or {})
            resp = conn.getresponse()
            data = resp.read()
            conn.close()
            return resp, data

        resp, data = call("GET", "/readyz")
        assert resp.status == 200 and json.loads(data)["in_rotation"] == 3
        resp, data = call("POST", "/augment", body=b"imgs",
                          headers={"X-FAA-Policy-Digest": "aa77"})
        assert resp.status == 200 and data == b"ok"
        assert resp.getheader("X-FAA-Routed-To") in ("r0", "r1", "r2")
        resp, data = call("GET", "/stats")
        st = json.loads(data)
        assert st["affinity"]["hits"] == 1
        resp, data = call("GET", "/metrics")
        assert resp.status == 200
        assert "faa_router_requests_total" in data.decode()
        resp, data = call("POST", "/augment", body=b"")
        assert resp.status == 400  # empty body refused at the router
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_rotation_events_journaled(tmp_path, stubs):
    """Eject/readmit transitions land as typed rotation journal
    events (the faa_status serving-section source)."""
    from fast_autoaugment_tpu.core import telemetry as T

    T.enable_telemetry(str(tmp_path / "tel"), tb_bridge=False)
    try:
        r = _router(static_replicas=_static(stubs), readmit_after=1,
                    eject_after=1)
        r.poll_once()
        stubs[0].set_ready(False)
        r.poll_once()
        stubs[0].set_ready(True)
        r.poll_once()
        T.journal_flush()
        import glob

        recs = []
        for path in glob.glob(str(tmp_path / "tel" / "journal-*.jsonl")):
            with open(path) as fh:
                recs += [json.loads(ln) for ln in fh if ln.strip()]
        rot = [x for x in recs if x["type"] == "rotation"]
        actions = [(x["action"], x["replica"]) for x in rot]
        assert ("eject", "r0") in actions and ("readmit", "r0") in actions
    finally:
        T._disable_for_tests()


def test_poll_loop_thread_lifecycle(tmp_path, stubs):
    d = tmp_path / "replicas"
    d.mkdir()
    for i, stub in enumerate(stubs):
        (d / f"replica{i}.json").write_text(json.dumps(
            {"tag": f"replica{i}", "host": "127.0.0.1",
             "port": stub.port}))
    r = _router(port_dir=str(d), poll_interval_s=0.05, readmit_after=1)
    r.start()
    try:
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if len(r.stats()["in_rotation"]) == 3:
                break
            time.sleep(0.05)
        assert len(r.stats()["in_rotation"]) == 3
    finally:
        r.stop()
