"""Optimizer/schedule/EMA semantics tests, including parity runs against
the reference's torch implementations on CPU."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from fast_autoaugment_tpu.ops import schedules
from fast_autoaugment_tpu.ops.optim import (
    build_optimizer,
    ema_update,
    non_bn_mask,
    rmsprop_tf,
)


def _load_ref_rmsprop():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "ref_rmsprop", "/root/reference/FastAutoAugment/tf_port/rmsprop.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.RMSpropTF


def test_rmsprop_tf_matches_reference_torch():
    torch = pytest.importorskip("torch")
    import os

    if not os.path.exists("/root/reference/FastAutoAugment/tf_port/rmsprop.py"):
        pytest.skip("reference tree /root/reference not present on this host")
    RMSpropTF = _load_ref_rmsprop()

    rng = np.random.default_rng(0)
    w0 = rng.normal(size=(5, 3)).astype(np.float32)
    grads = [rng.normal(size=(5, 3)).astype(np.float32) for _ in range(4)]

    # torch reference
    p = torch.nn.Parameter(torch.tensor(w0.copy()))
    opt = RMSpropTF([p], lr=0.01, alpha=0.9, momentum=0.9, eps=1e-3)
    for g in grads:
        opt.zero_grad()
        p.grad = torch.tensor(g)
        opt.step()
    want = p.detach().numpy()

    # ours
    tx = rmsprop_tf(0.01, alpha=0.9, momentum=0.9, eps=1e-3)
    params = {"w": jnp.asarray(w0)}
    state = tx.init(params)
    for g in grads:
        updates, state = tx.update({"w": jnp.asarray(g)}, state, params)
        params = optax.apply_updates(params, updates)
    got = np.asarray(params["w"])
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_sgd_nesterov_matches_torch():
    torch = pytest.importorskip("torch")
    rng = np.random.default_rng(1)
    w0 = rng.normal(size=(4, 4)).astype(np.float32)
    grads = [rng.normal(size=(4, 4)).astype(np.float32) for _ in range(3)]

    p = torch.nn.Parameter(torch.tensor(w0.copy()))
    opt = torch.optim.SGD([p], lr=0.1, momentum=0.9, nesterov=True, weight_decay=0.0)
    for g in grads:
        opt.zero_grad()
        p.grad = torch.tensor(g)
        opt.step()
    want = p.detach().numpy()

    tx = optax.chain(optax.trace(decay=0.9, nesterov=True), optax.scale(-0.1))
    params = {"w": jnp.asarray(w0)}
    state = tx.init(params)
    for g in grads:
        updates, state = tx.update({"w": jnp.asarray(g)}, state, params)
        params = optax.apply_updates(params, updates)
    np.testing.assert_allclose(np.asarray(params["w"]), want, rtol=1e-5, atol=1e-6)


def test_non_bn_mask_excludes_bn_modules():
    params = {
        "conv1": {"kernel": jnp.zeros((3, 3))},
        "bn1": {"scale": jnp.ones(3), "bias": jnp.zeros(3)},
        "layer1_0": {
            "conv2": {"kernel": jnp.zeros((3, 3)), "bias": jnp.zeros(3)},
            "downsample_bn": {"scale": jnp.ones(3)},
        },
        "linear": {"kernel": jnp.zeros((4, 4)), "bias": jnp.zeros(4)},
    }
    mask = non_bn_mask(params)
    assert mask["conv1"]["kernel"] is True
    assert mask["bn1"]["scale"] is False and mask["bn1"]["bias"] is False
    assert mask["layer1_0"]["conv2"]["bias"] is True
    assert mask["layer1_0"]["downsample_bn"]["scale"] is False
    assert mask["linear"]["bias"] is True


def test_build_optimizer_applies_wd_and_clip():
    # built WITHOUT params — the non-BN mask must still apply (callable
    # mask evaluated at init; regression for mask=None decaying BN)
    params = {"conv": {"kernel": jnp.full((2, 2), 2.0)}, "bn": {"scale": jnp.full((2,), 2.0)}}
    conf = {"type": "sgd", "decay": 0.1, "clip": 1e9, "momentum": 0.0, "nesterov": False}
    tx = build_optimizer(conf, lambda s: 1.0)
    state = tx.init(params)
    grads = jax.tree.map(jnp.zeros_like, params)
    updates, _ = tx.update(grads, state, params)
    # conv gets -lr * wd * p, bn gets nothing
    np.testing.assert_allclose(np.asarray(updates["conv"]["kernel"]), -0.2)
    np.testing.assert_allclose(np.asarray(updates["bn"]["scale"]), 0.0)


def test_ema_tf_warmup():
    shadow = {"w": jnp.zeros(3)}
    new = {"w": jnp.ones(3)}
    # step 1: mu_t = min(0.9999, 2/11) = 2/11 -> shadow = (1 - 2/11)*1
    out = ema_update(shadow, new, 0.9999, 1)
    np.testing.assert_allclose(np.asarray(out["w"]), 1.0 - 2.0 / 11.0, rtol=1e-6)
    # very large step: mu_t ~ mu
    out = ema_update(shadow, new, 0.5, 10**6)
    np.testing.assert_allclose(np.asarray(out["w"]), 0.5, rtol=1e-4)


# ---------------------------------------------------------------------------
# schedules vs torch schedulers (stepped fractionally like the reference)
# ---------------------------------------------------------------------------


def test_cosine_closed_form():
    # The reference ran torch 1.2, where CosineAnnealingLR.step(epoch)
    # evaluates the CLOSED FORM eta_min + base*(1+cos(pi t/T))/2 at the
    # (fractional) epoch; modern torch uses a recursive chained formula
    # that diverges under fractional stepping, so we assert the closed
    # form directly.
    base, total = 0.1, 10.0
    fn = schedules.cosine(base, total)
    for t in [0.0, 0.25, 3.7, 9.99]:
        want = base * (1.0 + np.cos(np.pi * t / total)) / 2.0
        assert float(fn(jnp.float32(t))) == pytest.approx(want, rel=1e-4, abs=1e-7), t
    assert float(fn(jnp.float32(total))) == pytest.approx(0.0, abs=1e-7)


def test_multistep_boundaries():
    fn = schedules.multistep(1.0, (30, 60, 80))
    assert float(fn(jnp.float32(29.9))) == pytest.approx(1.0)
    assert float(fn(jnp.float32(30.0))) == pytest.approx(0.1)
    assert float(fn(jnp.float32(79.9))) == pytest.approx(0.01)
    assert float(fn(jnp.float32(80.0))) == pytest.approx(0.001)


def test_warmup_wrap():
    inner = schedules.cosine(0.1, 200.0)
    fn = schedules.warmup_wrap(inner, 0.1, multiplier=2.0, warmup_epoch=5.0)
    assert float(fn(jnp.float32(0.0))) == pytest.approx(0.1)
    assert float(fn(jnp.float32(2.5))) == pytest.approx(0.15)
    assert float(fn(jnp.float32(5.0))) == pytest.approx(0.2)
    # just after warmup: 2 * cosine(0+) ~ 0.2
    assert float(fn(jnp.float32(5.01))) == pytest.approx(0.2, rel=1e-3)


def test_build_schedule_from_conf():
    conf = {
        "lr": 0.1,
        "epoch": 200,
        "lr_schedule": {"type": "cosine", "warmup": {"multiplier": 2, "epoch": 5}},
    }
    fn = schedules.build_schedule(conf, steps_per_epoch=100)
    assert float(fn(0)) == pytest.approx(0.1)
    assert float(fn(250)) == pytest.approx(0.15)  # t=2.5
    assert float(fn(500)) == pytest.approx(0.2)
    # world scaling
    fn8 = schedules.build_schedule(conf, steps_per_epoch=100, world_lr_scale=8.0)
    assert float(fn8(0)) == pytest.approx(0.8)
