"""CLI and fleet launcher tests (local process supervision — no real SSH)."""

import json
import os
import subprocess
import sys
import tempfile

import pytest

from fast_autoaugment_tpu.launch.fleet import expand_hosts


def test_expand_hosts():
    assert expand_hosts("3") == ["task1", "task2", "task3"]
    assert expand_hosts("a, b,c") == ["a", "b", "c"]


def test_train_cli_smoke(tmp_path):
    from fast_autoaugment_tpu.launch.train_cli import main

    conf = tmp_path / "conf.yaml"
    conf.write_text(
        "model:\n  type: wresnet10_1\ndataset: synthetic\naug: default\n"
        "cutout: 0\nbatch: 8\nepoch: 1\nlr: 0.05\n"
        "lr_schedule:\n  type: cosine\n"
        "optimizer:\n  type: sgd\n  decay: 0.0001\n  momentum: 0.9\n  nesterov: true\n"
    )
    save = tmp_path / "ck.msgpack"
    result = main([
        "-c", str(conf), "--dataroot", str(tmp_path), "--save", str(save),
        "--cv-ratio", "0.2", "--evaluation-interval", "1",
    ])
    assert result["epoch"] == 1
    assert os.path.exists(save)

    # --only-eval on the saved checkpoint
    result2 = main([
        "-c", str(conf), "--dataroot", str(tmp_path), "--save", str(save),
        "--cv-ratio", "0.2", "--only-eval",
    ])
    assert result2["top1_test"] == pytest.approx(result["top1_test"], abs=1e-6)


def test_train_cli_overrides(tmp_path):
    from fast_autoaugment_tpu.launch.train_cli import main

    conf = tmp_path / "conf.yaml"
    conf.write_text(
        "model:\n  type: wresnet10_1\ndataset: synthetic\naug: default\n"
        "cutout: 0\nbatch: 8\nepoch: 2\nlr: 0.05\n"
        "lr_schedule:\n  type: cosine\n"
        "optimizer:\n  type: sgd\n  decay: 0.0001\n  momentum: 0.9\n  nesterov: true\n"
    )
    result = main([
        "-c", str(conf), "--dataroot", str(tmp_path), "epoch=1", "batch=16",
    ])
    assert result["epoch"] == 1


def test_all_conf_presets_parse():
    from fast_autoaugment_tpu.core.config import load_config
    from fast_autoaugment_tpu.models import get_model, num_class

    confdir = os.path.join(os.path.dirname(__file__), "..", "confs")
    presets = sorted(os.listdir(confdir))
    # the 16 reference presets must all be present (reference confs/);
    # extra repo-local presets (e.g. the search-validation config) are fine
    reference_presets = {
        "efficientnet_b0.yaml", "efficientnet_b0_condconv.yaml",
        "efficientnet_b1.yaml", "efficientnet_b2.yaml",
        "efficientnet_b3.yaml", "efficientnet_b4.yaml",
        "pyramid272_cifar.yaml", "resnet200.yaml", "resnet50.yaml",
        "resnet50_mixup.yaml", "shake26_2x112d_cifar.yaml",
        "shake26_2x32d_cifar.yaml", "shake26_2x96d_cifar.yaml",
        "wresnet28x10_cifar.yaml", "wresnet28x10_svhn.yaml",
        "wresnet40x2_cifar.yaml",
    }
    assert reference_presets <= set(presets)
    for name in presets:
        conf = load_config(os.path.join(confdir, name))
        assert conf["model"]["type"]
        # every preset's model must be constructible
        model_conf = dict(conf["model"], dataset=conf["dataset"])
        get_model(model_conf, num_class(conf["dataset"]))
        assert conf["optimizer"]["type"] in ("sgd", "rmsprop")
