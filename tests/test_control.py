"""Closed-loop control plane (ISSUE 14, fast_autoaugment_tpu/control/):
CUSUM drift detection over journal-derived traffic statistics, the
FAA_FAULT drift verb, the served-traffic stats seam, reload digest /
provenance echo, the router canary split, the promotion gate, the
end-to-end loop state machine on stub transports, and the truncated
trial-log warm-start byte-identity pins.

All host-only / no-XLA-compile (tier-1 discipline); the live
3-replica drill is tests/test_control_e2e.py (slow).
"""

from __future__ import annotations

import glob
import json
import os
import sys
import threading

import numpy as np
import pytest

from fast_autoaugment_tpu.core import telemetry as T
from fast_autoaugment_tpu.control import (
    CanaryController,
    ControlLoop,
    CusumMeanShift,
    DriftMonitor,
    PromotionGate,
    TrafficSampleReader,
    compare_arms,
    load_provenance,
    policy_file_digest,
    provenance_path,
    select_canary_replicas,
    write_provenance,
)
from fast_autoaugment_tpu.control.research import seed_research_dir
from fast_autoaugment_tpu.serve.policy_server import PolicyServer
from fast_autoaugment_tpu.serve.router import Router
from fast_autoaugment_tpu.utils import faultinject

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
sys.path.insert(0, os.path.join(_REPO, "tools"))

IMG = 4


@pytest.fixture(autouse=True)
def _clean_env(monkeypatch):
    monkeypatch.delenv("FAA_TELEMETRY", raising=False)
    monkeypatch.delenv("FAA_FAULT", raising=False)
    monkeypatch.delenv("FAA_ATTEMPT", raising=False)
    faultinject.reset()
    # the registry is process-wide; loop/monitor counters share labels
    # across tests (unlike PolicyServer's per-instance server ids)
    T.registry()._reset_for_tests()
    yield
    T._disable_for_tests()
    faultinject.reset()


@pytest.fixture()
def journal_dir(tmp_path):
    d = str(tmp_path / "tel")
    T.enable_telemetry(d, tb_bridge=False)
    yield d
    T._disable_for_tests()


def _journal_records(directory):
    T.journal_flush()
    records = []
    for path in sorted(glob.glob(
            os.path.join(directory, "journal-*.jsonl"))):
        with open(path) as fh:
            records.extend(json.loads(ln) for ln in fh if ln.strip())
    records.sort(key=lambda r: r["seq"])
    return records


class DummyApplier:
    """Host-only applier: shifts pixels by `delta` (identifies WHICH
    policy served a batch) and carries a digest like the AOT applier."""

    def __init__(self, delta=1.0, digest=None):
        self.delta = float(delta)
        self.dispatch = "exact"
        self.max_batch = 8
        self.image = IMG
        self.channels = 3
        self.num_sub = 1
        self.shapes = (8,)
        self.digest = digest or f"dummy{delta:g}"

    def apply(self, images, keys):
        return np.asarray(images, np.float32) + self.delta


def _images(n, value=100.0):
    return np.full((n, IMG, IMG, 3), value, np.float32)


def _keys(n):
    return np.zeros((n, 2), np.uint32)


# ------------------------------------------------- FAA_FAULT drift verb


def test_drift_verb_parses_and_rejects():
    fs = faultinject.parse_fault_spec("drift@dispatch=3,shift=40.5")
    assert fs[0] == {"kind": "drift", "dispatch": 3, "shift": 40.5,
                     "fired": False}
    with pytest.raises(ValueError, match="missing"):
        faultinject.parse_fault_spec("drift@dispatch=3")
    with pytest.raises(ValueError, match="takes keys"):
        faultinject.parse_fault_spec("drift@dispatch=3,shift=1,bogus=2")


def test_drift_verb_latches_from_coordinate():
    plan = faultinject.FaultPlan(
        faultinject.parse_fault_spec("drift@dispatch=3,shift=40"))
    assert plan.drift_shift(1) is None
    assert plan.drift_shift(2) is None
    assert plan.drift_shift(3) == 40.0
    assert plan.drift_shift(2) is None  # below the coordinate: no fire
    assert plan.drift_shift(9) == 40.0  # latched at/past it


def test_drift_verb_attempt_gated(monkeypatch):
    plan = faultinject.FaultPlan(faultinject.parse_fault_spec(
        "drift@dispatch=1,shift=10,attempt=2"))
    assert plan.drift_shift(5) is None  # attempt 1: gated off
    monkeypatch.setenv("FAA_ATTEMPT", "2")
    assert plan.drift_shift(5) == 10.0


# ------------------------------------------------------------ the CUSUM


def test_cusum_stationary_traffic_never_trips():
    # default k/h: the slack absorbs in-band noise AND the frozen
    # window's estimation error (drift.py docstring has the measured
    # false-trip table behind these defaults)
    for seed in range(5):
        det = CusumMeanShift("m", baseline_n=20)
        rng = np.random.default_rng(seed)
        for v in 100.0 + rng.normal(0, 1.0, 1000):
            assert det.update(float(v)) is None, seed
        assert det.baselined


def test_cusum_mean_shift_trips_deterministically():
    def run():
        det = CusumMeanShift("m", baseline_n=10, k=0.5, h=8.0)
        rng = np.random.default_rng(1)
        vals = list(100.0 + rng.normal(0, 1.0, 60))
        vals += list(104.0 + rng.normal(0, 1.0, 60))  # the shift
        for i, v in enumerate(vals):
            ev = det.update(float(v))
            if ev is not None:
                return i, ev
        raise AssertionError("shift never detected")

    i1, ev1 = run()
    i2, ev2 = run()
    assert (i1, ev1) == (i2, ev2)  # seeded: same verdict, same sample
    assert ev1["direction"] == "up" and i1 >= 60
    assert ev1["stat"] > ev1["threshold"]
    assert abs(ev1["baseline_mean"] - 100.0) < 1.5


def test_cusum_detects_downward_shift_and_resets():
    det = CusumMeanShift("m", baseline_n=5, k=0.5, h=4.0)
    for _ in range(5):
        det.update(50.0)
    det.update(50.001)  # sigma floors at min_sigma; tiny jitter ok
    ev = None
    for _ in range(50):
        ev = det.update(40.0)
        if ev:
            break
    assert ev and ev["direction"] == "down"
    det.reset()
    assert not det.baselined and det.samples == 0


# ------------------------------------------ journal reader + monitor


def test_traffic_reader_tails_incrementally(tmp_path):
    d = str(tmp_path)
    path = os.path.join(d, "journal-hostX-a1-p1.000.jsonl")

    def rec(seq, mean):
        return json.dumps({"type": "dispatch", "label": "serve_dispatch",
                           "host": "hostX", "pid": 1, "seq": seq,
                           "t_wall": float(seq), "t_mono": float(seq),
                           "input_mean": mean, "reward_proxy": 0.1})

    reader = TrafficSampleReader(d)
    assert reader.poll() == []
    with open(path, "w") as fh:
        fh.write(rec(0, 100.0) + "\n" + rec(1, 101.0) + "\n")
    assert [r["seq"] for r in reader.poll()] == [0, 1]
    assert reader.poll() == []  # nothing new
    # a torn tail is not consumed until its newline lands
    with open(path, "a") as fh:
        fh.write(rec(2, 102.0))
    assert reader.poll() == []
    with open(path, "a") as fh:
        fh.write("\n")
    assert [r["seq"] for r in reader.poll()] == [2]
    # non-serve and field-less dispatch records are filtered out
    with open(path, "a") as fh:
        fh.write(json.dumps({"type": "dispatch", "label": "train",
                             "seq": 3, "input_mean": 1}) + "\n")
        fh.write(json.dumps({"type": "dispatch",
                             "label": "serve_dispatch", "seq": 4}) + "\n")
    assert reader.poll() == []


def test_drift_monitor_latches_and_rebaselines(journal_dir):
    feed: list[list[dict]] = []
    monitor = DriftMonitor(lambda: feed.pop(0) if feed else [],
                           metrics=("input_mean",), baseline_n=5,
                           cusum_k=0.5, cusum_h=4.0, name="drift-test")

    def samples(vals):
        return [{"input_mean": v, "host": "hostX", "seq": i}
                for i, v in enumerate(vals)]

    feed.append(samples([100.0, 101.0] * 5))
    assert monitor.poll() is None
    feed.append(samples([140.0] * 20))
    verdict = monitor.poll()
    assert verdict is not None and verdict["metric"] == "input_mean"
    assert verdict["direction"] == "up"
    assert monitor.latched
    # latched: further drifted samples produce no NEW verdict
    feed.append(samples([140.0] * 20))
    assert monitor.poll() is None
    # the verdict landed in the journal with its evidence inline
    drift_events = [r for r in _journal_records(journal_dir)
                    if r["type"] == "drift"]
    assert len(drift_events) == 1
    ev = drift_events[0]
    assert ev["label"] == "drift-test" and ev["stat"] > ev["threshold"]
    assert ev["baseline_mean"] is not None
    # rebaseline: the new regime becomes normal, then a NEW shift trips
    monitor.rebaseline()
    assert not monitor.latched
    feed.append(samples([140.0, 141.0] * 5))
    assert monitor.poll() is None
    feed.append(samples([100.0] * 20))
    second = monitor.poll()
    assert second is not None and second["direction"] == "down"
    assert second["id"] != verdict["id"]


# ------------------------------------- serve traffic stats + injection


def test_traffic_stats_gauges_journal_and_stats(journal_dir):
    srv = PolicyServer(DummyApplier(), max_wait_ms=1,
                       traffic_stats=True).start()
    try:
        srv.augment(_images(4), _keys(4))
        srv.augment(_images(4, value=200.0), _keys(4))
    finally:
        srv.stop()
    st = srv.stats()
    assert st["traffic"]["samples"] == 2
    assert st["traffic"]["input_mean"] is not None
    assert abs(st["traffic"]["reward_proxy"] - 1.0 / 255) < 1e-6
    disp = [r for r in _journal_records(journal_dir)
            if r["type"] == "dispatch" and r["label"] == "serve_dispatch"]
    assert len(disp) == 2
    assert disp[0]["input_mean"] == 100.0
    assert disp[1]["input_mean"] == 200.0
    assert all("reward_proxy" in d and "input_std" in d for d in disp)
    # the gauges are scrape-visible (the canary comparator's surface)
    text = T.registry().prometheus_text()
    assert f'faa_serve_reward_proxy{{server="{srv._server_id}"}}' in text


def test_traffic_stats_off_is_historical_stream(journal_dir):
    srv = PolicyServer(DummyApplier(), max_wait_ms=1).start()
    try:
        srv.augment(_images(4), _keys(4))
    finally:
        srv.stop()
    assert "traffic" not in srv.stats()
    disp = [r for r in _journal_records(journal_dir)
            if r["type"] == "dispatch" and r["label"] == "serve_dispatch"]
    assert disp and all("input_mean" not in d for d in disp)
    snap = T.registry().snapshot()["gauges"]
    assert not any(k.startswith("faa_serve_input_mean")
                   and f'server="{srv._server_id}"' in k for k in snap)


def test_drift_injection_shifts_inputs_and_stats(monkeypatch):
    monkeypatch.setenv("FAA_FAULT", "drift@dispatch=2,shift=50")
    faultinject.reset()
    srv = PolicyServer(DummyApplier(delta=0.0), max_wait_ms=1,
                       traffic_stats=True).start()
    try:
        out1 = srv.augment(_images(2), _keys(2))
        out2 = srv.augment(_images(2), _keys(2))
        out3 = srv.augment(_images(2), _keys(2))
    finally:
        srv.stop()
    # dispatch 1 unshifted; dispatches 2+ shifted (latched)
    assert float(out1.mean()) == 100.0
    assert float(out2.mean()) == 150.0
    assert float(out3.mean()) == 150.0


def test_reload_echoes_digest_and_journal(journal_dir):
    srv = PolicyServer(DummyApplier(digest="aaa111"), max_wait_ms=1).start()
    try:
        info = srv.swap_applier(DummyApplier(2.0, digest="bbb222"))
    finally:
        srv.stop()
    assert info["digest"] == "bbb222"
    assert srv.stats()["policy_digest"] == "bbb222"
    rel = [r for r in _journal_records(journal_dir)
           if r["type"] == "reload"]
    assert rel and rel[-1]["digest"] == "bbb222"


# -------------------------------------------- provenance sidecar


def test_provenance_roundtrip_and_digest(tmp_path):
    policy = [[["Rotate", 0.5, 0.4], ["Invert", 0.2, 0.0]]]
    ppath = str(tmp_path / "final_policy.json")
    with open(ppath, "w") as fh:
        json.dump(policy, fh)
    assert load_provenance(ppath) is None
    side = write_provenance(ppath, {"kind": "test", "topup_trials": 7})
    assert side == provenance_path(ppath)
    assert side.endswith("final_policy.provenance.json")
    prov = load_provenance(ppath)
    assert prov["kind"] == "test" and prov["topup_trials"] == 7
    assert prov["schema_version"] == 1
    # the sidecar digest IS the serving-plane digest of the bytes
    from fast_autoaugment_tpu.policies.archive import policy_to_tensor
    from fast_autoaugment_tpu.serve.policy_server import policy_digest

    expect = policy_digest(policy_to_tensor(
        [[(op, float(p), float(lv)) for op, p, lv in sub]
         for sub in policy]))
    assert prov["policy_digest"] == expect == policy_file_digest(ppath)
    # serve_cli's loader resolves the same sidecar
    from fast_autoaugment_tpu.serve.serve_cli import load_policy_provenance

    assert load_policy_provenance(ppath)["policy_digest"] == expect
    assert load_policy_provenance(str(tmp_path / "none.json")) is None


def test_seed_research_dir_copies_substrate(tmp_path):
    base = tmp_path / "base"
    base.mkdir()
    (base / "search_trials.json").write_text('{"0": []}')
    (base / "wresnet_cifar10_fold0_ratio0.40.msgpack").write_text("ckpt")
    (base / "audit.json").write_text("{}")
    (base / "final_policy.json").write_text("[]")
    (base / "search_result.json").write_text("{}")
    (base / "journal-host0-a1-p1.000.jsonl").write_text("")
    out = tmp_path / "cand"
    copied = seed_research_dir(str(base), str(out))
    assert "search_trials.json" in copied
    assert "wresnet_cifar10_fold0_ratio0.40.msgpack" in copied
    assert "audit.json" in copied
    assert not (out / "final_policy.json").exists()
    assert not (out / "search_result.json").exists()
    assert not list(out.glob("journal-*"))
    with pytest.raises(ValueError, match="unreadable base"):
        seed_research_dir(str(out / "missing"), str(tmp_path / "x"))
    empty = tmp_path / "empty"
    empty.mkdir()
    with pytest.raises(ValueError, match="no trial log"):
        seed_research_dir(str(empty), str(tmp_path / "y"))


# ------------------------------------------------ canary selection/gate


def test_select_canary_replicas_deterministic():
    tags = ["replica0", "replica1", "replica2"]
    a = select_canary_replicas("digest-a", tags, 1)
    assert a == select_canary_replicas("digest-a", list(reversed(tags)), 1)
    assert len(a) == 1 and a[0] in tags
    # at least one replica always stays baseline
    assert len(select_canary_replicas("digest-a", tags, 99)) == 2
    with pytest.raises(ValueError, match=">= 2 replicas"):
        select_canary_replicas("d", ["only"], 1)
    # the subset is the rendezvous prefix for THIS digest
    from fast_autoaugment_tpu.serve.router import rendezvous_order

    assert select_canary_replicas("digest-b", tags, 2) == \
        rendezvous_order("digest-b", sorted(tags))[:2]


def test_compare_arms_math():
    samples = {
        "c0": {"reachable": True, "reward_proxy": 0.30,
               "new_dispatches": 5, "new_breaker_fires": 0},
        "b0": {"reachable": True, "reward_proxy": 0.10,
               "new_dispatches": 6, "new_breaker_fires": 0},
        "b1": {"reachable": True, "reward_proxy": 0.20,
               "new_dispatches": 7, "new_breaker_fires": 1},
        "dead": {"reachable": False},
    }
    ev = compare_arms(samples, ["c0"], target=0.25)
    assert ev["canary"]["replicas"] == 1
    assert ev["baseline"]["replicas"] == 2
    assert abs(ev["canary"]["quality_distance"] - 0.05) < 1e-9
    # baseline distances: |0.1-0.25|=0.15, |0.2-0.25|=0.05 -> median 0.1
    assert abs(ev["baseline"]["quality_distance"] - 0.10) < 1e-9
    assert abs(ev["quality_delta"] - (-0.05)) < 1e-9
    assert ev["baseline"]["new_errors"] == 1
    assert ev["canary"]["new_dispatches"] == 5


def _evidence(delta, c_disp=5, b_disp=5, c_err=0):
    return {"canary": {"quality_distance": 0.1 + delta,
                       "new_dispatches": c_disp, "new_errors": c_err},
            "baseline": {"quality_distance": 0.1,
                         "new_dispatches": b_disp},
            "quality_delta": delta}


def test_gate_promotes_within_margin():
    g = PromotionGate(gate_polls=3, quality_margin=0.05)
    assert g.decide(_evidence(0.01))[0] is None
    assert g.decide(_evidence(-0.02))[0] is None
    action, reason, summary = g.decide(_evidence(0.03))
    assert action == "promote"
    assert summary["median_quality_delta"] == 0.01
    assert "within margin" in reason


def test_gate_rolls_back_on_quality_and_errors_and_starvation():
    g = PromotionGate(gate_polls=2, quality_margin=0.05)
    g.decide(_evidence(0.2))
    action, reason, _ = g.decide(_evidence(0.3))
    assert action == "rollback" and "exceeds margin" in reason
    # new canary errors roll back IMMEDIATELY
    g2 = PromotionGate(gate_polls=5, quality_margin=0.05)
    action, reason, _ = g2.decide(_evidence(0.0, c_err=2))
    assert action == "rollback" and "error" in reason
    # traffic-starved polls never judge; the timeout rolls back
    g3 = PromotionGate(gate_polls=2, quality_margin=0.05,
                       timeout_polls=4)
    for _ in range(3):
        assert g3.decide(_evidence(0.0, c_disp=0))[0] is None
    action, reason, _ = g3.decide(_evidence(0.0, c_disp=0))
    assert action == "rollback" and "starved" in reason


# ------------------------------------------------- router canary split


def _static_router(n=3, **kw):
    r = Router(static_replicas=[{"tag": f"replica{i}", "host": "h",
                                 "port": 1000 + i} for i in range(n)],
               **kw)
    for rep in r._replicas.values():
        rep.in_rotation = True
    return r


def test_router_canary_split_is_deterministic(journal_dir):
    r = _static_router()
    r.set_canary("digX", ["replica1"], every=3)
    firsts = [r.candidates(None)[0][0].tag for _ in range(9)]
    assert firsts.count("replica1") == 3  # exactly 1/3 of the traffic
    # canary-digest traffic steers TO the canary; other digests AWAY
    assert r.candidates("digX")[0][0].tag == "replica1"
    for d in ("someother", "third"):
        cands, _ = r.candidates(d)
        assert cands[0].tag != "replica1"
        assert cands[-1].tag == "replica1"  # still a last resort
    st = r.stats()["canary"]
    assert st["digest"] == "digX" and st["tags"] == ["replica1"]
    evs = [x for x in _journal_records(journal_dir)
           if x["type"] == "canary"]
    assert [e["action"] for e in evs] == ["split_set"]
    r.clear_canary()
    assert r.stats()["canary"] is None
    evs = [x for x in _journal_records(journal_dir)
           if x["type"] == "canary"]
    assert [e["action"] for e in evs] == ["split_set", "split_cleared"]


def test_router_canary_counts_arms(monkeypatch):
    r = _static_router()
    r.set_canary("digX", ["replica0"], every=2)
    monkeypatch.setattr(
        r, "_upstream",
        lambda rep, method, path, body, headers: (200, {}, b"ok"))
    for _ in range(6):
        status, _h, _b, routed = r.forward("POST", "/augment", b"x", {},
                                           None)
        assert status == 200
    routed_counts = r.stats()["canary"]["routed"]
    assert routed_counts["canary"] == 3
    assert routed_counts["baseline"] == 3


def test_router_cli_canary_admin_endpoint():
    from fast_autoaugment_tpu.serve.router_cli import (
        _RouterHTTPServer,
        make_router_handler,
    )
    import http.client

    r = _static_router()
    httpd = _RouterHTTPServer(("127.0.0.1", 0), make_router_handler(r))
    port = httpd.server_address[1]
    th = threading.Thread(target=httpd.serve_forever, daemon=True)
    th.start()
    try:
        def post(body):
            conn = http.client.HTTPConnection("127.0.0.1", port,
                                              timeout=10)
            try:
                conn.request("POST", "/canary",
                             body=json.dumps(body).encode())
                resp = conn.getresponse()
                return resp.status, json.loads(resp.read())
            finally:
                conn.close()

        status, out = post({"digest": "digZ", "replicas": ["replica2"],
                            "every": 4})
        assert status == 200 and out["canary"]["digest"] == "digZ"
        assert r.stats()["canary"]["every"] == 4
        status, out = post({"clear": True})
        assert status == 200 and out["canary"] is None
        status, out = post({"replicas": ["replica2"]})  # missing digest
        assert status == 400
        status, out = post({"digest": "d", "replicas": []})
        assert status == 400
    finally:
        httpd.shutdown()
        httpd.server_close()


# --------------------------------------------- the loop state machine


class _StubScraper:
    """Feeds the loop scripted per-replica quality rows."""

    def __init__(self, script):
        self.script = script  # list of {tag: row}
        self.calls = 0

    def sample(self, replicas):
        row = self.script[min(self.calls, len(self.script) - 1)]
        self.calls += 1
        return {str(r["tag"]): dict(row.get(str(r["tag"]),
                                            {"reachable": False}))
                for r in replicas}


def _loop_fixture(journal_dir, tmp_path, scraper_script,
                  research_exc=None):
    """A ControlLoop over stub transports; returns (loop, calls)."""
    policy = [[["Rotate", 0.5, 0.4], ["Invert", 0.2, 0.0]]]
    base = str(tmp_path / "baseline.json")
    cand = str(tmp_path / "candidate.json")
    with open(base, "w") as fh:
        json.dump(policy, fh)
    with open(cand, "w") as fh:
        json.dump([[["ShearX", 0.9, 0.1], ["Solarize", 0.3, 0.7]]], fh)
    write_provenance(cand, {"kind": "test_candidate"})
    cand_digest = policy_file_digest(cand)
    base_digest = policy_file_digest(base)

    calls = {"reloads": [], "router": []}

    def reload_fn(host, port, policy_path):
        calls["reloads"].append((host, port, policy_path))
        return {"digest": policy_file_digest(policy_path)}

    replicas = [{"tag": f"replica{i}", "host": "h", "port": 9000 + i}
                for i in range(3)]
    ctl = CanaryController(lambda: list(replicas), reload_fn=reload_fn)
    ctl._router_canary = lambda payload: calls["router"].append(payload)

    feed: list[list[dict]] = []

    def research_fn(verdict):
        if research_exc is not None:
            raise research_exc
        # the LOOP journals the research transition — stage fns stay
        # transport-agnostic (pinned by the chain assertion below)
        return {"policy": cand, "provenance": load_provenance(cand)}

    monitor = DriftMonitor(lambda: feed.pop(0) if feed else [],
                           metrics=("input_mean", "reward_proxy"),
                           baseline_n=5, cusum_k=0.5, cusum_h=4.0)
    loop = ControlLoop(
        monitor, research_fn, ctl,
        PromotionGate(gate_polls=2, quality_margin=0.05),
        _StubScraper(scraper_script),
        baseline_policy=base, baseline_digest=base_digest,
        n_canary=1, split_every=2)
    return loop, feed, calls, cand_digest, base_digest, cand, base


def _drift_feed(feed):
    def samples(vals):
        return [{"input_mean": v, "reward_proxy": 0.1, "host": "h",
                 "seq": i} for i, v in enumerate(vals)]

    feed.append(samples([100.0, 101.0] * 4))
    feed.append(samples([150.0] * 20))


def test_control_loop_promotes_end_to_end(journal_dir, tmp_path):
    canary_tag = select_canary_replicas(
        policy_file_digest_of_candidate(tmp_path),
        ["replica0", "replica1", "replica2"], 1)[0]
    good = {t: {"reachable": True, "reward_proxy": 0.1,
                "new_dispatches": 5, "new_breaker_fires": 0,
                "dispatches": 5, "breaker_fires": 0}
            for t in ("replica0", "replica1", "replica2")}
    loop, feed, calls, cand_digest, base_digest, cand, base = \
        _loop_fixture(journal_dir, tmp_path, [good])
    assert loop.step() == "watching"
    _drift_feed(feed)
    assert loop.step() == "watching"   # baseline window
    assert loop.step() == "research"   # verdict raised
    assert loop.step() == "canary"     # candidate produced
    assert loop.step() == "observing"  # rollout done, split armed
    assert calls["router"][0]["digest"] == cand_digest
    assert calls["router"][0]["replicas"] == [canary_tag]
    # rollout reloaded exactly the canary subset with the candidate
    assert [c[2] for c in calls["reloads"]] == [cand]
    assert loop.step() == "observing"  # gate poll 1/2
    assert loop.step() == "watching"   # gate poll 2/2 -> promote
    # promote reloaded the candidate on the two baseline replicas
    assert len(calls["reloads"]) == 3
    assert all(c[2] == cand for c in calls["reloads"])
    assert calls["router"][-1] == {"clear": True}
    # the candidate is the new baseline; the monitor re-baselined
    assert loop.baseline_digest == cand_digest
    assert not loop.monitor.latched
    assert loop.stats()["promotes"] == 1
    # the journal carries the full causal chain in order
    evs = [r for r in _journal_records(journal_dir)
           if r["type"] in ("drift", "research", "canary", "promote")]
    chain = [r["type"] for r in evs]
    assert chain == ["drift", "research", "canary", "promote"]
    promote = evs[-1]
    assert promote["digest"] == cand_digest
    assert promote["drift_id"] == evs[0]["id"]
    assert promote["detect_to_promote_sec"] >= 0
    assert promote["evidence"]["median_quality_delta"] is not None


def policy_file_digest_of_candidate(tmp_path):
    cand = str(tmp_path / "candidate.json")
    if not os.path.exists(cand):
        with open(cand, "w") as fh:
            json.dump([[["ShearX", 0.9, 0.1],
                        ["Solarize", 0.3, 0.7]]], fh)
    return policy_file_digest(cand)


def test_control_loop_rolls_back_on_bad_quality(journal_dir, tmp_path):
    cand_digest = policy_file_digest_of_candidate(tmp_path)
    canary_tag = select_canary_replicas(
        cand_digest, ["replica0", "replica1", "replica2"], 1)[0]
    rows = {}
    for t in ("replica0", "replica1", "replica2"):
        # canary's proxy sits far from the pre-drift baseline target
        proxy = 0.9 if t == canary_tag else 0.1
        rows[t] = {"reachable": True, "reward_proxy": proxy,
                   "new_dispatches": 5, "new_breaker_fires": 0,
                   "dispatches": 5, "breaker_fires": 0}
    loop, feed, calls, cand_digest, base_digest, cand, base = \
        _loop_fixture(journal_dir, tmp_path, [rows])
    _drift_feed(feed)
    for expect in ("watching", "research", "canary", "observing",
                   "observing"):
        assert loop.step() == expect
    assert loop.step() == "watching"  # gate filled -> rollback
    # the canary replica was reloaded BACK to the baseline policy
    assert calls["reloads"][-1][2] == base
    assert calls["router"][-1] == {"clear": True}
    assert loop.baseline_digest == base_digest  # unchanged
    assert loop.stats()["rollbacks"] == 1
    evs = [r["type"] for r in _journal_records(journal_dir)
           if r["type"] in ("drift", "canary", "promote", "rollback")]
    assert evs == ["drift", "canary", "rollback"]


def test_control_loop_survives_research_failure(journal_dir, tmp_path):
    loop, feed, calls, *_ = _loop_fixture(
        journal_dir, tmp_path, [{}],
        research_exc=RuntimeError("search exploded"))
    _drift_feed(feed)
    for expect in ("watching", "research"):
        assert loop.step() == expect
    assert loop.step() == "watching"  # failure -> back to watching
    assert calls["reloads"] == []     # nothing actuated
    marks = [r for r in _journal_records(journal_dir)
             if r["type"] == "mark"
             and r.get("event") == "research_failed"]
    assert marks and "search exploded" in marks[0]["error"]
    # the monitor stays latched: drift evidence is not forgotten just
    # because one search attempt failed
    assert loop.monitor.latched


def test_reload_digest_mismatch_aborts_rollout(journal_dir, tmp_path):
    good = {t: {"reachable": True, "reward_proxy": 0.1,
                "new_dispatches": 5, "new_breaker_fires": 0}
            for t in ("replica0", "replica1", "replica2")}
    loop, feed, calls, *_ = _loop_fixture(journal_dir, tmp_path, [good])
    loop.canary_ctl.reload_fn = \
        lambda host, port, path: {"digest": "wrong!"}
    _drift_feed(feed)
    for expect in ("watching", "research", "canary"):
        assert loop.step() == expect
    # the rollout verification failed -> rollback path, loop survives
    assert loop.step() == "watching"
    assert loop.stats()["rollbacks"] == 1
    marks = [r for r in _journal_records(journal_dir)
             if r["type"] == "mark"
             and r.get("event") == "rollout_failed"]
    assert marks and "echoed digest" in marks[0]["error"]


# ---------------------------------- truncated-log warm-start identity


def _stub_pipeline_log(num_search, k, seed=11, fold_trials=None,
                       max_inflight=1):
    """Drive run_fold_pipeline with a deterministic host-only stub
    evaluator (reward = policy-tensor sum mod 1) from an optional
    resumed trial log; returns the trial log."""
    import jax

    from fast_autoaugment_tpu.search.driver import make_search_space
    from fast_autoaugment_tpu.search.pipeline import (
        replay_trial_log,
        run_fold_pipeline,
    )
    from fast_autoaugment_tpu.search.tpe import TPE

    class _Stub:
        def evaluate(self, fold, params, batch_stats, policy_t, key):
            raise AssertionError("batched-only stub")

        def evaluate_batch(self, fold, params, batch_stats, policies_t,
                           keys):
            return [{"top1_valid": round(
                float(np.asarray(policies_t[i]).sum()) % 1.0, 6)}
                for i in range(int(policies_t.shape[0]))]

    tpe = TPE(make_search_space(1, 1), seed=seed, n_startup=4)
    fold_trials = list(fold_trials or [])
    replay_trial_log(tpe, fold_trials, k, num_search,
                     max_inflight=max_inflight)
    run_fold_pipeline(
        _Stub(), 0, None, None, tpe, jax.random.PRNGKey(0), fold_trials,
        num_search=num_search, trial_batch=k, actors=1, queue_depth=0,
        num_policy=1, num_op=1, persist=lambda: None,
        record_quarantine=lambda lo, hi, exc, worst: None)
    return fold_trials


def test_warm_start_from_truncated_log_is_byte_identical():
    """The satellite pin: a MID-ROUND truncated trial log replayed
    through the ledger and continued produces the uninterrupted run's
    log byte for byte (same JSON serialization)."""
    full = _stub_pipeline_log(num_search=12, k=3)
    assert len(full) == 12
    for cut in (7, 5, 10):  # none on a round boundary of K=3
        resumed = _stub_pipeline_log(num_search=12, k=3,
                                     fold_trials=full[:cut])
        assert json.dumps(resumed) == json.dumps(full), cut


def test_warm_start_topup_extends_and_zero_topup_is_identity():
    """Warm-start + top-up: the original budget's entries stay byte-
    identical and exactly the top-up appends; a zero top-up dispatches
    ZERO new trials."""
    full = _stub_pipeline_log(num_search=12, k=3)
    # zero new trials: the pipeline has nothing to dispatch
    same = _stub_pipeline_log(num_search=12, k=3, fold_trials=full)
    assert json.dumps(same) == json.dumps(full)
    # top-up of 6: first 12 entries byte-identical, 6 new
    topped = _stub_pipeline_log(num_search=18, k=3, fold_trials=full)
    assert len(topped) == 18
    assert json.dumps(topped[:12]) == json.dumps(full)
    # and topping up from a TRUNCATED log still converges to the same
    # 18-trial stream (replay + continue + extend in one pass)
    topped2 = _stub_pipeline_log(num_search=18, k=3,
                                 fold_trials=full[:7])
    assert json.dumps(topped2) == json.dumps(topped)


# --------------------------------------------- faa_status + CLI surface


def test_faa_status_control_section(journal_dir):
    T.emit("drift", "control", id="drift-1", metric="input_mean",
           direction="up", stat=9.1, value=150.0, baseline_mean=100.0)
    T.emit("research", "warm_start", candidate="/c/final_policy.json",
           digest="abc", topup_trials=25, wall_sec=4.2)
    T.emit("canary", "control", action="rollout", replica="replica2",
           digest="abc")
    T.emit("promote", "control", digest="abc", reason="within margin",
           drift_id="drift-1", canary=["replica2"],
           detect_to_promote_sec=3.21,
           evidence={"median_quality_delta": -0.01,
                     "quality_margin": 0.05})
    T.journal_flush()
    from faa_status import control_plane_status, fleet_status, render_table

    status = fleet_status(journal_dir)
    control = status["control"]
    assert control["drift_verdicts"][0]["id"] == "drift-1"
    assert control["researches"][0]["digest"] == "abc"
    assert control["promotes"] == 1 and control["rollbacks"] == 0
    assert control["last_decision"]["action"] == "promote"
    assert control["last_decision"]["detect_to_promote_sec"] == 3.21
    # the rollout precedes the decision -> no ACTIVE canary
    assert control["active_canary"] is None
    table = render_table(status)
    assert "control plane:" in table
    assert "drift drift-1" in table
    assert "PROMOTE abc" in table
    assert "detect->promote 3.21s" in table
    # a rollout AFTER the decision is the active canary
    T.emit("canary", "control", action="rollout", replica="replica0",
           digest="def")
    T.journal_flush()
    control = control_plane_status(
        __import__("faa_status").read_journal(journal_dir))
    assert control["active_canary"][0]["digest"] == "def"


def test_control_cli_parser_contract(tmp_path):
    from fast_autoaugment_tpu.launch.control_cli import build_parser

    args = build_parser().parse_args(
        ["--telemetry", "t", "--port-dir", "p",
         "--baseline-policy", "b.json", "--candidate-policy", "c.json",
         "--cusum-h", "4", "--gate-polls", "2"])
    assert args.candidate_policy == "c.json"
    assert args.cusum_h == 4.0
    from fast_autoaugment_tpu.launch.control_cli import main

    with pytest.raises(SystemExit):
        main(["--telemetry", "t", "--port-dir", "p",
              "--baseline-policy", "b.json"])  # no research seam
    with pytest.raises(SystemExit):
        main(["--telemetry", "t", "--port-dir", "p",
              "--baseline-policy", "b.json",
              "--research-cmd", "x", "--candidate-policy", "c.json"])


def test_search_cli_topup_flag():
    from fast_autoaugment_tpu.launch.search_cli import build_parser

    args = build_parser().parse_args(
        ["-c", "conf.yaml", "--topup-trials", "25"])
    assert args.topup_trials == 25
    assert build_parser().parse_args(["-c", "c.yaml"]).topup_trials == 0


def test_event_taxonomy_has_control_types():
    for etype in ("drift", "research", "canary", "promote", "rollback"):
        assert etype in T.EVENT_TYPES
