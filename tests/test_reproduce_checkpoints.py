"""End-to-end test of the published-checkpoint reproduction tool with a
locally-fabricated .pth (VERDICT round 1, missing item 4): manifest scan
-> torch import -> --only-eval -> report table + tolerance gate."""

import json
import os
import sys

import numpy as np
import pytest

from tests.test_datasets import _write_cifar10
from tests.test_forward_parity import ref  # noqa: F401  (fixture reuse)

torch = pytest.importorskip("torch")

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))


@pytest.mark.slow
def test_reproduce_tool_end_to_end(tmp_path, ref, capsys):  # noqa: F811
    import reproduce_checkpoints

    # fabricate the published WRN-40-2 checkpoint (random weights) under
    # its manifest name, and a miniature CIFAR-10 on disk
    ckpt_dir = tmp_path / "ckpts"
    os.makedirs(ckpt_dir)
    tm = ref["wrn"].WideResNet(40, 2, 0.0, 10)
    torch.save({"model": tm.state_dict(), "epoch": 200},
               ckpt_dir / "cifar10_wresnet40x2_top1_3.52.pth")
    _write_cifar10(str(tmp_path), n_per_batch=8)

    report = tmp_path / "repro.md"
    rc = reproduce_checkpoints.main([
        "--ckpt-dir", str(ckpt_dir), "--dataroot", str(tmp_path),
        "--batch", "8", "--report", str(report),
    ])
    out = capsys.readouterr().out

    # random weights cannot hit 3.52% error -> the tolerance gate fires
    assert rc == 1
    row = json.loads(next(ln for ln in out.splitlines() if ln.startswith("{")))
    assert row["file"] == "cifar10_wresnet40x2_top1_3.52.pth"
    assert 0.0 <= row["measured_err"] <= 100.0
    assert row["expected_err"] == 3.52
    text = report.read_text()
    assert "measured err%" in text and "wresnet40_2" in text
    # the other 12 manifest entries were skipped, not failed
    assert "12 manifest checkpoints not present" in out
