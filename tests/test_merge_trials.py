"""Trial/checkpoint pairing invariants of the multi-host merge tool.

A fold's TPE rewards are only meaningful against the fold checkpoint
they were computed with, so `tools/merge_trials.py` must never install
a checkpoint whose fold's winning trials came from somewhere else —
including the case where the pre-existing DESTINATION trials win a fold
but the destination has no checkpoint file (ADVICE round 1, low).
"""

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(__file__)), "tools"))

import merge_trials  # noqa: E402


def _mkdir(base, name, trials=None, ckpts=()):
    d = os.path.join(base, name)
    os.makedirs(d, exist_ok=True)
    if trials is not None:
        with open(os.path.join(d, "search_trials.json"), "w") as fh:
            json.dump(trials, fh)
    for ckpt in ckpts:
        with open(os.path.join(d, ckpt), "w") as fh:
            fh.write(name)  # payload identifies the origin dir
    return d


def test_destination_winning_fold_blocks_source_checkpoint(tmp_path):
    trial = [({"p": 0}, 0.5)]
    dest = _mkdir(tmp_path, "dest", trials={"0": trial * 3})  # wins fold 0, no ckpt
    src = _mkdir(tmp_path, "src", trials={"0": trial * 2},
                 ckpts=["fold0_wresnet40_2.msgpack"])

    merge_trials.main(["--into", dest, src])

    # src lost fold 0 -> its checkpoint must NOT be installed
    assert not os.path.exists(os.path.join(dest, "fold0_wresnet40_2.msgpack"))
    with open(os.path.join(dest, "search_trials.json")) as fh:
        assert len(json.load(fh)["0"]) == 3


def test_winning_source_checkpoint_travels_with_its_trials(tmp_path):
    trial = [({"p": 0}, 0.5)]
    dest = _mkdir(tmp_path, "dest")
    a = _mkdir(tmp_path, "a", trials={"1": trial * 5},
               ckpts=["fold1_wresnet40_2.msgpack"])
    b = _mkdir(tmp_path, "b", trials={"1": trial * 2},
               ckpts=["fold1_wresnet40_2.msgpack"])

    merge_trials.main(["--into", dest, b, a])

    path = os.path.join(dest, "fold1_wresnet40_2.msgpack")
    with open(path) as fh:
        assert fh.read() == "a", "checkpoint must come from the winning host"
    with open(os.path.join(dest, "search_trials.json")) as fh:
        assert len(json.load(fh)["1"]) == 5


def test_unclaimed_checkpoints_copy_if_missing(tmp_path):
    dest = _mkdir(tmp_path, "dest")
    src = _mkdir(tmp_path, "src", trials={},
                 ckpts=["fold2_wresnet40_2.msgpack"])
    merge_trials.main(["--into", dest, src])
    assert os.path.exists(os.path.join(dest, "fold2_wresnet40_2.msgpack"))
