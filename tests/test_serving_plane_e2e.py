"""The serving-plane acceptance drill (slow): a routed 3-replica fleet
with real AOT appliers serving mixed 2-policy traffic with digest
affinity; a COLD third policy warming into the tenancy LRU while warm
traffic keeps completing; one replica killed mid-run ejecting from
rotation with traffic failing over instead of collapsing; SIGTERM
drains at teardown (docs/SERVING.md "Acceptance")."""

from __future__ import annotations

import io
import json
import os
import signal
import subprocess
import sys
import threading
import time
from http.server import ThreadingHTTPServer

import numpy as np
import pytest

from fast_autoaugment_tpu.serve.router import Router
from fast_autoaugment_tpu.serve.router_cli import make_router_handler

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

IMG = 8
POLICY_A = [[["Rotate", 0.5, 0.4], ["Invert", 0.2, 0.0]]]
POLICY_B = [[["ShearX", 0.9, 0.1], ["Solarize", 0.3, 0.7]]]
POLICY_C = [[["Posterize", 0.7, 0.6], ["Contrast", 0.4, 0.5]]]


def _http(port, method, path, body=None, headers=None, timeout=60):
    import http.client

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    conn.request(method, path, body=body, headers=headers or {})
    resp = conn.getresponse()
    data = resp.read()
    conn.close()
    return resp, data


def _npz_body(imgs, seeds=None):
    buf = io.BytesIO()
    if seeds is None:
        np.savez(buf, images=imgs.astype(np.uint8))
    else:
        np.savez(buf, images=imgs.astype(np.uint8), seeds=seeds)
    return buf.getvalue()


def _wait_record(port_dir, tag, proc, timeout=180.0) -> int:
    path = os.path.join(port_dir, f"{tag}.json")
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if proc.poll() is not None:
            raise AssertionError(
                f"replica {tag} died early: rc={proc.returncode}")
        try:
            with open(path) as fh:
                return int(json.load(fh)["port"])
        except (OSError, ValueError, KeyError):
            time.sleep(0.2)
    raise AssertionError(f"replica {tag} never wrote its port record")


@pytest.mark.slow
def test_serving_plane_three_replica_drill(tmp_path):
    from fast_autoaugment_tpu.serve.policy_server import policy_digest
    from fast_autoaugment_tpu.serve.serve_cli import build_policy_tensor

    policy_dir = tmp_path / "policies"
    policy_dir.mkdir()
    paths = {}
    for name, spec in (("a", POLICY_A), ("b", POLICY_B), ("c", POLICY_C)):
        p = policy_dir / f"{name}.json"
        p.write_text(json.dumps(spec))
        paths[name] = str(p)
    digests = {name: policy_digest(build_policy_tensor(paths[name]))
               for name in paths}
    assert len(set(digests.values())) == 3

    port_dir = str(tmp_path / "replicas")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    procs = []
    router = None
    httpd = None
    try:
        # ---- 3 replicas: default policy A, tenancy capacity 2,
        # policy-dir recipes for B and C
        for i in range(3):
            env_i = dict(env, FAA_HOST_ID=str(i))
            procs.append(subprocess.Popen([
                sys.executable, "-m",
                "fast_autoaugment_tpu.serve.serve_cli",
                "--policy", paths["a"], "--image", str(IMG),
                "--shapes", "1,4", "--max-wait-ms", "2",
                "--tenant-capacity", "2",
                "--policy-dir", str(policy_dir),
                "--port", "0", "--port-dir", port_dir,
                "--host-tag", f"replica{i}",
            ], env=env_i, cwd=_REPO))
        ports = {}
        for i in range(3):
            ports[f"replica{i}"] = _wait_record(port_dir, f"replica{i}",
                                                procs[i])
        # pre-warm policy B everywhere (mixed warm 2-policy traffic)
        for tag, port in ports.items():
            resp, data = _http(port, "POST", "/tenants/warm",
                               body=json.dumps(
                                   {"policy": paths["b"]}).encode(),
                               timeout=180)
            assert resp.status == 200, (tag, data[:300])

        # ---- the router, in-process over the subprocess fleet
        router = Router(port_dir=port_dir, poll_interval_s=0.2,
                        eject_after=2, readmit_after=1,
                        name="e2e").start()
        deadline = time.monotonic() + 60.0
        while len(router.stats()["in_rotation"]) < 3 \
                and time.monotonic() < deadline:
            time.sleep(0.1)
        assert len(router.stats()["in_rotation"]) == 3
        httpd = ThreadingHTTPServer(("127.0.0.1", 0),
                                    make_router_handler(router))
        httpd.daemon_threads = True
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        rport = httpd.server_address[1]

        rng = np.random.default_rng(0)
        imgs = rng.integers(0, 256, (2, IMG, IMG, 3), np.uint8)
        body = _npz_body(imgs)

        # ---- mixed 2-policy traffic: every request 200, affinity
        # hit rate >= 95% (clean weather: every request lands on its
        # digest's rendezvous primary)
        for i in range(40):
            d = digests["a"] if i % 2 else digests["b"]
            resp, data = _http(rport, "POST", "/augment", body=body,
                               headers={"X-FAA-Policy-Digest": d})
            assert resp.status == 200, data[:300]
        affinity = router.stats()["affinity"]
        assert affinity["hit_rate"] >= 0.95, affinity

        # ---- cold third policy: first request 503 tenant_cold with
        # warming kicked; it becomes servable while WARM traffic keeps
        # completing with zero errors
        warm_errors = []
        stop = threading.Event()

        def warm_traffic():
            k = 0
            while not stop.is_set():
                d = digests["a"] if k % 2 else digests["b"]
                k += 1
                try:
                    resp, _data = _http(rport, "POST", "/augment",
                                        body=body,
                                        headers={"X-FAA-Policy-Digest":
                                                 d})
                    if resp.status != 200:
                        warm_errors.append(resp.status)
                except OSError as e:
                    warm_errors.append(repr(e))

        wt = threading.Thread(target=warm_traffic, daemon=True)
        wt.start()
        try:
            t0 = time.monotonic()
            status = None
            while time.monotonic() - t0 < 120.0:
                resp, data = _http(rport, "POST", "/augment", body=body,
                                   headers={"X-FAA-Policy-Digest":
                                            digests["c"]})
                status = resp.status
                if status == 200:
                    break
                rec = json.loads(data)
                assert rec.get("type") in ("tenant_cold", "no_replica",
                                           "upstream_unreachable"), rec
                time.sleep(0.5)
            assert status == 200, "cold policy never warmed in"
        finally:
            stop.set()
            wt.join(timeout=30.0)
        assert warm_errors == []  # warm tenants unbothered by the warm

        # ---- kill one replica (the unannounced-death case): it
        # ejects from rotation and traffic fails over — goodput
        # degrades (one fewer replica), availability does not collapse
        victim = procs[0]
        victim.send_signal(signal.SIGKILL)
        victim.wait(timeout=30)
        deadline = time.monotonic() + 30.0
        while len(router.stats()["in_rotation"]) > 2 \
                and time.monotonic() < deadline:
            time.sleep(0.1)
        st = router.stats()
        assert len(st["in_rotation"]) == 2, st["replicas"]
        ok = 0
        for i in range(30):
            d = digests["a"] if i % 2 else digests["b"]
            resp, _data = _http(rport, "POST", "/augment", body=body,
                                headers={"X-FAA-Policy-Digest": d})
            ok += resp.status == 200
        assert ok == 30  # bounded failover keeps every request alive

        # ---- SIGTERM drain: serving exit contract (exit 0) and the
        # discovery records disappear
        for p in procs[1:]:
            p.send_signal(signal.SIGTERM)
        for p in procs[1:]:
            assert p.wait(timeout=60) == 0
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline:
            left = [n for n in os.listdir(port_dir)
                    if n.endswith(".json")]
            if len(left) <= 1:  # the SIGKILLed record lingers
                break
            time.sleep(0.2)
        assert len([n for n in os.listdir(port_dir)
                    if n.endswith(".json")]) <= 1
    finally:
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if router is not None:
            router.stop()
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=10)
