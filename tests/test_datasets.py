"""Dataset reader tests against real on-disk formats (synthesized CIFAR
pickle batches, SVHN .mat, CIFAR-10.1 .npy), split parity, and a
learnability check that the full training loop actually learns."""

import os
import pickle

import numpy as np
import pytest

from fast_autoaugment_tpu.data.datasets import cv_split, load_dataset


def _write_cifar10(root, n_per_batch=20):
    base = os.path.join(root, "cifar-10-batches-py")
    os.makedirs(base, exist_ok=True)

    def batch(n, seed):
        r = np.random.default_rng(seed)
        return {
            b"data": r.integers(0, 256, (n, 3072), dtype=np.uint8).astype(np.uint8),
            b"labels": r.integers(0, 10, (n,)).tolist(),
        }

    for i in range(1, 6):
        with open(os.path.join(base, f"data_batch_{i}"), "wb") as fh:
            pickle.dump(batch(n_per_batch, i), fh)
    with open(os.path.join(base, "test_batch"), "wb") as fh:
        pickle.dump(batch(10, 99), fh)


def _write_svhn(root, n=30):
    import scipy.io

    rng = np.random.default_rng(1)
    for split, count in (("train", n), ("test", 10), ("extra", 15)):
        scipy.io.savemat(
            os.path.join(root, f"{split}_32x32.mat"),
            {
                "X": rng.integers(0, 256, (32, 32, 3, count), dtype=np.uint8),
                # SVHN labels are 1..10 with 10 meaning digit 0
                "y": rng.integers(1, 11, (count, 1)).astype(np.uint8),
            },
        )


def test_cifar10_pickle_reader(tmp_path):
    _write_cifar10(str(tmp_path))
    train, test = load_dataset("cifar10", str(tmp_path))
    assert train.images.shape == (100, 32, 32, 3) and train.images.dtype == np.uint8
    assert test.images.shape == (10, 32, 32, 3)
    assert train.num_classes == 10
    # HWC unpacking: channel planes must not be interleaved — rebuild one
    with open(tmp_path / "cifar-10-batches-py" / "data_batch_1", "rb") as fh:
        raw = pickle.load(fh, encoding="bytes")[b"data"][0]
    want = raw.reshape(3, 32, 32).transpose(1, 2, 0)
    np.testing.assert_array_equal(train.images[0], want)


def test_svhn_mat_reader(tmp_path):
    _write_svhn(str(tmp_path))
    train, test = load_dataset("svhn", str(tmp_path))
    # svhn = train + extra concatenated (reference data.py:130-134)
    assert len(train) == 45 and len(test) == 10
    assert train.images.shape[1:] == (32, 32, 3)
    # label 10 -> 0 like torchvision
    assert set(np.unique(train.labels)) <= set(range(10))


def test_cifar10_1_variant(tmp_path):
    _write_cifar10(str(tmp_path))
    rng = np.random.default_rng(3)
    np.save(tmp_path / "cifar10.1_v6_data.npy",
            rng.integers(0, 256, (7, 32, 32, 3), dtype=np.uint8))
    np.save(tmp_path / "cifar10.1_v6_labels.npy", rng.integers(0, 10, (7,)))
    train, test = load_dataset("cifar10.1", str(tmp_path))
    assert len(train) == 100 and len(test) == 7


def test_reduced_cifar10_requires_enough_examples(tmp_path):
    # reduced_cifar10 wants 46000 held out of 50000; synthetic 100-example
    # files must fail loudly, not silently produce an empty set
    _write_cifar10(str(tmp_path))
    with pytest.raises(ValueError):
        load_dataset("reduced_cifar10", str(tmp_path))


def test_cv_split_is_deterministic_and_overlapping():
    labels = np.repeat(np.arange(10), 50)
    a_train, a_valid = cv_split(labels, 0.4, 0)
    b_train, b_valid = cv_split(labels, 0.4, 0)
    np.testing.assert_array_equal(a_train, b_train)
    np.testing.assert_array_equal(a_valid, b_valid)
    # resamples overlap (NOT disjoint K-fold — SURVEY errata 3)
    _c_train, c_valid = cv_split(labels, 0.4, 1)
    assert len(np.intersect1d(a_valid, c_valid)) > 0
    assert len(a_train) == 300 and len(a_valid) == 200


def test_training_actually_learns():
    """Learnability: a tiny model on a linearly-separable synthetic task
    (class = which half of the image is brighter) must fit far above
    chance within a few epochs — the whole-loop sanity check the
    reference never had."""
    import jax

    from fast_autoaugment_tpu.core.config import Config
    from fast_autoaugment_tpu.data import datasets

    rng = np.random.default_rng(0)
    n = 512
    images = rng.integers(0, 100, (n, 32, 32, 3), dtype=np.uint8)
    labels = rng.integers(0, 2, (n,)).astype(np.int32)
    # paint the signal: class 1 -> bright top half
    images[labels == 1, :16] += 120

    ds = datasets.ArrayDataset(images, labels, 2)
    orig = datasets.load_dataset

    def fake_load(name, root):
        return ds, ds

    datasets.load_dataset = fake_load
    try:
        import fast_autoaugment_tpu.train.trainer as trainer_mod

        trainer_mod.load_dataset = fake_load
        conf = Config({
            "model": {"type": "wresnet10_1"},
            "dataset": "synthetic",  # only used for num_class -> override below
            "aug": "default",
            "cutout": 0,
            "batch": 16,
            "epoch": 3,
            "lr": 0.02,
            "lr_schedule": {"type": "cosine"},
            "optimizer": {"type": "sgd", "decay": 1e-4, "clip": 5.0,
                          "momentum": 0.9, "nesterov": True},
        })
        import fast_autoaugment_tpu.models as models_mod

        orig_nc = models_mod.num_class
        trainer_nc = trainer_mod.num_class
        models_mod.num_class = lambda d: 2
        trainer_mod.num_class = lambda d: 2
        try:
            result = trainer_mod.train_and_eval(
                conf, dataroot="/nonexistent", test_ratio=0.0,
                evaluation_interval=3, metric="last",
            )
        finally:
            models_mod.num_class = orig_nc
            trainer_mod.num_class = trainer_nc
    finally:
        datasets.load_dataset = orig
        import fast_autoaugment_tpu.train.trainer as trainer_mod

        trainer_mod.load_dataset = orig

    assert result["top1_train"] > 0.9, result["top1_train"]
    assert result["top1_test"] > 0.9, result["top1_test"]


def test_eval_batches_shards_across_processes():
    """Multi-host eval must partition work, not duplicate it: the union of
    per-process shards is the dataset exactly once, padding is masked out,
    and every shard is the same size (ADVICE round 1, medium)."""
    from fast_autoaugment_tpu.data.datasets import ArrayDataset
    from fast_autoaugment_tpu.data.pipeline import eval_batches

    n = 10  # deliberately not a multiple of batch or mesh size
    ds = ArrayDataset(
        np.arange(n, dtype=np.uint8).reshape(n, 1, 1, 1) * np.ones((1, 2, 2, 3), np.uint8),
        np.arange(n, dtype=np.int32), 10,
    )
    seen = []
    for pi in range(2):
        got = list(eval_batches(ds, None, 4, process_index=pi,
                                process_count=2, pad_multiple=4))
        sizes = {im.shape[0] for im, _, _ in got}
        assert sizes == {2}, "every global batch split evenly across 2 hosts"
        for im, lab, mask in got:
            assert im.shape[0] == len(lab) == len(mask)
            seen.extend(int(l) for l, m in zip(lab, mask) if m > 0)
    assert sorted(seen) == list(range(n)), "each sample exactly once globally"


def test_eval_batches_single_process_pads_to_multiple():
    from fast_autoaugment_tpu.data.datasets import ArrayDataset
    from fast_autoaugment_tpu.data.pipeline import eval_batches

    ds = ArrayDataset(np.zeros((5, 2, 2, 3), np.uint8),
                      np.arange(5, dtype=np.int32), 10)
    got = list(eval_batches(ds, None, 4, pad_multiple=4))
    assert [im.shape[0] for im, _, _ in got] == [4, 4]
    assert sum(int(m.sum()) for _, _, m in got) == 5


def test_prefetch_transform_runs_in_worker_and_propagates_errors(monkeypatch):
    """prefetch(transform=) applies the mapping off the consumer thread
    and re-raises worker exceptions (including strict-zip arity errors
    from shard_transform) at the consumer."""
    import pytest

    from fast_autoaugment_tpu.data.pipeline import prefetch

    monkeypatch.delenv("FAA_PREFETCH_SYNC", raising=False)  # async path

    items = [(np.ones((2, 2)), np.zeros(2)), (np.zeros((2, 2)), np.ones(2))]
    got = list(prefetch(iter(items), transform=lambda t: {"x": t[0], "y": t[1]}))
    assert [sorted(d) for d in got] == [["x", "y"], ["x", "y"]]

    def boom(_):
        raise ValueError("bad batch")

    with pytest.raises(ValueError, match="bad batch"):
        list(prefetch(iter(items), transform=boom))


def test_shard_transform_arity_is_strict():
    """shard_transform must fail loudly when the key tuple does not match
    the pipeline tuple (a silently dropped mask would surface later as a
    KeyError far from the call site)."""
    import jax
    import pytest

    from fast_autoaugment_tpu.parallel.mesh import make_mesh, shard_transform

    mesh = make_mesh(jax.devices()[:1])
    to_dev = shard_transform(mesh, ("x", "y"))
    out = to_dev((np.zeros((4, 2, 2, 3), np.uint8), np.zeros(4, np.int32)))
    assert set(out) == {"x", "y"} and out["x"].shape == (4, 2, 2, 3)

    with pytest.raises(ValueError):
        shard_transform(mesh, ("x", "y", "m"))(
            (np.zeros((4, 2, 2, 3), np.uint8), np.zeros(4, np.int32))
        )


def test_prefetch_early_abandon_releases_worker(monkeypatch):
    """Breaking out of a prefetch loop (bench/eval early exit) must stop
    the worker thread rather than leave it blocked on a full queue
    holding buffered (possibly device-resident) batches."""
    import threading
    import time

    from fast_autoaugment_tpu.data.pipeline import prefetch

    monkeypatch.delenv("FAA_PREFETCH_SYNC", raising=False)  # async path
    before = set(threading.enumerate())
    it = prefetch(iter(range(100)), depth=1)
    assert next(it) == 0
    spawned = [t for t in threading.enumerate() if t not in before]
    assert spawned, "prefetch did not spawn a worker thread"
    it.close()  # what an abandoned for-loop break does on GC
    for t in spawned:
        t.join(timeout=5.0)
    assert not any(t.is_alive() for t in spawned), "prefetch worker leaked"


def test_synthetic_shapes_difficulty_knobs():
    """The render knobs grade task difficulty: higher noise / lower glyph
    contrast measurably corrupts the clean image."""
    from fast_autoaugment_tpu.data.datasets import _synthetic_shapes

    clean_train, _ = _synthetic_shapes(n_train=32, n_test=1)
    noisy_train, _ = _synthetic_shapes(n_train=32, n_test=1, noise=60.0)
    faint_train, _ = _synthetic_shapes(n_train=32, n_test=1, fg_lo=5.0, fg_hi=10.0)
    assert clean_train.images.std() > faint_train.images.std(), \
        "lower fg contrast must flatten the image"
    diff = (noisy_train.images.astype(np.float32)
            - clean_train.images.astype(np.float32))
    assert np.abs(diff).mean() > 10.0, "higher noise floor must perturb pixels"


def test_synthetic_shapes_pose_variant():
    """The pose variant must actually vary pose: per-sample rotation and
    scale change the glyph footprint in ways the base render never does,
    and the registry name parametrizes train size."""
    from fast_autoaugment_tpu.data.datasets import _synthetic_shapes, load_dataset

    base_train, _ = _synthetic_shapes(n_train=64, n_test=1)
    pose_train, _ = _synthetic_shapes(n_train=64, n_test=1, max_rot=25.0,
                                      scale_lo=0.7, scale_hi=1.3)
    assert pose_train.images.shape == base_train.images.shape
    # same label stream (same seed), different rendered pixels
    np.testing.assert_array_equal(pose_train.labels, base_train.labels)
    diff = (pose_train.images.astype(np.int32)
            - base_train.images.astype(np.int32))
    assert np.abs(diff).mean() > 2.0, "pose knobs changed nothing"

    train, test = load_dataset("synthetic_shapes_pose300", dataroot="")
    assert len(train) == 300 and train.num_classes == 10 and len(test) == 2000
