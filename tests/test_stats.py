"""Student-t survival function + paired t-test (utils/stats.py) —
verified against closed forms and asymptotics, not scipy (not a
dependency)."""

import math

import numpy as np
import pytest

from fast_autoaugment_tpu.utils.stats import paired_t_test, t_sf


def test_t_sf_cauchy_closed_form():
    # df=1 is Cauchy: sf(t) = 1/2 - arctan(t)/pi
    for t in (0.0, 0.5, 1.0, 2.0, 10.0):
        expected = 0.5 - math.atan(t) / math.pi
        assert t_sf(t, 1) == pytest.approx(expected, abs=1e-6)


def test_t_sf_symmetry_and_normal_limit():
    assert t_sf(0.0, 7) == pytest.approx(0.5, abs=1e-9)
    assert t_sf(-1.3, 7) == pytest.approx(1.0 - t_sf(1.3, 7), abs=1e-9)
    # large df approaches the normal: sf(1.959964) -> 0.025
    assert t_sf(1.959964, 10000) == pytest.approx(0.025, abs=5e-4)
    # known table value: t_sf(2.0, 7) = 0.0428 (two-sided 0.0856)
    assert t_sf(2.0, 7) == pytest.approx(0.0428, abs=5e-4)


def test_paired_t_test_known_case():
    # d = a - b = [1, 2, 3, 4]: mean 2.5, sd sqrt(5/3), t = 3.873
    a = np.array([2.0, 4.0, 6.0, 8.0])
    b = np.array([1.0, 2.0, 3.0, 4.0])
    out = paired_t_test(a, b)
    assert out["n"] == 4 and out["df"] == 3
    assert out["mean_diff"] == pytest.approx(2.5)
    assert out["t_stat"] == pytest.approx(2.5 / (math.sqrt(5.0 / 3.0) / 2.0), rel=1e-9)
    # scipy.stats.ttest_rel gives p=0.030466 for this data
    assert out["p_value"] == pytest.approx(0.0305, abs=2e-3)


def test_paired_t_test_degenerate():
    same = np.array([1.0, 1.0, 1.0])
    assert paired_t_test(same, same)["p_value"] == 1.0
    assert paired_t_test(same + 2.0, same)["p_value"] == 0.0
    with pytest.raises(ValueError):
        paired_t_test([1.0], [2.0])
