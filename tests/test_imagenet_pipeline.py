"""ImageNet path tests: crop-box semantics vs the reference's math, the
lazy folder reader, and an end-to-end tiny train run over on-disk JPEGs."""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fast_autoaugment_tpu.ops.preprocess_imagenet import (
    center_crop_box,
    imagenet_eval_batch,
    imagenet_train_batch,
    random_crop_box,
)


def test_center_crop_box_matches_reference_formula():
    # reference data.py:326-345: crop = imgsize/(imgsize+32) * short side
    left, top, right, bottom = center_crop_box(500, 375, 224)
    crop = 224.0 / 256.0 * 375
    assert (right - left) == pytest.approx(crop)
    assert (bottom - top) == pytest.approx(crop)
    assert top == int(round((375 - crop) / 2.0))
    assert left == int(round((500 - crop) / 2.0))


def test_random_crop_box_respects_constraints():
    rng = np.random.default_rng(0)
    for _ in range(200):
        w, h = int(rng.integers(100, 600)), int(rng.integers(100, 600))
        x0, y0, x1, y1 = random_crop_box(rng, w, h, 224)
        assert 0 <= x0 < x1 <= w + 1e-6
        assert 0 <= y0 < y1 <= h + 1e-6
        area = (x1 - x0) * (y1 - y0)
        ar = (x1 - x0) / (y1 - y0)
        # either a valid sample (area/aspect in range) or the center-crop fallback
        in_range = (0.08 * w * h - 2 <= area <= 1.0 * w * h + 2) and (0.74 <= ar <= 4.0 / 3 + 0.01)
        is_fallback = abs((x1 - x0) - (y1 - y0)) < 1.5  # center crop is square
        assert in_range or is_fallback


def test_device_batch_shapes_and_normalization():
    imgs = jnp.asarray(
        np.random.default_rng(0).integers(0, 256, (4, 64, 64, 3), dtype=np.uint8)
    )
    out = imagenet_train_batch(imgs, jax.random.PRNGKey(0))
    assert out.shape == (4, 64, 64, 3)
    # normalized values should be in a plausible range
    a = np.asarray(out)
    assert -3.5 < a.min() and a.max() < 3.5
    out_eval = imagenet_eval_batch(imgs)
    gray = (imgs[0, 0, 0].astype(np.float32) / 255.0 - np.array([0.485, 0.456, 0.406])) / np.array(
        [0.229, 0.224, 0.225]
    )
    np.testing.assert_allclose(np.asarray(out_eval[0, 0, 0]), gray, rtol=1e-5)


def _write_fake_imagenet(root, n_classes=3, per_class=8, sizes=((80, 60), (64, 100))):
    import PIL.Image

    rng = np.random.default_rng(0)
    for split, count in (("train", per_class), ("val", 4)):
        for c in range(n_classes):
            cdir = os.path.join(root, split, f"n{c:08d}")
            os.makedirs(cdir, exist_ok=True)
            for i in range(count):
                w, h = sizes[i % len(sizes)]
                arr = rng.integers(0, 256, (h, w, 3), dtype=np.uint8)
                PIL.Image.fromarray(arr).save(os.path.join(cdir, f"img{i}.jpg"))


def test_lazy_reader_and_tiny_imagenet_train(tmp_path):
    from fast_autoaugment_tpu.core.config import Config
    from fast_autoaugment_tpu.data.datasets import load_dataset
    from fast_autoaugment_tpu.train.trainer import train_and_eval

    _write_fake_imagenet(tmp_path)
    train, test = load_dataset("imagenet", str(tmp_path))
    assert train.lazy and len(train) == 24 and len(test) == 12
    assert train.num_classes == 1000

    conf = Config({
        # wresnet on imagenet is not a reference config, but it is small
        # enough to compile quickly and exercises the imagenet data path
        "model": {"type": "wresnet10_1"},
        "dataset": "imagenet",
        "aug": "fa_reduced_imagenet",
        "cutout": 0,
        "batch": 1,
        "epoch": 1,
        "lr": 0.01,
        "lr_schedule": {"type": "cosine"},
        "optimizer": {"type": "sgd", "decay": 1e-4, "clip": 5.0,
                      "momentum": 0.9, "nesterov": True},
    })
    result = train_and_eval(conf, str(tmp_path), test_ratio=0.0,
                            evaluation_interval=1, metric="last")
    assert result["epoch"] == 1
    assert np.isfinite(result["loss_train"])
    assert result["num_test"] == 12


def test_native_and_pil_boxed_paths_agree_end_to_end(tmp_path, monkeypatch):
    """VERDICT round 2, next-step 6: the lazy ImageNet path must produce
    the same batches through the native C++ loader (libjpeg decode ->
    boxed crop -> resize) as through the PIL fallback, and training must
    actually exercise the native path when it is available."""
    from fast_autoaugment_tpu.core.config import Config
    from fast_autoaugment_tpu.data import native_loader
    from fast_autoaugment_tpu.data.datasets import load_dataset
    from fast_autoaugment_tpu.data.pipeline import BatchIterator
    from fast_autoaugment_tpu.train.trainer import train_and_eval

    if not native_loader.available():
        assert native_loader.build(), "g++/libjpeg build failed"

    _write_fake_imagenet(tmp_path)
    train, _test = load_dataset("imagenet", str(tmp_path))

    eval_box = lambda rng, w, h: center_crop_box(w, h, 32)  # noqa: E731
    it = BatchIterator(train, np.arange(8), eval_box_fn=eval_box, imgsize=32)

    native_batches = [b for b in it.eval_epoch(4)]
    assert native_batches and native_batches[0][0].dtype == np.uint8

    monkeypatch.setattr(native_loader, "available", lambda: False)
    pil_batches = [b for b in it.eval_epoch(4)]
    monkeypatch.undo()

    assert len(native_batches) == len(pil_batches)
    for (xn, yn, mn), (xp, yp, mp) in zip(native_batches, pil_batches):
        np.testing.assert_array_equal(yn, yp)
        np.testing.assert_array_equal(mn, mp)
        diff = np.abs(xn.astype(np.int32) - xp.astype(np.int32))
        # same libjpeg decode, same crop box, bilinear resample on the
        # same half-pixel grid -> rounding-level disagreement only
        assert np.mean(diff) < 4.0, np.mean(diff)

    # training exercises the native path for real (spy on the entry)
    calls = []
    real = native_loader.decode_resize_batch

    def spy(paths, size, boxes=None):
        calls.append(len(paths))
        return real(paths, size, boxes)

    monkeypatch.setattr(native_loader, "decode_resize_batch", spy)
    conf = Config({
        "model": {"type": "wresnet10_1"},
        "dataset": "imagenet",
        "aug": "default",
        "cutout": 0,
        "batch": 1,
        "epoch": 1,
        "lr": 0.001,
        "lr_schedule": {"type": "cosine"},
        "optimizer": {"type": "sgd", "decay": 1e-4, "momentum": 0.9,
                      "nesterov": True},
    })
    result = train_and_eval(conf, str(tmp_path), test_ratio=0.0,
                            evaluation_interval=1, metric="last")
    assert np.isfinite(result["loss_train"])
    assert calls, "train_and_eval never hit the native decode path"
