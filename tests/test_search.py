"""Search engine tests: TPE convergence on a toy problem, TTA-step
reduction semantics, and the end-to-end smoke search (the analog of the
reference's --smoke-test, search.py:153)."""

import json
import os
import tempfile

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fast_autoaugment_tpu.search.tpe import TPE, choice, uniform


def test_tpe_beats_random_on_quadratic():
    space = [uniform("x", 0, 1), uniform("y", 0, 1), choice("c", 4)]

    def objective(s):
        return -((s["x"] - 0.7) ** 2) - (s["y"] - 0.2) ** 2 + (0.5 if s["c"] == 2 else 0.0)

    tpe = TPE(space, seed=0)
    for _ in range(120):
        s = tpe.suggest()
        tpe.tell(s, objective(s))

    rng = np.random.default_rng(0)
    random_best = max(
        objective({"x": rng.uniform(), "y": rng.uniform(), "c": int(rng.integers(4))})
        for _ in range(120)
    )
    best_x, best_r = tpe.best
    assert best_r >= random_best - 0.02
    assert best_x["c"] == 2
    assert abs(best_x["x"] - 0.7) < 0.25


def test_tpe_deterministic():
    space = [uniform("x"), choice("c", 3)]
    a, b = TPE(space, seed=5), TPE(space, seed=5)
    for _ in range(30):
        sa, sb = a.suggest(), b.suggest()
        assert sa == sb
        a.tell(sa, sa["x"])
        b.tell(sb, sb["x"])


def test_tta_step_reductions():
    """Identity-policy TTA on a fixed linear model: minus_loss must be the
    batch-global min; correct must be the per-sample any() across draws."""
    from flax import linen as nn

    from fast_autoaugment_tpu.parallel.mesh import make_mesh, shard_transform
    from fast_autoaugment_tpu.search.tta import eval_tta, make_tta_step

    class Probe(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            # logits depend deterministically on mean pixel: samples with
            # high mean get class 1
            m = x.mean(axis=(1, 2, 3), keepdims=False)
            return jnp.stack([jnp.zeros_like(m), m * 10.0], axis=-1)

    model = Probe()
    tta = make_tta_step(model, num_policy=3, cutout_length=0,
                        augment_fn=lambda im, pol, k: im / 255.0 - 0.5)
    mesh = make_mesh(jax.devices()[:1])

    images = np.zeros((4, 8, 8, 3), np.uint8)
    images[2:] = 255  # samples 2,3 -> mean 0.5 -> logit 5 -> class 1
    labels = np.array([1, 1, 1, 1], np.int32)
    to_device = shard_transform(mesh, ("x", "y", "m"))
    out = eval_tta(tta, {}, {},
                   [to_device((images, labels, np.ones(4, np.float32)))],
                   jnp.zeros((1, 1, 3)), jax.random.PRNGKey(0))
    # samples 0,1 predict class 0 (wrong), 2,3 predict 1 (right)
    assert out["top1_valid"] == pytest.approx(0.5)
    # min nll over all = nll of a correct confident sample
    assert out["minus_loss"] < 0.0
    assert out["cnt"] == 4


@pytest.mark.slow
def test_smoke_search_on_imagenet_family(tmp_path):
    """Regression: phase 2 must decode lazy variable-size images through
    the boxed crop path and use the ImageNet TTA stack (was: np.stack
    shape crash + CIFAR stack silently applied)."""
    from tests.test_imagenet_pipeline import _write_fake_imagenet

    from fast_autoaugment_tpu.core.config import Config
    from fast_autoaugment_tpu.search.driver import search_policies

    _write_fake_imagenet(str(tmp_path))
    conf = Config({
        "model": {"type": "wresnet10_1"},
        "dataset": "imagenet",
        "aug": "default",
        "cutout": 0,
        "batch": 1,
        "epoch": 1,
        "lr": 0.01,
        "lr_schedule": {"type": "cosine"},
        "optimizer": {"type": "sgd", "decay": 1e-4, "clip": 5.0,
                      "momentum": 0.9, "nesterov": True},
    })
    result = search_policies(
        conf, dataroot=str(tmp_path), save_dir=str(tmp_path / "s"),
        cv_num=1, cv_ratio=0.4, num_policy=2, num_op=2,
        num_search=2, num_top=1, smoke_test=False,
    )
    assert 1 <= len(result["final_policy_set"]) <= 2


@pytest.mark.slow
def test_smoke_search_end_to_end():
    from fast_autoaugment_tpu.core.config import Config
    from fast_autoaugment_tpu.search.driver import search_policies

    conf = Config({
        "model": {"type": "wresnet10_1"},
        "dataset": "synthetic",
        "aug": "default",
        "cutout": 8,
        "batch": 8,
        "epoch": 1,
        "lr": 0.05,
        "lr_schedule": {"type": "cosine"},
        "optimizer": {"type": "sgd", "decay": 1e-4, "clip": 5.0,
                      "momentum": 0.9, "nesterov": True},
    })
    with tempfile.TemporaryDirectory() as tmp:
        result = search_policies(
            conf, dataroot=tmp, save_dir=os.path.join(tmp, "search"),
            cv_num=2, cv_ratio=0.4, num_policy=2, num_op=2,
            num_search=4, num_top=2, smoke_test=True,
        )
        pols = result["final_policy_set"]
        assert 1 <= len(pols) <= 2 * 2 * 2
        for sub in pols:
            assert len(sub) == 2
            for op, prob, level in sub:
                assert 0 <= prob <= 1 and 0 <= level <= 1
        # artifacts written
        assert os.path.exists(os.path.join(tmp, "search", "final_policy.json"))
        trials = json.load(open(os.path.join(tmp, "search", "search_trials.json")))
        assert set(trials) == {"0", "1"}
        assert result["tpu_secs_phase2"] > 0


@pytest.mark.slow
def test_audit_drops_destructive_keeps_benign(tmp_path):
    """Round-2 regression gate (docs/search_postmortem_r2.md): the
    sub-policy audit must drop policies that standalone-destroy fold
    accuracy (Invert/Solarize-to-0 on a bright-glyph task) and keep
    label-preserving ones (translate/near-identity brightness).  This is
    the exact mechanism whose absence let the round-2 e2e search ship a
    policy set that trained to random accuracy.

    Horizon note (PR-6 root-cause, docs/PARITY.md "audit-gate oracle"):
    at 20 epochs the seeded oracle converges to 0.344 in THIS
    container's jax build (bit-identical across PR 3..6 — the training
    stream never changed; the original authoring environment's kernels
    escaped the early plateau faster).  The cosine horizon is the
    lever: 35 epochs reaches 0.979 (vs 0.267-0.354 for 2x LR at any
    horizon).  The longer train pushes the test past the tier-1 wall
    budget, so it is slow-marked per the ROADMAP standing constraint —
    the audit-gate wiring stays covered in tier-1 by the cheaper
    agreement tests that defer semantics to this one."""
    from fast_autoaugment_tpu.core.config import Config
    from fast_autoaugment_tpu.search.driver import (
        _FoldEval,
        _fold_ckpt_path,
        audit_sub_policies,
    )
    from fast_autoaugment_tpu.train.trainer import train_and_eval

    conf = Config({
        "model": {"type": "wresnet10_1"},
        "dataset": "synthetic_shapes",
        "aug": "default",
        "cutout": 0,
        "batch": 2,  # global 16 on the 8-device mesh
        "epoch": 35,
        # conf lr is scaled by mesh.size (reference lr x world_size,
        # train.py:117): 0.00625 x 8 = effective 0.05
        "lr": 0.00625,
        "lr_schedule": {"type": "cosine", "warmup": {"multiplier": 1, "epoch": 2}},
        "optimizer": {"type": "sgd", "decay": 2e-4, "momentum": 0.9,
                      "nesterov": True},
    })
    from fast_autoaugment_tpu.parallel.mesh import make_mesh

    mesh = make_mesh()
    path = _fold_ckpt_path(str(tmp_path), conf, 0, 0.4)
    train_and_eval(conf.replace(aug="default"), str(tmp_path), test_ratio=0.4,
                   cv_fold=0, save_path=path, metric="last", seed=0)

    ev = _FoldEval(conf, str(tmp_path), mesh,
                   num_policy=5, num_op=2, cv_ratio=0.4, seed=0)
    base = ev.baseline(0, path)
    assert base > 0.5, f"fold oracle too weak to audit against ({base:.3f})"

    benign = [
        [("TranslateX", 0.5, 0.5), ("TranslateY", 0.5, 0.5)],
        [("Brightness", 0.5, 0.55), ("Cutout", 0.3, 0.3)],
        # 5 candidates total: forces the CHUNKED batched audit step
        # (make_audit_step), not the small-n fallback
        [("ShearX", 0.3, 0.5), ("Sharpness", 0.3, 0.5)],
    ]
    destructive = [
        # net polarity flips (NOT mutually-cancelling pairs: Invert+
        # Solarize(0) would compose back to identity)
        [("Invert", 1.0, 1.0), ("Cutout", 0.1, 0.1)],
        # Solarize level 0 -> threshold 0 -> every pixel inverted;
        # Brightness level 0.55 ~ factor 1.0 (identity)
        [("Solarize", 1.0, 0.0), ("Brightness", 1.0, 0.55)],
    ]
    kept, audit = audit_sub_policies(
        ev, benign + destructive, [path],
        fold_baselines={0: base}, candidate_folds=[0], audit_floor=0.7,
    )
    scores = {i: s["score"] for i, s in enumerate(audit["scores"])}
    assert all(b in kept for b in benign), scores
    assert not any(d in kept for d in destructive), scores


def test_tpe_beats_random_at_small_budget():
    """The 30-D mixed space benchmark at the 60-trial budget the e2e
    validation actually runs (VERDICT round 2, weak 4): with clean
    rewards TPE must clearly beat random, and under heavy observation
    noise it must at worst match it.  Metric is the TRUE reward of the
    best-by-observed incumbent (what top-N selection consumes).  Fully
    deterministic given the seeds; full budget x noise sweep in
    tools/bench_tpe.py / docs/tpe_benchmark.md."""
    import os
    import sys

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))
    import bench_tpe

    clean = bench_tpe.run_cell(trials=60, noise=0.02, runs=10)
    assert clean["wins"] >= 5, clean
    assert clean["gain"] > 0.01, clean

    # the regime the fold-quality gate exists to avoid: reward noise at
    # the weak-oracle spread — TPE may lose its edge but not its floor
    noisy = bench_tpe.run_cell(trials=60, noise=0.1, runs=10)
    assert noisy["gain"] > -0.02, noisy


def test_cli_defaults_are_the_validated_guards():
    """Round-3 regression (VERDICT r3, weak 1): the CLI's DEFAULT guard
    settings must be the validated recipe, not the settings that
    reproduced the round-2 destructive selection (audit floor 0.7, gate
    off — committed evidence search_e2e_r3/search_result_floor0.70.json)."""
    from fast_autoaugment_tpu.launch.search_cli import build_parser

    args = build_parser().parse_args(["-c", "conf.yaml"])
    assert args.audit_floor == 0.95
    assert args.fold_quality_floor == "auto"
    assert args.num_search == 200 and args.num_fold == 5  # reference scale


def test_resolve_quality_floor():
    from fast_autoaugment_tpu.search.driver import resolve_quality_floor

    # auto = chance-relative: close >=35% of the chance-to-perfect gap
    assert resolve_quality_floor("auto", 10) == pytest.approx(0.415)
    assert resolve_quality_floor("auto", 2) == pytest.approx(0.675)
    assert resolve_quality_floor("auto", 120) == pytest.approx(
        1 / 120 + 0.35 * (1 - 1 / 120))
    assert resolve_quality_floor("off", 10) is None
    assert resolve_quality_floor(None, 10) is None
    assert resolve_quality_floor(0.45, 10) == 0.45
    assert resolve_quality_floor("0.6", 10) == 0.6
    assert resolve_quality_floor(-1.0, 10) is None


@pytest.mark.slow
def test_phase2_crash_loses_at_most_inflight_trial(tmp_path, monkeypatch):
    """Per-trial persistence (VERDICT r3, weak 4): kill the search mid-
    fold and the trial log must already hold every COMPLETED trial; the
    resumed run finishes the budget without re-evaluating them."""
    from fast_autoaugment_tpu.core.config import Config
    from fast_autoaugment_tpu.search import driver
    from fast_autoaugment_tpu.search.driver import search_policies

    conf = Config({
        "model": {"type": "wresnet10_1"},
        "dataset": "synthetic",
        "aug": "default",
        "cutout": 8,
        "batch": 8,
        "epoch": 1,
        "lr": 0.05,
        "lr_schedule": {"type": "cosine"},
        "optimizer": {"type": "sgd", "decay": 1e-4, "clip": 5.0,
                      "momentum": 0.9, "nesterov": True},
    })
    save = str(tmp_path / "search")
    kwargs = dict(
        dataroot=str(tmp_path), save_dir=save, cv_num=1, cv_ratio=0.4,
        num_policy=2, num_op=2, num_search=6, num_top=2,
    )

    orig = driver._FoldEval.evaluate
    calls = {"n": 0}

    def crashing(self, *a, **kw):
        calls["n"] += 1
        if calls["n"] == 4:  # simulated kill mid-fold, 3 trials done
            raise KeyboardInterrupt("simulated kill")
        return orig(self, *a, **kw)

    monkeypatch.setattr(driver._FoldEval, "evaluate", crashing)
    with pytest.raises(KeyboardInterrupt):
        search_policies(conf, **kwargs)
    trials = json.load(open(os.path.join(save, "search_trials.json")))
    assert len(trials["0"]) == 3  # every completed trial persisted

    monkeypatch.setattr(driver._FoldEval, "evaluate", orig)
    result = search_policies(conf, **kwargs)  # resume=True default
    trials = json.load(open(os.path.join(save, "search_trials.json")))
    assert len(trials["0"]) == 6
    assert result["final_policy_set"]
    # one executable served every TTA evaluation (no recompiles)
    assert result["tta_executables"] in (None, 1)


def test_audit_batched_matches_sequential():
    """The chunked audit step (make_audit_step, sub-policy axis vmapped)
    must agree with per-sub-policy TTA evaluation up to augmentation
    sampling noise — same model, same batches, same reduction."""
    from flax import linen as nn

    from fast_autoaugment_tpu.parallel.mesh import make_mesh, shard_transform
    from fast_autoaugment_tpu.policies.archive import policy_to_tensor
    from fast_autoaugment_tpu.search.tta import (
        eval_tta,
        make_audit_step,
        make_tta_step,
    )

    class Probe(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            # class 1 iff mean pixel (post-normalize) above threshold:
            # sensitive to Brightness/Invert but ignores geometry
            m = x.mean(axis=(1, 2, 3))
            return jnp.stack([jnp.zeros_like(m), m * 8.0], axis=-1)

    model = Probe()
    tta = make_tta_step(model, num_policy=4, cutout_length=0)
    audit = make_audit_step(model, num_policy=4, cutout_length=0)
    mesh = make_mesh(jax.devices()[:1])
    to_device = shard_transform(mesh, ("x", "y", "m"))

    rng = np.random.default_rng(0)
    images = rng.integers(100, 180, (32, 8, 8, 3)).astype(np.uint8)
    labels = (images.mean(axis=(1, 2, 3)) > 140).astype(np.int32)
    batch = to_device((images, labels, np.ones(32, np.float32)))

    subs = [
        [("Brightness", 1.0, 0.9), ("Cutout", 0.0, 0.0)],
        [("Invert", 1.0, 1.0), ("Cutout", 0.0, 0.0)],
        [("TranslateX", 0.5, 0.5), ("Cutout", 0.0, 0.0)],
    ]
    subs_t = jnp.asarray(policy_to_tensor(subs))
    out = audit({}, {}, batch["x"], batch["y"], batch["m"], subs_t,
                jax.random.PRNGKey(5))
    batched = np.asarray(out["correct_mean_sum"]) / float(out["cnt"])

    for i, s in enumerate(subs):
        seq = eval_tta(tta, {}, {}, [batch],
                       jnp.asarray(policy_to_tensor([s])),
                       jax.random.PRNGKey(50 + i))["top1_mean"]
        # different draws -> sampling noise only (destructive-vs-benign
        # SEMANTICS are covered by test_audit_drops_destructive_keeps_benign
        # with a real trained model)
        assert abs(float(seq) - batched[i]) < 0.15, (i, float(seq), batched[i])


def test_draw_random_policy_set():
    """The phase-3 control arm (VERDICT r4 next-4): equal-size uniform
    draws from the same (op, prob, level) space, deduplicated and
    deterministic under a fixed seed."""
    from fast_autoaugment_tpu.ops.augment import SEARCH_OP_NAMES
    from fast_autoaugment_tpu.search.driver import draw_random_policy_set

    s1 = draw_random_policy_set(23, 5, 2, seed=42)
    s2 = draw_random_policy_set(23, 5, 2, seed=42)
    assert s1 == s2
    assert len(s1) == 23
    assert len({json.dumps(sub) for sub in s1}) == 23  # deduplicated
    for sub in s1:
        assert len(sub) == 2
        for op, prob, level in sub:
            assert op in SEARCH_OP_NAMES
            assert 0.0 <= prob <= 1.0 and 0.0 <= level <= 1.0
    assert draw_random_policy_set(7, 5, 2, seed=1) != \
        draw_random_policy_set(7, 5, 2, seed=2)


def test_quality_gate_retry_seed_reaches_hook():
    """ADVICE r4 (medium): the retry seed must reach a train_fold_fn
    override explicitly — a thin three-arg wrapper around
    train_and_eval used to retrain with the identical seed, silently
    voiding the quality gate's fresh-seed retry."""
    from fast_autoaugment_tpu.core.config import Config
    from fast_autoaugment_tpu.search.driver import _call_train_fold_fn

    conf = Config({"model": {"type": "wresnet10_1"}, "dataset": "synthetic",
                   "aug": "default", "batch": 2, "epoch": 1, "lr": 0.1,
                   "lr_schedule": {"type": "cosine"},
                   "optimizer": {"type": "sgd"}})
    calls = {}

    def legacy(conf, fold, path):
        calls["legacy"] = conf["seed"]

    def modern(conf, fold, path, *, seed):
        calls["modern"] = seed
        calls["modern_conf"] = conf["seed"]

    _call_train_fold_fn(legacy, conf, 0, "p", 123)
    _call_train_fold_fn(modern, conf, 0, "p", 456)
    assert calls == {"legacy": 123, "modern": 456, "modern_conf": 456}


def test_search_random_control_arm(tmp_path):
    """random_control=True draws, persists and resumes the control
    policy set, and the artifact records backend provenance
    (VERDICT r4 weak 5 + next-4)."""
    from fast_autoaugment_tpu.core.config import Config
    from fast_autoaugment_tpu.search.driver import search_policies

    conf = Config({
        "model": {"type": "wresnet10_1"},
        "dataset": "synthetic",
        "aug": "default",
        "cutout": 8,
        "batch": 8,
        "epoch": 1,
        "lr": 0.05,
        "lr_schedule": {"type": "cosine"},
        "optimizer": {"type": "sgd", "decay": 1e-4, "clip": 5.0,
                      "momentum": 0.9, "nesterov": True},
    })
    save = str(tmp_path / "search")
    kwargs = dict(
        cv_num=1, cv_ratio=0.4, num_policy=2, num_op=2,
        num_search=2, num_top=1, random_control=True,
    )
    result = search_policies(conf, dataroot=str(tmp_path), save_dir=save,
                             **kwargs)
    # ledger provenance: a CPU run must say so next to its device-secs
    assert result["backend"] == "cpu"
    assert result["device_count"] >= 1
    assert result["device_secs_phase2"] == result["tpu_secs_phase2"]
    rand = result["random_policy_set"]
    assert len(rand) == result["num_sub_policies_selected"]
    assert os.path.exists(os.path.join(save, "random_policy.json"))
    assert os.path.exists(os.path.join(save, "random_final_policy.json"))
    # resume must reuse the persisted draw, not redraw
    result2 = search_policies(conf, dataroot=str(tmp_path), save_dir=save,
                              **kwargs)
    assert result2["random_policy_set"] == rand


def test_fold_quality_floor_cli_validation(capsys):
    """ADVICE r4: malformed --fold-quality-floor fails at parse time as
    a CLI usage error, not a float() traceback inside the search."""
    from fast_autoaugment_tpu.launch.search_cli import build_parser

    p = build_parser()
    with pytest.raises(SystemExit):
        p.parse_args(["-c", "x.yaml", "--fold-quality-floor", "0,45"])
    assert "expected 'auto', 'off' or a float" in capsys.readouterr().err
    assert p.parse_args(
        ["-c", "x.yaml", "--fold-quality-floor", "0.45"]
    ).fold_quality_floor == "0.45"
    assert p.parse_args(
        ["-c", "x.yaml", "--fold-quality-floor", "OFF"]
    ).fold_quality_floor == "off"


def test_draw_random_policy_set_exhausted_space_raises():
    """num_op=1 leaves only 15 distinct op sequences; asking for 20
    must raise, not spin forever (round-5 review finding)."""
    from fast_autoaugment_tpu.search.driver import draw_random_policy_set

    with pytest.raises(ValueError, match="distinct sub-policies"):
        draw_random_policy_set(20, 5, 1, seed=0)


def test_fold_quality_floor_cli_rejects_non_finite():
    """float('nan') parses but nan > 0 is False — it would silently
    disable the gate; the validator must reject it (round-5 review)."""
    from fast_autoaugment_tpu.launch.search_cli import build_parser

    p = build_parser()
    for bad in ("nan", "inf", "-inf"):
        with pytest.raises(SystemExit):
            p.parse_args(["-c", "x.yaml", "--fold-quality-floor", bad])
