"""faalint (tools/faalint/): the multi-pass static analyzer.

Rule-matrix coverage (positive + negative + suppression) for the new
concurrency (C1–C3), dispatch (D1–D3) and determinism (T1–T3) passes
and the extended-blocking rule (R9); framework machinery (single
parse, severity threshold, baseline, stale-suppression S1/S2); the
pre-fix regression corpus; and the live-repo clean gate.  Everything
here is host-only AST work — no JAX, no compiles.
"""

import ast
import json
import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))

from faalint import check_source, lint_tree  # noqa: E402
from faalint.engine import (Finding, apply_baseline, default_rules,  # noqa: E402
                            failing, load_baseline)
from faalint.corpus import (CASES, HISTORICAL, check_corpus,  # noqa: E402
                            load as corpus_load, rule_pass_map)

CORE = "fast_autoaugment_tpu/core/x.py"
LAUNCH = "fast_autoaugment_tpu/launch/x.py"
TRAIN = "fast_autoaugment_tpu/train/x.py"
UTILS = "fast_autoaugment_tpu/utils/x.py"
DATA = "fast_autoaugment_tpu/data/x.py"


def _rules(findings):
    return [f.rule for f in findings]


# --------------------------------------------------------------- framework


def test_single_parse_per_file(monkeypatch):
    """The tentpole claim: one ast.parse per file no matter how many
    rules run (the legacy lint re-parsed per rule family)."""
    calls = {"n": 0}
    real_parse = ast.parse

    def counting_parse(*a, **kw):
        calls["n"] += 1
        return real_parse(*a, **kw)

    monkeypatch.setattr(ast, "parse", counting_parse)
    src = ("import queue, time\nq = queue.Queue()\nq.put(x)\n"
           "try:\n    f()\nexcept:\n    pass\n")
    findings = check_source(src, CORE)
    assert calls["n"] == 1
    assert {"R1", "R9"} <= set(_rules(findings))


def test_severity_threshold():
    fs = [Finding("a.py", 1, "C2", "m", "error"),
          Finding("a.py", 2, "D1", "m", "warning"),
          Finding("a.py", 3, "X", "m", "info")]
    assert len(failing(fs, "error")) == 1
    assert len(failing(fs, "warning")) == 2
    assert len(failing(fs, "info")) == 3
    assert failing(fs, "never") == []


def test_every_rule_declares_severity_and_pass():
    for rule in default_rules():
        assert rule.severity in ("error", "warning", "info"), rule.id
        assert rule.pass_name in ("robustness", "concurrency",
                                  "dispatch", "determinism",
                                  "fsseam"), rule.id


def test_baseline_requires_reason(tmp_path):
    p = tmp_path / "baseline.json"
    p.write_text(json.dumps({"entries": [
        {"path": "a.py", "rule": "C2", "line": 3}]}))
    try:
        load_baseline(str(p))
        raise AssertionError("unjustified baseline entry accepted")
    except ValueError:
        pass
    p.write_text(json.dumps({"entries": [
        {"path": "a.py", "rule": "C2", "line": 3, "reason": "reviewed"}]}))
    assert len(load_baseline(str(p))) == 1


def test_baseline_matches_and_flags_rot(tmp_path):
    p = tmp_path / "baseline.json"
    entries = [
        {"path": "a.py", "rule": "C2", "line": 3, "reason": "reviewed"},
        {"path": "gone.py", "rule": "T1", "line": 9, "reason": "stale"},
    ]
    findings = [Finding("a.py", 3, "C2", "m", "error")]
    out = apply_baseline(findings, entries, str(p))
    assert out[0].baselined and out[0].baseline_reason == "reviewed"
    s2 = [f for f in out if f.rule == "S2"]
    assert len(s2) == 1 and "gone.py" in s2[0].msg
    # a baselined error no longer fails the gate; the S2 warning does
    assert _rules(failing(out, "warning")) == ["S2"]


def test_shipped_baseline_is_empty():
    """The acceptance contract: the repo gate runs on an empty
    baseline (or every entry justified — but we ship none)."""
    from faalint.engine import default_baseline_path

    assert load_baseline(default_baseline_path()) == []


# ------------------------------------------------------- stale suppression


def test_stale_allow_marker_flagged():
    src = "x = 1  # robust: allow — nothing here triggers any rule\n"
    findings = check_source(src, UTILS, stale_check=True)
    assert _rules(findings) == ["S1"]


def test_used_allow_marker_not_stale():
    src = ("try:\n    f()\n"
           "except:  # robust: allow — deliberate\n    pass\n")
    assert not check_source(src, UTILS, stale_check=True)


def test_stale_check_off_by_default():
    src = "x = 1  # robust: allow — scope-forced matrix run\n"
    assert not check_source(src, UTILS)


# ----------------------------------------------------------------- R9


def test_r9_unbounded_put_and_sleep_loop_in_ext_scope():
    src = ("import queue, time\nq = queue.Queue()\nq.put(item)\n"
           "while True:\n    time.sleep(0.1)\n")
    for scope in (CORE, LAUNCH, DATA, UTILS):
        assert _rules(check_source(src, scope)).count("R9") == 2, scope
    # train/ stays out of every blocking scope
    assert "R9" not in _rules(check_source(src, TRAIN))


def test_r9_does_not_double_flag_r4_findings():
    """join/get on a tracked receiver in core/launch is R4's finding;
    R9 adds only what R4 misses (put/wait/sleep loops)."""
    src = ("import threading, queue\n"
           "t = threading.Thread(target=f)\nq = queue.Queue()\n"
           "t.join()\nq.get()\nq.put(x)\n")
    rules = _rules(check_source(src, LAUNCH))
    assert rules.count("R4") == 2
    assert rules.count("R9") == 1  # the put only
    # data/ has no R4, so R9 owns join/get there
    rules_data = _rules(check_source(src, DATA))
    assert rules_data.count("R4") == 0
    assert rules_data.count("R9") == 3


def test_r9_covers_fleet_transport_shapes_in_launch_scope():
    """The PR-13 satellite pin: the cross-host round transport's
    failure shapes — an actor claim poll with a bare sleep, an
    unbounded result-queue get — are R9 findings when they live in the
    launch/ transport layer, and the BOUNDED forms the shipped code
    uses stay clean."""
    bad = ("import time, queue\n"
           "res_q = queue.Queue()\n"
           "def actor_loop(transport):\n"
           "    while not transport.search_done():\n"
           "        time.sleep(0.5)\n"          # unbounded claim poll
           "    return res_q.get()\n")          # unbounded reward wait
    rules = _rules(check_source(bad, LAUNCH))
    assert rules.count("R9") == 1   # the sleep-in-while poll loop
    assert rules.count("R4") == 1   # launch/: the get is R4's finding
    good = bad.replace("time.sleep(0.5)",
                       "time.sleep(0.5)  # robust: allow") \
              .replace("res_q.get()", "res_q.get(timeout=5.0)")
    assert not check_source(good, LAUNCH)


def test_r7_covers_fleet_transport_shapes_in_search_scope():
    """The same transport shapes inside search/ (where the learner
    backend and actor loop actually live) belong to R7 — one engine,
    scope-keyed rule ids."""
    bad = ("import time\n"
           "def wait_checkpoint(rec):\n"
           "    while rec is None:\n"
           "        time.sleep(0.5)\n")
    search_path = "fast_autoaugment_tpu/search/x.py"
    rules = _rules(check_source(bad, search_path))
    assert rules == ["R7"]
    assert "R9" not in rules  # search keeps its own rule id
    allowed = bad.replace("time.sleep(0.5)",
                          "time.sleep(0.5)  # robust: allow")
    assert not check_source(allowed, search_path)


def test_r9_event_wait_flagged_and_bounded_ok():
    src = ("import threading\nevt = threading.Event()\nevt.wait()\n")
    assert _rules(check_source(src, CORE)) == ["R9"]
    assert not check_source(
        src.replace("evt.wait()", "evt.wait(5.0)"), CORE)


def test_r9_robust_allow_suppression():
    src = ("import queue\nq = queue.Queue()\n"
           "q.put(x)  # robust: allow — bounded by construction\n")
    assert not check_source(src, CORE)


# ----------------------------------------------------------------- C1


_C1_POS = ("import threading\n"
           "a = threading.Lock()\n"
           "b = threading.Lock()\n"
           "def f():\n"
           "    with a:\n"
           "        with b:\n"
           "            pass\n"
           "def g():\n"
           "    with b:\n"
           "        with a:\n"
           "            pass\n")


def test_c1_lock_order_inversion_flagged():
    findings = check_source(_C1_POS, UTILS)
    assert _rules(findings) == ["C1", "C1"]
    assert "deadlock" in findings[0].msg


def test_c1_consistent_order_ok():
    src = _C1_POS.replace("    with b:\n        with a:",
                          "    with a:\n        with b:")
    assert not check_source(src, UTILS)


def test_c1_self_locks_are_class_qualified():
    """Two classes each nesting their own self._lock under another's
    is fine; the same textual name must not self-collide."""
    src = ("import threading\n"
           "class A:\n"
           "    def __init__(self):\n"
           "        self._lock = threading.Lock()\n"
           "    def f(self):\n"
           "        with self._lock:\n"
           "            with self._lock:\n"
           "                pass\n")
    # reentrant same-lock nesting is not an ordering cycle
    assert not check_source(src, UTILS)


def test_c1_robust_allow_suppression():
    src = _C1_POS.replace("        with b:\n",
                          "        with b:  # robust: allow — reviewed\n")
    findings = check_source(src, UTILS)
    assert _rules(findings) == ["C1"]  # only the un-annotated edge


# ----------------------------------------------------------------- C2


def test_c2_thread_closure_write_vs_public_write():
    """The helper a thread body calls transitively is part of the
    thread body; a public unguarded write to the same attr races."""
    src = ("import threading\n"
           "class W:\n"
           "    def __init__(self):\n"
           "        self.state = 0\n"
           "    def start(self):\n"
           "        t = threading.Thread(target=self._run, daemon=True)\n"
           "        t.start()\n"
           "    def _run(self):\n"
           "        self._step()\n"
           "    def _step(self):\n"
           "        self.state += 1\n"
           "    def reset(self):\n"
           "        self.state = 0\n")
    findings = check_source(src, UTILS)
    assert _rules(findings) == ["C2", "C2"]  # _step write + reset write


def test_c2_guarded_writes_ok():
    src = ("import threading\n"
           "class W:\n"
           "    def __init__(self):\n"
           "        self.state = 0\n"
           "        self._lock = threading.Lock()\n"
           "    def start(self):\n"
           "        t = threading.Thread(target=self._run, daemon=True)\n"
           "        t.start()\n"
           "    def _run(self):\n"
           "        with self._lock:\n"
           "            self.state += 1\n"
           "    def reset(self):\n"
           "        with self._lock:\n"
           "            self.state = 0\n")
    assert not check_source(src, UTILS)


def test_c2_init_writes_are_happens_before():
    src = ("import threading\n"
           "class W:\n"
           "    def __init__(self):\n"
           "        self.state = 0\n"
           "        self.t = threading.Thread(target=self._run)\n"
           "    def _run(self):\n"
           "        with self._lock:\n"
           "            self.state = 1\n")
    # __init__ writes happen before the thread starts: no race
    assert not check_source(src, UTILS)


def test_c2_robust_allow_suppression():
    src = ("import threading\n"
           "class W:\n"
           "    def start(self):\n"
           "        t = threading.Thread(target=self._run)\n"
           "        t.start()\n"
           "    def _run(self):\n"
           "        self.n = 1  # robust: allow — reviewed\n"
           "    def bump(self):\n"
           "        self.n = 2  # robust: allow — reviewed\n")
    assert not check_source(src, UTILS)


# ----------------------------------------------------------------- C3


_C3_POS = ("import os\n"
           "from fast_autoaugment_tpu.search.driver import"
           " write_json_atomic\n"
           "def reclaim(path, rec):\n"
           "    os.remove(path)\n"
           "    write_json_atomic(path, rec)\n")


def test_c3_remove_then_recreate_flagged():
    findings = check_source(_C3_POS, LAUNCH)
    assert _rules(findings) == ["C3"]
    assert "absence window" in findings[0].msg


def test_c3_os_replace_destination_counts_as_recreate():
    src = ("import os\n"
           "def rotate(tmp, path):\n"
           "    os.remove(path)\n"
           "    os.replace(tmp, path)\n")
    assert _rules(check_source(src, LAUNCH)) == ["C3"]


def test_c3_atomic_link_claim_is_exempt():
    src = ("import os\n"
           "def claim(tmp, path):\n"
           "    os.remove(path)\n"
           "    os.link(tmp, path)\n")
    assert not check_source(src, LAUNCH)


def test_c3_remove_after_create_ok_and_scope():
    src = ("import os\n"
           "from fast_autoaugment_tpu.search.driver import"
           " write_json_atomic\n"
           "def publish(path, rec):\n"
           "    write_json_atomic(path, rec)\n"
           "    os.remove(path + '.tmp')\n")
    assert not check_source(src, LAUNCH)
    # utils/ is outside the lease/artifact scope
    assert "C3" not in _rules(check_source(_C3_POS, UTILS))


def test_c3_robust_allow_suppression():
    src = _C3_POS.replace(
        "os.remove(path)",
        "os.remove(path)  # robust: allow — single-process region")
    assert not check_source(src, LAUNCH)


# -------------------------------------------------------------- D1/D2/D3


def test_d1_item_in_loop_flagged_in_dispatch_scope_only():
    src = ("def f(xs):\n"
           "    out = []\n"
           "    for x in xs:\n"
           "        out.append(x.item())\n"
           "    return out\n")
    assert _rules(check_source(src, TRAIN)) == ["D1"]
    assert not check_source(src, CORE)  # core/ is not a dispatch path


def test_d1_unjitted_callee_not_flagged():
    src = ("def f(step, state, batches):\n"
           "    for b in batches:\n"
           "        state, m = step(state, b)\n"
           "        x = float(m['loss'])\n"
           "    return state\n")
    # `step` is a parameter: the analysis cannot prove it jitted
    assert not check_source(src, TRAIN)


def test_d1_severity_is_warning():
    src = ("def f(xs):\n"
           "    for x in xs:\n"
           "        y = x.item()\n")
    (finding,) = check_source(src, TRAIN)
    assert finding.severity == "warning"


def test_d2_robust_allow_suppression():
    src = corpus_load("jit_in_loop", "prefix").replace(
        "step = seam_jit(body, label=\"eval_step\")",
        "step = seam_jit(body, label=\"eval_step\")  # robust: allow — x")
    assert not check_source(src, TRAIN)


def test_d3_corpus_shapes():
    # exercised via the corpus (prefix flags, postfix clean); here the
    # suppression path
    src = corpus_load("mixed_commit", "prefix").replace(
        "state, metrics = step(state, cache, index)",
        "state, metrics = step(state, cache, index)  # robust: allow — x")
    assert not check_source(src, TRAIN)


# ------------------------------------------------------------------- D4


SERVE = "fast_autoaugment_tpu/serve/x.py"

_D4_POS = ("import io\n"
           "import numpy as np\n"
           "def _do_augment(self, server, body):\n"
           "    payload = np.load(io.BytesIO(body), allow_pickle=False)\n"
           "    return server.submit(payload['images'])\n")


def test_d4_np_load_in_request_handler_serve_scope_only():
    assert _rules(check_source(_D4_POS, SERVE)) == ["D4"]
    # train/ request-ish names are not a serving hot path
    assert "D4" not in _rules(check_source(_D4_POS, TRAIN))


def test_d4_handler_class_helper_methods_are_hot_path():
    src = ("import numpy as np\n"
           "class MyHandler:\n"
           "    def _parse_images(self, body):\n"
           "        return np.array(body)\n")
    assert _rules(check_source(src, SERVE)) == ["D4"]


def test_d4_tobytes_and_savez_flagged():
    src = ("import io\n"
           "import numpy as np\n"
           "def do_POST(self, out):\n"
           "    buf = io.BytesIO()\n"
           "    np.savez(buf, images=out)\n"
           "    return out.tobytes()\n")
    assert _rules(check_source(src, SERVE)) == ["D4", "D4"]


def test_d4_np_array_copy_false_is_a_view_not_flagged():
    src = ("import numpy as np\n"
           "def _do_augment(self, body):\n"
           "    return np.array(body, copy=False)\n")
    assert not check_source(src, SERVE)


def test_d4_non_handler_functions_exempt():
    # encode/decode helpers OUTSIDE a handler (wire.py's client-side
    # encoders legitimately materialize bytes)
    src = ("import numpy as np\n"
           "def encode_raw(images):\n"
           "    return images.tobytes()\n")
    assert not check_source(src, SERVE)


def test_d4_robust_allow_marks_the_legacy_npz_lane():
    src = _D4_POS.replace(
        "payload = np.load(io.BytesIO(body), allow_pickle=False)",
        "payload = np.load(io.BytesIO(body), allow_pickle=False)"
        "  # robust: allow — legacy npz lane")
    assert not check_source(src, SERVE)


def test_d4_corpus_case_registered():
    assert "npz_per_request" in CASES
    relpath, expected, pass_name = CASES["npz_per_request"]
    assert expected == {"D4"} and pass_name == "dispatch"


# -------------------------------------------------------------- T1/T2/T3


def test_t_rules_only_fire_in_persisting_functions():
    src = ("import time, os\n"
           "def measure():\n"
           "    t0 = time.time()\n"
           "    pid = os.getpid()\n"
           "    for x in {1, 2}:\n"
           "        pass\n"
           "    return t0, pid\n")
    # no writer call in the function: not an artifact path
    assert not check_source(src, CORE)


def test_t1_taint_through_assignment():
    src = ("import time\n"
           "from fast_autoaugment_tpu.search.driver import"
           " write_json_atomic\n"
           "def persist(path):\n"
           "    stamp = time.time()\n"
           "    payload = {'at': stamp}\n"
           "    write_json_atomic(path, payload)\n")
    findings = check_source(src, CORE)
    assert _rules(findings) == ["T1"]
    assert "time.time()" in findings[0].msg


def test_t2_sorted_wrappers_clean():
    assert not check_source(
        corpus_load("unsorted_listdir", "postfix"),
        "fast_autoaugment_tpu/core/checkpoint.py")


def test_t3_launch_is_out_of_scope_by_design():
    """Lease/heartbeat records are wall+pid stamped BY DESIGN —
    staleness detection is their function (docs/STATIC_ANALYSIS.md)."""
    src = corpus_load("wallclock_pid_payload", "prefix")
    assert not check_source(src, LAUNCH)


def test_t1_robust_allow_suppression():
    src = corpus_load("wallclock_pid_payload", "prefix").replace(
        "    write_json_atomic(path, payload)",
        "    write_json_atomic(path, payload)  # robust: allow — x")
    assert not check_source(src, "fast_autoaugment_tpu/core/checkpoint.py")


# ----------------------------------------------------------------- corpus


def test_corpus_is_green():
    problems = check_corpus()
    assert not problems, "\n".join(problems)


def test_historical_bugs_each_caught_by_exactly_one_pass():
    """The acceptance bullet: each pre-fix snippet of the three
    shipped-then-fixed bugs is flagged by the intended pass (and ONLY
    that pass), and the post-fix shape is clean."""
    passes = rule_pass_map()
    for name in HISTORICAL:
        relpath, expected, intended = CASES[name]
        findings = check_source(corpus_load(name, "prefix"), relpath)
        assert findings, name
        hit_passes = {passes[f.rule] for f in findings}
        assert hit_passes == {intended}, (name, hit_passes)
        assert {f.rule for f in findings} == expected, name
        assert not check_source(corpus_load(name, "postfix"), relpath), name


# ------------------------------------------------------------------- F1


SEARCH = "fast_autoaugment_tpu/search/x.py"
CONTROL = "fast_autoaugment_tpu/control/x.py"


def test_f1_direct_shared_dir_io_flagged_in_fsseam_scopes_only():
    src = ("import json, os\n"
           "def f(d):\n"
           "    names = os.listdir(d)\n"
           "    with open(os.path.join(d, names[0])) as fh:\n"
           "        return json.load(fh)\n")
    for scope in (LAUNCH, SEARCH, CONTROL):
        assert _rules(check_source(src, scope)).count("F1") == 3, scope
    # core/ holds the seam itself; train/ has no shared-dir protocol
    assert "F1" not in _rules(check_source(src, CORE))
    assert "F1" not in _rules(check_source(src, TRAIN))


def test_f1_shapes_stat_getsize_glob():
    src = ("import glob, os\n"
           "def f(d, p):\n"
           "    a = os.stat(p)\n"
           "    b = os.path.getsize(p)\n"
           "    c = glob.glob(os.path.join(d, '*.json'))\n"
           "    return a, b, c\n")
    assert _rules(check_source(src, CONTROL)).count("F1") == 3
    # json.loads (string-level) and os.path.join are not I/O
    src2 = ("import json, os\n"
            "def f(s, d):\n"
            "    return json.loads(s), os.path.join(d, 'x')\n")
    assert not check_source(src2, CONTROL)


def test_f1_seam_primitives_and_writer_are_clean():
    src = ("from fast_autoaugment_tpu.core import fsfault\n"
           "def f(d, p):\n"
           "    rec = fsfault.read_json(p)\n"
           "    names = fsfault.listdir(d)\n"
           "    fsfault.write_json_atomic(p, rec)\n"
           "    return names\n")
    assert not check_source(src, LAUNCH)
    # the atomic-writer primitive is the seam's own delegate (the R3
    # allowlist idiom): its internal open() is exempt by function name
    writer = ("import json, os\n"
              "def write_json_atomic(path, obj):\n"
              "    tmp = path + '.tmp'\n"
              "    with open(tmp, 'w') as fh:\n"
              "        json.dump(obj, fh)\n"
              "    os.replace(tmp, path)\n")
    assert "F1" not in _rules(check_source(writer, SEARCH))


def test_f1_robust_allow_suppression():
    src = ("import json\n"
           "def f(p):\n"
           "    with open(p) as fh:  # robust: allow — local-only file\n"
           "        return json.load(fh)  # robust: allow — local-only\n")
    assert not check_source(src, LAUNCH)


# -------------------------------------------------------------- live gates


def test_repo_is_clean_full_rule_set():
    """The live gate `make lint` runs: every package file, every pass,
    stale + baseline hygiene — zero fatal findings."""
    findings = failing(lint_tree(), "warning")
    assert not findings, "\n".join(map(repr, findings))


def test_cli_json_and_selfcheck(capsys):
    from faalint.cli import main

    assert main(["--selfcheck"]) == 0
    capsys.readouterr()
    assert main(["--json"]) == 0
    data = json.loads(capsys.readouterr().out)
    assert data["fatal"] == 0
    assert data["rules"] == len(default_rules())
    assert data["wall_sec"] < 20  # the ~10s budget, with slow-host slack
