"""Golden tests: on-device augmentation kernels vs PIL semantics.

Each case reproduces exactly what the reference does with PIL
(``/root/reference/FastAutoAugment/augmentations.py``) and checks the
jnp kernel matches bit-exactly (or within a documented tolerance) on
random uint8 images.  Mirroring randomness is bypassed by calling the
deterministic op functions directly with signed values.
"""

import numpy as np
import PIL.Image
import PIL.ImageDraw
import PIL.ImageEnhance
import PIL.ImageFilter
import PIL.ImageOps
import pytest

import jax
import jax.numpy as jnp

from fast_autoaugment_tpu.ops import augment as A


def _rand_img(seed, h=32, w=32):
    return np.random.default_rng(seed).integers(0, 256, (h, w, 3), dtype=np.uint8)


def _check(jnp_out, pil_img, atol=0):
    got = np.asarray(jnp_out).astype(np.int32)
    want = np.asarray(pil_img).astype(np.int32)
    assert got.shape == want.shape
    diff = np.abs(got - want)
    assert diff.max() <= atol, f"max diff {diff.max()} at {np.unravel_index(diff.argmax(), diff.shape)}"


KEY = jax.random.PRNGKey(0)
SIZES = [(32, 32), (17, 23)]


@pytest.mark.parametrize("h,w", SIZES)
@pytest.mark.parametrize("v", [-0.3, -0.1, 0.17, 0.3])
def test_shear(h, w, v):
    img = _rand_img(0, h, w)
    pim = PIL.Image.fromarray(img)
    _check(A.shear_x(jnp.float32(img), jnp.float32(v), KEY),
           pim.transform(pim.size, PIL.Image.AFFINE, (1, v, 0, 0, 1, 0)))
    _check(A.shear_y(jnp.float32(img), jnp.float32(v), KEY),
           pim.transform(pim.size, PIL.Image.AFFINE, (1, 0, 0, v, 1, 0)))


@pytest.mark.parametrize("h,w", SIZES)
@pytest.mark.parametrize("v", [-0.45, -0.2, 0.11, 0.45])
def test_translate_fractional(h, w, v):
    img = _rand_img(1, h, w)
    pim = PIL.Image.fromarray(img)
    _check(A.translate_x(jnp.float32(img), jnp.float32(v), KEY),
           pim.transform(pim.size, PIL.Image.AFFINE, (1, 0, v * w, 0, 1, 0)))
    _check(A.translate_y(jnp.float32(img), jnp.float32(v), KEY),
           pim.transform(pim.size, PIL.Image.AFFINE, (1, 0, 0, 0, 1, v * h)))


@pytest.mark.parametrize("v", [-10, -3, 0, 7, 10])
def test_translate_abs(v):
    img = _rand_img(2)
    pim = PIL.Image.fromarray(img)
    _check(A.translate_x_abs(jnp.float32(img), jnp.float32(v), KEY),
           pim.transform(pim.size, PIL.Image.AFFINE, (1, 0, v, 0, 1, 0)))
    _check(A.translate_y_abs(jnp.float32(img), jnp.float32(v), KEY),
           pim.transform(pim.size, PIL.Image.AFFINE, (1, 0, 0, 0, 1, v)))


@pytest.mark.parametrize("h,w", SIZES)
@pytest.mark.parametrize("v", [-30.0, -12.5, 7.3, 30.0])
def test_rotate(h, w, v):
    img = _rand_img(3, h, w)
    pim = PIL.Image.fromarray(img)
    _check(A.rotate(jnp.float32(img), jnp.float32(v), KEY), pim.rotate(v))


@pytest.mark.parametrize("seed", range(4))
def test_autocontrast(seed):
    img = _rand_img(seed)
    if seed == 1:  # low dynamic range exercises the stretch
        img = (img // 4 + 64).astype(np.uint8)
    pim = PIL.Image.fromarray(img)
    # atol=1: we use the exact integer LUT; PIL's double-precision
    # truncation occasionally lands 1 lower (see ops/augment.py).
    _check(A.auto_contrast(jnp.float32(img), jnp.float32(0), KEY),
           PIL.ImageOps.autocontrast(pim), atol=1)


def test_autocontrast_constant_channel():
    img = np.full((8, 8, 3), 77, np.uint8)
    pim = PIL.Image.fromarray(img)
    _check(A.auto_contrast(jnp.float32(img), jnp.float32(0), KEY),
           PIL.ImageOps.autocontrast(pim))


@pytest.mark.parametrize("seed", range(4))
def test_equalize(seed):
    img = _rand_img(seed)
    if seed == 2:  # skewed histogram
        img = (img.astype(np.float32) ** 2 / 255.0).astype(np.uint8)
    pim = PIL.Image.fromarray(img)
    _check(A.equalize(jnp.float32(img), jnp.float32(0), KEY), PIL.ImageOps.equalize(pim))


def test_equalize_constant_image():
    img = np.full((8, 8, 3), 9, np.uint8)
    _check(A.equalize(jnp.float32(img), jnp.float32(0), KEY),
           PIL.ImageOps.equalize(PIL.Image.fromarray(img)))


def test_invert():
    img = _rand_img(5)
    _check(A.invert(jnp.float32(img), jnp.float32(0), KEY),
           PIL.ImageOps.invert(PIL.Image.fromarray(img)))


@pytest.mark.parametrize("v", [0, 77.5, 128, 255, 256])
def test_solarize(v):
    img = _rand_img(6)
    _check(A.solarize(jnp.float32(img), jnp.float32(v), KEY),
           PIL.ImageOps.solarize(PIL.Image.fromarray(img), v))


@pytest.mark.parametrize("v", [0, 1, 2.7, 4, 4.9, 6, 8])
def test_posterize(v):
    img = _rand_img(7)
    _check(A.posterize(jnp.float32(img), jnp.float32(v), KEY),
           PIL.ImageOps.posterize(PIL.Image.fromarray(img), int(v)))
    _check(A.posterize2(jnp.float32(img), jnp.float32(v), KEY),
           PIL.ImageOps.posterize(PIL.Image.fromarray(img), int(v)))


@pytest.mark.parametrize("v", [0.1, 0.6, 1.0, 1.33, 1.9])
@pytest.mark.parametrize("enhancer,fn", [
    (PIL.ImageEnhance.Contrast, A.contrast),
    (PIL.ImageEnhance.Color, A.color),
    (PIL.ImageEnhance.Brightness, A.brightness),
])
def test_enhance_exact(v, enhancer, fn):
    img = _rand_img(8)
    pim = PIL.Image.fromarray(img)
    _check(fn(jnp.float32(img), jnp.float32(v), KEY), enhancer(pim).enhance(v))


@pytest.mark.parametrize("h,w", SIZES)
@pytest.mark.parametrize("v", [0.1, 0.6, 1.0, 1.9])
def test_sharpness(h, w, v):
    img = _rand_img(9, h, w)
    pim = PIL.Image.fromarray(img)
    _check(A.sharpness(jnp.float32(img), jnp.float32(v), KEY),
           PIL.ImageEnhance.Sharpness(pim).enhance(v))


@pytest.mark.parametrize("v", [0.0, 4.0, 11.3, 20.0])
def test_cutout_abs_matches_pil_rectangle(v):
    """Replicate the jax random draws on the host, then compare against
    the reference CutoutAbs drawing (augmentations.py:127-146)."""
    img = _rand_img(10)
    key = jax.random.PRNGKey(42)
    got = A.cutout_abs(jnp.float32(img), jnp.float32(v), key)

    h, w = img.shape[:2]
    kx, ky = jax.random.split(key)
    x0f = float(jax.random.uniform(kx, (), minval=0.0, maxval=float(w)))
    y0f = float(jax.random.uniform(ky, (), minval=0.0, maxval=float(h)))
    x0 = int(max(0, x0f - v / 2.0))
    y0 = int(max(0, y0f - v / 2.0))
    x1 = min(w, x0 + v)
    y1 = min(h, y0 + v)
    pim = PIL.Image.fromarray(img).copy()
    PIL.ImageDraw.Draw(pim).rectangle((x0, y0, x1, y1), tuple(int(c) for c in A.CUTOUT_COLOR))
    _check(got, pim)


def test_cutout_zero_is_identity():
    img = jnp.float32(_rand_img(11))
    out = A.cutout(img, jnp.float32(0.0), jax.random.PRNGKey(3))
    np.testing.assert_array_equal(np.asarray(out), np.asarray(img))


def test_flip():
    img = _rand_img(12)
    _check(A.flip(jnp.float32(img), jnp.float32(0), KEY),
           PIL.ImageOps.mirror(PIL.Image.fromarray(img)))


# ---------------------------------------------------------------------------
# dispatch machinery
# ---------------------------------------------------------------------------


def test_registry_matches_reference():
    names = [n for n, _, _ in A.augment_list(False)]
    assert names == [
        "ShearX", "ShearY", "TranslateX", "TranslateY", "Rotate",
        "AutoContrast", "Invert", "Equalize", "Solarize", "Posterize",
        "Contrast", "Color", "Brightness", "Sharpness", "Cutout",
    ]
    assert len(A.augment_list(True)) == 19
    assert "Flip" not in A.OP_NAMES


def test_apply_op_jits_with_traced_index():
    img = jnp.float32(_rand_img(13))

    @jax.jit
    def run(op_idx, level, key):
        return A.apply_op(img, op_idx, level, key)

    key = jax.random.PRNGKey(0)
    out_inv = run(jnp.int32(6), jnp.float32(0.5), key)
    _check(out_inv, PIL.ImageOps.invert(PIL.Image.fromarray(np.asarray(img, np.uint8))))
    # same compiled fn serves another op id — policy-as-data
    out_eq = run(jnp.int32(7), jnp.float32(0.5), key)
    _check(out_eq, PIL.ImageOps.equalize(PIL.Image.fromarray(np.asarray(img, np.uint8))))


def test_cutout_abs_never_mirrors_through_dispatch():
    """Regression: CutoutAbs must NOT sign-flip its value in apply_op —
    a negative value silently disables it (reference CutoutAbs has no
    mirror, augmentations.py:127-131)."""
    img = jnp.float32(np.zeros((32, 32, 3), np.uint8))
    keys = jax.random.split(jax.random.PRNGKey(11), 64)
    # op 15 = CutoutAbs at level 1.0 -> 20px box; on a black image the
    # gray fill must appear for EVERY key
    outs = jax.vmap(lambda k: A.apply_op(img, jnp.int32(15), jnp.float32(1.0), k))(keys)
    changed = (np.asarray(outs) != 0).any(axis=(1, 2, 3))
    assert changed.all(), f"CutoutAbs was a no-op for {int((~changed).sum())}/64 keys"


def test_mirror_flips_sign_half_the_time():
    img = jnp.float32(_rand_img(14))
    keys = jax.random.split(jax.random.PRNGKey(7), 200)
    # TranslateX at level 1.0 -> value +0.45 or -0.45; look at which side keeps pixels
    outs = jax.vmap(lambda k: A.apply_op(img, jnp.int32(2), jnp.float32(1.0), k))(keys)
    left_zero = (np.asarray(outs)[:, :, :10, :] == 0).all(axis=(1, 2, 3))
    frac = left_zero.mean()
    assert 0.3 < frac < 0.7, frac


def test_apply_policy_batch_shapes_and_determinism():
    imgs = jnp.float32(np.stack([_rand_img(s) for s in range(8)]))
    policy = jnp.float32(
        [[[6, 1.0, 0.0], [8, 1.0, 0.5]],
         [[7, 0.5, 0.0], [12, 1.0, 0.9]]]
    )
    key = jax.random.PRNGKey(5)
    out1 = A.apply_policy_batch(imgs, policy, key)
    out2 = A.apply_policy_batch(imgs, policy, key)
    assert out1.shape == imgs.shape
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
    # different key -> different augmentation
    out3 = A.apply_policy_batch(imgs, policy, jax.random.PRNGKey(6))
    assert not np.array_equal(np.asarray(out1), np.asarray(out3))


def test_prob_zero_policy_is_identity():
    imgs = jnp.float32(np.stack([_rand_img(s) for s in range(4)]))
    policy = jnp.float32([[[4, 0.0, 1.0], [0, 0.0, 1.0]]])
    out = A.apply_policy_batch(imgs, policy, jax.random.PRNGKey(1))
    np.testing.assert_array_equal(np.asarray(out), np.asarray(imgs))
