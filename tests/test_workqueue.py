"""Lease-queue units: claim/renew/steal races, observer-local stale
reclaim, epoch fencing, done markers, host census, and the
degraded-mode accounting — all fast, host-only, no jax.  The
multi-process story is the slow self-healing e2e
(tests/test_selfheal_fleet.py); the hostile-filesystem story is
tests/test_fsfault.py.
"""

from __future__ import annotations

import json
import os
import threading
import time

import pytest

from fast_autoaugment_tpu.launch.workqueue import LeaseLostError, WorkQueue
from fast_autoaugment_tpu.utils import faultinject


@pytest.fixture(autouse=True)
def _clean_fault_env():
    saved = os.environ.pop("FAA_FAULT", None)
    faultinject.reset()
    yield
    if saved is None:
        os.environ.pop("FAA_FAULT", None)
    else:
        os.environ["FAA_FAULT"] = saved
    faultinject.reset()


def _q(tmp_path, owner, ttl=60.0):
    return WorkQueue(str(tmp_path / "wq"), owner, lease_ttl=ttl)


def _watch_out_ttl(q: WorkQueue, unit: str, margin: float = 0.05):
    """Observer-local staleness: the claimant must WATCH the foreign
    lease sit unchanged for a full TTL on its own clock before a
    reclaim is allowed.  First claim observes (and declines), the wait
    makes the observation stale."""
    assert not q.claim(unit)  # records the observation
    time.sleep(q.lease_ttl + margin)


def test_claim_fresh_and_mutual_exclusion(tmp_path):
    a, b = _q(tmp_path, "a"), _q(tmp_path, "b")
    assert a.claim("u1")
    assert not b.claim("u1")  # live lease elsewhere
    lease = b.read_lease("u1")
    assert lease["owner"] == "a" and lease["attempt"] == 1
    assert lease["epoch"] == 1  # the fencing token, from birth


def test_reclaim_own_lease_after_restart(tmp_path):
    a = _q(tmp_path, "a")
    assert a.claim("u1")
    a2 = _q(tmp_path, "a")  # the relaunched process, same owner tag
    assert a2.claim("u1")   # immediate, no TTL wait
    lease = a2.read_lease("u1")
    assert lease["attempt"] == 1  # not a steal
    assert lease["epoch"] == 1    # same ownership chain, same epoch


def test_renew_refreshes_heartbeat(tmp_path):
    a = _q(tmp_path, "a")
    a.claim("u1")
    hb0 = a.read_lease("u1")["heartbeat"]
    time.sleep(0.02)
    a.renew("u1")
    lease = a.read_lease("u1")
    assert lease["heartbeat"] > hb0
    assert lease["epoch"] == 1  # renewals carry the token forward


def test_stale_lease_is_reclaimed_with_attempt_and_epoch_bump(tmp_path):
    a, b = _q(tmp_path, "a", ttl=0.15), _q(tmp_path, "b", ttl=0.15)
    assert a.claim("u1")
    _watch_out_ttl(b, "u1")       # b observes the dead owner's lease
    assert b.claim("u1")          # ...and reclaims past ITS OWN ttl
    lease = b.read_lease("u1")
    assert lease["owner"] == "b"
    assert lease["attempt"] == 2
    assert lease["epoch"] == 2    # fencing token advanced
    assert lease["reclaimed_from"] == "a"
    assert b.reclaimed_units == ["u1"]


def test_live_renewals_reset_the_observer_clock(tmp_path):
    """A SLOW owner that still heartbeats is never robbed: every renew
    changes the lease fingerprint, restarting the observer's staleness
    window."""
    a, b = _q(tmp_path, "a", ttl=0.2), _q(tmp_path, "b", ttl=0.2)
    assert a.claim("u1")
    for _ in range(3):
        assert not b.claim("u1")
        time.sleep(0.15)     # under the ttl each time...
        a.renew("u1")        # ...and the owner keeps beating
    assert not b.claim("u1")  # total elapsed >> ttl, still not stale


def test_skewed_heartbeat_stamps_cannot_fake_or_hide_death(tmp_path,
                                                           monkeypatch):
    """The skew-proof pin: a lease whose heartbeat STAMP is 10 minutes
    in the future (or past) reclaims on exactly the same observer-local
    schedule — wall stamps are compared for identity, never against
    the observer's clock."""
    a, b = _q(tmp_path, "a", ttl=0.15), _q(tmp_path, "b", ttl=0.15)
    assert a.claim("u1")
    path = a._lease_path("u1")
    rec = json.load(open(path))
    rec["heartbeat"] += 600.0  # a wildly fast clock on the owner host
    with open(path, "w") as fh:  # test-only surgery
        json.dump(rec, fh)
    _watch_out_ttl(b, "u1")
    assert b.claim("u1")  # future stamp did not immortalize the zombie
    assert b.read_lease("u1")["epoch"] == 2

    a2, c = _q(tmp_path, "a2", ttl=60.0), _q(tmp_path, "c", ttl=60.0)
    assert a2.claim("u2")
    path = a2._lease_path("u2")
    rec = json.load(open(path))
    rec["heartbeat"] -= 600.0  # a wildly slow clock on the owner host
    with open(path, "w") as fh:
        json.dump(rec, fh)
    # under the OLD wall-compare scheme this looked 10 min stale and
    # was robbed instantly; observer-local staleness declines
    assert not c.claim("u2")


def test_renew_after_steal_raises_lease_lost(tmp_path):
    a, b = _q(tmp_path, "a", ttl=0.15), _q(tmp_path, "b", ttl=0.15)
    a.claim("u1")
    _watch_out_ttl(b, "u1")
    assert b.claim("u1")
    with pytest.raises(LeaseLostError):
        a.renew("u1")  # the presumed-dead owner must stop working


def test_zombie_release_is_fenced_off(tmp_path):
    """THE fencing pin: a robbed zombie's late done-marker post raises
    instead of clobbering the reclaimed unit's completion record."""
    a, b = _q(tmp_path, "a", ttl=0.15), _q(tmp_path, "b", ttl=0.15)
    a.claim("u1")
    _watch_out_ttl(b, "u1")
    assert b.claim("u1")          # epoch 2, owner b
    with pytest.raises(LeaseLostError):
        a.release("u1", info={"rewards": [0.0]})  # zombie write FENCED
    assert not a.is_done("u1")    # nothing was clobbered
    b.release("u1", info={"rewards": [1.0]})
    done = b.done_record("u1")
    assert done["owner"] == "b" and done["epoch"] == 2
    assert done["info"] == {"rewards": [1.0]}
    # and a zombie racing AFTER the reclaimer finished is fenced by the
    # done marker's epoch even though the lease file is gone
    with pytest.raises(LeaseLostError):
        a.release("u1", info={"rewards": [0.0]})
    assert b.done_record("u1")["info"] == {"rewards": [1.0]}


def test_release_writes_done_marker_and_blocks_reclaim(tmp_path):
    a, b = _q(tmp_path, "a"), _q(tmp_path, "b")
    a.claim("u1")
    a.release("u1", info={"baseline": 0.9, "excluded": False})
    assert a.is_done("u1") and b.is_done("u1")
    assert not b.claim("u1")  # done units are never re-claimed
    assert b.done_info("u1") == {"baseline": 0.9, "excluded": False}
    assert a.read_lease("u1") is None  # lease cleaned up
    assert a.done_record("u1")["epoch"] == 1
    a.release("u1", info={"baseline": 0.9})  # idempotent re-release


def test_old_format_lease_without_epoch_still_reclaims(tmp_path):
    """Additive-format pin: a lease written by a pre-epoch build (no
    ``epoch`` field) reclaims normally and enters the sequence at 2."""
    a, b = _q(tmp_path, "a", ttl=0.15), _q(tmp_path, "b", ttl=0.15)
    a.claim("u1")
    path = a._lease_path("u1")
    rec = json.load(open(path))
    del rec["epoch"]
    with open(path, "w") as fh:  # the old on-disk format
        json.dump(rec, fh)
    _watch_out_ttl(b, "u1")
    assert b.claim("u1")
    lease = b.read_lease("u1")
    assert lease["attempt"] == 2 and lease["epoch"] == 2


def test_claim_race_exactly_one_winner(tmp_path):
    queues = [_q(tmp_path, f"h{i}") for i in range(8)]
    wins = []
    barrier = threading.Barrier(len(queues))

    def worker(q):
        barrier.wait(timeout=10)
        if q.claim("u1"):
            wins.append(q.owner)

    ts = [threading.Thread(target=worker, args=(q,)) for q in queues]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=10)
    assert len(wins) == 1, wins


def test_steal_race_exactly_one_winner(tmp_path):
    dead = _q(tmp_path, "dead", ttl=0.15)
    dead.claim("u1")
    queues = [_q(tmp_path, f"h{i}", ttl=0.15) for i in range(8)]
    for q in queues:
        assert not q.claim("u1")  # everyone observes the dead lease
    time.sleep(0.25)              # ...and watches out the ttl
    wins = []
    barrier = threading.Barrier(len(queues))

    def worker(q):
        barrier.wait(timeout=10)
        if q.claim("u1"):
            wins.append(q.owner)

    ts = [threading.Thread(target=worker, args=(q,)) for q in queues]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=10)
    assert len(wins) == 1, wins
    lease = queues[0].read_lease("u1")
    assert lease["owner"] == wins[0] and lease["attempt"] == 2
    assert lease["epoch"] == 2


def test_host_beats_and_lost_census(tmp_path):
    a, b = _q(tmp_path, "a", ttl=0.2), _q(tmp_path, "b", ttl=0.2)
    a.beat_host()
    b.beat_host()
    assert set(a.known_hosts()) == {"a", "b"}
    assert a.lost_hosts() == []
    time.sleep(0.3)
    a.beat_host()           # a stays live
    assert a.lost_hosts() == ["b"]
    b.mark_host_done()      # done, not lost
    assert a.lost_hosts() == []


def test_lost_census_never_lists_the_caller(tmp_path):
    """A host computing the census is alive by definition — its own
    stale beat (e.g. a long compile gap) must not list it lost."""
    a = _q(tmp_path, "a", ttl=0.1)
    a.beat_host()
    time.sleep(0.2)
    assert a.lost_hosts() == []
    b = _q(tmp_path, "b", ttl=0.1)
    assert b.lost_hosts() == ["a"]  # another host MAY call it lost


def test_accounting_reports_global_reclaims_with_epochs(tmp_path):
    a, b = _q(tmp_path, "a", ttl=0.15), _q(tmp_path, "b", ttl=0.15)
    a.claim("u1")
    _watch_out_ttl(b, "u1")
    b.claim("u1")
    b.release("u1")
    b.claim("u2")
    b.release("u2")
    # a THIRD host (no session-local reclaim state) sees the same story
    c = _q(tmp_path, "c", ttl=0.15)
    acct = c.accounting()
    assert acct["degraded"] is True
    assert acct["num_reclaimed_units"] == 1
    rec = acct["reclaimed_units"][0]
    assert rec["unit"] == "u1" and rec["finished_by"] == "b" \
        and rec["reclaimed_from"] == "a"
    assert rec["epoch"] == 2  # the reclaim provenance rides the marker


def test_accounting_clean_run_not_degraded(tmp_path):
    a = _q(tmp_path, "a")
    a.claim("u1")
    a.release("u1")
    a.mark_host_done()
    acct = a.accounting()
    assert acct == {"degraded": False, "lost_hosts": [],
                    "reclaimed_units": [], "num_reclaimed_units": 0}


def test_stale_lease_fault_drops_renewals(tmp_path):
    os.environ["FAA_FAULT"] = "stale_lease@unit=u1"
    faultinject.reset()
    a = _q(tmp_path, "a", ttl=5.0)
    a.claim("u1")
    hb0 = a.read_lease("u1")["heartbeat"]
    time.sleep(0.02)
    a.renew("u1")  # dropped by the injected wedged-heartbeat
    assert a.read_lease("u1")["heartbeat"] == hb0
    a.claim("u2")
    time.sleep(0.02)
    a.renew("u2")  # other units beat normally
    assert a.read_lease("u2")["heartbeat"] > hb0


def test_unit_names_are_sanitized(tmp_path):
    a = _q(tmp_path, "a")
    assert a.claim("../../etc/passwd")
    leases = os.listdir(os.path.join(a.root, "leases"))
    assert all(os.sep not in name and ".." not in name.replace("..", "_")
               or True for name in leases)
    assert all("/" not in name for name in leases)
    # the lease file landed INSIDE the queue dir
    assert a.read_lease("../../etc/passwd")["owner"] == "a"
