#!/bin/bash
# Reference-scale policy search (VERDICT round 3, next-step 2).
#
# The reference's production search shape (search.py:211-263,
# data.py:119): 5 folds x 200 TPE samples, WRN-40-2, batch 128, on a
# 4,000-sample 32px 10-class dataset, guards on (CLI defaults).  No
# CIFAR pickle exists in this zero-egress environment, so the dataset
# is the reference-SHAPED synthetic stand-in
# `synthetic_shapes_pose4000` (4,000 train / 2,000 test, 32px, 10
# classes, pose-varying glyphs) — clearly labeled as such in the
# artifact; swap `DATASET=reduced_cifar10` when real data is present.
#
#   bash tools/run_search_refscale.sh full      # TPU: the real thing
#   bash tools/run_search_refscale.sh costcert  # CPU: cost certification
#
# `full` certifies the <1 TPU-hour north star end to end (phases 1-3).
# `costcert` runs on the CPU host where full production depth is
# computationally out of reach (WRN-40-2 phase 1 alone is ~15 h/fold at
# CPU throughput): it keeps every SHAPE production-exact (model, batch,
# fold sizes, TTA draw count) but truncates phase-1 depth and the trial
# budget (NUM_SEARCH/fold), measures per-trial and per-epoch unit
# costs, and asserts the zero-recompile property across folds — the
# extrapolation basis recorded in docs/BENCHMARKS.md.
set -euo pipefail
cd "$(dirname "$0")/.."

MODE="${1:-full}"
DATASET="${DATASET:-synthetic_shapes_pose4000}"

case "$MODE" in
full)
    SAVE="${SAVE:-search_refscale}"
    python -m fast_autoaugment_tpu.launch.search_cli \
        -c confs/wresnet40x2_cifar.yaml \
        --dataroot ./data \
        --save-dir "$SAVE" \
        --seed 1 \
        "dataset=$DATASET" \
        2>&1 | tee "$SAVE.log"
    ;;
fold0)
    # Round-5 middle rung between costcert and full (VERDICT r4,
    # next-step 2): ONE fold at production shape with a non-chance
    # oracle and a real trial block, on the CPU host.  Full reference
    # depth (200 epochs + 200 trials) is ~18 h at measured CPU unit
    # costs — beyond a round — so depth is env-tunable and every unit
    # this run measures is full-shape and steady-state:
    #   - phase 1: FOLD0_EPOCHS epochs of WRN-40-2 b128 on the 2,400-
    #     sample fold (per-epoch cost incl. compile amortization);
    #   - phase 2: FOLD0_TRIALS TPE trials against that oracle (per-
    #     trial cost at a non-degenerate reward signal);
    #   - audit: actually SCORES the selected sub-policies (costcert's
    #     chance oracles forced an audit skip; the oracle here clears
    #     the 2x-chance audit floor).
    # The quality gate stays off as in costcert: at partial depth the
    # auto floor would retrain-then-exclude by construction.  Gate
    # behavior at full depth is certified by search_e2e_r4_defaults/.
    SAVE="${SAVE:-search_refscale_fold0}"
    FOLD0_EPOCHS="${FOLD0_EPOCHS:-30}"
    FOLD0_TRIALS="${FOLD0_TRIALS:-25}"
    env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
        python -m fast_autoaugment_tpu.launch.search_cli \
        -c confs/wresnet40x2_cifar.yaml \
        --dataroot ./data \
        --save-dir "$SAVE" \
        --seed 1 \
        --num-search "$FOLD0_TRIALS" \
        --phase1-epochs "$FOLD0_EPOCHS" \
        --fold-quality-floor off \
        --folds 0 \
        --until 2 \
        "dataset=$DATASET" \
        2>&1 | tee -a "$SAVE.log"
    ;;
costcert)
    SAVE="${SAVE:-search_refscale_costcert}"
    NUM_SEARCH="${NUM_SEARCH:-3}"
    # clean CPU env: the dead-tunnel PJRT plugin hangs/aborts any
    # interpreter that keeps PALLAS_AXON_POOL_IPS (tests/conftest.py).
    # The fold-quality gate is OFF here by necessity: a 2-epoch
    # WRN-40-2 oracle sits at ~0.13 accuracy, so the auto gate would
    # spend 3x phase-1 retraining and then exclude every fold — phase 2
    # (the unit-cost measurement this mode exists for) would never run.
    # The gate itself is validated at full depth by the committed
    # defaults-run (search_e2e_r4_defaults/); `full` mode keeps it on.
    env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
        python -m fast_autoaugment_tpu.launch.search_cli \
        -c confs/wresnet40x2_cifar.yaml \
        --dataroot ./data \
        --save-dir "$SAVE" \
        --seed 1 \
        --num-search "$NUM_SEARCH" \
        --num-top 1 \
        --phase1-epochs 2 \
        --fold-quality-floor off \
        --until 2 \
        "dataset=$DATASET" \
        2>&1 | tee "$SAVE.log"
    ;;
*)
    echo "usage: $0 [full|costcert]" >&2
    exit 2
    ;;
esac

# stage the committable summaries (bulk checkpoints stay gitignored)
git add -f "$SAVE/search_result.json" "$SAVE.log" 2>/dev/null || true
git add -f "$SAVE/final_policy.json" "$SAVE/audit.json" 2>/dev/null || true
echo "[refscale] summary artifacts staged"
