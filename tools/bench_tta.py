"""TTA/eval-shape throughput sample (VERDICT r4, next-step 1 + weak 3).

The <1 TPU-hour search-cost certification converts CPU-measured unit
costs to TPU with a ratio taken from the TRAIN-step benchmark
(``bench.py``); the search's actual inner loop is the compiled TTA
step (``search/tta.py``), whose arithmetic intensity differs (forward
only, num_policy draws per image, no optimizer).  This tool measures
that step directly at production shape — WRN-40-2, batch 128, 5 draws,
the ``confs/wresnet40x2_cifar.yaml`` search shape — so the CPU->TPU
conversion for trial cost rests on a measured TTA-shape rate, not the
train-shape proxy.  Reference anchor: ``search.py:112-125`` (the
TTA reward evaluation this step replaces).

Run on either backend; the JSON records which one actually measured:

    python tools/bench_tta.py --out docs/tta_bench_tpu.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="wresnet40_2")
    p.add_argument("--dataset", default="cifar10")
    p.add_argument("--batch", type=int, default=128)
    p.add_argument("--image", type=int, default=32)
    p.add_argument("--num-policy", type=int, default=5)
    p.add_argument("--num-op", type=int, default=2)
    p.add_argument("--calls", type=int, default=20)
    p.add_argument("--out", default=None)
    args = p.parse_args(argv)

    from bench import arm_compile_cache_from_env

    arm_compile_cache_from_env()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from fast_autoaugment_tpu.models import get_model, num_class
    from fast_autoaugment_tpu.ops.optim import build_optimizer
    from fast_autoaugment_tpu.search.tta import make_tta_step
    from fast_autoaugment_tpu.train.steps import create_train_state

    dev = jax.devices()[0]
    platform = dev.platform
    num_classes = num_class(args.dataset)
    model = get_model({"type": args.model, "dataset": args.dataset},
                      num_classes)
    tta_step = make_tta_step(model, num_policy=args.num_policy,
                             cutout_length=16)

    rng = np.random.RandomState(0)
    images = jnp.asarray(
        rng.rand(args.batch, args.image, args.image, 3).astype(np.float32))
    labels = jnp.asarray(rng.randint(0, num_classes, size=args.batch))
    mask = jnp.ones((args.batch,), jnp.float32)
    sample = jnp.zeros((2, args.image, args.image, 3), jnp.float32)
    optimizer = build_optimizer(
        {"type": "sgd", "lr": 0.1, "momentum": 0.9}, lambda s: 0.0)
    state = create_train_state(model, optimizer, jax.random.PRNGKey(0), sample,
                               use_ema=False)

    def policy_t(i: int):
        r = np.random.RandomState(100 + i)
        t = np.stack([
            np.stack([r.randint(0, 15, size=args.num_op).astype(np.float32),
                      r.rand(args.num_op).astype(np.float32),
                      r.rand(args.num_op).astype(np.float32)], axis=-1)
            for _ in range(args.num_policy)
        ])
        return jnp.asarray(t)

    t0 = time.perf_counter()
    out = tta_step(state.params, state.batch_stats, images, labels, mask,
                   policy_t(0), jax.random.PRNGKey(0))
    jax.block_until_ready(out)
    compile_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    for i in range(args.calls):
        out = tta_step(state.params, state.batch_stats, images, labels, mask,
                       policy_t(i + 1), jax.random.fold_in(
                           jax.random.PRNGKey(1), i))
    jax.block_until_ready(out)
    steady = time.perf_counter() - t0

    ms_per_call = steady / args.calls * 1e3
    # each call forwards batch x num_policy augmented images
    imgs_per_sec = args.batch * args.num_policy * args.calls / steady
    from bench import telemetry_stamp

    summary = {
        "backend": platform,
        "device_kind": getattr(dev, "device_kind", platform),
        "model": args.model,
        "batch": args.batch,
        "image": args.image,
        "num_policy": args.num_policy,
        "compile_s": round(compile_s, 2),
        "tta_ms_per_call": round(ms_per_call, 3),
        "tta_images_per_sec": round(imgs_per_sec, 1),
        "unix_time": time.time(),
    }
    # unified provenance block (schema_version + contention + shadow
    # watchdog + compile cache + telemetry counters) — one helper
    # across every bench tool (bench.telemetry_stamp)
    summary.update(telemetry_stamp([ms_per_call / 1e3], label="tta"))
    line = json.dumps(summary)
    print(line)
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        tmp = args.out + ".tmp"
        with open(tmp, "w") as fh:
            fh.write(line + "\n")
        os.replace(tmp, args.out)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
