"""Dispatch-hazard rules D1–D3 (JAX-specific, train/search/serve hot
paths).  Every rule pins a pathology this repo has MEASURED on this
host (docs/BENCHMARKS.md "Step dispatch & device cache"):

D1  **host-device sync inside a dispatch loop**: ``.item()`` anywhere
    in a loop body, or ``float()``/``int()``/``np.asarray()``/
    ``np.array()``/``jax.device_get()`` applied to a value produced by
    a jitted entry point INSIDE the same loop — each conversion blocks
    the dispatch queue on the device round-trip.  The fixed idiom is
    the PR-4 one: accumulate per-dispatch device values and convert
    once at the epoch boundary.

D2  **compile seam inside a loop body**: a direct ``jax.jit``/
    ``seam_jit``/``instrument_jitted``/``aot_compile`` call lexically
    inside a ``for``/``while`` builds a NEW jitted callable (and its
    first-call compile) per iteration — the 23–55 s compile tax the
    persistent cache exists to kill, re-paid every lap.  Hoist the
    seam call above the loop.

D3  **mixed mesh-commitment into a jitted entry point** (the measured
    17x dispatch-overhead pathology): a loop-carried argument (fed
    back from the jitted call's own result) that is never
    ``jax.device_put``/``place_*``-committed, dispatched alongside a
    committed sibling argument, knocks every call off the C++
    fast path.  Commit the carried state to the mesh before the loop.

D4  **per-request copy on a serving hot path** (serve/ scope only):
    ``np.load``/``np.savez``/``.tobytes()``/``np.array`` (which copies
    unless ``copy=False``) inside a request-handling function — a
    ``do_*``/``_do_*`` method or anything on a ``*Handler*`` class.
    Each is a full-tensor copy (or zlib codec) paid per request; the
    zero-copy wire format (serve/wire.py: ``np.frombuffer`` views in,
    pooled-arena encode out) exists to remove exactly these.  The
    retained npz fallback lane carries ``# robust: allow``.
"""

from __future__ import annotations

import ast

from .engine import Finding, FileContext, Rule

#: the compile-seam entry points whose call RESULT is a jitted callable
_JIT_FACTORIES = {"seam_jit", "instrument_jitted", "aot_compile",
                  "_jit_with_trace_counter"}

#: committing calls: the result lives on the mesh
_COMMIT_CALLS = {"device_put"}
_COMMIT_PREFIXES = ("place_", "shard_")

_CONVERTERS = {"float", "int"}
_NP_CONVERTERS = {"asarray", "array"}


def _callee_name(call: ast.Call) -> str | None:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def _is_jit_factory_call(call: ast.Call) -> bool:
    name = _callee_name(call)
    if name in _JIT_FACTORIES:
        return True
    if name == "jit":  # jax.jit(...)
        f = call.func
        return isinstance(f, ast.Attribute) \
            and isinstance(f.value, ast.Name) and f.value.id == "jax"
    # make_*step* factories (make_train_step, make_tta_step, ...)
    return bool(name and name.startswith("make_") and "step" in name)


def _is_commit_call(call: ast.Call) -> bool:
    name = _callee_name(call)
    if name in _COMMIT_CALLS:
        return True
    return bool(name and name.startswith(_COMMIT_PREFIXES))


def _base_name(expr) -> str | None:
    """``metrics['loss']`` / ``state.params`` -> the base Name."""
    while isinstance(expr, (ast.Subscript, ast.Attribute)):
        expr = expr.value
    return expr.id if isinstance(expr, ast.Name) else None


def _target_names(node: ast.Assign) -> set[str]:
    out: set[str] = set()
    for tgt in node.targets:
        elts = tgt.elts if isinstance(tgt, (ast.Tuple, ast.List)) else [tgt]
        for e in elts:
            if isinstance(e, ast.Name):
                out.add(e.id)
    return out


def _function_units(ctx: FileContext):
    """Analysis units: each function def, plus the module top level
    (nodes not inside any function)."""
    units: dict[int, list[ast.AST]] = {}
    keys: dict[int, ast.AST | None] = {}
    for node in ctx.nodes:
        fn = ctx.enclosing_function(node)
        fid = id(fn) if fn is not None else 0
        units.setdefault(fid, []).append(node)
        keys.setdefault(fid, fn)
    return [(keys[fid], nodes) for fid, nodes in units.items()]


class _FunctionFacts:
    """Per-function name classification shared by D1 and D3: which
    names hold jitted callables, which hold mesh-committed values."""

    def __init__(self, nodes: list[ast.AST]):
        self.jitted: set[str] = set()
        self.committed: set[str] = set()
        self.assigns = [n for n in nodes if isinstance(n, ast.Assign)]
        changed = True
        while changed:
            changed = False
            for node in self.assigns:
                value = node.value
                names = _target_names(node)
                if isinstance(value, ast.Call):
                    if _is_jit_factory_call(value) \
                            and not names <= self.jitted:
                        self.jitted |= names
                        changed = True
                    if _is_commit_call(value) \
                            and not names <= self.committed:
                        self.committed |= names
                        changed = True
                # commitment propagates through slicing/attribute
                # access of a committed base (idx = index_dev[e])
                base = _base_name(value)
                if base in self.committed and not names <= self.committed:
                    self.committed |= names
                    changed = True


class HostSyncInDispatchLoop(Rule):
    id = "D1"
    severity = "warning"
    pass_name = "dispatch"
    scope_key = "dispatch"

    def run(self, ctx: FileContext) -> list[Finding]:
        out: list[Finding] = []
        for fn, nodes in _function_units(ctx):
            facts = _FunctionFacts(nodes)
            # names produced by a jitted call, per producing loop
            produced_in_loop: dict[int, set[str]] = {}
            for node in facts.assigns:
                if isinstance(node.value, ast.Call):
                    callee = _base_name(node.value.func) \
                        if not isinstance(node.value.func, ast.Name) \
                        else node.value.func.id
                    if callee in facts.jitted:
                        loop = ctx.enclosing_loop(node)
                        if loop is not None:
                            produced_in_loop.setdefault(
                                id(loop), set()).update(_target_names(node))
            for call in (n for n in nodes if isinstance(n, ast.Call)):
                loop = ctx.enclosing_loop(call)
                if loop is None:
                    continue
                f = call.func
                if isinstance(f, ast.Attribute) and f.attr == "item":
                    out.append(self.finding(
                        ctx, call.lineno,
                        ".item() inside a dispatch loop — a per-"
                        "iteration host-device sync that stalls the "
                        "dispatch queue; accumulate on device and "
                        "convert once at the loop boundary"))
                    continue
                # conversions of values a jitted call produced in the
                # same loop — the per-dispatch readback shape
                device_names = set()
                cur = loop
                while cur is not None:
                    device_names |= produced_in_loop.get(id(cur), set())
                    cur = ctx.enclosing_loop(cur)
                arg_base = _base_name(call.args[0]) if call.args else None
                if arg_base is None or arg_base not in device_names:
                    continue
                conv = None
                if isinstance(f, ast.Name) and f.id in _CONVERTERS:
                    conv = f.id
                elif isinstance(f, ast.Attribute) \
                        and isinstance(f.value, ast.Name):
                    if f.value.id == "np" and f.attr in _NP_CONVERTERS:
                        conv = f"np.{f.attr}"
                    elif f.value.id == "jax" and f.attr == "device_get":
                        conv = "jax.device_get"
                if conv:
                    out.append(self.finding(
                        ctx, call.lineno,
                        f"{conv}() on '{arg_base}' (a jitted-call "
                        "result) inside the dispatch loop that produced "
                        "it — a per-dispatch host-device sync; sum on "
                        "device or convert once at the epoch boundary "
                        "(the PR-4 fix)"))
        return out


class JitInLoop(Rule):
    id = "D2"
    severity = "warning"
    pass_name = "dispatch"
    scope_key = "dispatch"

    def run(self, ctx: FileContext) -> list[Finding]:
        out: list[Finding] = []
        for call in ctx.of(ast.Call):
            if not _is_jit_factory_call(call):
                continue
            name = _callee_name(call)
            if name and name.startswith("make_"):
                continue  # step factories are cheap closures; the jit
                #           happens inside them, at their (linted) site
            if ctx.enclosing_loop(call) is not None:
                out.append(self.finding(
                    ctx, call.lineno,
                    f"compile seam call ({name}) inside a loop body — "
                    "builds a fresh jitted callable (and pays its "
                    "first-call compile) every iteration; hoist it "
                    "above the loop"))
        return out


class MixedCommitDispatch(Rule):
    id = "D3"
    severity = "warning"
    pass_name = "dispatch"
    scope_key = "dispatch"

    def run(self, ctx: FileContext) -> list[Finding]:
        out: list[Finding] = []
        for fn, nodes in _function_units(ctx):
            facts = _FunctionFacts(nodes)
            if not facts.jitted:
                continue
            for node in facts.assigns:
                call = node.value
                if not isinstance(call, ast.Call):
                    continue
                callee = call.func.id if isinstance(call.func, ast.Name) \
                    else _base_name(call.func)
                if callee not in facts.jitted:
                    continue
                if ctx.enclosing_loop(node) is None:
                    continue
                arg_names = {a.id for a in call.args
                             if isinstance(a, ast.Name)}
                carried = arg_names & _target_names(node)
                committed_args = arg_names & facts.committed
                uncommitted_carried = carried - facts.committed
                if committed_args and uncommitted_carried:
                    missing = ", ".join(sorted(uncommitted_carried))
                    out.append(self.finding(
                        ctx, node.lineno,
                        f"jitted call mixes mesh-committed arguments "
                        f"({', '.join(sorted(committed_args))}) with the "
                        f"uncommitted loop-carried state '{missing}' — "
                        "the measured 17x dispatch-overhead pathology "
                        "(docs/BENCHMARKS.md): jax.device_put the "
                        "carried state onto the mesh before the loop"))
        return out


#: numpy calls that are a per-request full-copy (or codec) by nature
_D4_NP_CALLS = {"load", "savez", "savez_compressed"}


class PerRequestCopy(Rule):
    id = "D4"
    severity = "warning"
    pass_name = "dispatch"
    scope_key = "serve"

    @staticmethod
    def _is_request_handler(ctx: FileContext, fn) -> bool:
        """Request-handling unit: a ``do_*``/``_do_*`` function, or any
        method of a ``*Handler*`` class (the http.server idiom — helper
        methods like ``_parse_images`` are the same hot path)."""
        name = getattr(fn, "name", "")
        if name.startswith(("do_", "_do_")):
            return True
        cls = ctx.enclosing(fn, (ast.ClassDef,))
        return cls is not None and "Handler" in cls.name

    @staticmethod
    def _copy_pattern(call: ast.Call) -> str | None:
        f = call.func
        if not isinstance(f, ast.Attribute):
            return None
        if f.attr == "tobytes":
            return ".tobytes()"
        if isinstance(f.value, ast.Name) and f.value.id == "np":
            if f.attr in _D4_NP_CALLS:
                return f"np.{f.attr}"
            if f.attr == "array":
                for kw in call.keywords:
                    if kw.arg == "copy" \
                            and isinstance(kw.value, ast.Constant) \
                            and kw.value.value is False:
                        return None  # an explicit view, not a copy
                return "np.array"
        return None

    def run(self, ctx: FileContext) -> list[Finding]:
        out: list[Finding] = []
        for call in ctx.of(ast.Call):
            fn = ctx.enclosing_function(call)
            if fn is None or not self._is_request_handler(ctx, fn):
                continue
            pat = self._copy_pattern(call)
            if pat is None:
                continue
            out.append(self.finding(
                ctx, call.lineno,
                f"{pat} inside request handler '{fn.name}' — a full "
                "per-request tensor copy (or codec) on the serving hot "
                "path; use the zero-copy wire format (serve/wire.py: "
                "np.frombuffer views in, pooled-arena encode out) or "
                "mark the legacy fallback lane `robust: allow`"))
        return out


def RULES() -> list[Rule]:
    return [HostSyncInDispatchLoop(), JitInLoop(), MixedCommitDispatch(),
            PerRequestCopy()]
