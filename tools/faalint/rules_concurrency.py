"""Concurrency rules C1–C3: the lock/thread/file-race bug classes this
repo has shipped and then fixed by hand (see docs/STATIC_ANALYSIS.md
for the incident each rule pins).

C1  **lock-order inversion**: a per-module lock acquisition graph is
    built from lexically nested ``with <lock>:`` blocks (receivers
    bound from Lock/RLock/Condition constructors, or named like
    locks); any cycle between two or more distinct locks is a
    deadlock-prone ordering and every edge on the cycle is flagged.

C2  **thread-shared unguarded writes** (the PR-9 watchdog EMA race
    shape): within a class that starts a ``Thread(target=...)``, an
    attribute written both from the thread body (including the methods
    it transitively calls and nested ``def`` targets) and from another
    method, where a write on either side is not under a ``with
    <lock>:``, is a data race.  ``__init__`` writes are
    happens-before thread start and excluded.

C3  **remove-then-recreate** (the PR-6 lease reclaim race): inside one
    function, ``os.remove``/``os.unlink`` of a path followed by a
    recreation of the SAME path expression (``write_json_atomic``,
    ``save_checkpoint``, write-mode ``open``, ``os.rename``/
    ``os.replace`` destination) leaves an absence window a racing
    claimer can land in.  ``os.link`` recreation is exempt — that IS
    the atomic test-and-set idiom the fix used.
"""

from __future__ import annotations

import ast

from .engine import Finding, FileContext, Rule, _ctor_name, _recv_key

_WRITE_TARGET_TYPES = (ast.Assign, ast.AugAssign, ast.AnnAssign)


def _self_attr_of_store(target) -> str | None:
    """``self.x = ...`` / ``self.x[k] = ...`` -> ``x`` (the shared
    attribute the store mutates), else None."""
    if isinstance(target, ast.Subscript):
        target = target.value
    if isinstance(target, ast.Attribute) \
            and isinstance(target.value, ast.Name) \
            and target.value.id == "self":
        return target.attr
    return None


def _stores_in(fn: ast.AST):
    """(attr, node) for every self-attribute store lexically inside
    `fn` (the caller re-attributes stores that sit inside a nested
    Thread-target def)."""
    for node in ast.walk(fn):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        for tgt in targets:
            attr = _self_attr_of_store(tgt)
            if attr is not None:
                yield attr, node


class LockOrderInversion(Rule):
    id = "C1"
    pass_name = "concurrency"
    scope_key = "concurrency"

    def _lock_key(self, ctx: FileContext, expr, node) -> str | None:
        if not ctx.is_lockish(expr):
            return None
        key = _recv_key(expr)
        if key is None:
            return None
        if key.startswith("self."):
            cls = ctx.enclosing_class(node)
            return f"{cls.name if cls else '?'}.{key}"
        return key

    def run(self, ctx: FileContext) -> list[Finding]:
        # edges: outer-lock -> inner-lock, with the observation site
        edges: dict[tuple[str, str], int] = {}
        for w in ctx.of(ast.With, ast.AsyncWith):
            inner_keys = [self._lock_key(ctx, item.context_expr, w)
                          for item in w.items]
            inner_keys = [k for k in inner_keys if k]
            if not inner_keys:
                continue
            outer_keys: list[str] = []
            # multi-item `with a, b:` — earlier items are outer
            for i, k in enumerate(inner_keys[:-1]):
                edges.setdefault((k, inner_keys[i + 1]), w.lineno)
            for anc in ctx.ancestors(w):
                if isinstance(anc, (ast.With, ast.AsyncWith)):
                    outer_keys.extend(
                        k for k in (self._lock_key(ctx, it.context_expr, anc)
                                    for it in anc.items) if k)
                if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    break  # ordering is a per-call-stack property
            for outer in outer_keys:
                for inner in inner_keys:
                    if outer != inner:
                        edges.setdefault((outer, inner), w.lineno)
        if not edges:
            return []
        # transitive closure; an edge is part of a cycle iff its head
        # reaches its tail
        reach: dict[str, set[str]] = {}
        for a, b in edges:
            reach.setdefault(a, set()).add(b)
        changed = True
        while changed:
            changed = False
            for a in list(reach):
                for b in list(reach[a]):
                    extra = reach.get(b, set()) - reach[a]
                    if extra:
                        reach[a] |= extra
                        changed = True
        out = []
        for (a, b), line in sorted(edges.items(), key=lambda kv: kv[1]):
            if a in reach.get(b, set()):
                out.append(self.finding(
                    ctx, line,
                    f"lock-order inversion: '{b}' is acquired under "
                    f"'{a}' here, but elsewhere '{a}' is acquired "
                    f"under '{b}' — a deadlock-prone cycle; pick one "
                    "global order"))
        return out


class ThreadSharedUnguardedWrite(Rule):
    id = "C2"
    pass_name = "concurrency"
    scope_key = "concurrency"

    def _thread_targets(self, ctx: FileContext, cls: ast.ClassDef,
                        methods: dict) -> list[ast.AST]:
        """The function bodies a ``Thread(target=...)`` created inside
        `cls` will run: bound methods (``target=self._run``) and
        nested ``def`` targets (``target=_worker``)."""
        bodies: list[ast.AST] = []
        for call in ast.walk(cls):
            if not (isinstance(call, ast.Call)
                    and _ctor_name(call) in ("Thread", "Timer")):
                continue
            for kw in call.keywords:
                if kw.arg != "target":
                    continue
                t = kw.value
                if isinstance(t, ast.Attribute) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == "self" and t.attr in methods:
                    bodies.append(methods[t.attr])
                elif isinstance(t, ast.Name):
                    fn = ctx.enclosing_function(call)
                    if fn is not None:
                        for node in ast.walk(fn):
                            if isinstance(node, (ast.FunctionDef,
                                                 ast.AsyncFunctionDef)) \
                                    and node.name == t.id:
                                bodies.append(node)
        return bodies

    def _closure(self, bodies: list, methods: dict) -> set[str]:
        """Method names transitively reachable from the thread bodies
        via ``self.m(...)`` calls — they run on the worker thread."""
        seen: set[str] = {b.name for b in bodies if hasattr(b, "name")}
        frontier = list(bodies)
        while frontier:
            fn = frontier.pop()
            for node in ast.walk(fn):
                if isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and isinstance(node.func.value, ast.Name) \
                        and node.func.value.id == "self" \
                        and node.func.attr in methods \
                        and node.func.attr not in seen:
                    seen.add(node.func.attr)
                    frontier.append(methods[node.func.attr])
        return seen

    def run(self, ctx: FileContext) -> list[Finding]:
        out: list[Finding] = []
        for cls in ctx.of(ast.ClassDef):
            methods = {n.name: n for n in cls.body
                       if isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))}
            bodies = self._thread_targets(ctx, cls, methods)
            if not bodies:
                continue
            thread_methods = self._closure(bodies, methods)
            nested_bodies = [b for b in bodies
                             if getattr(b, "name", None) not in methods]

            def in_nested_body(node) -> bool:
                return any(anc in nested_bodies
                           for anc in ctx.ancestors(node))

            thread_writes: dict[str, list] = {}
            other_writes: dict[str, list] = {}
            for name, fn in methods.items():
                if name == "__init__":
                    continue  # happens-before thread start
                bucket = thread_writes if name in thread_methods \
                    else other_writes
                for attr, node in _stores_in(fn):
                    # stores inside a nested Thread-target def belong
                    # to the thread body, not the enclosing method
                    if in_nested_body(node):
                        thread_writes.setdefault(attr, []).append(node)
                    else:
                        bucket.setdefault(attr, []).append(node)
            shared = set(thread_writes) & set(other_writes)
            seen_lines: set[int] = set()
            for attr in sorted(shared):
                for node in thread_writes[attr] + other_writes[attr]:
                    if ctx.lock_guarded(node) or node.lineno in seen_lines:
                        continue
                    seen_lines.add(node.lineno)
                    out.append(self.finding(
                        ctx, node.lineno,
                        f"attribute 'self.{attr}' is written both from "
                        f"a Thread(target=...) body and from another "
                        f"method of {cls.name}, and this write holds no "
                        "lock — the watchdog-EMA race class (PR 9): "
                        "guard every access with one lock"))
        return out


_REMOVERS = {"remove", "unlink"}


def _path_key(expr) -> str:
    return ast.dump(expr)


class RemoveThenRecreate(Rule):
    id = "C3"
    pass_name = "concurrency"
    scope_key = "artifact"

    def run(self, ctx: FileContext) -> list[Finding]:
        from .rules_robustness import _write_mode

        # bucket removals and recreations by enclosing function
        removals: dict[int, list[tuple[str, ast.Call]]] = {}
        recreates: dict[int, list[tuple[str, int]]] = {}
        for call in ctx.of(ast.Call):
            fn = ctx.enclosing_function(call)
            fid = id(fn) if fn is not None else 0
            f = call.func
            if isinstance(f, ast.Attribute) and f.attr in _REMOVERS \
                    and isinstance(f.value, ast.Name) \
                    and f.value.id == "os" and call.args:
                removals.setdefault(fid, []).append(
                    (_path_key(call.args[0]), call))
                continue
            target = None
            if isinstance(f, ast.Name):
                if f.id in ("write_json_atomic", "_write_json_atomic",
                            "save_checkpoint") and call.args:
                    target = call.args[0]
                elif f.id == "open" and call.args and _write_mode(call):
                    target = call.args[0]
            elif isinstance(f, ast.Attribute) \
                    and isinstance(f.value, ast.Name):
                if f.value.id == "os" and f.attr in ("rename", "replace") \
                        and len(call.args) >= 2:
                    target = call.args[1]
                # os.link is the atomic test-and-set claim idiom: a
                # remove-then-link race has exactly one winner, so it
                # is NOT the absence-window bug — exempt by design
            if target is not None:
                recreates.setdefault(fid, []).append(
                    (_path_key(target), call.lineno))
        out: list[Finding] = []
        for fid, removes in removals.items():
            creates = recreates.get(fid, [])
            for key, call in removes:
                later = [ln for k, ln in creates
                         if k == key and ln > call.lineno]
                if later:
                    out.append(self.finding(
                        ctx, call.lineno,
                        "remove-then-recreate on the same path (recreated "
                        f"at line {min(later)}) — the absence window lets "
                        "a racing claimer land and drop provenance (the "
                        "PR-6 lease race): replace in place "
                        "(write_json_atomic / os.replace) or claim via "
                        "atomic os.link"))
        return out


def RULES() -> list[Rule]:
    return [LockOrderInversion(), ThreadSharedUnguardedWrite(),
            RemoveThenRecreate()]
