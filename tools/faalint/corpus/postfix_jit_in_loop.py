"""Post-fix shape: the seam call is hoisted above the loop — one
jitted callable, one compile, N dispatches.  Must produce ZERO
findings."""

from fast_autoaugment_tpu.core.compilecache import seam_jit


def evaluate(body, state, batches):
    step = seam_jit(body, label="eval_step")
    outs = []
    for batch in batches:
        outs.append(step(state, batch))
    return outs
