"""Post-fix shape of the watchdog EMA race: every access to the shared
label state holds the one RLock (the shipped PR-9 fix).  Must produce
ZERO findings."""

import threading
import time


class DispatchWatchdog:
    def __init__(self, alpha=0.3):
        self.alpha = alpha
        self.fires = 0
        self._ema = {}
        self._calls = {}
        self._lock = threading.RLock()

    def observe(self, label, wall_sec):
        with self._lock:
            self._calls[label] = self._calls.get(label, 0) + 1
            prev = self._ema.get(label)
            if prev is None:
                self._ema[label] = float(wall_sec)
            else:
                self._ema[label] = (self.alpha * float(wall_sec)
                                    + (1.0 - self.alpha) * prev)

    def run(self, label, fn):
        def _monitor():
            t0 = time.monotonic()
            fn()
            with self._lock:
                self._ema[label] = time.monotonic() - t0

        t = threading.Thread(target=_monitor, daemon=True)
        t.start()
        t.join(timeout=60.0)
