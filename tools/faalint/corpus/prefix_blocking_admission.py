"""Pre-fix regression snippet: the PR-8 blocking-admission bug.

``PolicyServer.submit`` put requests on the bounded queue with a
BLOCKING put — a full queue parked every HTTP handler thread for the
full timeout instead of failing fast, and the serving plane collapsed
under overload (~70x goodput loss at 4x offered load).  Fixed by
non-blocking admission + typed ``ServerOverloadedError`` → 429 with
Retry-After (PR 8).

Intended pass: robustness/blocking (R6).
"""

import queue


class PolicyServer:
    def __init__(self, depth):
        self._q = queue.Queue(maxsize=depth)

    def submit(self, request):
        # PRE-FIX: blocking admission — a full queue parks the handler
        # thread instead of shedding with a typed overload error
        self._q.put(request)
        return request

    def _take(self):
        return self._q.get(timeout=0.25)
