"""Pre-fix regression snippet: per-dispatch host-device sync.

Converting a jitted step's metrics to Python floats INSIDE the
dispatch loop blocks the queue on a device round-trip every step —
the host-loop pitfall PR 4 measured and fixed with epoch-end host
summation.

Intended pass: dispatch (D1).
"""

from fast_autoaugment_tpu.core.compilecache import seam_jit


def train_epoch(body, state, batches):
    step = seam_jit(body, label="train_step")
    losses = []
    for batch in batches:
        state, metrics = step(state, batch)
        # PRE-FIX: a host-device sync per dispatch
        losses.append(float(metrics["loss"]))
    return state, losses
