"""Pre-fix regression snippet: unordered iteration feeding a persisted
artifact — readdir order and set order leak the filesystem / hash seed
into the payload.

Intended pass: determinism (T2).
"""

import os

from fast_autoaugment_tpu.search.driver import write_json_atomic


def collect_done_units(done_dir, out_path):
    units = []
    for name in os.listdir(done_dir):  # readdir order leaks in
        if name.endswith(".json"):
            units.append(name)
    seen = set(units)
    merged = [u for u in seen]  # set order leaks in
    write_json_atomic(out_path, {"units": merged})
