"""Post-fix shape: every unordered source is ``sorted()`` before it
reaches the artifact.  Must produce ZERO findings."""

import os

from fast_autoaugment_tpu.search.driver import write_json_atomic


def collect_done_units(done_dir, out_path):
    units = []
    for name in sorted(os.listdir(done_dir)):
        if name.endswith(".json"):
            units.append(name)
    seen = set(units)
    merged = [u for u in sorted(seen)]
    write_json_atomic(out_path, {"units": merged})
