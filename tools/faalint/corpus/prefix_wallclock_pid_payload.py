"""Pre-fix regression snippet: wall-clock and process identity flowing
into a persisted artifact.

The repo's acceptance drills diff artifacts byte-for-byte across
hosts, resumes and reclaims (PR 6/9) — a ``time.time()`` stamp or a
pid in the payload breaks every one of them.

Intended pass: determinism (T1 + T3).
"""

import os
import time

from fast_autoaugment_tpu.search.driver import write_json_atomic


def persist_result(path, results):
    payload = {
        "results": results,
        "finished_at": time.time(),  # wall clock into the artifact
        "writer_pid": os.getpid(),   # process identity into the artifact
    }
    write_json_atomic(path, payload)
