"""Post-fix shape of admission control: NON-BLOCKING put, typed
overload error for the 429 path — the shipped PR-8 idiom.  Must
produce ZERO findings."""

import queue


class ServerOverloadedError(RuntimeError):
    def __init__(self, retry_after_s=0.05):
        super().__init__("server overloaded")
        self.retry_after_s = retry_after_s


class PolicyServer:
    def __init__(self, depth):
        self._q = queue.Queue(maxsize=depth)

    def submit(self, request):
        try:
            self._q.put(request, block=False)  # fail-fast admission
        except queue.Full:
            raise ServerOverloadedError() from None
        return request

    def _take(self):
        return self._q.get(timeout=0.25)
