"""The faalint regression corpus: pre-fix snippets of the bugs this
repo shipped and then fixed by hand, each pinned to the pass that must
now catch it statically, plus the post-fix shape that must stay clean
(zero false positives).

``check_corpus()`` is the machine gate behind ``python -m tools.faalint
--selfcheck`` and the test suite: every prefix snippet is flagged by
EXACTLY the expected rules (and so by exactly one pass), every postfix
snippet produces zero findings.
"""

from __future__ import annotations

import os

from ..engine import check_source, default_rules

_HERE = os.path.dirname(os.path.abspath(__file__))

#: name -> (lint-as relpath, expected rule ids, intended pass).  The
#: relpath places the snippet in the scope the real bug lived in.
CASES = {
    # the three historical incidents named in docs/STATIC_ANALYSIS.md
    "watchdog_ema_race": (
        "fast_autoaugment_tpu/core/watchdog.py", {"C2"}, "concurrency"),
    "lease_remove_recreate": (
        "fast_autoaugment_tpu/launch/workqueue.py", {"C3"}, "concurrency"),
    "blocking_admission": (
        "fast_autoaugment_tpu/serve/policy_server.py", {"R6"},
        "robustness"),
    # the measured dispatch pathologies (PR 4 / docs/BENCHMARKS.md)
    "mixed_commit": (
        "fast_autoaugment_tpu/train/trainer.py", {"D3"}, "dispatch"),
    "host_sync_loop": (
        "fast_autoaugment_tpu/train/trainer.py", {"D1"}, "dispatch"),
    "jit_in_loop": (
        "fast_autoaugment_tpu/train/trainer.py", {"D2"}, "dispatch"),
    # the per-request copy tax the zero-copy data plane removed
    "npz_per_request": (
        "fast_autoaugment_tpu/serve/serve_cli.py", {"D4"}, "dispatch"),
    # the byte-identical-artifact contract
    "wallclock_pid_payload": (
        "fast_autoaugment_tpu/core/checkpoint.py", {"T1", "T3"},
        "determinism"),
    "unsorted_listdir": (
        "fast_autoaugment_tpu/core/checkpoint.py", {"T2"}, "determinism"),
}

#: the three pre-fix snippets of shipped-then-fixed incidents the
#: acceptance criteria name explicitly
HISTORICAL = ("watchdog_ema_race", "lease_remove_recreate",
              "blocking_admission")


def load(name: str, which: str = "prefix") -> str:
    with open(os.path.join(_HERE, f"{which}_{name}.py")) as fh:
        return fh.read()


def rule_pass_map() -> dict[str, str]:
    return {r.id: r.pass_name for r in default_rules()}


def check_case(name: str) -> list[str]:
    """Problems (empty = ok) for one corpus case: prefix flagged by
    exactly the expected rules of exactly the intended pass, postfix
    clean."""
    relpath, expected, intended_pass = CASES[name]
    passes = rule_pass_map()
    problems = []
    got = check_source(load(name, "prefix"), relpath)
    rules = {f.rule for f in got}
    if rules != expected:
        problems.append(
            f"{name}: prefix expected rules {sorted(expected)}, "
            f"got {sorted(rules)} ({[repr(f) for f in got]})")
    wrong_pass = {f.rule for f in got if passes.get(f.rule) != intended_pass}
    if wrong_pass:
        problems.append(
            f"{name}: prefix flagged by passes other than "
            f"{intended_pass}: {sorted(wrong_pass)}")
    post = check_source(load(name, "postfix"), relpath)
    if post:
        problems.append(
            f"{name}: postfix (fixed shape) is NOT clean: "
            f"{[repr(f) for f in post]}")
    return problems


def check_corpus() -> list[str]:
    problems: list[str] = []
    for name in sorted(CASES):
        problems.extend(check_case(name))
    return problems
