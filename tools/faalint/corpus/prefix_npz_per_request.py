"""Pre-fix shape of serve_cli's npz ingestion (PR 7–15): every request
paid a full npz decode copy in (zlib + tensor materialization) and an
npz encode copy out on the serving hot path — the per-request host
overhead the zero-copy wire format removed (serve/wire.py)."""
import io

import numpy as np


class Handler:
    def _do_augment(self, server):
        body = self.read_body()
        payload = np.load(io.BytesIO(body), allow_pickle=False)
        images = np.array(payload["images"])
        pending = server.submit(images)
        out = server.result(pending)
        buf = io.BytesIO()
        np.savez(buf, images=out.astype(np.uint8))
        self.send(200, buf.getvalue())
