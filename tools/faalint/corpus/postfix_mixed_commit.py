"""Post-fix shape of the mixed-commitment dispatch: the carried state
is committed to the mesh BEFORE the loop (the shipped PR-4 fix in
train/trainer.py).  Must produce ZERO findings."""

import jax

from fast_autoaugment_tpu.core.compilecache import seam_jit


def train_epochs(body, dataset, state, sharding, replicated, index, steps):
    step = seam_jit(body, label="train_step")
    cache = jax.device_put(dataset, sharding)
    # commit the carried state before the first dispatch: committed +
    # committed stays on the C++ fast path
    state = jax.device_put(state, replicated)
    for _ in range(steps):
        state, metrics = step(state, cache, index)
    return state
