"""Post-fix shape: artifacts stamp run INPUTS (seed, config, epoch,
FAA_HOST_ID/FAA_ATTEMPT identity) — all reproducible on resume; timing
evidence lives in the telemetry journal, not the artifact.  Must
produce ZERO findings."""

from fast_autoaugment_tpu.search.driver import write_json_atomic


def persist_result(path, results, seed, epoch, host_id):
    payload = {
        "results": results,
        "seed": int(seed),
        "epoch": int(epoch),
        "host": str(host_id),  # FAA_HOST_ID: stable across resume
    }
    write_json_atomic(path, payload)
