"""Post-fix shape: the raw-format request decodes as zero-copy
``np.frombuffer`` views (serve/wire.py) and the response assembles
into a pooled arena buffer with one fused clip-cast copy — nothing
per-request on either side of the dispatch."""
import numpy as np


class Handler:
    def _do_augment(self, server, wire, arena):
        body = self.read_body()
        images, keys = wire.decode_raw(body)
        pending = server.submit(images, keys)
        out = server.result(pending)
        np.clip(out, 0, 255, out=out)
        view, lease = wire.encode_raw_into(arena, out, as_dtype=np.uint8)
        try:
            self.send(200, view)
        finally:
            arena.checkin(lease)
