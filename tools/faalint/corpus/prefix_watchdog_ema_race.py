"""Pre-fix regression snippet: the PR-9 watchdog label-state data race.

``DispatchWatchdog`` EMA/call-count dicts were written both from the
monitor thread body and from the public ``observe()`` that every actor
thread calls — no lock anywhere.  One monitored dispatch per actor
concurrently lost observations and corrupted deadlines.  Fixed by
RLock-guarding all label state (PR 9 satellite b).

Intended pass: concurrency (C2).
"""

import threading
import time


class DispatchWatchdog:
    def __init__(self, alpha=0.3):
        self.alpha = alpha
        self.fires = 0
        self._ema = {}
        self._calls = {}

    def observe(self, label, wall_sec):
        # PUBLIC and UNLOCKED: actor threads call this concurrently
        # with the monitor thread's bookkeeping below
        self._calls[label] = self._calls.get(label, 0) + 1
        prev = self._ema.get(label)
        if prev is None:
            self._ema[label] = float(wall_sec)
        else:
            self._ema[label] = (self.alpha * float(wall_sec)
                                + (1.0 - self.alpha) * prev)

    def run(self, label, fn):
        def _monitor():
            t0 = time.monotonic()
            fn()
            # the thread body writes the same shared dict the public
            # method writes — the data race
            self._ema[label] = time.monotonic() - t0

        t = threading.Thread(target=_monitor, daemon=True)
        t.start()
        t.join(timeout=60.0)
