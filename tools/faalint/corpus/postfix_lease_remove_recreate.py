"""Post-fix shape of the lease reclaim: in-place replace (the lease
path never disappears) and atomic ``os.link`` test-and-set for fresh
claims — the shipped PR-6 idiom.  Must produce ZERO findings."""

import os

from fast_autoaugment_tpu.search.driver import write_json_atomic


def reclaim_stale_lease(lease_path, owner, stale):
    # in-place replace: write_json_atomic renames over the live lease,
    # so there is no absence window for a racing fresh claim
    write_json_atomic(lease_path, {
        "owner": owner,
        "attempt": int(stale.get("attempt", 1)) + 1,
        "reclaimed_from": stale.get("owner"),
    })
    return True


def claim_fresh(lease_path, tmp_path, owner):
    write_json_atomic(tmp_path, {"owner": owner, "attempt": 1})
    try:
        os.link(tmp_path, lease_path)  # atomic test-and-set: one winner
        return True
    except FileExistsError:
        return False
    finally:
        os.remove(tmp_path)
