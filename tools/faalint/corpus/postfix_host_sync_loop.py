"""Post-fix shape of the dispatch loop: device values accumulate
per-dispatch and convert ONCE at the epoch boundary (the shipped PR-4
``_sum_metric_dicts`` idiom).  Must produce ZERO findings."""

from fast_autoaugment_tpu.core.compilecache import seam_jit


def sum_metric_dicts(dicts):
    total = {}
    for d in dicts:
        for k, v in d.items():
            total[k] = total.get(k, 0.0) + v
    return total


def train_epoch(body, state, batches):
    step = seam_jit(body, label="train_step")
    per_dispatch = []
    for batch in batches:
        state, metrics = step(state, batch)
        per_dispatch.append(metrics)  # stays on device, no sync
    totals = sum_metric_dicts(per_dispatch)
    return state, float(totals["loss"])  # one conversion per epoch
