"""Pre-fix regression snippet: mixed mesh-commitment into a jitted
entry point — the measured 17x dispatch-overhead pathology (PR 4,
docs/BENCHMARKS.md "Step dispatch & device cache").

The device cache is mesh-committed but the loop-carried TrainState is
not: every dispatch re-resolves placement and falls off the C++ fast
path.  Fixed by ``jax.device_put``-committing the carried state before
the loop.

Intended pass: dispatch (D3).
"""

import jax

from fast_autoaugment_tpu.core.compilecache import seam_jit


def train_epochs(body, dataset, state, sharding, index, steps):
    step = seam_jit(body, label="train_step")
    cache = jax.device_put(dataset, sharding)  # mesh-committed
    for _ in range(steps):
        # PRE-FIX: `state` is never committed while `cache` is —
        # every dispatch pays the slow placement path
        state, metrics = step(state, cache, index)
    return state
