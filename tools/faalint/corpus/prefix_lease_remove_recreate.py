"""Pre-fix regression snippet: the PR-6 lease reclaim provenance race.

The stale-lease steal removed the lease file and then wrote a fresh
one.  In the absence window between the two, a racing host saw "no
lease", claimed fresh with attempt=1, and silently dropped the reclaim
provenance (a test caught it).  Fixed by replacing the lease IN PLACE
under a fence file taken via atomic ``os.link`` (PR 6).

Intended pass: concurrency (C3).
"""

import os

from fast_autoaugment_tpu.search.driver import write_json_atomic


def reclaim_stale_lease(lease_path, owner, stale):
    # PRE-FIX: drop the stale lease, then recreate it — the absence
    # window between remove and write lets a racing fresh claim land
    # with attempt=1
    os.remove(lease_path)
    write_json_atomic(lease_path, {
        "owner": owner,
        "attempt": int(stale.get("attempt", 1)) + 1,
        "reclaimed_from": stale.get("owner"),
    })
    return True
