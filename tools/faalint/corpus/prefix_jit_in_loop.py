"""Pre-fix regression snippet: compile seam inside a loop body.

Re-wrapping the body through the compile seam every iteration builds a
fresh jitted callable per lap — each first call pays the 23-55s
compile tax the persistent cache exists to kill.

Intended pass: dispatch (D2).
"""

from fast_autoaugment_tpu.core.compilecache import seam_jit


def evaluate(body, state, batches):
    outs = []
    for batch in batches:
        # PRE-FIX: a fresh jit (and a fresh compile) per iteration
        step = seam_jit(body, label="eval_step")
        outs.append(step(state, batch))
    return outs
