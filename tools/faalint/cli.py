"""faalint CLI: the `make lint` gate.

Exit 0 = clean at the --fail-on threshold (baselined findings and the
below-threshold tail are reported, not fatal); exit 1 = findings; exit
2 = configuration error (unparseable baseline).  Prints the measured
lint wall time — the single-parse engine must stay well under the ~10s
budget on this 1-core host so the tier-1 preamble never eats test wall.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from .engine import (REPO, default_baseline_path, default_rules, failing,
                     lint_tree, load_baseline)


def run_selfcheck(verbose: bool = True) -> list[str]:
    """Run the regression corpus (pre-fix snippets of the historical
    bugs): every prefix snippet must be flagged by exactly the intended
    pass, every postfix snippet must be clean.  Returns problems."""
    from .corpus import check_corpus

    problems = check_corpus()
    if verbose:
        for p in problems:
            print(f"faalint selfcheck: {p}", file=sys.stderr)
    return problems


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="faalint",
        description="multi-pass static analyzer (concurrency, dispatch "
                    "hazards, determinism, robustness)")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable findings + counts")
    parser.add_argument("--baseline", default=None,
                        help="reviewed baseline JSON (default: "
                             "tools/faalint/baseline.json)")
    parser.add_argument("--fail-on", default="warning",
                        choices=("error", "warning", "info", "never"),
                        help="minimum severity that fails the run "
                             "(default: warning)")
    parser.add_argument("--rules", default=None,
                        help="comma-separated rule ids to run "
                             "(default: all)")
    parser.add_argument("--root", default=REPO, help=argparse.SUPPRESS)
    parser.add_argument("--selfcheck", action="store_true",
                        help="verify the pre-fix regression corpus is "
                             "caught (and the post-fix shapes are not)")
    args = parser.parse_args(argv)

    if args.selfcheck:
        problems = run_selfcheck()
        if problems:
            print(f"faalint selfcheck: {len(problems)} problem(s)",
                  file=sys.stderr)
            return 1
        print("faalint selfcheck: corpus ok")
        return 0

    rule_ids = None
    if args.rules:
        rule_ids = [r.strip() for r in args.rules.split(",") if r.strip()]

    t0 = time.monotonic()
    baseline_path = args.baseline or default_baseline_path()
    try:
        load_baseline(baseline_path)  # fail fast on an unjustified entry
    except (ValueError, json.JSONDecodeError) as e:
        print(f"faalint: bad baseline {baseline_path}: {e}",
              file=sys.stderr)
        return 2
    findings = lint_tree(args.root, baseline_path=baseline_path,
                         rule_ids=rule_ids)
    wall = time.monotonic() - t0
    fatal = failing(findings, args.fail_on)
    n_rules = len(default_rules()) if rule_ids is None else len(rule_ids)

    if args.json:
        counts: dict[str, int] = {}
        for f in findings:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        print(json.dumps({
            "findings": [f.as_dict() for f in findings],
            "counts": counts,
            "fatal": len(fatal),
            "rules": n_rules,
            "wall_sec": round(wall, 3),
        }, indent=2, sort_keys=True))
        return 1 if fatal else 0

    for f in findings:
        tag = " [baselined]" if f.baselined else ""
        print(f"{f}{tag}")
    if fatal:
        print(f"faalint: {len(fatal)} finding(s) "
              f"({len(findings) - len(fatal)} baselined/below threshold) "
              f"in {wall:.2f}s", file=sys.stderr)
        return 1
    extra = f", {len(findings)} baselined/non-fatal" if findings else ""
    print(f"faalint: clean — {n_rules} rules, single parse per file"
          f"{extra}, {wall:.2f}s")
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
