"""faalint engine: single-parse, multi-pass static analysis.

The framework parses each file ONCE into a :class:`FileContext` — the
AST plus the shared indexes every pass consumes (parent links, nodes
bucketed by type, enclosing-function/loop/with maps, constructor-bound
receiver tables) — then runs every registered rule over that one
context.  The legacy ``tools/lint_robustness.py`` re-parsed and
re-walked the tree once per rule family; here the tree is walked once
and the passes share the indexes.

Three layers of verdict control, in order:

* ``# robust: allow`` on the offending line suppresses a finding at
  that line (put the one-line justification in the same comment).  A
  marker that suppresses NOTHING is itself a warning (rule ``S1``) so
  suppressions cannot rot silently.
* the reviewed baseline file (``tools/faalint/baseline.json``): each
  entry pins one known finding ``{path, rule, line, reason}`` and must
  carry a non-empty ``reason``.  Entries that no longer match any
  finding are flagged (rule ``S2``).
* severity: every rule declares ``error`` / ``warning`` / ``info``;
  the CLI fails at ``--fail-on`` (default ``warning``) and above.

Rule identifiers: ``R1``–``R9`` robustness/blocking (R1–R8 migrated
from the legacy lint, R9 the extended-scope blocking rule), ``C1``–
``C3`` concurrency, ``D1``–``D3`` dispatch hazards, ``T1``–``T3``
determinism, ``S1``/``S2`` suppression hygiene, ``R0`` syntax error.
See docs/STATIC_ANALYSIS.md for the catalog and the historical
incident each rule pins.
"""

from __future__ import annotations

import ast
import json
import os
from typing import Callable, Iterable

# repo root: tools/faalint/engine.py -> tools/faalint -> tools -> repo
REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
PACKAGE = "fast_autoaugment_tpu"

ALLOW_MARKER = "robust: allow"

SEVERITY_RANK = {"info": 0, "warning": 1, "error": 2}

# ----------------------------------------------------------------- scopes
# Directory scopes, one boolean per pass family, derived from the
# file's repo-relative path (or forced via overrides — the legacy
# ``check_source(..., *_scope=)`` shim and the rule-matrix tests).

ARTIFACT_DIRS = ("core", "search", "train", "launch")       # R3, C3
BLOCKING_DIRS = ("core", "launch", "search")                # R4
JIT_SEAM_DIRS = ("train", "search", "serve")                # R5
SERVE_BLOCKING_DIRS = ("serve",)                            # R6
SEARCH_BLOCKING_DIRS = ("search",)                          # R7
TIMING_SEAM_DIRS = ("train", "search", "serve")             # R8
# R9: the R6/R7 unbounded-blocking engine extended to the remaining
# thread code — supervision (core/, launch/), the prefetch pipeline
# (data/) and utility workers (utils/).  serve/ and search/ keep their
# own rule ids (R6/R7); join/get already policed by R4 in core/launch
# are not double-flagged.
EXT_BLOCKING_DIRS = ("core", "launch", "data", "utils")
# D1–D3: the train/search/serve hot paths whose dispatch loops must
# stay off the host-sync / recompile / mixed-commitment pathologies
# (docs/BENCHMARKS.md "Step dispatch & device cache").
DISPATCH_DIRS = ("train", "search", "serve")
# T1–T3: the artifact-writing layers (everything funneled through
# write_json_atomic / save_checkpoint).  launch/ is deliberately out:
# lease/heartbeat records are wall-clock + pid stamped BY DESIGN —
# staleness detection is their function, not a determinism bug.
DETERMINISM_DIRS = ("core", "search", "train")
# F1: the shared-directory layers whose file I/O must route through
# the core/fsfault.py fault seam (docs/RESILIENCE.md "Hostile shared
# filesystem") — the seam is core/, so it polices itself out of scope.
FSSEAM_DIRS = ("launch", "search", "control")

SCOPE_DIRS = {
    "artifact": ARTIFACT_DIRS,
    "blocking": BLOCKING_DIRS,
    "jit": JIT_SEAM_DIRS,
    "serve": SERVE_BLOCKING_DIRS,
    "search": SEARCH_BLOCKING_DIRS,
    "timing": TIMING_SEAM_DIRS,
    "ext_blocking": EXT_BLOCKING_DIRS,
    "dispatch": DISPATCH_DIRS,
    "determinism": DETERMINISM_DIRS,
    "fsseam": FSSEAM_DIRS,
    # C1/C2 run package-wide: threads and locks are legal anywhere, so
    # the analysis follows them anywhere
    "concurrency": None,
}


def _in_dirs(relpath: str, dirs: Iterable[str]) -> bool:
    norm = relpath.replace(os.sep, "/")
    return any(
        f"/{d}/" in f"/{norm}" or norm.startswith(f"{d}/")
        for d in (f"{PACKAGE}/{a}" for a in dirs))


def scopes_for(relpath: str, overrides: dict | None = None) -> dict:
    scopes = {}
    for key, dirs in SCOPE_DIRS.items():
        scopes[key] = True if dirs is None else _in_dirs(relpath, dirs)
    if overrides:
        for key, val in overrides.items():
            if val is not None:
                scopes[key] = bool(val)
    return scopes


# ---------------------------------------------------------------- finding
class Finding:
    """One diagnostic.  ``repr`` stays byte-compatible with the legacy
    lint (``path:line: RULE message``) so existing tooling and the
    rule-matrix tests keep parsing it."""

    def __init__(self, path: str, line: int, rule: str, msg: str,
                 severity: str = "error"):
        self.path, self.line, self.rule, self.msg = path, line, rule, msg
        self.severity = severity
        self.baselined = False
        self.baseline_reason: str | None = None

    def __repr__(self):
        return f"{self.path}:{self.line}: {self.rule} {self.msg}"

    def as_dict(self) -> dict:
        d = {"path": self.path, "line": self.line, "rule": self.rule,
             "severity": self.severity, "message": self.msg}
        if self.baselined:
            d["baselined"] = True
            d["baseline_reason"] = self.baseline_reason
        return d


# ----------------------------------------------------------- file context
_THREAD_CTORS = {"Thread", "Timer"}
_QUEUE_CTORS = {"Queue", "SimpleQueue", "LifoQueue", "PriorityQueue",
                "JoinableQueue"}
_WAIT_CTORS = {"Event", "Condition", "Barrier"}
_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore"}


def _recv_key(node) -> str | None:
    """A trackable receiver: ``name`` or ``obj.attr`` (one level)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
        return f"{node.value.id}.{node.attr}"
    return None


def _ctor_name(call: ast.Call) -> str | None:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


class FileContext:
    """One parse, one walk, shared indexes.

    ``tree`` is parsed exactly once; a single iterative walk records
    every node (``nodes``), buckets them by type (``by_type``) and
    links children to parents (``parent``).  Everything else the rules
    need — enclosing functions/classes/loops, with-statement ancestry,
    constructor-bound receiver tables — is derived from those indexes
    without touching the source again.
    """

    def __init__(self, src: str, relpath: str, scopes: dict):
        self.src = src
        self.relpath = relpath
        self.scopes = scopes
        self.lines = src.splitlines()
        self.allow_lines = {
            i + 1 for i, ln in enumerate(self.lines) if ALLOW_MARKER in ln}
        self.used_allow_lines: set[int] = set()
        self.syntax_error: SyntaxError | None = None
        self.nodes: list[ast.AST] = []
        self.by_type: dict[type, list] = {}
        self._parent: dict[int, ast.AST | None] = {}
        self._caches: dict[str, object] = {}
        try:
            self.tree = ast.parse(src)
        except SyntaxError as e:
            self.tree = None
            self.syntax_error = e
            return
        stack: list[tuple[ast.AST, ast.AST | None]] = [(self.tree, None)]
        while stack:
            node, parent = stack.pop()
            self._parent[id(node)] = parent
            self.nodes.append(node)
            self.by_type.setdefault(type(node), []).append(node)
            for child in ast.iter_child_nodes(node):
                stack.append((child, node))

    # -- structural helpers ------------------------------------------
    def of(self, *types) -> list:
        out: list = []
        for t in types:
            out.extend(self.by_type.get(t, ()))
        return out

    def parent(self, node) -> ast.AST | None:
        return self._parent.get(id(node))

    def ancestors(self, node):
        cur = self.parent(node)
        while cur is not None:
            yield cur
            cur = self.parent(cur)

    def enclosing(self, node, types) -> ast.AST | None:
        for anc in self.ancestors(node):
            if isinstance(anc, types):
                return anc
        return None

    def enclosing_function(self, node):
        return self.enclosing(node, (ast.FunctionDef, ast.AsyncFunctionDef))

    def enclosing_class(self, node):
        return self.enclosing(node, ast.ClassDef)

    def enclosing_loop(self, node):
        return self.enclosing(node, (ast.For, ast.While, ast.AsyncFor))

    def allowed(self, lineno: int) -> bool:
        """``# robust: allow`` on the line — record the use so the
        stale-suppression pass (S1) knows the marker earns its keep."""
        if lineno in self.allow_lines:
            self.used_allow_lines.add(lineno)
            return True
        return False

    # -- cached receiver tables --------------------------------------
    def _cache(self, key: str, build: Callable):
        if key not in self._caches:
            self._caches[key] = build()
        return self._caches[key]

    def _ctor_bound_keys(self, ctors: set[str]) -> set[str]:
        out: set[str] = set()
        for node in self.of(ast.Assign, ast.AnnAssign):
            value = node.value
            if not isinstance(value, ast.Call) or _ctor_name(value) not in ctors:
                continue
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for tgt in targets:
                key = _recv_key(tgt)
                if key:
                    out.add(key)
        return out

    def blocking_receivers(self) -> set[str]:
        """R4: names (incl. ``self.x``) bound from Thread/Queue
        constructors in this file."""
        return self._cache("r4_recv", lambda: self._ctor_bound_keys(
            _THREAD_CTORS | _QUEUE_CTORS))

    def bounded_receivers(self) -> tuple[set[str], set[str]]:
        """R6/R7/R9: (keys, attribute suffixes) bound from
        Thread/Queue/Event/Condition constructors — the suffix set
        matches cross-object uses (``pending.event.wait()``)."""
        def build():
            keys = self._ctor_bound_keys(
                _THREAD_CTORS | _QUEUE_CTORS | _WAIT_CTORS)
            return keys, {k.split(".")[-1] for k in keys}
        return self._cache("r6_recv", build)

    def lock_receivers(self) -> set[str]:
        """Receivers bound from Lock/RLock/Condition/Semaphore
        constructors (C1/C2 guard detection)."""
        return self._cache("lock_recv",
                           lambda: self._ctor_bound_keys(_LOCK_CTORS))

    def outer_func_of_line(self) -> dict[int, str]:
        """lineno -> OUTERMOST enclosing function name (the legacy R3
        allowlist semantics: the first walk claim wins, which is the
        outer def)."""
        def build():
            out: dict[int, str] = {}
            defs = self.of(ast.FunctionDef, ast.AsyncFunctionDef)

            def depth(d):
                return sum(1 for _ in self.ancestors(d))

            for fn in sorted(defs, key=lambda d: (depth(d), d.lineno)):
                for child in ast.walk(fn):
                    if hasattr(child, "lineno"):
                        out.setdefault(child.lineno, fn.name)
            return out
        return self._cache("func_of_line", build)

    def is_lockish(self, expr) -> bool:
        """Whether a with-item context expression looks like a lock:
        bound from a Lock-family constructor in this file, or named
        like one (``...lock``/``...cond``/``...mutex``)."""
        key = _recv_key(expr)
        if key is None:
            return False
        if key in self.lock_receivers():
            return True
        leaf = key.split(".")[-1].lower()
        return any(s in leaf for s in ("lock", "cond", "mutex"))

    def lock_guarded(self, node) -> bool:
        """Whether `node` sits lexically inside a ``with <lock>:``."""
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.With, ast.AsyncWith)):
                if any(self.is_lockish(item.context_expr)
                       for item in anc.items):
                    return True
        return False


# ------------------------------------------------------------------ rules
class Rule:
    """One pluggable check.  Subclasses set ``id``, ``severity``,
    ``pass_name`` and ``scope_key`` (None = always on) and implement
    :meth:`run` over the shared :class:`FileContext`."""

    id = "R?"
    severity = "error"
    pass_name = "robustness"
    scope_key: str | None = None

    def applies(self, ctx: FileContext) -> bool:
        return self.scope_key is None or bool(ctx.scopes.get(self.scope_key))

    def run(self, ctx: FileContext) -> list[Finding]:  # pragma: no cover
        raise NotImplementedError

    def finding(self, ctx: FileContext, line: int, msg: str) -> Finding:
        return Finding(ctx.relpath, line, self.id, msg, self.severity)


def default_rules() -> list[Rule]:
    """The full registered rule set, one instance per rule id."""
    from . import rules_concurrency, rules_determinism, rules_dispatch, \
        rules_fsseam, rules_robustness

    return (rules_robustness.RULES()
            + rules_concurrency.RULES()
            + rules_dispatch.RULES()
            + rules_determinism.RULES()
            + rules_fsseam.RULES())


LEGACY_RULE_IDS = ("R1", "R2", "R3", "R4", "R5", "R6", "R7", "R8")


# ----------------------------------------------------------------- runner
def check_source(src: str, relpath: str,
                 overrides: dict | None = None,
                 rule_ids: Iterable[str] | None = None,
                 stale_check: bool = False) -> list[Finding]:
    """Lint one source string under `relpath`'s (or the overridden)
    scopes.  Returns the ACTIVE findings (suppressed ones dropped),
    sorted by (line, rule).  `rule_ids` restricts the rule set (the
    legacy shim passes R1–R8); `stale_check` adds S1 findings for
    ``robust: allow`` markers that suppressed nothing (full-repo runs
    only — scope-forced matrix runs would see false stales)."""
    ctx = FileContext(src, relpath, scopes_for(relpath, overrides))
    if ctx.syntax_error is not None:
        e = ctx.syntax_error
        return [Finding(relpath, e.lineno or 0, "R0",
                        f"syntax error: {e.msg}")]
    wanted = None if rule_ids is None else set(rule_ids)
    findings: list[Finding] = []
    for rule in default_rules():
        if wanted is not None and rule.id not in wanted:
            continue
        if not rule.applies(ctx):
            continue
        for f in rule.run(ctx):
            if not ctx.allowed(f.line):
                findings.append(f)
    if stale_check:
        for line in sorted(ctx.allow_lines - ctx.used_allow_lines):
            findings.append(Finding(
                relpath, line, "S1",
                "stale `robust: allow` — this line no longer triggers "
                "any rule; delete the marker (suppressions must not "
                "rot silently)", "warning"))
    findings.sort(key=lambda f: (f.line, f.rule))
    return findings


def iter_package_files(root: str = REPO):
    """(abspath, relpath) for every package .py file, sorted."""
    pkg_root = os.path.join(root, PACKAGE)
    for dirpath, _dirnames, filenames in sorted(os.walk(pkg_root)):
        if "__pycache__" in dirpath:
            continue
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            yield path, os.path.relpath(path, root)


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.json")


def load_baseline(path: str | None) -> list[dict]:
    """The reviewed baseline: ``{"entries": [{path, rule, line,
    reason}, ...]}``.  Every entry MUST carry a non-empty reason — an
    unjustified baseline is just a hidden suppression."""
    if not path or not os.path.exists(path):
        return []
    with open(path) as fh:
        data = json.load(fh)
    entries = data.get("entries", [])
    for e in entries:
        if not str(e.get("reason", "")).strip():
            raise ValueError(
                f"baseline entry without a justification: {e!r} "
                "(every entry needs a one-line reason)")
    return entries


def apply_baseline(findings: list[Finding], entries: list[dict],
                   baseline_path: str) -> list[Finding]:
    """Mark findings matched by baseline entries; append an S2 warning
    for every entry that matched nothing (baseline rot)."""
    used = [False] * len(entries)
    for f in findings:
        for i, e in enumerate(entries):
            if (e.get("path") == f.path and e.get("rule") == f.rule
                    and int(e.get("line", -1)) == f.line):
                f.baselined = True
                f.baseline_reason = str(e.get("reason"))
                used[i] = True
                break
    rel = os.path.relpath(baseline_path, REPO) if baseline_path else "baseline"
    for i, e in enumerate(entries):
        if not used[i]:
            findings.append(Finding(
                rel, 0, "S2",
                f"baseline entry matches no finding and should be "
                f"removed: {e.get('path')}:{e.get('line')} "
                f"{e.get('rule')}", "warning"))
    return findings


def lint_tree(root: str = REPO, baseline_path: str | None = None,
              rule_ids: Iterable[str] | None = None) -> list[Finding]:
    """Full-repo run: every package file, every rule, suppression +
    stale + baseline machinery on.  Returns findings that COUNT
    (baselined ones are marked, not dropped — callers decide)."""
    findings: list[Finding] = []
    for path, rel in iter_package_files(root):
        with open(path) as fh:
            src = fh.read()
        findings.extend(check_source(src, rel, rule_ids=rule_ids,
                                     stale_check=True))
    if baseline_path is None:
        baseline_path = default_baseline_path()
    entries = load_baseline(baseline_path)
    if entries:
        findings = apply_baseline(findings, entries, baseline_path)
    return findings


def failing(findings: list[Finding], fail_on: str = "warning") -> list[Finding]:
    """The findings that make the run fail: at/above the severity
    threshold and not baselined."""
    if fail_on == "never":
        return []
    threshold = SEVERITY_RANK[fail_on]
    return [f for f in findings
            if not f.baselined and SEVERITY_RANK[f.severity] >= threshold]
