"""faalint — the repo's multi-pass static analyzer for concurrency,
dispatch-hazard, and determinism bugs (docs/STATIC_ANALYSIS.md).

Public API::

    from faalint import check_source, lint_tree, Finding
    findings = check_source(src, "fast_autoaugment_tpu/serve/x.py")
    findings = lint_tree()          # full repo, baseline + stale checks

CLI::

    python -m tools.faalint [--json] [--fail-on SEV] [--selfcheck]
"""

from .engine import (Finding, LEGACY_RULE_IDS, PACKAGE, REPO,  # noqa: F401
                     check_source, default_baseline_path, default_rules,
                     failing, lint_tree, load_baseline, scopes_for)
