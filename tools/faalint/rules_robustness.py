"""Robustness rules R1–R9 (R1–R8 migrated verbatim from the legacy
``tools/lint_robustness.py``; R9 extends the unbounded-blocking engine
to the remaining thread code).

R1  no bare ``except:`` — swallows KeyboardInterrupt/SystemExit and
    the typed resilience signals.
R2  no swallowed broad excepts — ``except Exception`` must log,
    re-raise, or capture the bound value.
R3  no direct run-artifact writes in core/search/train/launch —
    ``json.dump`` / write-mode ``open`` are reserved to
    ``write_json_atomic`` / ``save_checkpoint``.
R4  no untimed ``Thread.join()`` / ``Queue.get()`` in the
    supervision layers (core/launch/search).
R5  no ``jax.jit`` outside the compile seam in train/search/serve.
R6  no unbounded blocking in serve/ (the blocking-admission bug
    class, PR 8).
R7  the R6 engine over search/ (the async pipeline contract, PR 9).
R8  no raw ``time.time``/``time.perf_counter`` in train/search/serve
    hot paths — timing routes through the telemetry seam (PR 10).
R9  the R6/R7 unbounded-blocking engine extended to core/, launch/,
    data/ and utils/ thread code: untimed ``put``/``get``/``wait``/
    ``join`` on constructor-tracked receivers and bare
    ``time.sleep`` poll loops.  join/get already policed by R4 in
    core/launch are not double-flagged (one finding per hazard).
"""

from __future__ import annotations

import ast

from .engine import (BLOCKING_DIRS, Finding, FileContext, Rule, _in_dirs,
                     _recv_key)

_LOG_NAMES = {"logger", "logging", "log", "warnings"}
_LOG_METHODS = {"debug", "info", "warning", "warn", "error", "exception",
                "critical", "fatal"}

#: R6-family blocking methods and the positional index their timeout
#: lands at (``put(item)`` has ONE arg and still blocks forever;
#: ``get()``/``join()``/``wait()`` block with ZERO args)
_BOUNDED_METHODS = {"put": 1, "get": 0, "join": 0, "wait": 0}

_R8_CLOCKS = {"time", "perf_counter"}

# (relative module path suffix, function name) pairs allowed to write
# directly: THE atomic helpers themselves.
ARTIFACT_WRITERS = {
    ("core/checkpoint.py", "save_checkpoint"),
    ("core/fsfault.py", "write_json_atomic"),
    ("search/driver.py", "write_json_atomic"),
}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    names = []
    if isinstance(t, ast.Name):
        names = [t.id]
    elif isinstance(t, ast.Tuple):
        names = [e.id for e in t.elts if isinstance(e, ast.Name)]
    return any(n in ("Exception", "BaseException") for n in names)


def _handles_failure(handler: ast.ExceptHandler) -> bool:
    """True when the handler body logs, re-raises, or captures the
    bound exception value (the propagate-through-a-channel pattern)."""
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if handler.name and isinstance(node, ast.Name) \
                and node.id == handler.name \
                and isinstance(node.ctx, ast.Load):
            return True
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute):
                base = f.value
                if isinstance(base, ast.Name) and (
                        base.id in _LOG_NAMES
                        or base.id.startswith("log")) \
                        and f.attr in _LOG_METHODS | {"warn"}:
                    return True
                if isinstance(base, ast.Name) and base.id == "warnings" \
                        and f.attr == "warn":
                    return True
    return False


def _write_mode(call: ast.Call) -> str | None:
    """The mode string of an ``open`` call if it writes, else None."""
    mode = None
    if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant) \
            and isinstance(call.args[1].value, str):
        mode = call.args[1].value
    for kw in call.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant) \
                and isinstance(kw.value.value, str):
            mode = kw.value.value
    if mode and ("w" in mode or "x" in mode or "+" in mode):
        return mode
    return None


def _has_timeout(call: ast.Call) -> bool:
    """R4: ANY argument bounds the call (positional timeout,
    ``get(False)``, or ``timeout=``)."""
    return bool(call.args) or any(kw.arg == "timeout" for kw in call.keywords)


def _bounded(call: ast.Call, method: str) -> bool:
    """R6-family: positional args past the payload slot or a
    ``block=``/``timeout=`` keyword."""
    if len(call.args) > _BOUNDED_METHODS[method]:
        return True
    return any(kw.arg in ("timeout", "block") for kw in call.keywords)


def _sleep_calls_in_while(ctx: FileContext):
    """``time.sleep`` calls lexically inside a ``while`` body."""
    for call in ctx.of(ast.Call):
        f = call.func
        if isinstance(f, ast.Attribute) and f.attr == "sleep" \
                and isinstance(f.value, ast.Name) and f.value.id == "time" \
                and ctx.enclosing(call, ast.While) is not None:
            yield call


class BareExcept(Rule):
    id = "R1"

    def run(self, ctx: FileContext) -> list[Finding]:
        return [self.finding(
            ctx, h.lineno,
            "bare `except:` swallows SystemExit/KeyboardInterrupt and "
            "the typed resilience signals — name the exceptions")
            for h in ctx.of(ast.ExceptHandler) if h.type is None]


class SwallowedBroadExcept(Rule):
    id = "R2"

    def run(self, ctx: FileContext) -> list[Finding]:
        return [self.finding(
            ctx, h.lineno,
            "broad `except Exception` neither logs nor re-raises — a "
            "swallowed failure leaves no evidence")
            for h in ctx.of(ast.ExceptHandler)
            if h.type is not None and _is_broad(h)
            and not _handles_failure(h)]


class DirectArtifactWrite(Rule):
    id = "R3"
    scope_key = "artifact"

    def run(self, ctx: FileContext) -> list[Finding]:
        norm = ctx.relpath.replace("\\", "/")
        func_of = ctx.outer_func_of_line()

        def allowlisted(lineno: int) -> bool:
            fn = func_of.get(lineno, "")
            return any(norm.endswith(suffix) and fn == name
                       for suffix, name in ARTIFACT_WRITERS)

        out: list[Finding] = []
        for node in ctx.of(ast.Call):
            if allowlisted(node.lineno):
                continue
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr == "dump" \
                    and isinstance(f.value, ast.Name) and f.value.id == "json":
                out.append(self.finding(
                    ctx, node.lineno,
                    "direct json.dump to a run artifact — use "
                    "write_json_atomic (fsync + rename) so a crash "
                    "cannot tear the file"))
            elif isinstance(f, ast.Name) and f.id == "open":
                mode = _write_mode(node)
                if mode:
                    out.append(self.finding(
                        ctx, node.lineno,
                        f"direct open(..., {mode!r}) write to a run "
                        "artifact — route through write_json_atomic / "
                        "save_checkpoint"))
        return out


class UntimedSupervisionBlock(Rule):
    id = "R4"
    scope_key = "blocking"

    def run(self, ctx: FileContext) -> list[Finding]:
        blockers = ctx.blocking_receivers()
        if not blockers:
            return []
        out: list[Finding] = []
        for node in ctx.of(ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr in ("join", "get") \
                    and _recv_key(f.value) in blockers \
                    and not _has_timeout(node):
                out.append(self.finding(
                    ctx, node.lineno,
                    f"untimed blocking .{f.attr}() on a Thread/Queue — "
                    "pass a timeout (the watchdog contract: supervision "
                    "code must never be able to hang forever)"))
        return out


class DirectJit(Rule):
    id = "R5"
    scope_key = "jit"

    def run(self, ctx: FileContext) -> list[Finding]:
        # catches direct calls, functools.partial(jax.jit, ...) AND
        # @jax.jit decorators: any reference to the attribute in seam
        # scope is an uninstrumented compile path
        return [self.finding(
            ctx, node.lineno,
            "direct jax.jit outside the compile seam — route through "
            "core/compilecache.seam_jit / aot_compile so the first-call "
            "compile is timed and classified hit/miss against the "
            "persistent cache")
            for node in ctx.of(ast.Attribute)
            if node.attr == "jit" and isinstance(node.value, ast.Name)
            and node.value.id == "jax"]


class _BoundedBlockingEngine(Rule):
    """The shared R6/R7/R9 engine: unbounded ``put``/``get``/``wait``/
    ``join`` on constructor-tracked receivers (incl. attribute-suffix
    matches for deep chains) and bare ``time.sleep`` poll loops."""

    where = "?"
    contract = "?"
    skip_r4_duplicates = False

    def run(self, ctx: FileContext) -> list[Finding]:
        out: list[Finding] = []
        for call in _sleep_calls_in_while(ctx):
            out.append(self.finding(
                ctx, call.lineno,
                f"bare time.sleep inside a while loop in {self.where} "
                "— a poll loop with no deadline; use "
                "Event.wait(timeout) or a bounded Condition.wait so "
                "shutdown can interrupt it"))
        keys, suffixes = ctx.bounded_receivers()
        r4_blockers = ctx.blocking_receivers() \
            if self.skip_r4_duplicates else set()
        r4_active = self.skip_r4_duplicates \
            and _in_dirs(ctx.relpath, BLOCKING_DIRS)
        for node in ctx.of(ast.Call):
            f = node.func
            if not (isinstance(f, ast.Attribute)
                    and f.attr in _BOUNDED_METHODS
                    and not _bounded(node, f.attr)):
                continue
            key = _recv_key(f.value)
            suffix = None
            if key is None and isinstance(f.value, ast.Attribute):
                suffix = f.value.attr  # deep chains: match by suffix
            elif key is not None:
                suffix = key.split(".")[-1]
            if (key not in keys) and (suffix not in suffixes):
                continue
            if r4_active and f.attr in ("join", "get") \
                    and key in r4_blockers:
                continue  # R4 already owns this finding
            out.append(self.finding(
                ctx, node.lineno,
                f"unbounded blocking .{f.attr}() in {self.where} — "
                f"{self.contract}: no worker thread may park forever; "
                "pass a timeout (or non-blocking form) and fail fast "
                "on expiry"))
        return out


class ServeBlocking(_BoundedBlockingEngine):
    id = "R6"
    scope_key = "serve"
    where = "serve/"
    contract = "the overload contract"


class SearchBlocking(_BoundedBlockingEngine):
    id = "R7"
    scope_key = "search"
    where = "search/"
    contract = "the pipeline preemption contract"

    def applies(self, ctx: FileContext) -> bool:
        # a file lives in at most one of the serve/search scopes;
        # serve wins the shared engine's rule id (legacy semantics)
        return super().applies(ctx) and not ctx.scopes.get("serve")


class ExtendedBlocking(_BoundedBlockingEngine):
    id = "R9"
    scope_key = "ext_blocking"
    where = "thread/supervision code"
    contract = "the no-thread-parks-forever contract"
    skip_r4_duplicates = True

    def applies(self, ctx: FileContext) -> bool:
        # serve/search keep their own rule ids for the same engine
        return super().applies(ctx) and not ctx.scopes.get("serve") \
            and not ctx.scopes.get("search")


class RawClock(Rule):
    id = "R8"
    scope_key = "timing"

    def run(self, ctx: FileContext) -> list[Finding]:
        out: list[Finding] = []
        for node in ctx.of(ast.Attribute):
            if node.attr in _R8_CLOCKS \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id == "time":
                out.append(self.finding(
                    ctx, node.lineno,
                    f"raw time.{node.attr} in a train/search/serve hot "
                    "path — route timing through the telemetry seam "
                    "(core/telemetry.py wall()/mono()/span()) or "
                    "utils/profiling.py so the measurement reaches the "
                    "registry/journal the artifacts stamp from"))
        for node in ctx.of(ast.ImportFrom):
            if node.module != "time":
                continue
            for alias in node.names:
                if alias.name in _R8_CLOCKS:
                    out.append(self.finding(
                        ctx, node.lineno,
                        f"`from time import {alias.name}` in a "
                        "train/search/serve hot path — the import-alias "
                        "form of a raw clock read; use the telemetry "
                        "seam (core/telemetry.py)"))
        return out


def RULES() -> list[Rule]:
    return [BareExcept(), SwallowedBroadExcept(), DirectArtifactWrite(),
            UntimedSupervisionBlock(), DirectJit(), ServeBlocking(),
            SearchBlocking(), ExtendedBlocking(), RawClock()]
