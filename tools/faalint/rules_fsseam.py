"""FS-seam pass: shared-directory I/O must route through the
``core/fsfault.py`` fault seam.

PR 15 made the shared-filesystem layers (``launch/``, ``search/``,
``control/``) hostile-substrate-safe by funneling every shared-dir
read/list through ``core/fsfault.py`` — which is also where the
``FAA_FSFAULT`` drills inject lag / stale reads / transient EIO / torn
tails.  A direct ``open``/``os.listdir``/``os.stat``/``json.load``
added later in those layers would silently bypass both the hardening
and the drills (the seam would rot exactly like an unexercised
recovery path).  Rule F1 pins the funnel.

Exemptions mirror the R3 atomic-writer idiom: code inside a function
named ``write_json_atomic``/``_write_json_atomic`` IS the seam's
delegate, and ``# robust: allow`` escapes the rest (local-only files,
process-private scratch) with the justification on the line.
"""

from __future__ import annotations

import ast

from .engine import FileContext, Finding, Rule

#: the enclosing-function names that ARE the seam/writer primitives
_WRITER_FUNCS = {"write_json_atomic", "_write_json_atomic"}


def _call_desc(call: ast.Call) -> str | None:
    """A flagged call's description, or None when the call is not one
    of the direct-I/O shapes F1 polices."""
    f = call.func
    if isinstance(f, ast.Name) and f.id == "open":
        return "open(...)"
    if not isinstance(f, ast.Attribute):
        return None
    # os.listdir / os.stat / os.scandir
    if isinstance(f.value, ast.Name) and f.value.id == "os" \
            and f.attr in ("listdir", "stat", "scandir"):
        return f"os.{f.attr}(...)"
    # os.path.getsize / os.path.getmtime
    if isinstance(f.value, ast.Attribute) and f.value.attr == "path" \
            and isinstance(f.value.value, ast.Name) \
            and f.value.value.id == "os" \
            and f.attr in ("getsize", "getmtime"):
        return f"os.path.{f.attr}(...)"
    # json.load (json.loads is string-level, not I/O)
    if isinstance(f.value, ast.Name) and f.value.id == "json" \
            and f.attr == "load":
        return "json.load(...)"
    # glob.glob / glob.iglob (shared-dir discovery)
    if isinstance(f.value, ast.Name) and f.value.id == "glob" \
            and f.attr in ("glob", "iglob"):
        return f"glob.{f.attr}(...)"
    return None


class SharedDirIOSeamRule(Rule):
    """F1: direct filesystem I/O in the shared-dir layers outside the
    ``core/fsfault.py`` seam."""

    id = "F1"
    severity = "error"
    pass_name = "fsseam"
    scope_key = "fsseam"

    def run(self, ctx: FileContext) -> list[Finding]:
        out: list[Finding] = []
        func_of_line = ctx.outer_func_of_line()
        for call in ctx.of(ast.Call):
            desc = _call_desc(call)
            if desc is None:
                continue
            # the atomic-writer primitive is the seam's own delegate
            # (same allowlist semantics as R3)
            fn = None
            for anc in ctx.ancestors(call):
                if isinstance(anc, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    fn = anc.name
                    break
            if fn in _WRITER_FUNCS \
                    or func_of_line.get(call.lineno) in _WRITER_FUNCS:
                continue
            out.append(self.finding(
                ctx, call.lineno,
                f"direct shared-dir I/O ({desc}) outside the "
                "core/fsfault.py seam — route through fsfault."
                "read_json/load_json/listdir/getsize/read_from/"
                "glob_files so hardening AND the FAA_FSFAULT drills "
                "cover this access (local-only files: justify with "
                "`# robust: allow`)"))
        return out


def RULES() -> list[Rule]:
    return [SharedDirIOSeamRule()]
