"""Determinism rules T1–T3: the byte-identical-artifact contract,
enforced statically in the artifact-writing layers (core/, search/,
train/ — everything funneled through ``write_json_atomic`` /
``save_checkpoint``).  The repo's acceptance drills diff artifacts
byte-for-byte across hosts, resumes and reclaims; one wall-clock or
pid leaking into a payload breaks every one of them.

The rules are function-local and taint-based: a function counts as
artifact-writing when it calls one of the atomic writers; inside it,
values derived from nondeterministic sources that reach a writer call's
arguments are flagged.

T1  **wall-clock into a persisted payload**: ``time.time()`` /
    ``datetime.now()`` / the telemetry ``wall()`` seam flowing into a
    writer argument.
T2  **unordered iteration in an artifact-writing function**:
    iterating a ``set`` or an unsorted ``os.listdir`` — the iteration
    order (hash seed / readdir order) leaks into whatever is built
    from it; wrap in ``sorted()``.
T3  **process-identity into a persisted payload**: ``os.getpid()`` /
    ``id()`` / ``threading.get_ident()`` values are distinct per
    process by construction — a resume or a reclaiming host can never
    reproduce them.

launch/ is deliberately out of scope: lease and heartbeat records are
wall-clock + pid stamped BY DESIGN (staleness detection is their
function) — see docs/STATIC_ANALYSIS.md.
"""

from __future__ import annotations

import ast

from .engine import Finding, FileContext, Rule

_WRITERS = {"write_json_atomic", "_write_json_atomic", "save_checkpoint"}

#: wall-clock sources (T1): (base, attr) attribute calls or bare names
_WALL_ATTRS = {("time", "time"), ("time", "time_ns"),
               ("datetime", "now"), ("datetime", "utcnow"),
               ("datetime", "today"), ("date", "today"),
               ("telemetry", "wall")}
_WALL_NAMES = {"wall"}

#: process-identity sources (T3)
_PID_ATTRS = {("os", "getpid"), ("os", "getppid"),
              ("threading", "get_ident")}
_PID_NAMES = {"id"}


def _source_kind(call: ast.Call) -> tuple[str, str] | None:
    """('T1'|'T3', printable source name) when `call` reads a
    nondeterministic source."""
    f = call.func
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
        pair = (f.value.id, f.attr)
        if pair in _WALL_ATTRS:
            return "T1", f"{pair[0]}.{pair[1]}()"
        if pair in _PID_ATTRS:
            return "T3", f"{pair[0]}.{pair[1]}()"
    elif isinstance(f, ast.Name):
        if f.id in _WALL_NAMES:
            return "T1", f"{f.id}()"
        if f.id in _PID_NAMES and len(call.args) == 1:
            return "T3", f"{f.id}()"
    return None


def _writer_call(call: ast.Call) -> bool:
    f = call.func
    name = f.id if isinstance(f, ast.Name) else (
        f.attr if isinstance(f, ast.Attribute) else None)
    return name in _WRITERS


def _unordered_value(value) -> str | None:
    """Why iterating `value` is unordered: a set display/constructor or
    an unsorted os.listdir.  A top-level ``sorted(...)`` wrapper makes
    any of them ordered."""
    if isinstance(value, ast.Set):
        return "a set display"
    if isinstance(value, ast.Call):
        f = value.func
        if isinstance(f, ast.Name):
            if f.id == "set":
                return "set(...)"
            if f.id == "sorted":
                return None
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
                and f.value.id == "os" and f.attr == "listdir":
            return "os.listdir(...)"
    return None


class _DetFunctions:
    """The analysis units: functions containing a writer call, with a
    per-function taint table (name -> (kind, source)) built in one
    forward pass over the assignments."""

    def __init__(self, ctx: FileContext):
        self.ctx = ctx
        self.units: dict[int, dict] = {}
        for call in ctx.of(ast.Call):
            if _writer_call(call):
                fn = ctx.enclosing_function(call)
                unit = self.units.setdefault(
                    id(fn), {"fn": fn, "writers": [], "taint": {},
                             "unordered": {}})
                unit["writers"].append(call)
        if not self.units:
            return
        for fid, unit in self.units.items():
            fn = unit["fn"]
            if fn is None:  # module-level writer calls
                nodes = [n for n in ctx.nodes
                         if ctx.enclosing_function(n) is None]
            else:
                nodes = list(ast.walk(fn))
            taint: dict[str, set[tuple[str, str]]] = {}
            unordered: dict[str, str] = {}
            assigns = sorted(
                (n for n in nodes if isinstance(n, ast.Assign)),
                key=lambda n: n.lineno)
            for node in assigns:
                names = [t.id for t in node.targets
                         if isinstance(t, ast.Name)]
                if not names:
                    continue
                verdicts = self._expr_taint(node.value, taint)
                if verdicts:
                    for nm in names:
                        taint.setdefault(nm, set()).update(verdicts)
                why = _unordered_value(node.value)
                if why:
                    for nm in names:
                        unordered[nm] = why
            unit["taint"] = taint
            unit["unordered"] = unordered
            unit["nodes"] = nodes

    def _expr_taint(self, expr, taint) -> set[tuple[str, str]]:
        out: set[tuple[str, str]] = set()
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                kind = _source_kind(node)
                if kind:
                    out.add(kind)
            if isinstance(node, ast.Name) and node.id in taint \
                    and isinstance(node.ctx, ast.Load):
                out |= taint[node.id]
        return out


def _det_functions(ctx: FileContext) -> _DetFunctions:
    if "det_units" not in ctx._caches:
        ctx._caches["det_units"] = _DetFunctions(ctx)
    return ctx._caches["det_units"]


class _PayloadTaintRule(Rule):
    kind = "?"
    what = "?"
    fix = "?"

    def run(self, ctx: FileContext) -> list[Finding]:
        out: list[Finding] = []
        for unit in _det_functions(ctx).units.values():
            taint = unit["taint"]
            for writer in unit["writers"]:
                sources: set[str] = set()
                for arg in list(writer.args) + [kw.value for kw
                                                in writer.keywords]:
                    for node in ast.walk(arg):
                        if isinstance(node, ast.Call):
                            k = _source_kind(node)
                            if k and k[0] == self.kind:
                                sources.add(k[1])
                        elif isinstance(node, ast.Name) \
                                and isinstance(node.ctx, ast.Load) \
                                and node.id in taint:
                            for kind, src in taint[node.id]:
                                if kind == self.kind:
                                    sources.add(f"'{node.id}' (from {src})")
                if sources:
                    out.append(self.finding(
                        ctx, writer.lineno,
                        f"{self.what} flows into this persisted "
                        f"artifact via {', '.join(sorted(sources))} — "
                        "the byte-identical-artifact contract "
                        f"(docs/STATIC_ANALYSIS.md): {self.fix}"))
        return out


class WallClockIntoArtifact(_PayloadTaintRule):
    id = "T1"
    pass_name = "determinism"
    scope_key = "determinism"
    kind = "T1"
    what = "a wall-clock value"
    fix = ("derive stamps from run inputs (seed/config/epoch), or move "
           "timing evidence to the telemetry journal")


class PidIntoArtifact(_PayloadTaintRule):
    id = "T3"
    pass_name = "determinism"
    scope_key = "determinism"
    kind = "T3"
    what = "a process-identity value"
    fix = ("identify runs by FAA_HOST_ID/FAA_ATTEMPT (stable across "
           "resume), never by pid/id()")


class UnorderedIteration(Rule):
    id = "T2"
    pass_name = "determinism"
    scope_key = "determinism"

    def run(self, ctx: FileContext) -> list[Finding]:
        out: list[Finding] = []
        for unit in _det_functions(ctx).units.values():
            unordered = unit["unordered"]
            nodes = unit.get("nodes", [])
            iters = []
            for node in nodes:
                if isinstance(node, (ast.For, ast.AsyncFor)):
                    iters.append((node.iter, node.lineno))
                elif isinstance(node, ast.comprehension):
                    iters.append((node.iter, getattr(
                        node.iter, "lineno", 0)))
            for it, lineno in iters:
                why = _unordered_value(it)
                if why is None and isinstance(it, ast.Name) \
                        and it.id in unordered:
                    why = f"'{it.id}' ({unordered[it.id]})"
                if why:
                    out.append(self.finding(
                        ctx, lineno,
                        f"iteration over {why} in an artifact-writing "
                        "function — set/readdir order leaks the hash "
                        "seed / filesystem into the artifact; wrap in "
                        "sorted(...)"))
        return out


def RULES() -> list[Rule]:
    return [WallClockIntoArtifact(), UnorderedIteration(), PidIntoArtifact()]
