"""Host-feed throughput benchmark: native C++ loader vs PIL, and the
prefetch-depth sweep (VERDICT round 1, next-step 6).

Measures, on a directory of real JPEGs (generated on the fly if absent):

1. decode+crop+resize images/sec — native libjpeg thread-pool loader
   (``native/faa_loader.cpp``) vs the PIL fallback, batch after batch;
2. end-to-end `train_batches` + `prefetch(depth)` feed rate at several
   depths — the rate at which the host can actually hand batches to the
   device layer (reference baseline: 8 torch DataLoader workers per GPU,
   reference ``data.py:214-224``).

    python tools/bench_loader.py --n 512 --size 320 --target 224 \
        --report docs/loader_bench.md
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def make_jpegs(root: str, n: int, size: int) -> list[str]:
    """Synthesize photographic-ish JPEGs (smooth gradients + texture so
    entropy, and thus decode cost, is realistic)."""
    import PIL.Image

    os.makedirs(root, exist_ok=True)
    paths = []
    rng = np.random.default_rng(0)
    yy, xx = np.mgrid[0:size, 0:size].astype(np.float32) / size
    for i in range(n):
        base = np.stack([
            127 + 120 * np.sin(2 * np.pi * (xx * rng.uniform(1, 3) + rng.uniform())),
            127 + 120 * np.cos(2 * np.pi * (yy * rng.uniform(1, 3) + rng.uniform())),
            127 + 120 * np.sin(2 * np.pi * ((xx + yy) * rng.uniform(1, 2))),
        ], axis=-1)
        noise = rng.normal(0, 20, (size, size, 3))
        img = np.clip(base + noise, 0, 255).astype(np.uint8)
        p = os.path.join(root, f"img_{i:05d}.jpg")
        PIL.Image.fromarray(img).save(p, quality=90)
        paths.append(p)
    return paths


def bench_decoder(paths, target: int, batch: int, use_native: bool,
                  threads: int | None = None) -> float:
    """images/sec for full-frame decode+resize over all paths."""
    from fast_autoaugment_tpu.data import native_loader

    boxes = None  # full-frame
    t0 = time.perf_counter()
    n = 0
    for s in range(0, len(paths), batch):
        chunk = paths[s:s + batch]
        if use_native:
            full = np.array(
                [[0, 0, w, h] for w, h in
                 (native_loader.image_size(p) for p in chunk)], np.float32)
            out, failures = native_loader.decode_resize_batch(
                chunk, target, full, threads=threads)
            assert failures == 0
        else:
            import PIL.Image

            out = np.stack([
                np.asarray(
                    PIL.Image.open(p).convert("RGB")
                    .resize((target, target), PIL.Image.BICUBIC), np.uint8)
                for p in chunk
            ])
        n += len(chunk)
    return n / (time.perf_counter() - t0)


def bench_feed(paths, target: int, batch: int, depth: int, steps: int) -> float:
    """images/sec of the full train feed path (lazy dataset -> boxed
    decode -> prefetch queue), consumed as fast as possible."""
    from fast_autoaugment_tpu.data.datasets import ArrayDataset
    from fast_autoaugment_tpu.data.pipeline import SizeCache, prefetch, train_batches

    ds = ArrayDataset(np.asarray(paths, object),
                      np.zeros(len(paths), np.int32), 10, lazy=True)
    box = lambda rng, w, h: (0, 0, w, h)  # noqa: E731
    cache = SizeCache()
    it = prefetch(
        train_batches(ds, None, batch, epoch=1, box_fn=box, imgsize=target,
                      size_cache=cache),
        depth=depth,
    )
    n = 0
    t0 = time.perf_counter()
    for images, _labels in it:
        n += len(images)
        if n >= steps * batch:
            break
    return n / (time.perf_counter() - t0)


def bench_gather(n_examples=4096, img=32, batch=256, iters=30) -> dict:
    """Host-gather vs device-gather per-batch feed latency.

    The two ways a train step gets its batch from an eager dataset:

    - host: numpy fancy-index into the in-RAM array + ``device_put``
      onto the mesh per step (today's `train_batches` + shard path);
    - device: the array resident in HBM once (`DeviceCache`), a jitted
      index gather per step, only the int32 indices crossing the host
      boundary (`--device-cache`; docs/BENCHMARKS.md "Step dispatch &
      device cache").

    Emitted as one JSON line so the two feed paths are comparable next
    to the decode/prefetch numbers above — this is the in-memory
    (CIFAR) analog of the lazy-decode feed this tool historically
    benches.
    """
    import jax
    import jax.numpy as jnp

    from fast_autoaugment_tpu.data.datasets import ArrayDataset
    from fast_autoaugment_tpu.data.pipeline import DeviceCache
    from fast_autoaugment_tpu.parallel.mesh import (
        make_mesh,
        place_index_matrix,
        shard_batch,
    )

    rng = np.random.default_rng(0)
    ds = ArrayDataset(
        rng.integers(0, 256, (n_examples, img, img, 3), dtype=np.uint8),
        rng.integers(0, 10, (n_examples,), np.int32), 10)
    mesh = make_mesh()
    idx_all = [rng.permutation(n_examples)[:batch] for _ in range(iters)]

    def host_once(idx):
        b = shard_batch(mesh, {"x": ds.images[idx], "y": ds.labels[idx]})
        jax.block_until_ready(b["x"])
        return b

    cache = DeviceCache(ds, mesh)
    gather = jax.jit(lambda xs, ys, i: (jnp.take(xs, i, axis=0),
                                        jnp.take(ys, i, axis=0)))

    def device_once(idx):
        x, y = gather(cache.images, cache.labels,
                      place_index_matrix(mesh, idx))
        jax.block_until_ready(x)
        return x

    host_once(idx_all[0])  # warm any layout/transfer paths
    device_once(idx_all[0])  # compile outside the timed loop
    t0 = time.perf_counter()
    for idx in idx_all:
        host_once(idx)
    host_ms = (time.perf_counter() - t0) / iters * 1e3
    t0 = time.perf_counter()
    for idx in idx_all:
        device_once(idx)
    device_ms = (time.perf_counter() - t0) / iters * 1e3
    return {
        "metric": "feed_gather_ms_per_batch",
        "host_gather_device_put_ms": round(host_ms, 3),
        "device_resident_gather_ms": round(device_ms, 3),
        "speedup_device_vs_host": round(host_ms / device_ms, 2)
        if device_ms else None,
        "probe": {"n_examples": n_examples, "image": img, "batch": batch,
                  "iters": iters, "devices": mesh.size},
    }


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--dir", default="/tmp/faa_loader_bench")
    p.add_argument("--n", type=int, default=512)
    p.add_argument("--size", type=int, default=320, help="source JPEG side")
    p.add_argument("--target", type=int, default=224)
    p.add_argument("--batch", type=int, default=64)
    p.add_argument("--depths", default="1,2,4,8")
    p.add_argument("--threads-sweep", default=None,
                   help="comma list (e.g. 1,2,4,8,16): additionally bench "
                        "the native decoder's thread-pool scaling — the "
                        "measurement that justifies (or not) the C++ pool "
                        "on multi-core TPU-VM hosts.  On this 1-core "
                        "container the curve is flat by construction; the "
                        "claim stays 'unproven at scale' until run on a "
                        "real multi-core host (docs/loader_bench.md)")
    p.add_argument("--report", default=None)
    args = p.parse_args(argv)

    # loadavg/process provenance, shared with bench.py (VERDICT r5
    # weak 1); FAA_BENCH_REQUIRE_QUIET=1 refuses on a busy host
    import json

    from bench import (
        arm_compile_cache_from_env,
        host_contention_stamp,
        refuse_or_flag_contention,
        telemetry_stamp,
    )

    contention = refuse_or_flag_contention(host_contention_stamp())
    print(f"contention: {json.dumps(contention)}")
    arm_compile_cache_from_env()

    from fast_autoaugment_tpu.data import native_loader

    existing = sorted(
        os.path.join(args.dir, f) for f in os.listdir(args.dir)
        if f.endswith(".jpg")
    ) if os.path.isdir(args.dir) else []
    paths = existing if len(existing) >= args.n else make_jpegs(
        args.dir, args.n, args.size)

    rows = {}
    rows["pil"] = bench_decoder(paths, args.target, args.batch, use_native=False)
    print(f"PIL decode+resize:    {rows['pil']:8.1f} img/s")
    if native_loader.available():
        rows["native"] = bench_decoder(paths, args.target, args.batch, use_native=True)
        print(f"native decode+resize: {rows['native']:8.1f} img/s "
              f"({rows['native'] / rows['pil']:.1f}x PIL)")
    else:
        print("native loader not built (make -C native)")

    thread_rows = {}
    if args.threads_sweep and native_loader.available():
        sweep = [int(t) for t in args.threads_sweep.split(",")]
        for th in sweep:
            thread_rows[th] = bench_decoder(paths, args.target, args.batch,
                                            use_native=True, threads=th)
        base_th = 1 if 1 in thread_rows else min(thread_rows)
        base = thread_rows[base_th]
        for th in sweep:
            print(f"native threads={th}: {thread_rows[th]:8.1f} img/s "
                  f"({thread_rows[th] / base:.2f}x vs {base_th} thread)")

    depth_rows = {}
    steps = max(2, len(paths) // args.batch - 1)
    for depth in [int(d) for d in args.depths.split(",")]:
        r = bench_feed(paths, args.target, args.batch, depth, steps)
        depth_rows[depth] = r
        print(f"feed depth={depth}:  {r:8.1f} img/s")

    # eager-dataset feed paths: host fancy-gather + device_put vs the
    # device-resident cache gather, one comparable JSON line
    gather = bench_gather()
    # unified provenance block (bench.telemetry_stamp): schema_version
    # + contention + compile cache + registry counters in one schema
    gather.update(telemetry_stamp(contention=contention))
    print(json.dumps(gather))

    if args.report:
        with open(args.report, "w") as fh:
            fh.write(
                "# Host-feed throughput\n\n"
                f"{args.n} JPEGs {args.size}px -> {args.target}px, batch "
                f"{args.batch} (this machine; see docs/BENCHMARKS.md for "
                "context).\n\n"
                "| path | img/s |\n|---|---|\n"
                + f"| PIL decode+resize | {rows['pil']:.1f} |\n"
                + (f"| native decode+resize | {rows['native']:.1f} |\n"
                   if "native" in rows else "")
                + "".join(
                    f"| feed (prefetch depth {d}) | {r:.1f} |\n"
                    for d, r in depth_rows.items()
                )
                + "".join(
                    f"| native decoder, {t} threads | {r:.1f} |\n"
                    for t, r in thread_rows.items()
                )
                + (f"\nHost CPU count: {os.cpu_count()} — thread scaling "
                   "measured on fewer cores than threads is queueing, not "
                   "parallelism.\n" if thread_rows else "")
            )
        print(f"wrote {args.report}")
    return rows, depth_rows, thread_rows


if __name__ == "__main__":
    main()
