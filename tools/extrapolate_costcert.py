"""Project a full reference-scale search cost from a cost-certification
run (``tools/run_search_refscale.sh costcert``).

The costcert run keeps every per-unit SHAPE production-exact (WRN-40-2,
batch 128, 4,000-sample dataset, 2,400/1,600 fold splits, 5 TTA draws)
but truncates phase-1 depth and the per-fold trial budget so it fits
the CPU host.  This tool reads its ``search_result.json`` and scales
the measured unit costs back to the reference's production shape
(``search.py:211-263``: 5 folds x 200 trials, 200-epoch phase 1),
emitting one JSON line for docs/BENCHMARKS.md:

    python tools/extrapolate_costcert.py search_refscale_costcert \
        [--phase1-epochs-run 2] [--target-epochs 200] \
        [--trials-run 3] [--target-trials 200]

The projection is mechanical (unit cost x count) — phase 2 trials reuse
ONE compiled executable (asserted via tta_executables in the artifact),
so per-trial cost is constant by construction; phase-1 epochs are
likewise constant-cost after the first compile.  The honest caveats:
compile time is amortized differently at full depth (smaller share),
and the audit cost scales with the SELECTED sub-policy count, which a
200-trial search changes — both are called out in the output.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("save_dir")
    p.add_argument("--phase1-epochs-run", type=int, default=2)
    p.add_argument("--target-epochs", type=int, default=200)
    p.add_argument("--trials-run", type=int, default=3)
    p.add_argument("--target-trials", type=int, default=200)
    p.add_argument("--tpu-speedup", type=float, default=None,
                   help="optional measured TPU-vs-this-host step-rate "
                        "ratio; adds a projected TPU-hours figure")
    args = p.parse_args(argv)

    with open(os.path.join(args.save_dir, "search_result.json")) as fh:
        result = json.load(fh)

    p1 = result["tpu_secs_phase1"]
    p2 = result["tpu_secs_phase2"]
    audit = result.get("tpu_secs_audit", 0.0)
    folds = len(result.get("fold_baselines", {})) or 5

    p1_full = p1 * args.target_epochs / max(args.phase1_epochs_run, 1)
    p2_full = p2 * args.target_trials / max(args.trials_run, 1)
    out = {
        "metric": "refscale_search_cost_projection",
        "measured": {
            "phase1_secs": round(p1, 1),
            "phase1_epochs": args.phase1_epochs_run,
            "phase2_secs": round(p2, 1),
            "trials_per_fold": args.trials_run,
            "folds": folds,
            "audit_secs": round(audit, 1),
            "secs_per_trial": round(p2 / max(args.trials_run * folds, 1), 2),
            "tta_executables": result.get("tta_executables"),
            "zero_recompiles": (
                result.get("tta_executables") is not None
                and result.get("tta_executables")
                == result.get("tta_executables_first")
            ),
        },
        "projected_full_host_hours": round(
            (p1_full + p2_full + audit) / 3600.0, 2),
        "projection_basis": {
            "phase1": f"{args.target_epochs} epochs x measured per-epoch cost",
            "phase2": f"{args.target_trials} trials/fold x measured "
                      "per-trial cost (single compiled executable)",
            "audit": "measured as-is (scales with selected sub-policy "
                     "count, which a larger search changes)",
        },
    }
    if args.tpu_speedup:
        out["projected_tpu_hours"] = round(
            out["projected_full_host_hours"] / args.tpu_speedup, 3)
        out["tpu_speedup_basis"] = args.tpu_speedup
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
