"""Project a full reference-scale search cost from a cost-certification
run (``tools/run_search_refscale.sh costcert``).

The costcert run keeps every per-unit SHAPE production-exact (WRN-40-2,
batch 128, 4,000-sample dataset, 2,400/1,600 fold splits, 5 TTA draws)
but truncates phase-1 depth and the per-fold trial budget so it fits
the CPU host.  This tool reads its ``search_result.json`` and scales
the measured unit costs back to the reference's production shape
(``search.py:211-263``: 5 folds x 200 trials, 200-epoch phase 1),
emitting one JSON line for docs/BENCHMARKS.md:

    python tools/extrapolate_costcert.py search_refscale_costcert \
        [--phase1-epochs-run 2] [--target-epochs 200] \
        [--trials-run 3] [--target-trials 200]

The projection is mechanical (unit cost x count) — phase 2 trials reuse
ONE compiled executable (asserted via tta_executables in the artifact),
so per-trial cost is constant by construction; phase-1 epochs are
likewise constant-cost after the first compile.  The honest caveats:
compile time is amortized differently at full depth (smaller share),
and the audit cost scales with the SELECTED sub-policy count, which a
200-trial search changes — both are called out in the output.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _load_result(save_dir: str) -> dict:
    with open(os.path.join(save_dir, "search_result.json")) as fh:
        return json.load(fh)


def _tta_rate(path: str) -> float:
    with open(path) as fh:
        return float(json.load(fh)["tta_images_per_sec"])


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("save_dir")
    p.add_argument("--phase1-epochs-run", type=int, default=2)
    p.add_argument("--target-epochs", type=int, default=200)
    p.add_argument("--trials-run", type=int, default=3)
    p.add_argument("--target-trials", type=int, default=200)
    p.add_argument("--target-folds", type=int, default=5)
    p.add_argument("--tpu-speedup", type=float, default=None,
                   help="optional measured TPU-vs-this-host TRAIN step-"
                        "rate ratio (applied to phase 1); adds a "
                        "projected TPU-hours figure")
    p.add_argument("--fold0-dir", default=None,
                   help="a `run_search_refscale.sh fold0` artifact: one "
                        "fold at production shape with a non-chance "
                        "oracle and an executed audit.  When given, its "
                        "deeper unit costs REPLACE the costcert units "
                        "(the costcert run stays as the shape cross-"
                        "check) — one less stage of extrapolation "
                        "(VERDICT r4 weak 3)")
    p.add_argument("--fold0-epochs", type=int, default=30)
    p.add_argument("--fold0-trials", type=int, default=25)
    p.add_argument("--target-selected-subs", type=int, default=None,
                   help="expected SELECTED sub-policy count at the target "
                        "trial budget (the audit evaluates each selected "
                        "sub alone, so its cost scales with this count, "
                        "not just the fold count — ADVICE r5).  When "
                        "given (with the artifact's own selected count, "
                        "or --fold0-selected-subs), the audit projection "
                        "scales by selected-subs x folds; omitted, the "
                        "projection assumes the measured run's count and "
                        "SAYS SO in projection_basis")
    p.add_argument("--fold0-selected-subs", type=int, default=None,
                   help="override the fold0 artifact's recorded "
                        "num_sub_policies_selected as the audit unit-cost "
                        "divisor")
    p.add_argument("--tta-bench-cpu", default=None,
                   help="tools/bench_tta.py JSON measured on this host")
    p.add_argument("--tta-bench-tpu", default=None,
                   help="tools/bench_tta.py JSON measured on TPU "
                        "(docs/tta_bench_tpu.json); with --tta-bench-cpu "
                        "converts phase-2/audit cost at the MEASURED "
                        "TTA-shape ratio instead of the train-shape one")
    args = p.parse_args(argv)

    result = _load_result(args.save_dir)
    p1 = result["tpu_secs_phase1"]
    p2 = result["tpu_secs_phase2"]
    audit = result.get("tpu_secs_audit", 0.0)
    folds = len(result.get("fold_baselines", {})) or 5

    measured = {
        "phase1_secs": round(p1, 1),
        "phase1_epochs": args.phase1_epochs_run,
        "phase2_secs": round(p2, 1),
        "trials_per_fold": args.trials_run,
        "folds": folds,
        "audit_secs": round(audit, 1),
        "secs_per_trial": round(p2 / max(args.trials_run * folds, 1), 2),
        "tta_executables": result.get("tta_executables"),
        "zero_recompiles": (
            result.get("tta_executables") is not None
            and result.get("tta_executables")
            == result.get("tta_executables_first")
        ),
        "backend": result.get("backend", "unrecorded"),
    }
    # unit costs: costcert defaults, replaced by the deeper fold0
    # measurements when available
    secs_per_epoch_fold = p1 / max(args.phase1_epochs_run * folds, 1)
    secs_per_trial = measured["secs_per_trial"]
    audit_secs = audit
    unit_source = "costcert (2-epoch oracles, audit borrowed)"
    out = {"metric": "refscale_search_cost_projection", "measured": measured}
    audit_subs_measured = None
    if args.fold0_dir:
        f0 = _load_result(args.fold0_dir)
        f0_p1, f0_p2 = f0["tpu_secs_phase1"], f0["tpu_secs_phase2"]
        f0_audit = f0.get("tpu_secs_audit", 0.0)
        secs_per_epoch_fold = f0_p1 / max(args.fold0_epochs, 1)
        secs_per_trial = f0_p2 / max(args.fold0_trials, 1)
        # audit cost scales with folds x SELECTED sub-policies (each
        # selected sub is scored alone on every gated fold); the fold
        # count is known, the selected count at a 200-trial budget is
        # not — project it when the caller supplies an expectation,
        # otherwise assume the measured count and record the assumption
        # (ADVICE r5: the old folds-only scaling was silently optimistic)
        audit_subs_measured = (args.fold0_selected_subs
                               or f0.get("num_sub_policies_selected"))
        audit_secs = f0_audit * args.target_folds
        if args.target_selected_subs and audit_subs_measured:
            audit_secs *= args.target_selected_subs / audit_subs_measured
        unit_source = (
            f"fold0 depth run ({args.fold0_epochs}-epoch oracle, "
            f"{args.fold0_trials} trials, audit EXECUTED)")
        out["measured_fold0"] = {
            "phase1_secs": round(f0_p1, 1),
            "secs_per_epoch": round(secs_per_epoch_fold, 2),
            "phase2_secs": round(f0_p2, 1),
            "secs_per_trial": round(secs_per_trial, 2),
            "audit_secs": round(f0_audit, 1),
            "audit_selected_subs": audit_subs_measured,
            "oracle_baseline": f0.get("fold_baselines", {}).get("0"),
            "backend": f0.get("backend", "unrecorded"),
        }

    if not args.fold0_dir:
        audit_basis = (
            "costcert run's audit cost carried over UNSCALED (its audit ran "
            "over the truncated search's selected subs on its own folds) — "
            "both the fold count and the selected-sub-policy count at the "
            "target budget are unmodeled here; prefer --fold0-dir with "
            "--target-selected-subs for a defensible audit term")
    elif args.target_selected_subs and audit_subs_measured:
        audit_basis = (
            f"measured audit cost x {args.target_folds} folds x "
            f"({args.target_selected_subs} expected selected subs / "
            f"{audit_subs_measured} measured)")
    else:
        audit_basis = (
            f"measured audit cost x {args.target_folds} folds, ASSUMING the "
            "selected-sub-policy count stays at the measured run's"
            + (f" ({audit_subs_measured})" if audit_subs_measured else "")
            + " — a full trial budget typically selects more subs, so this "
              "term is optimistic; pass --target-selected-subs to scale it")

    p1_full = secs_per_epoch_fold * args.target_epochs * args.target_folds
    p2_full = secs_per_trial * args.target_trials * args.target_folds
    out["projected_full_host_hours"] = round(
        (p1_full + p2_full + audit_secs) / 3600.0, 2)
    out["projection_basis"] = {
        "unit_source": unit_source,
        "phase1": f"{args.target_folds} folds x {args.target_epochs} epochs "
                  "x measured per-epoch cost",
        "phase2": f"{args.target_folds} folds x {args.target_trials} trials "
                  "x measured per-trial cost (single compiled executable)",
        "audit": audit_basis,
    }
    if args.tpu_speedup:
        # train-shape ratio for phase 1; TTA-shape ratio for phase 2 +
        # audit when both bench_tta samples exist, else train-shape
        tta_ratio = args.tpu_speedup
        tta_basis = "train-shape ratio (no TTA-shape sample)"
        if args.tta_bench_cpu and args.tta_bench_tpu:
            tta_ratio = _tta_rate(args.tta_bench_tpu) / _tta_rate(
                args.tta_bench_cpu)
            tta_basis = "measured TTA-shape images/sec ratio"
        out["projected_tpu_hours"] = round(
            (p1_full / args.tpu_speedup
             + (p2_full + audit_secs) / tta_ratio) / 3600.0, 3)
        out["tpu_speedup_basis"] = {
            "phase1_train_shape": args.tpu_speedup,
            "phase2_audit_tta_shape": round(tta_ratio, 1),
            "tta_shape_source": tta_basis,
        }
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
