"""Benchmark the in-tree TPE on the REAL 30-D policy search space.

VERDICT round 1 (weak 4): the TPE had only been validated on a 2-D
quadratic.  This tool runs it on the actual space the search uses —
``make_search_space(5, 2)``: 10 x choice(15) + 20 x U(0,1) — against a
planted-policy synthetic reward, and compares best-so-far curves with
pure random search over many seeds.  (HyperOpt itself is not available
in this zero-egress image, and installs are forbidden; random search is
the standard no-model control — TPE earning a clear margin over it on
this space is the property phase 2 relies on.)

Reward (search-shaped by construction, like the density-matching
objective): a hidden target policy is planted; each (sub-policy, op)
slot scores partial credit — op-identity match (the categorical part)
gated with Gaussian closeness of prob and level (the continuous part) —
plus observation noise.  Flat elsewhere, multi-modal across slots,
mixed categorical/continuous: the properties that break naive
optimizers.

    python tools/bench_tpe.py --runs 20 --trials 200 \
        --report docs/tpe_benchmark.md
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fast_autoaugment_tpu.search.driver import make_search_space  # noqa: E402
from fast_autoaugment_tpu.search.tpe import TPE  # noqa: E402

NUM_POLICY, NUM_OP, NUM_OPS = 5, 2, 15


def plant_target(rng) -> dict:
    t = {}
    for i in range(NUM_POLICY):
        for j in range(NUM_OP):
            t[f"policy_{i}_{j}"] = int(rng.integers(0, NUM_OPS))
            t[f"prob_{i}_{j}"] = float(rng.uniform())
            t[f"level_{i}_{j}"] = float(rng.uniform())
    return t


def make_reward(target: dict, noise: float, rng):
    """Partial-credit closeness to the planted policy, in [0, ~1].
    Returns (observed_fn, true_fn): observed adds N(0, noise) per
    evaluation; true is the noiseless value."""

    def true_fn(x: dict) -> float:
        s = 0.0
        for i in range(NUM_POLICY):
            for j in range(NUM_OP):
                if x[f"policy_{i}_{j}"] == target[f"policy_{i}_{j}"]:
                    dp = x[f"prob_{i}_{j}"] - target[f"prob_{i}_{j}"]
                    dl = x[f"level_{i}_{j}"] - target[f"level_{i}_{j}"]
                    s += float(np.exp(-0.5 * (dp / 0.2) ** 2)
                               * np.exp(-0.5 * (dl / 0.2) ** 2))
        return s / (NUM_POLICY * NUM_OP)

    def observed_fn(x: dict) -> float:
        return true_fn(x) + float(rng.normal(0, noise))

    return observed_fn, true_fn


def driver_n_startup(trials: int) -> int:
    """The startup rule phase 2 uses (search/driver.py): hyperopt's 20
    at reference budgets, proportional at small ones."""
    return min(20, max(5, trials // 4))


def bench_ask_tell_latency(ks=(1, 4, 16), warm_obs: int = 60,
                           reps: int = 30, seed: int = 0) -> list[dict]:
    """Host-side ask/tell latency per batch size K on the real 30-D
    policy space — the OVERLAP HEADROOM number the async pipeline bench
    cites (``tools/bench_pipeline.py``): every millisecond the learner
    spends in ``ask``/``tell`` is a millisecond the serial scheduler
    holds the device idle, and exactly what ``--async-pipeline on``
    hides behind the in-flight dispatch.

    The TPE is warmed past its startup phase with `warm_obs` planted-
    reward observations (the posterior path is the expensive one: good/
    bad split + Parzen scoring per dimension), then `reps` ask/tell
    round trips are timed per K.  Pure host math — no JAX, no device."""
    import time

    rng = np.random.default_rng((seed, 1))
    target = plant_target(np.random.default_rng((seed, 2)))
    observed_fn, _true = make_reward(target, 0.05, rng)
    space = make_search_space(NUM_POLICY, NUM_OP)
    rows = []
    for k in ks:
        opt = TPE(space, seed=seed, n_startup=driver_n_startup(200))
        for _ in range(warm_obs):
            x = opt._random_sample()
            opt.tell(x, observed_fn(x))
        ask_secs = np.empty(reps)
        tell_secs = np.empty(reps)
        for r in range(reps):
            t0 = time.perf_counter()
            ps = opt.ask(k)
            t1 = time.perf_counter()
            opt.tell_batch(ps, [observed_fn(p) for p in ps])
            t2 = time.perf_counter()
            ask_secs[r] = t1 - t0
            tell_secs[r] = t2 - t1
        rows.append({
            "k": int(k),
            "warm_obs": int(warm_obs),
            "reps": int(reps),
            "ask_ms_mean": round(float(ask_secs.mean()) * 1e3, 4),
            "ask_ms_p99": round(float(np.percentile(ask_secs, 99)) * 1e3, 4),
            "ask_ms_per_trial": round(
                float(ask_secs.mean()) * 1e3 / k, 4),
            "tell_ms_mean": round(float(tell_secs.mean()) * 1e3, 4),
            "asks_per_sec": round(1.0 / float(ask_secs.mean()), 2),
        })
    return rows


def run_strategy(strategy: str, trials: int, seed: int, noise: float,
                 n_startup: int | None = None) -> np.ndarray:
    """TRUE reward of the incumbent (best-by-OBSERVED) after each trial.

    Under observation noise, best-so-far *observed* reward is inflated
    by lucky noise draws; what phase 2 actually consumes is the ranking
    by observed reward (top-N selection, search.py:253-259), so the
    honest quality metric is the noiseless value of the trial the
    optimizer would rank first."""
    rng = np.random.default_rng((seed, 1))  # observation noise
    # distinct stream from TPE(seed=seed)'s sampler — identical streams
    # would make the first random proposal BE the planted target
    target = plant_target(np.random.default_rng((seed, 2)))
    observed_fn, true_fn = make_reward(target, noise, rng)
    space = make_search_space(NUM_POLICY, NUM_OP)
    opt = TPE(space, seed=seed,
              n_startup=n_startup if n_startup is not None
              else driver_n_startup(trials))
    curve = np.empty(trials)
    best_obs, best_true = -np.inf, 0.0
    for t in range(trials):
        x = opt._random_sample() if strategy == "random" else opt.suggest()
        r = observed_fn(x)
        opt.tell(x, r)
        if r > best_obs:
            best_obs, best_true = r, true_fn(x)
        curve[t] = best_true
    return curve


def run_cell(trials: int, noise: float, runs: int):
    """(wins, gain, means) for one (budget, noise) cell over paired seeds."""
    finals = {}
    for strat in ("random", "tpe"):
        finals[strat] = np.array([
            run_strategy(strat, trials, seed, noise)[-1]
            for seed in range(runs)
        ])
    wins = int((finals["tpe"] > finals["random"]).sum())
    ties = int((finals["tpe"] == finals["random"]).sum())
    gain = float(finals["tpe"].mean() - finals["random"].mean())
    return {
        "trials": trials, "noise": noise, "wins": wins, "ties": ties,
        "runs": runs, "gain": gain,
        "random_mean": float(finals["random"].mean()),
        "random_std": float(finals["random"].std()),
        "tpe_mean": float(finals["tpe"].mean()),
        "tpe_std": float(finals["tpe"].std()),
    }


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--runs", type=int, default=20)
    p.add_argument("--trials", type=int, nargs="+", default=[60, 200],
                   help="budgets to test (60 = the e2e validation's, "
                        "200 = the reference's, search.py:230)")
    p.add_argument("--noise", type=float, nargs="+", default=[0.02, 0.05, 0.1],
                   help="observation-noise sigmas (0.05-0.1 matches the "
                        "round-2 fold-TTA spread; VERDICT round 2 weak 4)")
    p.add_argument("--latency-ks", type=int, nargs="+", default=[1, 4, 16],
                   help="batch sizes for the host-side ask/tell latency "
                        "rows (the pipeline bench's overlap-headroom "
                        "citation)")
    p.add_argument("--report", default=None)
    args = p.parse_args(argv)

    # loadavg/process provenance, shared with bench.py (VERDICT r5
    # weak 1): TPE cells are pure-host timing-free quality numbers, but
    # the committed report is still a capture artifact — stamp it, and
    # honor FAA_BENCH_REQUIRE_QUIET=1 like every other bench tool
    from bench import (
        host_contention_stamp,
        refuse_or_flag_contention,
        telemetry_stamp,
    )

    contention = refuse_or_flag_contention(host_contention_stamp())
    print(f"contention: {json.dumps(contention)}")

    # host-side ask/tell latency per K: the overlap-headroom numbers
    # the async pipeline bench cites (one JSON line, machine-readable;
    # unified provenance via bench.telemetry_stamp)
    latency = bench_ask_tell_latency(ks=tuple(args.latency_ks))
    print("tpe_latency: " + json.dumps(
        {**telemetry_stamp(contention=contention), "rows": latency}))
    for row in latency:
        print(f"  K={row['k']}: ask {row['ask_ms_mean']:.2f} ms "
              f"(p99 {row['ask_ms_p99']:.2f}, "
              f"{row['ask_ms_per_trial']:.2f}/trial), "
              f"tell {row['tell_ms_mean']:.3f} ms")

    cells = []
    for trials in args.trials:
        for noise in args.noise:
            cell = run_cell(trials, noise, args.runs)
            cells.append(cell)
            print(f"trials={trials} noise={noise}: tpe {cell['tpe_mean']:.4f}"
                  f"±{cell['tpe_std']:.4f} vs random {cell['random_mean']:.4f}"
                  f"±{cell['random_std']:.4f} — wins {cell['wins']}/{args.runs}"
                  f" (ties {cell['ties']}), gain {cell['gain']:+.4f}")

    if args.report:
        lines = [
            "# In-tree TPE vs random search — 30-D policy space",
            "",
            "Planted-policy synthetic reward on the real search space",
            f"(10 x choice(15) + 20 x U(0,1)); {args.runs} paired seeds per",
            "cell.  The metric is the TRUE (noiseless) reward of the",
            "incumbent — the trial the optimizer ranks first by observed",
            "reward — because top-N selection by noisy observed reward is",
            "exactly what phase 2 consumes (search.py:253-259); best-so-far",
            "OBSERVED reward would be inflated by lucky noise draws.",
            "`n_startup` follows the driver rule min(20, max(5, trials/4)).",
            "HyperOpt is unavailable in this image (zero-egress, installs",
            "forbidden), so the control is pure random search — see",
            "`tools/bench_tpe.py` docstring.",
            "",
            "| budget | noise σ | random (mean±std) | tpe (mean±std) | gain | tpe wins |",
            "|---|---|---|---|---|---|",
        ]
        for c in cells:
            lines.append(
                f"| {c['trials']} | {c['noise']} "
                f"| {c['random_mean']:.4f}±{c['random_std']:.4f} "
                f"| {c['tpe_mean']:.4f}±{c['tpe_std']:.4f} "
                f"| {c['gain']:+.4f} | {c['wins']}/{c['runs']} |"
            )
        lines += [
            "",
            "The 60-trial rows are the budget the synthetic-shapes e2e",
            "validation actually runs; the 200-trial rows are the",
            "reference's production budget.",
            "",
            f"Capture contention stamp: `{json.dumps(contention)}`.",
        ]
        with open(args.report, "w") as fh:
            fh.write("\n".join(lines) + "\n")
        print(f"wrote {args.report}")

    return cells


if __name__ == "__main__":
    cells = main()
    print(json.dumps([{k: (round(v, 4) if isinstance(v, float) else v)
                       for k, v in c.items()} for c in cells]))
