"""Benchmark the in-tree TPE on the REAL 30-D policy search space.

VERDICT round 1 (weak 4): the TPE had only been validated on a 2-D
quadratic.  This tool runs it on the actual space the search uses —
``make_search_space(5, 2)``: 10 x choice(15) + 20 x U(0,1) — against a
planted-policy synthetic reward, and compares best-so-far curves with
pure random search over many seeds.  (HyperOpt itself is not available
in this zero-egress image, and installs are forbidden; random search is
the standard no-model control — TPE earning a clear margin over it on
this space is the property phase 2 relies on.)

Reward (search-shaped by construction, like the density-matching
objective): a hidden target policy is planted; each (sub-policy, op)
slot scores partial credit — op-identity match (the categorical part)
gated with Gaussian closeness of prob and level (the continuous part) —
plus observation noise.  Flat elsewhere, multi-modal across slots,
mixed categorical/continuous: the properties that break naive
optimizers.

    python tools/bench_tpe.py --runs 20 --trials 200 \
        --report docs/tpe_benchmark.md
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fast_autoaugment_tpu.search.driver import make_search_space  # noqa: E402
from fast_autoaugment_tpu.search.tpe import TPE  # noqa: E402

NUM_POLICY, NUM_OP, NUM_OPS = 5, 2, 15


def plant_target(rng) -> dict:
    t = {}
    for i in range(NUM_POLICY):
        for j in range(NUM_OP):
            t[f"policy_{i}_{j}"] = int(rng.integers(0, NUM_OPS))
            t[f"prob_{i}_{j}"] = float(rng.uniform())
            t[f"level_{i}_{j}"] = float(rng.uniform())
    return t


def make_reward(target: dict, noise: float, rng):
    """Partial-credit closeness to the planted policy, in [0, ~1]."""

    def reward(x: dict) -> float:
        s = 0.0
        for i in range(NUM_POLICY):
            for j in range(NUM_OP):
                if x[f"policy_{i}_{j}"] == target[f"policy_{i}_{j}"]:
                    dp = x[f"prob_{i}_{j}"] - target[f"prob_{i}_{j}"]
                    dl = x[f"level_{i}_{j}"] - target[f"level_{i}_{j}"]
                    s += float(np.exp(-0.5 * (dp / 0.2) ** 2)
                               * np.exp(-0.5 * (dl / 0.2) ** 2))
        return s / (NUM_POLICY * NUM_OP) + float(rng.normal(0, noise))

    return reward


def run_strategy(strategy: str, trials: int, seed: int, noise: float) -> np.ndarray:
    """Best-so-far reward curve for one run."""
    rng = np.random.default_rng((seed, 1))  # observation noise
    # distinct stream from TPE(seed=seed)'s sampler — identical streams
    # would make the first random proposal BE the planted target
    target = plant_target(np.random.default_rng((seed, 2)))
    reward_fn = make_reward(target, noise, rng)
    space = make_search_space(NUM_POLICY, NUM_OP)
    opt = TPE(space, seed=seed)
    curve = np.empty(trials)
    best = -np.inf
    for t in range(trials):
        x = opt._random_sample() if strategy == "random" else opt.suggest()
        r = reward_fn(x)
        opt.tell(x, r)
        best = max(best, r)
        curve[t] = best
    return curve


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--runs", type=int, default=20)
    p.add_argument("--trials", type=int, default=200)
    p.add_argument("--noise", type=float, default=0.02)
    p.add_argument("--report", default=None)
    args = p.parse_args(argv)

    marks = [m for m in (25, 50, 100, 150, 200, args.trials) if m <= args.trials]
    marks = sorted(set(marks))
    curves = {}
    for strat in ("random", "tpe"):
        runs = np.stack([
            run_strategy(strat, args.trials, seed, args.noise)
            for seed in range(args.runs)
        ])
        curves[strat] = runs
        print(f"{strat}: " + "  ".join(
            f"@{m}={runs[:, m - 1].mean():.4f}±{runs[:, m - 1].std():.4f}"
            for m in marks
        ))

    wins = int((curves["tpe"][:, -1] > curves["random"][:, -1]).sum())
    final_gain = curves["tpe"][:, -1].mean() - curves["random"][:, -1].mean()
    print(f"tpe wins {wins}/{args.runs} paired seeds; "
          f"final mean gain {final_gain:+.4f}")

    if args.report:
        lines = [
            "# In-tree TPE vs random search — 30-D policy space",
            "",
            "Planted-policy synthetic reward on the real search space",
            f"(10 x choice(15) + 20 x U(0,1)); {args.runs} seeds x "
            f"{args.trials} trials; observation noise sigma={args.noise}.",
            "HyperOpt is unavailable in this image (zero-egress, installs",
            "forbidden), so the control is pure random search — see",
            "`tools/bench_tpe.py` docstring.",
            "",
            "| trials | " + " | ".join(["random (mean±std)", "tpe (mean±std)", "gain"]) + " |",
            "|---|---|---|---|",
        ]
        for m in marks:
            r = curves["random"][:, m - 1]
            t = curves["tpe"][:, m - 1]
            lines.append(
                f"| {m} | {r.mean():.4f}±{r.std():.4f} "
                f"| {t.mean():.4f}±{t.std():.4f} | {t.mean() - r.mean():+.4f} |"
            )
        lines += [
            "",
            f"TPE wins {wins}/{args.runs} paired seeds at the final trial; "
            f"final mean gain {final_gain:+.4f}.",
        ]
        with open(args.report, "w") as fh:
            fh.write("\n".join(lines) + "\n")
        print(f"wrote {args.report}")

    return {"wins": wins, "runs": args.runs, "final_gain": float(final_gain),
            "marks": {str(m): [float(curves[s][:, m - 1].mean())
                               for s in ("random", "tpe")] for m in marks}}


if __name__ == "__main__":
    out = main()
    print(json.dumps({"wins": out["wins"], "runs": out["runs"],
                      "final_gain": round(out["final_gain"], 4)}))
