"""Reproduce the reference's published-checkpoint numbers.

The reference README publishes 13 trained checkpoints with their test
top-1 errors (reference ``README.md:20-52``; machine-readable bracket in
``BASELINE.md``).  This tool holds that table as a MANIFEST — published
filename -> (model conf, dataset, expected top-1 error %) — and, for
every manifest file present under ``--ckpt-dir``, runs the full
import + ``--only-eval`` pipeline and compares the measured error
against the published number:

    python tools/reproduce_checkpoints.py --ckpt-dir /ckpts \
        --dataroot /data --report docs/repro_report.md

Files that are absent are skipped (the build environment is zero-egress;
drop whatever .pth files you have into --ckpt-dir).  Exit code is 1 if
any evaluated checkpoint misses its expected error by more than --tol
percentage points.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# name -> (model conf, dataset, expected top-1 error %, imgsize override)
# expected = the published checkpoint's own error where the filename
# records one, else the paper's direct-search number (README.md:20-52)
MANIFEST: dict[str, dict] = {
    "cifar10_wresnet40x2_top1_3.52.pth": {
        "model": {"type": "wresnet40_2"}, "dataset": "cifar10", "expected": 3.52},
    "cifar10_wresnet28x10_top1.pth": {
        "model": {"type": "wresnet28_10"}, "dataset": "cifar10", "expected": 2.7},
    "cifar10_shake26_2x32d_top1_2.68.pth": {
        "model": {"type": "shakeshake26_2x32d"}, "dataset": "cifar10", "expected": 2.68},
    "cifar10_shake26_2x96d_top1_1.97.pth": {
        "model": {"type": "shakeshake26_2x96d"}, "dataset": "cifar10", "expected": 1.97},
    "cifar10_shake26_2x112d_top1_2.04.pth": {
        "model": {"type": "shakeshake26_2x112d"}, "dataset": "cifar10", "expected": 2.04},
    "cifar10_pyramid272_top1_1.44.pth": {
        "model": {"type": "pyramid", "depth": 272, "alpha": 200, "bottleneck": True},
        "dataset": "cifar10", "expected": 1.44},
    "cifar100_wresnet40x2_top1_20.43.pth": {
        "model": {"type": "wresnet40_2"}, "dataset": "cifar100", "expected": 20.43},
    "cifar100_wresnet28x10_top1_17.17.pth": {
        "model": {"type": "wresnet28_10"}, "dataset": "cifar100", "expected": 17.17},
    "cifar100_shake26_2x96d_top1_15.15.pth": {
        "model": {"type": "shakeshake26_2x96d"}, "dataset": "cifar100", "expected": 15.15},
    "cifar100_pyramid272_top1_11.74.pth": {
        "model": {"type": "pyramid", "depth": 272, "alpha": 200, "bottleneck": True},
        "dataset": "cifar100", "expected": 11.74},
    "imagenet_resnet50_top1_22.2.pth": {
        "model": {"type": "resnet50"}, "dataset": "imagenet", "expected": 22.2},
    "imagenet_resnet200_top1_19.4.pth": {
        "model": {"type": "resnet200"}, "dataset": "imagenet", "expected": 19.4,
        "imgsize": 320},
    "imagenet_resnet200_res224.pth": {
        "model": {"type": "resnet200"}, "dataset": "imagenet", "expected": 20.0},
}


def evaluate_checkpoint(pth: str, entry: dict, dataroot: str, work_dir: str,
                        batch: int = 64) -> dict:
    """Import one .pth and run --only-eval; returns the result row."""
    from import_checkpoint import main as import_main

    from fast_autoaugment_tpu.core.config import Config
    from fast_autoaugment_tpu.train.trainer import train_and_eval

    model_conf = dict(entry["model"])
    out = os.path.join(
        work_dir, os.path.basename(pth).replace(".pth", ".msgpack"))
    import_args = ["--pth", pth, "--model", model_conf["type"],
                   "--dataset", entry["dataset"], "--out", out]
    import_main(import_args)

    conf = Config({
        "model": model_conf,
        "dataset": entry["dataset"],
        "aug": "default",
        "batch": batch,
        "epoch": 1,
        "lr": 0.1,
        "lr_schedule": {"type": "cosine", "warmup": {"multiplier": 1, "epoch": 0}},
        "optimizer": {"type": "sgd", "decay": 0.0, "momentum": 0.9,
                      "nesterov": True},
        **({"imgsize": entry["imgsize"]} if "imgsize" in entry else {}),
    })
    result = train_and_eval(conf, dataroot, save_path=out, only_eval=True,
                            metric="last")
    err = (1.0 - float(result["top1_test"])) * 100.0
    return {
        "file": os.path.basename(pth),
        "model": model_conf["type"],
        "dataset": entry["dataset"],
        "expected_err": entry["expected"],
        "measured_err": round(err, 2),
        "delta": round(err - entry["expected"], 2),
    }


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--ckpt-dir", required=True)
    p.add_argument("--dataroot", required=True)
    p.add_argument("--work-dir", default=None,
                   help="where imported .msgpack files go (default: ckpt-dir)")
    p.add_argument("--batch", type=int, default=64)
    p.add_argument("--tol", type=float, default=0.2,
                   help="allowed |measured - expected| in percentage points")
    p.add_argument("--report", default=None, help="markdown report path")
    args = p.parse_args(argv)

    work = args.work_dir or args.ckpt_dir
    os.makedirs(work, exist_ok=True)
    rows, missing = [], []
    for name, entry in MANIFEST.items():
        pth = os.path.join(args.ckpt_dir, name)
        if not os.path.exists(pth):
            missing.append(name)
            continue
        print(f"== {name}", flush=True)
        rows.append(evaluate_checkpoint(pth, entry, args.dataroot, work,
                                        batch=args.batch))
        print(json.dumps(rows[-1]), flush=True)

    lines = [
        "| checkpoint | model | dataset | expected err% | measured err% | delta |",
        "|---|---|---|---|---|---|",
    ]
    for r in rows:
        lines.append(
            f"| {r['file']} | {r['model']} | {r['dataset']} | "
            f"{r['expected_err']} | {r['measured_err']} | {r['delta']:+.2f} |"
        )
    table = "\n".join(lines)
    print(table)
    if missing:
        print(f"({len(missing)} manifest checkpoints not present, skipped)")
    if args.report:
        with open(args.report, "w") as fh:
            fh.write(
                "# Published-checkpoint reproduction\n\n"
                "Reference README download table vs this framework's "
                "import + `--only-eval` (reference ``README.md:20-52``).\n\n"
                + table + "\n\n"
                + (f"Skipped (not on disk): {', '.join(missing)}\n" if missing else "")
            )

    bad = [r for r in rows if abs(r["delta"]) > args.tol]
    if bad:
        print(f"FAIL: {len(bad)} checkpoint(s) outside ±{args.tol}pp")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
