#!/usr/bin/env python
"""Compatibility shim over ``tools/faalint`` — the robustness lint's
historical entry point.

The rules R1–R8 that lived here migrated into the faalint framework as
pluggable passes (``tools/faalint/rules_robustness.py``); this module
keeps the legacy surface stable:

* ``check_source(src, relpath, *_scope=...)`` — lint one source string
  with the LEGACY rule set (R1–R8 only) and the same scope-forcing
  keywords the rule-matrix tests use.
* ``lint_tree()`` — the full-repo gate.  This now runs the COMPLETE
  faalint rule set (R1–R9, C1–C3, D1–D3, T1–T3 + suppression/baseline
  hygiene): ``make lint-robust`` is an alias for ``make lint``.
* ``main()`` — delegates to the faalint CLI.

See docs/STATIC_ANALYSIS.md for the rule catalog; the per-rule
rationale that used to live in this docstring moved there.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from faalint import engine as _engine  # noqa: E402
from faalint.engine import (  # noqa: E402,F401 — legacy re-exports
    ARTIFACT_DIRS, BLOCKING_DIRS, JIT_SEAM_DIRS, LEGACY_RULE_IDS, PACKAGE,
    REPO, SEARCH_BLOCKING_DIRS, SERVE_BLOCKING_DIRS, TIMING_SEAM_DIRS,
    Finding)


def check_source(src: str, relpath: str,
                 artifact_scope: bool | None = None,
                 blocking_scope: bool | None = None,
                 jit_scope: bool | None = None,
                 serve_scope: bool | None = None,
                 search_scope: bool | None = None,
                 timing_scope: bool | None = None) -> list[Finding]:
    """Lint one file's source with the legacy R1–R8 rule set.  Each
    ``*_scope`` kwarg forces that rule family on/off (None = derive
    from `relpath`), exactly as before the faalint migration."""
    overrides = {
        "artifact": artifact_scope,
        "blocking": blocking_scope,
        "jit": jit_scope,
        "serve": serve_scope,
        "search": search_scope,
        "timing": timing_scope,
    }
    return _engine.check_source(src, relpath, overrides=overrides,
                                rule_ids=LEGACY_RULE_IDS)


def lint_tree(root: str = REPO) -> list[Finding]:
    """The full-repo gate — now the complete faalint rule set (the
    robustness rules plus the concurrency/dispatch/determinism passes
    and the suppression/baseline hygiene checks)."""
    findings = _engine.lint_tree(root)
    return [f for f in findings if not f.baselined]


def main(argv=None) -> int:
    from faalint.cli import main as faalint_main

    return faalint_main(argv)


if __name__ == "__main__":
    sys.exit(main())
