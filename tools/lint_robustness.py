#!/usr/bin/env python
"""AST robustness lint for the resilience contract (docs/RESILIENCE.md).

Three rules, each a failure-handling discipline the resilience
subsystem depends on:

R1  **no bare ``except:``** anywhere in the package — a bare except
    swallows KeyboardInterrupt/SystemExit and (worse here) the typed
    PreemptedError/CheckpointCorruptError signals the recovery paths
    route on.

R2  **no swallowed broad excepts**: an ``except Exception`` /
    ``except BaseException`` handler must log (``logger.*``,
    ``logging.*``, ``warnings.warn``) or re-``raise`` — silently eating
    unknown failures is how a production stack loses its only evidence.

R3  **no direct run-artifact writes**: inside the run-artifact layers
    (``core/``, ``search/``, ``train/``, ``launch/``), ``json.dump``
    and write-mode ``open(...)`` are reserved to the atomic helpers
    (``write_json_atomic``, ``save_checkpoint``) — a bare write torn by
    a crash is exactly the corruption the restore chain exists to
    survive.  Append-mode logs and reads are fine.

Suppress a finding (sparingly, with a reason nearby) by putting
``robust: allow`` in a comment on the offending line.

Exit status: 0 clean, 1 findings (printed one per line,
``path:line: rule message``).  Wired as ``make lint-robust`` and run in
``make test-t1``'s preamble.
"""

from __future__ import annotations

import ast
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE = "fast_autoaugment_tpu"

# R3 scope: the layers that write run artifacts (checkpoints, trial
# logs, result JSONs).  utils/ (ScalarWriter's append-mode JSONL,
# tb_events' event files) and data/ (dataset downloads) are excluded —
# their files are streams/caches, not resumable run state.
ARTIFACT_DIRS = ("core", "search", "train", "launch")

# (relative module path suffix, function name) pairs allowed to write
# directly: THE atomic helpers themselves.
ARTIFACT_WRITERS = {
    ("core/checkpoint.py", "save_checkpoint"),
    ("search/driver.py", "write_json_atomic"),
}

_LOG_NAMES = {"logger", "logging", "log", "warnings"}
_LOG_METHODS = {"debug", "info", "warning", "warn", "error", "exception",
                "critical", "fatal"}


class Finding:
    def __init__(self, path: str, line: int, rule: str, msg: str):
        self.path, self.line, self.rule, self.msg = path, line, rule, msg

    def __repr__(self):
        return f"{self.path}:{self.line}: {self.rule} {self.msg}"


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    names = []
    if isinstance(t, ast.Name):
        names = [t.id]
    elif isinstance(t, ast.Tuple):
        names = [e.id for e in t.elts if isinstance(e, ast.Name)]
    return any(n in ("Exception", "BaseException") for n in names)


def _handles_failure(handler: ast.ExceptHandler) -> bool:
    """True when the handler body logs, re-raises, or captures the
    bound exception value (``except ... as e: err.append(e)`` — the
    propagate-through-a-channel pattern); swallowed means the failure
    is DISCARDED with no evidence."""
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if handler.name and isinstance(node, ast.Name) \
                and node.id == handler.name \
                and isinstance(node.ctx, ast.Load):
            return True
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute):
                base = f.value
                if isinstance(base, ast.Name) and (
                        base.id in _LOG_NAMES
                        or base.id.startswith("log")) \
                        and f.attr in _LOG_METHODS | {"warn"}:
                    return True
                if isinstance(base, ast.Name) and base.id == "warnings" \
                        and f.attr == "warn":
                    return True
    return False


def _write_mode(call: ast.Call) -> str | None:
    """The mode string of an ``open`` call if it writes, else None."""
    mode = None
    if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant) \
            and isinstance(call.args[1].value, str):
        mode = call.args[1].value
    for kw in call.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant) \
                and isinstance(kw.value.value, str):
            mode = kw.value.value
    if mode and ("w" in mode or "x" in mode or "+" in mode):
        return mode
    return None


def check_source(src: str, relpath: str,
                 artifact_scope: bool | None = None) -> list[Finding]:
    """Lint one file's source.  `artifact_scope` forces R3 on/off
    (None = derive from `relpath`)."""
    findings: list[Finding] = []
    lines = src.splitlines()

    def allowed(lineno: int) -> bool:
        return 0 < lineno <= len(lines) and "robust: allow" in lines[lineno - 1]

    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [Finding(relpath, e.lineno or 0, "R0", f"syntax error: {e.msg}")]

    if artifact_scope is None:
        norm = relpath.replace(os.sep, "/")
        artifact_scope = any(
            f"/{d}/" in f"/{norm}" or norm.startswith(f"{d}/")
            for d in (f"{PACKAGE}/{a}" for a in ARTIFACT_DIRS))

    # enclosing-function map for the R3 allowlist
    func_of: dict[int, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for child in ast.walk(node):
                if hasattr(child, "lineno"):
                    func_of.setdefault(child.lineno, node.name)

    norm = relpath.replace(os.sep, "/")

    def is_allowlisted_writer(lineno: int) -> bool:
        fn = func_of.get(lineno, "")
        return any(norm.endswith(suffix) and fn == name
                   for suffix, name in ARTIFACT_WRITERS)

    for node in ast.walk(tree):
        if isinstance(node, ast.ExceptHandler):
            if allowed(node.lineno):
                continue
            if node.type is None:
                findings.append(Finding(
                    relpath, node.lineno, "R1",
                    "bare `except:` swallows SystemExit/KeyboardInterrupt "
                    "and the typed resilience signals — name the "
                    "exceptions"))
            elif _is_broad(node) and not _handles_failure(node):
                findings.append(Finding(
                    relpath, node.lineno, "R2",
                    "broad `except Exception` neither logs nor re-raises "
                    "— a swallowed failure leaves no evidence"))
        elif artifact_scope and isinstance(node, ast.Call):
            if allowed(node.lineno) or is_allowlisted_writer(node.lineno):
                continue
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr == "dump" \
                    and isinstance(f.value, ast.Name) and f.value.id == "json":
                findings.append(Finding(
                    relpath, node.lineno, "R3",
                    "direct json.dump to a run artifact — use "
                    "write_json_atomic (fsync + rename) so a crash "
                    "cannot tear the file"))
            elif isinstance(f, ast.Name) and f.id == "open":
                mode = _write_mode(node)
                if mode:
                    findings.append(Finding(
                        relpath, node.lineno, "R3",
                        f"direct open(..., {mode!r}) write to a run "
                        "artifact — route through write_json_atomic / "
                        "save_checkpoint"))
    return findings


def lint_tree(root: str = REPO) -> list[Finding]:
    findings: list[Finding] = []
    pkg_root = os.path.join(root, PACKAGE)
    for dirpath, _dirnames, filenames in os.walk(pkg_root):
        if "__pycache__" in dirpath:
            continue
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            rel = os.path.relpath(path, root)
            with open(path) as fh:
                findings.extend(check_source(fh.read(), rel))
    return findings


def main(argv=None) -> int:
    findings = lint_tree()
    for f in findings:
        print(f)
    if findings:
        print(f"lint-robust: {len(findings)} finding(s)", file=sys.stderr)
        return 1
    print("lint-robust: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
