#!/usr/bin/env python
"""AST robustness lint for the resilience contract (docs/RESILIENCE.md).

Three rules, each a failure-handling discipline the resilience
subsystem depends on:

R1  **no bare ``except:``** anywhere in the package — a bare except
    swallows KeyboardInterrupt/SystemExit and (worse here) the typed
    PreemptedError/CheckpointCorruptError signals the recovery paths
    route on.

R2  **no swallowed broad excepts**: an ``except Exception`` /
    ``except BaseException`` handler must log (``logger.*``,
    ``logging.*``, ``warnings.warn``) or re-``raise`` — silently eating
    unknown failures is how a production stack loses its only evidence.

R3  **no direct run-artifact writes**: inside the run-artifact layers
    (``core/``, ``search/``, ``train/``, ``launch/``), ``json.dump``
    and write-mode ``open(...)`` are reserved to the atomic helpers
    (``write_json_atomic``, ``save_checkpoint``) — a bare write torn by
    a crash is exactly the corruption the restore chain exists to
    survive.  Append-mode logs and reads are fine.

R4  **no untimed blocking** in ``core/``, ``launch/`` and ``search/``:
    a ``Thread.join()`` or ``Queue.get()`` without a ``timeout=`` on a
    variable bound from a ``Thread(...)``/``Queue(...)`` constructor in
    the same file.  The watchdog subsystem (``core/watchdog.py``)
    exists because dispatches wedge; an untimed join/get anywhere in
    the supervision layers is the same hazard reintroduced — the
    monitor becomes the thing that hangs.  (Receiver tracking is
    constructor-based, so ``str.join`` / ``dict.get`` never match.)

R5  **no direct ``jax.jit`` outside the compile seam** in ``train/``,
    ``search/`` and ``serve/``: every jit entry point on those hot
    paths must route through ``core/compilecache.py`` (``seam_jit`` /
    ``aot_compile``) so its first-call compile is timed, classified
    hit/miss against the persistent compilation cache, and stamped
    into the run artifacts — an uninstrumented ``jax.jit`` silently
    reintroduces the invisible 23-55 s compile tax the cache
    subsystem exists to measure and kill.

R6  **no unbounded blocking in the serving hot path** (``serve/``):
    a ``Queue.put``/``Queue.get``, ``Event``/``Condition`` ``.wait``
    or ``Thread.join`` without a timeout, or a bare ``time.sleep``
    inside a ``while`` loop.  The policy server's overload contract is
    that NO thread — HTTP handler, coalescing worker, supervision
    loop — can park forever: a blocking admission put was exactly the
    bug that held handler threads 30 s on a full queue, and a bare
    sleep-poll loop has no deadline to fail fast on.  Receivers are
    tracked from Thread/Queue/Event/Condition constructors in the same
    file, both directly and by attribute suffix (``pending.event`` is
    matched by the ``self.event = threading.Event()`` construction in
    the request class).

R7  **no unbounded blocking in the search pipeline** (``search/``):
    the R6 rule set extended to the async actor/learner scheduler
    (``search/pipeline.py``) and everything around it — an untimed
    ``Queue.put``/``Queue.get``, ``Event``/``Condition`` ``.wait``,
    ``Thread.join``, or a bare ``time.sleep`` poll loop in search
    scope.  The pipeline's learner/actor threads coordinate through
    queues under a preemption contract (SIGTERM must reach exit 77
    promptly); one untimed wait turns a lost actor into a wedged
    search.  Gated from day one so new pipeline code cannot regress.

R8  **no raw clock reads in the train/search/serve hot paths**: a
    ``time.time()`` / ``time.perf_counter()`` reference (call, alias,
    or ``from time import time/perf_counter``) outside the telemetry/
    profiling seam.  Timing that bypasses ``core/telemetry.py``
    (``wall()``/``mono()``/``span()``) or ``utils/profiling.py`` is a
    measurement the registry, the flight-recorder journal and the
    artifact stamps can never see — exactly the private-schema
    accounting drift the unified telemetry layer exists to end
    (docs/OBSERVABILITY.md).  ``time.monotonic``/``time.sleep`` are not
    timing evidence and stay unflagged.

Suppress a finding (sparingly, with a reason nearby) by putting
``robust: allow`` in a comment on the offending line.

Exit status: 0 clean, 1 findings (printed one per line,
``path:line: rule message``).  Wired as ``make lint-robust`` and run in
``make test-t1``'s preamble.
"""

from __future__ import annotations

import ast
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE = "fast_autoaugment_tpu"

# R3 scope: the layers that write run artifacts (checkpoints, trial
# logs, result JSONs).  utils/ (ScalarWriter's append-mode JSONL,
# tb_events' event files) and data/ (dataset downloads) are excluded —
# their files are streams/caches, not resumable run state.
ARTIFACT_DIRS = ("core", "search", "train", "launch")

# R4 scope: the supervision/orchestration layers where an untimed
# block turns a wedged dispatch into a wedged SUPERVISOR.  data/'s
# prefetch worker is excluded: its consumer-side get() is the
# documented pipeline backpressure, not supervision.
BLOCKING_DIRS = ("core", "launch", "search")

# R5 scope: the layers whose jit entry points must stay
# cache-instrumented (core/compilecache.py seam).  ops/ and models/
# are excluded: their jits are library/bench conveniences, not run
# hot paths, and the seam wraps them at the train/search call sites.
JIT_SEAM_DIRS = ("train", "search", "serve")

# R6 scope: the serving layer, where EVERY thread must stay
# deadline-bounded (handler threads, the coalescing worker, the
# supervision loops) — docs/RESILIENCE.md "Serving under overload".
SERVE_BLOCKING_DIRS = ("serve",)

# R7 scope: the search layer — the async actor/learner pipeline
# (search/pipeline.py) threads dispatches concurrently under the same
# no-thread-parks-forever contract as serving.
SEARCH_BLOCKING_DIRS = ("search",)

# R8 scope: the hot paths whose timing must stay on the telemetry/
# profiling seam (core/telemetry.py wall/mono/span; utils/profiling.py).
# core/ and utils/ are the seam itself; launch/ is supervision, its
# wall-clock heartbeats are protocol stamps, not measurements.
TIMING_SEAM_DIRS = ("train", "search", "serve")

#: the raw clock attributes R8 flags (time.monotonic is deadline
#: plumbing, time.sleep is not a measurement — both stay legal)
_R8_CLOCKS = {"time", "perf_counter"}

# constructor names whose instances carry blocking .join()/.get()
_THREAD_CTORS = {"Thread", "Timer"}
_QUEUE_CTORS = {"Queue", "SimpleQueue", "LifoQueue", "PriorityQueue",
                "JoinableQueue"}
# R6 additionally tracks waitable sync primitives and flags .put()
_WAIT_CTORS = {"Event", "Condition", "Barrier"}
#: R6 blocking methods and the positional index their timeout lands at
#: (Queue.put(item, block, timeout) -> a bare put(item) has ONE arg and
#: still blocks forever; get()/join()/wait() block with ZERO args)
_R6_METHODS = {"put": 1, "get": 0, "join": 0, "wait": 0}

# (relative module path suffix, function name) pairs allowed to write
# directly: THE atomic helpers themselves.
ARTIFACT_WRITERS = {
    ("core/checkpoint.py", "save_checkpoint"),
    ("search/driver.py", "write_json_atomic"),
}

_LOG_NAMES = {"logger", "logging", "log", "warnings"}
_LOG_METHODS = {"debug", "info", "warning", "warn", "error", "exception",
                "critical", "fatal"}


class Finding:
    def __init__(self, path: str, line: int, rule: str, msg: str):
        self.path, self.line, self.rule, self.msg = path, line, rule, msg

    def __repr__(self):
        return f"{self.path}:{self.line}: {self.rule} {self.msg}"


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    names = []
    if isinstance(t, ast.Name):
        names = [t.id]
    elif isinstance(t, ast.Tuple):
        names = [e.id for e in t.elts if isinstance(e, ast.Name)]
    return any(n in ("Exception", "BaseException") for n in names)


def _handles_failure(handler: ast.ExceptHandler) -> bool:
    """True when the handler body logs, re-raises, or captures the
    bound exception value (``except ... as e: err.append(e)`` — the
    propagate-through-a-channel pattern); swallowed means the failure
    is DISCARDED with no evidence."""
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if handler.name and isinstance(node, ast.Name) \
                and node.id == handler.name \
                and isinstance(node.ctx, ast.Load):
            return True
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute):
                base = f.value
                if isinstance(base, ast.Name) and (
                        base.id in _LOG_NAMES
                        or base.id.startswith("log")) \
                        and f.attr in _LOG_METHODS | {"warn"}:
                    return True
                if isinstance(base, ast.Name) and base.id == "warnings" \
                        and f.attr == "warn":
                    return True
    return False


def _write_mode(call: ast.Call) -> str | None:
    """The mode string of an ``open`` call if it writes, else None."""
    mode = None
    if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant) \
            and isinstance(call.args[1].value, str):
        mode = call.args[1].value
    for kw in call.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant) \
                and isinstance(kw.value.value, str):
            mode = kw.value.value
    if mode and ("w" in mode or "x" in mode or "+" in mode):
        return mode
    return None


def _recv_key(node) -> str | None:
    """A trackable receiver: ``name`` or ``obj.attr`` (one level)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
        return f"{node.value.id}.{node.attr}"
    return None


def _ctor_name(call: ast.Call) -> str | None:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def _blocking_receivers(tree) -> set[str]:
    """Names (incl. ``self.x``) bound from Thread/Queue constructors in
    this file — the receivers whose ``.join()``/``.get()`` block."""
    out: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            if _ctor_name(node.value) in _THREAD_CTORS | _QUEUE_CTORS:
                for tgt in node.targets:
                    key = _recv_key(tgt)
                    if key:
                        out.add(key)
        elif isinstance(node, ast.AnnAssign) and \
                isinstance(node.value, ast.Call):
            if _ctor_name(node.value) in _THREAD_CTORS | _QUEUE_CTORS:
                key = _recv_key(node.target)
                if key:
                    out.add(key)
    return out


def _has_timeout(call: ast.Call) -> bool:
    """True when the blocking call carries ANY argument — a positional
    timeout (``join(5)``), ``get(False)`` (non-blocking), or an
    explicit ``timeout=`` keyword.  Only the bare zero-arg form blocks
    forever."""
    return bool(call.args) or any(kw.arg == "timeout" for kw in call.keywords)


def _r6_bounded(call: ast.Call, method: str) -> bool:
    """Whether an R6 blocking call is bounded/non-blocking: positional
    args past the method's payload slot (``put(item, False)``,
    ``get(False)``, ``wait(0.1)``) or a ``block=``/``timeout=``
    keyword."""
    payload_args = _R6_METHODS[method]
    if len(call.args) > payload_args:
        return True
    return any(kw.arg in ("timeout", "block") for kw in call.keywords)


def _r6_receivers(tree) -> tuple[set[str], set[str]]:
    """(receiver keys, attribute suffixes) bound from
    Thread/Queue/Event/Condition constructors in this file.  The
    suffix set matches cross-object uses — ``pending.event.wait()`` is
    caught via the ``self.event = Event()`` construction elsewhere in
    the file."""
    ctors = _THREAD_CTORS | _QUEUE_CTORS | _WAIT_CTORS
    keys: set[str] = set()
    for node in ast.walk(tree):
        value = None
        targets = []
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            value, targets = node.value, node.targets
        elif isinstance(node, ast.AnnAssign) and \
                isinstance(node.value, ast.Call):
            value, targets = node.value, [node.target]
        if value is not None and _ctor_name(value) in ctors:
            for tgt in targets:
                key = _recv_key(tgt)
                if key:
                    keys.add(key)
    suffixes = {k.split(".")[-1] for k in keys}
    return keys, suffixes


def _sleep_in_while(tree) -> list[ast.Call]:
    """``time.sleep`` calls lexically inside a ``while`` body — a poll
    loop with no deadline."""
    hits = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.While):
            continue
        for child in ast.walk(node):
            if isinstance(child, ast.Call) \
                    and isinstance(child.func, ast.Attribute) \
                    and child.func.attr == "sleep" \
                    and isinstance(child.func.value, ast.Name) \
                    and child.func.value.id == "time":
                hits.append(child)
    return hits


def check_source(src: str, relpath: str,
                 artifact_scope: bool | None = None,
                 blocking_scope: bool | None = None,
                 jit_scope: bool | None = None,
                 serve_scope: bool | None = None,
                 search_scope: bool | None = None,
                 timing_scope: bool | None = None) -> list[Finding]:
    """Lint one file's source.  `artifact_scope` forces R3 on/off,
    `blocking_scope` forces R4 on/off, `jit_scope` forces R5 on/off,
    `serve_scope` forces R6 on/off, `search_scope` forces R7 on/off,
    `timing_scope` forces R8 on/off (None = derive from `relpath`)."""
    findings: list[Finding] = []
    lines = src.splitlines()

    def allowed(lineno: int) -> bool:
        return 0 < lineno <= len(lines) and "robust: allow" in lines[lineno - 1]

    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [Finding(relpath, e.lineno or 0, "R0", f"syntax error: {e.msg}")]

    def _in_dirs(dirs) -> bool:
        norm = relpath.replace(os.sep, "/")
        return any(
            f"/{d}/" in f"/{norm}" or norm.startswith(f"{d}/")
            for d in (f"{PACKAGE}/{a}" for a in dirs))

    if artifact_scope is None:
        artifact_scope = _in_dirs(ARTIFACT_DIRS)
    if blocking_scope is None:
        blocking_scope = _in_dirs(BLOCKING_DIRS)
    if jit_scope is None:
        jit_scope = _in_dirs(JIT_SEAM_DIRS)
    if serve_scope is None:
        serve_scope = _in_dirs(SERVE_BLOCKING_DIRS)
    if search_scope is None:
        search_scope = _in_dirs(SEARCH_BLOCKING_DIRS)
    if timing_scope is None:
        timing_scope = _in_dirs(TIMING_SEAM_DIRS)
    blockers = _blocking_receivers(tree) if blocking_scope else set()
    # R6 (serve/) and R7 (search/) share one rule engine; a file lives
    # in at most one of the two scopes
    bounded_rule = "R6" if serve_scope else ("R7" if search_scope else None)
    bounded_where = "serve/" if serve_scope else "search/"
    bounded_contract = (
        "the overload contract" if serve_scope
        else "the pipeline preemption contract")
    r6_keys: set[str] = set()
    r6_suffixes: set[str] = set()
    if bounded_rule:
        r6_keys, r6_suffixes = _r6_receivers(tree)
        for call in _sleep_in_while(tree):
            if not allowed(call.lineno):
                findings.append(Finding(
                    relpath, call.lineno, bounded_rule,
                    f"bare time.sleep inside a while loop in "
                    f"{bounded_where} — a poll loop with no deadline; "
                    "use Event.wait(timeout) or a bounded "
                    "Condition.wait so shutdown can interrupt it"))

    # enclosing-function map for the R3 allowlist
    func_of: dict[int, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for child in ast.walk(node):
                if hasattr(child, "lineno"):
                    func_of.setdefault(child.lineno, node.name)

    norm = relpath.replace(os.sep, "/")

    def is_allowlisted_writer(lineno: int) -> bool:
        fn = func_of.get(lineno, "")
        return any(norm.endswith(suffix) and fn == name
                   for suffix, name in ARTIFACT_WRITERS)

    for node in ast.walk(tree):
        if isinstance(node, ast.ExceptHandler):
            if allowed(node.lineno):
                continue
            if node.type is None:
                findings.append(Finding(
                    relpath, node.lineno, "R1",
                    "bare `except:` swallows SystemExit/KeyboardInterrupt "
                    "and the typed resilience signals — name the "
                    "exceptions"))
            elif _is_broad(node) and not _handles_failure(node):
                findings.append(Finding(
                    relpath, node.lineno, "R2",
                    "broad `except Exception` neither logs nor re-raises "
                    "— a swallowed failure leaves no evidence"))
        elif artifact_scope and isinstance(node, ast.Call):
            if allowed(node.lineno) or is_allowlisted_writer(node.lineno):
                continue
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr == "dump" \
                    and isinstance(f.value, ast.Name) and f.value.id == "json":
                findings.append(Finding(
                    relpath, node.lineno, "R3",
                    "direct json.dump to a run artifact — use "
                    "write_json_atomic (fsync + rename) so a crash "
                    "cannot tear the file"))
            elif isinstance(f, ast.Name) and f.id == "open":
                mode = _write_mode(node)
                if mode:
                    findings.append(Finding(
                        relpath, node.lineno, "R3",
                        f"direct open(..., {mode!r}) write to a run "
                        "artifact — route through write_json_atomic / "
                        "save_checkpoint"))
        if blockers and isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr in ("join", "get") \
                    and _recv_key(f.value) in blockers \
                    and not _has_timeout(node) \
                    and not allowed(node.lineno):
                findings.append(Finding(
                    relpath, node.lineno, "R4",
                    f"untimed blocking .{f.attr}() on a Thread/Queue — "
                    "pass a timeout (the watchdog contract: supervision "
                    "code must never be able to hang forever)"))
        if bounded_rule and isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr in _R6_METHODS \
                    and not _r6_bounded(node, f.attr) \
                    and not allowed(node.lineno):
                key = _recv_key(f.value)
                suffix = None
                if key is None and isinstance(f.value, ast.Attribute):
                    suffix = f.value.attr  # deep chains: match by suffix
                elif key is not None:
                    suffix = key.split(".")[-1]
                if (key in r6_keys) or (suffix in r6_suffixes):
                    findings.append(Finding(
                        relpath, node.lineno, bounded_rule,
                        f"unbounded blocking .{f.attr}() in "
                        f"{bounded_where} — {bounded_contract}: no "
                        "worker thread may park forever; pass a timeout "
                        "(or non-blocking form) and fail fast on expiry"))
        if timing_scope and isinstance(node, ast.Attribute) \
                and node.attr in _R8_CLOCKS \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "time" \
                and not allowed(node.lineno):
            findings.append(Finding(
                relpath, node.lineno, "R8",
                f"raw time.{node.attr} in a train/search/serve hot path "
                "— route timing through the telemetry seam "
                "(core/telemetry.py wall()/mono()/span()) or "
                "utils/profiling.py so the measurement reaches the "
                "registry/journal the artifacts stamp from"))
        if timing_scope and isinstance(node, ast.ImportFrom) \
                and node.module == "time" \
                and not allowed(node.lineno):
            for alias in node.names:
                if alias.name in _R8_CLOCKS:
                    findings.append(Finding(
                        relpath, node.lineno, "R8",
                        f"`from time import {alias.name}` in a "
                        "train/search/serve hot path — the import-alias "
                        "form of a raw clock read; use the telemetry "
                        "seam (core/telemetry.py)"))
        if jit_scope and isinstance(node, ast.Attribute) \
                and node.attr == "jit" \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "jax" \
                and not allowed(node.lineno):
            # catches direct calls, functools.partial(jax.jit, ...) AND
            # @jax.jit decorators: any reference to the attribute in
            # seam scope is an uninstrumented compile path
            findings.append(Finding(
                relpath, node.lineno, "R5",
                "direct jax.jit outside the compile seam — route "
                "through core/compilecache.seam_jit / aot_compile so "
                "the first-call compile is timed and classified "
                "hit/miss against the persistent cache"))
    return findings


def lint_tree(root: str = REPO) -> list[Finding]:
    findings: list[Finding] = []
    pkg_root = os.path.join(root, PACKAGE)
    for dirpath, _dirnames, filenames in os.walk(pkg_root):
        if "__pycache__" in dirpath:
            continue
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            rel = os.path.relpath(path, root)
            with open(path) as fh:
                findings.extend(check_source(fh.read(), rel))
    return findings


def main(argv=None) -> int:
    findings = lint_tree()
    for f in findings:
        print(f)
    if findings:
        print(f"lint-robust: {len(findings)} finding(s)", file=sys.stderr)
        return 1
    print("lint-robust: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
