#!/usr/bin/env python
"""One fleet table from telemetry journals + fleet heartbeats.

    python tools/faa_status.py --dir /shared/run
    python tools/faa_status.py --dir /shared/run --json

Aggregates, across every host that writes under ``--dir``:

- **flight-recorder journals** (``journal-*.jsonl``,
  ``core/telemetry.py`` — the CLIs' ``--telemetry DIR`` / the fleet's
  ``--telemetry``): per-host device busy fraction and dispatch-gap
  p50/p99 from the ``dispatch`` event windows (union-merged per thread,
  the ``DispatchTrace`` math), plus watchdog-fire / breaker-fire /
  shed / preempt counts and the age of the newest event;
- **fleet/workqueue heartbeats** (``hosts/<owner>.json`` —
  ``launch/workqueue.py::beat_host`` and ``serve_cli
  --heartbeat-dir``): alive / done / STALE verdicts against ``--ttl``;
- **done markers** (``done/<unit>.json``): units finished per host and
  the reclaimed-unit evidence (``attempt > 1``);
- **the fleet-search topology** (docs/RESILIENCE.md "Fleet search"):
  per-host role (learner/actor, from role-stamped host beats and the
  journaled ``round`` events), round units currently claimed (live
  leases), in-flight window occupancy (published rounds with no posted
  result), and the cross-host lane-concurrency evidence — seconds a
  phase-1 training lane on one host overlapped phase-2 TTA lanes on
  DIFFERENT hosts (the transferable multi-host win a 1-core container
  cannot show as wall);
- **the serving plane** (docs/SERVING.md): replica census from
  ``--port-dir`` discovery records (+ heartbeats and same-host pid
  probes), in/out-of-rotation verdicts from the router's journaled
  ``rotation`` events, resident-tenant counts from ``tenant``
  admit/evict events, and the last N autoscaler ``scale_up``/
  ``scale_down`` decisions with their metric evidence inline.

Everything is read-only over shared files — safe against a live fleet,
host-only (no jax import), and exactly the cross-host view no single
``search_result.json`` can stamp.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_TOOLS_DIR = os.path.dirname(os.path.abspath(__file__))
if _TOOLS_DIR not in sys.path:
    sys.path.insert(0, _TOOLS_DIR)

from trace_export import read_journal  # noqa: E402  (sibling tool)

#: event types counted per host in the incident columns
_COUNTED = ("watchdog_fire", "breaker_fire", "shed", "preempt", "lease")


def _merge(windows: list[tuple[float, float]]) -> list[list[float]]:
    merged: list[list[float]] = []
    for t0, t1 in sorted(windows):
        if merged and t0 <= merged[-1][1]:
            merged[-1][1] = max(merged[-1][1], t1)
        else:
            merged.append([t0, t1])
    return merged


def _percentile(xs: list[float], q: float) -> float | None:
    if not xs:
        return None
    xs = sorted(xs)
    idx = min(len(xs) - 1, max(0, int(round(q / 100.0 * (len(xs) - 1)))))
    return xs[idx]


def dispatch_stats(records: list[dict]) -> dict:
    """Busy-frac + gap percentiles from one host's ``dispatch`` windows
    (grouped per (pid, tid) — concurrent actors merge per thread, the
    same union semantics as ``search/pipeline.py::DispatchTrace``)."""
    lanes: dict[tuple, list[tuple[float, float]]] = {}
    for r in records:
        if r.get("type") != "dispatch":
            continue
        t0, t1 = r.get("t_mono_start"), r.get("t_mono_end")
        if isinstance(t0, (int, float)) and isinstance(t1, (int, float)):
            lanes.setdefault((r.get("pid", 0), r.get("tid", 0)),
                             []).append((float(t0), float(t1)))
    busy = span = 0.0
    gaps: list[float] = []
    n = 0
    for windows in lanes.values():
        merged = _merge(windows)
        busy += sum(t1 - t0 for t0, t1 in merged)
        span += merged[-1][1] - merged[0][0]
        gaps.extend(b[0] - a[1] for a, b in zip(merged, merged[1:]))
        n += len(windows)
    p50 = _percentile(gaps, 50)
    p99 = _percentile(gaps, 99)
    return {
        "dispatches": n,
        "busy_secs": round(busy, 3),
        "busy_frac": round(busy / span, 4) if span > 0 else None,
        "gap_p50_ms": None if p50 is None else round(p50 * 1e3, 3),
        "gap_p99_ms": None if p99 is None else round(p99 * 1e3, 3),
    }


def _read_json(path: str) -> dict | None:
    try:
        with open(path) as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None


def read_heartbeats(root: str) -> dict[str, dict]:
    out: dict[str, dict] = {}
    hosts_dir = os.path.join(root, "hosts")
    try:
        names = sorted(os.listdir(hosts_dir))
    except OSError:
        return out
    for name in names:
        if name.endswith(".json"):
            rec = _read_json(os.path.join(hosts_dir, name))
            if rec and rec.get("owner"):
                out[str(rec["owner"])] = rec
    return out


def read_done_markers(root: str) -> list[dict]:
    out: list[dict] = []
    done_dir = os.path.join(root, "done")
    try:
        names = sorted(os.listdir(done_dir))
    except OSError:
        return out
    for name in names:
        if name.endswith(".json"):
            rec = _read_json(os.path.join(done_dir, name))
            if rec:
                out.append(rec)
    return out


def read_leases(root: str) -> dict[str, dict]:
    """Live lease records by unit (``leases/<unit>.json``) — the
    claimed-unit view of the workqueue/fleet-search protocols."""
    out: dict[str, dict] = {}
    leases_dir = os.path.join(root, "leases")
    try:
        names = sorted(os.listdir(leases_dir))
    except OSError:
        return out
    for name in names:
        if name.endswith(".json"):
            rec = _read_json(os.path.join(leases_dir, name))
            if rec and rec.get("unit"):
                out[str(rec["unit"])] = rec
    return out


def _phase_windows(journal: list[dict], lane: str) -> dict[str, list]:
    """Per-host wall-aligned ``(t0, t1)`` windows from ``phase`` events
    of one lane.  Monotonic spans align onto the wall clock through
    each record's own (t_wall - t_mono) emit offset — the same
    alignment trick as the trace export, good to the emit jitter."""
    out: dict[str, list] = {}
    for r in journal:
        if r.get("type") != "phase" or r.get("lane") != lane:
            continue
        vals = (r.get("t_mono_start"), r.get("t_mono_end"),
                r.get("t_wall"), r.get("t_mono"))
        if not all(isinstance(v, (int, float)) for v in vals):
            continue
        t0, t1, tw, tm = (float(v) for v in vals)
        off = tw - tm
        out.setdefault(str(r.get("host")), []).append((t0 + off, t1 + off))
    return out


def _windows_overlap_secs(a: list, b: list) -> float:
    return sum(max(0.0, min(e0, e1) - max(s0, s1))
               for s0, e0 in a for s1, e1 in b)


_ROUND_ACTIONS = {"publish": "published", "claim": "claimed",
                  "return": "returned", "apply": "applied"}


def search_fleet_status(root: str, journal: list[dict],
                        beats: dict[str, dict]) -> dict | None:
    """The fleet-search topology section: per-host role (learner/actor
    from host beats, falling back to what the ``round`` events prove),
    per-host round counts, round units currently claimed (live
    leases), the in-flight window occupancy (published rounds with no
    posted result), and the cross-host lane-concurrency evidence —
    seconds during which a phase-1 lane on one host overlapped a
    phase-2 lane on a DIFFERENT host (the ROADMAP acceptance surface:
    the wall win the 1-core container cannot show).  None when the dir
    shows no fleet search at all."""
    hosts: dict[str, dict] = {}

    def _row(host: str) -> dict:
        return hosts.setdefault(host, {
            "role": None, "published": 0, "claimed": 0, "returned": 0,
            "applied": 0})

    for r in journal:
        if r.get("type") != "round":
            continue
        key = _ROUND_ACTIONS.get(r.get("action"))
        if key:
            _row(str(r.get("host")))[key] += 1
    for owner, rec in beats.items():
        if rec.get("role"):
            _row(str(owner))["role"] = rec["role"]
    for row in hosts.values():
        if row["role"] is None:  # infer from the journal evidence
            if row["published"] or row["applied"]:
                row["role"] = "learner"
            elif row["claimed"] or row["returned"]:
                row["role"] = "actor"

    leases = read_leases(root)
    claimed_rounds: dict[str, list[str]] = {}
    for unit, rec in leases.items():
        if unit.startswith("p2r-"):
            claimed_rounds.setdefault(str(rec.get("owner")),
                                      []).append(unit)
    for owner, units in claimed_rounds.items():
        _row(owner).setdefault("role", None)
        hosts[owner]["claimed_units"] = sorted(units)

    # per-unit lease epochs (the fencing tokens): epoch > 1 = the unit
    # was reclaimed at least once; the provenance any host can read
    lease_epochs = {
        unit: {"epoch": int(rec.get("epoch", 1)),
               "owner": rec.get("owner"),
               "attempt": int(rec.get("attempt", 1)),
               **({"reclaimed_from": rec["reclaimed_from"]}
                  if rec.get("reclaimed_from") else {})}
        for unit, rec in sorted(leases.items())}

    # skew suspects: a lease heartbeat or host beat stamped in THIS
    # observer's future means the writer's wall clock runs ahead —
    # harmless to reclaim correctness (observer-local staleness), but
    # worth a loud line before someone trusts a wall comparison
    now = time.time()
    margin = 2.0  # NTP-honest hosts stay well inside this
    skew_suspects = []
    for unit, rec in sorted(leases.items()):
        hb = rec.get("heartbeat")
        if isinstance(hb, (int, float)) and hb > now + margin:
            skew_suspects.append({
                "kind": "lease", "name": unit,
                "owner": rec.get("owner"),
                "ahead_sec": round(float(hb) - now, 1)})
    for owner, rec in sorted(beats.items()):
        hb = rec.get("heartbeat")
        if isinstance(hb, (int, float)) and hb > now + margin:
            skew_suspects.append({
                "kind": "host", "name": owner,
                "ahead_sec": round(float(hb) - now, 1)})

    # fs-fault injection counters (the FAA_FSFAULT seam journals one
    # typed event per injection): what the hostile substrate did
    fsfault_counts: dict[str, int] = {}
    for r in journal:
        if r.get("type") == "fsfault":
            kind = str(r.get("label"))
            fsfault_counts[kind] = fsfault_counts.get(kind, 0) + 1

    # in-flight window occupancy: published rounds with no result yet
    open_rounds: list[str] = []
    work_dir = os.path.join(root, "work")
    done_dir = os.path.join(root, "done")
    try:
        names = sorted(os.listdir(work_dir))
    except OSError:
        names = []
    for name in names:
        if name.endswith(".json") and name.startswith("p2r-"):
            unit = name[:-5]
            if not os.path.exists(os.path.join(done_dir, name)):
                open_rounds.append(unit)

    # cross-host lane concurrency: phase-1 training on host A while
    # phase-2 TTA on host B (actors emit per-round phase2 windows)
    p1 = _phase_windows(journal, "phase1")
    p2 = _phase_windows(journal, "phase2")
    lane_pairs = []
    total_overlap = 0.0
    for h1, w1 in p1.items():
        for h2, w2 in p2.items():
            if h1 == h2:
                continue
            ov = _windows_overlap_secs(w1, w2)
            if ov > 0:
                lane_pairs.append({"phase1_host": h1, "phase2_host": h2,
                                   "overlap_secs": round(ov, 3)})
                total_overlap += ov
    lane_pairs.sort(key=lambda p: -p["overlap_secs"])

    if not hosts and not open_rounds:
        return None
    return {
        "hosts": {k: hosts[k] for k in sorted(hosts)},
        "open_rounds": open_rounds,
        "inflight_rounds": len(open_rounds),
        "concurrent_lane_pairs": lane_pairs,
        "concurrent_lane_secs": round(total_overlap, 3),
        "search_done": os.path.exists(
            os.path.join(root, "search_done.json")),
        "lease_epochs": lease_epochs,
        "skew_suspects": skew_suspects,
        "fsfault_injections": fsfault_counts,
    }


def read_port_records(port_dir: str) -> list[dict]:
    """Replica-discovery records (``serve_cli --port-dir``): one
    ``<tag>.json`` per live replica; a drained replica removed its
    record, so presence ~ membership."""
    out: list[dict] = []
    try:
        names = sorted(os.listdir(port_dir))
    except OSError:
        return out
    for name in names:
        if name.endswith(".json") and not name.startswith("."):
            rec = _read_json(os.path.join(port_dir, name))
            if rec and "port" in rec:
                out.append(rec)
    return out


def _pid_alive(pid: int) -> bool | None:
    """Same-host liveness probe; None when unknowable (pid 0/other
    host)."""
    if not pid:
        return None
    try:
        os.kill(int(pid), 0)
        return True
    except ProcessLookupError:
        return False
    except (OSError, ValueError):
        return None


def serving_plane_status(root: str, journal: list[dict],
                         beats: dict[str, dict],
                         port_dir: str | None = None, ttl: float = 60.0,
                         now: float | None = None,
                         scale_events: int = 5) -> dict | None:
    """The serving-plane section: replica census (port-dir records +
    heartbeats + same-host pid probes), in/out-of-rotation verdicts
    (the router's journaled ``rotation`` events), resident-tenant
    counts (net ``tenant`` admit/evict events per host), and the last
    N autoscaler ``scale_up``/``scale_down`` decisions with their
    metric evidence.  Read-only over shared files, like everything
    else here.  None when the dir shows no serving plane at all."""
    now = time.time() if now is None else now
    if port_dir is None:
        cand = os.path.join(root, "replicas")
        port_dir = cand if os.path.isdir(cand) else None
    records = read_port_records(port_dir) if port_dir else []

    # rotation: the LAST journaled verdict per replica tag wins
    rotation: dict[str, dict] = {}
    tenants: dict[str, set] = {}
    scales: list[dict] = []
    for rec in journal:
        etype = rec.get("type")
        if etype == "rotation":
            tag = str(rec.get("replica"))
            rotation[tag] = {"action": rec.get("action"),
                             "reason": rec.get("reason"),
                             "t_wall": rec.get("t_wall")}
        elif etype == "tenant":
            key = f"{rec.get('host')}/{rec.get('label')}"
            cur = tenants.setdefault(key, set())
            digest = rec.get("digest")
            if rec.get("action") == "admit":
                cur.add(digest)
            elif rec.get("action") == "evict":
                cur.discard(digest)
        elif etype in ("scale_up", "scale_down"):
            scales.append({
                "action": etype,
                "replica": rec.get("replica"),
                "replicas_after": rec.get("replicas_after"),
                "queue_depth": rec.get("queue_depth"),
                "shed_rate": rec.get("shed_rate"),
                "breaker_open": rec.get("breaker_open"),
                "t_wall": rec.get("t_wall"),
            })
    scales.sort(key=lambda s: s.get("t_wall") or 0)

    replicas: dict[str, dict] = {}
    for rec in records:
        tag = str(rec.get("tag"))
        row = {"addr": f"{rec.get('host')}:{rec.get('port')}",
               "pid": rec.get("pid"),
               "pid_alive": _pid_alive(rec.get("pid", 0))}
        beat = beats.get(tag)
        if beat is None:
            row["beat"] = "none"
        elif beat.get("done"):
            row["beat"] = "done"
        else:
            age = now - float(beat.get("heartbeat", 0.0))
            row["beat"] = "alive" if age <= ttl else f"STALE {age:.0f}s"
        rot = rotation.get(tag)
        if rot is None:
            row["rotation"] = "unknown"
        else:
            row["rotation"] = ("in" if rot["action"] == "readmit"
                               else "OUT")
            row["rotation_reason"] = rot.get("reason")
        replicas[tag] = row
    # rotation verdicts for replicas the router saw but whose record
    # is gone (killed replica: the eject evidence must not vanish)
    for tag, rot in rotation.items():
        if tag not in replicas:
            replicas[tag] = {"addr": None, "pid": None, "pid_alive": None,
                             "beat": beats.get(tag, {}).get("done")
                             and "done" or "none",
                             "rotation": ("in" if rot["action"] ==
                                          "readmit" else "OUT"),
                             "rotation_reason": rot.get("reason")}
    if not replicas and not tenants and not scales:
        return None
    return {
        "port_dir": port_dir,
        "replicas": replicas,
        "resident_tenants": {k: sorted(d for d in v if d)
                             for k, v in sorted(tenants.items())},
        "scale_events": scales[-max(0, int(scale_events)):],
        "scale_event_total": len(scales),
    }


def control_plane_status(journal: list[dict],
                         drift_events: int = 5) -> dict | None:
    """The control-plane section (docs/CONTROL.md): drift verdicts,
    the active canary (rollouts newer than the last gate decision),
    and the last promote/rollback with its evidence — all read from
    the journal's typed drift/research/canary/promote/rollback
    events.  None when the journal shows no control plane."""
    drifts: list[dict] = []
    researches: list[dict] = []
    rollouts: list[dict] = []
    decisions: list[dict] = []
    for rec in journal:
        etype = rec.get("type")
        if etype == "drift":
            drifts.append({
                "id": rec.get("id"), "metric": rec.get("metric"),
                "direction": rec.get("direction"),
                "stat": rec.get("stat"), "value": rec.get("value"),
                "baseline_mean": rec.get("baseline_mean"),
                "t_wall": rec.get("t_wall")})
        elif etype == "research":
            researches.append({
                "candidate": rec.get("candidate"),
                "digest": rec.get("digest"),
                "topup_trials": rec.get("topup_trials"),
                "wall_sec": rec.get("wall_sec"),
                "t_wall": rec.get("t_wall")})
        elif etype == "canary" and rec.get("action") == "rollout":
            rollouts.append({
                "replica": rec.get("replica"),
                "digest": rec.get("digest"),
                "t_wall": rec.get("t_wall")})
        elif etype in ("promote", "rollback"):
            decisions.append({
                "action": etype, "digest": rec.get("digest"),
                "reason": rec.get("reason"),
                "drift_id": rec.get("drift_id"),
                "canary": rec.get("canary"),
                "detect_to_promote_sec":
                    rec.get("detect_to_promote_sec"),
                "evidence": rec.get("evidence"),
                "t_wall": rec.get("t_wall")})
    if not (drifts or researches or rollouts or decisions):
        return None
    for seq in (drifts, researches, rollouts, decisions):
        seq.sort(key=lambda e: e.get("t_wall") or 0)
    last_decision = decisions[-1] if decisions else None
    decided_at = (last_decision or {}).get("t_wall") or 0
    active = [r for r in rollouts if (r.get("t_wall") or 0) > decided_at]
    return {
        "drift_verdicts": drifts[-max(0, int(drift_events)):],
        "drift_verdict_total": len(drifts),
        "researches": researches[-max(0, int(drift_events)):],
        "active_canary": active or None,
        "last_decision": last_decision,
        "promotes": sum(1 for d in decisions if d["action"] == "promote"),
        "rollbacks": sum(1 for d in decisions
                         if d["action"] == "rollback"),
    }


def gameday_status(journal: list[dict],
                   verdict_rows: int = 12) -> dict | None:
    """The game-day section (docs/GAMEDAYS.md): the active scenario
    (latest ``start`` without a newer ``end``), its current phase and
    rolling offered-vs-served progress, mid-scenario kills, finished
    verdicts — all from the journal's typed ``scenario`` / ``verdict``
    events.  None when the journal shows no game day."""
    starts: list[dict] = []
    ends: dict[str, dict] = {}
    phases: dict[str, str] = {}
    progress: dict[str, dict] = {}
    kills: list[dict] = []
    verdicts: list[dict] = []
    for rec in journal:
        etype = rec.get("type")
        if etype == "scenario":
            name = str(rec.get("label"))
            action = rec.get("action")
            if action == "start":
                starts.append(rec)
            elif action == "end":
                ends[name] = rec
            elif action == "phase":
                phases[name] = rec.get("phase")
            elif action == "progress":
                progress[name] = rec
            elif action == "kill":
                kills.append({"scenario": name,
                              "replica": rec.get("replica"),
                              "victim_pid": rec.get("victim_pid"),
                              "t_wall": rec.get("t_wall")})
        elif etype == "verdict":
            verdicts.append({
                "scenario": str(rec.get("label")),
                "predicate": rec.get("predicate"),
                "ok": rec.get("ok"),
                "observed": rec.get("observed"),
                "t_wall": rec.get("t_wall")})
    if not (starts or verdicts):
        return None
    starts.sort(key=lambda r: r.get("t_wall") or 0)
    verdicts.sort(key=lambda r: r.get("t_wall") or 0)
    active = None
    for rec in reversed(starts):
        name = str(rec.get("label"))
        end = ends.get(name)
        if end is not None and (end.get("t_wall") or 0) \
                >= (rec.get("t_wall") or 0):
            break  # the newest scenario already finished
        prog = progress.get(name) or {}
        offered, ok = prog.get("offered"), prog.get("ok")
        active = {
            "scenario": name,
            "seed": rec.get("seed"),
            "schedule_digest": rec.get("schedule_digest"),
            "expect": rec.get("expect"),
            "requests": rec.get("requests"),
            "phase": phases.get(name) or "bring-up",
            "offered": offered,
            "completed": prog.get("completed"),
            "ok": ok,
            "served_frac": (round(ok / offered, 4)
                            if offered and ok is not None else None),
        }
        break
    finished = [
        {"scenario": name, "passed": end.get("passed"),
         "expect": end.get("expect"),
         "ok_as_expected": end.get("ok_as_expected"),
         "schedule_digest": end.get("schedule_digest"),
         "elapsed_s": end.get("elapsed_s"), "t_wall": end.get("t_wall")}
        for name, end in ends.items()]
    finished.sort(key=lambda r: r.get("t_wall") or 0)
    return {
        "active": active,
        "finished": finished,
        "kills": kills,
        "verdicts": verdicts[-max(0, int(verdict_rows)):],
        "verdict_total": len(verdicts),
    }


def fleet_status(root: str, ttl: float = 60.0,
                 now: float | None = None,
                 port_dir: str | None = None) -> dict:
    """The aggregated per-host view (JSON-ready)."""
    now = time.time() if now is None else now
    journal = read_journal(root)
    beats = read_heartbeats(root)
    done = read_done_markers(root)

    by_host: dict[str, list[dict]] = {}
    for rec in journal:
        by_host.setdefault(str(rec.get("host")), []).append(rec)

    hosts: dict[str, dict] = {}
    for name in sorted(set(by_host) | set(beats)):
        recs = by_host.get(name, [])
        row = dispatch_stats(recs)
        for etype in _COUNTED:
            row[etype + "s"] = sum(1 for r in recs
                                   if r.get("type") == etype)
        row["attempts"] = max(
            [int(r.get("attempt", 1)) for r in recs], default=None)
        walls = [r["t_wall"] for r in recs
                 if isinstance(r.get("t_wall"), (int, float))]
        row["last_event_age_s"] = (round(now - max(walls), 1)
                                   if walls else None)
        beat = beats.get(name)
        if beat is None:
            row["beat"] = "none"
        elif beat.get("done"):
            row["beat"] = "done"
        else:
            age = now - float(beat.get("heartbeat", 0.0))
            row["beat"] = "alive" if age <= ttl else f"STALE {age:.0f}s"
        row["units_done"] = sum(1 for d in done if d.get("owner") == name)
        hosts[name] = row

    reclaimed = [
        {"unit": d.get("unit"), "attempt": int(d.get("attempt", 1)),
         "finished_by": d.get("owner"),
         "reclaimed_from": d.get("reclaimed_from")}
        for d in done if int(d.get("attempt", 1)) > 1
    ]
    out = {
        "dir": os.path.abspath(root),
        "generated_at": now,
        "ttl_s": ttl,
        "hosts": hosts,
        "units_done": len(done),
        "reclaimed_units": reclaimed,
        "journal_records": len(journal),
    }
    serving = serving_plane_status(root, journal, beats,
                                   port_dir=port_dir, ttl=ttl, now=now)
    if serving is not None:
        out["serving"] = serving
    search_fleet = search_fleet_status(root, journal, beats)
    if search_fleet is not None:
        out["search_fleet"] = search_fleet
    control = control_plane_status(journal)
    if control is not None:
        out["control"] = control
    gameday = gameday_status(journal)
    if gameday is not None:
        out["gameday"] = gameday
    return out


_COLUMNS = (
    ("beat", "beat"),
    ("busy_frac", "busy"),
    ("gap_p50_ms", "gap p50"),
    ("gap_p99_ms", "gap p99"),
    ("dispatches", "disp"),
    ("watchdog_fires", "wd"),
    ("breaker_fires", "brk"),
    ("sheds", "shed"),
    ("preempts", "preempt"),
    ("units_done", "units"),
    ("attempts", "att"),
    ("last_event_age_s", "last ev"),
)


def render_table(status: dict) -> str:
    rows = [["host"] + [h for _k, h in _COLUMNS]]
    for name, row in sorted(status["hosts"].items()):
        rows.append([name] + [
            "-" if row.get(k) is None else str(row.get(k))
            for k, _h in _COLUMNS])
    widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
    lines = ["  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip()
             for r in rows]
    lines.insert(1, "  ".join("-" * w for w in widths))
    tail = (f"{status['units_done']} unit(s) done, "
            f"{len(status['reclaimed_units'])} reclaimed, "
            f"{status['journal_records']} journal record(s)")
    for rec in status["reclaimed_units"]:
        tail += (f"\n  reclaimed: {rec['unit']} attempt {rec['attempt']} "
                 f"finished by {rec['finished_by']} "
                 f"(from {rec['reclaimed_from']})")
    fleet_search = status.get("search_fleet")
    if fleet_search:
        tail += "\n\nfleet search:"
        for name, row in sorted(fleet_search["hosts"].items()):
            counts = (f"published={row['published']} "
                      f"claimed={row['claimed']} "
                      f"returned={row['returned']} "
                      f"applied={row['applied']}")
            tail += (f"\n  {name}: role={row.get('role') or '?'}  "
                     f"{counts}")
            units = row.get("claimed_units")
            if units:
                tail += f"  holding [{', '.join(units)}]"
        tail += (f"\n  in-flight window: {fleet_search['inflight_rounds']} "
                 "open round(s)")
        if fleet_search["open_rounds"]:
            tail += f" [{', '.join(fleet_search['open_rounds'][:8])}" + \
                    ("...]" if len(fleet_search["open_rounds"]) > 8 else "]")
        if fleet_search["search_done"]:
            tail += "  (search done)"
        pairs = fleet_search["concurrent_lane_pairs"]
        if pairs:
            tail += (f"\n  concurrent lanes (distinct hosts): "
                     f"{fleet_search['concurrent_lane_secs']}s total")
            for pr in pairs[:6]:
                tail += (f"\n    phase1@{pr['phase1_host']} || "
                         f"phase2@{pr['phase2_host']}: "
                         f"{pr['overlap_secs']}s")
        else:
            tail += "\n  concurrent lanes (distinct hosts): none observed"
        epochs = fleet_search.get("lease_epochs") or {}
        reclaimed_leases = {u: r for u, r in epochs.items()
                            if r["epoch"] > 1}
        if epochs:
            tail += (f"\n  lease epochs: {len(epochs)} live lease(s), "
                     f"{len(reclaimed_leases)} past epoch 1")
            for unit, rec in list(reclaimed_leases.items())[:6]:
                tail += (f"\n    {unit}: epoch {rec['epoch']} "
                         f"owner {rec['owner']}"
                         + (f" (reclaimed from {rec['reclaimed_from']})"
                            if rec.get("reclaimed_from") else ""))
        fs_counts = fleet_search.get("fsfault_injections") or {}
        if fs_counts:
            tail += "\n  fs-fault injections: " + ", ".join(
                f"{k}={v}" for k, v in sorted(fs_counts.items()))
        for sus in fleet_search.get("skew_suspects") or []:
            tail += (f"\n  WARNING skew suspect: {sus['kind']} "
                     f"{sus['name']} heartbeat {sus['ahead_sec']}s in "
                     "this observer's FUTURE (writer clock runs ahead; "
                     "lease reclaim is observer-local and unaffected)")
    serving = status.get("serving")
    if serving:
        tail += "\n\nserving plane:"
        for tag, row in sorted(serving["replicas"].items()):
            alive = row.get("pid_alive")
            tail += (f"\n  {tag}: {row.get('addr') or '-'}  "
                     f"rotation={row.get('rotation')}  "
                     f"beat={row.get('beat')}  "
                     f"pid={'?' if alive is None else ('up' if alive else 'DEAD')}")
            if row.get("rotation_reason"):
                tail += f"  ({row['rotation_reason']})"
        for key, digests in serving["resident_tenants"].items():
            tail += (f"\n  tenants {key}: {len(digests)} resident"
                     f" [{', '.join(digests)}]" if digests else
                     f"\n  tenants {key}: 0 resident")
        n_total = serving.get("scale_event_total", 0)
        shown = serving.get("scale_events", [])
        if shown:
            tail += (f"\n  autoscaler: last {len(shown)} of {n_total} "
                     "scale event(s):")
            for ev in shown:
                tail += (f"\n    {ev['action']} -> {ev.get('replica')}"
                         f" (replicas={ev.get('replicas_after')}, "
                         f"queue={ev.get('queue_depth')}, "
                         f"shed_rate={ev.get('shed_rate')}, "
                         f"breaker={ev.get('breaker_open')})")
    control = status.get("control")
    if control:
        tail += "\n\ncontrol plane:"
        n_total = control.get("drift_verdict_total", 0)
        for ev in control.get("drift_verdicts", []):
            tail += (f"\n  drift {ev.get('id')}: {ev.get('metric')} "
                     f"{ev.get('direction')} (stat={ev.get('stat')}, "
                     f"value={ev.get('value')}, "
                     f"baseline={ev.get('baseline_mean')})")
        if n_total > len(control.get("drift_verdicts", [])):
            tail += (f"\n  ({n_total} drift verdict(s) total)")
        for ev in control.get("researches", []):
            tail += (f"\n  research -> {ev.get('digest')} "
                     f"(topup={ev.get('topup_trials')}, "
                     f"{ev.get('wall_sec')}s)")
        active = control.get("active_canary")
        if active:
            reps = sorted({str(r.get('replica')) for r in active})
            tail += (f"\n  ACTIVE canary: {active[0].get('digest')} on "
                     f"[{', '.join(reps)}]")
        dec = control.get("last_decision")
        if dec:
            tail += (f"\n  last decision: {dec['action'].upper()} "
                     f"{dec.get('digest')} ({dec.get('reason')})")
            if dec.get("detect_to_promote_sec") is not None:
                tail += (f"\n    detect->promote "
                         f"{dec['detect_to_promote_sec']}s")
            ev = dec.get("evidence") or {}
            if ev.get("median_quality_delta") is not None:
                tail += (f"; median quality delta "
                         f"{ev['median_quality_delta']:+.6f} vs margin "
                         f"{ev.get('quality_margin')}")
        tail += (f"\n  decisions: {control.get('promotes', 0)} "
                 f"promote(s), {control.get('rollbacks', 0)} "
                 "rollback(s)")
    gameday = status.get("gameday")
    if gameday:
        tail += "\n\ngame day:"
        act = gameday.get("active")
        if act:
            tail += (f"\n  ACTIVE {act['scenario']} "
                     f"(expect {act.get('expect')}, "
                     f"digest {act.get('schedule_digest')}): "
                     f"phase={act.get('phase')}")
            if act.get("offered") is not None:
                tail += (f"  offered={act['offered']} "
                         f"served={act.get('ok')}"
                         f" ({act.get('served_frac')})")
        for fin in gameday.get("finished", []):
            mark = "PASS" if fin.get("passed") else "FAIL"
            expect = ("as expected" if fin.get("ok_as_expected")
                      else "NOT as expected")
            tail += (f"\n  {fin['scenario']}: {mark} "
                     f"(expect {fin.get('expect')}, {expect}, "
                     f"{fin.get('elapsed_s')}s, "
                     f"digest {fin.get('schedule_digest')})")
        for k in gameday.get("kills", []):
            tail += (f"\n  kill: {k['scenario']} SIGKILLed "
                     f"{k.get('replica')} (pid {k.get('victim_pid')})")
        n_total = gameday.get("verdict_total", 0)
        shown = gameday.get("verdicts", [])
        if shown:
            tail += f"\n  last {len(shown)} of {n_total} verdict row(s):"
            for v in shown:
                tail += (f"\n    {v['scenario']} :: {v['predicate']}: "
                         f"{'ok' if v.get('ok') else 'FAIL'}")
    return "\n".join(lines) + "\n" + tail


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="aggregate telemetry journals + fleet heartbeats "
                    "into one per-host status table")
    p.add_argument("--dir", required=True,
                   help="the shared dir: --telemetry journals and/or a "
                        "workqueue/heartbeat layout (hosts/, done/)")
    p.add_argument("--ttl", type=float, default=60.0,
                   help="heartbeat staleness bound (the workqueue lease "
                        "TTL; default 60s)")
    p.add_argument("--json", action="store_true",
                   help="emit the aggregate as one JSON object instead "
                        "of the table")
    p.add_argument("--port-dir", default=None, metavar="DIR",
                   help="serving-plane replica-discovery dir "
                        "(serve_cli --port-dir); default: "
                        "<dir>/replicas when present")
    args = p.parse_args(argv)

    status = fleet_status(args.dir, ttl=args.ttl, port_dir=args.port_dir)
    if not status["hosts"] and not status.get("serving") \
            and not status.get("search_fleet") \
            and not status.get("control"):
        print(f"faa_status: nothing under {args.dir} (no journal-*.jsonl, "
              "no hosts/*.json, no serving-plane or fleet-search records)",
              file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(status))
    else:
        print(render_table(status))
    return 0


if __name__ == "__main__":
    sys.exit(main())
