#!/usr/bin/env python
"""One fleet table from telemetry journals + fleet heartbeats.

    python tools/faa_status.py --dir /shared/run
    python tools/faa_status.py --dir /shared/run --json

Aggregates, across every host that writes under ``--dir``:

- **flight-recorder journals** (``journal-*.jsonl``,
  ``core/telemetry.py`` — the CLIs' ``--telemetry DIR`` / the fleet's
  ``--telemetry``): per-host device busy fraction and dispatch-gap
  p50/p99 from the ``dispatch`` event windows (union-merged per thread,
  the ``DispatchTrace`` math), plus watchdog-fire / breaker-fire /
  shed / preempt counts and the age of the newest event;
- **fleet/workqueue heartbeats** (``hosts/<owner>.json`` —
  ``launch/workqueue.py::beat_host`` and ``serve_cli
  --heartbeat-dir``): alive / done / STALE verdicts against ``--ttl``;
- **done markers** (``done/<unit>.json``): units finished per host and
  the reclaimed-unit evidence (``attempt > 1``).

Everything is read-only over shared files — safe against a live fleet,
host-only (no jax import), and exactly the cross-host view no single
``search_result.json`` can stamp.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_TOOLS_DIR = os.path.dirname(os.path.abspath(__file__))
if _TOOLS_DIR not in sys.path:
    sys.path.insert(0, _TOOLS_DIR)

from trace_export import read_journal  # noqa: E402  (sibling tool)

#: event types counted per host in the incident columns
_COUNTED = ("watchdog_fire", "breaker_fire", "shed", "preempt", "lease")


def _merge(windows: list[tuple[float, float]]) -> list[list[float]]:
    merged: list[list[float]] = []
    for t0, t1 in sorted(windows):
        if merged and t0 <= merged[-1][1]:
            merged[-1][1] = max(merged[-1][1], t1)
        else:
            merged.append([t0, t1])
    return merged


def _percentile(xs: list[float], q: float) -> float | None:
    if not xs:
        return None
    xs = sorted(xs)
    idx = min(len(xs) - 1, max(0, int(round(q / 100.0 * (len(xs) - 1)))))
    return xs[idx]


def dispatch_stats(records: list[dict]) -> dict:
    """Busy-frac + gap percentiles from one host's ``dispatch`` windows
    (grouped per (pid, tid) — concurrent actors merge per thread, the
    same union semantics as ``search/pipeline.py::DispatchTrace``)."""
    lanes: dict[tuple, list[tuple[float, float]]] = {}
    for r in records:
        if r.get("type") != "dispatch":
            continue
        t0, t1 = r.get("t_mono_start"), r.get("t_mono_end")
        if isinstance(t0, (int, float)) and isinstance(t1, (int, float)):
            lanes.setdefault((r.get("pid", 0), r.get("tid", 0)),
                             []).append((float(t0), float(t1)))
    busy = span = 0.0
    gaps: list[float] = []
    n = 0
    for windows in lanes.values():
        merged = _merge(windows)
        busy += sum(t1 - t0 for t0, t1 in merged)
        span += merged[-1][1] - merged[0][0]
        gaps.extend(b[0] - a[1] for a, b in zip(merged, merged[1:]))
        n += len(windows)
    p50 = _percentile(gaps, 50)
    p99 = _percentile(gaps, 99)
    return {
        "dispatches": n,
        "busy_secs": round(busy, 3),
        "busy_frac": round(busy / span, 4) if span > 0 else None,
        "gap_p50_ms": None if p50 is None else round(p50 * 1e3, 3),
        "gap_p99_ms": None if p99 is None else round(p99 * 1e3, 3),
    }


def _read_json(path: str) -> dict | None:
    try:
        with open(path) as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None


def read_heartbeats(root: str) -> dict[str, dict]:
    out: dict[str, dict] = {}
    hosts_dir = os.path.join(root, "hosts")
    try:
        names = sorted(os.listdir(hosts_dir))
    except OSError:
        return out
    for name in names:
        if name.endswith(".json"):
            rec = _read_json(os.path.join(hosts_dir, name))
            if rec and rec.get("owner"):
                out[str(rec["owner"])] = rec
    return out


def read_done_markers(root: str) -> list[dict]:
    out: list[dict] = []
    done_dir = os.path.join(root, "done")
    try:
        names = sorted(os.listdir(done_dir))
    except OSError:
        return out
    for name in names:
        if name.endswith(".json"):
            rec = _read_json(os.path.join(done_dir, name))
            if rec:
                out.append(rec)
    return out


def fleet_status(root: str, ttl: float = 60.0,
                 now: float | None = None) -> dict:
    """The aggregated per-host view (JSON-ready)."""
    now = time.time() if now is None else now
    journal = read_journal(root)
    beats = read_heartbeats(root)
    done = read_done_markers(root)

    by_host: dict[str, list[dict]] = {}
    for rec in journal:
        by_host.setdefault(str(rec.get("host")), []).append(rec)

    hosts: dict[str, dict] = {}
    for name in sorted(set(by_host) | set(beats)):
        recs = by_host.get(name, [])
        row = dispatch_stats(recs)
        for etype in _COUNTED:
            row[etype + "s"] = sum(1 for r in recs
                                   if r.get("type") == etype)
        row["attempts"] = max(
            [int(r.get("attempt", 1)) for r in recs], default=None)
        walls = [r["t_wall"] for r in recs
                 if isinstance(r.get("t_wall"), (int, float))]
        row["last_event_age_s"] = (round(now - max(walls), 1)
                                   if walls else None)
        beat = beats.get(name)
        if beat is None:
            row["beat"] = "none"
        elif beat.get("done"):
            row["beat"] = "done"
        else:
            age = now - float(beat.get("heartbeat", 0.0))
            row["beat"] = "alive" if age <= ttl else f"STALE {age:.0f}s"
        row["units_done"] = sum(1 for d in done if d.get("owner") == name)
        hosts[name] = row

    reclaimed = [
        {"unit": d.get("unit"), "attempt": int(d.get("attempt", 1)),
         "finished_by": d.get("owner"),
         "reclaimed_from": d.get("reclaimed_from")}
        for d in done if int(d.get("attempt", 1)) > 1
    ]
    return {
        "dir": os.path.abspath(root),
        "generated_at": now,
        "ttl_s": ttl,
        "hosts": hosts,
        "units_done": len(done),
        "reclaimed_units": reclaimed,
        "journal_records": len(journal),
    }


_COLUMNS = (
    ("beat", "beat"),
    ("busy_frac", "busy"),
    ("gap_p50_ms", "gap p50"),
    ("gap_p99_ms", "gap p99"),
    ("dispatches", "disp"),
    ("watchdog_fires", "wd"),
    ("breaker_fires", "brk"),
    ("sheds", "shed"),
    ("preempts", "preempt"),
    ("units_done", "units"),
    ("attempts", "att"),
    ("last_event_age_s", "last ev"),
)


def render_table(status: dict) -> str:
    rows = [["host"] + [h for _k, h in _COLUMNS]]
    for name, row in sorted(status["hosts"].items()):
        rows.append([name] + [
            "-" if row.get(k) is None else str(row.get(k))
            for k, _h in _COLUMNS])
    widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
    lines = ["  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip()
             for r in rows]
    lines.insert(1, "  ".join("-" * w for w in widths))
    tail = (f"{status['units_done']} unit(s) done, "
            f"{len(status['reclaimed_units'])} reclaimed, "
            f"{status['journal_records']} journal record(s)")
    for rec in status["reclaimed_units"]:
        tail += (f"\n  reclaimed: {rec['unit']} attempt {rec['attempt']} "
                 f"finished by {rec['finished_by']} "
                 f"(from {rec['reclaimed_from']})")
    return "\n".join(lines) + "\n" + tail


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="aggregate telemetry journals + fleet heartbeats "
                    "into one per-host status table")
    p.add_argument("--dir", required=True,
                   help="the shared dir: --telemetry journals and/or a "
                        "workqueue/heartbeat layout (hosts/, done/)")
    p.add_argument("--ttl", type=float, default=60.0,
                   help="heartbeat staleness bound (the workqueue lease "
                        "TTL; default 60s)")
    p.add_argument("--json", action="store_true",
                   help="emit the aggregate as one JSON object instead "
                        "of the table")
    args = p.parse_args(argv)

    status = fleet_status(args.dir, ttl=args.ttl)
    if not status["hosts"]:
        print(f"faa_status: nothing under {args.dir} (no journal-*.jsonl, "
              "no hosts/*.json)", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(status))
    else:
        print(render_table(status))
    return 0


if __name__ == "__main__":
    sys.exit(main())
