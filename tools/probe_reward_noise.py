"""Measure the TTA reward noise the TPE optimizer actually faces.

VERDICT r3, weak 3: the TPE-vs-random benchmark (docs/tpe_benchmark.md)
shows TPE's edge vanishing past reward noise sigma ~0.05, and the
driver's defense (the fold-quality gate keeps oracles strong enough
that sigma stays ~0.02) was validated only on glyph tasks.  This probe
measures sigma directly at any search shape: load the phase-1 fold
checkpoints of a finished (or partial) search run, evaluate a handful
of fixed candidate policies repeatedly with fresh augmentation draws,
and report the per-policy std of `top1_valid` — the quantity TPE
conditions on.

    python tools/probe_reward_noise.py <save_dir> -c confs/....yaml \
        [--dataroot ./data] [--folds 0] [--policies 3] [--draws 8]

Emits one JSON line: per-fold sigma estimates + the pooled estimate,
ready for docs/BENCHMARKS.md and comparable against the TPE benchmark's
noise grid.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("save_dir")
    p.add_argument("-c", "--conf", required=True)
    p.add_argument("--dataroot", default="./data")
    p.add_argument("--cv-ratio", type=float, default=0.4)
    p.add_argument("--folds", default="0")
    p.add_argument("--policies", type=int, default=3)
    p.add_argument("--draws", type=int, default=8)
    p.add_argument("--num-policy", type=int, default=5)
    p.add_argument("--num-op", type=int, default=2)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("override", nargs="*",
                   help="dotted conf overrides, e.g. dataset=... (must "
                        "match the search run's, or the checkpoint paths "
                        "and fold data will not line up)")
    args = p.parse_args(argv)

    import jax
    import jax.numpy as jnp

    from fast_autoaugment_tpu.core.config import load_config
    from fast_autoaugment_tpu.parallel.mesh import make_mesh
    from fast_autoaugment_tpu.policies.archive import policy_decoder, policy_to_tensor
    from fast_autoaugment_tpu.search.driver import (
        _FoldEval,
        _fold_ckpt_path,
        make_search_space,
    )
    from fast_autoaugment_tpu.search.tpe import TPE

    conf = load_config(args.conf, overrides=args.override)
    mesh = make_mesh()
    evaluator = _FoldEval(conf, args.dataroot, mesh,
                          num_policy=args.num_policy, num_op=args.num_op,
                          cv_ratio=args.cv_ratio, seed=args.seed)

    # sample candidate policies the way phase 2 does (TPE startup draws)
    tpe = TPE(make_search_space(args.num_policy, args.num_op), seed=args.seed)
    cands = [policy_decoder(tpe.suggest(), args.num_policy, args.num_op)
             for _ in range(args.policies)]

    out = {"metric": "tta_reward_noise", "draws": args.draws,
           "policies": args.policies, "folds": {}}
    sigmas = []
    for fold in [int(f) for f in args.folds.split(",")]:
        path = _fold_ckpt_path(args.save_dir, conf, fold, args.cv_ratio)
        if not os.path.exists(path):
            print(f"[noise] fold {fold}: no checkpoint at {path} — skipped",
                  file=sys.stderr)
            continue
        params, batch_stats = evaluator.load_fold(path)
        fold_stats = []
        for ci, cand in enumerate(cands):
            pol_t = jnp.asarray(policy_to_tensor(cand))
            vals = [
                evaluator.evaluate(
                    fold, params, batch_stats, pol_t,
                    jax.random.PRNGKey(1000 * fold + 37 * ci + d),
                )["top1_valid"]
                for d in range(args.draws)
            ]
            fold_stats.append({
                "mean": float(np.mean(vals)),
                "sigma": float(np.std(vals, ddof=1)),
            })
            sigmas.append(fold_stats[-1]["sigma"])
        out["folds"][str(fold)] = fold_stats
    if not sigmas:
        print("[noise] no folds probed", file=sys.stderr)
        return 1
    out["sigma_pooled"] = float(np.sqrt(np.mean(np.square(sigmas))))
    out["tpe_edge_context"] = (
        "docs/tpe_benchmark.md: TPE beats random for sigma <= 0.02, "
        "parity by sigma ~0.05-0.1"
    )
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
