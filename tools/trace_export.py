#!/usr/bin/env python
"""Render a telemetry flight-recorder journal as a Chrome trace.

    python tools/trace_export.py --telemetry DIR --out trace.json
    # then open chrome://tracing (or https://ui.perfetto.dev) and load it

Reads every ``journal-*.jsonl`` segment under the ``--telemetry`` dir
(``core/telemetry.py::FlightRecorder`` — one file set per process
chain, host/attempt identity in the filename and in every record) and
emits the Chrome trace-event format (the JSON Perfetto and
chrome://tracing both load):

- ``dispatch``/``compile`` events (and any record carrying a
  ``t_mono_start``/``t_mono_end`` pair) become COMPLETE ("X") slices on
  their real thread lane — per-actor TTA dispatches, trainer dispatch
  chunks, serve dispatches and compile windows all land where they
  actually ran;
- ``phase`` events become slices on two synthetic per-process lanes —
  "phase-1 (train)" and "phase-2 (search)" — so a PR-9 overlapped run
  renders fold k's search visibly overlapping fold k+1's training;
- everything else (``shed``, ``breaker_fire``, ``watchdog_fire``,
  ``lease``, ``trial``, ``checkpoint``, ``reload``, ``preempt``,
  ``scenario``, ``verdict``, ``mark``) becomes an INSTANT ("i")
  marker — so a game-day run (docs/GAMEDAYS.md) shows its scenario
  phases, kills and verdict rows on the same timeline as the plane's
  dispatches, sheds and scale events.

Clock alignment: monotonic stamps are consistent only within a
process, so each record's own ``(t_wall, t_mono)`` pair (taken at emit)
estimates that process's wall-minus-mono offset; slices are placed at
``offset + t_mono_start``.  Offsets are estimated per (host, pid) as
the median over that process's records, which absorbs per-record jitter
and aligns multiple hosts onto one shared wall timeline (good to NTP
skew — the same bound the workqueue lease protocol already accepts).

Host-only and dependency-free (no jax import): safe to run anywhere,
including next to a live run.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

#: synthetic tids for the phase-overlap lanes (well above real OS tids
#: never collide in practice; metadata names make them readable)
PHASE_LANES = {"phase1": 10_000_001, "phase2": 10_000_002}
PHASE_LANE_NAMES = {"phase1": "phase-1 (train)",
                    "phase2": "phase-2 (search)"}

#: journal event types rendered as duration slices when they carry a
#: mono window; everything else becomes an instant marker
_SLICE_TYPES = {"dispatch", "compile", "phase"}


def read_journal(directory: str) -> list[dict]:
    """Load every journal segment under `directory` (recursively — a
    fleet shares one dir, or each host nests its own), tolerating a
    torn trailing line per segment (killed writer)."""
    records: list[dict] = []
    pattern = os.path.join(directory, "**", "journal-*.jsonl")
    files = sorted(glob.glob(pattern, recursive=True))
    for path in files:
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # torn tail of a killed writer
                if isinstance(rec, dict) and "type" in rec:
                    records.append(rec)
    records.sort(key=lambda r: (str(r.get("host")), r.get("pid", 0),
                                r.get("seq", 0)))
    return records


def _median(xs: list[float]) -> float:
    xs = sorted(xs)
    n = len(xs)
    if n == 0:
        return 0.0
    mid = n // 2
    return xs[mid] if n % 2 else 0.5 * (xs[mid - 1] + xs[mid])


def _wall_offsets(records: list[dict]) -> dict[tuple, float]:
    """Per-(host, pid) wall-minus-mono offset (median over records)."""
    samples: dict[tuple, list[float]] = {}
    for r in records:
        tw, tm = r.get("t_wall"), r.get("t_mono")
        if isinstance(tw, (int, float)) and isinstance(tm, (int, float)):
            samples.setdefault((str(r.get("host")), r.get("pid", 0)),
                               []).append(float(tw) - float(tm))
    return {k: _median(v) for k, v in samples.items()}


def _args_of(rec: dict) -> dict:
    """Extra fields -> the slice's args payload (identity/clock fields
    are already encoded in pid/tid/ts)."""
    skip = {"type", "label", "t_wall", "t_mono", "t_mono_start",
            "t_mono_end", "host", "attempt", "pid", "tid", "thread",
            "seq"}
    return {k: v for k, v in rec.items() if k not in skip}


def journal_to_trace(records: list[dict]) -> dict:
    """Records -> ``{"traceEvents": [...], "displayTimeUnit": "ms"}``.

    pids are dense ints per (host, attempt, os-pid) with process_name
    metadata ``host/attempt/pid``; thread_name metadata carries the
    recorded thread names plus the two synthetic phase lanes."""
    offsets = _wall_offsets(records)
    if not records:
        return {"traceEvents": [], "displayTimeUnit": "ms"}

    # trace ts is microseconds from the earliest aligned wall stamp —
    # chrome://tracing renders absolute epoch µs poorly, so re-base
    def aligned_wall(rec: dict, mono: float) -> float:
        key = (str(rec.get("host")), rec.get("pid", 0))
        return offsets.get(key, 0.0) + float(mono)

    t_base: float | None = None
    for r in records:
        start = r.get("t_mono_start", r.get("t_mono"))
        if isinstance(start, (int, float)):
            w = aligned_wall(r, float(start))
            t_base = w if t_base is None else min(t_base, w)
    t_base = t_base or 0.0

    pid_map: dict[tuple, int] = {}
    events: list[dict] = []
    thread_named: set[tuple] = set()

    def pid_of(rec: dict) -> int:
        key = (str(rec.get("host")), rec.get("attempt", 1),
               rec.get("pid", 0))
        if key not in pid_map:
            pid_map[key] = len(pid_map) + 1
            events.append({
                "ph": "M", "name": "process_name", "pid": pid_map[key],
                "tid": 0,
                "args": {"name": f"{key[0]} a{key[1]} pid{key[2]}"},
            })
        return pid_map[key]

    def name_thread(pid: int, tid: int, name: str) -> None:
        if (pid, tid) in thread_named:
            return
        thread_named.add((pid, tid))
        events.append({"ph": "M", "name": "thread_name", "pid": pid,
                       "tid": tid, "args": {"name": name}})

    for rec in records:
        etype = str(rec.get("type"))
        label = rec.get("label") or etype
        pid = pid_of(rec)
        has_window = isinstance(rec.get("t_mono_start"), (int, float)) \
            and isinstance(rec.get("t_mono_end"), (int, float))
        if etype in _SLICE_TYPES and has_window:
            t0 = aligned_wall(rec, float(rec["t_mono_start"]))
            t1 = aligned_wall(rec, float(rec["t_mono_end"]))
            if etype == "phase":
                lane = rec.get("lane")
                if lane not in PHASE_LANES:
                    lane = "phase1" if str(label).startswith("phase1") \
                        else "phase2"
                tid = PHASE_LANES[lane]
                name_thread(pid, tid, PHASE_LANE_NAMES[lane])
            else:
                tid = int(rec.get("tid", 0))
                name_thread(pid, tid, str(rec.get("thread", f"tid{tid}")))
            events.append({
                "ph": "X", "name": str(label), "cat": etype,
                "pid": pid, "tid": tid,
                "ts": round((t0 - t_base) * 1e6, 3),
                "dur": round(max(0.0, t1 - t0) * 1e6, 3),
                "args": _args_of(rec),
            })
        else:
            tm = rec.get("t_mono")
            if not isinstance(tm, (int, float)):
                continue
            tid = int(rec.get("tid", 0))
            name_thread(pid, tid, str(rec.get("thread", f"tid{tid}")))
            events.append({
                "ph": "i", "name": f"{etype}:{label}", "cat": etype,
                "pid": pid, "tid": tid, "s": "t",
                "ts": round((aligned_wall(rec, float(tm)) - t_base) * 1e6,
                            3),
                "args": _args_of(rec),
            })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def validate_trace(trace: dict) -> list[str]:
    """Schema check against the Chrome trace-event format; returns a
    list of problems (empty = valid).  The round-trip test gates on
    this, so a format regression fails loudly instead of silently
    producing a file chrome://tracing refuses."""
    problems: list[str] = []
    if not isinstance(trace, dict):
        return ["trace must be a JSON object"]
    evs = trace.get("traceEvents")
    if not isinstance(evs, list):
        return ["traceEvents must be a list"]
    for i, ev in enumerate(evs):
        where = f"traceEvents[{i}]"
        if not isinstance(ev, dict):
            problems.append(f"{where}: not an object")
            continue
        ph = ev.get("ph")
        if ph not in ("X", "i", "M", "B", "E"):
            problems.append(f"{where}: unknown ph {ph!r}")
            continue
        for field in ("name", "pid", "tid"):
            if field not in ev:
                problems.append(f"{where}: missing {field}")
        if ph in ("X", "i"):
            ts = ev.get("ts")
            if not isinstance(ts, (int, float)) or ts < 0:
                problems.append(f"{where}: bad ts {ts!r}")
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                problems.append(f"{where}: bad dur {dur!r}")
        if ph == "i" and ev.get("s") not in ("t", "p", "g"):
            problems.append(f"{where}: instant event missing scope 's'")
    return problems


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="telemetry journal -> Chrome trace-event JSON "
                    "(chrome://tracing / Perfetto)")
    p.add_argument("--telemetry", required=True, metavar="DIR",
                   help="the --telemetry journal dir (FAA_TELEMETRY)")
    p.add_argument("--out", default="trace.json",
                   help="output path (default ./trace.json)")
    args = p.parse_args(argv)

    records = read_journal(args.telemetry)
    if not records:
        print(f"trace_export: no journal-*.jsonl records under "
              f"{args.telemetry}", file=sys.stderr)
        return 2
    trace = journal_to_trace(records)
    problems = validate_trace(trace)
    if problems:
        for pr in problems[:20]:
            print(f"trace_export: INVALID: {pr}", file=sys.stderr)
        return 1
    with open(args.out, "w") as fh:
        json.dump(trace, fh)
    slices = sum(1 for e in trace["traceEvents"] if e["ph"] == "X")
    marks = sum(1 for e in trace["traceEvents"] if e["ph"] == "i")
    print(f"trace_export: {len(records)} journal records -> "
          f"{slices} slices + {marks} markers -> {args.out} "
          f"(open in chrome://tracing or ui.perfetto.dev)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
