#!/usr/bin/env python
"""Multi-host MPMD fleet search bench (``make bench-fleet-search``).

Runs the SAME seeded search through two arms:

- **single**: the single-host ``--async-pipeline on`` scheduler (the
  PR-9 baseline — actors are threads);
- **fleet**: a real 3-process fleet — one LEARNER(+trainer) host and N
  ACTOR hosts (``search_cli --search-role``) over a shared
  ``--fleet-transport`` dir, with the telemetry journal pointed at the
  same dir so every host's evidence lands in one place.

The JSON line reports:

- **transport overhead** from the journaled ``round`` events:
  round publish->claim and reward return->tell-apply latencies
  (p50/p99), plus the measured learner-side cost per round (the
  publish write + the result read) against the ask(K) TPE latency
  already measured by ``tools/bench_tpe.py`` — the transport must stay
  cheaper than the host math it overlaps, or it becomes the new
  dispatch gap (the acceptance budget);
- **per-host busy fractions** from union-merged journal dispatch
  windows and the **journal-proven concurrent phase-1/phase-2 lanes on
  distinct host ids** (``tools/faa_status.py`` math — the same numbers
  ``make status`` renders);
- **byte-identity** of ``search_trials.json`` + ``final_policy.json``
  between the arms (the fleet determinism acceptance);
- wall per arm, stamped ``single_core_caveat``: every "host" here
  shares ONE core, so the wall ratio measures scheduling plumbing —
  the transferable evidence is the lane concurrency + the latency
  table, not wall.

Honors ``FAA_BENCH_REQUIRE_QUIET=1`` (refuses on a contended host,
exit 3).

    python tools/bench_fleet_search.py --num-search 8 --actor-hosts 2
    make bench-fleet-search
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
sys.path.insert(0, os.path.join(_REPO, "tools"))

_CONF_YAML = (
    "model:\n  type: wresnet10_1\ndataset: synthetic\naug: default\n"
    "cutout: 8\nbatch: 8\nepoch: 1\nlr: 0.05\n"
    "lr_schedule:\n  type: cosine\n"
    "optimizer:\n  type: sgd\n  decay: 0.0001\n  momentum: 0.9\n"
    "  nesterov: true\n")


def _pct(xs, q):
    import numpy as np

    xs = [x for x in xs if x is not None]
    return round(float(np.percentile(np.asarray(xs, float), q)), 3) \
        if xs else None


def _base_cmd(conf, dataroot, args, cache):
    return [
        sys.executable, "-m", "fast_autoaugment_tpu.launch.search_cli",
        "-c", conf, "--dataroot", dataroot,
        "--num-fold", str(args.num_fold),
        "--num-search", str(args.num_search),
        "--num-policy", str(args.num_policy),
        "--num-op", str(args.num_op), "--num-top", "2",
        "--trial-batch", str(args.trial_batch),
        "--until", "2", "--fold-quality-floor", "off",
        "--seed", str(args.seed), "--compile-cache", cache,
        "--async-pipeline", "on",
        "--pipeline-actors", str(args.actor_hosts),
        "--pipeline-queue-depth", str(args.queue_depth),
    ]


def round_transport_stats(journal: list[dict]) -> dict:
    """Per-unit transport latencies from the journaled round events:
    publish->claim (cross-host wall clocks — same machine here, NTP-
    bounded on a real fleet), return->apply (stamped by the learner at
    adoption), and the learner's measured per-round transport cost
    (publish write + result read — the part that could crowd the ask
    horizon)."""
    publish: dict[str, dict] = {}
    claim: dict[str, dict] = {}
    apply_: dict[str, dict] = {}
    for r in journal:
        if r.get("type") != "round":
            continue
        unit = str(r.get("label"))
        a = r.get("action")
        if a == "publish":
            publish[unit] = r
        elif a == "claim" and unit not in claim:  # first claim wins
            claim[unit] = r
        elif a == "apply":
            apply_[unit] = r
    pub_to_claim = [
        (claim[u]["t_wall"] - publish[u]["t_wall"]) * 1e3
        for u in publish if u in claim
        if isinstance(publish[u].get("t_wall"), (int, float))
        and isinstance(claim[u].get("t_wall"), (int, float))
    ]
    ret_to_apply = [r.get("return_to_apply_ms") for r in apply_.values()]
    learner_cost = [
        (publish[u].get("publish_secs") or 0.0) * 1e3
        + (apply_[u].get("poll_secs") or 0.0) * 1e3
        for u in publish if u in apply_
    ]
    return {
        "rounds_published": len(publish),
        "rounds_claimed": len(claim),
        "rounds_applied": len(apply_),
        "publish_to_claim_ms": {"p50": _pct(pub_to_claim, 50),
                                "p99": _pct(pub_to_claim, 99)},
        "return_to_apply_ms": {"p50": _pct(ret_to_apply, 50),
                               "p99": _pct(ret_to_apply, 99)},
        "learner_cost_per_round_ms": {"p50": _pct(learner_cost, 50),
                                      "p99": _pct(learner_cost, 99)},
    }


def run_fleet_search_bench(args, workdir: str) -> dict:
    from faa_status import (
        dispatch_stats,
        read_heartbeats,
        search_fleet_status,
    )
    from trace_export import read_journal

    conf = os.path.join(workdir, "conf.yaml")
    with open(conf, "w") as fh:
        fh.write(_CONF_YAML)
    cache = os.path.join(workdir, "compile_cache")
    base = _base_cmd(conf, workdir, args, cache)
    env = dict(os.environ, JAX_PLATFORMS=os.environ.get(
        "JAX_PLATFORMS", "cpu"))
    env.pop("FAA_FAULT", None)

    # ---- arm 1: single host (threads); also warms the compile cache
    single_dir = os.path.join(workdir, "single")
    t0 = time.time()
    r = subprocess.run(base + ["--save-dir", single_dir], env=env,
                       capture_output=True, text=True,
                       timeout=args.timeout, cwd=_REPO)
    single_wall = time.time() - t0
    if r.returncode != 0:
        raise RuntimeError(
            f"single-host arm failed rc={r.returncode}:\n"
            + r.stdout[-3000:])

    # ---- arm 2: 1 learner + N actor hosts over the shared transport
    transport = os.path.join(workdir, "transport")
    fleet_dir = os.path.join(workdir, "fleet")
    fleet_base = base + ["--save-dir", fleet_dir,
                         "--fleet-transport", transport,
                         "--telemetry", transport,
                         "--lease-ttl", str(args.lease_ttl)]
    t0 = time.time()
    procs = [subprocess.Popen(
        fleet_base + ["--search-role", "learner", "--host-id", "0"],
        env=dict(env, FAA_HOST_ID="0"), stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True, cwd=_REPO)]
    for i in range(1, args.actor_hosts + 1):
        procs.append(subprocess.Popen(
            fleet_base + ["--search-role", "actor",
                          "--host-id", str(i)],
            env=dict(env, FAA_HOST_ID=str(i)), stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True, cwd=_REPO))
    outs = []
    try:
        for p in procs:
            outs.append(p.communicate(timeout=args.timeout)[0])
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    fleet_wall = time.time() - t0
    if any(p.returncode for p in procs):
        raise RuntimeError(
            "fleet arm failed rcs="
            + str([p.returncode for p in procs]) + ":\n"
            + "\n".join(o[-1500:] for o in outs))

    # ---- byte-identity: the fleet determinism acceptance
    trials_match = (
        open(os.path.join(single_dir, "search_trials.json"), "rb").read()
        == open(os.path.join(fleet_dir, "search_trials.json"),
                "rb").read())
    final_match = (
        open(os.path.join(single_dir, "final_policy.json"), "rb").read()
        == open(os.path.join(fleet_dir, "final_policy.json"),
                "rb").read())

    # ---- journal evidence (the same math make status renders)
    journal = read_journal(transport)
    beats = read_heartbeats(transport)
    by_host: dict[str, list[dict]] = {}
    for rec in journal:
        by_host.setdefault(str(rec.get("host")), []).append(rec)
    per_host = {h: dict(dispatch_stats(rs),
                        role=(beats.get(h) or {}).get("role"))
                for h, rs in sorted(by_host.items())}
    fleet_topo = search_fleet_status(transport, journal, beats) or {}
    transport_stats = round_transport_stats(journal)

    result = json.load(open(os.path.join(fleet_dir,
                                         "search_result.json")))
    return {
        "bench": "fleet_search",
        "actor_hosts": args.actor_hosts,
        "num_fold": args.num_fold,
        "num_search": args.num_search,
        "trial_batch": args.trial_batch,
        "window": args.actor_hosts + args.queue_depth,
        "single_wall_secs": round(single_wall, 3),
        "fleet_wall_secs": round(fleet_wall, 3),
        "wall_ratio_single_over_fleet": round(
            single_wall / fleet_wall, 3) if fleet_wall else None,
        "artifacts_bitwise_match": bool(trials_match and final_match),
        "transport": transport_stats,
        "per_host": per_host,
        "concurrent_lane_secs": fleet_topo.get("concurrent_lane_secs"),
        "concurrent_lane_pairs": fleet_topo.get("concurrent_lane_pairs"),
        "degraded": result.get("degraded"),
        "reclaimed_units": result.get("reclaimed_units"),
        "compile_cache": result.get("compile_cache"),
        # every "host" shares ONE core: the wall ratio is scheduling
        # plumbing, NOT the multi-host win — the transferable evidence
        # is concurrent_lane_secs on distinct host ids plus the
        # transport latency table staying under the ask(K) headroom
        "single_core_caveat": True,
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--num-fold", type=int, default=2)
    p.add_argument("--num-search", type=int, default=8)
    p.add_argument("--num-policy", type=int, default=1)
    p.add_argument("--num-op", type=int, default=1)
    p.add_argument("--trial-batch", type=int, default=2)
    p.add_argument("--actor-hosts", type=int, default=2)
    p.add_argument("--queue-depth", type=int, default=2)
    p.add_argument("--lease-ttl", type=float, default=30.0)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--timeout", type=float, default=1800.0)
    p.add_argument("--workdir", default=None,
                   help="scratch dir (default: a fresh tempdir, "
                        "removed on success)")
    p.add_argument("--out", default=None,
                   help="also write the JSON line here")
    args = p.parse_args(argv)

    from bench import (
        host_contention_stamp,
        refuse_or_flag_contention,
        telemetry_stamp,
    )
    from bench_tpe import bench_ask_tell_latency

    contention = refuse_or_flag_contention(host_contention_stamp())
    print(f"contention: {json.dumps(contention)}")

    workdir = args.workdir or tempfile.mkdtemp(prefix="faa_bench_fleet_")
    made_temp = args.workdir is None
    record = run_fleet_search_bench(args, workdir)
    record.update(telemetry_stamp(contention=contention))

    # the acceptance budget: added learner-side overhead per round must
    # stay within the ask(K) host latency the pipeline already pays —
    # otherwise the transport becomes the new dispatch gap
    tpe_rows = bench_ask_tell_latency(ks=(args.trial_batch,), reps=20)
    record["tpe_latency"] = tpe_rows
    ask_ms = tpe_rows[0]["ask_ms_mean"]
    learner_ms = (record["transport"]["learner_cost_per_round_ms"]["p99"]
                  or 0.0)
    record["transport_within_ask_budget"] = bool(learner_ms <= ask_ms)

    t = record["transport"]
    print(f"transport: publish->claim p50 "
          f"{t['publish_to_claim_ms']['p50']}ms p99 "
          f"{t['publish_to_claim_ms']['p99']}ms; return->apply p50 "
          f"{t['return_to_apply_ms']['p50']}ms p99 "
          f"{t['return_to_apply_ms']['p99']}ms; learner cost/round p99 "
          f"{t['learner_cost_per_round_ms']['p99']}ms vs ask({args.trial_batch}) "
          f"{ask_ms}ms")
    for host, row in record["per_host"].items():
        print(f"  {host}: role={row.get('role')} "
              f"busy_frac={row.get('busy_frac')} "
              f"dispatches={row.get('dispatches')}")
    print(f"concurrent phase-1/phase-2 lanes on distinct hosts: "
          f"{record['concurrent_lane_secs']}s "
          f"(wall single/fleet {record['wall_ratio_single_over_fleet']}x "
          "— single_core_caveat)")
    ok = (record["artifacts_bitwise_match"]
          and record["transport_within_ask_budget"]
          and (record["concurrent_lane_secs"] or 0.0) > 0.0)
    print("acceptance (bitwise artifacts AND transport <= ask(K) budget "
          "AND journal-proven cross-host lane overlap): "
          f"{'PASS' if ok else 'FAIL'}")

    line = json.dumps(record)
    print(line)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(line + "\n")
    if made_temp:
        shutil.rmtree(workdir, ignore_errors=True)
    return 0 if ok else 4


if __name__ == "__main__":
    raise SystemExit(main())
