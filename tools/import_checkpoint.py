"""Import a reference PyTorch checkpoint into this framework's format.

The reference publishes trained .pth checkpoints (its README download
table).  This tool converts one into a fast-autoaugment-tpu msgpack
checkpoint that ``--only-eval`` / resume can consume:

    python tools/import_checkpoint.py --pth wresnet40x2_cifar10.pth \
        --model wresnet40_2 --dataset cifar10 --out ckpt/wrn.msgpack

Handles the reference's checkpoint dict layout {'model': state_dict,
'epoch': ..., 'ema': ...} as well as bare state_dicts, and strips DDP
'module.' prefixes (reference ``train.py:191-218``).
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def family_of(model_type: str) -> str:
    if model_type.startswith("wresnet"):
        return "wideresnet"
    if model_type.startswith("resnet"):
        return "resnet"
    if model_type.startswith("shakeshake"):
        return "shakeshake_next" if "next" in model_type else "shakeshake"
    if model_type == "pyramid":
        return "pyramid"
    if model_type.startswith("efficientnet"):
        return "efficientnet"
    raise ValueError(f"no importer for model type {model_type!r}")


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--pth", required=True)
    p.add_argument("--model", required=True, help="model type (e.g. wresnet40_2)")
    p.add_argument("--dataset", default="cifar10")
    p.add_argument("--condconv-num-expert", type=int, default=0,
                   help="expert count for efficientnet-*-condconv checkpoints")
    p.add_argument("--out", required=True, help="output .msgpack path")
    args = p.parse_args(argv)

    import torch

    from fast_autoaugment_tpu.core.checkpoint import save_checkpoint
    from fast_autoaugment_tpu.utils.interop import import_state_dict

    ckpt = torch.load(args.pth, map_location="cpu", weights_only=False)
    if isinstance(ckpt, dict) and "model" in ckpt:
        sd, epoch = ckpt["model"], int(ckpt.get("epoch", 0))
        ema_sd = ckpt.get("ema")
    else:
        sd, epoch, ema_sd = ckpt, 0, None

    family = family_of(args.model)
    flax_model = None
    if family == "efficientnet":
        # CondConv expert unflattening needs the target model's block shapes
        from fast_autoaugment_tpu.models import get_model, num_class

        flax_model = get_model(
            {"type": args.model, "dataset": args.dataset,
             "condconv_num_expert": args.condconv_num_expert},
            num_class(args.dataset),
        )

    variables = import_state_dict(sd, family, model=flax_model)
    state = {
        "step": 0,
        "params": variables["params"],
        "batch_stats": variables["batch_stats"],
    }
    if ema_sd:
        ema_vars = import_state_dict(ema_sd, family, model=flax_model)
        state["ema"] = {"params": ema_vars["params"],
                        "batch_stats": ema_vars["batch_stats"]}
    save_checkpoint(
        args.out, state,
        {"epoch": epoch, "imported_from": args.pth, "has_ema": bool(ema_sd)},
    )
    print(f"imported {args.pth} (epoch {epoch}) -> {args.out}")


if __name__ == "__main__":
    main()
