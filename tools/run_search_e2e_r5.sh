#!/bin/bash
# Round-5 flagship evidence run (VERDICT round 4, next-steps 3+4).
#
# Extends the committed pose300 search artifact to n>=30 seeds/mode and
# adds the random-policy control arm:
#   - seeds the run dir from search_e2e_r4_ext (phase-1 checkpoints,
#     trial log, audit cache, 16 completed retrains per mode resume
#     instantly — only new work pays);
#   - --num-result-per-cv 30 pushes default+augment from n=16 to n=30;
#   - --phase3-random draws an equal-size uniform policy set from the
#     same space, audits it identically, and retrains the SAME seeds —
#     the three-way searched vs random vs default comparison.
# The CLI persists search_result.json after EVERY phase-3 run, so the
# artifact is valid at whatever n the round boundary interrupts.
#
#   bash tools/run_search_e2e_r5.sh [seeds]
set -euo pipefail
cd "$(dirname "$0")/.."

SEEDS="${1:-30}"
SRC=search_e2e_r4_ext
SAVE=search_e2e_r5

if [ ! -d "$SAVE" ] && [ -d "$SRC" ]; then
    cp -r "$SRC" "$SAVE"
    rm -f "$SAVE/search_result.json"   # recomputed with r5 fields
fi

# clean CPU env (the dead-tunnel PJRT plugin wedges any interpreter
# that keeps PALLAS_AXON_POOL_IPS; tests/conftest.py)
env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python -m fast_autoaugment_tpu.launch.search_cli \
    -c confs/wresnet10x1_shapes_hard.yaml \
    --dataroot ./data \
    --save-dir "$SAVE" \
    --seed 1 \
    --num-result-per-cv "$SEEDS" \
    --phase3-random \
    "dataset=synthetic_shapes_pose300" \
    2>&1 | tee -a "$SAVE.log"

git add -f "$SAVE/search_result.json" "$SAVE/final_policy.json" \
    "$SAVE/audit.json" "$SAVE/audit_random.json" \
    "$SAVE/random_final_policy.json" "$SAVE/search_trials.json" \
    "$SAVE.log" 2>/dev/null || true
echo "[e2e-r5] summary artifacts staged; commit them to activate the tests"
