"""Annotate committed search artifacts with backend provenance.

VERDICT r4 weak 5: ``tpu_secs_*`` / ``tpu_hours_total`` in artifacts
recorded before round 5 are wall x device_count on whatever backend ran
— for every committed run so far, the CPU host — and the artifact alone
did not say so.  ``search_policies`` now records backend/device_kind/
device_count at run time; this one-shot tool back-fills the SAME fields
into already-committed artifacts, explicitly marked ``annotated_post_
hoc`` with the evidence source (the run log that records the
``JAX_PLATFORMS=cpu`` invocation), and mirrors the legacy ``tpu_*``
keys under the honest ``device_*`` names.  Measured values are never
touched — this adds provenance, it does not re-measure.

    python tools/annotate_backend.py search_refscale_costcert/search_result.json \
        --backend cpu --source search_refscale_costcert.log
"""

from __future__ import annotations

import argparse
import json
import os

_LEGACY_KEYS = ("tpu_secs_phase1", "tpu_secs_phase2", "tpu_secs_audit",
                "tpu_secs_audit_random")


def _write_json_atomic(path: str, obj) -> None:
    # inlined from search.driver.write_json_atomic: a JSON-editing tool
    # must not import the jax stack (on this host any jax import claims
    # the single TPU, and a dead tunnel can abort the process)
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(obj, fh)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)


def annotate(path: str, backend: str, device_kind: str, device_count: int,
             source: str, force: bool = False) -> dict:
    with open(path) as fh:
        artifact = json.load(fh)

    def put(key, value):
        if force:
            artifact[key] = value
        else:
            artifact.setdefault(key, value)

    put("backend", backend)
    put("device_kind", device_kind)
    put("device_count", device_count)
    put("backend_note",
        f"annotated_post_hoc: fields added by tools/annotate_backend.py, "
        f"measured values untouched; evidence: {source}")
    for key in _LEGACY_KEYS:
        if key in artifact:
            artifact.setdefault(key.replace("tpu_", "device_", 1),
                                artifact[key])
    if "tpu_hours_total" in artifact:
        artifact.setdefault("device_hours_total", artifact["tpu_hours_total"])
    _write_json_atomic(path, artifact)
    return artifact


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("paths", nargs="+")
    p.add_argument("--backend", required=True)
    p.add_argument("--device-kind", default=None)
    p.add_argument("--device-count", type=int, default=1)
    p.add_argument("--source", required=True,
                   help="where the backend is evidenced (run log path)")
    p.add_argument("--force", action="store_true",
                   help="overwrite existing provenance fields (default "
                        "setdefault-only, which silently keeps stale values)")
    args = p.parse_args(argv)
    for path in args.paths:
        artifact = annotate(path, args.backend,
                            args.device_kind or args.backend,
                            args.device_count, args.source, force=args.force)
        print(f"{path}: backend={artifact['backend']} "
              f"device_kind={artifact['device_kind']} "
              f"device_count={artifact['device_count']} "
              f"device_hours_total={artifact.get('device_hours_total')}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
