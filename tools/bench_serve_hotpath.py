#!/usr/bin/env python
"""Serving data-plane hotpath bench (``make bench-serve-hotpath``).

Measures the per-request HOST overhead the zero-copy data plane
removes, as two real ``serve_cli`` replicas under saturating
closed-loop HTTP load:

- **legacy**: default replica (no donation, no double-buffering),
  clients speak the npz wire format over a FRESH TCP connection per
  request — the pre-zero-copy client shape, byte for byte;
- **zerocopy**: ``--donate --double-buffer`` replica, clients speak
  the raw tensor wire format (FAAR1) over pooled keep-alive
  connections (``wire.ConnectionPool``).

Host overhead is taken from the replica's own instrumentation, not
inferred from wall latency: each round snapshots
``faa_serve_stage_seconds_sum{stage=}`` before and after the load
window and charges the HOST-side stages (decode + pad + h2d + scatter
+ serialize) per request served in that window.  ``queue_wait`` and
``dispatch`` are excluded — queueing and device time are what the
overhead rides on top of, and in the pipelined (double-buffered)
replica the dispatch wall includes overlap wait by design.

Arms run as PAIRED ALTERNATING rounds (legacy,zerocopy /
zerocopy,legacy / ...) with per-arm MEDIANS — the 1-core A/B
discipline (docs/BENCHMARKS.md measurement notes).  Before the load
rounds, one fixed seeded batch is pushed through BOTH replicas in BOTH
wire formats and the four decoded results are compared bitwise — the
acceptance gate that the zero-copy plane (and the raw format) changes
no served byte.

    python tools/bench_serve_hotpath.py [--pairs 3]
        [--seconds-per-arm 2] [--image 8] [--shapes 1,4]
        [--out BENCH_r09_serve_hotpath.json]
"""

from __future__ import annotations

import argparse
import io
import json
import os
import re
import signal
import subprocess
import sys
import tempfile
import threading
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
sys.path.insert(0, os.path.join(_REPO, "tools"))

from bench_router import _http, _median, wait_port_record, wait_ready

#: one deterministic single-sub policy (exact dispatch — the fast shape)
POLICY = [[["Rotate", 0.5, 0.4], ["Invert", 0.2, 0.0]]]

#: the host-side stages charged as per-request overhead (decode /
#: serialize live in the HTTP front, pad / h2d / scatter around the
#: dispatch); queue_wait and dispatch are the work itself, not overhead
HOST_STAGES = ("decode", "pad", "h2d", "scatter", "serialize")

_SUM_RE = re.compile(
    r'^faa_serve_stage_seconds_sum\{[^}]*stage="([^"]+)"[^}]*\} '
    r'([0-9.eE+-]+)$')
_REQ_RE = re.compile(r"^faa_serve_requests_total(?:\{[^}]*\})? "
                     r"([0-9.eE+-]+)$")


def scrape_stages(host: str, port: int) -> tuple[dict, float]:
    """One ``/metrics`` scrape -> (stage -> seconds-sum, requests
    served).  Missing stages read as 0 (a fresh replica has not lazily
    registered them yet)."""
    _s, _h, body = _http(host, port, "GET", "/metrics", timeout=10.0)
    stages: dict[str, float] = {}
    requests = 0.0
    for line in body.decode().splitlines():
        m = _SUM_RE.match(line)
        if m:
            stages[m.group(1)] = float(m.group(2))
            continue
        m = _REQ_RE.match(line)
        if m:
            requests = float(m.group(1))
    return stages, requests


def run_arm(name: str, port: int, body: bytes, ctype: str, pool,
            seconds: float, concurrency: int) -> dict:
    """One closed-loop load round against one replica: `concurrency`
    client threads re-posting `body` until the window closes.  The
    legacy arm pays a fresh TCP connection per request (pool=None);
    the zerocopy arm reuses pooled keep-alive connections."""
    import numpy as np

    lock = threading.Lock()
    lats: list[float] = []
    failed = [0]
    stop_at = time.perf_counter() + seconds
    headers = {"Content-Type": ctype}

    def client():
        while time.perf_counter() < stop_at:
            t0 = time.perf_counter()
            try:
                if pool is None:
                    status, _h, _d = _http("127.0.0.1", port, "POST",
                                           "/augment", body, headers)
                else:
                    status, _h, _d = pool.request("127.0.0.1", port,
                                                  "POST", "/augment",
                                                  body, headers)
            except OSError:
                with lock:
                    failed[0] += 1
                continue
            wall = time.perf_counter() - t0
            with lock:
                if status == 200:
                    lats.append(wall)
                else:
                    failed[0] += 1

    before, req_before = scrape_stages("127.0.0.1", port)
    threads = [threading.Thread(target=client, daemon=True)
               for _ in range(max(1, concurrency))]
    t_start = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=seconds + 60.0)
    wall = time.perf_counter() - t_start
    after, req_after = scrape_stages("127.0.0.1", port)

    served = req_after - req_before
    host_s = sum(after.get(s, 0.0) - before.get(s, 0.0)
                 for s in HOST_STAGES)
    lat_ms = np.asarray(lats) * 1e3 if lats else np.asarray([0.0])
    return {
        "arm": name,
        "requests_ok": len(lats),
        "requests_failed": failed[0],
        "rps": round(len(lats) / wall, 1) if wall > 0 else 0.0,
        "latency_ms": {
            "p50": round(float(np.percentile(lat_ms, 50)), 3),
            "p99": round(float(np.percentile(lat_ms, 99)), 3),
        },
        "host_overhead_ms_per_request": (
            round(host_s / served * 1e3, 4) if served else None),
        "host_stage_ms_per_request": {
            s: round((after.get(s, 0.0) - before.get(s, 0.0))
                     / served * 1e3, 4)
            for s in HOST_STAGES} if served else {},
        "requests_served_window": int(served),
    }


def bitwise_probe(ports: dict, images, seeds) -> dict:
    """Push ONE fixed seeded batch through both replicas in both wire
    formats; decode the four results and compare bitwise.  The raw
    format carries the per-image PRNG keys the npz path derives
    server-side (serve_cli ``_seed_keys``), so all four requests name
    the identical device computation."""
    import jax
    import numpy as np

    from fast_autoaugment_tpu.serve import wire

    keys = np.asarray(
        jax.vmap(jax.random.PRNGKey)(
            np.asarray(seeds, np.int64) & 0x7FFFFFFF), np.uint32)

    buf = io.BytesIO()
    np.savez(buf, images=images, seeds=np.asarray(seeds, np.int64))
    npz_body = buf.getvalue()
    raw_body = wire.encode_raw(images, seeds=keys)

    results = {}
    for arm, port in ports.items():
        status, _h, data = _http(
            "127.0.0.1", port, "POST", "/augment", npz_body,
            {"Content-Type": "application/octet-stream"}, timeout=60.0)
        if status != 200:
            raise RuntimeError(f"{arm} npz probe failed: {status}")
        results[(arm, "npz")] = np.asarray(
            np.load(io.BytesIO(data))["images"])
        status, _h, data = _http(
            "127.0.0.1", port, "POST", "/augment", raw_body,
            {"Content-Type": wire.RAW_CONTENT_TYPE}, timeout=60.0)
        if status != 200:
            raise RuntimeError(f"{arm} raw probe failed: {status}")
        out, _k = wire.decode_raw(data)
        results[(arm, "raw")] = np.asarray(out)

    ref = results[("legacy", "npz")]
    verdict = {f"{arm}_{fmt}": bool(np.array_equal(ref, r))
               for (arm, fmt), r in results.items()}
    return {
        "bitwise_match": all(verdict.values()),
        "per_request": verdict,
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--pairs", type=int, default=3,
                   help="paired alternating rounds per arm (medians "
                        "reported)")
    p.add_argument("--seconds-per-arm", type=float, default=2.0)
    p.add_argument("--image", type=int, default=8)
    p.add_argument("--shapes", default="1,4")
    p.add_argument("--imgs-per-request", type=int, default=4)
    p.add_argument("--concurrency", type=int, default=4)
    p.add_argument("--max-wait-ms", type=float, default=2.0)
    p.add_argument("--startup-timeout", type=float, default=180.0)
    p.add_argument("--out", default="",
                   help="also write the JSON line here "
                        "(BENCH_r09_serve_hotpath.json)")
    args = p.parse_args(argv)

    from bench import (
        host_contention_stamp,
        refuse_or_flag_contention,
        telemetry_stamp,
    )

    contention = refuse_or_flag_contention(host_contention_stamp())

    import numpy as np

    from fast_autoaugment_tpu.serve import wire

    procs: list[subprocess.Popen] = []
    out = {}
    with tempfile.TemporaryDirectory(prefix="bench_hotpath_") as tmp:
        port_dir = os.path.join(tmp, "replicas")
        policy_path = os.path.join(tmp, "policy.json")
        with open(policy_path, "w") as fh:
            json.dump(POLICY, fh)

        env = dict(os.environ, JAX_PLATFORMS="cpu")
        try:
            # ---- the two replicas: identical policy/shapes, the data
            # plane is the only variable
            common = [
                sys.executable, "-m",
                "fast_autoaugment_tpu.serve.serve_cli",
                "--policy", policy_path, "--image", str(args.image),
                "--shapes", args.shapes,
                "--max-wait-ms", str(args.max_wait_ms),
                "--port", "0", "--port-dir", port_dir,
            ]
            procs.append(subprocess.Popen(
                common + ["--host-tag", "legacy"], env=env, cwd=_REPO))
            procs.append(subprocess.Popen(
                common + ["--host-tag", "zerocopy", "--donate",
                          "--double-buffer"], env=env, cwd=_REPO))
            ports = {}
            for i, arm in enumerate(("legacy", "zerocopy")):
                port = wait_port_record(port_dir, arm, procs[i],
                                        args.startup_timeout)
                wait_ready("127.0.0.1", port, procs[i],
                           args.startup_timeout)
                ports[arm] = port

            rng = np.random.default_rng(0)
            images = rng.integers(
                0, 256, (args.imgs_per_request, args.image, args.image,
                         3), dtype=np.uint8)
            seeds = np.arange(args.imgs_per_request)

            # ---- acceptance gate first: both wire formats, both data
            # planes, one seeded batch, bitwise
            bitwise = bitwise_probe(ports, images, seeds)

            # ---- the load bodies (no seeds: the latency rounds reuse
            # the replica's default keys; determinism is the probe's
            # job).  Same pixels on both arms.
            buf = io.BytesIO()
            np.savez(buf, images=images)
            npz_body = buf.getvalue()
            raw_body = wire.encode_raw(images)
            pool = wire.ConnectionPool(
                timeout_s=30.0, max_idle_per_key=max(1, args.concurrency))

            def one_round(name: str) -> dict:
                if name == "legacy":
                    return run_arm(name, ports[name], npz_body,
                                   "application/octet-stream", None,
                                   args.seconds_per_arm,
                                   args.concurrency)
                return run_arm(name, ports[name], raw_body,
                               wire.RAW_CONTENT_TYPE, pool,
                               args.seconds_per_arm, args.concurrency)

            # warm both dispatch paths out of the measured windows
            for name, port in ports.items():
                body = npz_body if name == "legacy" else raw_body
                ctype = ("application/octet-stream" if name == "legacy"
                         else wire.RAW_CONTENT_TYPE)
                _http("127.0.0.1", port, "POST", "/augment", body,
                      {"Content-Type": ctype}, timeout=60.0)

            rounds = []
            for i in range(max(1, args.pairs)):
                order = (("legacy", "zerocopy") if i % 2 == 0
                         else ("zerocopy", "legacy"))
                for name in order:
                    rounds.append(one_round(name))

            meds = {}
            for name in ("legacy", "zerocopy"):
                rows = [r for r in rounds if r["arm"] == name]
                ovh = [r["host_overhead_ms_per_request"] for r in rows
                       if r["host_overhead_ms_per_request"] is not None]
                meds[name] = {
                    "rps_median": round(_median(
                        [r["rps"] for r in rows]), 1),
                    "p50_ms_median": round(_median(
                        [r["latency_ms"]["p50"] for r in rows]), 3),
                    "p99_ms_median": round(_median(
                        [r["latency_ms"]["p99"] for r in rows]), 3),
                    "host_overhead_ms_median": round(_median(ovh), 4),
                    "requests_ok": sum(r["requests_ok"] for r in rows),
                    "requests_failed": sum(r["requests_failed"]
                                           for r in rows),
                }
            ratio = (meds["legacy"]["host_overhead_ms_median"]
                     / meds["zerocopy"]["host_overhead_ms_median"]
                     if meds["zerocopy"]["host_overhead_ms_median"]
                     else None)
            out = {
                "metric": "serve_hotpath_host_overhead",
                "pairs": args.pairs,
                "seconds_per_arm": args.seconds_per_arm,
                "image": args.image,
                "shapes": args.shapes,
                "imgs_per_request": args.imgs_per_request,
                "concurrency": args.concurrency,
                "host_stages": list(HOST_STAGES),
                "arms": meds,
                "legacy_over_zerocopy_host_overhead": (
                    round(ratio, 2) if ratio else None),
                "client_connections": pool.stats(),
                **bitwise,
                "rounds": rounds,
                # every process shares one core: absolute rps is
                # plumbing-level; the per-request host-overhead ratio
                # is the portable number (docs/BENCHMARKS.md)
                "single_core_caveat": True,
                **telemetry_stamp(contention=contention),
            }
            pool.close_all()
        finally:
            for proc in reversed(procs):
                if proc.poll() is None:
                    try:
                        proc.send_signal(signal.SIGTERM)
                    except ProcessLookupError:
                        pass
            deadline = time.monotonic() + 30.0
            for proc in procs:
                left = max(0.5, deadline - time.monotonic())
                try:
                    proc.wait(timeout=left)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait(timeout=5.0)

    line = json.dumps(out)
    print(line)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(line + "\n")
    ok = bool(out) and out.get("bitwise_match") \
        and out["arms"]["legacy"]["requests_ok"] > 0 \
        and out["arms"]["zerocopy"]["requests_ok"] > 0
    return 0 if ok else 4


if __name__ == "__main__":
    raise SystemExit(main())
