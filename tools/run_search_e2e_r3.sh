#!/bin/bash
# Round-3 end-to-end search validation (VERDICT round 2, next-step 1).
#
# Runs the full 3-phase search on the glyph task with the round-3
# selection guards enabled (fold-oracle quality gate, longer phase-1
# pretraining, per-sub-policy audit) and an accuracy-headroom-calibrated
# train-set size.  MUST run on the real TPU chip (ambient env); takes
# roughly an hour.  Artifacts land in search_e2e_r3/ (summary JSONs are
# committed; bulk outputs are gitignored).
#
#   bash tools/run_search_e2e_r3.sh [dataset] [save_dir]
set -euo pipefail
cd "$(dirname "$0")/.."

DATASET="${1:-synthetic_shapes_n120}"
SAVE="${2:-search_e2e_r3}"

python -m fast_autoaugment_tpu.launch.search_cli \
    -c confs/wresnet10x1_shapes_hard.yaml \
    --dataroot ./data \
    --save-dir "$SAVE" \
    --num-search 100 \
    --num-top 10 \
    --seed 1 \
    --fold-quality-floor 0.60 \
    --fold-retrain-tries 2 \
    --phase1-epochs 200 \
    --audit-floor 0.7 \
    "dataset=$DATASET" \
    2>&1 | tee "$SAVE.log"
