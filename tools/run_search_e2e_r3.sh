#!/bin/bash
# Round-3 end-to-end search validation (VERDICT round 2, next-step 1).
#
# Runs the full 3-phase search on the pose-varying glyph task with the
# round-3 selection guards enabled (fold-oracle quality gate, longer
# phase-1 pretraining, per-sub-policy audit).  Artifacts land in the
# save dir below (summary JSONs are force-added to git; bulk outputs
# are gitignored).  Takes ~1 h on the TPU chip, ~3 h on the CPU host.
#
#   bash tools/run_search_e2e_r3.sh [dataset] [save_dir]
set -euo pipefail
cd "$(dirname "$0")/.."

# synthetic_shapes_pose300: per-sample rotation/scale that default
# crop+flip cannot cover — the regime where searched augmentation
# demonstrably pays (default 0.772 vs augmented 0.788 mean test top-1
# over 5 seeds at these exact settings; docs/search_postmortem_r2.md)
DATASET="${1:-synthetic_shapes_pose300}"
SAVE="${2:-search_e2e_r3_pose}"

python -m fast_autoaugment_tpu.launch.search_cli \
    -c confs/wresnet10x1_shapes_hard.yaml \
    --dataroot ./data \
    --save-dir "$SAVE" \
    --num-search 100 \
    --num-top 10 \
    --seed 1 \
    --fold-quality-floor 0.45 \
    --fold-retrain-tries 2 \
    --phase1-epochs 200 \
    --audit-floor 0.95 \
    "dataset=$DATASET" \
    epoch=200 \
    2>&1 | tee "$SAVE.log"
