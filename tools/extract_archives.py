"""One-time extraction of the found-policy archives into JSON data files.

The reference ships its discovered policies as giant Python literals
(`/root/reference/FastAutoAugment/archive.py:281-293`) plus the
AutoAugment/ARS-Aug paper policies remapped through `autoaug2arsaug`
(`archive.py:59-87`).  Policies are *data*, not code — the TPU framework
stores them as JSON under `fast_autoaugment_tpu/policies/data/` and owns
its own codec.  This tool evaluates the reference module once (with its
torch/torchvision imports stubbed out) and dumps each policy list.

Run: python tools/extract_archives.py
"""

import json
import os
import sys
import types

REF = "/root/reference"
OUT = os.path.join(os.path.dirname(__file__), "..", "fast_autoaugment_tpu", "policies", "data")


def _stub(name, **attrs):
    mod = types.ModuleType(name)
    for k, v in attrs.items():
        setattr(mod, k, v)
    sys.modules[name] = mod
    return mod


def main():
    # Stub the heavyweight imports augmentations.py pulls in; none are used
    # by the policy data itself.
    _stub("torch", Tensor=object)
    _stub("torchvision")
    _stub("torchvision.transforms")
    _stub("torchvision.transforms.transforms", Compose=object)

    sys.path.insert(0, REF)
    from FastAutoAugment import archive  # noqa: E402

    os.makedirs(OUT, exist_ok=True)
    names = [
        "fa_reduced_cifar10",
        "fa_resnet50_rimagenet",
        "fa_reduced_svhn",
        "autoaug_policy",
        "autoaug_paper_cifar10",
        "arsaug_policy",
    ]
    for name in names:
        policies = getattr(archive, name)()
        # normalize: list of sub-policies; each sub-policy is a list of
        # [op_name, prob, level] with level already in [0, 1]
        data = [[[str(op), float(p), float(lv)] for op, p, lv in sub] for sub in policies]
        path = os.path.join(OUT, f"{name}.json")
        with open(path, "w") as fh:
            json.dump(data, fh)
        print(f"{name}: {len(data)} sub-policies -> {path}")


if __name__ == "__main__":
    main()
