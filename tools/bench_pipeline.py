"""Serial vs async phase-2 scheduling: dispatch-gap histograms + wall.

The async actor/learner pipeline (``search/pipeline.py``,
``--async-pipeline on``) exists to drive the idle time BETWEEN device
dispatches to ~0: in the serial scheduler every round pays host-side
TPE math (``tools/bench_tpe.py`` measures ~3-5 ms/trial on the real
30-D space), policy decode + tensor upload, and an fsync'd trial-log
persist while the device waits.  This bench runs the SAME seeded search
twice — serial (``FAA_PIPELINE_TRACE=1`` arms the dispatch trace on the
historical scheduler) and async — and reports, per arm:

- the dispatch-gap histogram (p50/p99 inter-dispatch idle, log-bucket
  counts) and the device busy fraction during phase 2,
- end-to-end ``search_secs`` (phase-2 wall) and the async speedup,
- the host ask/tell latency rows for the configured trial batch (the
  overlap headroom the pipeline hides), and
- contention + compile-cache stamps (every number on this host is a
  1-core CPU plumbing number; the cache keeps the first dispatch from
  reading as a 7 s "busy" window in both arms).

Phase 1 is trained once in a warmup run and its fold checkpoint is
copied into both arms' save dirs, so the comparison is pure phase-2
scheduling.  Honors ``FAA_BENCH_REQUIRE_QUIET=1`` (refuses on a
contended host, exit 3).

    python tools/bench_pipeline.py --num-search 32 --trial-batch 4
    make bench-pipeline
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def _conf(batch: int, epoch: int):
    from fast_autoaugment_tpu.core.config import Config

    return Config({
        "model": {"type": "wresnet10_1"},
        "dataset": "synthetic",
        "aug": "default",
        "cutout": 8,
        "batch": batch,
        "epoch": epoch,
        "lr": 0.05,
        "lr_schedule": {"type": "cosine"},
        "optimizer": {"type": "sgd", "decay": 1e-4, "clip": 5.0,
                      "momentum": 0.9, "nesterov": True},
    })


_CKPT_COPY_SUFFIXES = ("", ".meta.json")


def _copy_fold_ckpt(src_dir: str, dst_dir: str, name: str) -> None:
    os.makedirs(dst_dir, exist_ok=True)
    for suffix in _CKPT_COPY_SUFFIXES:
        src = os.path.join(src_dir, name + suffix)
        if os.path.exists(src):
            shutil.copy2(src, os.path.join(dst_dir, name + suffix))


def run_pipeline_bench(args, workdir: str) -> dict:
    import jax

    from fast_autoaugment_tpu.search.driver import (
        _fold_ckpt_path,
        search_policies,
    )

    conf = _conf(args.batch, 1)
    cache_dir = os.path.join(workdir, "compile_cache")
    common = dict(
        dataroot=workdir, cv_num=1, cv_ratio=args.cv_ratio,
        num_policy=args.num_policy, num_op=args.num_op,
        num_top=5, trial_batch=args.trial_batch, seed=args.seed,
        compile_cache=cache_dir,
    )
    devices = jax.device_count()

    # warmup: train the shared phase-1 fold + fill the compile cache
    # (one round of trials compiles the TTA step into the cache, so
    # neither measured arm's first dispatch is a compile window)
    warm_dir = os.path.join(workdir, "warm")
    search_policies(conf, save_dir=warm_dir,
                    num_search=max(1, args.trial_batch), **common)
    ckpt_name = os.path.basename(_fold_ckpt_path(warm_dir, conf, 0,
                                                 args.cv_ratio))

    def _one_arm(tag: str, async_on: bool) -> dict:
        save_dir = os.path.join(workdir, tag)
        _copy_fold_ckpt(warm_dir, save_dir, ckpt_name)
        if not async_on:
            os.environ["FAA_PIPELINE_TRACE"] = "1"
        try:
            t0 = time.time()
            result = search_policies(
                conf, save_dir=save_dir, num_search=args.num_search,
                async_pipeline="on" if async_on else "off",
                pipeline_actors=args.actors,
                pipeline_queue_depth=args.queue_depth, **common)
            wall = time.time() - t0
        finally:
            os.environ.pop("FAA_PIPELINE_TRACE", None)
        pipe = result.get("pipeline") or {}
        return {
            "mode": "async" if async_on else "serial",
            "actors": args.actors if async_on else None,
            "queue_depth": args.queue_depth if async_on else None,
            "search_secs": round(wall, 3),
            "phase2_secs": round(
                result["device_secs_phase2"] / max(1, devices), 3),
            "device_busy_frac": pipe.get("device_busy_frac"),
            "dispatch_gaps": pipe.get("dispatch_gaps"),
            "tell_reorders": pipe.get("tell_reorders"),
            "num_sub_policies": result.get("num_sub_policies"),
            "compile_cache": result.get("compile_cache"),
        }

    serial = _one_arm("serial", False)
    async_ = _one_arm("async", True)
    speedup = (serial["phase2_secs"] / async_["phase2_secs"]
               if async_["phase2_secs"] else None)
    return {
        "bench": "pipeline",
        "devices": devices,
        "num_search": args.num_search,
        "trial_batch": args.trial_batch,
        "num_policy": args.num_policy,
        "num_op": args.num_op,
        "serial": serial,
        "async": async_,
        "phase2_speedup": round(speedup, 3) if speedup else None,
    }


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--num-search", type=int, default=24)
    p.add_argument("--trial-batch", type=int, default=4)
    p.add_argument("--num-policy", type=int, default=5)
    p.add_argument("--num-op", type=int, default=2)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--cv-ratio", type=float, default=0.4)
    p.add_argument("--actors", type=int, default=1)
    p.add_argument("--queue-depth", type=int, default=1)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--workdir", default=None,
                   help="scratch dir (default: a fresh tempdir, removed "
                        "on success)")
    p.add_argument("--out", default=None, help="also write the JSON line here")
    args = p.parse_args(argv)

    from bench import (
        host_contention_stamp,
        refuse_or_flag_contention,
        telemetry_stamp,
    )
    from bench_tpe import bench_ask_tell_latency

    contention = refuse_or_flag_contention(host_contention_stamp())
    print(f"contention: {json.dumps(contention)}")

    workdir = args.workdir or tempfile.mkdtemp(prefix="faa_bench_pipeline_")
    made_temp = args.workdir is None
    record = run_pipeline_bench(args, workdir)
    # unified provenance block (bench.telemetry_stamp): contention +
    # compile cache + registry counters in the shared schema
    record.update(telemetry_stamp(contention=contention))
    # the overlap headroom the async arm hides: host ask/tell latency
    # at this bench's trial batch (same JSON line, per the bench_tpe
    # citation contract)
    record["tpe_latency"] = bench_ask_tell_latency(
        ks=(args.trial_batch,), reps=20)

    for arm in ("serial", "async"):
        a = record[arm]
        gaps = a["dispatch_gaps"] or {}
        print(f"{arm}: phase2 {a['phase2_secs']}s, busy_frac "
              f"{a['device_busy_frac']}, gap p50 {gaps.get('gap_p50_ms')}ms "
              f"p99 {gaps.get('gap_p99_ms')}ms over {gaps.get('num_gaps')} "
              f"gaps ({gaps.get('num_dispatches')} dispatches)")
    print(f"phase2_speedup: {record['phase2_speedup']}x")
    busy = record["async"]["device_busy_frac"] or 0.0
    ok = busy >= 0.9 or (record["phase2_speedup"] or 0.0) >= 1.5
    print("acceptance (busy_frac >= 0.9 during phase 2 OR >= 1.5x "
          f"phase-2 speedup): {'PASS' if ok else 'FAIL'}")

    line = json.dumps(record)
    print(line)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(line + "\n")
    if made_temp:
        shutil.rmtree(workdir, ignore_errors=True)
    return record


if __name__ == "__main__":
    main()
